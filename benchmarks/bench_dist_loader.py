"""Distributed loader throughput + exchange-capacity validation.

Reference counterpart: `benchmarks/api/bench_dist_neighbor_loader.py`
(2 nodes x 2 GPUs, RPC sampling) — here the mesh-collective engine:
graph sharded over N devices, per-device seed shards, cross-partition
neighbor exchange on ICI (or the virtual CPU mesh).

Usage::

    # virtual 8-device mesh anywhere:
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python benchmarks/bench_dist_loader.py --quick

    # capacity sweep: P in {8,16,32} x {exact, slack 2.0} at the
    # reference workload (batch 1024, fanout [15,10,5]); each config
    # in its own subprocess with its own virtual mesh size, printing
    # padding-waste %% and drop-rate %% from the exchange telemetry:
    python benchmarks/bench_dist_loader.py --capacity-sweep
"""
import argparse
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import numpy as np

from benchmarks.common import (Timer, build_graph, cpu_mesh_env, emit,
                               run_in_fresh_process)


def capacity_worker(num_parts: int, slack, batch: int, fanout,
                    num_nodes: int):
  """One capacity config on a ``num_parts``-device virtual mesh —
  measures what VERDICT-r1 called the frontier-capacity math: hop-3
  frontier = batch * 15 * 10 ids/device exchanged under a 2x-balanced
  cap vs exact."""
  import jax
  from graphlearn_tpu.parallel import (DistDataset, DistNeighborLoader,
                                       make_mesh)
  assert len(jax.devices()) == num_parts, (
      f'mesh env failed: {len(jax.devices())} devices != {num_parts}')
  rows, cols = build_graph(num_nodes)
  ds = DistDataset.from_full_graph(num_parts, rows, cols,
                                   num_nodes=num_nodes)
  seeds = np.random.default_rng(1).integers(
      0, num_nodes, batch * num_parts * 3)
  loader = DistNeighborLoader(ds, fanout, seeds, batch_size=batch,
                              shuffle=True, mesh=make_mesh(num_parts),
                              collect_features=False, seed=0,
                              exchange_slack=slack)
  it = iter(loader)
  b = next(it)                    # compile + warm
  b.node.block_until_ready()
  with Timer() as t:
    n_batches = 0
    last = None
    for b in it:
      last = b
      n_batches += 1
    last.node.block_until_ready()
  st = loader.sampler.exchange_stats(tick_metrics=False)
  sent = st['dist.frontier.offered'] - st['dist.frontier.dropped']
  waste = 100.0 * (1 - sent / max(st['dist.frontier.slots'], 1))
  drop = 100.0 * st['dist.frontier.dropped'] / max(
      st['dist.frontier.offered'], 1)
  emit('dist_exchange_capacity',
       n_batches * batch * num_parts / t.dt / 1e3, 'K seeds/s',
       num_parts=num_parts,
       slack=('exact' if slack is None else slack), batch=batch,
       fanout=list(fanout), padding_waste_pct=round(waste, 2),
       drop_rate_pct=round(drop, 3),
       frontier_offered=st['dist.frontier.offered'],
       frontier_dropped=st['dist.frontier.dropped'])


def subgraph_worker(num_parts: int, hop_chunk, batch: int,
                    num_nodes: int):
  """SEAL-at-scale envelope (VERDICT r2 item 7): induced-subgraph
  loader with the full-window hop CHUNKED, so the widest all_to_all is
  ``[P, chunk, max_degree]`` regardless of closure size — the config
  that aborted at P>=16 when the window spanned the whole node table."""
  import jax
  from graphlearn_tpu.parallel import (DistDataset, DistSubGraphLoader,
                                       make_mesh)
  assert len(jax.devices()) == num_parts
  rows, cols = build_graph(num_nodes)
  ds = DistDataset.from_full_graph(num_parts, rows, cols,
                                   num_nodes=num_nodes)
  seeds = np.random.default_rng(1).integers(0, num_nodes,
                                            batch * num_parts * 3)
  max_degree = int(np.diff(ds.graph.indptr, axis=1).max())
  loader = DistSubGraphLoader(ds, [5, 5], seeds, batch_size=batch,
                              shuffle=True, mesh=make_mesh(num_parts),
                              collect_features=False, seed=0,
                              hop_chunk=hop_chunk)
  node_cap = loader.sampler.node_capacity(batch)
  it = iter(loader)
  b = next(it)
  b.node.block_until_ready()
  with Timer() as t:
    n_batches = 0
    last = None
    for b in it:
      last = b
      n_batches += 1
    last.node.block_until_ready()
  chunk = hop_chunk or node_cap
  emit('dist_subgraph_capacity',
       n_batches * batch * num_parts / t.dt, 'seeds/s',
       num_parts=num_parts,
       hop_chunk=('none' if hop_chunk is None else hop_chunk),
       node_cap=node_cap, max_degree=max_degree, batch=batch,
       window_exchange_width=num_parts * min(chunk, node_cap)
       * max_degree)


#: IGBH-large shapes for the memory envelope (PUBLIC IGB paper
#: figures, approximate — exact counts come from the npy headers when
#: the dataset is on disk; every type carries 1024-dim f32 features,
#: `reference examples/igbh/download_igbh_large.sh`).
IGBH_LARGE_SHAPES = {
    'nodes': {'paper': 100e6, 'author': 100e6, 'fos': 0.7e6,
              'institute': 0.03e6, 'journal': 0.05e6,
              'conference': 0.005e6},
    'feat_dim': 1024,
    'edges': 2.2e9,           # directed, pre-reverse; x2 with reverse
}


def memory_envelope(num_parts: int = 128, hbm_gb: float = 95.0,
                    split_ratio: float = 0.25, feat_bytes: int = 4):
  """BASELINE north-star check (VERDICT r4 #9): does IGBH-large fit a
  v5p-128 pod under the host-local tiered layout?  Array-residency
  bytes per chip, analytic from `IGBH_LARGE_SHAPES`:

    * features: ``split_ratio`` of each type's rows in HBM (hotness
      prefix), the rest in that host's DRAM (`DistFeature.cold_local`);
    * topology: CSR int32 indices + per-part indptr, by-src sharded,
      x2 for the reverse-edge types the RGNN recipes add;
    * labels/books: int32 paper labels + O(P) range books (negligible).

  Exchange/activation peaks ride on top but are capacity-bounded
  (``exchange_slack`` x balanced share; the [P, C] buffers at batch
  1024, fanout [15,10,5] are tens of MB — `capacity_sweep` measures
  them).  Returns the per-chip table; `--memory-envelope` prints it.
  """
  n_total = sum(IGBH_LARGE_SHAPES['nodes'].values())
  d = IGBH_LARGE_SHAPES['feat_dim']
  e = IGBH_LARGE_SHAPES['edges'] * 2          # with reverse etypes
  feat_total = n_total * d * feat_bytes
  feat_hbm_chip = feat_total * split_ratio / num_parts
  feat_host_chip = feat_total * (1 - split_ratio) / num_parts
  topo_chip = (e * 4) / num_parts + n_total * 4 / num_parts
  labels_chip = IGBH_LARGE_SHAPES['nodes']['paper'] * 4 / num_parts
  hbm_chip = feat_hbm_chip + topo_chip + labels_chip
  return {
      'config': f'IGBH-large on v5p-{num_parts} '
                f'(split_ratio={split_ratio}, f32 feats)',
      'nodes_M': round(n_total / 1e6, 1),
      'feat_total_GB': round(feat_total / 1e9, 1),
      'per_chip_feat_hbm_GB': round(feat_hbm_chip / 1e9, 2),
      'per_chip_topo_GB': round(topo_chip / 1e9, 2),
      'per_chip_hbm_GB': round(hbm_chip / 1e9, 2),
      'per_chip_hbm_frac_of_v5p': round(hbm_chip / (hbm_gb * 1e9), 3),
      'per_host_cold_dram_GB': round(feat_host_chip * 4 / 1e9, 1),
      'fits': bool(hbm_chip < 0.7 * hbm_gb * 1e9),
      'note': ('fully-HBM (split_ratio=1.0) also fits: '
               f'{round((feat_total + e * 4) / num_parts / 1e9, 1)} '
               f'GB/chip vs {hbm_gb} GB v5p HBM; the tiered layout is '
               'for bf16-less full-dim features plus headroom, and '
               'IGBH-full (~5.5x)'),
  }


def _epoch_exchange_rows(loader, epochs: int, batch: int,
                         num_parts: int):
  """Run ``epochs`` epochs, returning (n_seeds, per-epoch
  (waste_pct, drop_pct) rows) from the frontier exchange deltas."""
  rows = []
  n_seeds = 0
  b = None
  for _ in range(epochs):
    prev = loader.sampler.exchange_stats(tick_metrics=False)
    for b in loader:
      n_seeds += batch * num_parts
    st = loader.sampler.exchange_stats(tick_metrics=False)
    offered = (st['dist.frontier.offered']
               - prev['dist.frontier.offered'])
    dropped = (st['dist.frontier.dropped']
               - prev['dist.frontier.dropped'])
    slots = st['dist.frontier.slots'] - prev['dist.frontier.slots']
    rows.append((round(100.0 * (1 - (offered - dropped)
                                / max(slots, 1)), 2),
                 round(100.0 * dropped / max(offered, 1), 3)))
  if b is not None:
    import jax
    jax.block_until_ready(b)
  return n_seeds, rows


def _locality_comparison(num_parts: int, rows, cols, num_nodes: int,
                         batch: int, mesh, rng, epochs: int = 4,
                         dim: int = 256):
  """Locality-aware partitioning x exchange co-design probe (ISSUE 20).

  The envelope's headline homo run is featureless (frontier exchange
  only), so it cannot see the feature plane the locality work targets.
  This sub-run re-runs the same graph FEATURED (``collect_features=
  True`` — the feature attribution matrix ticks) under two arms that
  differ ONLY in the partitioner:

    * ``range``    — the historical seeded round-robin placement;
    * ``locality`` — the streaming edge-cut minimizer plus the full
      co-design: replica cache (hot remote rows served locally) and
      EWMA capacity retune at the epoch seam.

  Per-arm ``cross_partition_bytes_frac`` / ``seeds_per_sec`` are what
  the ``dist.locality.*`` regression guards read (headline = final
  epoch, after the EWMA retune recompile has settled).  The
  ``rename_equivalent`` bool replays the locality arm's relabel as an
  explicit-``node_pb`` build in the renamed id space and checks one
  epoch of batches byte-identical — the pure-rename contract.
  """
  import os
  import time
  import jax
  from graphlearn_tpu.parallel import DistDataset, DistNeighborLoader
  feats = np.random.default_rng(2).standard_normal(
      (num_nodes, dim)).astype(np.float32)
  seeds = rng.integers(0, num_nodes, batch * num_parts * 8)
  res = {}
  ds_loc = None
  for arm in ('range', 'locality'):
    saved = {k: os.environ.pop(k, None)
             for k in ('GLT_EXCHANGE_EWMA', 'GLT_PARTITIONER',
                       'GLT_LOCALITY_REPLICA_FRAC')}
    os.environ['GLT_EXCHANGE_EWMA'] = '1'   # both arms: same config
    try:
      ds = DistDataset.from_full_graph(
          num_parts, rows, cols, node_feat=feats, num_nodes=num_nodes,
          partitioner=arm,
          replica_frac=(0.35 if arm == 'locality' else None))
      loader = DistNeighborLoader(ds, [5, 5], seeds, batch_size=batch,
                                  shuffle=True, mesh=mesh,
                                  collect_features=True, seed=0,
                                  exchange_slack=1.25)
      if arm == 'locality':
        ds_loc = ds
      rates = []
      last = None
      nb = 0
      for ep in range(epochs):
        t0 = time.perf_counter()
        nb = 0
        for b in loader:
          last = b
          nb += 1
        jax.block_until_ready(last)
        rates.append(round(nb * batch * num_parts
                           / (time.perf_counter() - t0), 1))
      # headline rate: one re-timed window over the FINAL capacity
      # program (the early epochs pay compiles + the EWMA retune
      # recompiles; per-epoch batch counts are small enough that a
      # single epoch is noisy)
      t0 = time.perf_counter()
      for _ in range(2):
        for b in loader:
          last = b
      jax.block_until_ready(last)
      steady = round(2 * nb * batch * num_parts
                     / (time.perf_counter() - t0), 1)
      att = loader.sampler.attribution_stats(tick_metrics=False)
      st = loader.sampler.exchange_stats(tick_metrics=False)
      res[arm] = {
          'partitioner': getattr(ds, 'partitioner', arm),
          'cross_partition_bytes_frac':
              att['cross_partition_bytes_frac'],
          'cross_partition_ids_frac': att['cross_partition_ids_frac'],
          'locally_served_ids': att.get('locally_served_ids', 0),
          'seeds_per_sec': steady,
          'seeds_per_sec_by_epoch': rates,
          'drop_rate_pct': round(
              100.0 * st['dist.frontier.dropped']
              / max(st['dist.frontier.offered'], 1), 3),
          'feature_drop_rate_pct': round(
              100.0 * st['dist.feature.dropped']
              / max(st['dist.feature.offered'], 1), 3),
      }
    finally:
      for k, v in saved.items():
        if v is None:
          os.environ.pop(k, None)
        else:
          os.environ[k] = v
  res['locality_over_range_speedup'] = round(
      res['locality']['seeds_per_sec']
      / max(res['range']['seeds_per_sec'], 1e-9), 3)
  # pure-rename contract: rebuild the locality arm's placement as an
  # explicit node_pb over the ALREADY-relabeled edge list — the
  # relabel must come out the identity and one epoch byte-identical
  o2n, n2o = ds_loc.old2new, ds_loc.new2old
  pb_new = (np.searchsorted(ds_loc.graph.bounds, np.arange(num_nodes),
                            'right') - 1).astype(np.int32)
  # the twin must carry the SAME replica cache (hotness = in-degree,
  # expressed in its own id space): the masked gather changes which
  # ids compete for exchange slots, so a cache-less twin can drop
  # rows the replica arm serves locally
  ds_ren = DistDataset.from_full_graph(
      num_parts, o2n[rows], o2n[cols], node_feat=feats[n2o],
      num_nodes=num_nodes, node_pb=pb_new, replica_frac=0.35,
      hotness=np.bincount(o2n[cols], minlength=num_nodes))
  la = DistNeighborLoader(ds_loc, [5, 5], seeds, batch_size=batch,
                          shuffle=True, mesh=mesh,
                          collect_features=True, seed=0,
                          exchange_slack=1.25)
  lb = DistNeighborLoader(ds_ren, [5, 5], o2n[seeds], batch_size=batch,
                          shuffle=True, mesh=mesh,
                          collect_features=True, seed=0,
                          exchange_slack=1.25)
  equivalent = bool(np.array_equal(ds_ren.old2new,
                                   np.arange(num_nodes)))
  for ba, bb in zip(la, lb):
    for f in ('node', 'x', 'edge_index', 'batch'):
      if not np.array_equal(np.asarray(jax.device_get(getattr(ba, f))),
                            np.asarray(jax.device_get(getattr(bb, f)))):
        equivalent = False
    if not equivalent:
      break
  res['rename_equivalent'] = equivalent
  return res


def envelope_worker(num_parts: int, mode: str, batch: int,
                    num_nodes: int, epochs: int = 5):
  """Scale-envelope probe at ``num_parts`` VIRTUAL devices (VERDICT r3
  #6: past P=32): a deliberately tiny workload — the point is the
  PER-P exchange behavior (padding waste, drops, adaptive-slack
  convergence), not throughput, since 64-128 virtual devices
  oversubscribe this box's cores ~10x.  ``mode``: 'homo' (adaptive
  slack, several epochs so the controller can walk), 'hetero'
  (per-type exchanges, adaptive), 'seal' (chunked full-window
  subgraph hop).  Prints ONE JSON line.

  The headline ``padding_waste_pct`` / ``drop_rate_pct`` are the
  FINAL epoch's (the adaptive ladder's converged state — the steady
  state an IGBH-scale run lives in, and the same convention as the
  main dist row's ``waste_by_epoch[-1]``); the full trajectory and
  the run-cumulative figures ride alongside.  ``mode='homo'`` also
  re-runs one epoch per exchange layout (dense / compact / hier, all
  at the same static slack) so the artifact captures the layout
  comparison at this P.
  """
  import json
  import time
  import jax
  from graphlearn_tpu.parallel import make_mesh, resolve_layout
  assert len(jax.devices()) == num_parts, len(jax.devices())
  rows, cols = build_graph(num_nodes)
  rng = np.random.default_rng(1)
  mesh = make_mesh(num_parts)
  out = {'metric': 'dist_scale_envelope', 'num_parts': num_parts,
         'mode': mode, 'batch': batch, 'num_nodes': num_nodes}

  def make_homo_loader(layout=None, slack='adaptive'):
    from graphlearn_tpu.parallel import DistDataset, DistNeighborLoader
    ds = DistDataset.from_full_graph(num_parts, rows, cols,
                                     num_nodes=num_nodes)
    seeds = rng.integers(0, num_nodes, batch * num_parts * 2)
    return DistNeighborLoader(ds, [5, 5], seeds, batch_size=batch,
                              shuffle=True, mesh=mesh,
                              collect_features=False, seed=0,
                              exchange_slack=slack,
                              exchange_layout=layout)

  if mode == 'seal':
    from graphlearn_tpu.parallel import DistDataset, DistSubGraphLoader
    ds = DistDataset.from_full_graph(num_parts, rows, cols,
                                     num_nodes=num_nodes)
    seeds = rng.integers(0, num_nodes, batch * num_parts * 2)
    loader = DistSubGraphLoader(ds, [5, 5], seeds, batch_size=batch,
                                shuffle=True, mesh=mesh,
                                collect_features=False, seed=0,
                                hop_chunk=256)
    epochs = 1
  elif mode == 'hetero':
    from graphlearn_tpu.parallel import DistHeteroNeighborLoader
    from graphlearn_tpu.parallel.dist_hetero import DistHeteroDataset
    nu = num_nodes
    ni = num_nodes // 2
    ds = DistHeteroDataset.from_full_graph(
        num_parts,
        {('u', 'to', 'i'): (rows % nu, cols % ni),
         ('i', 'rev_to', 'u'): (cols % ni, rows % nu)},
        num_nodes_dict={'u': nu, 'i': ni})
    seeds = rng.integers(0, nu, batch * num_parts * 2)
    loader = DistHeteroNeighborLoader(ds, [5, 5], ('u', seeds),
                                      batch_size=batch, shuffle=True,
                                      mesh=mesh,
                                      collect_features=False, seed=0,
                                      exchange_slack='adaptive')
  else:
    loader = make_homo_loader()
  t0 = time.perf_counter()
  b = next(iter(loader))
  jax.block_until_ready(b)
  out['compile_secs'] = round(time.perf_counter() - t0, 1)
  t0 = time.perf_counter()
  n_seeds, ep_rows = _epoch_exchange_rows(loader, epochs, batch,
                                          num_parts)
  dt = time.perf_counter() - t0
  st = loader.sampler.exchange_stats(tick_metrics=False)
  sent = st['dist.frontier.offered'] - st['dist.frontier.dropped']
  out.update(
      # the active partitioner rides on every envelope row so regress
      # baselines are never compared across a partitioner change
      # (ISSUE 20; the `same:` opt on the dist.locality.* guards)
      partitioner=getattr(getattr(loader, 'ds', None), 'partitioner',
                          None),
      seeds_per_sec=round(n_seeds / dt, 1),
      # headline = converged (final-epoch) exchange state; the
      # trajectory + run-cumulative figures follow
      padding_waste_pct=ep_rows[-1][0],
      drop_rate_pct=ep_rows[-1][1],
      padding_waste_pct_by_epoch=[r[0] for r in ep_rows],
      drop_rate_pct_by_epoch=[r[1] for r in ep_rows],
      padding_waste_pct_cum=round(
          100.0 * (1 - sent / max(st['dist.frontier.slots'], 1)), 2),
      drop_rate_pct_cum=round(100.0 * st['dist.frontier.dropped']
                              / max(st['dist.frontier.offered'], 1),
                              3),
      slack_final=getattr(loader.sampler, 'exchange_slack', None),
      exchange_layout=resolve_layout(
          getattr(loader.sampler, 'exchange_layout', None), num_parts))
  if mode == 'homo':
    # per-partition traffic attribution (ISSUE 16): the P×P exchange
    # byte matrix + hot-range table from the run above — the envelope
    # is where locality regressions are cheapest to catch, and the
    # regress gate guards the P=16 row's headline fractions
    try:
      out['attribution'] = loader.sampler.attribution_stats(
          tick_metrics=False)
    except Exception as e:          # never sink the envelope row
      out['attribution_error'] = f'{type(e).__name__}: {e}'
    # dense-vs-compacted-vs-hierarchical at the same static slack:
    # one epoch each, fresh loader (fresh compile) per layout
    comparison = {}
    for layout in ('dense', 'compact', 'hier'):
      ll = make_homo_loader(layout=layout, slack=1.25)
      _, lrows = _epoch_exchange_rows(ll, 1, batch, num_parts)
      lst = ll.sampler.exchange_stats(tick_metrics=False)
      comparison[layout] = {
          'padding_waste_pct': lrows[-1][0],
          'drop_rate_pct': lrows[-1][1],
          'frontier_slots': lst['dist.frontier.slots'],
          'frontier_offered': lst['dist.frontier.offered'],
      }
    out['layouts'] = comparison
    # locality-aware partitioning x exchange co-design (ISSUE 20):
    # range-vs-locality on the SAME graph, featured so the feature
    # attribution plane ticks — feeds the dist.locality.* guards
    try:
      out['locality'] = _locality_comparison(num_parts, rows, cols,
                                             num_nodes, batch, mesh,
                                             rng)
    except Exception as e:          # never sink the envelope row
      out['locality_error'] = f'{type(e).__name__}: {e}'
  # the BASELINE north-star memory check rides along on every
  # envelope row (VERDICT r4 #9)
  out['memory_envelope_v5p128'] = memory_envelope(128)
  print(json.dumps(out), flush=True)
  from benchmarks.common import tee_record
  tee_record(out)


def _chaos_server_proc(port_q, num_nodes, dim, jsonl, worker_plan):
  """Sampling-server process for the chaos smoke (spawn-started so it
  inherits THIS env assignment — its producer workers read the kill
  plan from GLT_FAULT_PLAN)."""
  import os
  if worker_plan:
    os.environ['GLT_FAULT_PLAN'] = worker_plan
  os.environ['GLT_TELEMETRY_JSONL'] = jsonl
  import numpy as np
  from graphlearn_tpu.distributed import (HostDataset, init_server,
                                          wait_and_shutdown_server)
  from graphlearn_tpu.telemetry import recorder
  recorder.enable(jsonl)
  rows, cols = build_graph(num_nodes)
  feats = np.random.default_rng(0).standard_normal(
      (num_nodes, dim)).astype(np.float32)
  ds = HostDataset.from_coo(rows, cols, num_nodes, node_features=feats)
  srv = init_server(num_servers=1, num_clients=1, rank=0, dataset=ds,
                    host='127.0.0.1', port=0)
  port_q.put(srv.port)
  wait_and_shutdown_server(timeout=600)


def chaos_smoke(batch: int = 64, num_nodes: int = 5000, dim: int = 32,
                epochs: int = 3):
  """Resilience smoke on the HOST server->client path (ISSUE 4): time
  fault-free epochs WITH the retry/idempotency layer on (the
  ``dist.chaos.fault_free_seeds_per_sec`` regression guard — the
  resilience layer must not tax the hot path), then run one chaos
  epoch (worker kill + connection drop + delayed fetch) and assert
  exact batch accounting.  Prints ONE JSON row."""
  import json
  import multiprocessing as mp
  import os
  import tempfile
  import time
  import numpy as np
  from graphlearn_tpu import native
  if not native.available():
    row = {'metric': 'dist_chaos_smoke', 'skipped': True,
           'reason': 'native lib unavailable'}
    print(json.dumps(row), flush=True)
    return
  from graphlearn_tpu.distributed import (
      DistNeighborLoader, RemoteDistSamplingWorkerOptions, init_client,
      shutdown_client)
  from graphlearn_tpu.distributed.dist_loader import DistLoader
  from graphlearn_tpu.telemetry import recorder
  from graphlearn_tpu.testing import chaos

  n_seeds = batch * 32
  n_batches = n_seeds // batch
  chaos_epoch = epochs             # epochs 0..epochs-1 fault-free
  jsonl = os.path.join(tempfile.mkdtemp(prefix='glt_chaos_'),
                       'server.jsonl')
  # the kill fires only in the chaos epoch (epoch filter) and only in
  # the ORIGINAL worker incarnation (generation filter), so the timed
  # fault-free epochs run untouched and the supervisor's replacement
  # worker survives to finish the replay
  worker_plan = (f'producer.worker:kill:2:worker=0:'
                 f'epoch={chaos_epoch}:generation=0')
  ctx = mp.get_context('spawn')
  port_q = ctx.Queue()
  proc = ctx.Process(target=_chaos_server_proc,
                     args=(port_q, num_nodes, dim, jsonl, worker_plan),
                     daemon=False)
  proc.start()
  port = port_q.get(timeout=300)
  init_client([('127.0.0.1', port)], rank=0, num_clients=1)
  recorder.enable(None)            # ring: rpc.retry/peer.lost capture
  DistLoader.RECV_POLL_SECS = 2.0
  seeds = np.arange(n_seeds) % num_nodes
  loader = DistNeighborLoader(
      None, [10, 5], seeds, batch_size=batch, shuffle=True,
      worker_options=RemoteDistSamplingWorkerOptions(
          server_rank=0, num_workers=2, prefetch_size=2),
      to_device=False, seed=0)

  # -- fault-free phase (epoch 0 warms the pipeline, rest are timed) --
  for b in loader:
    pass
  t0 = time.perf_counter()
  timed_batches = 0
  for _ in range(epochs - 1):
    for b in loader:
      timed_batches += 1
  dt = time.perf_counter() - t0
  fault_free_rate = timed_batches * batch / max(dt, 1e-9)
  base_retries = len(recorder.events('rpc.retry'))

  # -- chaos epoch ----------------------------------------------------
  chaos.install('rpc.request:drop:2:op=fetch_one_sampled_message;'
                'rpc.request:delay:4:op=fetch_one_sampled_message:'
                'secs=0.5')
  got = 0
  seen = set()
  for b in loader:
    got += 1
  ch = loader.channel
  seen = set(getattr(ch, '_seen_seqs', ()))
  dup = getattr(ch, 'duplicates_discarded', 0)
  retries = len(recorder.events('rpc.retry')) - base_retries
  chaos.uninstall()
  loader.shutdown()
  shutdown_client()
  proc.join(timeout=60)
  server_events = ''
  try:
    with open(jsonl) as f:
      server_events = f.read()
  except OSError:
    pass
  row = {
      'metric': 'dist_chaos_smoke',
      'batch': batch, 'num_nodes': num_nodes,
      'epochs_fault_free': epochs,
      'fault_free_seeds_per_sec': round(fault_free_rate, 1),
      'chaos_epoch': {
          'expected_batches': n_batches,
          'received_batches': got,
          'unique_seqs': len(seen),
          'duplicates_discarded': int(dup),
          'rpc_retries': retries,
          'producer_restart_logged':
              '"kind": "producer.restart"' in server_events,
          'fault_injected_logged':
              '"kind": "fault.injected"' in server_events,
      },
      'ok': bool(got == n_batches and len(seen) == n_batches
                 and retries >= 1),
  }
  print(json.dumps(row), flush=True)
  from benchmarks.common import tee_record
  tee_record(row)
  return row


def resume_smoke(batch: int = 64, num_nodes: int = 2048):
  """Preemption-resume smoke (ISSUE 6): time a snapshotting epoch
  against the no-snapshot line on the host mp producer path, then run
  the kill→restore→finish loop and report ``restore_secs`` (durable
  snapshot load + data-plane rewind) and ``replayed_batches`` (the
  re-produced prefix the consumer discards) — the two regression-
  guarded ``dist.resume.*`` metrics.  Prints ONE JSON row.

  The mesh ``dist.tiered`` line is snapshot-free by construction
  (snapshots are opt-in per driver via ``attach_snapshots`` /
  ``GLT_SNAPSHOT_DIR``), so the snapshot-overhead comparison is
  measured here on the path that DOES snapshot: the row's
  ``snap_over_nosnap_ratio`` (snapshotting / no-snapshot throughput,
  ~1.0 when overhead is in the noise) is what the
  ``dist.resume.snap_over_nosnap_ratio`` regression guard holds the
  line on (the raw signed ``snapshot_overhead_pct`` is reported for
  humans but is ratio-unsafe as a guard: its healthy baseline
  straddles zero)."""
  import json
  import shutil
  import tempfile
  import time as _time
  import numpy as np
  from graphlearn_tpu import native
  if not native.available():
    row = {'metric': 'dist_resume_smoke', 'skipped': True,
           'reason': 'native lib unavailable'}
    print(json.dumps(row), flush=True)
    return
  from graphlearn_tpu.distributed import (DistNeighborLoader,
                                          HostDataset,
                                          MpDistSamplingWorkerOptions)
  from graphlearn_tpu.utils.checkpoint import SnapshotManager

  n = num_nodes
  rows = np.repeat(np.arange(n), 2)
  cols = np.stack([(np.arange(n) + 1) % n,
                   (np.arange(n) + 2) % n], 1).reshape(-1)
  feats = np.tile(np.arange(n, dtype=np.float32)[:, None], (1, 16))
  ds = HostDataset.from_coo(rows, cols, n, node_features=feats,
                            node_labels=np.arange(n) % 4)

  def make_loader():
    return DistNeighborLoader(
        ds, [5, 5], np.arange(n), batch_size=batch, shuffle=True,
        worker_options=MpDistSamplingWorkerOptions(
            num_workers=2, mp_start_method='spawn'),
        to_device=False, seed=7)

  n_batches = (n + batch - 1) // batch
  snap_root = tempfile.mkdtemp(prefix='glt_resume_')
  try:
    # -- epoch timing: no-snapshot line vs snapshot-every-batch ------
    loader = make_loader()
    for b in loader:                       # warm the producer pool
      pass
    # the 5% criterion reads this comparison.  On the fused tiered
    # path a snapshot boundary is a GLT_FUSED_COLD_CHUNK (64-step)
    # chunk; the host loader's boundary is a single batch, so
    # GLT_SNAPSHOT_EVERY here defaults to 8 batches as the
    # chunk-equivalent cadence (a per-batch fsync is not the deployed
    # regime on any path).  Min over 3 epochs per arm: the mp producer
    # wall is noisy (worker scheduling), the floor is the signal.
    from graphlearn_tpu.utils.checkpoint import snapshot_every_from_env
    every = snapshot_every_from_env(default=8)
    snap = SnapshotManager(snap_root + '/overhead', every=every)
    nosnap_secs = snap_secs = float('inf')
    for _ in range(3):
      t0 = _time.perf_counter()
      for b in loader:
        pass
      nosnap_secs = min(nosnap_secs, _time.perf_counter() - t0)
      t0 = _time.perf_counter()
      seen = 0
      for b in loader:
        seen += 1
        if snap.due():
          snap.save(loader.state_dict(),
                    {'epoch': 2, 'next_chunk': seen})
      snap_secs = min(snap_secs, _time.perf_counter() - t0)
    rate_nosnap = n / max(nosnap_secs, 1e-9)
    rate_snap = n / max(snap_secs, 1e-9)
    overhead_pct = 100.0 * (snap_secs - nosnap_secs) / max(nosnap_secs,
                                                           1e-9)

    # -- kill -> restore -> finish -----------------------------------
    consumed = n_batches // 2
    it = iter(loader)
    for _ in range(consumed):
      next(it)
    resume_snap = SnapshotManager(snap_root + '/resume', every=1)
    resume_snap.save(loader.state_dict(),
                     {'epoch': 3, 'next_chunk': consumed})
    loader.shutdown()                      # the preemption

    resumed = make_loader()
    t0 = _time.perf_counter()
    payload = SnapshotManager(snap_root + '/resume').restore_latest()
    resumed.load_state_dict(payload['plane'])
    restore_secs = _time.perf_counter() - t0
    rest = sum(1 for _ in resumed.resume_epoch())
    replayed = int(getattr(resumed, 'replayed_discarded', 0))
    resumed.shutdown()
  finally:
    shutil.rmtree(snap_root, ignore_errors=True)

  row = {
      'metric': 'dist_resume_smoke',
      'batch': batch, 'num_nodes': n,
      'restore_secs': round(restore_secs, 4),
      'replayed_batches': replayed,
      'resumed_batches': rest,
      'consumed_before_kill': consumed,
      'seeds_per_sec_nosnap': round(rate_nosnap, 1),
      'seeds_per_sec_snap': round(rate_snap, 1),
      'snapshot_overhead_pct': round(overhead_pct, 2),
      'snap_over_nosnap_ratio': round(
          rate_snap / max(rate_nosnap, 1e-9), 4),
      'ok': bool(consumed + rest == n_batches
                 and replayed >= consumed),
  }
  print(json.dumps(row), flush=True)
  from benchmarks.common import tee_record
  tee_record(row)
  return row


def failover_smoke(batch: int = 64, num_nodes: int = 20_000,
                   dim: int = 32):
  """Elastic-failover smoke (ISSUE 15): one partition owner killed
  mid-epoch on the virtual mesh with a durable shard present under
  ``GLT_SHARD_DIR`` — a survivor adopts the orphaned shard and the
  epoch must finish with the EXACT-completion contract: the full
  expected batch count (``completed_ratio`` 1.0), batches
  byte-identical to the fault-free run, exactly ONE adoption, and
  ``recovery_secs`` (classification -> first served batch) gauged —
  the two ``dist.failover.*`` regression-guarded metrics.  Prints ONE
  JSON row; the caller exits nonzero unless ``ok``."""
  import json
  import os
  import shutil
  import tempfile
  import time
  import jax
  from graphlearn_tpu.parallel import (DistDataset, DistNeighborLoader,
                                       make_mesh)
  from graphlearn_tpu.telemetry import recorder
  from graphlearn_tpu.testing import chaos

  num_parts = len(jax.devices())
  mesh = make_mesh(num_parts)
  rows, cols = build_graph(num_nodes)
  feats = np.random.default_rng(0).standard_normal(
      (num_nodes, dim)).astype(np.float32)
  labels = (np.arange(num_nodes) % 7).astype(np.int32)

  def make_loader():
    ds = DistDataset.from_full_graph(num_parts, rows, cols,
                                     node_feat=feats, node_label=labels,
                                     num_nodes=num_nodes)
    seeds = np.random.default_rng(1).permutation(
        num_nodes)[:batch * num_parts * 10]
    return ds, DistNeighborLoader(ds, [10, 5], seeds, batch_size=batch,
                                  shuffle=True, mesh=mesh, seed=0)

  def grab(b):
    return tuple(np.asarray(jax.device_get(x))
                 for x in (b.node, b.x, b.y, b.edge_index))

  # -- fault-free reference: epoch 1 is the byte-identity reference
  # (the shuffle permutation advances per epoch, and the failover run
  # below is ITS loader's epoch 1 too); epoch 2 is the post-compile
  # timed line
  _, ref_loader = make_loader()
  ref = [grab(b) for b in ref_loader]
  t0 = time.perf_counter()
  for b in ref_loader:
    pass
  fault_free_secs = time.perf_counter() - t0
  n_batches = len(ref)
  kill_step = max(2, n_batches // 2)

  # -- failover epoch: durable shards on, one owner killed mid-epoch --
  shard_dir = tempfile.mkdtemp(prefix='glt_failover_')
  saved = {k: os.environ.pop(k, None)
           for k in ('GLT_SHARD_DIR', 'GLT_DEGRADED_OK')}
  os.environ['GLT_SHARD_DIR'] = shard_dir
  victim = num_parts // 2
  recorder.enable(None)
  chaos.install(f'partition.owner:kill:{kill_step}:partition={victim}')
  try:
    ds, loader = make_loader()
    t0 = time.perf_counter()
    got = [grab(b) for b in loader]
    failover_secs = time.perf_counter() - t0
    adopts = recorder.events('partition.adopt')
  finally:
    chaos.uninstall()
    recorder.disable()
    for k, v in saved.items():
      if v is None:
        os.environ.pop(k, None)
      else:
        os.environ[k] = v
    shutil.rmtree(shard_dir, ignore_errors=True)

  executed = [e for e in adopts if e.get('phase') is None]
  recovered = [e for e in adopts if e.get('phase') == 'recovered']
  byte_identical = len(got) == n_batches and all(
      all(np.array_equal(a, b) for a, b in zip(r, g))
      for r, g in zip(ref, got))
  completed_ratio = round(len(got) / max(n_batches, 1), 4)
  recovery_secs = recovered[0]['secs'] if recovered else None
  row = {
      'metric': 'dist_failover_smoke',
      'batch': batch, 'num_nodes': num_nodes, 'num_parts': num_parts,
      'expected_batches': n_batches,
      'received_batches': len(got),
      'completed_ratio': completed_ratio,
      'byte_identical': bool(byte_identical),
      'adoptions_total': len(executed),
      'book_version': int(ds.partition_book.version),
      'killed_partition': victim,
      'kill_step': kill_step,
      'recovery_secs': (round(recovery_secs, 4)
                        if recovery_secs is not None else None),
      'fault_free_epoch_secs': round(fault_free_secs, 3),
      'failover_epoch_secs': round(failover_secs, 3),
      'ok': bool(byte_identical and completed_ratio == 1.0
                 and len(executed) == 1
                 and ds.partition_book.version == 1
                 and recovery_secs is not None and recovery_secs > 0),
  }
  print(json.dumps(row), flush=True)
  from benchmarks.common import tee_record
  tee_record(row)
  return row


def capacity_sweep(quick: bool):
  import json
  fanout = [15, 10, 5]
  batch = 1024
  n = 100_000 if quick else 500_000
  script = str(Path(__file__).resolve())
  for p in (8, 16, 32):
    for slack in ('exact', 2.0):
      if slack == 'exact' and p > 8:
        # exact exchange at P>=16 with batch-1024 frontiers means
        # ~[P, 154k] all_to_all buffers per hop — beyond the virtual
        # CPU mesh's in-process collectives (rendezvous aborts on the
        # single-core CI box), and exactly the configuration the
        # capacity cap exists to avoid.  Recorded explicitly: no
        # silent truncation of the sweep.
        print(json.dumps(
            {'metric': 'dist_exchange_capacity', 'skipped': True,
             'num_parts': p, 'slack': 'exact',
             'reason': 'exact exchange buffers exceed virtual-mesh '
                       'capacity; use slack'}), flush=True)
        continue
      run_in_fresh_process(
          script,
          ['--capacity-worker', '--num-parts', p, '--slack', slack,
           '--batch', batch, '--nodes', n,
           '--fanout', ','.join(map(str, fanout))],
          env=cpu_mesh_env(p))
  # SEAL envelope: chunked full-window hops keep the exact subgraph
  # scan bounded where the unchunked window aborted at P>=16
  sg_n = 50_000 if quick else 100_000
  for p, chunk in ((8, 'none'), (8, 512), (16, 512)):
    run_in_fresh_process(
        script,
        ['--subgraph-worker', '--num-parts', p, '--hop-chunk', chunk,
         '--batch', 32, '--nodes', sg_n],
        env=cpu_mesh_env(p))
  # scale envelope past P=32 (VERDICT r3 #6): P=64/128 homo with
  # adaptive slack, hetero and chunked-SEAL at P=64 — tiny shapes (the
  # virtual devices oversubscribe the cores; the exchange accounting,
  # not throughput, is the deliverable)
  env_n = 20_000 if quick else 50_000
  for p, mode, batch in ((64, 'homo', 64), (128, 'homo', 32),
                         (64, 'hetero', 32), (64, 'seal', 8)):
    run_in_fresh_process(
        script,
        ['--envelope-worker', '--num-parts', p, '--mode', mode,
         '--batch', batch, '--nodes', env_n],
        env=cpu_mesh_env(p))


def main():
  ap = argparse.ArgumentParser()
  ap.add_argument('--quick', action='store_true')
  ap.add_argument('--num-parts', type=int, default=None)
  ap.add_argument('--dim', type=int, default=64)
  ap.add_argument('--capacity-sweep', action='store_true')
  ap.add_argument('--capacity-worker', action='store_true')
  ap.add_argument('--subgraph-worker', action='store_true')
  ap.add_argument('--envelope-worker', action='store_true')
  ap.add_argument('--memory-envelope', action='store_true',
                  help='print the IGBH-large-on-v5p-128 per-chip '
                       'memory table (VERDICT r4 #9)')
  ap.add_argument('--chaos', action='store_true',
                  help='resilience smoke: fault-free host '
                       'server->client throughput with the retry '
                       'layer on, then one chaos epoch (worker kill '
                       '+ connection drop + delayed fetch) with '
                       'exact-accounting checks')
  ap.add_argument('--resume', action='store_true',
                  help='preemption-resume smoke: snapshot-overhead '
                       'epoch timing vs the no-snapshot line, then '
                       'kill -> durable restore -> finish with exact '
                       'accounting (dist.resume.* metrics)')
  ap.add_argument('--failover', action='store_true',
                  help='elastic-failover smoke (ISSUE 15): kill one '
                       'partition owner mid-epoch with a durable '
                       'shard under GLT_SHARD_DIR — exits nonzero '
                       'unless the epoch completes EXACTLY '
                       '(completed_ratio 1.0, batches byte-identical '
                       'to the fault-free run) with ONE adoption; '
                       'reports the guarded dist.failover.* metrics')
  ap.add_argument('--mode', default='homo')
  ap.add_argument('--epochs', type=int, default=5,
                  help='envelope-worker epochs (the adaptive ladder '
                       'walks one rung per drop-free epoch)')
  ap.add_argument('--slack', default='exact')
  ap.add_argument('--hop-chunk', default='none')
  ap.add_argument('--batch', type=int, default=1024)
  ap.add_argument('--nodes', type=int, default=500_000)
  ap.add_argument('--fanout', default='15,10,5')
  ap.add_argument('--fused', action='store_true',
                  help='also time parallel.FusedDistEpoch (whole '
                       'epoch = one SPMD scan program, WITH the DP '
                       'train step) against the per-batch loader + '
                       'DP-step loop — ~17 s of CPU-mesh compile at '
                       'the default shape (r4 measurement); the '
                       'multi-minute regime is the big-model shape, '
                       'see benchmarks/bench_compile.py')
  args = ap.parse_args()

  # live ops plane (r13): honor GLT_OPS_PORT so a long-running dist
  # bench is scrapeable mid-run (no-op at the 0/unset default)
  from graphlearn_tpu.telemetry import maybe_start_from_env
  maybe_start_from_env()

  if args.chaos:
    chaos_smoke(batch=args.batch if args.batch != 1024 else 64,
                num_nodes=min(args.nodes, 5000))
    return
  if args.resume:
    resume_smoke(batch=args.batch if args.batch != 1024 else 64,
                 num_nodes=min(args.nodes, 2048))
    return
  if args.failover:
    row = failover_smoke(batch=args.batch if args.batch != 1024 else 64,
                         num_nodes=min(args.nodes, 20_000))
    if not row.get('ok'):
      raise SystemExit(1)
    return
  if args.capacity_sweep:
    capacity_sweep(args.quick)
    return
  if args.capacity_worker:
    slack = None if args.slack == 'exact' else float(args.slack)
    capacity_worker(args.num_parts, slack, args.batch,
                    [int(k) for k in args.fanout.split(',')], args.nodes)
    return
  if args.subgraph_worker:
    chunk = None if args.hop_chunk == 'none' else int(args.hop_chunk)
    subgraph_worker(args.num_parts, chunk, args.batch, args.nodes)
    return
  if args.memory_envelope:
    import json
    print(json.dumps(memory_envelope(args.num_parts or 128)),
          flush=True)
    return
  if args.envelope_worker:
    envelope_worker(args.num_parts, args.mode, args.batch, args.nodes,
                    epochs=args.epochs)
    return

  import jax
  from graphlearn_tpu.parallel import (DistDataset, DistNeighborLoader,
                                       make_mesh)

  num_parts = args.num_parts or len(jax.devices())
  mesh = make_mesh(num_parts)
  n = 100_000 if args.quick else 500_000
  rows, cols = build_graph(n)
  feats = np.random.default_rng(0).standard_normal(
      (n, args.dim)).astype(np.float32)
  labels = (np.arange(n) % 47).astype(np.int32)
  ds = DistDataset.from_full_graph(num_parts, rows, cols,
                                   node_feat=feats, node_label=labels,
                                   num_nodes=n)

  seeds = np.random.default_rng(1).permutation(n)[:8192 if args.quick
                                                  else 65536]
  for batch_size in (256, 512):
    loader = DistNeighborLoader(ds, [10, 5], seeds,
                                batch_size=batch_size, shuffle=True,
                                mesh=mesh, seed=0)
    b = next(iter(loader))          # compile
    b.x.block_until_ready()
    batches = 0
    with Timer() as t:
      last = None
      for b in loader:
        last = b
        batches += 1
      last.x.block_until_ready()
    global_batch = batch_size * num_parts
    emit('dist_loader_seeds_per_sec',
         batches * global_batch / t.dt / 1e3, 'K seeds/s',
         batch=batch_size, num_parts=num_parts,
         platform=jax.devices()[0].platform)

  # -- tiered rows (r10): static split vs cache + cold pipeline ----------
  # The same workload against a split_ratio=0.3 store, twice: the r5
  # static-split configuration (no cache, synchronous overlay) and the
  # r10 default (HBM victim cache + double-buffered cold overlay).
  # Both rows land in BENCH_ARTIFACT.jsonl; the bench.py twin of this
  # measurement feeds the guarded `dist.tiered.seeds_per_sec` /
  # `dist.feature.cache_hit_rate` regression keys.
  import os
  ds_t = DistDataset.from_full_graph(num_parts, rows, cols,
                                     node_feat=feats, node_label=labels,
                                     num_nodes=n, split_ratio=0.3)
  # third row (r11): GNS-on vs GNS-off tiered comparison — the same
  # cache + pipeline with the sampler-side bias added (GLT_GNS=1
  # exercises the env-knob path the way a deployment would set it)
  for mode, env in (('static_split', {'GLT_COLD_CACHE_ROWS': '0',
                                      'GLT_COLD_PREFETCH': '0'}),
                    ('cached_pipelined', {}),
                    ('gns_cached_pipelined', {'GLT_GNS': '1'})):
    saved = {k: os.environ.pop(k, None)
             for k in ('GLT_COLD_CACHE_ROWS', 'GLT_COLD_PREFETCH',
                       'GLT_GNS')}
    os.environ.update(env)
    try:
      lt = DistNeighborLoader(ds_t, [10, 5], seeds, batch_size=512,
                              shuffle=True, mesh=mesh, seed=0,
                              prefetch=2)
      it = iter(lt)
      b = next(it)
      b.x.block_until_ready()
      nt = 0
      with Timer() as t:
        for b in it:
          b.x.block_until_ready()
          nt += 1
      st = lt.sampler.exchange_stats(tick_metrics=False)
      emit('dist_tiered_seeds_per_sec',
           nt * 512 * num_parts / t.dt / 1e3, 'K seeds/s',
           mode=mode, split_ratio=0.3, batch=512, num_parts=num_parts,
           gns=bool(lt.sampler.gns),
           cold_cache_rows=(lt.sampler._cold_cache.capacity
                            if lt.sampler._cold_cache else 0),
           cold_lookups=st['dist.feature.cold_lookups'],
           cold_misses=st['dist.feature.cold_misses'],
           hot_hit_rate=round(st['dist.feature.hot_hit_rate'], 4),
           cache_hit_rate=round(st['dist.feature.cache_hit_rate'], 4),
           platform=jax.devices()[0].platform)
    finally:
      for k, v in saved.items():
        if v is None:
          os.environ.pop(k, None)
        else:
          os.environ[k] = v

  if args.fused:
    # fused whole-epoch vs per-batch loader + DP step, same workload
    # (the dispatch-overhead measurement, mesh edition)
    import optax
    from graphlearn_tpu.models import GraphSAGE, create_train_state
    from graphlearn_tpu.parallel import (FusedDistEpoch,
                                         make_dp_supervised_step,
                                         replicate)
    bs = 256 if args.quick else 512
    fanout = [10, 5]   # matches the loader phase above (NOT --fanout,
                       # which parameterizes the capacity workers)
    model = GraphSAGE(hidden_features=64, out_features=47, num_layers=2)
    tx = optax.adam(3e-3)
    it = iter(DistNeighborLoader(ds, fanout, seeds, batch_size=bs,
                                 shuffle=True, mesh=mesh, seed=0))
    b0 = next(it)
    b0_local = jax.tree_util.tree_map(lambda x: x[0], b0)
    state, apply_fn = create_train_state(model, jax.random.key(0),
                                         b0_local, tx)
    step = make_dp_supervised_step(apply_fn, tx, bs, mesh)
    state = replicate(state, mesh)
    state, _, _ = step(state, b0)               # compile + warm
    jax.tree_util.tree_leaves(state.params)[0].block_until_ready()
    nb = 0
    with Timer() as t:
      for b in it:
        state, _, _ = step(state, b)
        nb += 1
      jax.tree_util.tree_leaves(state.params)[0].block_until_ready()
    emit('dist_train_seeds_per_sec', nb * bs * num_parts / t.dt / 1e3,
         'K seeds/s', mode='per-batch', batch=bs, fanout=fanout,
         num_parts=num_parts, platform=jax.devices()[0].platform)

    fused = FusedDistEpoch(ds, fanout, seeds, apply_fn, tx,
                           batch_size=bs, mesh=mesh, shuffle=True,
                           seed=0)
    for _ in range(2):                  # compile + donated recompile
      state, _ = fused.run(state)
    jax.tree_util.tree_leaves(state.params)[0].block_until_ready()
    with Timer() as t:
      state, _ = fused.run(state)
      jax.tree_util.tree_leaves(state.params)[0].block_until_ready()
    emit('dist_train_seeds_per_sec',
         len(fused) * bs * num_parts / t.dt / 1e3, 'K seeds/s',
         mode='fused', batch=bs, fanout=fanout, num_parts=num_parts,
         platform=jax.devices()[0].platform)


if __name__ == '__main__':
  main()
