"""End-to-end loader throughput: batches/s and sampled-edges/s
including collation (features + labels + batch assembly).

Reference counterpart: `benchmarks/api/bench_dist_neighbor_loader.py`'s
single-node half — the number the training loop actually sees.

Usage::

    python benchmarks/bench_loader.py [--cpu] [--quick]

r5 PROTOCOL CAVEAT: this sweep still times dispatch loops with
`block_until_ready`, which the tunneled chip can under-report by
orders of magnitude (elided executions — see benchmarks/README
"r5 protocol note").  Its numbers are comparative between configs in
one run, NOT absolute; the authoritative pull-protocol numbers are
`bench.py`'s (gather roofline, epoch walls).
"""
import argparse
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import numpy as np

from benchmarks.common import Timer, build_graph, emit


def main():
  ap = argparse.ArgumentParser()
  ap.add_argument('--cpu', action='store_true')
  ap.add_argument('--quick', action='store_true')
  ap.add_argument('--dim', type=int, default=128)
  args = ap.parse_args()

  import jax
  if args.cpu:
    jax.config.update('jax_platforms', 'cpu')
  from graphlearn_tpu.data import Dataset
  from graphlearn_tpu.loader import NeighborLoader

  n = 200_000 if args.quick else 1_000_000
  rows, cols = build_graph(n)
  feats = np.random.default_rng(0).standard_normal(
      (n, args.dim)).astype(np.float32)
  labels = (np.arange(n) % 47).astype(np.int32)
  ds = (Dataset()
        .init_graph((rows, cols), layout='COO', num_nodes=n)
        .init_node_features(feats, split_ratio=1.0)
        .init_node_labels(labels))

  seeds = np.random.default_rng(1).permutation(n)[:20_000 if args.quick
                                                  else 100_000]
  for batch_size in (512, 1024):
    loader = NeighborLoader(ds, [15, 10, 5], seeds, batch_size=batch_size,
                            shuffle=True, seed=0)
    import jax.numpy as jnp
    b = next(iter(loader))          # compile
    b.x.block_until_ready()
    batches = 0
    # device-side accumulator: no per-batch host sync (which would
    # deflate throughput) and no batch retention (which would grow
    # device memory across the epoch)
    edges_dev = jnp.zeros((), jnp.int32)  # ~100k-seed epochs: <2^31 edges
    with Timer() as t:
      last = None
      for b in loader:
        last = b
        batches += 1
        edges_dev = edges_dev + b.edge_mask.sum()
      last.x.block_until_ready()
      edges_dev.block_until_ready()
    edges = int(edges_dev)
    emit('loader_batches_per_sec', batches / t.dt, 'batches/s',
         batch=batch_size, platform=jax.devices()[0].platform)
    emit('loader_edges_per_sec', edges / t.dt / 1e6, 'M edges/s',
         batch=batch_size, platform=jax.devices()[0].platform)


if __name__ == '__main__':
  main()
