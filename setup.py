"""Build hook: compile the native host runtime before packaging.

The reference builds a torch cpp_extension wheel (`setup.py:26-74`
there); here the native layer is a plain shared library (ctypes-bound,
no torch/pybind11 dependency) built by `csrc/Makefile` and shipped as
package data.  `pip install .` compiles it when a toolchain exists and
falls back to the checked-in binary otherwise (the Python layer also
degrades gracefully at runtime when the library is missing — device
paths never need it).
"""
import subprocess
from pathlib import Path

from setuptools import setup
from setuptools.command.build_py import build_py


class BuildWithNative(build_py):
  def run(self):
    root = Path(__file__).resolve().parent
    try:
      subprocess.run(['make', '-C', str(root / 'csrc')], check=True)
    except (OSError, subprocess.CalledProcessError) as e:
      print(f'[graphlearn-tpu] native build skipped ({e}); '
            'using the bundled libglt_native.so if present')
    super().run()


setup(cmdclass={'build_py': BuildWithNative})
