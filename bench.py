"""Headline benchmark: GraphSAGE epoch time + sampling throughput
+ distributed (virtual-mesh) loader section + fused whole-epoch number.

PRIMARY metric (BASELINE.json: "GraphSAGE epoch time on
ogbn-products"): wall-clock of one full training epoch — seed shuffle
-> multi-hop sampling (fanout [15, 10, 5], batch 1024,
`examples/train_sage_ogbn_products.py:16`) -> feature/label collation
-> fused train step — on an ogbn-products-scale synthetic graph (2.45M
nodes, ~61M directed edges, 100-dim features, ~8% train split).
When the dedicated fused session lands, the HEADLINE `value` is the
whole-epoch `FusedEpoch` time (the same epoch as ONE XLA program);
the per-batch epoch median is always reported alongside.

SECONDARY: the reference's "Sampled Edges per secs" definition
(`benchmarks/api/bench_sampler.py:46-54`), a feature-gather roofline
phase (`achieved_hbm_frac` — bytes moved / HBM peak, v5e 819 GB/s),
and a `dist` section — a P=8 virtual-CPU-mesh distributed loader epoch
(edges/sec/chip, padding-waste %, drop rate from exchange telemetry;
labeled "virtual CPU mesh — relative only", the intent of reference
`benchmarks/api/bench_dist_neighbor_loader.py`).

INDESTRUCTIBLE-ARTIFACT CONTRACT (r3 shipped rc=124 with NO number
because the aggregate printed only once, at the very end): the full
cumulative aggregate JSON line — same schema, updated stats — is
printed after EVERY completed phase (each primary session, the dist
section, the fused session).  The driver's last-JSON-line salvage
therefore always finds the newest complete headline no matter where
the process is killed.  The default total budget is 1200 s (was
3000 s, which overran the driver's wall); phases run in the order
primary -> fused -> dist -> scale-envelope -> extra primary sessions
(the headline fused session outranks the CPU-mesh dist section for
budget) and each clamps itself to the remaining budget.

Honest variance reporting: the tunnel to the chip swings wall-clock
several-fold BETWEEN processes, and within a process only the first
timed burst reflects true device throughput (benchmarks/README,
"first-burst validity").  Sessions are fresh subprocesses; the
per-batch headline is the MEDIAN over completed sessions (min/med/max
reported).  Every session runs the FAST protocol (3-batch warmup
covers the compile, then one measured epoch): measured per-session
cost is ~410 s, dominated by the fixed ~1 GB feature device_put over
the tunnel, so a "full" warmup epoch buys nothing but risk.

``vs_baseline`` divides a NOMINAL single-A100 epoch time of 2.0 s into
the headline (the reference publishes figures, not numbers — 2.0 s is
a mid-range read of public GLT-class A100 pipelines on this workload;
BASELINE.md documents the absence of published values).  > 1.0 means
faster than that nominal A100.

Prints ONE JSON line per completed phase; the LAST line is the
artifact: {"metric", "value", "unit", "vs_baseline", ...}.
"""
import json
import os
import statistics
import subprocess
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from benchmarks.common import (NUM_NODES, build_graph,  # noqa: E402
                               build_graph_csr, cpu_mesh_env)

#: nominal single-A100 epoch seconds (see module docstring)
BASELINE_EPOCH_SECS = 2.0
#: round-1 normalization constant for the secondary sampling metric
BASELINE_EDGES_PER_SEC = 100e6
#: TPU v5e peak HBM bandwidth, bytes/s (public spec; the roofline
#: denominator for `achieved_hbm_frac`)
HBM_PEAK = {'tpu': 819e9}

FANOUT = (15, 10, 5)
BATCH = 1024
DIM = 100
CLASSES = 47
SAMPLE_ITERS = 30

#: dist section: smaller graph (CPU mesh), reference bench workload
DIST_PARTS = 8
DIST_NODES = 500_000
DIST_DIM = 64


def _sample_window_bytes(batch, fanouts):
  """Analytic upper bound on HBM bytes the multihop sampler's window
  gathers move per batch: each hop gathers a ``W = default_window(k)``
  wide int32 window of `indices` per frontier node (`ops/neighbor.py`
  — the exact-without-replacement path; hub nodes with ``deg > W``
  read only k draws, so this is an upper bound).  The same
  bytes-over-peak accounting as the Pallas window writeup
  (`ops/pallas_gather.py:26-42`)."""
  from graphlearn_tpu.ops.neighbor import default_window
  frontier, total = batch, 0
  for k in fanouts:
    total += frontier * default_window(k) * 4
    frontier *= k
  return total


def worker(fused_only: bool = False):
  """One fresh-session measurement: epoch time first (the primary,
  measured on this process's first burst), then sampling throughput,
  then the feature-gather roofline phase.  ``fused_only`` is the
  DEDICATED fused session: same setup, then only the whole-epoch
  `FusedEpoch` measurement — it gets its own session because its
  fresh compile (~250 s) cannot share a 600 s budget with the primary
  phases.  (The fused program itself always bypasses the persistent
  compilation cache — `loader.fused._uncached_jit`, pinned in the
  class after r3's poisoned-cache TPU-worker crashes — so enabling
  the /tmp cache here only speeds the small setup compiles.)"""
  import jax
  try:
    jax.config.update('jax_compilation_cache_dir', '/tmp/glt_jax_cache')
  except Exception:
    pass
  if '--cpu' in sys.argv:
    jax.config.update('jax_platforms', 'cpu')
  import jax.numpy as jnp
  import optax
  from graphlearn_tpu.data import Dataset
  from graphlearn_tpu.loader import NeighborLoader
  from graphlearn_tpu.models import (GraphSAGE, create_train_state,
                                     make_supervised_step)
  from graphlearn_tpu.sampler import NeighborSampler, NodeSamplerInput

  n = NUM_NODES
  indptr, indices, eids = build_graph_csr(n)     # cached across sessions
  rng = np.random.default_rng(0)
  feats = rng.random((n, DIM), dtype=np.float32)
  labels = rng.integers(0, CLASSES, n).astype(np.int32)
  ds = (Dataset()
        .init_graph((indptr, indices), edge_ids=eids, layout='CSR',
                    num_nodes=n)
        .init_node_features(feats, split_ratio=1.0)
        .init_node_labels(labels))
  train_idx = rng.permutation(n)[:max(n // 12, 1)]
  loader = NeighborLoader(ds, list(FANOUT), train_idx, batch_size=BATCH,
                          shuffle=True, seed=0)
  platform = jax.devices()[0].platform
  # the ~1 GB feature upload happens OUTSIDE the compile timing — it
  # is transfer, not compilation, and it dominates the session cost
  feat = ds.node_features
  feat.lazy_init()
  feat.hot_tier.block_until_ready()
  # sampler-pipeline compile = wall of the very first batch
  t0 = time.perf_counter()
  it0 = iter(loader)
  first_batch = next(it0)
  first_batch.x.block_until_ready()
  sampler_compile = time.perf_counter() - t0
  model = GraphSAGE(hidden_features=256, out_features=CLASSES,
                    num_layers=3)
  tx = optax.adam(3e-3)
  state, apply_fn = create_train_state(
      model, jax.random.key(0), first_batch, tx)

  if fused_only:
    result = {'mode': 'fused-session', 'platform': platform}
    try:
      from graphlearn_tpu.loader import FusedEpoch
      fused = FusedEpoch(ds, list(FANOUT), train_idx, apply_fn, tx,
                         batch_size=BATCH, shuffle=True, seed=0,
                         remat=True)
      # two warm runs: first compile, second the donated-input
      # recompile; the third run is the steady state.  Both compile
      # walls are REPORTED (VERDICT r3 #4: compile time is a real
      # deployment cost and was untracked), and the line is
      # CHECKPOINTED after them so a timeout mid-measure still
      # salvages the compile numbers.
      compile_secs = []
      for _ in range(2):
        t0 = time.perf_counter()
        state, _ = fused.run(state)
        jax.tree_util.tree_leaves(state.params)[0].block_until_ready()
        compile_secs.append(round(time.perf_counter() - t0, 1))
      result['fused_compile_secs'] = compile_secs
      print(json.dumps(result), flush=True)
      t0 = time.perf_counter()
      state, _ = fused.run(state)
      jax.tree_util.tree_leaves(state.params)[0].block_until_ready()
      result['epoch_secs_fused'] = time.perf_counter() - t0
    except Exception as e:          # noqa: BLE001
      result['fused_error'] = f'{type(e).__name__}: {e}'[:200]
    print(json.dumps(result), flush=True)
    return

  step = make_supervised_step(apply_fn, tx, BATCH)

  # step compile = wall of the first train-step call; together with
  # the sampler compile above this is the per-batch pipeline's full
  # compile cost (VERDICT r3 #4: compile time tracked in the artifact)
  t0 = time.perf_counter()
  state, loss, _ = step(state, first_batch)
  jax.tree_util.tree_leaves(state.params)[0].block_until_ready()
  compile_secs = sampler_compile + time.perf_counter() - t0
  # warmup: two more batches cover the donated-layout recompile;
  # the next epoch is THE measured first burst
  for i, batch in enumerate(it0):
    state, loss, _ = step(state, batch)
    if i >= 1:
      break
  jax.tree_util.tree_leaves(state.params)[0].block_until_ready()

  t0 = time.perf_counter()
  for batch in loader:
    state, loss, _ = step(state, batch)
  jax.tree_util.tree_leaves(state.params)[0].block_until_ready()
  epoch_secs = time.perf_counter() - t0
  # CHECKPOINT the line after every phase (same contract as the dist
  # worker): a slow-day timeout mid-sampling or mid-gather must not
  # cost the already-measured PRIMARY number — _run_session salvages
  # the last complete line from partial stdout
  result = {'epoch_secs': epoch_secs,
            'compile_secs': round(compile_secs, 1),
            'steps': len(loader), 'mode': 'primary',
            'platform': platform}
  print(json.dumps(result), flush=True)

  # secondary: sampling-only throughput, reference metric definition,
  # plus the window-bytes roofline fraction
  iters = SAMPLE_ITERS
  sampler = NeighborSampler(ds.get_graph(), FANOUT, seed=0)
  srng = np.random.default_rng(1)
  seed_batches = [srng.integers(0, n, BATCH).astype(np.int32)
                  for _ in range(3 + iters)]
  for i in range(3):
    out = sampler.sample_from_nodes(NodeSamplerInput(node=seed_batches[i]))
  out.node.block_until_ready()
  t0 = time.perf_counter()
  outs = [sampler.sample_from_nodes(NodeSamplerInput(node=seed_batches[3 + i]))
          for i in range(iters)]
  for o in outs:
    o.row.block_until_ready()
  dt = time.perf_counter() - t0
  edges = int(sum((o.edge_mask.sum() for o in outs),
                  jnp.zeros((), jnp.int32)))
  sample_hbm = (iters * _sample_window_bytes(BATCH, FANOUT) / dt
                / HBM_PEAK[platform] if platform in HBM_PEAK else None)
  result.update(edges_per_sec=edges / dt,
                sample_hbm_frac=(round(sample_hbm, 4)
                                 if sample_hbm else None))
  print(json.dumps(result), flush=True)

  # roofline phase: feature-store row gather as ONE long program (a
  # fori_loop of random-row gathers) so the tunnel's
  # post-first-burst dispatch overhead (~0.1-0.3 s PER program,
  # benchmarks/README) amortizes against >= 0.7 s of device work at
  # peak — N small dispatches here measured the tunnel, not HBM.
  # A LOWER bound in two ways: dispatch overhead sits inside the
  # wall, and the serialized loop (reduce-carried dependency) runs
  # the gather slower than the epoch's pipelined per-batch programs
  # (r4 probes: ~38 GB/s D=100 / ~48 GB/s D=128 in this regime; the
  # async-dispatch regime could not be measured cleanly — the tunnel
  # elides repeat executions outside the first timed window).
  gather_hbm = gather_gbps = None
  if platform in HBM_PEAK:
    giters, grows = 1500, 1 << 20
    from graphlearn_tpu.ops.pallas_gather import gather_rows

    @jax.jit
    def gather_burst(table, key):
      # ids are DENSE ASCENDING (random start, stride 2) — the hot
      # path's actual pattern: the sampler's node table is
      # sorted-unique (sort_locality), ~40% dense at products scale,
      # and gathered through `gather_rows` (the feature store's
      # primitive).  Fully-random ids measured 37 GB/s on this table
      # (true random-row bandwidth) vs the sorted pattern's streaming
      # rate — report the pattern the store actually sees.
      def body(i, acc):
        k = jax.random.fold_in(key, i)
        start = jax.random.randint(k, (), 0, table.shape[0] - 2 * grows)
        ids = start + 2 * jnp.arange(grows, dtype=jnp.int32)
        return acc + gather_rows(table, ids).sum(dtype=jnp.float32)
      return jax.lax.fori_loop(0, giters, body, jnp.float32(0))

    hot = feat.hot_tier
    gather_burst(hot, jax.random.key(1)).block_until_ready()  # compile
    t0 = time.perf_counter()
    gather_burst(hot, jax.random.key(2)).block_until_ready()
    gdt = time.perf_counter() - t0
    gather_bytes = giters * grows * DIM * 4
    gather_hbm = gather_bytes / gdt / HBM_PEAK[platform]
    gather_gbps = gather_bytes / gdt / 1e9

  result.update(gather_hbm_frac=(round(gather_hbm, 4)
                                 if gather_hbm else None),
                gather_gbps=(round(gather_gbps, 1)
                             if gather_gbps else None))
  print(json.dumps(result), flush=True)


def dist_worker():
  """P=8 virtual-mesh distributed loader epoch (VERDICT r2 item 3):
  the reference dist-bench workload (batch 1024, fanout [15,10,5]) on
  the mesh engine, with capacity-capped exchanges and telemetry-backed
  padding/drop accounting.  CPU-mesh numbers are RELATIVE (no ICI);
  the label says so.  A complete JSON line is printed after every
  phase (base / tiered) so the harness can salvage whatever
  finished."""
  import jax
  # NOTE: deliberately NOT enabling the /tmp compilation cache here —
  # XLA:CPU AOT cache entries recorded with different target-feature
  # sets (prefer-no-scatter/-gather) load with "could lead to SIGILL"
  # errors on this box and killed the worker mid-phase when tried.
  from graphlearn_tpu.parallel import (DistDataset, DistNeighborLoader,
                                       make_mesh)
  assert len(jax.devices()) == DIST_PARTS, jax.devices()
  rows, cols = build_graph(DIST_NODES)
  rng = np.random.default_rng(0)
  feats = rng.random((DIST_NODES, DIST_DIM), dtype=np.float32)
  labels = rng.integers(0, CLASSES, DIST_NODES).astype(np.int32)
  ds = DistDataset.from_full_graph(DIST_PARTS, rows, cols,
                                   node_feat=feats, node_label=labels,
                                   num_nodes=DIST_NODES)
  seeds = rng.permutation(DIST_NODES)[:BATCH * DIST_PARTS * 4]
  loader = DistNeighborLoader(ds, list(FANOUT), seeds, batch_size=BATCH,
                              shuffle=True, mesh=make_mesh(DIST_PARTS),
                              seed=0)
  it = iter(loader)
  t0 = time.perf_counter()
  b = next(it)                      # compile + warm
  b.x.block_until_ready()
  compile_secs = time.perf_counter() - t0
  edges = 0
  t0 = time.perf_counter()
  n_batches = 0
  for b in it:
    edges += int(np.asarray(b.edge_mask.sum()))
    n_batches += 1
  dt = time.perf_counter() - t0
  st = loader.sampler.exchange_stats(tick_metrics=False)
  sent = st['dist.frontier.offered'] - st['dist.frontier.dropped']
  waste = 100.0 * (1 - sent / max(st['dist.frontier.slots'], 1))
  drop = 100.0 * st['dist.frontier.dropped'] / max(
      st['dist.frontier.offered'], 1)
  out = {
      'label': 'virtual CPU mesh - relative only',
      'num_parts': DIST_PARTS, 'batch': BATCH, 'fanout': list(FANOUT),
      'num_nodes': DIST_NODES, 'batches': n_batches,
      'compile_secs': round(compile_secs, 1),
      'edges_per_sec_per_chip': round(edges / dt / DIST_PARTS, 1),
      'seeds_per_sec': round(n_batches * BATCH * DIST_PARTS / dt, 1),
      'padding_waste_pct': round(waste, 2),
      'drop_rate_pct': round(drop, 3),
  }
  # base numbers are safe NOW: if the tiered phase below times out or
  # fails, the harness parser takes the last printed JSON line — this
  # one — instead of losing everything
  print(json.dumps(out), flush=True)
  # tiered store in the MEASURED path (r2 weak #1: the cold tier never
  # appeared in a bench number): same workload, 30% of each
  # partition's rows in "HBM", the rest served by the host overlay
  ds_t = DistDataset.from_full_graph(DIST_PARTS, rows, cols,
                                     node_feat=feats, node_label=labels,
                                     num_nodes=DIST_NODES,
                                     split_ratio=0.3)
  # prefetch=2: the next batch's cold-tier overlay (a host sync) runs
  # on a worker thread while the current batch computes — the overlap
  # the tiered store needs, measured here in the artifact
  lt = DistNeighborLoader(ds_t, list(FANOUT),
                          seeds[:BATCH * DIST_PARTS * 4],
                          batch_size=BATCH, shuffle=True,
                          mesh=make_mesh(DIST_PARTS), seed=0,
                          prefetch=2)
  it = iter(lt)
  b = next(it)
  b.x.block_until_ready()
  t0 = time.perf_counter()
  nt = 0
  for b in it:
    b.x.block_until_ready()
    nt += 1
  dt_t = time.perf_counter() - t0
  st_t = lt.sampler.exchange_stats(tick_metrics=False)
  out['tiered'] = {
      'split_ratio': 0.3, 'prefetch': 2,
      'seeds_per_sec': round(nt * BATCH * DIST_PARTS / max(dt_t, 1e-9),
                             1),
      'cold_hit_rate': round(st_t['dist.feature.cold_hit_rate'], 4),
      'cold_misses': st_t['dist.feature.cold_misses'],
  }
  print(json.dumps(out), flush=True)

  # fused mesh epoch vs per-batch DP loop, SAME shape (r4: previously
  # exiled to `bench_dist_loader.py --fused` on an r3 note claiming
  # >20 min of scan compile at this batch — re-measured this round:
  # the [10,5]/h64-2-layer/B=512 fused program compiles in ~17 s, so
  # the comparison rides in the artifact; the >20 min regime is the
  # HEADLINE model shape [15,10,5]/h256-3-layer, tracked by
  # `benchmarks/bench_compile.py`)
  import optax
  from graphlearn_tpu.models import GraphSAGE, create_train_state
  from graphlearn_tpu.parallel import (FusedDistEpoch,
                                       local_batch_piece,
                                       make_dp_supervised_step,
                                       replicate)
  b2, fan2 = 512, [10, 5]
  mesh2 = make_mesh(DIST_PARTS)
  seeds2 = rng.permutation(DIST_NODES)[:b2 * DIST_PARTS * 4]
  it2 = iter(DistNeighborLoader(ds, fan2, seeds2, batch_size=b2,
                                shuffle=True, mesh=mesh2, seed=0))
  # time the sampling-program compile too, so per_batch_compile_secs
  # covers the SAME span as the fused program (sampling + train) —
  # the worker()'s sampler+step convention
  t0 = time.perf_counter()
  b0 = next(it2)
  b0.x.block_until_ready()
  pb_sampler_compile = time.perf_counter() - t0
  b0_local = local_batch_piece(b0, DIST_PARTS)
  model = GraphSAGE(hidden_features=64, out_features=CLASSES,
                    num_layers=2)
  tx = optax.adam(3e-3)
  state, apply_fn = create_train_state(
      model, jax.random.key(0), b0_local, tx)
  step = make_dp_supervised_step(apply_fn, tx, b2, mesh2)
  state = replicate(state, mesh2)
  t0 = time.perf_counter()
  state, _, _ = step(state, b0)
  jax.tree_util.tree_leaves(state.params)[0].block_until_ready()
  pb_compile = pb_sampler_compile + time.perf_counter() - t0
  npb = 0
  t0 = time.perf_counter()
  for b in it2:
    state, _, _ = step(state, b)
    npb += 1
  jax.tree_util.tree_leaves(state.params)[0].block_until_ready()
  pb_dt = time.perf_counter() - t0
  fused = FusedDistEpoch(ds, fan2, seeds2, apply_fn, tx, batch_size=b2,
                         mesh=mesh2, shuffle=True, seed=0)
  fstate, _ = create_train_state(model, jax.random.key(1), b0_local, tx)
  fstate = replicate(fstate, mesh2)
  t0 = time.perf_counter()
  fstate, _ = fused.run(fstate)
  jax.tree_util.tree_leaves(fstate.params)[0].block_until_ready()
  f_compile = time.perf_counter() - t0
  fstate, _ = fused.run(fstate)         # donated-layout recompile
  jax.tree_util.tree_leaves(fstate.params)[0].block_until_ready()
  t0 = time.perf_counter()
  fstate, _ = fused.run(fstate)
  jax.tree_util.tree_leaves(fstate.params)[0].block_until_ready()
  f_dt = time.perf_counter() - t0
  pb_rate = npb * b2 * DIST_PARTS / max(pb_dt, 1e-9)
  f_rate = len(fused) * b2 * DIST_PARTS / max(f_dt, 1e-9)
  out['fused_mesh'] = {
      'batch': b2, 'fanout': fan2,
      'per_batch_seeds_per_sec': round(pb_rate, 1),
      'fused_seeds_per_sec': round(f_rate, 1),
      'fused_vs_per_batch': round(f_rate / max(pb_rate, 1e-9), 2),
      'per_batch_compile_secs': round(pb_compile, 1),
      'fused_compile_secs': round(f_compile, 1),
  }
  print(json.dumps(out), flush=True)


def _run_session(timeout: int, fused: bool = False):
  cmd = [sys.executable, os.path.abspath(__file__),
         '--fused-session' if fused else '--bench-worker']
  cmd += [a for a in sys.argv[1:]
          if a not in ('--bench-worker', '--fused-session')]
  try:
    out = subprocess.run(cmd, capture_output=True, text=True,
                         cwd=os.path.dirname(os.path.abspath(__file__)),
                         timeout=timeout)
    stdout = out.stdout or ''
    stderr = out.stderr or ''
  except subprocess.TimeoutExpired as e:
    # each session prints one complete JSON line as soon as its
    # numbers exist — salvage whatever made it out before the kill
    # (a timed-out fused session has nothing to salvage; primary
    # sessions keep their result)
    print(f'session timed out after {timeout}s (parsing partial '
          f'output)', file=sys.stderr)
    stdout = e.stdout or b''
    if isinstance(stdout, bytes):
      stdout = stdout.decode(errors='replace')
    stderr = e.stderr or b''
    if isinstance(stderr, bytes):
      stderr = stderr.decode(errors='replace')
  for ln in reversed(stdout.strip().splitlines()):
    if ln.startswith('{'):
      try:
        return json.loads(ln)
      except json.JSONDecodeError:
        continue      # truncated mid-print: fall through to the
                      # previous (complete) line
  print(f'session failed:\n{stdout[-2000:]}\n{stderr[-2000:]}',
        file=sys.stderr)
  return None


def _run_dist_section(timeout: int):
  cmd = [sys.executable, os.path.abspath(__file__), '--dist-worker']
  timed_out = False
  try:
    out = subprocess.run(cmd, capture_output=True, text=True,
                         cwd=os.path.dirname(os.path.abspath(__file__)),
                         env=cpu_mesh_env(DIST_PARTS), timeout=timeout)
    stdout, stderr = out.stdout or '', out.stderr or ''
  except subprocess.TimeoutExpired as e:
    # the worker prints a complete JSON line after EVERY phase —
    # salvage the last one instead of losing base+tiered to a slow
    # bonus phase (measured: the same phases swing 330 s to 900 s+
    # between days on this box)
    timed_out = True
    stdout = e.stdout or b''
    if isinstance(stdout, bytes):
      stdout = stdout.decode(errors='replace')
    stderr = e.stderr or b''
    if isinstance(stderr, bytes):
      stderr = stderr.decode(errors='replace')
  for ln in reversed(stdout.strip().splitlines()):
    if ln.startswith('{'):
      try:
        r = json.loads(ln)
      except json.JSONDecodeError:
        continue
      if timed_out:
        r['note'] = f'partial: dist worker hit the {timeout}s budget'
      return r
  cause = (f'timed out after {timeout}s with no JSON'
           if timed_out else 'failed')
  return {'error': f'dist section {cause}: {stderr[-500:]}'}


def _run_envelope_row(num_parts: int, batch: int, timeout: int):
  """One P-row of the scale envelope (VERDICT r3 #6): spawn the tiny
  `bench_dist_loader.py --envelope-worker` config on a ``num_parts``
  virtual mesh and parse its JSON line (None on failure/timeout)."""
  script = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        'benchmarks', 'bench_dist_loader.py')
  cmd = [sys.executable, script, '--envelope-worker', '--num-parts',
         str(num_parts), '--mode', 'homo', '--batch', str(batch),
         '--nodes', '20000']
  try:
    out = subprocess.run(cmd, capture_output=True, text=True,
                         env=cpu_mesh_env(num_parts), timeout=timeout)
  except subprocess.TimeoutExpired:
    return None
  for ln in reversed((out.stdout or '').strip().splitlines()):
    if ln.startswith('{'):
      try:
        return json.loads(ln)
      except json.JSONDecodeError:
        continue
  return None


def _aggregate(results, fused_res, dist):
  """The full artifact schema from whatever phases have completed so
  far.  The HEADLINE `value` is the fused whole-epoch time when the
  fused session has landed, else the per-batch epoch median; the
  metric string names which.  Printed after EVERY completed phase —
  the last JSON line on stdout is always the newest complete
  aggregate, so a kill at ANY point leaves a parseable artifact."""
  # salvaged sessions may carry only a PREFIX of the phases (the
  # worker checkpoints its line after each one) — aggregate whatever
  # keys exist
  ep = sorted(r['epoch_secs'] for r in results if 'epoch_secs' in r)
  es = sorted(r['edges_per_sec'] for r in results
              if 'edges_per_sec' in r)
  cs = sorted(r['compile_secs'] for r in results if 'compile_secs' in r)
  fu = ([fused_res['epoch_secs_fused']]
        if fused_res and 'epoch_secs_fused' in fused_res else [])
  med_ep = statistics.median(ep) if ep else None
  med_es = statistics.median(es) if es else None
  platform = (results[0]['platform'] if results
              else (fused_res or {}).get('platform', '?'))
  shape = (f'products-scale synthetic, fanout {list(FANOUT)}, '
           f'batch {BATCH}, {platform}')
  if fu:
    metric = f'graphsage_fused_epoch_secs ({shape})'
    value = round(fu[0], 4)
  elif med_ep is not None:
    metric = f'graphsage_epoch_secs ({shape})'
    value = round(med_ep, 4)
  else:
    metric = f'graphsage_epoch_secs ({shape})'
    value = None
  hbm = {}
  for k in ('sample_hbm_frac', 'gather_hbm_frac'):
    v = [r[k] for r in results if r.get(k) is not None]
    if v:
      hbm[k.replace('_hbm_frac', '')] = round(statistics.median(v), 4)
  return {
      'metric': metric,
      'value': value,
      'unit': 's',
      'vs_baseline': (round(BASELINE_EPOCH_SECS / value, 4)
                      if value else None),
      'epoch_secs_min_med_max': ([round(ep[0], 4), round(med_ep, 4),
                                  round(ep[-1], 4)] if ep else None),
      'epoch_vs_baseline': (round(BASELINE_EPOCH_SECS / med_ep, 4)
                            if med_ep else None),
      'sampled_edges_per_sec_M_min_med_max': (
          [round(es[0] / 1e6, 1), round(med_es / 1e6, 1),
           round(es[-1] / 1e6, 1)] if es else None),
      'sampling_vs_a100_nominal': (round(med_es / BASELINE_EDGES_PER_SEC,
                                         2) if med_es else None),
      'fused_epoch_secs': round(fu[0], 4) if fu else None,
      'fused_vs_baseline': (round(BASELINE_EPOCH_SECS / fu[0], 4)
                            if fu else None),
      'fused_compile_secs': (fused_res or {}).get('fused_compile_secs'),
      'fused_error': (fused_res or {}).get('fused_error'),
      'compile_secs_med': (round(statistics.median(cs), 1)
                           if cs else None),
      'achieved_hbm_frac': hbm or None,
      'sessions': len(results),
      'session_modes': [r['mode'] for r in results],
      'steps_per_epoch': results[0]['steps'] if results else None,
      'dist': dist,
  }


def main():
  sessions = int(os.environ.get('GLT_BENCH_SESSIONS', 5))
  build_graph_csr(NUM_NODES)      # warm the /tmp graph+CSR caches once
  # measured ~410 s per session on an idle box (fixed overhead — the
  # ~1 GB feature device_put over the tunnel — dominates); 600 leaves
  # headroom for load without letting a wedged chip eat the budget
  session_timeout = int(os.environ.get('GLT_BENCH_SESSION_TIMEOUT', 600))
  # hard wall for the whole harness, sized INSIDE the driver's wall
  # (r3's 3000 s default overran it and shipped nothing): one primary
  # session + the dist phase + the fused session fit a typical day
  # (~410 + ~330 + ~450 s); slow days degrade phase by phase, each
  # one leaving a fresh cumulative artifact line behind
  total_budget = float(os.environ.get('GLT_BENCH_TOTAL_BUDGET', 1200))
  # measured ~5.5 min on this box (compile dominates); the wall keeps
  # a wedged mesh from eating the whole budget, not a perf target
  dist_timeout = int(os.environ.get('GLT_BENCH_DIST_TIMEOUT', 600))
  fused_timeout = int(os.environ.get('GLT_BENCH_FUSED_TIMEOUT', 600))
  t_start = time.time()

  def budget_left():
    return total_budget - (time.time() - t_start)

  results, fused_res, dist = [], None, None

  def emit():
    """The indestructible-artifact contract: full cumulative
    aggregate after every completed phase."""
    if results or fused_res or dist:
      print(json.dumps(_aggregate(results, fused_res, dist)),
            flush=True)

  # phase 1 — one primary session (epoch + sampling + roofline).
  # Retry up to 3 attempts while nothing has landed and the budget
  # still leaves room for the later phases to salvage something.
  attempts = 0
  while not results and attempts < 3:
    tmo = int(min(session_timeout, max(budget_left() - 60, 120)))
    if budget_left() < 180:
      print(f'budget: giving up on primary after {attempts} attempts',
            file=sys.stderr)
      break
    r = _run_session(tmo)
    attempts += 1
    if r is not None:
      results.append(r)
      emit()

  # phase 2 — dedicated fused session (whole-epoch FusedEpoch,
  # ALWAYS a fresh compile after the latch fix, ~400-500 s): lands
  # the HEADLINE number, so it outranks the dist section for budget —
  # the dist worker salvages per-phase no matter how little remains
  if budget_left() > 150:
    fused_res = _run_session(
        int(min(fused_timeout, max(budget_left() - 10, 120))),
        fused=True)
    emit()
  else:
    print(f'budget: skipping the fused session '
          f'({budget_left():.0f}s left)', file=sys.stderr)

  # phase 3 — dist section (CPU mesh; tunnel-independent; emits a
  # complete JSON line after EVERY internal phase, so even a heavily
  # clamped timeout records base numbers)
  if budget_left() > 90:
    dist = _run_dist_section(
        int(min(dist_timeout, max(budget_left() - 30, 60))))
    emit()
  else:
    print(f'budget: skipping dist ({budget_left():.0f}s left)',
          file=sys.stderr)

  # opportunistic — per-P scale-envelope rows for the dist section
  # (VERDICT r3 #6): P=16/64 homo exchange accounting; the full sweep
  # (P<=128, hetero, chunked-SEAL) is
  # `benchmarks/bench_dist_loader.py --capacity-sweep`
  if isinstance(dist, dict) and 'error' not in dist \
      and budget_left() > 300:
    env_rows = []
    for p_, bsz in ((16, 64), (64, 32)):
      if budget_left() < 200:
        break
      r = _run_envelope_row(p_, bsz,
                            int(min(280, max(budget_left() - 30, 60))))
      if r is not None:
        env_rows.append(r)
    if env_rows:
      dist['scale_envelope'] = env_rows
      emit()

  # phase 4 — extra primary sessions stabilize the per-batch median
  # (fast days only; each one re-emits the cumulative aggregate)
  while (len(results) < sessions and attempts < sessions + 3
         and budget_left() > session_timeout * 0.75):
    r = _run_session(int(min(session_timeout, budget_left())))
    attempts += 1
    if r is not None:
      results.append(r)
      emit()

  if not (results or fused_res or dist):
    raise SystemExit('all bench phases failed')
  emit()                            # final (possibly repeated) line


if __name__ == '__main__':
  if '--dist-worker' in sys.argv:
    dist_worker()
  elif '--fused-session' in sys.argv:
    worker(fused_only=True)
  elif '--bench-worker' in sys.argv:
    worker()
  else:
    main()
