"""Headline benchmark: GraphSAGE epoch time + sampling throughput
+ distributed (virtual-mesh) loader section.

PRIMARY metric (BASELINE.json: "GraphSAGE epoch time on
ogbn-products"): wall-clock of one full training epoch — seed shuffle
-> multi-hop sampling (fanout [15, 10, 5], batch 1024,
`examples/train_sage_ogbn_products.py:16`) -> feature/label collation
-> fused train step — on an ogbn-products-scale synthetic graph (2.45M
nodes, ~61M directed edges, 100-dim features, ~8% train split).

SECONDARY: the reference's "Sampled Edges per secs" definition
(`benchmarks/api/bench_sampler.py:46-54`), and a `dist` section — a
P=8 virtual-CPU-mesh distributed loader epoch (edges/sec/chip,
padding-waste %, drop rate from the exchange telemetry; labeled
"virtual CPU mesh — relative only", the intent of reference
`benchmarks/api/bench_dist_neighbor_loader.py`).

Honest variance reporting: the tunnel to the chip swings wall-clock
several-fold BETWEEN processes, and within a process only the first
timed burst reflects true device throughput (benchmarks/README,
"first-burst validity").  The harness runs ``GLT_BENCH_SESSIONS``
(default 5) fresh subprocess sessions and reports min/median/max; the
headline `value` is the MEDIAN epoch time.  Session 0 runs the full
protocol (warmup epoch + measured epoch); later sessions run a FAST
protocol (3-batch warmup covers the compile, then one measured epoch)
so a slow-tunnel day still yields >= 3 sessions inside the budget
(r2's harness lost 3 of 5 sessions to one 480 s timeout).

``vs_baseline`` divides a NOMINAL single-A100 epoch time of 2.0 s into
the median (the reference publishes figures, not numbers — 2.0 s is a
mid-range read of public GLT-class A100 pipelines on this workload;
BASELINE.md documents the absence of published values).  > 1.0 means
faster than that nominal A100.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.
"""
import json
import os
import statistics
import subprocess
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from benchmarks.common import (NUM_NODES, build_graph,  # noqa: E402
                               build_graph_csr, cpu_mesh_env)

#: nominal single-A100 epoch seconds (see module docstring)
BASELINE_EPOCH_SECS = 2.0
#: round-1 normalization constant for the secondary sampling metric
BASELINE_EDGES_PER_SEC = 100e6

FANOUT = (15, 10, 5)
BATCH = 1024
DIM = 100
CLASSES = 47
SAMPLE_ITERS = 30

#: dist section: smaller graph (CPU mesh), reference bench workload
DIST_PARTS = 8
DIST_NODES = 500_000
DIST_DIM = 64


def worker(fast: bool, fused_only: bool = False):
  """One fresh-session measurement: epoch time first (the primary,
  measured on this process's first burst), then sampling throughput.
  ``fast`` warms up on 3 batches (covers the compile — every batch
  shares one static shape) instead of a full epoch.  ``fused_only``
  is the DEDICATED fused session: same setup, then only the
  whole-epoch `FusedEpoch` measurement — it gets its own session
  because its fresh compile (~250 s, see below) cannot share a 600 s
  budget with the primary phases."""
  import jax
  if not fused_only:
    # NO compilation cache in the fused session — not even for the
    # setup compiles: jax initializes the cache once, at the FIRST
    # compile, and later config updates are ignored, so setting the
    # dir to None just before the fused compile would be a no-op and
    # the fused program would still load the poisoned cached
    # executable (see below)
    try:
      jax.config.update('jax_compilation_cache_dir',
                        '/tmp/glt_jax_cache')
    except Exception:
      pass
  if '--cpu' in sys.argv:
    jax.config.update('jax_platforms', 'cpu')
  import jax.numpy as jnp
  import optax
  from graphlearn_tpu.data import Dataset
  from graphlearn_tpu.loader import NeighborLoader
  from graphlearn_tpu.models import (GraphSAGE, create_train_state,
                                     make_supervised_step)
  from graphlearn_tpu.sampler import NeighborSampler, NodeSamplerInput

  n = NUM_NODES
  indptr, indices, eids = build_graph_csr(n)     # cached across sessions
  rng = np.random.default_rng(0)
  feats = rng.random((n, DIM), dtype=np.float32)
  labels = rng.integers(0, CLASSES, n).astype(np.int32)
  ds = (Dataset()
        .init_graph((indptr, indices), edge_ids=eids, layout='CSR',
                    num_nodes=n)
        .init_node_features(feats, split_ratio=1.0)
        .init_node_labels(labels))
  train_idx = rng.permutation(n)[:max(n // 12, 1)]
  loader = NeighborLoader(ds, list(FANOUT), train_idx, batch_size=BATCH,
                          shuffle=True, seed=0)
  model = GraphSAGE(hidden_features=256, out_features=CLASSES,
                    num_layers=3)
  tx = optax.adam(3e-3)
  state, apply_fn = create_train_state(
      model, jax.random.key(0), next(iter(loader)), tx)

  if fused_only:
    result = {'mode': 'fused-session',
              'platform': jax.devices()[0].platform}
    try:
      # compiles FRESH, never from the /tmp cache (never configured in
      # this process — see the fused_only gate at the top): executing
      # the DESERIALIZED cached fused program crashes the tunneled TPU
      # worker ("TPU device error"), while the same program compiled
      # from scratch runs clean — reproduced both ways back to back.
      from graphlearn_tpu.loader import FusedEpoch
      fused = FusedEpoch(ds, list(FANOUT), train_idx, apply_fn, tx,
                         batch_size=BATCH, shuffle=True, seed=0,
                         remat=True)
      # two warm runs: first compile, second the donated-input
      # recompile; the third run is the steady state
      for _ in range(2):
        state, _ = fused.run(state)
      jax.tree_util.tree_leaves(state.params)[0].block_until_ready()
      t0 = time.perf_counter()
      state, _ = fused.run(state)
      jax.tree_util.tree_leaves(state.params)[0].block_until_ready()
      result['epoch_secs_fused'] = time.perf_counter() - t0
    except Exception as e:          # noqa: BLE001
      result['fused_error'] = f'{type(e).__name__}: {e}'[:200]
    print(json.dumps(result), flush=True)
    return

  step = make_supervised_step(apply_fn, tx, BATCH)

  # warmup covers compile; the next epoch is THE measured first burst
  if fast:
    for i, batch in enumerate(loader):
      state, loss, _ = step(state, batch)
      if i >= 2:
        break
    jax.tree_util.tree_leaves(state.params)[0].block_until_ready()
    epochs = (1,)
  else:
    epochs = (0, 1)
  epoch_secs = None
  for epoch in epochs:
    t0 = time.perf_counter()
    for batch in loader:
      state, loss, _ = step(state, batch)
    jax.tree_util.tree_leaves(state.params)[0].block_until_ready()
    if epoch == 1 or fast:
      epoch_secs = time.perf_counter() - t0

  # secondary: sampling-only throughput, reference metric definition
  iters = 10 if fast else SAMPLE_ITERS
  sampler = NeighborSampler(ds.get_graph(), FANOUT, seed=0)
  srng = np.random.default_rng(1)
  seed_batches = [srng.integers(0, n, BATCH).astype(np.int32)
                  for _ in range(3 + iters)]
  for i in range(3):
    out = sampler.sample_from_nodes(NodeSamplerInput(node=seed_batches[i]))
  out.node.block_until_ready()
  t0 = time.perf_counter()
  outs = [sampler.sample_from_nodes(NodeSamplerInput(node=seed_batches[3 + i]))
          for i in range(iters)]
  for o in outs:
    o.row.block_until_ready()
  dt = time.perf_counter() - t0
  edges = int(sum((o.edge_mask.sum() for o in outs),
                  jnp.zeros((), jnp.int32)))
  print(json.dumps({'epoch_secs': epoch_secs,
                    'edges_per_sec': edges / dt,
                    'steps': len(loader),
                    'mode': 'fast' if fast else 'full',
                    'platform': jax.devices()[0].platform}),
        flush=True)


def dist_worker():
  """P=8 virtual-mesh distributed loader epoch (VERDICT r2 item 3):
  the reference dist-bench workload (batch 1024, fanout [15,10,5]) on
  the mesh engine, with capacity-capped exchanges and telemetry-backed
  padding/drop accounting.  CPU-mesh numbers are RELATIVE (no ICI);
  the label says so.  A complete JSON line is printed after every
  phase (base / tiered) so the harness can salvage whatever
  finished."""
  import jax
  # NOTE: deliberately NOT enabling the /tmp compilation cache here —
  # XLA:CPU AOT cache entries recorded with different target-feature
  # sets (prefer-no-scatter/-gather) load with "could lead to SIGILL"
  # errors on this box and killed the worker mid-phase when tried.
  from graphlearn_tpu.parallel import (DistDataset, DistNeighborLoader,
                                       make_mesh)
  assert len(jax.devices()) == DIST_PARTS, jax.devices()
  rows, cols = build_graph(DIST_NODES)
  rng = np.random.default_rng(0)
  feats = rng.random((DIST_NODES, DIST_DIM), dtype=np.float32)
  labels = rng.integers(0, CLASSES, DIST_NODES).astype(np.int32)
  ds = DistDataset.from_full_graph(DIST_PARTS, rows, cols,
                                   node_feat=feats, node_label=labels,
                                   num_nodes=DIST_NODES)
  seeds = rng.permutation(DIST_NODES)[:BATCH * DIST_PARTS * 4]
  loader = DistNeighborLoader(ds, list(FANOUT), seeds, batch_size=BATCH,
                              shuffle=True, mesh=make_mesh(DIST_PARTS),
                              seed=0)
  it = iter(loader)
  b = next(it)                      # compile + warm
  b.x.block_until_ready()
  edges = 0
  t0 = time.perf_counter()
  n_batches = 0
  for b in it:
    edges += int(np.asarray(b.edge_mask.sum()))
    n_batches += 1
  dt = time.perf_counter() - t0
  st = loader.sampler.exchange_stats(tick_metrics=False)
  sent = st['dist.frontier.offered'] - st['dist.frontier.dropped']
  waste = 100.0 * (1 - sent / max(st['dist.frontier.slots'], 1))
  drop = 100.0 * st['dist.frontier.dropped'] / max(
      st['dist.frontier.offered'], 1)
  out = {
      'label': 'virtual CPU mesh - relative only',
      'num_parts': DIST_PARTS, 'batch': BATCH, 'fanout': list(FANOUT),
      'num_nodes': DIST_NODES, 'batches': n_batches,
      'edges_per_sec_per_chip': round(edges / dt / DIST_PARTS, 1),
      'seeds_per_sec': round(n_batches * BATCH * DIST_PARTS / dt, 1),
      'padding_waste_pct': round(waste, 2),
      'drop_rate_pct': round(drop, 3),
  }
  # base numbers are safe NOW: if the tiered phase below times out or
  # fails, the harness parser takes the last printed JSON line — this
  # one — instead of losing everything
  print(json.dumps(out), flush=True)
  # tiered store in the MEASURED path (r2 weak #1: the cold tier never
  # appeared in a bench number): same workload, 30% of each
  # partition's rows in "HBM", the rest served by the host overlay
  ds_t = DistDataset.from_full_graph(DIST_PARTS, rows, cols,
                                     node_feat=feats, node_label=labels,
                                     num_nodes=DIST_NODES,
                                     split_ratio=0.3)
  # prefetch=2: the next batch's cold-tier overlay (a host sync) runs
  # on a worker thread while the current batch computes — the overlap
  # the tiered store needs, measured here in the artifact
  lt = DistNeighborLoader(ds_t, list(FANOUT),
                          seeds[:BATCH * DIST_PARTS * 4],
                          batch_size=BATCH, shuffle=True,
                          mesh=make_mesh(DIST_PARTS), seed=0,
                          prefetch=2)
  it = iter(lt)
  b = next(it)
  b.x.block_until_ready()
  t0 = time.perf_counter()
  nt = 0
  for b in it:
    b.x.block_until_ready()
    nt += 1
  dt_t = time.perf_counter() - t0
  st_t = lt.sampler.exchange_stats(tick_metrics=False)
  out['tiered'] = {
      'split_ratio': 0.3, 'prefetch': 2,
      'seeds_per_sec': round(nt * BATCH * DIST_PARTS / max(dt_t, 1e-9),
                             1),
      'cold_hit_rate': round(st_t['dist.feature.cold_hit_rate'], 4),
      'cold_misses': st_t['dist.feature.cold_misses'],
  }
  print(json.dumps(out), flush=True)

  # NOTE: the FusedDistEpoch-vs-per-batch comparison lives in
  # `benchmarks/bench_dist_loader.py --fused`, NOT here: its two
  # extra CPU-mesh scan compiles need >20 min at this batch size
  # (measured), which no session budget survives.  The artifact keeps
  # base+tiered; the fused mesh path is covered by
  # tests/test_fused_dist_epoch.py and the standalone benchmark.


def _run_session(fast: bool, timeout: int, fused: bool = False):
  cmd = [sys.executable, os.path.abspath(__file__),
         '--fused-session' if fused else '--bench-worker']
  if fast:
    cmd.append('--fast')
  cmd += [a for a in sys.argv[1:]
          if a not in ('--bench-worker', '--fused-session', '--fast')]
  try:
    out = subprocess.run(cmd, capture_output=True, text=True,
                         cwd=os.path.dirname(os.path.abspath(__file__)),
                         timeout=timeout)
    stdout = out.stdout or ''
    stderr = out.stderr or ''
  except subprocess.TimeoutExpired as e:
    # each session prints one complete JSON line as soon as its
    # numbers exist — salvage whatever made it out before the kill
    # (a timed-out fused session has nothing to salvage; primary
    # sessions keep their result)
    print(f'session timed out after {timeout}s (parsing partial '
          f'output)', file=sys.stderr)
    stdout = e.stdout or b''
    if isinstance(stdout, bytes):
      stdout = stdout.decode(errors='replace')
    stderr = e.stderr or b''
    if isinstance(stderr, bytes):
      stderr = stderr.decode(errors='replace')
  for ln in reversed(stdout.strip().splitlines()):
    if ln.startswith('{'):
      try:
        return json.loads(ln)
      except json.JSONDecodeError:
        continue      # truncated mid-print: fall through to the
                      # previous (complete) line
  print(f'session failed:\n{stdout[-2000:]}\n{stderr[-2000:]}',
        file=sys.stderr)
  return None


def _run_dist_section(timeout: int):
  cmd = [sys.executable, os.path.abspath(__file__), '--dist-worker']
  timed_out = False
  try:
    out = subprocess.run(cmd, capture_output=True, text=True,
                         cwd=os.path.dirname(os.path.abspath(__file__)),
                         env=cpu_mesh_env(DIST_PARTS), timeout=timeout)
    stdout, stderr = out.stdout or '', out.stderr or ''
  except subprocess.TimeoutExpired as e:
    # the worker prints a complete JSON line after EVERY phase —
    # salvage the last one instead of losing base+tiered to a slow
    # bonus phase (measured: the same phases swing 330 s to 900 s+
    # between days on this box)
    timed_out = True
    stdout = e.stdout or b''
    if isinstance(stdout, bytes):
      stdout = stdout.decode(errors='replace')
    stderr = e.stderr or b''
    if isinstance(stderr, bytes):
      stderr = stderr.decode(errors='replace')
  for ln in reversed(stdout.strip().splitlines()):
    if ln.startswith('{'):
      try:
        r = json.loads(ln)
      except json.JSONDecodeError:
        continue
      if timed_out:
        r['note'] = f'partial: dist worker hit the {timeout}s budget'
      return r
  cause = (f'timed out after {timeout}s with no JSON'
           if timed_out else 'failed')
  return {'error': f'dist section {cause}: {stderr[-500:]}'}


def main():
  sessions = int(os.environ.get('GLT_BENCH_SESSIONS', 5))
  build_graph_csr(NUM_NODES)      # warm the /tmp graph+CSR caches once
  # measured ~410 s per session on an idle box (fixed overhead — the
  # ~1 GB feature device_put over the tunnel — dominates); 600 leaves
  # headroom for load without letting a wedged chip eat the budget
  session_timeout = int(os.environ.get('GLT_BENCH_SESSION_TIMEOUT', 600))
  # fast sessions do LESS WORK, not less time: the fixed overhead is
  # identical, so a shorter timeout would just re-lose them on slow
  # days (r2's failure mode)
  fast_timeout = session_timeout
  # hard wall for the whole harness: tunnel-slow days must yield a
  # degraded (fewer-session) number, never a timeout with NO number;
  # sized for 3 x 600 s slow-day sessions + the fused session + the
  # dist phase (fast days fit all 5 primary sessions instead)
  total_budget = float(os.environ.get('GLT_BENCH_TOTAL_BUDGET', 3000))
  # measured ~5.5 min on this box (compile dominates); the wall keeps
  # a wedged mesh from eating the whole budget, not a perf target
  dist_timeout = int(os.environ.get('GLT_BENCH_DIST_TIMEOUT', 600))
  fused_timeout = int(os.environ.get('GLT_BENCH_FUSED_TIMEOUT', 600))
  t_start = time.time()

  def budget_left():
    return total_budget - (time.time() - t_start)

  results = []
  attempts = 0
  # session 0 full, the rest fast; keep attempting (within budget)
  # until the floor is met — never fewer because one timed out.  The
  # floor respects an EXPLICIT lower GLT_BENCH_SESSIONS (smoke runs).
  floor = min(3, sessions)
  while attempts < sessions + 3 and (len(results) < sessions
                                     or len(results) < floor):
    fast = attempts > 0
    tmo = fast_timeout if fast else session_timeout
    # the session floor is the hard deliverable (r2 shipped 2): only
    # once it's met does the budget guard start reserving the fused
    # session and the dist phase (which itself self-clamps to the
    # remaining budget).  The wall also binds with ZERO results — a
    # wedged chip must fail within ~the budget, not after sessions+3
    # timeouts.
    reserve = (dist_timeout + fused_timeout
               if len(results) >= floor else 60)
    if attempts > 0 and budget_left() < tmo + reserve:
      print(f'budget: stopping after {len(results)} sessions '
            f'({attempts} attempts)', file=sys.stderr)
      break
    if attempts >= sessions and len(results) >= 3:
      break
    r = _run_session(fast, tmo)
    attempts += 1
    if r is not None:
      results.append(r)
  if not results:
    raise SystemExit('all bench sessions failed')

  # dedicated fused session (whole-epoch FusedEpoch, fresh compile —
  # ~350-450 s): bonus, only with budget to spare beyond the dist
  # phase; a failure or skip costs nothing but the fused stats
  fused_res = None
  # reserve a realistic dist-phase cushion (measured ~330 s) beyond
  # the fused session itself: the bonus must never starve the dist
  # numbers out of the artifact
  if budget_left() > fused_timeout + 400:
    fused_res = _run_session(True, fused_timeout, fused=True)
  else:
    print(f'budget: skipping the fused session '
          f'({budget_left():.0f}s left)', file=sys.stderr)

  dist = _run_dist_section(min(dist_timeout, max(int(budget_left()), 60)))

  ep = sorted(r['epoch_secs'] for r in results)
  es = sorted(r['edges_per_sec'] for r in results)
  fu = ([fused_res['epoch_secs_fused']]
        if fused_res and 'epoch_secs_fused' in fused_res else [])
  med_ep = statistics.median(ep)
  med_es = statistics.median(es)
  print(json.dumps({
      'metric': f'graphsage_epoch_secs (products-scale synthetic, '
                f'fanout {list(FANOUT)}, batch {BATCH}, '
                f'{results[0]["platform"]})',
      'value': round(med_ep, 4),
      'unit': 's',
      'vs_baseline': round(BASELINE_EPOCH_SECS / med_ep, 4),
      'epoch_secs_min_med_max': [round(ep[0], 4), round(med_ep, 4),
                                 round(ep[-1], 4)],
      'sampled_edges_per_sec_M_min_med_max': [
          round(es[0] / 1e6, 1), round(med_es / 1e6, 1),
          round(es[-1] / 1e6, 1)],
      'sampling_vs_a100_nominal': round(med_es / BASELINE_EDGES_PER_SEC,
                                        2),
      'fused_epoch_secs': round(fu[0], 4) if fu else None,
      'fused_vs_baseline': (round(BASELINE_EPOCH_SECS / fu[0], 4)
                            if fu else None),
      'fused_error': (fused_res or {}).get('fused_error'),
      'sessions': len(results),
      'session_modes': [r['mode'] for r in results],
      'steps_per_epoch': results[0]['steps'],
      'dist': dist,
  }))


if __name__ == '__main__':
  if '--dist-worker' in sys.argv:
    dist_worker()
  elif '--fused-session' in sys.argv:
    worker(fast=True, fused_only=True)
  elif '--bench-worker' in sys.argv:
    worker(fast='--fast' in sys.argv)
  else:
    main()
