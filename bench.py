"""Headline benchmark: GraphSAGE epoch time + sampling throughput.

PRIMARY metric (BASELINE.json: "GraphSAGE epoch time on
ogbn-products"): wall-clock of one full training epoch — seed shuffle
-> multi-hop sampling (fanout [15, 10, 5], batch 1024,
`examples/train_sage_ogbn_products.py:16`) -> feature/label collation
-> fused train step — on an ogbn-products-scale synthetic graph (2.45M
nodes, ~61M directed edges, 100-dim features, ~8% train split).

SECONDARY: the reference's "Sampled Edges per secs" definition
(`benchmarks/api/bench_sampler.py:46-54`).

Honest variance reporting: the tunnel to the chip swings wall-clock
several-fold BETWEEN processes, and within a process only the first
timed burst reflects true device throughput (benchmarks/README,
"first-burst validity").  So the harness runs ``GLT_BENCH_SESSIONS``
(default 5) fresh subprocess sessions and reports min/median/max
across them; the headline `value` is the MEDIAN epoch time.

``vs_baseline`` divides a NOMINAL single-A100 epoch time of 2.0 s into
the median (the reference publishes figures, not numbers — 2.0 s is a
mid-range read of public GLT-class A100 pipelines on this workload;
BASELINE.md documents the absence of published values).  > 1.0 means
faster than that nominal A100.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.
"""
import json
import os
import statistics
import subprocess
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from benchmarks.common import (NUM_NODES, build_graph,  # noqa: E402
                               build_graph_csr)

#: nominal single-A100 epoch seconds (see module docstring)
BASELINE_EPOCH_SECS = 2.0
#: round-1 normalization constant for the secondary sampling metric
BASELINE_EDGES_PER_SEC = 100e6

FANOUT = (15, 10, 5)
BATCH = 1024
DIM = 100
CLASSES = 47
SAMPLE_ITERS = 30


def worker():
  """One fresh-session measurement: epoch time first (the primary,
  measured on this process's first burst), then sampling throughput."""
  import jax
  try:
    jax.config.update('jax_compilation_cache_dir', '/tmp/glt_jax_cache')
  except Exception:
    pass
  if '--cpu' in sys.argv:
    jax.config.update('jax_platforms', 'cpu')
  import jax.numpy as jnp
  import optax
  from graphlearn_tpu.data import Dataset
  from graphlearn_tpu.loader import NeighborLoader
  from graphlearn_tpu.models import (GraphSAGE, create_train_state,
                                     make_supervised_step)
  from graphlearn_tpu.sampler import NeighborSampler, NodeSamplerInput

  n = NUM_NODES
  indptr, indices, eids = build_graph_csr(n)     # cached across sessions
  rng = np.random.default_rng(0)
  feats = rng.random((n, DIM), dtype=np.float32)
  labels = rng.integers(0, CLASSES, n).astype(np.int32)
  ds = (Dataset()
        .init_graph((indptr, indices), edge_ids=eids, layout='CSR',
                    num_nodes=n)
        .init_node_features(feats, split_ratio=1.0)
        .init_node_labels(labels))
  train_idx = rng.permutation(n)[:max(n // 12, 1)]
  loader = NeighborLoader(ds, list(FANOUT), train_idx, batch_size=BATCH,
                          shuffle=True, seed=0)
  model = GraphSAGE(hidden_features=256, out_features=CLASSES,
                    num_layers=3)
  tx = optax.adam(3e-3)
  state, apply_fn = create_train_state(
      model, jax.random.key(0), next(iter(loader)), tx)
  step = make_supervised_step(apply_fn, tx, BATCH)

  # epoch 0 = warmup/compile; epoch 1 = THE measured first burst
  epoch_secs = None
  for epoch in range(2):
    t0 = time.perf_counter()
    for batch in loader:
      state, loss, _ = step(state, batch)
    jax.tree_util.tree_leaves(state.params)[0].block_until_ready()
    if epoch == 1:
      epoch_secs = time.perf_counter() - t0

  # secondary: sampling-only throughput, reference metric definition
  sampler = NeighborSampler(ds.get_graph(), FANOUT, seed=0)
  srng = np.random.default_rng(1)
  seed_batches = [srng.integers(0, n, BATCH).astype(np.int32)
                  for _ in range(3 + SAMPLE_ITERS)]
  for i in range(3):
    out = sampler.sample_from_nodes(NodeSamplerInput(node=seed_batches[i]))
  out.node.block_until_ready()
  t0 = time.perf_counter()
  outs = [sampler.sample_from_nodes(NodeSamplerInput(node=seed_batches[3 + i]))
          for i in range(SAMPLE_ITERS)]
  for o in outs:
    o.row.block_until_ready()
  dt = time.perf_counter() - t0
  edges = int(sum((o.edge_mask.sum() for o in outs),
                  jnp.zeros((), jnp.int32)))
  print(json.dumps({'epoch_secs': epoch_secs,
                    'edges_per_sec': edges / dt,
                    'steps': len(loader),
                    'platform': jax.devices()[0].platform}),
        flush=True)


def main():
  sessions = int(os.environ.get('GLT_BENCH_SESSIONS', 5))
  build_graph_csr(NUM_NODES)      # warm the /tmp graph+CSR caches once
  results = []
  session_timeout = int(os.environ.get('GLT_BENCH_SESSION_TIMEOUT', 480))
  # hard wall for the whole harness: tunnel-slow days must yield a
  # degraded (fewer-session) number, never a timeout with NO number
  total_budget = float(os.environ.get('GLT_BENCH_TOTAL_BUDGET', 1500))
  t_start = time.time()
  for s in range(sessions):
    if results and time.time() - t_start > total_budget - session_timeout:
      print(f'budget: stopping after {len(results)} sessions',
            file=sys.stderr)
      break
    cmd = [sys.executable, os.path.abspath(__file__), '--bench-worker']
    cmd += [a for a in sys.argv[1:] if a != '--bench-worker']
    try:
      out = subprocess.run(cmd, capture_output=True, text=True,
                           cwd=os.path.dirname(os.path.abspath(__file__)),
                           timeout=session_timeout)
    except subprocess.TimeoutExpired:
      print(f'session {s} timed out after {session_timeout}s',
            file=sys.stderr)
      continue
    line = None
    for ln in reversed(out.stdout.strip().splitlines()):
      if ln.startswith('{'):
        line = ln
        break
    if line is None:
      print(f'session {s} failed:\n{out.stdout[-2000:]}\n'
            f'{out.stderr[-2000:]}', file=sys.stderr)
      continue
    results.append(json.loads(line))
  if not results:
    raise SystemExit('all bench sessions failed')
  ep = sorted(r['epoch_secs'] for r in results)
  es = sorted(r['edges_per_sec'] for r in results)
  med_ep = statistics.median(ep)
  med_es = statistics.median(es)
  print(json.dumps({
      'metric': f'graphsage_epoch_secs (products-scale synthetic, '
                f'fanout {list(FANOUT)}, batch {BATCH}, '
                f'{results[0]["platform"]})',
      'value': round(med_ep, 4),
      'unit': 's',
      'vs_baseline': round(BASELINE_EPOCH_SECS / med_ep, 4),
      'epoch_secs_min_med_max': [round(ep[0], 4), round(med_ep, 4),
                                 round(ep[-1], 4)],
      'sampled_edges_per_sec_M_min_med_max': [
          round(es[0] / 1e6, 1), round(med_es / 1e6, 1),
          round(es[-1] / 1e6, 1)],
      'sampling_vs_a100_nominal': round(med_es / BASELINE_EDGES_PER_SEC,
                                        2),
      'sessions': len(results),
      'steps_per_epoch': results[0]['steps'],
  }))


if __name__ == '__main__':
  if '--bench-worker' in sys.argv:
    worker()
  else:
    main()
