"""Headline benchmark: GraphSAGE epoch time + sampling throughput
+ feature-gather roofline + distributed (virtual-mesh) loader section
+ fused whole-epoch number.

PRIMARY metric (BASELINE.json: "GraphSAGE epoch time on
ogbn-products"): wall-clock of one full training epoch — seed shuffle
-> multi-hop sampling (fanout [15, 10, 5], batch 1024,
`examples/train_sage_ogbn_products.py:16`) -> feature/label collation
-> fused train step — on an ogbn-products-scale synthetic graph (2.45M
nodes, ~61M directed edges, 100-dim features, ~8% train split).
The HEADLINE `value` is the whole-epoch `FusedEpoch` time (the same
epoch as ONE XLA program); the per-batch epoch median is always
reported alongside.

MEASUREMENT PROTOCOL (r5 — supersedes r2-r4 numbers). Probing this
round established that the tunnel's async dispatch makes
`block_until_ready` walls unreliable: programs re-timed after their
first execution can report walls 100-1000x below the physical HBM
floor (r4 shipped fused_epoch_secs=0.0071 for an epoch whose feature
gather alone moves ~75 GB — impossible under the 819 GB/s ceiling).
Every timed number here therefore:
  * derives a SCALAR from the computation and pulls it via float()
    (a d2h value dependency the runtime cannot skip);
  * uses distinct arguments per timed call (no repeat-elision);
  * is cross-checked against an analytic HBM floor
    (`*_floor_secs`); any wall below its floor is flagged
    `suspect_elision` and excluded from the headline.
r2-r4 epoch/fused numbers predate this protocol and are NOT
comparable; this round re-bases the series (see COVERAGE.md).

SETUP COST: the graph + features + labels are generated ON DEVICE
(`benchmarks/common.build_graph_csr_device`, device-native Dataset
paths) — zero host↔device upload, where r4 paid a ~410 s/session
~1.5 GB device_put through the tunnel.  Sessions are cheap enough
for >= 3 primary sessions AND a complete dist phase inside the
1200 s budget.

SECONDARY: the reference's "Sampled Edges per secs" definition
(`benchmarks/api/bench_sampler.py:46-54`), a feature-gather roofline
phase (achieved vs ACHIEVABLE: the measured row-granular bound of
XLA's gather on this chip — descriptor-bound at ~100M rows/s across
row widths 256B-16KB, measured r5 — and the streaming bound for
context), and a `dist` section — a P=8 virtual-CPU-mesh distributed
loader run with >= 2 epochs so `exchange_slack='adaptive'` shows its
padding-waste trajectory (VERDICT r4 #3).

``vs_baseline`` divides a NOMINAL single-A100 epoch time of 2.0 s into
the headline (the reference publishes figures, not numbers — 2.0 s is
a mid-range read of public GLT-class A100 pipelines on this workload;
BASELINE.md documents the absence of published values).  > 1.0 means
faster than that nominal A100.

ARTIFACT CONTRACT (r6): the FULL aggregate JSON is written to
`BENCH_ARTIFACT.json` (`GLT_BENCH_ARTIFACT` overrides the path) after
every completed phase — atomic replace, so a kill at any point leaves
the newest complete artifact on disk.  Stdout carries only a SHORT
summary line (<= 2000 chars, `telemetry.sink.summary_line`) naming the
artifact file: r5's evidence chain broke because the full aggregate
outgrew the driver's 2000-char stdout tail (`BENCH_r05.json`
"parsed": null).  The dist section also runs with the flight recorder
on, writing per-hop padding / slack-transition / exchange events to
`BENCH_TELEMETRY.jsonl` (`GLT_TELEMETRY_JSONL` overrides).

`--trace-dir DIR` captures an xprof trace (TensorBoard profile plugin
format) around the fused session's epoch dispatches, which carry
`StepTraceAnnotation` step markers.

`--check-regression` runs the bench regression gate after the final
artifact lands (`telemetry/regress.py`, loaded by path like the sink):
the artifact's headline metrics are compared against
`BENCH_BASELINE.json` (`GLT_BENCH_BASELINE` / `--baseline` override;
created FROM this artifact on the first run) and the driver exits
nonzero with a per-metric report when any metric slows more than the
threshold (default 20%; `--regress-threshold 0.1` /
`GLT_REGRESS_THRESHOLD`).  The compact verdict is stamped into the
artifact summary line under `regression`.
"""
import json
import os
import statistics
import subprocess
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from benchmarks.common import (NUM_NODES, build_graph,  # noqa: E402
                               cpu_mesh_env)

#: nominal single-A100 epoch seconds (see module docstring)
BASELINE_EPOCH_SECS = 2.0
#: round-1 normalization constant for the secondary sampling metric
BASELINE_EDGES_PER_SEC = 100e6
#: TPU v5e peak HBM bandwidth, bytes/s (public spec; the roofline
#: denominator for `achieved_hbm_frac`)
HBM_PEAK = {'tpu': 819e9}
#: v5e peak f32 FLOP/s (MXU bf16 197e12 / 4 — public spec ratio);
#: the `train_step_mfu` denominator (model runs f32)
F32_PEAK = 49.2e12

FANOUT = (15, 10, 5)
BATCH = 1024
DIM = 100
CLASSES = 47
SAMPLE_ITERS = 30
EPOCHS_PER_SESSION = 2

#: dist section: smaller graph (CPU mesh), reference bench workload
#: shape at half batch — r5 shrank it (batch 1024, 4 batches/epoch,
#: 500k nodes needed ~100 s/batch on the 8x-oversubscribed virtual
#: mesh and could not finish 3 adaptive epochs inside any budget);
#: numbers remain RELATIVE, the config is in the artifact
DIST_PARTS = 8
DIST_NODES = 200_000
DIST_DIM = 64
DIST_BATCH = 512
DIST_BATCHES_PER_EPOCH = 2


def _arg_after(flag: str):
  """Value following ``flag`` on argv (None when absent)."""
  if flag in sys.argv:
    i = sys.argv.index(flag)
    if i + 1 < len(sys.argv):
      return sys.argv[i + 1]
  return None


def _pull(x) -> float:
  """Force REAL completion: a scalar d2h value dependency.  This is
  the only sync primitive the r5 protocol trusts (module docstring)."""
  import jax.numpy as jnp
  return float(jnp.sum(x))


def _pull_state(state) -> float:
  import jax
  return _pull(jax.tree_util.tree_leaves(state.params)[0])


def _sample_window_bytes(batch, fanouts):
  """See `benchmarks.common.sample_window_bytes` (one definition)."""
  from benchmarks.common import sample_window_bytes
  return sample_window_bytes(batch, fanouts)


def _tree_step_flops(batch, fanouts, dim, hidden, classes):
  """Analytic fwd+bwd matmul FLOPs of one tree-layout SAGE step
  (`models.tree.TreeSAGE`): layer ``l`` applies its self+neighbor
  matmul pair to every level that still matters."""
  sizes = [batch]
  for k in fanouts:
    sizes.append(sizes[-1] * int(k))
  num_layers = len(fanouts)
  dims = [dim] + [hidden] * (num_layers - 1) + [classes]
  fwd = 0
  for l in range(num_layers):
    rows = sum(sizes[t] for t in range(num_layers - l))
    fwd += 2 * rows * dims[l] * dims[l + 1] * 2
  return 3 * fwd


def _sage_step_flops(node_cap, fanouts, batch, dim, hidden, classes,
                     num_layers=3):
  """Analytic forward+backward FLOPs of one supervised SAGE step on
  the padded static shapes (matmuls only; the segment mean/sum and
  elementwise tails are bandwidth, not FLOPs).  Each SAGE layer runs
  two [rows, in]x[in, out] matmuls (self + aggregated neighbor); the
  backward pass costs ~2x the forward's matmul FLOPs."""
  rows = node_cap
  dims = [dim] + [hidden] * (num_layers - 1) + [classes]
  fwd = 0
  for lin, lout in zip(dims[:-1], dims[1:]):
    fwd += 2 * rows * lin * lout * 2        # 2 matmuls per layer
  return 3 * fwd                            # fwd + ~2x bwd


def _build_device_dataset(jax, jnp, feat_dtype=None):
  """Products-scale synthetic dataset generated entirely on device
  (zero upload — module docstring, SETUP COST)."""
  from benchmarks.common import build_graph_csr_device
  from graphlearn_tpu.data import Dataset
  n = int(os.environ.get('GLT_BENCH_NODES', NUM_NODES))  # smoke knob
  indptr, indices, _ = build_graph_csr_device(n)
  kf, kl = jax.random.split(jax.random.key(7))
  feats = jax.random.uniform(kf, (n, DIM), jnp.float32)
  if feat_dtype is not None:
    feats = feats.astype(feat_dtype)
  labels = jax.random.randint(kl, (n,), 0, CLASSES, jnp.int32)
  ds = (Dataset()
        .init_graph((indptr, indices), layout='CSR', num_nodes=n)
        .init_node_features(feats)
        .init_node_labels(labels))
  return ds, n


def worker(fused_only: bool = False):
  """One fresh-session measurement under the r5 pull-protocol: the
  per-batch epoch (x EPOCHS_PER_SESSION), then sampling throughput,
  then the feature-gather roofline.  ``fused_only`` is the DEDICATED
  fused session: same setup, then the whole-epoch `FusedEpoch`
  measured as a first-class program (compile walls reported, steady
  state = median of 3 pulled runs with distinct epoch keys)."""
  import jax
  try:
    jax.config.update('jax_compilation_cache_dir', '/tmp/glt_jax_cache')
  except Exception:
    pass
  if '--cpu' in sys.argv:
    jax.config.update('jax_platforms', 'cpu')
  import jax.numpy as jnp
  import optax
  from graphlearn_tpu.loader import NeighborLoader
  from graphlearn_tpu.models import (GraphSAGE, create_train_state,
                                     make_supervised_step)
  from graphlearn_tpu.sampler import NeighborSampler

  t_setup = time.perf_counter()
  ds, n = _build_device_dataset(jax, jnp)
  _pull(ds.get_graph().indptr[-8:])        # sync: graph build done
  _pull(ds.node_features.hot_tier[0])
  setup_secs = round(time.perf_counter() - t_setup, 1)
  platform = jax.devices()[0].platform
  peak = HBM_PEAK.get(platform)
  train_idx = np.random.default_rng(0).permutation(n)[:max(n // 12, 1)]
  loader = NeighborLoader(ds, list(FANOUT), train_idx, batch_size=BATCH,
                          shuffle=True, seed=0)
  node_cap = NeighborSampler(ds.get_graph(), FANOUT,
                             seed=0).node_capacity(BATCH)
  steps = len(loader)
  # analytic per-epoch HBM floor: the feature gather's table reads
  # alone (node_cap rows x DIM f32 per step) — everything else
  # (windows, labels, model) only raises it, so a wall BELOW this is
  # physically impossible and flags a broken measurement
  epoch_floor = (steps * node_cap * DIM * 4 / peak) if peak else 0.0
  step_flops = _sage_step_flops(node_cap, FANOUT, BATCH, DIM, 256,
                                CLASSES)

  # sampler-pipeline compile = wall of the very first batch
  t0 = time.perf_counter()
  it0 = iter(loader)
  first_batch = next(it0)
  _pull(first_batch.x)
  sampler_compile = time.perf_counter() - t0
  model = GraphSAGE(hidden_features=256, out_features=CLASSES,
                    num_layers=3)
  tx = optax.adam(3e-3)
  state, apply_fn = create_train_state(
      model, jax.random.key(0), first_batch, tx)

  if fused_only:
    # the fused HEADLINE is the TREE-LAYOUT epoch (`FusedTreeEpoch` —
    # scatter-free, sort-free; measured 12x the subgraph fused path's
    # step rate on this chip, r5 decomposition in
    # loader/fused_tree.py).  The subgraph fused path (the reference's
    # dedup estimator) is measured after it when budget remains.
    tree_flops = _tree_step_flops(BATCH, FANOUT, DIM, 256, CLASSES)
    result = {'mode': 'fused-session', 'platform': platform,
              'epoch_floor_secs': round(epoch_floor, 4),
              'fused_layout': 'tree',
              'tree_step_flops': tree_flops,
              'setup_secs': setup_secs, 'steps': steps}
    try:
      # chunked programs are watchdog-safe AND cache-safe (r5 re-test,
      # `loader.fused._uncached_jit` docstring) — opt into the
      # persistent cache so later sessions/rounds compile in ~12 s
      os.environ.setdefault('GLT_FUSED_COMPILE_CACHE', '1')
      from graphlearn_tpu.loader import FusedEpoch, FusedTreeEpoch
      from graphlearn_tpu.models import TreeSAGE
      tree = TreeSAGE(hidden_features=256, out_features=CLASSES,
                      num_layers=3)
      fused = FusedTreeEpoch(ds, list(FANOUT), train_idx, tree, tx,
                             batch_size=BATCH, shuffle=True, seed=0,
                             max_steps_per_program=100)
      tstate = fused.init_state(jax.random.key(0))
      # --trace-dir: xprof capture around the headline epochs (the
      # fused drivers wrap each dispatch in a StepTraceAnnotation, so
      # the timeline segments by chunk).  The finally covers the
      # COMPILE dispatch too — jax materializes the trace only on
      # stop_trace, and the compile is the most expensive thing the
      # flag exists to profile.
      trace_dir = _arg_after('--trace-dir')
      runs = []
      try:
        if trace_dir:
          from graphlearn_tpu.utils.profiling import start_trace
          start_trace(trace_dir)
          result['trace_dir'] = trace_dir
        t0 = time.perf_counter()
        tstate, _ = fused.run(tstate)
        _pull_state(tstate)
        result['fused_compile_secs'] = round(time.perf_counter() - t0,
                                             1)
        print(json.dumps(result), flush=True)
        for _ in range(3):          # distinct epoch keys per run
          t0 = time.perf_counter()
          tstate, _ = fused.run(tstate)
          _pull_state(tstate)
          runs.append(round(time.perf_counter() - t0, 4))
      finally:
        if trace_dir:
          from graphlearn_tpu.utils.profiling import stop_trace
          stop_trace()
      result['fused_epoch_runs'] = runs
      med = statistics.median(runs)
      result['epoch_secs_fused'] = med
      result['suspect_elision'] = bool(med < epoch_floor)
      result['train_step_mfu'] = (
          round(tree_flops / (med / steps) / F32_PEAK, 4)
          if med >= epoch_floor else None)
      print(json.dumps(result), flush=True)
      # bf16 compute variant (MXU half precision, f32 params)
      tree16 = TreeSAGE(hidden_features=256, out_features=CLASSES,
                        num_layers=3, dtype=jnp.bfloat16)
      fused16 = FusedTreeEpoch(ds, list(FANOUT), train_idx, tree16, tx,
                               batch_size=BATCH, shuffle=True, seed=0,
                               max_steps_per_program=100)
      state16 = fused16.init_state(jax.random.key(0))
      t0 = time.perf_counter()
      state16, _ = fused16.run(state16)
      _pull_state(state16)
      result['fused_bf16_compile_secs'] = round(
          time.perf_counter() - t0, 1)
      runs16 = []
      for _ in range(2):
        t0 = time.perf_counter()
        state16, _ = fused16.run(state16)
        _pull_state(state16)
        runs16.append(round(time.perf_counter() - t0, 4))
      result['fused_epoch_runs_bf16'] = runs16
      med16 = statistics.median(runs16)
      # same floor as f32: only the COMPUTE dtype is bf16 here — the
      # feature table (the floor's byte source) stays f32
      result['fused_epoch_secs_bf16'] = (
          med16 if med16 >= epoch_floor else None)
      print(json.dumps(result), flush=True)
      # subgraph fused path (the reference's dedup estimator), chunked
      # under the tunnel's ~70 s execution watchdog.  Measured on a
      # 96-step SUBSET (one chunk): a full 200-step epoch of this
      # path runs ~90 s (its step is scatter-bound, the very thing
      # the tree layout removes) and would not fit the session budget
      # — the artifact reports its honest ms/step instead.
      if os.environ.get('GLT_BENCH_SUBGRAPH_FUSED', '1') != '0':
        sub_steps = 96
        sub = FusedEpoch(ds, list(FANOUT), train_idx[:BATCH * sub_steps],
                         apply_fn, tx, batch_size=BATCH, shuffle=True,
                         seed=0, remat=True,
                         max_steps_per_program=sub_steps)
        t0 = time.perf_counter()
        state, _ = sub.run(state)
        _pull_state(state)
        result['fused_subgraph_compile_secs'] = round(
            time.perf_counter() - t0, 1)       # compile + first run
        t0 = time.perf_counter()
        state, _ = sub.run(state)
        _pull_state(state)
        sub_dt = time.perf_counter() - t0
        result['fused_subgraph_ms_per_step'] = round(
            1000 * sub_dt / sub_steps, 1)
        result['fused_subgraph_epoch_secs_est'] = round(
            sub_dt / sub_steps * steps, 2)
    except Exception as e:          # noqa: BLE001
      result['fused_error'] = f'{type(e).__name__}: {e}'[:200]
    print(json.dumps(result), flush=True)
    return

  step = make_supervised_step(apply_fn, tx, BATCH)

  # step compile = wall of the first train-step call; together with
  # the sampler compile above this is the per-batch pipeline's full
  # compile cost
  t0 = time.perf_counter()
  state, loss, _ = step(state, first_batch)
  _pull_state(state)
  compile_secs = sampler_compile + time.perf_counter() - t0
  # two more batches cover the donated-layout recompile
  for i, batch in enumerate(it0):
    state, loss, _ = step(state, batch)
    if i >= 1:
      break
  _pull_state(state)

  epochs = []
  for _ in range(EPOCHS_PER_SESSION):
    t0 = time.perf_counter()
    for batch in loader:
      state, loss, _ = step(state, batch)
    _pull_state(state)
    epochs.append(round(time.perf_counter() - t0, 4))
  valid = [e for e in epochs if e >= epoch_floor]
  result = {'epoch_runs': epochs,
            'epoch_secs': (statistics.median(valid) if valid else None),
            'epoch_floor_secs': round(epoch_floor, 4),
            'suspect_elision': len(valid) < len(epochs),
            'compile_secs': round(compile_secs, 1),
            'sampler_compile_secs': round(sampler_compile, 1),
            'steps': steps, 'mode': 'primary',
            'node_cap': int(node_cap),
            'train_step_flops': step_flops,
            'setup_secs': setup_secs,
            'platform': platform}
  if valid:
    result['train_step_mfu'] = round(
        step_flops / (statistics.median(valid) / steps) / F32_PEAK, 4)
  # CHECKPOINT the line after every phase: a timeout mid-sampling or
  # mid-roofline must not cost the already-measured PRIMARY number
  print(json.dumps(result), flush=True)

  # secondary: sampling-only DEVICE throughput, reference metric
  # definition ("Sampled Edges per secs").  The whole burst runs as
  # ONE scan program over [iters, B] seed batches — a per-batch
  # dispatch loop here measures the tunnel's ~100 ms/batch dispatch
  # latency, not the sampler (measured r5; on a TPU-VM the per-batch
  # loop approaches this number).  AOT-compiled, first execution,
  # value pull.
  iters = SAMPLE_ITERS
  from benchmarks.common import make_sample_burst
  g = ds.get_graph()
  srng = np.random.default_rng(1)
  seeds_all = jnp.asarray(
      srng.integers(0, n, (iters, BATCH)).astype(np.int32))
  sample_burst = make_sample_burst(FANOUT, node_cap, iters)
  comp = jax.jit(sample_burst).lower(
      g.indptr, g.indices, seeds_all, jax.random.key(11)).compile()
  t0 = time.perf_counter()
  edges = int(comp(g.indptr, g.indices, seeds_all, jax.random.key(12)))
  dt = time.perf_counter() - t0
  window_bytes = iters * _sample_window_bytes(BATCH, FANOUT)
  sample_floor = window_bytes / peak if peak else 0.0
  sample_hbm = (window_bytes / dt / peak) if peak else None
  result.update(edges_per_sec=edges / dt,
                sample_secs=round(dt, 4),
                sample_floor_secs=round(sample_floor, 4),
                sample_hbm_frac=(round(sample_hbm, 4)
                                 if sample_hbm else None))
  print(json.dumps(result), flush=True)

  # roofline phase: achieved vs ACHIEVABLE for the feature-row gather
  # (VERDICT r4 #1).  Three AOT-compiled programs, each timed on its
  # FIRST execution with a value pull:
  #   gather      — the real pattern (sorted ~50%-dense ids, D=100)
  #   gather_128  — same ids on a lane-padded [n,128] table (rules
  #                 out alignment as the limiter)
  #   stream      — contiguous block copy of the same byte volume
  #                 (the extraction-free streaming bound)
  # The ACHIEVABLE bound for a row-granular gather on this chip is
  # rows/s-limited (descriptor-bound ~100M rows/s measured across row
  # widths 256B-16KB; `ops/pallas_gather.py` documents the kernel
  # attempts) — achieved/achievable is reported against the best
  # measured row rate this session.
  if peak and n > (1 << 21) + 8:
    # (the n guard keeps the GLT_BENCH_NODES smoke knob from driving
    # randint maxval negative — ids span [start, start + 2*grows) —
    # and measuring clamped garbage accesses)
    grows = 1 << 20
    from jax import lax

    def make_prog(kind, d, giters):
      def run(table, key):
        def body(i, acc):
          k = jax.random.fold_in(key, i)
          start = jax.random.randint(k, (), 0,
                                     table.shape[0] - 2 * grows)
          if kind == 'stream':
            rows = lax.dynamic_slice(table, (start, 0), (grows, d))
          else:
            ids = start + 2 * jnp.arange(grows, dtype=jnp.int32)
            rows = jnp.take(table, ids, axis=0)
          rows = lax.optimization_barrier(rows)
          return acc + rows.sum(dtype=jnp.float32)
        return lax.fori_loop(0, giters, body, jnp.float32(0))
      return run

    def timed(kind, table, giters):
      d = table.shape[1]
      fn = jax.jit(make_prog(kind, d, giters))
      comp = fn.lower(table, jax.random.key(3)).compile()
      t0 = time.perf_counter()
      float(comp(table, jax.random.key(4)))
      dt = time.perf_counter() - t0
      gb = giters * grows * d * 4 / 1e9
      return gb / dt, dt

    # volumes sized for >= 2 s of device time per program: the
    # process's dispatch path carries a ~0.3 s constant overhead by
    # this point in the session (post-pull degrade, benchmarks/README
    # "first-burst validity"), which a small burst would fold into
    # the rate
    hot = ds.node_features.hot_tier
    g100, _ = timed('gather', hot, 240)
    hot128 = jnp.pad(hot, ((0, 0), (0, 28)))
    g128, _ = timed('gather', hot128, 240)
    stream, _ = timed('stream', hot128, 1200)
    del hot128
    rows_per_s = max(g100 * 1e9 / (DIM * 4), g128 * 1e9 / (128 * 4))
    achievable = rows_per_s * DIM * 4 / 1e9       # GB/s at D=100 rows
    result.update(
        gather_gbps=round(g100, 1),
        gather_gbps_d128=round(g128, 1),
        stream_gbps=round(stream, 1),
        gather_rows_per_sec_M=round(rows_per_s / 1e6, 1),
        gather_achievable_gbps=round(achievable, 1),
        gather_hbm_frac=round(g100 * 1e9 / peak, 4),
        gather_achievable_frac=round(achievable * 1e9 / peak, 4),
        gather_achieved_vs_achievable=round(g100 / achievable, 3),
        stream_hbm_frac=round(stream * 1e9 / peak, 4))
  print(json.dumps(result), flush=True)


#: hetero session: ogbn-mag-scale synthetic (reference workload:
#: `examples/hetero/train_hgt_mag.py:102-121` — paper/author/cites/
#: writes, 349 classes)
MAG_PAPER, MAG_AUTHOR, MAG_CLASSES, MAG_DIM = 736_389, 1_134_649, 349, 128


def hetero_worker():
  """On-chip `FusedHeteroEpoch` measurement (VERDICT r4 #8): RGCN
  training epochs on a device-built MAG-scale hetero graph as one
  scan program per chunk, pull-protocol timed."""
  import jax
  try:
    jax.config.update('jax_compilation_cache_dir', '/tmp/glt_jax_cache')
  except Exception:
    pass
  if '--cpu' in sys.argv:
    jax.config.update('jax_platforms', 'cpu')
  import jax.numpy as jnp
  import optax
  os.environ.setdefault('GLT_FUSED_COMPILE_CACHE', '1')
  from benchmarks.common import build_bipartite_csr_device
  from graphlearn_tpu.data import Dataset
  from graphlearn_tpu.loader import FusedHeteroEpoch, NeighborLoader  # noqa: F401
  from graphlearn_tpu.models import RGCN
  from graphlearn_tpu.models.train import TrainState

  t_setup = time.perf_counter()
  np_, na = MAG_PAPER, MAG_AUTHOR
  if os.environ.get('GLT_BENCH_NODES'):          # smoke knob
    np_ = int(os.environ['GLT_BENCH_NODES'])
    na = np_ * 3 // 2
  P_, A = 'paper', 'author'
  cites = build_bipartite_csr_device(np_, np_, 7, seed=1)
  writes = build_bipartite_csr_device(na, np_, 7, seed=2)
  rev = build_bipartite_csr_device(np_, na, 4, seed=3)
  kf1, kf2, kl = jax.random.split(jax.random.key(9), 3)
  etypes = {(P_, 'cites', P_): cites, (A, 'writes', P_): writes,
            (P_, 'rev_writes', A): rev}
  ds = (Dataset()
        .init_graph(etypes, layout='CSR',
                    num_nodes={P_: np_, A: na})
        .init_node_features(
            {P_: jax.random.uniform(kf1, (np_, MAG_DIM), jnp.float32),
             A: jax.random.uniform(kf2, (na, MAG_DIM), jnp.float32)})
        .init_node_labels(
            {P_: jax.random.randint(kl, (np_,), 0, MAG_CLASSES,
                                    jnp.int32)}))
  _pull(ds.node_features[P_].hot_tier[0])
  result = {'mode': 'hetero-session',
            'platform': jax.devices()[0].platform,
            'setup_secs': round(time.perf_counter() - t_setup, 1),
            'paper': np_, 'author': na, 'classes': MAG_CLASSES}
  batch, fanouts, steps = 512, [10, 10], 64
  train_idx = np.random.default_rng(0).permutation(np_)[:batch * steps]
  model = RGCN(etypes=tuple(etypes.keys()), hidden_features=128,
               out_features=MAG_CLASSES, num_layers=2,
               target_ntype=P_)
  tx = optax.adam(1e-3)
  fused = FusedHeteroEpoch(ds, fanouts, (P_, train_idx), model.apply,
                           tx, batch_size=batch, shuffle=True, seed=0,
                           max_steps_per_program=steps)
  result.update(batch=batch, fanouts=fanouts, steps=steps)
  # init params from one tiny traced batch via the fused machinery's
  # own collation (shapes only)
  seeds0 = jnp.asarray(train_idx[:batch].astype(np.int32))
  b0 = fused._sample_collate(seeds0, jax.random.key(0), fused._dev,
                             False)
  params = model.init(jax.random.key(0), b0.x_dict,
                      b0.edge_index_dict, b0.edge_mask_dict)
  state = TrainState(params, tx.init(params), jnp.zeros((), jnp.int32))
  t0 = time.perf_counter()
  state, _ = fused.run(state)
  _pull_state(state)
  result['fused_hetero_compile_secs'] = round(time.perf_counter() - t0,
                                              1)
  print(json.dumps(result), flush=True)
  runs = []
  for _ in range(2):
    t0 = time.perf_counter()
    state, stats = fused.run(state)
    _pull_state(state)
    runs.append(round(time.perf_counter() - t0, 4))
  result['fused_hetero_epoch_runs'] = runs
  result['fused_hetero_epoch_secs'] = statistics.median(runs)
  result['fused_hetero_ms_per_step'] = round(
      1000 * statistics.median(runs) / steps, 1)
  print(json.dumps(result), flush=True)


def dist_worker():
  """P=8 virtual-mesh distributed loader run (VERDICT r4 #3): the
  reference dist-bench workload (batch 1024, fanout [15,10,5]) on the
  mesh engine, run for MULTIPLE epochs with ``exchange_slack=
  'adaptive'`` so the artifact records the padding-waste trajectory
  as the capacity ladder converges (r4 shipped only the static
  slack-2.0 floor, 58.9%).  CPU-mesh numbers are RELATIVE (no ICI);
  the label says so.  A complete JSON line is printed after every
  phase (adaptive / tiered / fused-mesh) so the harness can salvage
  whatever finished."""
  import jax
  # NOTE: deliberately NOT enabling the /tmp compilation cache here —
  # XLA:CPU AOT cache entries recorded with different target-feature
  # sets load with "could lead to SIGILL" errors on this box and
  # killed the worker mid-phase when tried.
  from graphlearn_tpu.parallel import (DistDataset, DistNeighborLoader,
                                       make_mesh)
  from graphlearn_tpu.telemetry import recorder
  # flight recorder ON for the dist section: per-hop padding fill,
  # slack-ladder transitions, exchange/cold-tier deltas land in a
  # JSONL next to the artifact (costs one nsn sync per batch — this
  # section measures exchange accounting, not dispatch latency)
  jsonl_path = os.environ.get('GLT_TELEMETRY_JSONL',
                              'BENCH_TELEMETRY.jsonl')
  # fresh flight log per bench run: close any import-time file handle
  # FIRST (with GLT_TELEMETRY_JSONL set, the recorder enabled at
  # import holding this very path — unlinking under it would orphan
  # the inode and lose every event), then unlink, then (re)open
  recorder.disable()
  try:
    os.unlink(jsonl_path)
  except OSError:
    pass
  recorder.enable(jsonl_path)
  assert len(jax.devices()) == DIST_PARTS, jax.devices()
  rows, cols = build_graph(DIST_NODES)
  rng = np.random.default_rng(0)
  feats = rng.random((DIST_NODES, DIST_DIM), dtype=np.float32)
  labels = rng.integers(0, CLASSES, DIST_NODES).astype(np.int32)
  ds = DistDataset.from_full_graph(DIST_PARTS, rows, cols,
                                   node_feat=feats, node_label=labels,
                                   num_nodes=DIST_NODES)
  seeds = rng.permutation(DIST_NODES)[
      :DIST_BATCH * DIST_PARTS * DIST_BATCHES_PER_EPOCH]
  mesh = make_mesh(DIST_PARTS)
  loader = DistNeighborLoader(ds, list(FANOUT), seeds,
                              batch_size=DIST_BATCH,
                              shuffle=True, mesh=mesh, seed=0,
                              exchange_slack='adaptive')
  epochs = int(os.environ.get('GLT_BENCH_DIST_EPOCHS', 3))
  t0 = time.perf_counter()
  waste_by_epoch, compile_secs, edges, n_batches = [], None, 0, 0
  t_epoch = time.perf_counter()
  for ep in range(epochs):
    prev = loader.sampler.exchange_stats(tick_metrics=False)
    for i, b in enumerate(iter(loader)):
      if ep == 0 and i == 0:
        compile_secs = time.perf_counter() - t_epoch
      edges += int(np.asarray(b.edge_mask.sum()))
      n_batches += 1
    st = loader.sampler.exchange_stats(tick_metrics=False)
    sent = ((st['dist.frontier.offered'] - prev['dist.frontier.offered'])
            - (st['dist.frontier.dropped'] - prev['dist.frontier.dropped']))
    slots = st['dist.frontier.slots'] - prev['dist.frontier.slots']
    waste_by_epoch.append(round(100.0 * (1 - sent / max(slots, 1)), 2))
  dt = time.perf_counter() - t0
  st = loader.sampler.exchange_stats(tick_metrics=False)
  drop = 100.0 * st['dist.frontier.dropped'] / max(
      st['dist.frontier.offered'], 1)
  out = {
      'label': 'virtual CPU mesh - relative only',
      'num_parts': DIST_PARTS, 'batch': DIST_BATCH,
      'fanout': list(FANOUT),
      'num_nodes': DIST_NODES, 'batches': n_batches, 'epochs': epochs,
      'compile_secs': round(compile_secs or 0.0, 1),
      'edges_per_sec_per_chip': round(
          edges / max(dt - (compile_secs or 0), 1e-9) / DIST_PARTS, 1),
      'seeds_per_sec': round(
          n_batches * DIST_BATCH * DIST_PARTS
          / max(dt - (compile_secs or 0), 1e-9), 1),
      'exchange_slack': 'adaptive',
      'padding_waste_pct_by_epoch': waste_by_epoch,
      'padding_waste_pct': waste_by_epoch[-1] if waste_by_epoch else None,
      'drop_rate_pct': round(drop, 3),
      # cluster-wide derived aggregates (== host-local on this
      # single-controller mesh; sums host cold counters at multi-host)
      'cluster': loader.sampler.cluster_exchange_stats(),
      'flight_recorder': jsonl_path,
      'slack_transitions': len(recorder.events('slack.transition')),
      # the adaptive phase runs recorder-ON (it IS the attribution
      # phase); its seeds/edges rates carry the per-batch nsn sync +
      # JSONL writes.  All later timed windows run recorder-off.
      'recorder_on_during_adaptive': True,
  }
  # adaptive-phase numbers are safe NOW: if the later phases time out,
  # the harness takes the last printed JSON line
  print(json.dumps(out), flush=True)
  # recorder OFF for the remaining TIMED windows (README: attribution
  # on, throughput off — the per-batch nsn sync + JSONL writes must
  # not ride inside a measured loop); re-enabled briefly around the
  # fused warm run below so its hop events still land in the JSONL
  recorder.disable()
  # tiered store in the MEASURED path: same workload, 30% of each
  # partition's rows in "HBM", the rest served by the r10 cold-cache +
  # pipelined overlay (benchmarks/README "Cold-tier cache").  The
  # cache gets the EQUAL-HBM-BUDGET size (one hot shard's rows per
  # device) so the dynamic-vs-static comparison is spend-for-spend.
  ds_t = DistDataset.from_full_graph(DIST_PARTS, rows, cols,
                                     node_feat=feats, node_label=labels,
                                     num_nodes=DIST_NODES,
                                     split_ratio=0.3)
  # prefetch=2: the next batch's cold-tier overlay (a host sync) runs
  # on a worker thread while the current batch computes
  cache_rows = int(np.max(ds_t.node_features.hot_counts))
  lt = DistNeighborLoader(ds_t, list(FANOUT), seeds,
                          batch_size=DIST_BATCH, shuffle=True,
                          mesh=mesh, seed=0, prefetch=2,
                          cold_cache_rows=cache_rows)
  # r05-PROTOCOL window (the comparison target for the guarded
  # `dist.tiered.seeds_per_sec`): first batch warms the compiles, the
  # REMAINDER OF THE EPOCH is timed — identical to the r5 measurement
  # that scored the static split 250.6, so the delta is machinery, not
  # protocol.  With prefetch + the dispatch-ahead pipeline, the timed
  # batches' sampling and cold service largely overlap the warm
  # window — which is the point being measured.
  it = iter(lt)
  b = next(it)
  b.x.block_until_ready()
  t0 = time.perf_counter()
  nt = 0
  for b in it:
    b.x.block_until_ready()
    nt += 1
  dt_t = time.perf_counter() - t0
  # STEADY-STATE window: epochs 2..n timed whole (every dispatch and
  # every cold service inside the timer) — the conservative number,
  # and the denominator window for the hit rates (cache warm)
  st_w = lt.sampler.exchange_stats(tick_metrics=False)
  t0 = time.perf_counter()
  ns = 0
  for _ in range(max(epochs - 1, 1)):
    for b in iter(lt):
      b.x.block_until_ready()
      ns += 1
  dt_s = time.perf_counter() - t0
  st_t = lt.sampler.exchange_stats(tick_metrics=False)
  d = {k: st_t[k] - st_w[k] for k in
       ('dist.feature.lookups', 'dist.feature.cold_lookups',
        'dist.feature.cold_misses', 'dist.feature.cache_hits')}
  lk = max(d['dist.feature.lookups'], 1)
  cl = max(d['dist.feature.cold_lookups'], 1)
  out['tiered'] = {
      'split_ratio': 0.3, 'prefetch': 2,
      'cold_cache_rows': cache_rows,
      'cold_pipeline': lt._cold_pipeline,
      'seeds_per_sec': round(
          nt * DIST_BATCH * DIST_PARTS / max(dt_t, 1e-9), 1),
      'steady_state_seeds_per_sec': round(
          ns * DIST_BATCH * DIST_PARTS / max(dt_s, 1e-9), 1),
      'steady_state_epochs': max(epochs - 1, 1),
      # r10 vocabulary (benchmarks/README "Cold-tier metrics"):
      # lookups/cold_lookups are the DENOMINATORS the two hit rates
      # divide by — r5 printed cold_misses with no denominator.
      # Steady-state (post-warm-epoch) deltas.
      'lookups': d['dist.feature.lookups'],
      'cold_lookups': d['dist.feature.cold_lookups'],
      'cold_misses': d['dist.feature.cold_misses'],
      'cache_hits': d['dist.feature.cache_hits'],
      'hot_hit_rate': round(1.0 - cl / lk, 4),
      'cache_hit_rate': round(
          1.0 - d['dist.feature.cold_misses'] / cl, 4),
      # the DIRECT successor of r5's (misnamed) "cold_hit_rate 0.329":
      # the fraction of ALL feature lookups served on-device — static
      # hot tier + dynamic cache together vs the host
      'hbm_served_rate': round(
          1.0 - d['dist.feature.cold_misses'] / lk, 4),
  }
  out['tiered']['cold_hit_rate'] = out['tiered']['cache_hit_rate']
  # nested twin of the guarded dotted keys: `dist.feature.cache_hit_rate`
  # resolves here (regress._get walks dict levels, not literal dots)
  out['feature'] = {
      'cache_hit_rate': out['tiered']['cache_hit_rate'],
      'hot_hit_rate': out['tiered']['hot_hit_rate'],
      'hbm_served_rate': out['tiered']['hbm_served_rate'],
      'cold_lookups': out['tiered']['cold_lookups'],
  }
  print(json.dumps(out), flush=True)

  # -- cache-aware GNS row (r11): same tiered store, sampler-side bias --
  # Identical workload/protocol as the tiered row, with Global
  # Neighbor Sampling on: neighbor selection biased toward hot split ∪
  # cache residents with the 1/q correction (benchmarks/README
  # "Cache-aware sampling").  Feeds the guarded
  # `dist.gns.cache_hit_rate` / `dist.gns.seeds_per_sec` keys; the
  # ceiling being broken is `budget_over_universe` (the r10 honesty
  # note's 0.056).
  lg = DistNeighborLoader(ds_t, list(FANOUT), seeds,
                          batch_size=DIST_BATCH, shuffle=True,
                          mesh=mesh, seed=0, prefetch=2,
                          cold_cache_rows=cache_rows, gns=True)
  it = iter(lg)
  b = next(it)
  b.x.block_until_ready()
  t0 = time.perf_counter()
  ng = 0
  for b in it:
    b.x.block_until_ready()
    ng += 1
  dt_g = time.perf_counter() - t0
  st_w = lg.sampler.exchange_stats(tick_metrics=False)
  t0 = time.perf_counter()
  ngs = 0
  for b in iter(lg):
    b.x.block_until_ready()
    ngs += 1
  dt_gs = time.perf_counter() - t0
  st_g = lg.sampler.exchange_stats(tick_metrics=False)
  dg = {k: st_g[k] - st_w[k] for k in
        ('dist.feature.lookups', 'dist.feature.cold_lookups',
         'dist.feature.cold_misses', 'dist.feature.cache_hits')}
  clg = max(dg['dist.feature.cold_lookups'], 1)
  counts = np.diff(ds_t.graph.bounds)
  cold_universe = int(np.maximum(
      counts - ds_t.node_features.hot_counts, 0).sum())
  out['gns'] = {
      'split_ratio': 0.3, 'boost': float(lg.sampler.gns_boost),
      'cold_cache_rows': cache_rows,
      'budget_over_universe': round(
          cache_rows / max(cold_universe, 1), 4),
      'seeds_per_sec': round(
          ng * DIST_BATCH * DIST_PARTS / max(dt_g, 1e-9), 1),
      'steady_state_seeds_per_sec': round(
          ngs * DIST_BATCH * DIST_PARTS / max(dt_gs, 1e-9), 1),
      'lookups': dg['dist.feature.lookups'],
      'cold_lookups': dg['dist.feature.cold_lookups'],
      'cold_misses': dg['dist.feature.cold_misses'],
      'cache_hits': dg['dist.feature.cache_hits'],
      'cache_hit_rate': round(
          1.0 - dg['dist.feature.cold_misses'] / clg, 4),
      'hot_hit_rate': round(
          1.0 - clg / max(dg['dist.feature.lookups'], 1), 4),
      'vs_gns_off_cache_hit_rate': out['tiered']['cache_hit_rate'],
  }
  print(json.dumps(out), flush=True)

  # fused mesh epoch vs per-batch DP loop, SAME shape; the fused
  # program now also runs its evaluate() pass (VERDICT r4 #5)
  import optax
  from graphlearn_tpu.models import GraphSAGE, create_train_state
  from graphlearn_tpu.parallel import (FusedDistEpoch,
                                       local_batch_piece,
                                       make_dp_supervised_step,
                                       replicate)
  b2, fan2 = 512, [10, 5]
  seeds2 = rng.permutation(DIST_NODES)[:b2 * DIST_PARTS * 4]
  it2 = iter(DistNeighborLoader(ds, fan2, seeds2, batch_size=b2,
                                shuffle=True, mesh=mesh, seed=0))
  t0 = time.perf_counter()
  b0 = next(it2)
  b0.x.block_until_ready()
  pb_sampler_compile = time.perf_counter() - t0
  b0_local = local_batch_piece(b0, DIST_PARTS)
  model = GraphSAGE(hidden_features=64, out_features=CLASSES,
                    num_layers=2)
  tx = optax.adam(3e-3)
  state, apply_fn = create_train_state(
      model, jax.random.key(0), b0_local, tx)
  step = make_dp_supervised_step(apply_fn, tx, b2, mesh)
  state = replicate(state, mesh)
  t0 = time.perf_counter()
  state, _, _ = step(state, b0)
  jax.tree_util.tree_leaves(state.params)[0].block_until_ready()
  pb_compile = pb_sampler_compile + time.perf_counter() - t0
  npb = 0
  t0 = time.perf_counter()
  for b in it2:
    state, _, _ = step(state, b)
    npb += 1
  jax.tree_util.tree_leaves(state.params)[0].block_until_ready()
  pb_dt = time.perf_counter() - t0
  fused = FusedDistEpoch(ds, fan2, seeds2, apply_fn, tx, batch_size=b2,
                         mesh=mesh, shuffle=True, seed=0)
  fstate, _ = create_train_state(model, jax.random.key(1), b0_local, tx)
  fstate = replicate(fstate, mesh)
  t0 = time.perf_counter()
  fstate, _ = fused.run(fstate)
  jax.tree_util.tree_leaves(fstate.params)[0].block_until_ready()
  f_compile = time.perf_counter() - t0
  # warm run with the recorder ON: the fused epoch's per-hop
  # padding-fill events land in the JSONL without touching the timed
  # window below
  recorder.enable(jsonl_path)
  fstate, _ = fused.run(fstate)         # donated-layout recompile
  jax.tree_util.tree_leaves(fstate.params)[0].block_until_ready()
  recorder.disable()
  t0 = time.perf_counter()
  fstate, _ = fused.run(fstate)
  jax.tree_util.tree_leaves(fstate.params)[0].block_until_ready()
  f_dt = time.perf_counter() - t0
  pb_rate = npb * b2 * DIST_PARTS / max(pb_dt, 1e-9)
  f_rate = len(fused) * b2 * DIST_PARTS / max(f_dt, 1e-9)
  out['fused_mesh'] = {
      'batch': b2, 'fanout': fan2,
      'per_batch_seeds_per_sec': round(pb_rate, 1),
      'fused_seeds_per_sec': round(f_rate, 1),
      'fused_vs_per_batch': round(f_rate / max(pb_rate, 1e-9), 2),
      'per_batch_compile_secs': round(pb_compile, 1),
      'fused_compile_secs': round(f_compile, 1),
  }
  try:
    acc = fused.evaluate(fstate.params, seeds2[:b2 * DIST_PARTS])
    out['fused_mesh']['eval_acc'] = round(float(acc), 4)
  except Exception as e:            # noqa: BLE001
    out['fused_mesh']['eval_error'] = f'{type(e).__name__}: {e}'[:160]
  print(json.dumps(out), flush=True)

  # TREE-layout mesh epochs (r5 flagship, distributed form): same
  # shape as the fused_mesh comparison above
  try:
    from graphlearn_tpu.models import TreeSAGE
    from graphlearn_tpu.parallel import FusedDistTreeEpoch
    tmodel = TreeSAGE(hidden_features=64, out_features=CLASSES,
                      num_layers=2)
    tfused = FusedDistTreeEpoch(ds, fan2, seeds2, tmodel, tx,
                                batch_size=b2, mesh=mesh,
                                shuffle=True, seed=0)
    tstate = tfused.init_state(jax.random.key(2))
    t0 = time.perf_counter()
    tstate, _ = tfused.run(tstate)
    jax.tree_util.tree_leaves(tstate.params)[0].block_until_ready()
    t_compile = time.perf_counter() - t0
    tstate, _ = tfused.run(tstate)       # donated-layout recompile
    jax.tree_util.tree_leaves(tstate.params)[0].block_until_ready()
    t0 = time.perf_counter()
    tstate, _ = tfused.run(tstate)
    jax.tree_util.tree_leaves(tstate.params)[0].block_until_ready()
    t_dt = time.perf_counter() - t0
    out['fused_mesh']['tree_seeds_per_sec'] = round(
        len(tfused) * b2 * DIST_PARTS / max(t_dt, 1e-9), 1)
    out['fused_mesh']['tree_compile_secs'] = round(t_compile, 1)
  except Exception as e:            # noqa: BLE001
    out['fused_mesh']['tree_error'] = f'{type(e).__name__}: {e}'[:160]
  print(json.dumps(out), flush=True)


def _run_session(timeout: int, fused: bool = False):
  cmd = [sys.executable, os.path.abspath(__file__),
         '--fused-session' if fused else '--bench-worker']
  cmd += [a for a in sys.argv[1:]
          if a not in ('--bench-worker', '--fused-session')]
  try:
    out = subprocess.run(cmd, capture_output=True, text=True,
                         cwd=os.path.dirname(os.path.abspath(__file__)),
                         timeout=timeout)
    stdout = out.stdout or ''
    stderr = out.stderr or ''
  except subprocess.TimeoutExpired as e:
    # each session prints one complete JSON line as soon as its
    # numbers exist — salvage whatever made it out before the kill
    print(f'session timed out after {timeout}s (parsing partial '
          f'output)', file=sys.stderr)
    stdout = e.stdout or b''
    if isinstance(stdout, bytes):
      stdout = stdout.decode(errors='replace')
    stderr = e.stderr or b''
    if isinstance(stderr, bytes):
      stderr = stderr.decode(errors='replace')
  for ln in reversed(stdout.strip().splitlines()):
    if ln.startswith('{'):
      try:
        return json.loads(ln)
      except json.JSONDecodeError:
        continue      # truncated mid-print: fall through to the
                      # previous (complete) line
  print(f'session failed:\n{stdout[-2000:]}\n{stderr[-2000:]}',
        file=sys.stderr)
  return None


def _run_dist_section(timeout: int):
  cmd = [sys.executable, os.path.abspath(__file__), '--dist-worker']
  timed_out = False
  try:
    out = subprocess.run(cmd, capture_output=True, text=True,
                         cwd=os.path.dirname(os.path.abspath(__file__)),
                         env=cpu_mesh_env(DIST_PARTS), timeout=timeout)
    stdout, stderr = out.stdout or '', out.stderr or ''
  except subprocess.TimeoutExpired as e:
    # the worker prints a complete JSON line after EVERY phase —
    # salvage the last one
    timed_out = True
    stdout = e.stdout or b''
    if isinstance(stdout, bytes):
      stdout = stdout.decode(errors='replace')
    stderr = e.stderr or b''
    if isinstance(stderr, bytes):
      stderr = stderr.decode(errors='replace')
  for ln in reversed(stdout.strip().splitlines()):
    if ln.startswith('{'):
      try:
        r = json.loads(ln)
      except json.JSONDecodeError:
        continue
      if timed_out:
        r['note'] = f'partial: dist worker hit the {timeout}s budget'
      return r
  cause = (f'timed out after {timeout}s with no JSON'
           if timed_out else 'failed')
  return {'error': f'dist section {cause}: {stderr[-500:]}'}


def _run_hetero_session(timeout: int):
  """Spawn the hetero fused session; parse its last JSON line."""
  cmd = [sys.executable, os.path.abspath(__file__), '--hetero-session']
  try:
    out = subprocess.run(cmd, capture_output=True, text=True,
                         cwd=os.path.dirname(os.path.abspath(__file__)),
                         timeout=timeout)
    stdout = out.stdout or ''
  except subprocess.TimeoutExpired as e:
    stdout = e.stdout or b''
    if isinstance(stdout, bytes):
      stdout = stdout.decode(errors='replace')
  for ln in reversed(stdout.strip().splitlines()):
    if ln.startswith('{'):
      try:
        return json.loads(ln)
      except json.JSONDecodeError:
        continue
  return None


def _run_envelope_row(num_parts: int, batch: int, timeout: int):
  """One P-row of the scale envelope: spawn the tiny
  `bench_dist_loader.py --envelope-worker` config on a ``num_parts``
  virtual mesh and parse its JSON line (None on failure/timeout)."""
  script = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        'benchmarks', 'bench_dist_loader.py')
  cmd = [sys.executable, script, '--envelope-worker', '--num-parts',
         str(num_parts), '--mode', 'homo', '--batch', str(batch),
         '--nodes', '20000', '--epochs', '5']
  try:
    out = subprocess.run(cmd, capture_output=True, text=True,
                         env=cpu_mesh_env(num_parts), timeout=timeout)
  except subprocess.TimeoutExpired:
    return None
  for ln in reversed((out.stdout or '').strip().splitlines()):
    if ln.startswith('{'):
      try:
        return json.loads(ln)
      except json.JSONDecodeError:
        continue
  return None


def _run_dist_loader_row(flags, timeout: int, env=None, pin_key=None):
  """Shared `benchmarks/bench_dist_loader.py` subprocess harness for
  the chaos / resume / failover rows: spawn with ``flags``, scan
  stdout bottom-up for the last JSON line, return the parsed row
  (None on timeout / no parseable output).  With ``pin_key`` the
  worker's exit verdict is stamped into that key ('ok'/'FAILED') so
  the pin survives in the artifact, not only in a discarded code."""
  script = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        'benchmarks', 'bench_dist_loader.py')
  cmd = [sys.executable, script, *flags]
  try:
    out = subprocess.run(cmd, capture_output=True, text=True, env=env,
                         timeout=timeout)
  except subprocess.TimeoutExpired:
    return None
  for ln in reversed((out.stdout or '').strip().splitlines()):
    if ln.startswith('{'):
      try:
        r = json.loads(ln)
      except json.JSONDecodeError:
        continue
      if pin_key is not None:
        r[pin_key] = 'ok' if out.returncode == 0 else 'FAILED'
      return r
  return None


def _run_chaos_row(timeout: int):
  """The `bench_dist_loader.py --chaos` resilience smoke in a
  subprocess; returns its JSON row (None on failure/timeout)."""
  return _run_dist_loader_row(('--chaos',), timeout)


def _run_resume_row(timeout: int):
  """The `bench_dist_loader.py --resume` preemption-resume smoke in a
  subprocess; returns its JSON row (None on failure/timeout)."""
  return _run_dist_loader_row(('--resume',), timeout)


def _run_failover_row(timeout: int):
  """The `bench_dist_loader.py --failover` elastic-failover smoke
  (ISSUE 15) on the 8-device virtual mesh: one partition owner killed
  mid-epoch with a durable shard under GLT_SHARD_DIR — a survivor
  adopts, the epoch must complete EXACTLY (completed_ratio 1.0,
  batches byte-identical to the fault-free run, ONE adoption).  The
  worker exits nonzero unless the pin holds — stamped into
  ``failover_pin``.  Feeds the dist.failover.recovery_secs /
  dist.failover.completed_ratio regression guards."""
  r = _run_dist_loader_row(('--failover', '--nodes', '5000'), timeout,
                           env=cpu_mesh_env(8),
                           pin_key='failover_pin')
  if r is not None and r['failover_pin'] != 'ok':
    print('failover phase: epoch not exactly complete / not '
          'byte-identical / adoption count wrong (see dist.failover)',
          file=sys.stderr)
  return r


def _run_bench_serving(timeout: int, extra_args=(),
                       script_name='bench_serving.py', env=None):
  """Shared benchmarks/ subprocess harness for the serving, fleet,
  ingest and autoscale phases: spawn with forced-CPU env (optionally a
  caller-supplied one, e.g. cpu_mesh_env for phases that need a
  virtual device mesh), scan stdout bottom-up for the last JSON line,
  return (row, returncode) — or None on timeout/no-parseable-output."""
  script = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        'benchmarks', script_name)
  cmd = [sys.executable, script, '--cpu', *extra_args]
  env = dict(env if env is not None else os.environ)
  env.setdefault('JAX_PLATFORMS', 'cpu')
  try:
    out = subprocess.run(cmd, capture_output=True, text=True, env=env,
                         timeout=timeout)
  except subprocess.TimeoutExpired:
    return None
  for ln in reversed((out.stdout or '').strip().splitlines()):
    if ln.startswith('{'):
      try:
        return json.loads(ln), out.returncode
      except json.JSONDecodeError:
        continue
  return None


def _run_serving_row(timeout: int):
  """The `bench_serving.py` online-serving phase (ISSUE 9) in a
  subprocess: Zipf open-loop traffic against the coalescing tier on a
  single CPU device — p50/p95/p99 + sustained QPS + shed rate feed
  the dist.serving.p99_ms / dist.serving.qps regression guards, and
  the worker exits nonzero if any shape recompiled after warmup.
  Returns its last JSON row (None on failure/timeout)."""
  got = _run_bench_serving(timeout)
  if got is None:
    return None
  r, returncode = got
  # the worker exits nonzero when ANY phase recompiled after
  # warmup OR the mid-run live-ops scrape failed validation
  # (r13: bench_serving runs with the ops endpoint on and
  # strictly parses /metrics during traffic) — stamp the verdict
  # into the artifact row so the pin is visible there, not only
  # in a discarded exit code
  r['recompile_pin'] = 'ok' if returncode == 0 else 'FAILED'
  if returncode != 0:
    print('serving phase: recompile after warmup or failed '
          'live-ops scrape (see dist.serving rows / the ops '
          'block)', file=sys.stderr)
  return r


def _run_fleet_row(timeout: int):
  """`bench_serving.py --fleet 3` (ISSUE 13): the Zipf open loop
  spread over 3 in-process replicas behind the `FleetRouter`, with a
  chaos stall-then-kill on one replica mid-run.  The worker exits
  nonzero when any request failed/dropped across the failover or the
  fleet qps recovered to < 0.6x pre-kill — stamped into
  ``failover_pin`` so the verdict survives in the artifact.  Returns
  the fleet keys (``fleet_qps`` / ``failover_failed_requests`` /
  ``recovery_ratio`` / ``redriven`` / ``evictions`` + the full
  ``fleet`` row) to merge into the dist.serving block."""
  got = _run_bench_serving(timeout, extra_args=('--fleet', '3'))
  if got is None or 'fleet' not in got[0]:
    return None
  r, returncode = got
  keys = ('fleet_qps', 'failover_failed_requests',
          'recovery_ratio', 'redriven', 'evictions',
          'traced_tail_count', 'traced_tail_max_spans',
          'fleet_headroom_qps')
  row = {k: r[k] for k in keys if k in r}
  row['fleet'] = r['fleet']
  row['failover_pin'] = 'ok' if returncode == 0 else 'FAILED'
  if returncode != 0:
    print('fleet phase: failed/dropped requests, qps recovery below '
          '0.6x across the mid-run replica kill, or the tracing '
          'acceptance (>=1 slow-tail trace with >=5 spans + a live '
          'headroom gauge) failed (see dist.serving.fleet)',
          file=sys.stderr)
  return row


def _run_ingest_row(timeout: int):
  """`benchmarks/bench_ingest.py` (ISSUE 14): the freshness-vs-
  throughput open loop — events/s ingested through the WAL-backed
  delta-CSR pipeline while the Zipf serving load holds its p99.  The
  worker exits nonzero on ANY shed/errored request during
  steady-state ingest, a recompile after warmup, or unapplied lag at
  the end — stamped into ``ingest_pin``.  Feeds
  dist.ingest.events_per_sec / dist.ingest.p99_during_ingest_ms."""
  got = _run_bench_serving(timeout, script_name='bench_ingest.py')
  if got is None:
    return None
  r, returncode = got
  if 'events_per_sec' not in r:        # died before the final row
    return None
  r['ingest_pin'] = 'ok' if returncode == 0 else 'FAILED'
  if returncode != 0:
    print('ingest phase: shed/error during steady-state ingest, '
          'recompile after warmup, or unapplied lag (see '
          'dist.ingest)', file=sys.stderr)
  return r


def _run_autoscale_row(timeout: int):
  """`benchmarks/bench_autoscale.py` (ISSUE 19): the diurnal open
  loop against the `ElasticController` — sinusoidal arrivals over a
  1→3-replica fleet with a chaos-failed first spawn (typed rollback)
  and a mid-epoch planned partition handoff on the 8-device virtual
  mesh.  The worker exits nonzero unless the fleet scaled out AND
  back in, every request completed, the burn stayed < 1 outside the
  chaos incident, the elastic p99 held vs the static baseline, and
  the handoff produced zero degraded batches with exactly one
  PartitionBook bump — stamped into ``autoscale_pin``.  Feeds
  dist.autoscale.p99_held_ms / .burn_max /
  .handoff_degraded_batches."""
  got = _run_bench_serving(timeout, script_name='bench_autoscale.py',
                           env=cpu_mesh_env(8))
  if got is None:
    return None
  r, returncode = got
  if 'p99_held_ms' not in r:           # died before the final row
    return None
  r['autoscale_pin'] = 'ok' if returncode == 0 else 'FAILED'
  if returncode != 0:
    print('autoscale phase: fleet failed to scale out+in, a request '
          'failed, burn >= 1 outside the chaos incident, elastic p99 '
          'regressed vs static, or the handoff degraded a batch (see '
          'dist.autoscale)', file=sys.stderr)
  return r


def _run_pallas_row(timeout: int):
  """`benchmarks/bench_pallas_sample.py` (ISSUE 18): FusedEpoch step
  time through the r19 `sample_one_hop_auto` dispatcher with the knob
  OFF (the threading must cost the default path nothing), the
  pinned-host cold gather at split<1 against the FIXED 1.355 GB/s
  untiered XLA line, and the delta-CSR merge rate.  Runs on whatever
  accelerator the driver sees — the kernel-ON rows are hardware-only
  and skip cleanly on CPU (interpret-mode walls measure the
  interpreter, not the lowering).  Feeds pallas.fused_step_ms /
  pallas.feature_lookup_gbps / pallas.delta_merge_events_per_sec."""
  script = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        'benchmarks', 'bench_pallas_sample.py')
  cmd = [sys.executable, script, '--quick']
  try:
    out = subprocess.run(cmd, capture_output=True, text=True,
                         timeout=timeout)
  except subprocess.TimeoutExpired:
    return None
  for ln in reversed((out.stdout or '').strip().splitlines()):
    if ln.startswith('{'):
      try:
        r = json.loads(ln)
      except json.JSONDecodeError:
        continue
      if r.get('metric') == 'pallas_sample':   # per-row emit lines
        return r                               # also start with '{'
  return None


def _aggregate(results, fused_res, dist, hetero=None, pallas=None):
  """The full artifact schema from whatever phases have completed so
  far.  The HEADLINE `value` is the fused whole-epoch time when the
  fused session has landed (and passed its floor check), else the
  per-batch epoch median.  Printed after EVERY completed phase —
  the last JSON line on stdout is always the newest complete
  aggregate, so a kill at ANY point leaves a parseable artifact."""
  ep = sorted(r['epoch_secs'] for r in results
              if r.get('epoch_secs') is not None)
  # spread over FLOOR-VALID runs only: an elision-flagged wall must
  # not reappear as the series min (the r5 protocol's whole point);
  # salvaged sessions without per-run lists contribute their median
  all_runs = []
  for r in results:
    runs = r.get('epoch_runs') or (
        [r['epoch_secs']] if r.get('epoch_secs') is not None else [])
    floor = r.get('epoch_floor_secs', 0.0)
    all_runs += [e for e in runs if e >= floor]
  es = sorted(r['edges_per_sec'] for r in results
              if 'edges_per_sec' in r)
  cs = sorted(r['compile_secs'] for r in results if 'compile_secs' in r)
  fused_ok = (fused_res and fused_res.get('epoch_secs_fused') is not None
              and not fused_res.get('suspect_elision'))
  fu = [fused_res['epoch_secs_fused']] if fused_ok else []
  med_ep = statistics.median(ep) if ep else None
  med_es = statistics.median(es) if es else None
  platform = (results[0]['platform'] if results
              else (fused_res or {}).get('platform', '?'))
  shape = (f'products-scale synthetic, fanout {list(FANOUT)}, '
           f'batch {BATCH}, {platform}')
  if fu:
    metric = f'graphsage_fused_epoch_secs ({shape})'
    value = round(fu[0], 4)
  else:
    metric = f'graphsage_epoch_secs ({shape})'
    value = round(med_ep, 4) if med_ep is not None else None
  mfu = [r['train_step_mfu'] for r in results
         if r.get('train_step_mfu') is not None]
  if fused_res and fused_res.get('train_step_mfu') is not None:
    mfu.append(fused_res['train_step_mfu'])
  gather = {}
  for k in ('gather_gbps', 'gather_gbps_d128', 'stream_gbps',
            'gather_rows_per_sec_M', 'gather_achievable_gbps',
            'gather_hbm_frac', 'gather_achievable_frac',
            'gather_achieved_vs_achievable', 'stream_hbm_frac'):
    v = [r[k] for r in results if r.get(k) is not None]
    if v:
      gather[k] = round(statistics.median(v), 4)
  hbm = {}
  sf = [r['sample_hbm_frac'] for r in results
        if r.get('sample_hbm_frac') is not None]
  if sf:
    hbm['sample'] = round(statistics.median(sf), 4)
  if 'gather_hbm_frac' in gather:
    hbm['gather'] = gather['gather_hbm_frac']
  floors = [r['epoch_floor_secs'] for r in results
            if r.get('epoch_floor_secs') is not None]
  return {
      'metric': metric,
      'value': value,
      'unit': 's',
      'vs_baseline': (round(BASELINE_EPOCH_SECS / value, 4)
                      if value else None),
      'protocol': 'r5 pull+floor (r2-r4 walls not comparable)',
      'epoch_secs_min_med_max': (
          [round(min(all_runs), 4), round(med_ep, 4),
           round(max(all_runs), 4)] if ep and all_runs else None),
      'epoch_floor_secs': (round(statistics.median(floors), 4)
                           if floors else None),
      'epoch_vs_baseline': (round(BASELINE_EPOCH_SECS / med_ep, 4)
                            if med_ep else None),
      'sampled_edges_per_sec_M_min_med_max': (
          [round(es[0] / 1e6, 1), round(med_es / 1e6, 1),
           round(es[-1] / 1e6, 1)] if es else None),
      'sampling_vs_a100_nominal': (round(med_es / BASELINE_EDGES_PER_SEC,
                                         2) if med_es else None),
      'fused_epoch_secs': round(fu[0], 4) if fu else None,
      'fused_layout': (fused_res or {}).get('fused_layout'),
      'fused_epoch_runs': (fused_res or {}).get('fused_epoch_runs'),
      'fused_vs_baseline': (round(BASELINE_EPOCH_SECS / fu[0], 4)
                            if fu else None),
      'fused_epoch_secs_bf16': (fused_res or {}).get(
          'fused_epoch_secs_bf16'),
      'fused_subgraph_ms_per_step': (fused_res or {}).get(
          'fused_subgraph_ms_per_step'),
      'fused_subgraph_epoch_secs_est': (fused_res or {}).get(
          'fused_subgraph_epoch_secs_est'),
      'fused_compile_secs': (fused_res or {}).get('fused_compile_secs'),
      'fused_bf16_compile_secs': (fused_res or {}).get(
          'fused_bf16_compile_secs'),
      'fused_error': (fused_res or {}).get('fused_error'),
      'fused_suspect_elision': (fused_res or {}).get('suspect_elision'),
      'train_step_mfu': (round(statistics.median(mfu), 4)
                         if mfu else None),
      'compile_secs_med': (round(statistics.median(cs), 1)
                           if cs else None),
      'achieved_hbm_frac': hbm or None,
      'gather_roofline': gather or None,
      'fused_hetero_epoch_secs': (hetero or {}).get(
          'fused_hetero_epoch_secs'),
      'fused_hetero_ms_per_step': (hetero or {}).get(
          'fused_hetero_ms_per_step'),
      'hetero': hetero,
      'sessions': len(results),
      'session_modes': [r['mode'] for r in results],
      'steps_per_epoch': results[0]['steps'] if results else None,
      'dist': dist,
      'pallas': pallas,
  }


_SINK = None
_REGRESS = None


def _light_module(name: str, cache: str):
  """Load a json-only telemetry module directly by file path, keeping
  the driver process free of the full package (and jax) import
  chain."""
  import importlib.util
  p = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                   'graphlearn_tpu', 'telemetry', f'{name}.py')
  spec = importlib.util.spec_from_file_location(cache, p)
  mod = importlib.util.module_from_spec(spec)
  spec.loader.exec_module(mod)
  return mod


def _sink_module():
  """Load `telemetry/sink.py` directly by file path: the sink is
  json/os/tempfile-only, and loading it this way keeps the driver
  process free of the full package (and jax) import chain."""
  global _SINK
  if _SINK is None:
    _SINK = _light_module('sink', '_bench_sink')
  return _SINK


def _regress_module():
  """Load `telemetry/regress.py` by file path (json/os-only, like the
  sink)."""
  global _REGRESS
  if _REGRESS is None:
    _REGRESS = _light_module('regress', '_bench_regress')
  return _REGRESS


def _run_regression_gate(art) -> int:
  """The `--check-regression` gate (telemetry.regress): compare the
  just-written artifact against BENCH_BASELINE.json (created from this
  artifact on the first run, since the trajectory starts empty), print
  the per-metric report, stamp the compact verdict into the artifact's
  summary, and return the exit code: 0 = PASS/baseline created, 1 = a
  headline metric slowed past the threshold, 2 = the gate itself could
  not run (which must NOT fail a completed bench — main() exits
  nonzero only on rc 1)."""
  try:
    regress = _regress_module()
    sink = _sink_module()
    thr = _arg_after('--regress-threshold')
    try:
      thr = float(thr) if thr else None
    except ValueError:
      # a typo'd flag must not crash the gate AFTER the whole bench
      # ran: degrade to the env/default threshold like regress does
      print(f'--regress-threshold {thr!r} is not a number; using the '
            'default', file=sys.stderr)
      thr = None
    # gate the IN-MEMORY aggregate when we have it: if the artifact
    # sink degraded to stdout this run, the file on disk may be a
    # STALE previous run's — it must never be what gets gated
    verdict, rc = regress.check(
        art if art is not None else sink.artifact_path(),
        baseline=_arg_after('--baseline'),
        threshold=thr)
    print(regress.format_report(verdict), flush=True)
    if art is not None:
      # re-emit with the verdict so the artifact file + the bounded
      # summary line both carry it ('regression' sits near the front
      # of sink._SUMMARY_KEYS — a FAIL survives line degradation).
      # Best-effort: a re-emit failure must not downgrade an rc-1
      # verdict to the non-fatal rc 2 (CI would miss the regression).
      try:
        art = dict(art)
        art['regression'] = regress.summary(verdict)
        art['regression_report'] = verdict
        print(_emit_artifact(art), flush=True)
      except Exception as e:      # noqa: BLE001
        print(f'could not stamp the regression verdict into the '
              f'artifact ({type(e).__name__}: {e})', file=sys.stderr)
    return rc
  except Exception as e:          # noqa: BLE001 — the gate must
    # report, never traceback-crash a driver whose bench phases all
    # completed (missing artifact, unreadable baseline, ...)
    print(f'regression gate could not run '
          f'({type(e).__name__}: {e})', file=sys.stderr)
    return 2


def _emit_artifact(art):
  """The r6 artifact sink contract: write the FULL aggregate to the
  artifact file (atomic) and return the short stdout summary line —
  always <= 2000 chars, always naming the artifact file.  The driver's
  last-JSON-line salvage parses the summary; the evidence lives in the
  file.

  Degrades, never dies: if the sink cannot write (read-only cwd, disk
  full), the FULL aggregate JSON goes to stdout exactly as before r6 —
  a sink failure must not cost the measurement (the indestructible-
  artifact contract this sink exists to strengthen)."""
  try:
    sink = _sink_module()
    path = sink.write_artifact(art)
    return sink.summary_line(art, artifact=path)
  except Exception as e:            # noqa: BLE001 — degrade to stdout
    print(f'artifact sink failed ({type(e).__name__}: {e}); '
          f'falling back to full JSON on stdout', file=sys.stderr)
    return json.dumps(art)


def main():
  sessions = int(os.environ.get('GLT_BENCH_SESSIONS', 4))
  session_timeout = int(os.environ.get('GLT_BENCH_SESSION_TIMEOUT', 420))
  # hard wall for the whole harness, sized INSIDE the driver's wall:
  # with the zero-upload setup a primary session costs ~2-4 min and
  # the fused session ~4-6 min (compile-dominated); slow days degrade
  # phase by phase, each one leaving a fresh cumulative artifact line
  total_budget = float(os.environ.get('GLT_BENCH_TOTAL_BUDGET', 1200))
  dist_timeout = int(os.environ.get('GLT_BENCH_DIST_TIMEOUT', 600))
  fused_timeout = int(os.environ.get('GLT_BENCH_FUSED_TIMEOUT', 600))
  t_start = time.monotonic()

  def budget_left():
    return total_budget - (time.monotonic() - t_start)

  results, fused_res, dist, hetero = [], None, None, None
  pallas_row = [None]
  last_art = [None]

  def emit():
    """The indestructible-artifact contract: full cumulative
    aggregate to the artifact FILE after every completed phase;
    stdout gets only the bounded summary line."""
    if results or fused_res or dist or hetero or pallas_row[0]:
      last_art[0] = _aggregate(results, fused_res, dist, hetero,
                               pallas_row[0])
      print(_emit_artifact(last_art[0]), flush=True)

  # phase 1 — one primary session (epochs + sampling + roofline).
  attempts = 0
  while not results and attempts < 3:
    tmo = int(min(session_timeout, max(budget_left() - 60, 120)))
    if budget_left() < 180:
      print(f'budget: giving up on primary after {attempts} attempts',
            file=sys.stderr)
      break
    r = _run_session(tmo)
    attempts += 1
    if r is not None:
      results.append(r)
      emit()

  # phase 2 — dedicated fused session (whole-epoch FusedEpoch,
  # always fresh compiles): lands the HEADLINE number
  if budget_left() > 150:
    fused_res = _run_session(
        int(min(fused_timeout, max(budget_left() - 10, 120))),
        fused=True)
    emit()
  else:
    print(f'budget: skipping the fused session '
          f'({budget_left():.0f}s left)', file=sys.stderr)

  # phase 3 — dist section (CPU mesh; tunnel-independent; emits a
  # complete JSON line after EVERY internal phase)
  if budget_left() > 90:
    dist = _run_dist_section(
        int(min(dist_timeout, max(budget_left() - 30, 60))))
    emit()
  else:
    print(f'budget: skipping dist ({budget_left():.0f}s left)',
          file=sys.stderr)

  # phase 3b — hetero fused session (VERDICT r4 #8).  ~100-150 s with
  # a warm compile cache (the MAG-scale graph builders and the RGCN
  # scan all cache); it outranks extra primary sessions — a unique
  # datum beats another sample of an existing one
  if budget_left() > 200:
    hetero = _run_hetero_session(
        int(min(480, max(budget_left() - 20, 120))))
    emit()
  else:
    print(f'budget: skipping hetero ({budget_left():.0f}s left)',
          file=sys.stderr)

  # phase 3c — per-P scale-envelope rows for the dist section (each
  # ~60-120 s; a new datum, so it outranks extra primary samples —
  # the r5 runs where this sat after phase 4 never reached it)
  if not (isinstance(dist, dict) and 'error' not in dist):
    print('skipping envelope rows: no dist section to attach to',
          file=sys.stderr)
  elif budget_left() <= 160:
    print(f'budget: skipping envelope rows ({budget_left():.0f}s left)',
          file=sys.stderr)
  else:
    env_rows = []
    for p_, bsz in ((16, 64), (64, 32)):
      # rows now include the per-layout comparison epochs (3 extra
      # compiles) and the 5-epoch adaptive walk: up to ~7 min worst
      # case, typically 2-3 — don't launch with less than ~3 min left
      # (a timed-out row burns the budget AND leaves the guarded
      # dist.scale_envelope.pNN metrics unwatched)
      if budget_left() < 200:
        break
      r = _run_envelope_row(p_, bsz,
                            int(min(420, max(budget_left() - 30, 170))))
      if r is not None:
        env_rows.append(r)
    if env_rows:
      dist['scale_envelope'] = env_rows
      # lift the P=16 row's traffic attribution to a stable dotted
      # address (ISSUE 16): the regress gate guards
      # dist.attribution.cross_partition_bytes_frac (lower) and
      # dist.attribution.hot_range_coverage (higher)
      att = next((r['attribution'] for r in env_rows
                  if r.get('num_parts') == 16
                  and isinstance(r.get('attribution'), dict)), None)
      if att:
        dist['attribution'] = {
            'num_parts': att.get('num_parts'),
            'cross_partition_bytes_frac': att.get(
                'cross_partition_bytes_frac'),
            'cross_partition_ids_frac': att.get(
                'cross_partition_ids_frac'),
            'hot_range_coverage': att.get('hot_range_coverage'),
            'hotness_source': att.get('hotness_source'),
        }
      # lift the P=16 row's locality comparison (ISSUE 20) the same
      # way: dist.locality.cross_partition_bytes_frac (lower) and
      # dist.locality.seeds_per_sec (higher) are regression-guarded,
      # each with `same: dist.locality.partitioner` so a partitioner
      # change resets the baseline instead of tripping the gate
      loc = next((r['locality'] for r in env_rows
                  if r.get('num_parts') == 16
                  and isinstance(r.get('locality'), dict)
                  and isinstance(r['locality'].get('locality'), dict)),
                 None)
      if loc:
        arm = loc['locality']
        dist['locality'] = {
            'num_parts': 16,
            'partitioner': arm.get('partitioner'),
            'cross_partition_bytes_frac': arm.get(
                'cross_partition_bytes_frac'),
            'cross_partition_ids_frac': arm.get(
                'cross_partition_ids_frac'),
            'locally_served_ids': arm.get('locally_served_ids'),
            'seeds_per_sec': arm.get('seeds_per_sec'),
            'drop_rate_pct': arm.get('drop_rate_pct'),
            'range_cross_partition_bytes_frac': loc.get(
                'range', {}).get('cross_partition_bytes_frac'),
            'locality_over_range_speedup': loc.get(
                'locality_over_range_speedup'),
            'rename_equivalent': loc.get('rename_equivalent'),
        }
      emit()

  # phase 3d — resilience smoke (ISSUE 4): the host server->client
  # path with the retry/idempotency layer on — fault-free throughput
  # feeds the dist.chaos.fault_free_seeds_per_sec regression guard,
  # and one chaos epoch proves exact accounting under faults
  if not (isinstance(dist, dict) and 'error' not in dist):
    print('skipping chaos smoke: no dist section to attach to',
          file=sys.stderr)
  elif budget_left() <= 150:
    print(f'budget: skipping chaos smoke ({budget_left():.0f}s left)',
          file=sys.stderr)
  else:
    r = _run_chaos_row(int(min(300, max(budget_left() - 30, 120))))
    if r is not None:
      dist['chaos'] = r
      emit()

  # phase 3e — preemption-resume smoke (ISSUE 6): snapshot-overhead
  # epoch timing vs the no-snapshot line + kill -> durable restore ->
  # finish; feeds the dist.resume.restore_secs / replayed_batches
  # regression guards
  if isinstance(dist, dict) and 'error' not in dist and \
      budget_left() > 150:
    r = _run_resume_row(int(min(300, max(budget_left() - 30, 120))))
    if r is not None:
      dist['resume'] = r
      emit()

  # phase 3f — online serving (ISSUE 9): Zipf open-loop traffic
  # against the coalescing tier; feeds dist.serving.p99_ms /
  # dist.serving.qps (+ shed_rate reported) and pins zero recompiles
  # after warmup
  if isinstance(dist, dict) and 'error' not in dist and \
      budget_left() > 120:
    r = _run_serving_row(int(min(300, max(budget_left() - 30, 90))))
    if r is not None:
      dist['serving'] = r
      emit()
    # fleet failover acceptance (ISSUE 13): same Zipf open loop across
    # 3 replicas behind the FleetRouter with a stall-then-kill on one
    # — feeds dist.serving.fleet_qps / .failover_failed_requests (the
    # worker exits nonzero on ANY failed/dropped request or a <0.6x
    # qps recovery, stamped into failover_pin)
    if budget_left() > 90:
      fr = _run_fleet_row(int(min(300, max(budget_left() - 30, 90))))
      if fr is not None and isinstance(dist.get('serving'), dict):
        dist['serving'].update(fr)
        emit()
      elif fr is not None:
        dist['serving'] = fr
        emit()
  elif isinstance(dist, dict) and 'error' not in dist:
    print(f'budget: skipping serving phase ({budget_left():.0f}s left)',
          file=sys.stderr)

  # phase 3g — streaming ingestion (ISSUE 14): the freshness-vs-
  # throughput open loop (events/s through the WAL-backed delta-CSR
  # pipeline while the Zipf serving p99 holds); feeds
  # dist.ingest.events_per_sec / .p99_during_ingest_ms, and the
  # worker's nonzero exit (any shed during ingest / recompile /
  # unapplied lag) lands in ingest_pin
  if isinstance(dist, dict) and 'error' not in dist and \
      budget_left() > 90:
    r = _run_ingest_row(int(min(300, max(budget_left() - 30, 90))))
    if r is not None:
      dist['ingest'] = r
      emit()
  elif isinstance(dist, dict) and 'error' not in dist:
    print(f'budget: skipping ingest phase ({budget_left():.0f}s left)',
          file=sys.stderr)

  # phase 3h — elastic partition failover (ISSUE 15): one owner
  # killed mid-epoch with a durable shard present — adoption, exact
  # completion, byte-identity; feeds dist.failover.recovery_secs /
  # .completed_ratio, and the worker's nonzero exit (any completion
  # or identity violation) lands in failover_pin
  if isinstance(dist, dict) and 'error' not in dist and \
      budget_left() > 90:
    r = _run_failover_row(int(min(300, max(budget_left() - 30, 90))))
    if r is not None:
      dist['failover'] = r
      emit()
  elif isinstance(dist, dict) and 'error' not in dist:
    print(f'budget: skipping failover phase ({budget_left():.0f}s '
          f'left)', file=sys.stderr)

  # phase 3i — Pallas fused-pipeline rows (ISSUE 18): dispatcher-
  # threaded FusedEpoch step time (knob OFF), pinned-host cold-gather
  # GB/s at split<1 (hardware-only, 1.355 GB/s pin), delta-merge
  # events/s; feeds the pallas.* regression guards.  Unlike the dist
  # phases this row does NOT need the dist section — it measures
  # single-process paths and attaches at the artifact top level
  if budget_left() > 120:
    r = _run_pallas_row(int(min(420, max(budget_left() - 30, 90))))
    if r is not None:
      pallas_row[0] = r
      emit()
  else:
    print(f'budget: skipping pallas rows ({budget_left():.0f}s left)',
          file=sys.stderr)

  # phase 3j — closed-loop elastic autoscaling + planned handoff
  # (ISSUE 19): the diurnal open loop drives ElasticController
  # scale-out/in with a chaos-faulted first spawn, then a planned
  # mid-epoch partition handoff; feeds dist.autoscale.p99_held_ms /
  # .burn_max / .handoff_degraded_batches, and the worker's nonzero
  # exit (missed scale event, failed request, burn >= 1 outside the
  # incident, degraded handoff batch) lands in autoscale_pin
  if isinstance(dist, dict) and 'error' not in dist and \
      budget_left() > 90:
    r = _run_autoscale_row(int(min(300, max(budget_left() - 30, 90))))
    if r is not None:
      dist['autoscale'] = r
      emit()
  elif isinstance(dist, dict) and 'error' not in dist:
    print(f'budget: skipping autoscale phase ({budget_left():.0f}s '
          f'left)', file=sys.stderr)

  # phase 4 — extra primary sessions stabilize the per-batch median
  while (len(results) < sessions and attempts < sessions + 3
         and budget_left() > session_timeout * 0.75):
    r = _run_session(int(min(session_timeout, budget_left())))
    attempts += 1
    if r is not None:
      results.append(r)
      emit()

  if not (results or fused_res or dist):
    raise SystemExit('all bench phases failed')
  emit()                            # final (possibly repeated) line

  # phase 5 — the bench regression gate (--check-regression): fail
  # the run ONLY on a genuine regression (rc 1).  A gate that could
  # not run at all (rc 2: missing artifact, unwritable baseline dir)
  # is reported but must not fail a bench whose measurement phases
  # all completed.
  if '--check-regression' in sys.argv:
    rc = _run_regression_gate(last_art[0])
    if rc == 1:
      raise SystemExit(1)


if __name__ == '__main__':
  if '--dist-worker' in sys.argv:
    dist_worker()
  elif '--hetero-session' in sys.argv:
    hetero_worker()
  elif '--fused-session' in sys.argv:
    worker(fused_only=True)
  elif '--bench-worker' in sys.argv:
    worker()
  else:
    main()
