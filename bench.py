"""Headline benchmark: neighbor-sampling throughput on one TPU chip.

Reproduces the reference's metric definition — "Sampled Edges per secs"
(`benchmarks/api/bench_sampler.py:46-54`: wall-clock around
`sampler.sample_from_nodes`, edges counted from the sampled topology) —
on the reference's flagship config: fanout [15, 10, 5], batch 1024
(`examples/train_sage_ogbn_products.py:16`), on an ogbn-products-scale
synthetic graph (2.45M nodes, ~62M directed edges).

The reference publishes figures, not numbers (`BASELINE.md`);
``BASELINE_EDGES_PER_SEC`` is our normalization constant: 100M
sampled-edges/sec, a mid-range read of GLT's single-A100 scale_up plot
era. vs_baseline > 1.0 means faster than that nominal A100 figure.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from benchmarks.common import NUM_NODES, build_graph  # noqa: E402

BASELINE_EDGES_PER_SEC = 100e6

FANOUT = (15, 10, 5)
BATCH = 1024
WARMUP = 3
ITERS = 50


def main():
  import jax
  sys.path.insert(0, '.')
  from graphlearn_tpu.data import Dataset
  from graphlearn_tpu.sampler import NeighborSampler, NodeSamplerInput

  if '--cpu' in sys.argv:
    jax.config.update('jax_platforms', 'cpu')
  dev = jax.devices()[0]

  rows, cols = build_graph()
  ds = Dataset().init_graph((rows, cols), layout='COO', num_nodes=NUM_NODES)
  g = ds.get_graph()
  g.lazy_init()

  sampler = NeighborSampler(g, FANOUT, seed=0)
  rng = np.random.default_rng(1)
  # Pre-generate seed batches (the reference iterates a pre-built
  # DataLoader over train_idx likewise); transfer stays in the timer.
  seed_batches = [rng.integers(0, NUM_NODES, BATCH).astype(np.int32)
                  for _ in range(WARMUP + ITERS)]

  def one_batch(i):
    return sampler.sample_from_nodes(
        NodeSamplerInput(node=seed_batches[i]))

  # Warmup (compile) — not timed.
  for i in range(WARMUP):
    out = one_batch(i)
  out.node.block_until_ready()

  # Best of 3 repetitions: the sampling program is deterministic-cost;
  # repetition suppresses host/dispatch jitter (which otherwise swings
  # the measurement several-fold on tunneled chips).  Edge counting
  # happens ON DEVICE (one scalar pull per rep): bulk device->host
  # pulls permanently degrade tunneled dispatch (benchmarks/README,
  # "first-burst validity"), which would poison reps 2-3.
  import jax.numpy as jnp
  best_dt, edges = None, 0
  for _ in range(3):
    t0 = time.perf_counter()
    outs = []
    for i in range(ITERS):
      outs.append(one_batch(WARMUP + i))
    for o in outs:
      o.row.block_until_ready()
    dt = time.perf_counter() - t0
    if best_dt is None or dt < best_dt:
      best_dt = dt
      edges_dev = sum((o.edge_mask.sum() for o in outs),
                      jnp.zeros((), jnp.int32))
      edges = int(edges_dev)       # single tiny transfer, post-timer
  eps = edges / best_dt
  print(json.dumps({
      'metric': f'sampled_edges_per_sec (fanout {list(FANOUT)}, '
                f'batch {BATCH}, {dev.platform})',
      'value': round(eps / 1e6, 3),
      'unit': 'M edges/s',
      'vs_baseline': round(eps / BASELINE_EDGES_PER_SEC, 4),
  }))


if __name__ == '__main__':
  main()
