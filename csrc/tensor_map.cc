// Flat binary serialization of a sample message: Dict[str, ndarray].
//
// Counterpart of the reference's TensorMapSerializer
// (`csrc/tensor_map.cc:28-85`, `include/tensor_map.h:21-28`), host-only
// (device arrays are materialized to host by the producer before
// enqueue — there is no CUDA memcpy analog; TPU batches cross the
// process boundary as host numpy buffers and are device_put by the
// consumer).
//
// Layout (little-endian, 8-byte aligned data):
//   u64 magic | u32 n_entries | per entry:
//     u16 key_len | key bytes | u8 dtype | u8 ndim | u64 shape[ndim]
//     | pad to 8 | u64 nbytes | data | pad to 8
//
// dtype codes match numpy via the Python wrapper's table.
#include <cstdint>
#include <cstring>

#include "common.h"

namespace {
constexpr uint64_t kMagic = 0x474c54544d415031ull;  // "GLTTMAP1"
inline uint64_t pad8(uint64_t x) { return (x + 7) & ~7ull; }
}  // namespace

extern "C" {

// Compute the serialized size of a message described by parallel
// arrays (key lengths, ndims, shapes flattened, nbytes per tensor).
uint64_t glt_tmap_size(uint32_t n, const uint16_t* key_lens,
                       const uint8_t* ndims, const uint64_t* nbytes) {
  uint64_t sz = 8 + 4;
  for (uint32_t i = 0; i < n; ++i) {
    sz += 2 + key_lens[i] + 1 + 1 + 8ull * ndims[i];
    sz = pad8(sz);
    sz += 8 + nbytes[i];
    sz = pad8(sz);
  }
  return sz;
}

// Serialize into `out` (caller sized it with glt_tmap_size).
// `keys` is the concatenation of key bytes; `shapes` the concatenation
// of per-tensor shapes; `datas` an array of source pointers.
// Returns bytes written.
uint64_t glt_tmap_write(uint32_t n, const uint16_t* key_lens,
                        const char* keys, const uint8_t* dtypes,
                        const uint8_t* ndims, const uint64_t* shapes,
                        const uint64_t* nbytes, const void* const* datas,
                        char* out) {
  char* p = out;
  memcpy(p, &kMagic, 8); p += 8;
  memcpy(p, &n, 4); p += 4;
  const char* kp = keys;
  const uint64_t* sp = shapes;
  for (uint32_t i = 0; i < n; ++i) {
    memcpy(p, &key_lens[i], 2); p += 2;
    memcpy(p, kp, key_lens[i]); p += key_lens[i]; kp += key_lens[i];
    *p++ = (char)dtypes[i];
    *p++ = (char)ndims[i];
    memcpy(p, sp, 8ull * ndims[i]); p += 8ull * ndims[i]; sp += ndims[i];
    p = out + pad8(p - out);
    memcpy(p, &nbytes[i], 8); p += 8;
    memcpy(p, datas[i], nbytes[i]); p += nbytes[i];
    p = out + pad8(p - out);
  }
  return (uint64_t)(p - out);
}

// Parse pass 1: entry count (0 on bad magic).
uint32_t glt_tmap_count(const char* buf, uint64_t len) {
  if (len < 12) return 0;
  uint64_t magic;
  memcpy(&magic, buf, 8);
  if (magic != kMagic) return 0;
  uint32_t n;
  memcpy(&n, buf + 8, 4);
  return n;
}

// Parse pass 2: fill parallel descriptor arrays; data_offsets are
// byte offsets into `buf` (so Python can build zero-copy views).
// Returns 0 ok, -1 malformed.
int glt_tmap_parse(const char* buf, uint64_t len, uint16_t* key_lens,
                   char* keys /*cap: sum of key_lens*/, uint8_t* dtypes,
                   uint8_t* ndims, uint64_t* shapes /*cap: sum ndims*/,
                   uint64_t* nbytes, uint64_t* data_offsets) {
  uint32_t n = glt_tmap_count(buf, len);
  const char* p = buf + 12;
  const char* end = buf + len;
  char* kp = keys;
  uint64_t* sp = shapes;
  for (uint32_t i = 0; i < n; ++i) {
    if (p + 2 > end) return -1;
    memcpy(&key_lens[i], p, 2); p += 2;
    if (p + key_lens[i] + 2 > end) return -1;
    memcpy(kp, p, key_lens[i]); p += key_lens[i]; kp += key_lens[i];
    dtypes[i] = (uint8_t)*p++;
    ndims[i] = (uint8_t)*p++;
    if (p + 8ull * ndims[i] > end) return -1;
    memcpy(sp, p, 8ull * ndims[i]); p += 8ull * ndims[i]; sp += ndims[i];
    p = buf + pad8(p - buf);
    if (p + 8 > end) return -1;
    memcpy(&nbytes[i], p, 8); p += 8;
    if (p + nbytes[i] > end) return -1;
    data_offsets[i] = (uint64_t)(p - buf);
    p += nbytes[i];
    p = buf + pad8(p - buf);
  }
  return 0;
}

}  // extern "C"
