// Cross-process sample-message queue in SysV shared memory.
//
// TPU-native rethink of the reference's ShmQueue
// (`csrc/shm_queue.cc`, `include/shm_queue.h:30-240`).  The reference
// allocates variable-size blocks on a byte ring with per-block
// semaphores because its torch messages are ragged.  Our whole design
// is static-shape (padded batches), so every message in an epoch has
// the same byte size: a fixed-slot bounded MPMC ring (Vyukov sequence
// numbers for slot ownership + two counting semaphores for blocking)
// is simpler, has no fragmentation, and one fewer copy on the reader
// side.  Multi-producer / multi-consumer, blocking semantics identical
// to the reference (producers block when full, consumers when empty).
//
// The queue is picklable by shmid (reference `py_export.cc:132-140`):
// any process on the host can attach with `glt_queue_attach`.
#include <cerrno>
#include <ctime>
#include <semaphore.h>
#include <sys/ipc.h>
#include <sys/shm.h>

#include <atomic>
#include <cstdint>
#include <cstring>

#include "common.h"

namespace {

struct SlotHeader {
  std::atomic<uint64_t> seq;  // Vyukov sequence number.
  uint64_t len;               // payload bytes actually used.
};

struct QueueHeader {
  uint64_t magic;
  uint64_t num_slots;
  uint64_t slot_bytes;  // payload capacity per slot (excl. SlotHeader)
  std::atomic<uint64_t> head;  // producer ticket
  std::atomic<uint64_t> tail;  // consumer ticket
  sem_t free_slots;    // counts empty slots; producers wait here
  sem_t filled_slots;  // counts ready slots; consumers wait here
};

constexpr uint64_t kMagic = 0x474c545451ull;  // "GLTTQ"
constexpr size_t kAlign = 64;

inline size_t aligned(size_t x) { return (x + kAlign - 1) / kAlign * kAlign; }

inline size_t slot_stride(uint64_t slot_bytes) {
  return aligned(sizeof(SlotHeader)) + aligned(slot_bytes);
}

struct Queue {
  int shmid;
  QueueHeader* hdr;
  char* slots;

  SlotHeader* slot_hdr(uint64_t i) const {
    return reinterpret_cast<SlotHeader*>(
        slots + i * slot_stride(hdr->slot_bytes));
  }
  char* slot_data(uint64_t i) const {
    return slots + i * slot_stride(hdr->slot_bytes) +
           aligned(sizeof(SlotHeader));
  }
};

Queue* attach(int shmid) {
  void* base = shmat(shmid, nullptr, 0);
  if (base == (void*)-1) return nullptr;
  auto* q = new Queue();
  q->shmid = shmid;
  q->hdr = reinterpret_cast<QueueHeader*>(base);
  q->slots = reinterpret_cast<char*>(base) + aligned(sizeof(QueueHeader));
  return q;
}

}  // namespace

extern "C" {

// Create a queue with `num_slots` slots of `slot_bytes` payload each.
// Returns an opaque handle, or null on failure.  The segment is
// created IPC_PRIVATE: share it by passing `glt_queue_shmid` to
// children (fork/spawn both fine).
void* glt_queue_create(uint64_t num_slots, uint64_t slot_bytes) {
  size_t total =
      aligned(sizeof(QueueHeader)) + num_slots * slot_stride(slot_bytes);
  int shmid = shmget(IPC_PRIVATE, total, IPC_CREAT | 0600);
  if (shmid < 0) return nullptr;
  Queue* q = attach(shmid);
  if (!q) return nullptr;
  q->hdr->magic = kMagic;
  q->hdr->num_slots = num_slots;
  q->hdr->slot_bytes = slot_bytes;
  q->hdr->head.store(0);
  q->hdr->tail.store(0);
  sem_init(&q->hdr->free_slots, /*pshared=*/1, num_slots);
  sem_init(&q->hdr->filled_slots, /*pshared=*/1, 0);
  for (uint64_t i = 0; i < num_slots; ++i) {
    q->slot_hdr(i)->seq.store(i);
    q->slot_hdr(i)->len = 0;
  }
  // Mark for auto-removal once every attached process detaches (or
  // dies) — the kernel reclaims the segment, so no leak on crash.
  shmctl(shmid, IPC_RMID, nullptr);
  return q;
}

void* glt_queue_attach(int shmid) { return attach(shmid); }

int glt_queue_shmid(void* handle) {
  return static_cast<Queue*>(handle)->shmid;
}

uint64_t glt_queue_slot_bytes(void* handle) {
  return static_cast<Queue*>(handle)->hdr->slot_bytes;
}

uint64_t glt_queue_num_slots(void* handle) {
  return static_cast<Queue*>(handle)->hdr->num_slots;
}

// Number of messages currently ready to read.
uint64_t glt_queue_size(void* handle) {
  Queue* q = static_cast<Queue*>(handle);
  int v = 0;
  sem_getvalue(&q->hdr->filled_slots, &v);
  return v < 0 ? 0 : (uint64_t)v;
}

// Blocking enqueue.  Returns 0 ok, -1 message too large.
int glt_queue_put(void* handle, const void* data, uint64_t len) {
  Queue* q = static_cast<Queue*>(handle);
  if (len > q->hdr->slot_bytes) return -1;
  sem_wait(&q->hdr->free_slots);
  uint64_t ticket = q->hdr->head.fetch_add(1);
  uint64_t i = ticket % q->hdr->num_slots;
  SlotHeader* sh = q->slot_hdr(i);
  // Wait until this slot's previous consumer has fully released it.
  while (sh->seq.load(std::memory_order_acquire) != ticket) {
  }
  memcpy(q->slot_data(i), data, len);
  sh->len = len;
  sh->seq.store(ticket + 1, std::memory_order_release);
  sem_post(&q->hdr->filled_slots);
  return 0;
}

// Blocking dequeue into `out` (capacity `cap`).  Returns payload
// length, or -1 if the message exceeds `cap` (message is dropped).
int64_t glt_queue_get(void* handle, void* out, uint64_t cap) {
  Queue* q = static_cast<Queue*>(handle);
  sem_wait(&q->hdr->filled_slots);
  uint64_t ticket = q->hdr->tail.fetch_add(1);
  uint64_t i = ticket % q->hdr->num_slots;
  SlotHeader* sh = q->slot_hdr(i);
  while (sh->seq.load(std::memory_order_acquire) != ticket + 1) {
  }
  int64_t len = (int64_t)sh->len;
  int64_t ret = len;
  if ((uint64_t)len <= cap) {
    memcpy(out, q->slot_data(i), len);
  } else {
    ret = -1;
  }
  sh->seq.store(ticket + q->hdr->num_slots, std::memory_order_release);
  sem_post(&q->hdr->free_slots);
  return ret;
}

// Timed dequeue: like glt_queue_get but waits at most `timeout_ms`
// for a message.  Returns payload length, -1 oversized (dropped),
// -2 timeout (nothing consumed).  Lets consumers run liveness
// watchdogs without busy-polling or losing the blocking fast path.
int64_t glt_queue_get_timed(void* handle, void* out, uint64_t cap,
                            int64_t timeout_ms) {
  Queue* q = static_cast<Queue*>(handle);
  struct timespec ts;
  clock_gettime(CLOCK_REALTIME, &ts);
  ts.tv_sec += timeout_ms / 1000;
  ts.tv_nsec += (timeout_ms % 1000) * 1000000L;
  if (ts.tv_nsec >= 1000000000L) {
    ts.tv_sec += 1;
    ts.tv_nsec -= 1000000000L;
  }
  while (sem_timedwait(&q->hdr->filled_slots, &ts) != 0) {
    if (errno == ETIMEDOUT) return -2;
    if (errno != EINTR) return -2;  // treat other failures as timeout
  }
  uint64_t ticket = q->hdr->tail.fetch_add(1);
  uint64_t i = ticket % q->hdr->num_slots;
  SlotHeader* sh = q->slot_hdr(i);
  while (sh->seq.load(std::memory_order_acquire) != ticket + 1) {
  }
  int64_t len = (int64_t)sh->len;
  int64_t ret = len;
  if ((uint64_t)len <= cap) {
    memcpy(out, q->slot_data(i), len);
  } else {
    ret = -1;
  }
  sh->seq.store(ticket + q->hdr->num_slots, std::memory_order_release);
  sem_post(&q->hdr->free_slots);
  return ret;
}

// Non-blocking probe: returns 1 if a message is ready.
int glt_queue_empty(void* handle) {
  return glt_queue_size(handle) == 0 ? 1 : 0;
}

void glt_queue_detach(void* handle) {
  Queue* q = static_cast<Queue*>(handle);
  shmdt(reinterpret_cast<void*>(q->hdr));
  delete q;
}

}  // extern "C"
