// Host-side stateful inducer: cross-hop dedup + global->local relabel.
//
// Counterpart of the reference's CPU inducer (`csrc/cpu/inducer.cc`,
// `include/inducer.h:27-70`): `InitNode(seed)` seeds the table,
// `InduceNext(...)` inserts new nodes and emits local COO.  The host
// side has no static-shape constraint, so a plain open-addressing
// table is the right tool (the device twin in
// `graphlearn_tpu/ops/unique.py` is sort-based with fixed capacity).
// Inputs are the dense `[B, k]` + mask layout of our sampling ops;
// masked slots produce no edges.
#include <cstdint>
#include <cstring>
#include <vector>

#include "common.h"

using glt::kInvalidId;
using glt::splitmix64;

namespace {

// Open-addressing global->local map sized for ~millions of nodes.
class Inducer {
 public:
  explicit Inducer(int64_t capacity_hint) { reserve(capacity_hint * 2 + 64); }

  void clear() {
    std::fill(keys_.begin(), keys_.end(), kInvalidId);
    vals_.assign(vals_.size(), 0);
    nodes_.clear();
  }

  // Insert; returns local id.
  int32_t insert(int64_t g) {
    if (nodes_.size() * 2 >= keys_.size()) grow();
    size_t m = keys_.size() - 1;
    size_t pos = splitmix64((uint64_t)g) & m;
    while (true) {
      if (keys_[pos] == g) return vals_[pos];
      if (keys_[pos] == kInvalidId) {
        keys_[pos] = g;
        vals_[pos] = (int32_t)nodes_.size();
        nodes_.push_back(g);
        return vals_[pos];
      }
      pos = (pos + 1) & m;
    }
  }

  int32_t lookup(int64_t g) const {
    size_t m = keys_.size() - 1;
    size_t pos = splitmix64((uint64_t)g) & m;
    while (true) {
      if (keys_[pos] == g) return vals_[pos];
      if (keys_[pos] == kInvalidId) return -1;
      pos = (pos + 1) & m;
    }
  }

  const std::vector<int64_t>& nodes() const { return nodes_; }

 private:
  void reserve(size_t n) {
    size_t cap = 64;
    while (cap < n) cap <<= 1;
    keys_.assign(cap, kInvalidId);
    vals_.assign(cap, 0);
  }
  void grow() {
    std::vector<int64_t> old_nodes = nodes_;
    reserve(keys_.size() * 2);
    nodes_.clear();
    for (int64_t g : old_nodes) insert(g);
  }

  std::vector<int64_t> keys_;
  std::vector<int32_t> vals_;
  std::vector<int64_t> nodes_;
};

}  // namespace

extern "C" {

void* glt_inducer_create(int64_t capacity_hint) {
  return new Inducer(capacity_hint);
}

void glt_inducer_destroy(void* h) { delete static_cast<Inducer*>(h); }

void glt_inducer_clear(void* h) { static_cast<Inducer*>(h)->clear(); }

int64_t glt_inducer_num_nodes(void* h) {
  return (int64_t)static_cast<Inducer*>(h)->nodes().size();
}

// Seed the table; writes local ids of the seeds to `out_local`.
void glt_inducer_init(void* h, const int64_t* seeds, int64_t n,
                      int32_t* out_local) {
  auto* ind = static_cast<Inducer*>(h);
  for (int64_t i = 0; i < n; ++i) {
    out_local[i] =
        seeds[i] == kInvalidId ? -1 : ind->insert(seeds[i]);
  }
}

// One hop: srcs [B] global, nbrs/mask [B, k].  Emits local COO into
// row_local/col_local (capacity B*k; masked slots get -1) and returns
// the number of *new* unique nodes appended to the table (fetch them
// with glt_inducer_nodes_since).
int64_t glt_inducer_induce(void* h, const int64_t* srcs, const int64_t* nbrs,
                           const uint8_t* mask, int64_t batch, int64_t k,
                           int32_t* row_local, int32_t* col_local) {
  auto* ind = static_cast<Inducer*>(h);
  int64_t before = (int64_t)ind->nodes().size();
  for (int64_t b = 0; b < batch; ++b) {
    int64_t s = srcs[b];
    int32_t sl = s == kInvalidId ? -1 : ind->insert(s);
    for (int64_t j = 0; j < k; ++j) {
      int64_t idx = b * k + j;
      if (sl < 0 || !mask[idx] || nbrs[idx] == kInvalidId) {
        row_local[idx] = -1;
        col_local[idx] = -1;
        continue;
      }
      int32_t nl = ind->insert(nbrs[idx]);
      // PyG message-passing direction: edge from neighbor -> seed
      // (reference transposes likewise,
      //  `sampler/neighbor_sampler.py:159-166`).
      row_local[idx] = nl;
      col_local[idx] = sl;
    }
  }
  return (int64_t)ind->nodes().size() - before;
}

// Copy table nodes [start, start+n) into `out` (global ids in local-id
// order).
void glt_inducer_nodes_since(void* h, int64_t start, int64_t n,
                             int64_t* out) {
  auto* ind = static_cast<Inducer*>(h);
  memcpy(out, ind->nodes().data() + start, sizeof(int64_t) * n);
}

// One HETERO hop: the frontier lives in a *different* (source-type)
// table, so its local ids are passed in directly; neighbors insert
// into THIS (destination-type) table.  Counterpart of the reference's
// per-node-type hetero inducer (`csrc/cpu/inducer.cc`, hetero variants
// keyed by type at `csrc/cuda/inducer.cu:149+`).  src_local [B] are
// seed-side local ids (already -1 for invalid slots); nbrs/mask [B,k]
// are destination-type globals.  Emits neighbor->seed local COO (row =
// dst-table local, col = src-table local) and returns the number of
// new unique nodes appended to this table.
int64_t glt_inducer_induce_pair(void* dst_h, const int32_t* src_local,
                                const int64_t* nbrs, const uint8_t* mask,
                                int64_t batch, int64_t k,
                                int32_t* row_local, int32_t* col_local) {
  auto* dst = static_cast<Inducer*>(dst_h);
  int64_t before = (int64_t)dst->nodes().size();
  for (int64_t b = 0; b < batch; ++b) {
    int32_t sl = src_local[b];
    for (int64_t j = 0; j < k; ++j) {
      int64_t idx = b * k + j;
      if (sl < 0 || !mask[idx] || nbrs[idx] == kInvalidId) {
        row_local[idx] = -1;
        col_local[idx] = -1;
        continue;
      }
      row_local[idx] = dst->insert(nbrs[idx]);
      col_local[idx] = sl;
    }
  }
  return (int64_t)dst->nodes().size() - before;
}

}  // extern "C"
