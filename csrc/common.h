// Shared helpers for the graphlearn_tpu native host runtime.
//
// TPU-native counterpart of the reference's `include/common.h`: the
// device plane is JAX/XLA (no CUDA here); this library provides the
// *host* runtime — cross-process queues, serialization, and CPU twins
// of the sampling ops for producer processes (reference:
// `csrc/cpu/*.cc`).  All external entry points are `extern "C"` for
// ctypes binding (no pybind11 in this build).
#pragma once

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>

namespace glt {

// Sentinel for padded/invalid ids — must match
// graphlearn_tpu/utils/padding.py INVALID_ID.
constexpr int64_t kInvalidId = -1;

// SplitMix64 — counter-based, statistically solid, fast.  Used to
// derive per-row streams so sampling is order-independent and
// reproducible, mirroring the counter-based (threefry/Philox) stance
// of the device ops.
inline uint64_t splitmix64(uint64_t x) {
  x += 0x9e3779b97f4a7c15ull;
  x = (x ^ (x >> 30)) * 0xbf58476d1ce4e5b9ull;
  x = (x ^ (x >> 27)) * 0x94d049bb133111ebull;
  return x ^ (x >> 31);
}

struct Rng {
  uint64_t state;
  explicit Rng(uint64_t seed) : state(splitmix64(seed)) {}
  inline uint64_t next() {
    state = state * 6364136223846793005ull + 1442695040888963407ull;
    uint64_t x = state;
    x ^= x >> 33;
    x *= 0xff51afd7ed558ccdull;
    x ^= x >> 33;
    return x;
  }
  // Unbiased-enough bounded draw (Lemire).
  inline uint64_t bounded(uint64_t n) {
    if (n == 0) return 0;
    __uint128_t m = (__uint128_t)next() * n;
    return (uint64_t)(m >> 64);
  }
};

}  // namespace glt
