// Host-side CPU twins of the sampling ops, for producer processes.
//
// Counterparts of the reference's CPU kernels
// (`csrc/cpu/random_sampler.cc:76-113`,
// `csrc/cpu/random_negative_sampler.cc`, `csrc/cpu/subgraph_op.cc`,
// `graph.cc`) — but emitting the *dense* `[B, k]` + validity-mask
// layout of the device (XLA) ops rather than the reference's ragged
// `(nbrs, nbrs_num)`, so host-produced and device-produced batches are
// interchangeable pytrees.  Parallelized with OpenMP (the reference
// uses at::parallel_for).
#include <omp.h>

#include <algorithm>
#include <cstdint>
#include <vector>

#include "common.h"

using glt::kInvalidId;
using glt::Rng;
using glt::splitmix64;

extern "C" {

// ---------------------------------------------------------------------------
// coo_to_csr: counting sort on row ids.  Returns edge permutation so
// callers can carry edge ids / features (`utils/topo.py` twin).
// ---------------------------------------------------------------------------
void glt_coo_to_csr(const int64_t* rows, const int64_t* cols, int64_t num_edges,
                    int64_t num_nodes, int64_t* indptr /*[n+1]*/,
                    int64_t* indices /*[e]*/, int64_t* perm /*[e]*/) {
  for (int64_t i = 0; i <= num_nodes; ++i) indptr[i] = 0;
  for (int64_t e = 0; e < num_edges; ++e) indptr[rows[e] + 1]++;
  for (int64_t i = 0; i < num_nodes; ++i) indptr[i + 1] += indptr[i];
  // Stable fill using a moving cursor per row.
  std::vector<int64_t> cursor(indptr, indptr + num_nodes);
  for (int64_t e = 0; e < num_edges; ++e) {
    int64_t pos = cursor[rows[e]]++;
    indices[pos] = cols[e];
    perm[pos] = e;
  }
}

// ---------------------------------------------------------------------------
// Uniform neighbor sampling, dense layout.
//
// Per row: deg <= k -> copy all; deg > k -> k distinct picks via
// Floyd's algorithm (O(k) memory, exact without-replacement), the
// sequential-host answer to the reference's GPU reservoir kernel
// (`random_sampler.cu:58-108`).  Seeds may be kInvalidId (padded rows)
// -> fully masked output.
// ---------------------------------------------------------------------------
void glt_sample_one_hop(const int64_t* indptr, const int64_t* indices,
                        const int64_t* edge_ids /*nullable*/,
                        const int64_t* seeds, int64_t batch,
                        int64_t num_nodes, int64_t k, uint64_t seed,
                        int64_t* out_nbrs /*[B,k]*/,
                        uint8_t* out_mask /*[B,k]*/,
                        int64_t* out_eids /*nullable [B,k]*/) {
#pragma omp parallel for schedule(dynamic, 64)
  for (int64_t b = 0; b < batch; ++b) {
    int64_t* nb = out_nbrs + b * k;
    uint8_t* mk = out_mask + b * k;
    int64_t* ei = out_eids ? out_eids + b * k : nullptr;
    int64_t v = seeds[b];
    // out-of-range ids degrade to empty rows, like the reference's
    // empty-sample fallback (`sampler/neighbor_sampler.py:118-136`)
    if (v < 0 || v >= num_nodes) {
      for (int64_t j = 0; j < k; ++j) {
        nb[j] = kInvalidId;
        mk[j] = 0;
        if (ei) ei[j] = kInvalidId;
      }
      continue;
    }
    int64_t lo = indptr[v], hi = indptr[v + 1];
    int64_t deg = hi - lo;
    if (deg <= k) {
      for (int64_t j = 0; j < deg; ++j) {
        nb[j] = indices[lo + j];
        mk[j] = 1;
        if (ei) ei[j] = edge_ids ? edge_ids[lo + j] : lo + j;
      }
      for (int64_t j = deg; j < k; ++j) {
        nb[j] = kInvalidId;
        mk[j] = 0;
        if (ei) ei[j] = kInvalidId;
      }
      continue;
    }
    // Floyd's sampling of k distinct offsets in [0, deg).
    Rng rng(splitmix64(seed) ^ splitmix64((uint64_t)v * 0x9e3779b9ull + b));
    int64_t picks[256];  // k is a fanout, always small (<=256 enforced
                         // by the Python wrapper).
    int64_t np = 0;
    for (int64_t j = deg - k; j < deg; ++j) {
      int64_t t = (int64_t)rng.bounded((uint64_t)(j + 1));
      bool seen = false;
      for (int64_t s = 0; s < np; ++s)
        if (picks[s] == t) { seen = true; break; }
      picks[np++] = seen ? j : t;
    }
    for (int64_t j = 0; j < k; ++j) {
      int64_t off = lo + picks[j];
      nb[j] = indices[off];
      mk[j] = 1;
      if (ei) ei[j] = edge_ids ? edge_ids[off] : off;
    }
  }
}

// ---------------------------------------------------------------------------
// Weighted per-node sampling probability propagation for the frequency
// partitioner (`random_sampler.cu:166-208` CalNbrProbKernel analog):
// prob_out[nbr] += min(1, k/deg(v)) * prob_in[v] accumulated over edges.
// ---------------------------------------------------------------------------
void glt_cal_nbr_prob(const int64_t* indptr, const int64_t* indices,
                      const float* prob_in, int64_t num_nodes, int64_t k,
                      float* prob_out) {
  for (int64_t v = 0; v < num_nodes; ++v) {
    int64_t lo = indptr[v], hi = indptr[v + 1];
    int64_t deg = hi - lo;
    if (deg == 0 || prob_in[v] == 0.f) continue;
    float w = prob_in[v] * std::min(1.0f, (float)k / (float)deg);
    for (int64_t e = lo; e < hi; ++e) prob_out[indices[e]] += w;
  }
}

// ---------------------------------------------------------------------------
// Random negative sampling with strict CSR rejection
// (`random_negative_sampler.cu:37-120` behavior): draw (r, c) pairs;
// in strict mode reject pairs that exist as edges (binary search in
// the row's column range); retry up to `trials` rounds; if `padding`,
// fill the remainder with non-strict draws.  Returns count written.
// ---------------------------------------------------------------------------
int64_t glt_negative_sample(const int64_t* indptr, const int64_t* indices,
                            int64_t num_nodes, int64_t req_num, int64_t trials,
                            int strict, int padding, uint64_t seed,
                            int64_t* out_rows, int64_t* out_cols) {
  int64_t count = 0;
  Rng rng(seed);
  for (int64_t t = 0; t < trials && count < req_num; ++t) {
    for (int64_t i = count; i < req_num; ++i) {
      int64_t r = (int64_t)rng.bounded((uint64_t)num_nodes);
      int64_t c = (int64_t)rng.bounded((uint64_t)num_nodes);
      if (strict) {
        // Linear membership scan: CSR columns are not required to be
        // sorted within a row (unlike the reference's binary-search
        // `EdgeInCSR`, which assumes sorted columns).
        const int64_t* lo = indices + indptr[r];
        const int64_t* hi = indices + indptr[r + 1];
        if (std::find(lo, hi, c) != hi) continue;
      }
      out_rows[count] = r;
      out_cols[count] = c;
      ++count;
    }
  }
  if (padding) {
    while (count < req_num) {
      out_rows[count] = (int64_t)rng.bounded((uint64_t)num_nodes);
      out_cols[count] = (int64_t)rng.bounded((uint64_t)num_nodes);
      ++count;
    }
  }
  return count;
}

}  // extern "C"
