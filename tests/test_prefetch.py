"""Prefetching iterator: equivalence, overlap, failure propagation.

VERDICT-r1 weak #4: the cold-tier gather + device_put ran inside the
batch critical path.  `prefetch=N` moves the next batch's host work
onto a worker thread; these tests pin the contract — identical batch
streams, real wall-clock overlap, exceptions surfacing at the
consumer, and clean early abandonment.
"""
import time

import numpy as np
import pytest

jax = pytest.importorskip('jax')

from graphlearn_tpu.data import Dataset
from graphlearn_tpu.loader import NeighborLoader, PrefetchIterator

N = 256


def _dataset(split_ratio):
  rng = np.random.default_rng(0)
  rows = np.repeat(np.arange(N), 4)
  cols = rng.integers(0, N, N * 4)
  feats = np.tile(np.arange(N, dtype=np.float32)[:, None], (1, 8))
  return (Dataset()
          .init_graph((rows, cols), layout='COO', num_nodes=N)
          .init_node_features(feats, split_ratio=split_ratio)
          .init_node_labels(np.arange(N) % 4))


@pytest.mark.parametrize('split_ratio', [1.0, 0.5])
def test_prefetch_yields_identical_batches(split_ratio):
  ds = _dataset(split_ratio)
  plain = NeighborLoader(ds, [3, 2], np.arange(N), batch_size=32,
                         shuffle=True, seed=7)
  pre = NeighborLoader(ds, [3, 2], np.arange(N), batch_size=32,
                       shuffle=True, seed=7, prefetch=2)
  got_a = list(plain)
  got_b = list(pre)
  assert len(got_a) == len(got_b) == len(plain)
  for a, b in zip(got_a, got_b):
    np.testing.assert_array_equal(np.asarray(a.batch), np.asarray(b.batch))
    np.testing.assert_array_equal(np.asarray(a.node), np.asarray(b.node))
    np.testing.assert_allclose(np.asarray(a.x), np.asarray(b.x))


def test_prefetch_overlaps_producer_with_consumer():
  """With depth 2, producer (d seconds/item) and consumer (d seconds/
  item) pipeline: total ~= n*d, not n*2d."""
  d = 0.05
  n = 10

  def slow_producer():
    for i in range(n):
      time.sleep(d)
      yield i

  t0 = time.perf_counter()
  got = []
  for item in PrefetchIterator(slow_producer(), depth=2):
    time.sleep(d)            # consumer work
    got.append(item)
  elapsed = time.perf_counter() - t0
  assert got == list(range(n))
  # serial would be >= n*2*d = 1.0s; overlapped ~ n*d + d.  Require
  # >= 60% of the producer time hidden (loose for CI noise).
  assert elapsed < n * 2 * d * 0.8, elapsed


def test_prefetch_propagates_exceptions():
  def boom():
    yield 1
    raise RuntimeError('producer failed')

  it = PrefetchIterator(boom(), depth=2)
  assert next(it) == 1
  with pytest.raises(RuntimeError, match='producer failed'):
    next(it)


def test_abandoned_prefetch_epoch_cannot_steal_next_epoch():
  """Breaking out of a prefetch epoch must not cost the NEXT epoch any
  batches (regression: an orphaned worker shared the seed iterator and
  consumed the new epoch's seeds into its dead queue)."""
  ds = _dataset(1.0)
  loader = NeighborLoader(ds, [3], np.arange(N), batch_size=8,
                          shuffle=True, seed=1, prefetch=2)
  it = iter(loader)
  next(it)                       # abandon mid-epoch
  abandoned_thread = it._thread
  seen = sum(1 for _ in loader)  # fresh epoch
  assert seen == len(loader) == N // 8
  # and the abandoned epoch's worker was closed by the new epoch
  abandoned_thread.join(timeout=10)
  assert not abandoned_thread.is_alive()


def test_prefetch_early_abandonment_stops_worker():
  def endless():
    i = 0
    while True:
      yield i
      i += 1

  it = PrefetchIterator(endless(), depth=2)
  assert next(it) == 0
  thread = it._thread
  it.close()
  thread.join(timeout=5)
  assert not thread.is_alive()


@pytest.mark.slow
def test_mesh_loader_prefetch_matches_sync():
  """prefetch=2 on the mesh loaders yields the SAME batches as the
  synchronous path (same seed stream), overlapped on a worker thread."""
  import jax
  from graphlearn_tpu.parallel import (DistDataset, DistNeighborLoader,
                                       make_mesh)
  n = 64
  rows = np.concatenate([np.arange(n), np.arange(n)])
  cols = np.concatenate([(np.arange(n) + 1) % n, (np.arange(n) + 2) % n])
  feats = np.arange(n, dtype=np.float32)[:, None] * np.ones((1, 3),
                                                            np.float32)
  ds = DistDataset.from_full_graph(4, rows, cols, node_feat=feats,
                                   num_nodes=n, split_ratio=0.5)
  outs = []
  for pf in (0, 2):
    loader = DistNeighborLoader(ds, [2, 2], np.arange(n), batch_size=4,
                                shuffle=True, mesh=make_mesh(4), seed=3,
                                prefetch=pf)
    acc = []
    for _ in range(2):                     # two epochs: worker reuse
      for b in loader:
        acc.append((np.asarray(b.node), np.asarray(b.x)))
    outs.append(acc)
  assert len(outs[0]) == len(outs[1])
  for (n0, x0), (n1, x1) in zip(outs[0], outs[1]):
    np.testing.assert_array_equal(n0, n1)
    np.testing.assert_allclose(x0, x1)
