"""SubGraphLoader tests: induced edges match brute force; mapping
exposes seed positions (mirrors reference `test/python/test_subgraph.py`
intent)."""
import numpy as np

from graphlearn_tpu.data import Dataset
from graphlearn_tpu.loader import SubGraphLoader


def _random_dataset(n=30, e=120, d=4, seed=0):
  rng = np.random.default_rng(seed)
  rows = rng.integers(0, n, e)
  cols = rng.integers(0, n, e)
  feats = np.arange(n, dtype=np.float32)[:, None] * np.ones((1, d),
                                                            np.float32)
  ds = (Dataset()
        .init_graph((rows, cols), layout='COO', num_nodes=n)
        .init_node_features(feats, split_ratio=1.0))
  return ds, rows, cols


def test_induced_subgraph_matches_bruteforce():
  ds, rows, cols = _random_dataset()
  loader = SubGraphLoader(ds, [3], np.arange(30), batch_size=6, seed=0)
  for batch in loader:
    nodes = np.asarray(batch.node)
    nmask = np.asarray(batch.node_mask)
    kept = set(nodes[nmask].tolist())
    ei = np.asarray(batch.edge_index)
    em = np.asarray(batch.edge_mask)
    got = set()
    for i in np.nonzero(em)[0]:
      u, v = nodes[ei[0, i]], nodes[ei[1, i]]
      got.add((int(u), int(v)))
    # Brute force: all graph edges with both endpoints in the node set.
    expect = set()
    for u, v in zip(rows.tolist(), cols.tolist()):
      if u in kept and v in kept:
        expect.add((u, v))
    assert got == expect


def test_mapping_locates_seeds():
  ds, _, _ = _random_dataset()
  loader = SubGraphLoader(ds, [2], np.arange(12), batch_size=4,
                          shuffle=False, seed=0)
  for bi, batch in enumerate(loader):
    mapping = np.asarray(batch.metadata['mapping'])
    nodes = np.asarray(batch.node)
    seeds = np.asarray(batch.batch)
    valid = seeds >= 0
    np.testing.assert_array_equal(nodes[mapping[valid]], seeds[valid])
