"""Live ops plane (ISSUE 12): scrapeable metrics registry + HTTP
endpoint, SLO burn tracking, post-mortem bundles, and the
off-by-default byte-identity contract.

Pins, per the issue's test satellite:
  * concurrent scrape-under-load returns a CONSISTENT snapshot (no
    torn histogram buckets: ``count == sum(buckets)`` always);
  * ``/healthz`` flips on an injected worker death;
  * a post-mortem bundle is produced on an injected `MeshStallError`
    (the existing ``fused.dispatch`` chaos site) and on a chaos
    ``producer.worker`` kill, and ``report --postmortem`` renders it;
  * ``GLT_OPS_PORT=0`` (the default) is byte-identical to having no
    ops plane at all;
  * a stalled or dropped ``ops.scrape`` never blocks the serving
    executor.
"""
import json
import os
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from graphlearn_tpu.data import Dataset
from graphlearn_tpu.serving import ServingEngine, ServingFrontend
from graphlearn_tpu.telemetry import (LiveRegistry, Metrics, OpsServer,
                                      SloTracker, live, recorder)
from graphlearn_tpu.telemetry import opsserver, postmortem
from graphlearn_tpu.telemetry.histogram import from_snapshot
from graphlearn_tpu.telemetry.live import parse_prometheus_text
from graphlearn_tpu.telemetry.recorder import EventRecorder
from graphlearn_tpu.testing import chaos

N, D = 64, 6
FANOUTS = [3, 2]
BUCKETS = (1, 2, 4)


@pytest.fixture(autouse=True)
def _clean():
  chaos.uninstall()
  postmortem.reset()
  recorder.enable(None)
  recorder.clear()
  yield
  chaos.uninstall()
  postmortem.reset()
  opsserver.stop_global()
  live.unregister_health('server')
  live.unregister_health('producer')
  recorder.clear()
  recorder.disable()


def _dataset():
  rng = np.random.default_rng(0)
  rows = np.repeat(np.arange(N), 4)
  cols = rng.integers(0, N, rows.shape[0])
  feats = (np.arange(N, dtype=np.float32)[:, None]
           * np.ones((1, D), np.float32))
  return (Dataset().init_graph((rows, cols), layout='COO', num_nodes=N)
          .init_node_features(feats))


@pytest.fixture(scope='module')
def engine():
  eng = ServingEngine(_dataset(), FANOUTS, seed=7, buckets=BUCKETS)
  eng.warmup()
  return eng


def _get(url, timeout=10):
  with urllib.request.urlopen(url, timeout=timeout) as r:
    return r.status, r.read().decode()


# -- registry ---------------------------------------------------------------
def test_registry_strict_declared_names():
  reg = LiveRegistry(store=Metrics(), strict=True)
  with pytest.raises(ValueError, match='not declared'):
    reg.counter('rogue.metric_total')
  with pytest.raises(ValueError, match='snake.dot'):
    reg.counter('NotSnake')
  # declared under the wrong kind is refused too
  with pytest.raises(ValueError, match="declared as 'counter'"):
    reg.gauge('serving.requests_total')


def test_counter_gauge_histogram_snapshot_and_prometheus():
  reg = LiveRegistry(store=Metrics(), strict=True)
  reg.counter('serving.requests_total').inc(3)
  reg.gauge('serving.queue_depth', fn=lambda: 5)
  h = reg.histogram('serving.request_latency', labels={'bucket': 4})
  h.observe(0.004)
  h.observe(0.004)
  snap = reg.snapshot()
  assert snap['serving.requests_total'] == 3
  assert snap['serving.queue_depth'] == 5
  parsed = parse_prometheus_text(reg.prometheus_text())
  assert parsed['glt_serving_requests_total'] == 3
  assert parsed['glt_serving_queue_depth'] == 5
  assert parsed['glt_serving_request_latency_count{bucket="4"}'] == 2
  # +Inf cumulative bucket equals count (well-formed histogram)
  assert parsed[
      'glt_serving_request_latency_bucket{bucket="4",le="+Inf"}'] == 2
  # a raising gauge drops its sample, never the scrape
  reg.gauge('serving.in_flight', fn=lambda: 1 / 0)
  parsed = parse_prometheus_text(reg.prometheus_text())
  assert 'glt_serving_in_flight' not in parsed


def test_parse_prometheus_text_rejects_malformed():
  with pytest.raises(ValueError, match='malformed sample'):
    parse_prometheus_text('ok_metric 1\nbroken{ 2\n')
  with pytest.raises(ValueError, match='malformed comment'):
    parse_prometheus_text('# not a help line\n')


def test_concurrent_scrape_no_torn_histograms():
  """Scrape-under-load consistency: every snapshot taken while
  writer threads hammer one histogram must satisfy
  ``count == sum(buckets)`` — the inc_many single-lock contract."""
  reg = LiveRegistry(store=Metrics(), strict=True)
  h = reg.histogram('serving.request_latency')
  stop = threading.Event()

  def writer(seed):
    rng = np.random.default_rng(seed)
    while not stop.is_set():
      h.observe(float(rng.random()) * 1e-3)

  threads = [threading.Thread(target=writer, args=(i,), daemon=True)
             for i in range(4)]
  for t in threads:
    t.start()
  try:
    checked = 0
    deadline = time.monotonic() + 30.0
    while checked < 50 and time.monotonic() < deadline:
      hists = from_snapshot(reg._backing().snapshot())
      for hist in hists.values():
        assert sum(hist.buckets) == hist.count, \
            'torn histogram: bucket sum diverged from count'
        checked += 1
      parse_prometheus_text(reg.prometheus_text())  # always valid
  finally:
    stop.set()
    for t in threads:
      t.join(5)
  assert checked >= 50, 'writers never produced observable load'
  final = from_snapshot(reg._backing().snapshot())
  assert final['serving.request_latency'].count > 0


# -- ops endpoint -----------------------------------------------------------
def test_ops_endpoints_serve_metrics_varz_healthz():
  reg = LiveRegistry(store=Metrics(), strict=True)
  reg.counter('serving.requests_total').inc(7)
  srv = OpsServer(registry=reg, port=0)
  try:
    status, txt = _get(f'{srv.url}/metrics')
    assert status == 200
    assert parse_prometheus_text(txt)['glt_serving_requests_total'] == 7
    status, body = _get(f'{srv.url}/varz')
    varz = json.loads(body)
    assert varz['metrics']['serving.requests_total'] == 7
    assert 'ring_capacity' in varz['recorder']
    status, body = _get(f'{srv.url}/healthz')
    assert status == 200 and json.loads(body)['ok'] is True
    with pytest.raises(urllib.error.HTTPError) as ei:
      _get(f'{srv.url}/nope')
    assert ei.value.code == 404
    # the scrape counter itself ticked (the 404 too — it hit the
    # handler past the chaos seam)
    assert reg.snapshot()['ops.scrapes_total'] >= 4
  finally:
    srv.close()


def test_healthz_flips_unhealthy_component():
  reg = LiveRegistry(store=Metrics(), strict=True)
  state = {'healthy': True}
  reg.register_health('producer', lambda: dict(state))
  srv = OpsServer(registry=reg, port=0)
  try:
    status, _ = _get(f'{srv.url}/healthz')
    assert status == 200
    state['healthy'] = False
    with pytest.raises(urllib.error.HTTPError) as ei:
      _get(f'{srv.url}/healthz')
    assert ei.value.code == 503
    assert json.loads(ei.value.read())['ok'] is False
  finally:
    srv.close()


def test_ops_port_zero_is_disabled(monkeypatch):
  monkeypatch.setenv(opsserver.OPS_PORT_ENV, '0')
  assert opsserver.maybe_start_from_env() is None
  monkeypatch.delenv(opsserver.OPS_PORT_ENV)
  assert opsserver.maybe_start_from_env() is None
  assert opsserver.global_server() is None


def test_ops_plane_byte_identical_to_disabled(monkeypatch, engine):
  """GLT_OPS_PORT=0 (default): serving output with NO ops plane is
  byte-identical to serving under a live, actively-scraped one."""
  seeds = np.asarray([5, 9, 17], np.int64)
  monkeypatch.setenv(opsserver.OPS_PORT_ENV, '0')
  fe = ServingFrontend(engine, auto_start=False, warmup=False)
  fut = fe.submit(seeds)
  fe.pump_once(block=False)
  base = fut.result(10)
  fe.shutdown()
  srv = OpsServer(port=0)             # live plane + concurrent scrape
  try:
    fe2 = ServingFrontend(engine, auto_start=False, warmup=False)
    fut2 = fe2.submit(seeds)
    _get(f'{srv.url}/metrics')
    fe2.pump_once(block=False)
    _get(f'{srv.url}/varz')
    out = fut2.result(10)
    fe2.shutdown()
  finally:
    srv.close()
  assert np.asarray(base.nodes).tobytes() == \
      np.asarray(out.nodes).tobytes()
  assert np.asarray(base.x).tobytes() == np.asarray(out.x).tobytes()


def test_cache_counters_render_labeled_on_metrics():
  """emit_cache_events registers LABELED per-scope instances — the
  /metrics rendering must carry the real counts, not a permanently
  zero unlabeled twin (review finding on r13)."""
  from graphlearn_tpu.data.cold_cache import emit_cache_events
  from graphlearn_tpu.utils.profiling import metrics
  before = metrics.snapshot().get('cache.hits_total{scope=testscope}',
                                  0.0)
  emit_cache_events('testscope', hits=3, misses=2, admits=1, evicts=0)
  parsed = parse_prometheus_text(live.prometheus_text())
  assert parsed['glt_cache_hits_total{scope="testscope"}'] \
      == before + 3
  assert parsed['glt_cache_misses_total{scope="testscope"}'] >= 2
  assert 'glt_cache_hits_total' not in parsed  # no zero twin


def test_frontend_shutdown_unregisters_gauges(engine):
  fe = ServingFrontend(engine, auto_start=False, warmup=False)
  reg_keys = {k for k in live._instances}
  assert ('gauge', 'serving.queue_depth') in reg_keys
  # a SECOND frontend takes the gauges over; the FIRST one's
  # shutdown must not evict the replacement (fn-identity guard)
  fe2 = ServingFrontend(engine, auto_start=False, warmup=False)
  fe.shutdown()
  assert ('gauge', 'serving.queue_depth') in live._instances
  assert ('gauge', 'serving.slo.p50_ms') in live._instances
  # the /healthz provider survives the STALE frontend's shutdown too
  assert 'serving' in live.healthz()['components']
  fe2.shutdown()
  assert 'serving' not in live.healthz()['components']
  assert ('gauge', 'serving.queue_depth') not in live._instances
  assert ('gauge', 'serving.slo.p50_ms') not in live._instances
  assert ('gauge', 'serving.slo.burn_rate{window=60s}') \
      not in live._instances


def test_rpc_and_snapshot_gauges_unregister(tmp_path):
  from graphlearn_tpu.distributed.rpc import RpcServer
  from graphlearn_tpu.utils.checkpoint import SnapshotManager
  srv = RpcServer('127.0.0.1', 0)
  srv.start()                        # shutdown() joins serve_forever
  assert ('gauge', 'rpc.replay_cache_entries') in live._instances
  srv.shutdown()
  assert ('gauge', 'rpc.replay_cache_entries') not in live._instances
  mgr = SnapshotManager(directory=str(tmp_path / 'snaps'))
  assert ('gauge', 'snapshot.save_age_seconds') in live._instances
  mgr.close()
  assert ('gauge', 'snapshot.save_age_seconds') not in live._instances
  assert ('gauge', 'snapshot.restore_age_seconds') \
      not in live._instances


# -- chaos: ops.scrape ------------------------------------------------------
def test_stalled_scrape_never_blocks_executor(engine):
  chaos.install('ops.scrape:delay:1:secs=0.8:op=/metrics')
  srv = OpsServer(port=0)             # global registry: serving wired
  fe = ServingFrontend(engine, auto_start=False, warmup=False)
  done = {}

  def scrape():
    t0 = time.monotonic()
    done['status'], done['body'] = _get(f'{srv.url}/metrics')
    done['secs'] = time.monotonic() - t0

  t = threading.Thread(target=scrape, daemon=True)
  try:
    t.start()
    time.sleep(0.1)                  # scrape is now inside the delay
    fut = fe.submit(np.asarray([3]))
    t0 = time.monotonic()
    assert fe.pump_once(block=False) == 1
    fut.result(5)
    pumped = time.monotonic() - t0
    assert pumped < 0.5, \
        f'executor stalled {pumped:.2f}s behind a chaos-delayed scrape'
    t.join(10)
    assert done['status'] == 200 and done['secs'] >= 0.8
    parse_prometheus_text(done['body'])
  finally:
    fe.shutdown()
    srv.close()


def test_dropped_scrape_is_503_and_isolated(engine):
  chaos.install('ops.scrape:drop:1')
  srv = OpsServer(port=0)
  fe = ServingFrontend(engine, auto_start=False, warmup=False)
  try:
    with pytest.raises(urllib.error.HTTPError) as ei:
      _get(f'{srv.url}/metrics')
    assert ei.value.code == 503
    fut = fe.submit(np.asarray([3]))
    assert fe.pump_once(block=False) == 1
    fut.result(5)
    # the fault fired once; the next scrape is healthy
    status, _ = _get(f'{srv.url}/metrics')
    assert status == 200
  finally:
    fe.shutdown()
    srv.close()


# -- SLO tracker ------------------------------------------------------------
def test_slo_burn_trips_once_and_rearms():
  clock = {'t': 1000.0}
  reg = LiveRegistry(store=Metrics(), strict=True)
  tr = SloTracker(p99_target_ms=10.0, qps_target=50.0,
                  windows=(10.0, 40.0), registry=reg,
                  clock=lambda: clock['t'])
  for _ in range(20):                # all violating: burn = 100x
    clock['t'] += 0.3
    tr.observe(50.0, ok=True)
  burns = recorder.events('slo.burn')
  assert len(burns) == 2, burns      # one per window, once each
  assert {e['window_secs'] for e in burns} == {10.0, 40.0}
  assert burns[0]['burn_rate'] > 1.0
  st = tr.window_stats(10.0)
  assert st['violations'] == st['count'] > 0
  parsed = parse_prometheus_text(reg.prometheus_text())
  assert parsed['glt_serving_slo_burn_rate{window="10s"}'] > 1.0
  assert parsed['glt_serving_slo_p99_ms'] == 50.0
  assert 'glt_serving_slo_qps_ratio' in parsed
  # recovery: fast traffic ages the violations out -> re-armed ->
  # a NEW burn logs again (one event per incident, not per request)
  for _ in range(300):
    clock['t'] += 0.3
    tr.observe(1.0, ok=True)
  assert tr.window_stats(10.0)['burn_rate'] == 0.0
  recorder.clear()
  for _ in range(20):
    clock['t'] += 0.3
    tr.observe(50.0, ok=True)
  assert recorder.events('slo.burn'), 'burn did not re-arm'


def test_slo_failed_requests_count_against_budget():
  clock = {'t': 0.0}
  reg = LiveRegistry(store=Metrics(), strict=True)
  tr = SloTracker(p99_target_ms=1000.0, windows=(10.0, 20.0),
                  registry=reg, clock=lambda: clock['t'])
  for _ in range(10):
    clock['t'] += 0.3
    tr.observe(1.0, ok=False)        # fast but FAILED
  assert tr.window_stats(10.0)['violations'] == 10


# -- recorder ring drops ----------------------------------------------------
def test_ring_drop_count_and_one_shot_overflow_event():
  rec = EventRecorder(max_events=4)
  rec.enable()
  for i in range(4):
    rec.emit('adhoc.fill', i=i)
  assert rec.dropped_total == 0
  rec.emit('adhoc.overflowing')      # drops one + the one-shot event
  assert rec.dropped_total == 2      # the overflow event evicts too
  kinds = [e['kind'] for e in rec.events()]
  assert kinds.count('recorder.overflow') == 1
  for i in range(10):
    rec.emit('adhoc.more', i=i)
  kinds = [e['kind'] for e in rec.events()]
  assert kinds.count('recorder.overflow') == 0  # aged out, not re-emitted
  assert rec.dropped_total == 12
  assert rec.stats()['ring_dropped'] == 12
  # the global registry exports the GLOBAL recorder's drop count
  assert live.gauge('recorder.ring_dropped').value() == \
      recorder.stats()['ring_dropped']


# -- post-mortem ------------------------------------------------------------
def _bundles(d):
  return sorted(p for p in os.listdir(d) if p.startswith('postmortem-'))


def test_postmortem_on_injected_mesh_stall_and_report(
    monkeypatch, tmp_path, capsys):
  """THE acceptance pin: an injected MeshStallError (existing
  fused.dispatch chaos site) produces a bundle report --postmortem
  renders."""
  from graphlearn_tpu.distributed.resilience import (MeshStallError,
                                                     run_with_deadline)
  from graphlearn_tpu.telemetry.report import main as report_main
  from graphlearn_tpu.telemetry.spans import span
  pmdir = tmp_path / 'pm'
  monkeypatch.setenv(postmortem.POSTMORTEM_DIR_ENV, str(pmdir))
  chaos.install('fused.dispatch:delay:1:secs=1.0')

  def dispatch():
    with span('fused.dispatch', chunk=0):
      chaos.fused_dispatch_check(chunk=0, epoch=0)

  with pytest.raises(MeshStallError):
    run_with_deadline(dispatch, deadline=0.2, scope='fused.dispatch')
  files = _bundles(pmdir)
  assert len(files) == 1, files
  bundle = postmortem.load_bundle(str(pmdir / files[0]))
  assert bundle['reason'] == 'mesh.stall'
  assert bundle['error']['type'] == 'MeshStallError'
  kinds = {e['kind'] for e in bundle['events']}
  assert {'fault.injected', 'mesh.stall'} <= kinds
  assert bundle['metrics'], 'metrics snapshot missing from bundle'
  # a second stall in the same process is one-shot: no second bundle
  chaos.install('fused.dispatch:delay:1:secs=1.0')
  with pytest.raises(MeshStallError):
    run_with_deadline(dispatch, deadline=0.2, scope='fused.dispatch')
  assert len(_bundles(pmdir)) == 1
  assert report_main(['--postmortem', str(pmdir / files[0])]) == 0
  out = capsys.readouterr().out
  assert 'mesh.stall' in out
  assert 'spans in flight' in out
  assert 'fused.dispatch' in out
  assert 'final 60s window' in out


def test_postmortem_on_chaos_producer_worker_kill(monkeypatch,
                                                  tmp_path):
  """A chaos producer.worker kill with the restart budget exhausted
  is an irrecoverable pool -> peer.lost bundle; /healthz flips."""
  from graphlearn_tpu.distributed import (DistNeighborLoader,
                                          HostDataset,
                                          MpDistSamplingWorkerOptions,
                                          PeerLostError)
  pmdir = tmp_path / 'pm'
  monkeypatch.setenv(postmortem.POSTMORTEM_DIR_ENV, str(pmdir))
  monkeypatch.setenv('GLT_FAULT_PLAN',
                     'producer.worker:kill:1:worker=0:epoch=0')
  monkeypatch.setenv('GLT_MAX_WORKER_RESTARTS', '0')
  n = 24
  rng = np.random.default_rng(0)
  rows = np.arange(n).repeat(2)
  cols = (rows + rng.integers(1, n, rows.shape[0])) % n
  ds = HostDataset.from_coo(
      rows, cols, n,
      node_features=rng.random((n, 4), np.float32).astype(np.float32))
  loader = DistNeighborLoader(
      ds, [2], np.arange(n), batch_size=4, shuffle=False,
      worker_options=MpDistSamplingWorkerOptions(
          num_workers=2, mp_start_method='spawn'),
      to_device=False, seed=3)
  live.register_health('producer', loader._producer.health)
  assert live.healthz()['ok'] is True
  with pytest.raises(PeerLostError):
    for _ in loader:
      pass
  health = live.healthz()
  assert health['ok'] is False, \
      '/healthz must flip on an irrecoverable worker death'
  comp = health['components']['producer']
  assert comp['alive_workers'] < comp['num_workers']
  assert comp['lost_workers'] == [0]
  files = _bundles(pmdir)
  assert len(files) == 1, files
  bundle = postmortem.load_bundle(str(pmdir / files[0]))
  assert bundle['reason'] == 'peer.lost'
  kinds = {e['kind'] for e in bundle['events']}
  assert 'peer.lost' in kinds
  loader.shutdown()


def test_postmortem_disabled_without_dir(monkeypatch):
  monkeypatch.delenv(postmortem.POSTMORTEM_DIR_ENV, raising=False)
  assert postmortem.dump('mesh.stall') is None


def test_serving_executor_fault_dumps_bundle(monkeypatch, tmp_path,
                                             engine):
  pmdir = tmp_path / 'pm'
  monkeypatch.setenv(postmortem.POSTMORTEM_DIR_ENV, str(pmdir))
  chaos.install('serving.request:drop:1:op=dispatch')
  fe = ServingFrontend(engine, auto_start=False, warmup=False)
  fut = fe.submit(np.asarray([3]))
  fe.pump_once(block=False)
  with pytest.raises(chaos.InjectedFault):
    fut.result(5)
  fe.shutdown()
  files = _bundles(pmdir)
  assert len(files) == 1, files
  assert postmortem.load_bundle(
      str(pmdir / files[0]))['reason'] == 'serving.executor_fault'
