"""Closed-loop elastic autoscaling (ISSUE 19): the `ElasticController`
decision machine under an injected clock.

The contract stack: scale-out on a burn spike admits only a verified
warm replica; the per-direction cooldowns suppress re-fires and a
rolled-back decision does NOT spend them (re-arm is the point of a
typed rollback); scale-in drains the coldest replica and retires it
only after quiesce — a quiesce timeout un-drains and keeps it;
min/max bounds are hard stops; the hysteresis band between in_burn
and out_burn decides nothing.  Plus the `SloTracker` idle contract
the controller's first post-scale-out evaluation depends on (empty /
idle / zero-budget windows read burn 0.0, never NaN or stale), and
the open-loop client side of draining: `pace_schedule` resubmits
``retry_after_ms``-hinted drain sheds instead of counting them.
"""
import os
import sys

import pytest

from graphlearn_tpu.serving.autoscaler import (ElasticController,
                                               ScaleAbortedError)
from graphlearn_tpu.telemetry.live import LiveRegistry
from graphlearn_tpu.telemetry.slo import SloTracker
from graphlearn_tpu.testing import chaos

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), 'benchmarks'))


# -- scripted fleet ---------------------------------------------------------

def _hb(short_burn=0.0, long_burn=0.0, qps=1.0, depth=0, max_q=64,
        state='healthy'):
  return {'state': state, 'serving': {
      'queue_depth': depth, 'max_queue': max_q,
      'slo': {'windows': [
          {'window_secs': 1.0, 'burn_rate': short_burn, 'qps': qps},
          {'window_secs': 3.0, 'burn_rate': long_burn, 'qps': qps}]}}}


class FakeAdmission:
  def __init__(self):
    self.draining = False

  def set_draining(self, flag):
    self.draining = bool(flag)


class FakeEngine:
  def __init__(self, compiles=0):
    self._compiles = compiles

  def compile_count(self):
    return self._compiles


class FakeFrontend:
  def __init__(self, compiles=0, quiesces=True):
    self.engine = FakeEngine(compiles)
    self.admission = FakeAdmission()
    self._quiesces = quiesces

  def quiesced(self):
    return self._quiesces and self.admission.draining


class FakeReplica:
  def __init__(self, name, compiles=0, quiesces=True):
    self.name = name
    self.frontend = FakeFrontend(compiles, quiesces)
    self.closed = False

  def heartbeat(self):
    return {'serving': {'closed': False, 'draining': False}}

  def close(self):
    self.closed = True


class FakeRouter:
  def __init__(self, hb):
    self.hb = dict(hb)
    self.replicas = {}
    self.removed = []

  def heartbeats(self):
    return {k: dict(v) for k, v in self.hb.items()}

  def add_replica(self, handle):
    self.replicas[handle.name] = handle

  def remove_replica(self, name):
    self.removed.append(name)
    return self.replicas.pop(name, None)

  def get_replica(self, name):
    return self.replicas.get(name)


def _controller(router, spawn, **kw):
  kw.setdefault('min_replicas', 1)
  kw.setdefault('max_replicas', 3)
  kw.setdefault('cooldown_s', (3.0, 15.0))
  kw.setdefault('out_burn', 1.0)
  kw.setdefault('in_burn', 0.1)
  kw.setdefault('auto_start', False)
  return ElasticController(router, spawn, **kw)


# -- scale-out --------------------------------------------------------------

def test_scale_out_on_burn_spike_admits_warm_replica():
  router = FakeRouter({'r0': _hb(short_burn=2.0)})
  spawned = []

  def spawn():
    h = FakeReplica(f'spawn-{len(spawned)}')
    spawned.append(h)
    return h

  ctl = _controller(router, spawn)
  rec = ctl.evaluate(now=10.0)
  assert rec['dir'] == 'out' and rec['outcome'] == 'ok'
  assert rec['replica'] == 'spawn-0' and rec['short_burn'] == 2.0
  assert 'spawn-0' in router.replicas and not spawned[0].closed


def test_queue_is_a_leading_indicator():
  # no burn at all, but the queue near its bound scales out anyway
  router = FakeRouter({'r0': _hb(depth=60, max_q=64)})
  ctl = _controller(router, lambda: FakeReplica('s'), queue_ratio=0.7)
  rec = ctl.evaluate(now=0.0)
  assert rec['dir'] == 'out' and rec['outcome'] == 'ok'


def test_cooldown_suppresses_then_rearms():
  router = FakeRouter({'r0': _hb(short_burn=2.0)})
  ctl = _controller(router, lambda: FakeReplica('s0'))
  assert ctl.evaluate(now=10.0)['outcome'] == 'ok'
  held = ctl.evaluate(now=10.5)
  assert held['dir'] == 'out' and held['outcome'] == 'held:cooldown'
  # past the out-cooldown the same signal fires again
  router.replicas.clear()
  assert ctl.evaluate(now=13.5)['outcome'] == 'ok'


def test_bounds_are_hard_stops():
  router = FakeRouter({'r0': _hb(short_burn=2.0)})
  ctl = _controller(router, lambda: FakeReplica('s'), max_replicas=1)
  assert ctl.evaluate(now=0.0)['outcome'] == 'held:bounds'
  router = FakeRouter({'r0': _hb()})
  ctl = _controller(router, lambda: FakeReplica('s'), min_replicas=1)
  rec = ctl.evaluate(now=0.0)
  assert rec['dir'] == 'in' and rec['outcome'] == 'held:bounds'


def test_hysteresis_band_decides_nothing():
  # burn between in_burn and out_burn: steady state, no record at all
  router = FakeRouter({'r0': _hb(short_burn=0.5)})
  ctl = _controller(router, lambda: FakeReplica('s'))
  assert ctl.evaluate(now=0.0) is None
  assert ctl.decisions() == []


def test_spawn_chaos_fault_rolls_back_and_rearms():
  """The mid-flight fault contract: a chaos scale.spawn failure rolls
  back typed (fleet unchanged, postmortem dumped) and does NOT spend
  the out-cooldown — the very next evaluation retries."""
  router = FakeRouter({'r0': _hb(short_burn=2.0)})
  ctl = _controller(router, lambda: FakeReplica('s0'))
  chaos.install('scale.spawn:fail:1')
  try:
    rec = ctl.evaluate(now=10.0)
  finally:
    chaos.uninstall()
  assert rec['outcome'] == 'rolled_back'
  assert 'InjectedFault' in rec['error']
  assert router.replicas == {}              # fleet unchanged
  # cooldown NOT spent: an immediate retry succeeds
  rec2 = ctl.evaluate(now=10.1)
  assert rec2['outcome'] == 'ok' and 's0' in router.replicas


def test_cold_replica_refused_at_admission():
  # the warm pin: compile_count()>0 after warmup means the shared AOT
  # cache did not cover every bucket — the replica is closed, never
  # admitted, and the rollback re-arms
  router = FakeRouter({'r0': _hb(short_burn=2.0)})
  cold = FakeReplica('cold', compiles=2)
  ctl = _controller(router, lambda: cold)
  rec = ctl.evaluate(now=0.0)
  assert rec['outcome'] == 'rolled_back'
  assert 'warm-restore pin' in rec['error']
  assert cold.closed and router.replicas == {}


# -- scale-in ---------------------------------------------------------------

def test_scale_in_drains_coldest_then_retires():
  router = FakeRouter({'hot': _hb(qps=5.0), 'cold': _hb(qps=1.0)})
  victim = FakeReplica('cold')
  router.replicas = {'hot': FakeReplica('hot'), 'cold': victim}
  ctl = _controller(router, lambda: None)
  rec = ctl.evaluate(now=100.0)
  assert rec['dir'] == 'in' and rec['outcome'] == 'ok'
  assert rec['replica'] == 'cold'           # lowest short-window qps
  assert router.removed == ['cold'] and victim.closed
  assert victim.frontend.admission.draining  # drained before retire
  # the in-cooldown holds the next retirement (the heartbeat feed
  # still reads two entries — the fleet is above min bounds)
  assert ctl.evaluate(now=101.0)['outcome'] == 'held:cooldown'


def test_quiesce_timeout_undrains_and_keeps_victim():
  router = FakeRouter({'hot': _hb(qps=5.0), 'wedged': _hb(qps=1.0)})
  victim = FakeReplica('wedged', quiesces=False)
  router.replicas = {'hot': FakeReplica('hot'), 'wedged': victim}
  ctl = _controller(router, lambda: None, quiesce_timeout_s=0.05)
  rec = ctl.evaluate(now=100.0)
  assert rec['outcome'] == 'rolled_back'
  assert 'quiesce' in rec['error']
  assert not victim.frontend.admission.draining  # back in rotation
  assert not victim.closed and 'wedged' in router.replicas
  # rollback re-arms: the in-cooldown was not spent
  rec2 = ctl.evaluate(now=100.2)
  assert rec2['outcome'] == 'rolled_back'   # still wedged, still typed


def test_dead_and_quarantined_replicas_feed_no_signals():
  router = FakeRouter({'r0': _hb(short_burn=0.0),
                       'gone': _hb(short_burn=9.0, state='dead'),
                       'flap': _hb(short_burn=9.0,
                                   state='quarantined')})
  ctl = _controller(router, lambda: None)
  sig = ctl.signals()
  assert sig['replicas'] == 1 and sig['short_burn'] == 0.0


# -- the SloTracker idle contract -------------------------------------------

def _tracker(now, **kw):
  kw.setdefault('p99_target_ms', 100.0)
  kw.setdefault('qps_target', 0.0)
  kw.setdefault('windows', (1.0, 3.0))
  kw.setdefault('budget', 0.1)
  return SloTracker(registry=LiveRegistry(),
                    clock=lambda: now[0], **kw)


def test_fresh_tracker_reads_burn_zero():
  now = [1000.0]
  t = _tracker(now)
  try:
    for w in t.windows:
      st = t.window_stats(w)
      assert st['count'] == 0 and st['burn_rate'] == 0.0
    assert all(w['burn_rate'] == 0.0
               for w in t.snapshot()['windows'])
  finally:
    t.close()


def test_idle_window_reads_burn_zero_not_stale():
  """Violations that age out of the window leave burn 0.0 — an idle
  replica must not keep reporting the spike it absorbed minutes ago
  (the ElasticController would never scale it in)."""
  now = [1000.0]
  t = _tracker(now)
  try:
    for _ in range(5):
      t.observe(500.0, ok=True)     # 5/5 violating: burn = 10
    assert t.window_stats(1.0)['burn_rate'] == pytest.approx(10.0)
    now[0] += 60.0                  # both windows age to empty
    st = t.window_stats(1.0)
    assert st['count'] == 0 and st['burn_rate'] == 0.0
    assert st['burn_rate'] == st['burn_rate']   # not NaN
  finally:
    t.close()


def test_zero_budget_and_zero_target_read_burn_zero():
  now = [1000.0]
  for kw in ({'budget': 0.0}, {'p99_target_ms': 0.0}):
    t = _tracker(now, **kw)
    try:
      t.observe(500.0, ok=False)
      assert t.window_stats(1.0)['burn_rate'] == 0.0
    finally:
      t.close()


# -- the open-loop client side of draining ----------------------------------

def test_pace_schedule_resubmits_drain_sheds():
  """Satellite 1: a ``reason='draining'`` refusal with a
  ``retry_after_ms`` hint is resubmitted after the hint, not counted
  a shed — every request lands once the drain window passes."""
  import time as _time
  from bench_serving import pace_schedule
  from graphlearn_tpu.serving import AdmissionRejected

  t_open = _time.monotonic() + 0.06

  def submit(seeds):
    if _time.monotonic() < t_open:
      raise AdmissionRejected('draining', reason='draining',
                              retry_after_ms=15.0)
    return ('ok', seeds)

  plan = [(i * 0.005, i) for i in range(5)]
  out, _t0 = pace_schedule(plan, submit)
  assert len(out) == 5
  assert all(isinstance(r, tuple) and r[0] == 'ok' for _, r in out)


def test_pace_schedule_drain_retries_are_bounded():
  from bench_serving import pace_schedule
  from graphlearn_tpu.serving import AdmissionRejected

  def submit(seeds):
    raise AdmissionRejected('draining', reason='draining',
                            retry_after_ms=1.0)

  out, _t0 = pace_schedule([(0.0, 0)], submit, max_retries=2)
  assert [r for _, r in out] == ['shed']
