"""The bench artifact contract, pinned (VERDICT r3 #1).

Round 3 shipped rc=124 with NO perf number because the aggregate JSON
printed only once, at the very end.  The contract since r4: the FULL
cumulative aggregate prints after every completed phase, tolerates
prefix-only (salvaged) session dicts, and the headline `value` is the
fused whole-epoch time when the fused session landed.  These tests
import the harness module directly (no chip, no subprocesses) and pin
the schema a driver's last-JSON-line salvage depends on.
"""
import importlib.util
import json
import sys
from pathlib import Path

import pytest

_BENCH = Path(__file__).resolve().parent.parent / 'bench.py'


@pytest.fixture(scope='module')
def bench():
  spec = importlib.util.spec_from_file_location('bench_under_test',
                                                _BENCH)
  mod = importlib.util.module_from_spec(spec)
  argv = sys.argv
  sys.argv = ['bench.py']
  try:
    spec.loader.exec_module(mod)
  finally:
    sys.argv = argv
  return mod


def _primary(**extra):
  r = {'epoch_secs': 0.25, 'compile_secs': 6.0, 'steps': 200,
       'mode': 'primary', 'platform': 'tpu'}
  r.update(extra)
  return r


FULL = dict(edges_per_sec=1.6e9, sample_hbm_frac=0.11,
            gather_hbm_frac=0.05, gather_gbps=38.0)


def test_aggregate_full_schema(bench):
  fused = {'mode': 'fused-session', 'platform': 'tpu',
           'fused_compile_secs': [70.0, 66.0],
           'epoch_secs_fused': 0.007}
  dist = {'label': 'virtual CPU mesh - relative only',
          'edges_per_sec_per_chip': 2e4}
  out = bench._aggregate([_primary(**FULL)], fused, dist)
  json.dumps(out)                         # must be JSON-serializable
  assert out['metric'].startswith('graphsage_fused_epoch_secs')
  assert out['value'] == 0.007            # fused IS the headline
  assert out['vs_baseline'] == pytest.approx(2.0 / 0.007, rel=1e-3)
  assert out['epoch_secs_min_med_max'][1] == 0.25
  assert out['fused_compile_secs'] == [70.0, 66.0]
  assert out['achieved_hbm_frac'] == {'sample': 0.11, 'gather': 0.05}
  assert out['dist'] is dist


def test_aggregate_prefix_only_sessions(bench):
  """Salvaged sessions carry only the phases that finished: an
  epoch-only line plus a compile-only fused line must still produce
  a parseable aggregate with the per-batch headline."""
  fused_partial = {'mode': 'fused-session', 'platform': 'tpu',
                   'fused_compile_secs': [70.0, 66.0]}
  out = bench._aggregate([_primary()], fused_partial, None)
  json.dumps(out)
  assert out['metric'].startswith('graphsage_epoch_secs')
  assert out['value'] == 0.25
  assert out['fused_epoch_secs'] is None
  assert out['fused_compile_secs'] == [70.0, 66.0]
  assert out['sampled_edges_per_sec_M_min_med_max'] is None
  assert out['achieved_hbm_frac'] is None


def test_aggregate_mixed_sessions_median(bench):
  rs = [_primary(**FULL),
        _primary(epoch_secs=0.35),           # salvaged: epoch only
        _primary(epoch_secs=0.30, **FULL)]
  out = bench._aggregate(rs, None, None)
  assert out['epoch_secs_min_med_max'] == [0.25, 0.3, 0.35]
  # sampling median over the two sessions that reached that phase
  assert out['sampled_edges_per_sec_M_min_med_max'][1] == 1600.0
  assert out['sessions'] == 3


def test_aggregate_dist_only(bench):
  """A day where every chip session dies must still leave a
  parseable line with the dist numbers."""
  dist = {'label': 'virtual CPU mesh - relative only'}
  out = bench._aggregate([], None, dist)
  json.dumps(out)
  assert out['value'] is None
  assert out['dist'] is dist
  assert out['sessions'] == 0


def test_aggregate_floor_filters_elided_runs(bench):
  """r5 protocol: a wall below the session's analytic HBM floor must
  not reappear as the artifact's series min."""
  r = _primary(epoch_runs=[0.007, 8.2, 8.4], epoch_secs=8.3,
               epoch_floor_secs=1.5)
  out = bench._aggregate([r], None, None)
  assert out['epoch_secs_min_med_max'][0] == 8.2
  assert out['protocol'].startswith('r5')


def test_aggregate_elision_suspect_fused_not_headline(bench):
  """A fused number flagged suspect_elision must NOT become the
  headline value."""
  fused = {'mode': 'fused-session', 'platform': 'tpu',
           'fused_compile_secs': 62.0, 'epoch_secs_fused': 0.007,
           'suspect_elision': True, 'fused_layout': 'tree'}
  out = bench._aggregate([_primary()], fused, None)
  assert out['metric'].startswith('graphsage_epoch_secs')
  assert out['value'] == 0.25
  assert out['fused_suspect_elision'] is True


def test_artifact_file_written_and_parseable(bench, tmp_path,
                                             monkeypatch):
  """r6 sink contract: the FULL aggregate lands in BENCH_ARTIFACT.json
  (env-overridable), parseable, while stdout carries only the bounded
  summary naming the file."""
  dest = tmp_path / 'BENCH_ARTIFACT.json'
  monkeypatch.setenv('GLT_BENCH_ARTIFACT', str(dest))
  # a dist payload far beyond any stdout tail: the file must carry it
  # all, the summary must still fit
  dist = {'label': 'virtual CPU mesh - relative only',
          'padding_waste_pct': 71.2, 'drop_rate_pct': 0.0,
          'num_parts': 8,
          'scale_envelope': [{'row': i, 'blob': 'x' * 500}
                             for i in range(16)]}
  fused = {'mode': 'fused-session', 'platform': 'tpu',
           'fused_compile_secs': 60.0, 'epoch_secs_fused': 7.1,
           'fused_layout': 'tree'}
  art = bench._aggregate([_primary(**FULL)], fused, dist)
  line = bench._emit_artifact(art)
  assert dest.exists()
  full = json.loads(dest.read_text())
  assert full['value'] == 7.1
  assert len(full['dist']['scale_envelope']) == 16   # nothing truncated
  # the stdout line: bounded, parseable, names the artifact, carries
  # the headline
  assert len(line) <= 2000
  summary = json.loads(line)
  assert summary['artifact'] == str(dest)
  assert summary['value'] == 7.1
  assert summary['metric'].startswith('graphsage_fused_epoch_secs')
  assert summary['dist']['padding_waste_pct'] == 71.2


def test_summary_line_bounded_on_pathological_artifact(bench, tmp_path,
                                                       monkeypatch):
  """Even an artifact whose every headline field is huge must yield a
  parseable summary under the 2000-char tail budget."""
  from graphlearn_tpu.telemetry import sink
  art = {'metric': 'm' * 500, 'value': 1.0, 'unit': 's',
         'protocol': 'p' * 900,
         'epoch_secs_min_med_max': [0.1] * 200,
         'dist': {'padding_waste_pct': 1.0, 'error': 'e' * 900}}
  line = sink.summary_line(art, artifact=str(tmp_path / 'a.json'))
  assert len(line) <= 2000
  parsed = json.loads(line)
  assert parsed['value'] == 1.0
