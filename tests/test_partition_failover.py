"""Elastic partition failover (ISSUE 15): PartitionBook-routed
ownership transfer with exact-completion recovery.

The contract stack, bottom-up: the book's RCU versioning and typed
adoption refusals; durable-shard adoption byte-identity (a quiesced
adopted shard serves exactly what the statically loaded one would);
exact completion under a mid-epoch owner kill (full expected seed
count, batches byte-identical to the fault-free run, one adoption);
the GNS bitmask invalidating on a book-version bump; the documented
degraded fallback when no durable shard exists; and the repo-wide
"no `% P` routing convention outside partition_book" grep pin.
"""
import os
import re
import tempfile
from pathlib import Path

import numpy as np
import pytest

from graphlearn_tpu.parallel.dist_data import DistDataset
from graphlearn_tpu.parallel.dist_sampler import (DistLinkNeighborLoader,
                                                  DistNeighborLoader)
from graphlearn_tpu.parallel.failover import (NoDurableShardError,
                                              PartitionLostError,
                                              ShardStore, adopt_shard)
from graphlearn_tpu.parallel.partition_book import (AdoptionRefusedError,
                                                    PartitionBook,
                                                    hot_split_host)
from graphlearn_tpu.testing import chaos

P = 8
N, E = 200, 1200


def _graph(seed=0):
  rng = np.random.default_rng(seed)
  rows = rng.integers(0, N, E)
  cols = rng.integers(0, N, E)
  feat = (np.arange(N)[:, None] + np.zeros((1, 6))).astype(np.float32)
  lab = (np.arange(N) % 4).astype(np.int64)
  return rows, cols, feat, lab


def _dataset(split_ratio=1.0, seed=0):
  rows, cols, feat, lab = _graph(seed)
  return DistDataset.from_full_graph(P, rows, cols, feat, lab,
                                     split_ratio=split_ratio)


def _loader(ds, **kw):
  kw.setdefault('batch_size', 4)
  kw.setdefault('shuffle', True)
  kw.setdefault('seed', 0)
  return DistNeighborLoader(ds, [3, 2], np.arange(N), **kw)


def _assert_batches_equal(ref, got, what=''):
  assert len(ref) == len(got), f'{what}: {len(got)} != {len(ref)}'
  for i, (a, b) in enumerate(zip(ref, got)):
    assert np.array_equal(np.asarray(a.node), np.asarray(b.node)), \
        f'{what}: node differs at batch {i}'
    assert np.array_equal(np.asarray(a.x), np.asarray(b.x)), \
        f'{what}: x differs at batch {i}'
    assert np.array_equal(np.asarray(a.y), np.asarray(b.y)), \
        f'{what}: y differs at batch {i}'
    assert np.array_equal(np.asarray(a.edge_index),
                          np.asarray(b.edge_index)), \
        f'{what}: edge_index differs at batch {i}'


# -- the book ---------------------------------------------------------------

def test_book_rcu_version_fencing():
  book = PartitionBook(np.arange(P + 1) * 10)
  v0 = book.view()
  assert v0.version == 0 and v0.is_identity and v0.spec() is None
  assert v0.num_lanes == 1
  v1 = book.adopt(3, 5)
  # RCU: the pinned old view is untouched; the new view reroutes
  assert v0.version == 0 and int(v0.owners[3]) == 3
  assert v1.version == 1 and int(v1.owners[3]) == 5
  assert int(v1.lane_of_range[3]) == 1 and v1.num_lanes == 2
  assert int(v1.slot_ranges[5, 0]) == 5
  assert int(v1.slot_ranges[5, 1]) == 3
  spec = v1.spec()
  assert spec is not None and spec.version == 1
  assert book.view() is v1 or book.view().version == 1
  ledger = book.adoptions()
  assert ledger == [{'lost': 3, 'survivor': 5, 'version': 1}]


def test_book_typed_refusals():
  book = PartitionBook(np.arange(P + 1))
  book.adopt(1, 2)
  # double adoption forks the routing authority -> typed refusal
  with pytest.raises(AdoptionRefusedError, match='already adopted'):
    book.adopt(1, 4)
  # the dead partition cannot be a survivor
  with pytest.raises(AdoptionRefusedError, match='itself dead'):
    book.adopt(3, 1)
  # one adopted lane per survivor in v1
  with pytest.raises(AdoptionRefusedError, match='already carries'):
    book.adopt(3, 2)
  # self-adoption and out-of-range are refused before any mutation
  with pytest.raises(AdoptionRefusedError):
    book.adopt(4, 4)
  with pytest.raises(AdoptionRefusedError):
    book.adopt(99, 0)
  assert book.version == 1    # refusals never mutated the book
  # deterministic survivor pick skips the loaded survivor
  assert book.pick_survivor(3) == 0


def test_hot_split_host_keys_on_range():
  bounds = np.asarray([0, 10, 30, 60])
  hot = np.asarray([5, 10, 10])
  ids = np.asarray([-1, 0, 7, 12, 25, 35, 55])
  rng, local, cold = hot_split_host(bounds, hot, ids)
  assert rng.tolist()[1:] == [0, 0, 1, 1, 2, 2]
  assert local.tolist() == [0, 0, 7, 2, 15, 5, 25]
  assert cold.tolist() == [False, False, True, False, True, False,
                           True]


# -- durable shards + adoption ----------------------------------------------

def test_adopted_shard_byte_identity_vs_static(tmp_path):
  """The durable payload loaded by `adopt_shard` is byte-identical to
  the statically loaded shard, and the quiesced adopted epoch equals
  the fault-free epoch batch-for-batch."""
  ds, loader = _dataset(), None
  store = ShardStore(tmp_path / 'shards')
  store.write_dataset_shards(ds)
  payload = store.load_shard(2)
  assert np.array_equal(payload['indptr'], ds.graph.indptr[2])
  assert np.array_equal(payload['indices'], ds.graph.indices[2])
  assert np.array_equal(payload['eids'], ds.graph.edge_ids[2])
  assert np.array_equal(payload['fshard'], ds.node_features.shards[2])
  assert np.array_equal(payload['lshard'],
                        np.asarray(ds.node_labels)[2])

  ref_loader = _loader(_dataset())
  ref = [b for b in ref_loader]

  ds2 = _dataset()
  loader = _loader(ds2)
  info = adopt_shard(ds2, store, 2)
  assert info['version'] == 1 and 2 in ds2.adopted_shards
  got = [b for b in loader]     # whole epoch under the adopted book
  _assert_batches_equal(ref, got, 'adopted quiesced epoch')


def test_exact_completion_mid_epoch_kill(tmp_path, monkeypatch):
  """THE acceptance pin: owner killed mid-epoch with a durable shard
  present -> the epoch finishes with the FULL expected seed count,
  batches byte-identical to the fault-free run, adoptions_total == 1,
  recovery_secs gauged."""
  from graphlearn_tpu.telemetry.recorder import recorder
  ref = [b for b in _loader(_dataset())]

  monkeypatch.setenv('GLT_SHARD_DIR', str(tmp_path / 'shards'))
  monkeypatch.delenv('GLT_DEGRADED_OK', raising=False)
  ds = _dataset()
  loader = _loader(ds)
  recorder.enable(None)
  recorder.clear()
  chaos.install('partition.owner:kill:4:partition=3')
  try:
    got = [b for b in loader]
  finally:
    chaos.uninstall()
    recorder.disable()
  _assert_batches_equal(ref, got, 'mid-epoch kill')
  assert ds.partition_book.version == 1
  adopts = recorder.events('partition.adopt')
  kinds = [e.get('phase') for e in adopts]
  assert kinds.count(None) == 1          # ONE adoption executed
  assert kinds.count('recovered') == 1   # and its recovery clock closed
  rec = [e for e in adopts if e.get('phase') == 'recovered'][0]
  assert rec['secs'] > 0
  recorder.clear()


def test_exact_completion_link_loader_kill(tmp_path, monkeypatch):
  """Mesh parity: the link loader runs the same ladder (its dispatch
  seam shares `_partition_supervision`)."""
  rows, cols, _f, _l = _graph()
  pairs = (rows[:160], cols[:160])

  def build():
    ds = _dataset()
    return ds, DistLinkNeighborLoader(
        ds, [2, 2], pairs, neg_sampling='binary', batch_size=4,
        shuffle=True, seed=0, input_space='new')

  _, ref_loader = build()
  ref = [b for b in ref_loader]
  monkeypatch.setenv('GLT_SHARD_DIR', str(tmp_path / 'shards'))
  ds, loader = build()
  chaos.install('partition.owner:kill:3:partition=6')
  try:
    got = [b for b in loader]
  finally:
    chaos.uninstall()
  assert len(got) == len(ref)
  for i, (a, b) in enumerate(zip(ref, got)):
    assert np.array_equal(np.asarray(a.node), np.asarray(b.node)), i
    assert np.array_equal(np.asarray(a.x), np.asarray(b.x)), i
  assert ds.partition_book.version == 1


def test_exact_completion_resumed_from_snapshot(tmp_path, monkeypatch):
  """Owner killed mid-epoch in a RESUMED epoch (the r6 snapshot
  path): kill -> snapshot restore in a fresh loader -> the chaos kill
  fires during the resumed remainder -> adoption -> the resumed
  epoch's remaining batches are byte-identical and complete."""
  monkeypatch.setenv('GLT_SHARD_DIR', str(tmp_path / 'shards'))
  ref = [b for b in _loader(_dataset())]

  ds = _dataset()
  loader = _loader(ds)
  it = iter(loader)
  got = [next(it) for _ in range(3)]
  state = loader.state_dict()

  # fresh loader (the restarted process), resume, then the kill fires
  ds2 = _dataset()
  loader2 = _loader(ds2)
  loader2.load_state_dict(state)
  chaos.install('partition.owner:kill:2:partition=1')
  try:
    got += [b for b in loader2.resume_epoch()]
  finally:
    chaos.uninstall()
  _assert_batches_equal(ref, got, 'resumed epoch')
  assert ds2.partition_book.version == 1


def test_gns_bitmask_invalidated_on_book_bump(tmp_path):
  """A book-version bump must rebuild the cached-set bitmask at the
  same fence that rebuilds the arrays (derived structures refresh
  with the placement they derive from)."""
  ds = _dataset(split_ratio=0.5)
  loader = _loader(ds, gns=True)
  s = loader.sampler
  assert s.gns
  _ = [b for b in loader]
  bits_before = s._gns_bits
  assert bits_before is not None
  assert s._gns_ver >= 0
  store = ShardStore(tmp_path / 'shards')
  store.write_dataset_shards(ds)
  adopt_shard(ds, store, 4)
  s.maybe_refresh_book()
  assert s._gns_ver == -1        # invalidated at the fence
  _ = [b for b in loader]        # next epoch rebuilds
  assert s._gns_ver >= 0


def test_no_durable_shard_falls_back_degraded(monkeypatch):
  """The documented ladder tail: no GLT_SHARD_DIR -> degraded when
  opted in (reduced data: the orphaned shard's nodes vanish; the
  loss is flagged peer.lost degraded=true), typed raise otherwise."""
  from graphlearn_tpu.telemetry.recorder import recorder
  monkeypatch.delenv('GLT_SHARD_DIR', raising=False)
  monkeypatch.delenv('GLT_DEGRADED_OK', raising=False)
  loader = _loader(_dataset())
  chaos.install('partition.owner:kill:2:partition=5')
  try:
    with pytest.raises(PartitionLostError, match='GLT_SHARD_DIR'):
      _ = [b for b in loader]
  finally:
    chaos.uninstall()

  monkeypatch.setenv('GLT_DEGRADED_OK', '1')
  ds = _dataset()
  loader = _loader(ds)
  recorder.enable(None)
  recorder.clear()
  chaos.install('partition.owner:kill:2:partition=5')
  try:
    got = [b for b in loader]
  finally:
    chaos.uninstall()
    recorder.disable()
  assert len(got) == len(loader)     # exact accounting, reduced data
  lost = [e for e in recorder.events('peer.lost') if e.get('degraded')]
  assert lost and lost[0]['peer'] == 5
  assert ds.partition_book.version == 0     # nothing adopted
  # the write-off's data effect, pinned at the stacks AND in served
  # batches: partition 5's CSR row is emptied (its expansions vanish
  # from the epoch; seeds can still name p5 ids) and every p5 node a
  # batch still carries reads a zeroed feature row
  bounds = np.asarray(ds.graph.bounds, np.int64)
  assert not np.asarray(ds.graph.indptr)[5].any()
  assert np.all(np.asarray(ds.graph.indices)[5] == -1)
  found_p5 = False
  for b in got[2:]:                # post-kill batches (kill at step 2)
    node = np.asarray(b.node)
    x = np.asarray(b.x)
    p5 = (node >= bounds[5]) & (node < bounds[6])
    found_p5 = found_p5 or bool(p5.any())
    assert np.all(x[p5] == 0)
  assert found_p5, 'no batch named a p5 node — the pin is vacuous'
  recorder.clear()


def test_double_kill_second_adoption_runs_or_refuses(tmp_path,
                                                     monkeypatch):
  """Two distinct owners lost: both adopt (different survivors), and
  a third loss of an ALREADY-adopted partition is a no-op fence, not
  a re-adoption."""
  monkeypatch.setenv('GLT_SHARD_DIR', str(tmp_path / 'shards'))
  ref = [b for b in _loader(_dataset())]
  ds = _dataset()
  loader = _loader(ds)
  chaos.install('partition.owner:kill:2:partition=3;'
                'partition.owner:kill:5:partition=6')
  try:
    got = [b for b in loader]
  finally:
    chaos.uninstall()
  _assert_batches_equal(ref, got, 'double adoption')
  assert ds.partition_book.version == 2
  lanes = ds.partition_book.view()
  assert int(lanes.owners[3]) != 3 and int(lanes.owners[6]) != 6


def test_adopt_timeout_and_missing_shard_typed(tmp_path):
  ds = _dataset()
  store = ShardStore(tmp_path / 'empty')
  with pytest.raises(NoDurableShardError, match='GLT_DEGRADED_OK'):
    adopt_shard(ds, store, 1)
  # a store written for another partition count is refused typed
  store2 = ShardStore(tmp_path / 'other')
  store2.save_meta({'num_parts': 4})
  store2.save_shard(1, {'indptr': np.zeros(3, np.int64),
                        'indices': np.zeros(2, np.int32),
                        'eids': np.zeros(2, np.int64)})
  with pytest.raises(AdoptionRefusedError, match='partitions'):
    adopt_shard(ds, store2, 1)


# -- the routing-convention pin ---------------------------------------------

def test_no_mod_p_routing_convention_outside_book():
  """Acceptance criterion: every ownership read in `parallel/` goes
  through partition_book — no inline `searchsorted(bounds...)` owner
  lambdas, no `% num_parts` / `// num_parts` routing arithmetic in
  non-comment code outside the module (construction-time ceil-divs
  and the book's own definitions excepted)."""
  import io
  import tokenize
  root = Path(__file__).resolve().parents[1] / 'graphlearn_tpu'
  owner_pat = re.compile(
      r'searchsorted\((?:g\.)?bounds\w*,')
  mod_pat = re.compile(r'[-\w\])]\s*%\s*(?:num_parts|self\.num_parts|P\b)')
  offenders = []
  for f in sorted((root / 'parallel').glob('*.py')):
    if f.name == 'partition_book.py':
      continue
    src = f.read_text()
    lines = src.splitlines()
    # blank out strings/comments token-wise so docstrings that QUOTE
    # the conventions don't trip the code-only pin
    code_lines = list(lines)
    for tok in tokenize.generate_tokens(io.StringIO(src).readline):
      if tok.type in (tokenize.STRING, tokenize.COMMENT):
        (r0, c0), (r1, c1) = tok.start, tok.end
        for r in range(r0, r1 + 1):
          line = code_lines[r - 1]
          lo = c0 if r == r0 else 0
          hi = c1 if r == r1 else len(line)
          code_lines[r - 1] = line[:lo] + ' ' * (hi - lo) + line[hi:]
    for ln, code in enumerate(code_lines, 1):
      if owner_pat.search(code) or mod_pat.search(code):
        offenders.append(f'{f.name}:{ln}: {lines[ln - 1].strip()}')
  assert not offenders, (
      'ownership arithmetic outside partition_book.py (route through '
      'range_of/range_owner_fn/edge_owner_* / hot_split_host):\n'
      + '\n'.join(offenders))


# -- shard refresh at the ingest compaction seam ----------------------------

def test_shard_refresh_at_compaction_seam(tmp_path):
  """`ShardStore.refresh_cb` wired as the IngestPipeline's
  compaction hook rewrites the durable shards from the dataset's
  CURRENT stacks — an adoption after ingest loads the streamed
  topology."""
  from graphlearn_tpu.streaming.delta import StreamingGraph
  from graphlearn_tpu.streaming.ingest import IngestPipeline
  rows, cols, feat, lab = _graph()
  ds = DistDataset.from_full_graph(P, rows, cols, feat, lab)
  store = ShardStore(tmp_path / 'shards')
  store.write_dataset_shards(ds)
  before = store.load_shard(0)

  stream = StreamingGraph.from_coo(rows, cols, num_nodes=N,
                                   device=False)
  ds.attach_stream(stream)
  pipe = IngestPipeline(stream, wal_dir=str(tmp_path / 'wal'),
                        compact_every=1, recover=False,
                        shard_refresh=store.refresh_cb(ds))
  try:
    rng = np.random.default_rng(7)
    pipe.ingest(rng.integers(0, N, 20), rng.integers(0, N, 20))
    # the loader seam restacks ds.graph from the stream; ingest then
    # compacts again and the refresh must snapshot the NEW stacks
    loader = _loader(ds, shuffle=False)
    _ = next(iter(loader))
    pipe.ingest(rng.integers(0, N, 20), rng.integers(0, N, 20))
  finally:
    pipe.close()
  after = store.load_shard(0)
  assert not np.array_equal(before['indptr'],
                            after['indptr'][:len(before['indptr'])]) \
      or not np.array_equal(before['indices'],
                            after['indices'][:len(before['indices'])])
  assert store.meta()['num_parts'] == P
