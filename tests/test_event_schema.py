"""Static event-kind / span-name schema enforcement (ISSUE 2
satellite): every ``recorder.emit('<kind>', ...)`` and
``span('<name>', ...)`` call site in the package must be registered in
`telemetry.schema`, and the registry must not hold stale or
undocumented entries.

The AST scan that used to live here migrated to glint's
``event-schema`` pass (ISSUE 11) — this test is now the tier-1 driver
invocation, so any new subsystem gets the same enforcement for free
(plus the other five passes via ``test_glint.py``'s whole-tree run).
"""
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from tools.glint.driver import DEFAULT_BASELINE, run_glint  # noqa: E402


def test_event_schema_clean():
  # paths narrowed to the package: the pass ignores everything else
  # anyway, and test_glint.py's whole-tree run covers the full roots
  live = [f for f in run_glint(paths=['graphlearn_tpu'],
                               rules=['event-schema'],
                               baseline=DEFAULT_BASELINE) if f.live]
  assert not live, 'event-schema drift:\n' + '\n'.join(
      f.render() for f in live)
