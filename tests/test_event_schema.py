"""Static event-kind / span-name schema enforcement (ISSUE 2
satellite): every ``recorder.emit('<kind>', ...)`` and
``span('<name>', ...)`` call site in the package must be registered in
`telemetry.schema`, and the registry must not hold stale entries —
exporters and dashboards key off these strings, and an unregistered
kind is a consumer that silently sees nothing.
"""
import ast
from pathlib import Path

from graphlearn_tpu.telemetry.schema import EVENT_KINDS, SPAN_NAMES

PKG = Path(__file__).resolve().parent.parent / 'graphlearn_tpu'


def _callee_name(func) -> str:
  if isinstance(func, ast.Attribute):
    return func.attr
  if isinstance(func, ast.Name):
    return func.id
  return ''


def _call_sites(callee: str):
  """``{first_string_arg: [files...]}`` for every real AST call of
  ``callee`` in the package (docstring examples don't count — the
  registry tracks call SITES)."""
  out = {}
  for py in sorted(PKG.rglob('*.py')):
    tree = ast.parse(py.read_text())
    for node in ast.walk(tree):
      if (isinstance(node, ast.Call)
          and _callee_name(node.func) == callee and node.args
          and isinstance(node.args[0], ast.Constant)
          and isinstance(node.args[0].value, str)):
        out.setdefault(node.args[0].value, []).append(
            str(py.relative_to(PKG)))
  return out


def test_all_emitted_kinds_registered():
  sites = _call_sites('emit')
  # spans.py emits the span.begin/end pair; everything else emits
  # point events — all must be registered
  unregistered = {k: v for k, v in sites.items() if k not in EVENT_KINDS}
  assert not unregistered, (
      f'unregistered event kinds {unregistered} — add them to '
      'telemetry/schema.py::EVENT_KINDS (with a field summary) so '
      'exporters and dashboards do not go stale')


def test_no_stale_registered_kinds():
  sites = _call_sites('emit')
  stale = set(EVENT_KINDS) - set(sites)
  assert not stale, (
      f'registered kinds with no emit call site: {stale} — remove '
      'them from telemetry/schema.py::EVENT_KINDS')


def test_all_span_names_registered():
  sites = _call_sites('span')
  unregistered = {k: v for k, v in sites.items() if k not in SPAN_NAMES}
  assert not unregistered, (
      f'unregistered span names {unregistered} — add them to '
      'telemetry/schema.py::SPAN_NAMES')


def test_no_stale_span_names():
  sites = _call_sites('span')
  stale = set(SPAN_NAMES) - set(sites)
  assert not stale, (
      f'registered span names with no call site: {stale} — remove '
      'them from telemetry/schema.py::SPAN_NAMES')


def test_tests_emit_only_registered_or_local_kinds():
  """The recorder tests exercise ad-hoc kinds on PRIVATE EventRecorder
  instances, which is fine; the GLOBAL recorder in package code is the
  contract.  This test pins the boundary: schema entries must be
  non-empty strings documenting emitter + fields."""
  for table in (EVENT_KINDS, SPAN_NAMES):
    for kind, doc in table.items():
      assert isinstance(kind, str) and kind
      assert isinstance(doc, str) and len(doc) > 10, kind
