"""Device-mesh link sampling: collective strict negatives + endpoint
expansion under shard_map (the SPMD analog of the reference's
`_sample_from_edges`, `distributed/dist_neighbor_sampler.py:327-453`),
checked against host-side ground truth on the 8-device CPU mesh."""
import numpy as np
import pytest

from graphlearn_tpu.parallel import (DistDataset, DistLinkNeighborLoader,
                                     make_mesh)

N, M, P = 256, 128, 8


def _setup():
  rng = np.random.default_rng(0)
  rows = np.repeat(np.arange(N), 4)
  cols = rng.integers(0, N, N * 4)
  feats = (np.arange(N)[:, None] + np.zeros((1, 8))).astype(np.float32)
  dds = DistDataset.from_full_graph(P, rows, cols, node_feat=feats,
                                    num_nodes=N)
  edge_set = set(zip(rows.tolist(), cols.tolist()))
  idx = rng.choice(len(rows), M, replace=False)
  return dds, edge_set, rows[idx], cols[idx], dds.new2old


@pytest.mark.slow
def test_mesh_link_binary_strict():
  dds, edge_set, src, dst, new2old = _setup()
  mesh = make_mesh(P)
  loader = DistLinkNeighborLoader(dds, [3, 2], (src, dst),
                                  neg_sampling='binary', batch_size=4,
                                  mesh=mesh)
  total_pos = 0
  for batch in loader:
    node = np.asarray(batch.node)
    eli = np.asarray(batch.metadata['edge_label_index'])
    lab = np.asarray(batch.metadata['edge_label'])
    lmask = np.asarray(batch.metadata['edge_label_mask'])
    ei = np.asarray(batch.edge_index)
    x = np.asarray(batch.x)
    for p in range(P):
      mm = ei[p, 0] >= 0
      gs = new2old[node[p][ei[p, 1, mm]]]
      gd = new2old[node[p][ei[p, 0, mm]]]
      for a, b in zip(gs.tolist(), gd.tolist()):
        assert (a, b) in edge_set
      # feature provenance: row value encodes the OLD global id
      nm = node[p] >= 0
      assert np.all(x[p][nm, 0] == new2old[node[p][nm]])
      ok = lmask[p]
      gs = new2old[node[p][eli[p, 0, ok]]]
      gd = new2old[node[p][eli[p, 1, ok]]]
      for a, b, y in zip(gs.tolist(), gd.tolist(), lab[p][ok].tolist()):
        if y >= 1:
          assert (a, b) in edge_set
          total_pos += 1
        else:
          assert (a, b) not in edge_set
  assert total_pos == M


def test_mesh_link_triplet_strict():
  dds, edge_set, src, dst, new2old = _setup()
  mesh = make_mesh(P)
  loader = DistLinkNeighborLoader(dds, [3], (src, dst),
                                  neg_sampling=('triplet', 2),
                                  batch_size=4, mesh=mesh)
  pairs_seen = 0
  for batch in loader:
    node = np.asarray(batch.node)
    si = np.asarray(batch.metadata['src_index'])
    dp = np.asarray(batch.metadata['dst_pos_index'])
    dn = np.asarray(batch.metadata['dst_neg_index'])
    pm = np.asarray(batch.metadata['pair_mask'])
    for p in range(P):
      gs = new2old[node[p][si[p][pm[p]]]]
      gp = new2old[node[p][dp[p][pm[p]]]]
      for a, b in zip(gs.tolist(), gp.tolist()):
        assert (a, b) in edge_set
      pairs_seen += len(gs)
      for j, a in enumerate(gs.tolist()):
        for dl in dn[p][pm[p]][j].tolist():
          if dl < 0:
            continue               # exhausted-trials slot, masked out
          assert (a, new2old[node[p][dl]]) not in edge_set
  assert pairs_seen == M


def test_mesh_link_no_negatives():
  dds, edge_set, src, dst, new2old = _setup()
  mesh = make_mesh(P)
  loader = DistLinkNeighborLoader(dds, [2], (src, dst), batch_size=4,
                                  mesh=mesh)
  for batch in loader:
    node = np.asarray(batch.node)
    eli = np.asarray(batch.metadata['edge_label_index'])
    lmask = np.asarray(batch.metadata['edge_label_mask'])
    for p in range(P):
      ok = lmask[p]
      gs = new2old[node[p][eli[p, 0, ok]]]
      gd = new2old[node[p][eli[p, 1, ok]]]
      for a, b in zip(gs.tolist(), gd.tolist()):
        assert (a, b) in edge_set
