"""Canary: a parity slice re-run under the PRODUCTION XLA pipeline.

`tests/conftest.py` sets ``jax_disable_most_optimizations`` for the
whole suite (compile-wall economics), which means every parity test
normally runs a different pass pipeline than production — a fusion
bug that changes masked-reduction numerics would be invisible
(ADVICE r4).  This canary re-executes one fused-epoch parity test and
one device-native loader parity test in a SUBPROCESS with
``GLT_TEST_NO_FAST_XLA=1``, i.e. with the full optimization pipeline
on, so at least one representative of each family runs production
passes on every default `pytest` invocation.
"""
import os
import subprocess
import sys

import pytest


def _run_with_full_passes(*test_ids: str):
  env = dict(os.environ, GLT_TEST_NO_FAST_XLA='1')
  out = subprocess.run(
      [sys.executable, '-m', 'pytest', '-q', '-p', 'no:cacheprovider',
       *test_ids],
      cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
      env=env, capture_output=True, text=True, timeout=420)
  assert out.returncode == 0, (
      f'parity failed under the production XLA pipeline:\n'
      f'{out.stdout[-2000:]}\n{out.stderr[-1000:]}')


@pytest.mark.slow
def test_parity_under_production_passes():
  _run_with_full_passes(
      'tests/test_fused_epoch.py::test_fused_step_matches_manual_batch',
      'tests/test_device_native.py::test_device_loader_parity')
