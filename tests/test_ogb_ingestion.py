"""OGB on-disk layout ingestion (VERDICT r2 item 5): raw CSV + binary
layouts round-trip into Dataset / partition layout; the accuracy
harness' ingestion path learns on a synthetic OGB-layout dataset.
Real ogbn-products accuracy asserts in `examples/acc_ogbn_products.py`
wherever the data exists (clean SKIP offline)."""
import gzip

import numpy as np
import pytest

from graphlearn_tpu.data import (Dataset, load_ogb_dir, ogb_to_dataset,
                                 partition_ogb, save_binary)

N, E, D = 30, 90, 5


def _write_raw(root, with_split=True):
  rng = np.random.default_rng(0)
  rows = rng.integers(0, N, E)
  cols = rng.integers(0, N, E)
  feats = rng.normal(size=(N, D)).astype(np.float32)
  feats[:, 0] = np.arange(N)
  labels = (np.arange(N) % 4).astype(np.int64)
  raw = root / 'raw'
  raw.mkdir(parents=True)
  with gzip.open(raw / 'edge.csv.gz', 'wt') as f:
    for r, c in zip(rows, cols):
      f.write(f'{r},{c}\n')
  with gzip.open(raw / 'node-feat.csv.gz', 'wt') as f:
    for row in feats:
      f.write(','.join(f'{v:.6f}' for v in row) + '\n')
  with gzip.open(raw / 'node-label.csv.gz', 'wt') as f:
    for v in labels:
      f.write(f'{v}\n')
  with gzip.open(raw / 'num-node-list.csv.gz', 'wt') as f:
    f.write(f'{N}\n')
  if with_split:
    sp = root / 'split' / 'sales_ranking'
    sp.mkdir(parents=True)
    idx = np.arange(N)
    for name, sl in (('train', idx[:20]), ('valid', idx[20:25]),
                     ('test', idx[25:])):
      with gzip.open(sp / f'{name}.csv.gz', 'wt') as f:
        for v in sl:
          f.write(f'{v}\n')
  return rows, cols, feats, labels


def test_raw_csv_layout(tmp_path):
  rows, cols, feats, labels = _write_raw(tmp_path)
  d = load_ogb_dir(tmp_path)
  assert d['num_nodes'] == N
  np.testing.assert_array_equal(d['edge_index'][0], rows)
  np.testing.assert_array_equal(d['edge_index'][1], cols)
  np.testing.assert_allclose(d['node_feat'], feats, atol=1e-5)
  np.testing.assert_array_equal(d['node_label'], labels)
  np.testing.assert_array_equal(d['train_idx'], np.arange(20))
  np.testing.assert_array_equal(d['test_idx'], np.arange(25, N))


def test_binary_roundtrip(tmp_path):
  rows, cols, feats, labels = _write_raw(tmp_path)
  out = tmp_path / 'bin'
  save_binary(tmp_path, out)
  d = load_ogb_dir(out)
  assert d['num_nodes'] == N
  np.testing.assert_array_equal(d['edge_index'][0], rows)
  np.testing.assert_allclose(d['node_feat'], feats, atol=1e-5)
  np.testing.assert_array_equal(d['node_label'], labels)
  np.testing.assert_array_equal(d['valid_idx'], np.arange(20, 25))


def test_ogb_to_dataset_and_partition(tmp_path):
  rows, cols, feats, labels = _write_raw(tmp_path)
  ds, splits = ogb_to_dataset(tmp_path)
  assert isinstance(ds, Dataset)
  got = np.asarray(ds.get_node_feature().host_get(np.arange(N)))
  np.testing.assert_allclose(got[:, 0], np.arange(N), atol=1e-5)
  np.testing.assert_array_equal(np.asarray(ds.get_node_label()), labels)
  assert set(splits) == {'train', 'valid', 'test'}
  # partition layout feeds the distributed loaders
  pdir = tmp_path / 'part'
  partition_ogb(tmp_path, pdir, 2)
  from graphlearn_tpu.parallel import DistDataset
  dd = DistDataset.from_partition_dir(pdir)
  assert dd.num_partitions == 2
  assert dd.graph.num_nodes == N


def test_sort_hot_split(tmp_path):
  _write_raw(tmp_path)
  ds, _ = ogb_to_dataset(tmp_path, split_ratio=0.5, sort_hot=True)
  feat = ds.get_node_feature()
  assert feat.hot_rows == N // 2
  got = np.asarray(feat.host_get(np.arange(N)))
  np.testing.assert_allclose(got[:, 0], np.arange(N), atol=1e-5)


def test_accuracy_harness_ingestion_path(tmp_path):
  """The acc harness' exact pipeline (ogb_to_dataset -> NeighborLoader
  -> GraphSAGE) learns a clustered OGB-layout dataset to high accuracy
  — validates everything but the real download."""
  import sys
  from pathlib import Path
  sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
  from examples._synthetic import clustered_graph
  rows, cols, feats, labels = clustered_graph(n=600, deg=8, classes=4,
                                              d=16, seed=0)
  out = tmp_path / 'bin'
  out.mkdir()
  np.save(out / 'edge_index.npy', np.stack([rows, cols]))
  np.save(out / 'node_feat.npy', feats)
  np.save(out / 'node_label.npy', labels.astype(np.int64))
  idx = np.random.default_rng(0).permutation(600)
  np.save(out / 'train_idx.npy', idx[:400])
  np.save(out / 'test_idx.npy', idx[400:])

  import jax
  import optax
  from graphlearn_tpu.data import ogb_to_dataset
  from graphlearn_tpu.loader import NeighborLoader
  from graphlearn_tpu.models import (GraphSAGE, create_train_state,
                                     make_eval_step,
                                     make_supervised_step)
  ds, splits = ogb_to_dataset(out)
  train_loader = NeighborLoader(ds, [5, 5], splits['train'],
                                batch_size=64, shuffle=True, seed=0)
  test_loader = NeighborLoader(ds, [5, 5], splits['test'], batch_size=64)
  model = GraphSAGE(hidden_features=32, out_features=4, num_layers=2)
  tx = optax.adam(5e-3)
  state, apply_fn = create_train_state(
      model, jax.random.key(0), next(iter(train_loader)), tx)
  step = make_supervised_step(apply_fn, tx, 64)
  eval_step = make_eval_step(apply_fn, 64)
  for _ in range(5):
    for batch in train_loader:
      state, _, _ = step(state, batch)
  correct = total = 0
  for batch in test_loader:
    c, t = eval_step(state.params, batch)
    correct += int(c)
    total += int(t)
  assert correct / total > 0.9, correct / total


def test_multitask_labels_keep_shape(tmp_path):
  """Multi-column label tables (ogbn-proteins style) must keep [N, K]
  — flattening would silently misalign labels with nodes."""
  _write_raw(tmp_path)
  raw = tmp_path / 'raw'
  (raw / 'node-label.csv.gz').unlink()
  lab = np.arange(N * 3).reshape(N, 3)
  with gzip.open(raw / 'node-label.csv.gz', 'wt') as f:
    for row in lab:
      f.write(','.join(str(v) for v in row) + '\n')
  d = load_ogb_dir(tmp_path)
  assert d['node_label'].shape == (N, 3)
  np.testing.assert_array_equal(d['node_label'], lab)
  out = tmp_path / 'bin'
  save_binary(tmp_path, out)
  d2 = load_ogb_dir(out)
  assert d2['node_label'].shape == (N, 3)
