"""Heterogeneous host-runtime loaders — collocated, mp and
server-client modes (the hetero arm of the reference's distributed
loader tests, `test/python/test_dist_neighbor_loader.py`, with the
SURVEY §4 provenance trick: feature values encode their global id so
correctness is checkable arithmetically from any process)."""
import multiprocessing as mp

import numpy as np
import pytest

from graphlearn_tpu import native

pytestmark = pytest.mark.skipif(not native.available(),
                                reason='native lib unavailable')

NU, NI = 40, 25
ET = ('u', 'to', 'i')
REV = ('i', 'rev_to', 'u')


def _bipartite():
  """Deterministic u->i graph: u connects to (u % NI) and (u*3 % NI);
  features encode 'type base + id' for provenance checks."""
  from graphlearn_tpu.distributed import HostHeteroDataset
  urow = np.repeat(np.arange(NU), 2)
  icol = np.stack([np.arange(NU) % NI, (np.arange(NU) * 3) % NI],
                  1).reshape(-1)
  feats = {
      'u': (np.arange(NU)[:, None] + np.zeros((1, 4))).astype(np.float32),
      'i': (1000 + np.arange(NI)[:, None]
            + np.zeros((1, 4))).astype(np.float32),
  }
  labels = {'u': (np.arange(NU) % 3).astype(np.int64)}
  ds = HostHeteroDataset.from_coo(
      {ET: (urow, icol), REV: (icol, urow)},
      node_features=feats, node_labels=labels)
  edge_set = set(zip(urow.tolist(), icol.tolist()))
  return ds, edge_set, urow, icol


def _check_batch(batch, edge_set):
  u_ids = np.asarray(batch.node_dict['u'])
  i_ids = np.asarray(batch.node_dict['i'])
  xu = np.asarray(batch.x_dict['u'])
  xi = np.asarray(batch.x_dict['i'])
  assert np.all(xu[u_ids >= 0, 0] == u_ids[u_ids >= 0])
  assert np.all(xi[i_ids >= 0, 0] == 1000 + i_ids[i_ids >= 0])
  if 'u' in batch.y_dict:
    yu = np.asarray(batch.y_dict['u'])
    assert np.all(yu[u_ids >= 0] == u_ids[u_ids >= 0] % 3)
  # u->i edges are emitted under the REVERSED etype, direction
  # neighbor->seed: row = i-local, col = u-local
  ei = np.asarray(batch.edge_index_dict[REV])
  m = ei[0] >= 0
  for a, b in zip(u_ids[ei[1, m]].tolist(), i_ids[ei[0, m]].tolist()):
    assert (a, b) in edge_set
  return int(m.sum())


def test_collocated_hetero_node_loader():
  from graphlearn_tpu.distributed import DistNeighborLoader
  ds, edge_set, _, _ = _bipartite()
  loader = DistNeighborLoader(ds, [2, 2], ('u', np.arange(NU)),
                              batch_size=8, to_device=False)
  total_edges = 0
  for batch in loader:
    total_edges += _check_batch(batch, edge_set)
  assert total_edges > 0


def test_collocated_hetero_per_etype_fanouts():
  """Dict-valued num_neighbors restricts sampling to listed etypes."""
  from graphlearn_tpu.distributed import DistNeighborLoader
  ds, edge_set, _, _ = _bipartite()
  loader = DistNeighborLoader(ds, {ET: [2], REV: [0]},
                              ('u', np.arange(NU)), batch_size=8,
                              to_device=False)
  for batch in loader:
    _check_batch(batch, edge_set)
    # one hop u->i only: every u node is a seed, no second-hop u's
    u_ids = np.asarray(batch.node_dict['u'])
    assert int((u_ids >= 0).sum()) <= 8


def test_collocated_hetero_link_binary():
  from graphlearn_tpu.distributed import DistLinkNeighborLoader
  ds, edge_set, urow, icol = _bipartite()
  src, dst = urow[:32], icol[:32]
  loader = DistLinkNeighborLoader(ds, [2, 2], (ET, (src, dst)),
                                  neg_sampling='binary', batch_size=8,
                                  to_device=False)
  for batch in loader:
    _check_batch(batch, edge_set)
    eli = np.asarray(batch.metadata['edge_label_index'])
    lab = np.asarray(batch.metadata['edge_label'])
    mask = np.asarray(batch.metadata['edge_label_mask'])
    u_ids = np.asarray(batch.node_dict['u'])
    i_ids = np.asarray(batch.node_dict['i'])
    gs = u_ids[eli[0, mask]]
    gd = i_ids[eli[1, mask]]
    pos = lab[mask] >= 1
    assert pos.any() and (~pos).any()
    for a, b, p in zip(gs.tolist(), gd.tolist(), pos.tolist()):
      assert ((a, b) in edge_set) == bool(p)


def test_collocated_hetero_link_triplet():
  from graphlearn_tpu.distributed import DistLinkNeighborLoader
  ds, edge_set, urow, icol = _bipartite()
  src, dst = urow[:32], icol[:32]
  loader = DistLinkNeighborLoader(ds, [2, 2], (ET, (src, dst)),
                                  neg_sampling=('triplet', 2),
                                  batch_size=8, to_device=False)
  for batch in loader:
    si = np.asarray(batch.metadata['src_index'])
    dp = np.asarray(batch.metadata['dst_pos_index'])
    dn = np.asarray(batch.metadata['dst_neg_index'])
    pm = np.asarray(batch.metadata['pair_mask'])
    u_ids = np.asarray(batch.node_dict['u'])
    i_ids = np.asarray(batch.node_dict['i'])
    gs = u_ids[si[pm]]
    for a, b in zip(gs.tolist(), i_ids[dp[pm]].tolist()):
      assert (a, b) in edge_set
    for j, a in enumerate(gs.tolist()):
      for b in i_ids[dn[pm][j]].tolist():
        assert (a, b) not in edge_set


def test_mp_hetero_loader_epochs():
  from graphlearn_tpu.distributed import (DistNeighborLoader,
                                          MpDistSamplingWorkerOptions)
  ds, edge_set, _, _ = _bipartite()
  # dict fanouts exercise the fanout-forwarding path into workers
  loader = DistNeighborLoader(
      ds, {ET: [2, 2], REV: [2, 2]}, ('u', np.arange(NU)), batch_size=8,
      shuffle=True, to_device=False,
      worker_options=MpDistSamplingWorkerOptions(num_workers=2))
  try:
    for _ in range(2):
      seen = 0
      for batch in loader:
        _check_batch(batch, edge_set)
        seen += int((np.asarray(batch.batch_dict['u']) >= 0).sum())
      assert seen == NU
  finally:
    loader.shutdown()


def _hetero_server_proc(port_q):
  from graphlearn_tpu.distributed import (init_server,
                                          wait_and_shutdown_server)
  ds, _, _, _ = _bipartite()
  srv = init_server(num_servers=1, num_clients=1, rank=0, dataset=ds,
                    host='127.0.0.1', port=0)
  port_q.put(srv.port)
  wait_and_shutdown_server(timeout=60)


def test_remote_hetero_loader():
  """Client passes dataset=None: capacities come from the server's
  hetero dataset meta."""
  ctx = mp.get_context('forkserver')
  port_q = ctx.Queue()
  p = ctx.Process(target=_hetero_server_proc, args=(port_q,),
                  daemon=False)
  p.start()
  port = port_q.get(timeout=30)

  from graphlearn_tpu.distributed import (
      DistNeighborLoader, RemoteDistSamplingWorkerOptions, init_client,
      shutdown_client)
  init_client([('127.0.0.1', port)], rank=0, num_clients=1)
  _, edge_set, _, _ = _bipartite()
  # dict fanouts must survive the client->server RPC intact
  loader = DistNeighborLoader(
      None, {ET: [2, 2], REV: [2, 2]}, ('u', np.arange(NU)), batch_size=8,
      worker_options=RemoteDistSamplingWorkerOptions(
          server_rank=0, num_workers=1, prefetch_size=2),
      to_device=False)
  for _ in range(2):
    seen = 0
    for batch in loader:
      _check_batch(batch, edge_set)
      seen += int((np.asarray(batch.batch_dict['u']) >= 0).sum())
    assert seen == NU
  loader.shutdown()
  shutdown_client()
  p.join(timeout=20)
  assert not p.is_alive()


def test_hetero_partition_roundtrip_host_dataset():
  """Offline hetero partitions load into per-partition host datasets
  with provenance intact."""
  from graphlearn_tpu.distributed import (DistNeighborLoader,
                                          HostHeteroDataset)
  from graphlearn_tpu.partition import RandomPartitioner
  import tempfile
  ds, edge_set, urow, icol = _bipartite()
  with tempfile.TemporaryDirectory() as root:
    part = RandomPartitioner(
        root, num_parts=2,
        num_nodes={'u': NU, 'i': NI},
        edge_index={ET: (urow, icol), REV: (icol, urow)},
        node_feat={nt: ds.node_features[nt] for nt in ('u', 'i')},
        node_label={'u': ds.node_labels['u']})
    part.partition()
    for idx in range(2):
      shard = HostHeteroDataset.from_partition_dir(root, idx)
      assert shard.num_nodes == {'u': NU, 'i': NI}
      # every owned edge must be real (features zero-filled for
      # non-owned rows, so only check edges)
      indptr, indices, _ = shard.csr[ET]
      for u in range(NU):
        for j in range(indptr[u], indptr[u + 1]):
          assert (u, int(indices[j])) in edge_set
      # a local-only loader over a SHARD is REFUSED (r3 guard: it
      # would silently under-sample remote neighborhoods); the full
      # graph still loads fine
      with pytest.raises(ValueError, match='partition shard'):
        DistNeighborLoader(shard, [2], ('u', np.arange(8)),
                           batch_size=4, to_device=False)
    loader = DistNeighborLoader(ds, [2], ('u', np.arange(8)),
                                batch_size=4, to_device=False)
    for batch in loader:
      ei = np.asarray(batch.edge_index_dict[REV])
      u_ids = np.asarray(batch.node_dict['u'])
      i_ids = np.asarray(batch.node_dict['i'])
      m = ei[0] >= 0
      for a, b in zip(u_ids[ei[1, m]].tolist(),
                      i_ids[ei[0, m]].tolist()):
        assert (a, b) in edge_set


def test_hetero_error_paths_and_config_reuse():
  from graphlearn_tpu.distributed import (CollocatedSamplingProducer,
                                          DistNeighborLoader,
                                          DistSubGraphLoader,
                                          HostSamplingConfig)
  ds, _, _, _ = _bipartite()
  # hetero producer without an input_type fails loudly, not opaquely
  prod = CollocatedSamplingProducer(ds, [2], batch_size=4)
  with pytest.raises(ValueError, match='input_type'):
    next(prod.epoch(np.arange(8)))
  # hetero subgraph mode is rejected at construction
  with pytest.raises(ValueError, match='homogeneous-only'):
    DistSubGraphLoader(ds, [2], ('u', np.arange(8)), to_device=False)
  # a config object shared across loaders is not mutated in place
  cfg = HostSamplingConfig(sampling_type='node')
  DistNeighborLoader(ds, [2], ('u', np.arange(8)), batch_size=4,
                     sampling_config=cfg, to_device=False)
  assert cfg.input_type is None
  li = DistNeighborLoader(ds, [2], ('i', np.arange(8)), batch_size=4,
                          sampling_config=cfg, to_device=False)
  for batch in li:
    i_ids = np.asarray(batch.node_dict['i'])
    xi = np.asarray(batch.x_dict['i'])
    assert np.all(xi[i_ids >= 0, 0] == 1000 + i_ids[i_ids >= 0])


def test_hetero_with_edge_static_pytree():
  """Every batch carries the same edge_dict/edge_index key set even
  when an etype samples nothing, so jitted consumers never retrace."""
  import jax
  from graphlearn_tpu.distributed import DistNeighborLoader
  ds, edge_set, urow, icol = _bipartite()
  # second hop only expands i->u, so batches where hop-1 found no new
  # i nodes would otherwise drop the rev-etype keys
  loader = DistNeighborLoader(ds, {ET: [2, 0], REV: [0, 2]},
                              ('u', np.arange(NU)), batch_size=8,
                              with_edge=True, to_device=False)
  structs = set()
  for batch in loader:
    assert set(batch.metadata['edge_dict'].keys()) == set(
        batch.edge_index_dict.keys())
    structs.add(jax.tree_util.tree_structure(
        jax.tree_util.tree_map(lambda a: a.shape, batch)))
    # emitted global edge ids refer to real edges of the right etype
    for et, ev in batch.metadata['edge_dict'].items():
      ev = np.asarray(ev)
      em = np.asarray(batch.edge_mask_dict[et])
      assert np.all(ev[em] >= 0)
  assert len(structs) == 1


# -- degraded completion (ISSUE 6 satellite) --------------------------------

def _degraded_server_proc(port_q, rank, fault_plan):
  """One of two hetero sampling servers; ``fault_plan`` (rank 1) kills
  its only producer worker with a zero restart budget, so its pool
  dies mid-epoch and fetches surface as typed peer-lost errors."""
  import os
  if fault_plan:
    os.environ['GLT_FAULT_PLAN'] = fault_plan
    os.environ['GLT_MAX_WORKER_RESTARTS'] = '0'
  from graphlearn_tpu.distributed import (init_server,
                                          wait_and_shutdown_server)
  ds, _, _, _ = _bipartite()
  srv = init_server(num_servers=2, num_clients=1, rank=rank,
                    dataset=ds, host='127.0.0.1', port=0)
  port_q.put(srv.port)
  wait_and_shutdown_server(timeout=120)


def test_remote_hetero_degraded_drops_dead_server(monkeypatch):
  """The PR 4 homogeneous degraded contract, heterogeneous: one of two
  sampling servers dies mid-epoch (its producer worker is killed with
  no restart budget); with ``GLT_DEGRADED_OK=1`` the epoch finishes on
  the survivor with a REDUCED-BUT-EXACT batch set — every delivered
  batch provenance-checked, no duplicate seeds, the loss flagged as a
  ``peer.lost`` event with ``degraded=True``."""
  from graphlearn_tpu.distributed.dist_loader import DistLoader
  from graphlearn_tpu.telemetry import recorder
  monkeypatch.setenv('GLT_DEGRADED_OK', '1')
  monkeypatch.setattr(DistLoader, 'RECV_POLL_SECS', 1.0)
  recorder.enable(None)
  recorder.clear()
  ctx = mp.get_context('spawn')
  procs, ports = [], []
  for rank in range(2):
    q = ctx.Queue()
    plan = ('producer.worker:kill:2:worker=0:epoch=0'
            if rank == 1 else '')
    p = ctx.Process(target=_degraded_server_proc,
                    args=(q, rank, plan), daemon=False)
    p.start()
    procs.append(p)
    ports.append(q.get(timeout=120))

  from graphlearn_tpu.distributed import (
      DistNeighborLoader, RemoteDistSamplingWorkerOptions, init_client,
      shutdown_client)
  init_client([('127.0.0.1', pt) for pt in ports], rank=0,
              num_clients=1)
  _, edge_set, _, _ = _bipartite()
  loader = DistNeighborLoader(
      None, {ET: [2, 2], REV: [2, 2]}, ('u', np.arange(NU)),
      batch_size=8, shuffle=False,
      worker_options=RemoteDistSamplingWorkerOptions(
          server_rank=[0, 1], num_workers=1, prefetch_size=1),
      to_device=False)
  try:
    batches = []
    for batch in loader:
      _check_batch(batch, edge_set)
      batches.append(batch)
    lost_evs = [e for e in recorder.events('peer.lost')
                if e.get('degraded')]
    assert lost_evs, 'degraded completion must be flagged'
    lost = sum(e['lost_batches'] for e in lost_evs)
    assert lost >= 1
    # reduced-but-EXACT: every delivered seed exactly once
    seeds = np.concatenate(
        [np.asarray(b.batch_dict['u']) for b in batches])
    seeds = seeds[seeds >= 0]
    assert len(seeds) == len(set(seeds.tolist()))
    assert 0 < len(seeds) < NU, 'reduced: the dead server\'s share lost'
    assert len(batches) == loader._expected
  finally:
    loader.shutdown()
    shutdown_client()
    recorder.clear()
    recorder.disable()
    for p in procs:
      p.join(timeout=60)
      assert not p.is_alive()


def test_remote_hetero_adoption_exact_completion(monkeypatch,
                                                 tmp_path):
  """ISSUE 15 hetero parity: the SAME dead-server classification now
  routes through the adoption path — with ``GLT_SHARD_DIR`` set (the
  failover opt-in) the dead server's producer is recreated on the
  survivor and the epoch finishes with the FULL expected batch set
  (every seed exactly once — not the reduced degraded contract), one
  ``partition.adopt`` event, ``partition.adoptions_total == 1``."""
  from graphlearn_tpu.distributed.dist_loader import DistLoader
  from graphlearn_tpu.telemetry import recorder
  monkeypatch.setenv('GLT_SHARD_DIR', str(tmp_path / 'shards'))
  # degraded stays OFF: adoption must carry the epoch alone
  monkeypatch.delenv('GLT_DEGRADED_OK', raising=False)
  monkeypatch.setattr(DistLoader, 'RECV_POLL_SECS', 1.0)
  recorder.enable(None)
  recorder.clear()
  ctx = mp.get_context('spawn')
  procs, ports = [], []
  for rank in range(2):
    q = ctx.Queue()
    plan = ('producer.worker:kill:2:worker=0:epoch=0'
            if rank == 1 else '')
    p = ctx.Process(target=_degraded_server_proc,
                    args=(q, rank, plan), daemon=False)
    p.start()
    procs.append(p)
    ports.append(q.get(timeout=120))

  from graphlearn_tpu.distributed import (
      DistNeighborLoader, RemoteDistSamplingWorkerOptions, init_client,
      shutdown_client)
  init_client([('127.0.0.1', pt) for pt in ports], rank=0,
              num_clients=1)
  _, edge_set, _, _ = _bipartite()
  loader = DistNeighborLoader(
      None, {ET: [2, 2], REV: [2, 2]}, ('u', np.arange(NU)),
      batch_size=8, shuffle=False,
      worker_options=RemoteDistSamplingWorkerOptions(
          server_rank=[0, 1], num_workers=1, prefetch_size=1),
      to_device=False)
  try:
    batches = []
    for batch in loader:
      _check_batch(batch, edge_set)
      batches.append(batch)
    adopts = [e for e in recorder.events('partition.adopt')]
    assert len(adopts) == 1, adopts
    assert adopts[0]['scope'] == 'server'
    # EXACT completion: the full seed set, every seed exactly once
    seeds = np.concatenate(
        [np.asarray(b.batch_dict['u']) for b in batches])
    seeds = seeds[seeds >= 0]
    assert len(seeds) == NU, f'{len(seeds)} != {NU} (reduced?)'
    assert len(set(seeds.tolist())) == NU
    assert len(batches) == loader._expected
    # no degraded write-off happened
    assert not [e for e in recorder.events('peer.lost')
                if e.get('degraded')]
  finally:
    loader.shutdown()
    shutdown_client()
    recorder.clear()
    recorder.disable()
    for p in procs:
      p.join(timeout=60)
      assert not p.is_alive()
