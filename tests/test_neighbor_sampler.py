"""NeighborSampler tests.

Mirrors reference `test/python/test_neighbor_sampler.py` plus the
deterministic circular-graph provenance checks of
`test/python/dist_test_utils.py:26-50` (node v's out-neighbors are
{v+1, v+2} mod N, so every sampled edge is arithmetically checkable).
"""
import numpy as np
import pytest

import jax.numpy as jnp

from graphlearn_tpu.data import CSRTopo, Graph
from graphlearn_tpu.sampler import (EdgeSamplerInput, NegativeSampling,
                                    NeighborSampler, NodeSamplerInput,
                                    RandomNegativeSampler)


def circular_graph(n=40):
  rows = np.repeat(np.arange(n), 2)
  cols = np.stack([(np.arange(n) + 1) % n, (np.arange(n) + 2) % n],
                  axis=1).reshape(-1)
  return CSRTopo((rows, cols), layout='COO', num_nodes=n)


@pytest.fixture(scope='module')
def graph():
  return Graph(circular_graph(40), mode='device')


def _check_edges(out, n=40):
  node = np.asarray(out.node)
  row = np.asarray(out.row)
  col = np.asarray(out.col)
  mask = np.asarray(out.edge_mask)
  assert mask.sum() > 0
  for r, c in zip(row[mask], col[mask]):
    src, dst = node[c], node[r]
    assert dst in ((src + 1) % n, (src + 2) % n)


def test_sample_from_nodes_basic(graph):
  sampler = NeighborSampler(graph, [2, 2], seed=7)
  seeds = np.array([0, 5, 10, 15], np.int32)
  out = sampler.sample_from_nodes(NodeSamplerInput(node=seeds))
  node = np.asarray(out.node)
  # seeds occupy the first local slots in order
  np.testing.assert_array_equal(node[:4], seeds)
  assert int(out.node_count) <= node.shape[0]
  # every valid node id is a real node, padding is INVALID
  cnt = int(out.node_count)
  assert (node[:cnt] >= 0).all() and (node[:cnt] < 40).all()
  assert (node[cnt:] == -1).all()
  _check_edges(out)
  # per-hop accounting
  nsn = np.asarray(out.num_sampled_nodes)
  assert nsn.sum() == cnt
  assert nsn[0] == 4


def test_full_fanout_exact(graph):
  # fanout >= degree: every neighbor must appear exactly once.
  sampler = NeighborSampler(graph, [2], seed=0, with_edge=True)
  seeds = np.array([3, 9], np.int32)
  out = sampler.sample_from_nodes(NodeSamplerInput(node=seeds))
  node = np.asarray(out.node)
  row, col = np.asarray(out.row), np.asarray(out.col)
  mask = np.asarray(out.edge_mask)
  got = {(node[c], node[r]) for r, c in zip(row[mask], col[mask])}
  want = {(3, 4), (3, 5), (9, 10), (9, 11)}
  assert got == want
  # edge ids are the global CSR positions
  eids = np.asarray(out.edge)[mask]
  assert set(eids.tolist()) == {6, 7, 18, 19}


def test_duplicate_seeds_deduped(graph):
  sampler = NeighborSampler(graph, [2], seed=1)
  seeds = np.array([7, 7, 8, 7], np.int32)
  out = sampler.sample_from_nodes(NodeSamplerInput(node=seeds))
  node = np.asarray(out.node)
  assert node[0] == 7 and node[1] == 8
  cnt = int(out.node_count)
  vals = node[:cnt]
  assert len(set(vals.tolist())) == cnt  # all unique


def test_determinism(graph):
  s1 = NeighborSampler(graph, [2, 2], seed=42)
  s2 = NeighborSampler(graph, [2, 2], seed=42)
  seeds = np.arange(8, dtype=np.int32)
  o1 = s1.sample_from_nodes(NodeSamplerInput(node=seeds))
  o2 = s2.sample_from_nodes(NodeSamplerInput(node=seeds))
  np.testing.assert_array_equal(np.asarray(o1.node), np.asarray(o2.node))
  np.testing.assert_array_equal(np.asarray(o1.row), np.asarray(o2.row))


def test_padded_seeds(graph):
  sampler = NeighborSampler(graph, [2], seed=3)
  seeds = np.array([1, 2, -1, -1], np.int32)  # INVALID-padded tail
  out = sampler.sample_from_nodes(NodeSamplerInput(node=seeds))
  node = np.asarray(out.node)
  assert node[0] == 1 and node[1] == 2
  _check_edges(out)


def test_sample_from_edges_binary(graph):
  sampler = NeighborSampler(graph, [2], seed=11, with_neg=True)
  row = np.array([0, 1, 2, 3], np.int32)
  col = np.array([1, 2, 3, 4], np.int32)
  out = sampler.sample_from_edges(
      EdgeSamplerInput(row=row, col=col),
      neg_sampling=NegativeSampling('binary', 1))
  eli = np.asarray(out.metadata['edge_label_index'])
  lab = np.asarray(out.metadata['edge_label'])
  assert eli.shape == (2, 8)
  np.testing.assert_array_equal(lab, [1, 1, 1, 1, 0, 0, 0, 0])
  node = np.asarray(out.node)
  # positive pairs resolve back to the original global edges
  for i in range(4):
    assert node[eli[0, i]] == row[i]
    assert node[eli[1, i]] == col[i]
  # negatives are non-edges (strict, modulo padding): dst not in {src+1, src+2}
  neg_src = node[eli[0, 4:]]
  neg_dst = node[eli[1, 4:]]
  for s, d in zip(neg_src, neg_dst):
    assert d not in ((s + 1) % 40, (s + 2) % 40)


def test_sample_from_edges_triplet(graph):
  sampler = NeighborSampler(graph, [2], seed=13, with_neg=True)
  row = np.array([0, 10], np.int32)
  col = np.array([1, 11], np.int32)
  out = sampler.sample_from_edges(
      EdgeSamplerInput(row=row, col=col),
      neg_sampling=NegativeSampling('triplet', 2))
  md = out.metadata
  node = np.asarray(out.node)
  assert np.asarray(md['src_index']).shape == (2,)
  assert np.asarray(md['dst_pos_index']).shape == (2,)
  assert np.asarray(md['dst_neg_index']).shape == (2, 2)
  np.testing.assert_array_equal(node[np.asarray(md['src_index'])], row)
  np.testing.assert_array_equal(node[np.asarray(md['dst_pos_index'])], col)
  neg = node[np.asarray(md['dst_neg_index'])]
  for i, s in enumerate(row):
    for d in neg[i]:
      assert d not in ((s + 1) % 40, (s + 2) % 40)


def test_subgraph(graph):
  sampler = NeighborSampler(graph, [2], seed=17)
  seeds = np.array([0, 1, 2], np.int32)
  out = sampler.subgraph(NodeSamplerInput(node=seeds))
  node = np.asarray(out.node)
  cnt = int(out.node_count)
  nodeset = set(node[:cnt].tolist())
  row, col, mask = (np.asarray(out.row), np.asarray(out.col),
                    np.asarray(out.edge_mask))
  # subgraph outputs are in natural src->dst orientation (unlike the
  # transposed hop edges), matching the reference SubGraphOp.
  got = {(node[r], node[c]) for r, c in zip(row[mask], col[mask])}
  # expected: all circular edges among the collected closure
  want = {(u, v) for u in nodeset for v in ((u + 1) % 40, (u + 2) % 40)
          if v in nodeset}
  assert got == want
  # mapping points seeds at their local slots
  np.testing.assert_array_equal(np.asarray(out.metadata['mapping'])[:3],
                                [0, 1, 2])


def test_negative_sampler_class(graph):
  ns = RandomNegativeSampler(graph, seed=5)
  ei = np.asarray(ns.sample(16))
  assert ei.shape == (2, 16)
  for s, d in zip(ei[0], ei[1]):
    assert d not in ((s + 1) % 40, (s + 2) % 40)


def test_sample_prob(graph):
  sampler = NeighborSampler(graph, [2, 2], seed=0)
  prob = np.asarray(sampler.sample_prob(np.array([0], np.int32), 40))
  assert prob.shape == (40,)
  assert prob[0] == 1.0
  # nodes 1..4 are reachable within 2 hops of node 0; far nodes are not
  assert (prob[1:5] > 0).all()
  assert (prob[10:30] == 0).all()
