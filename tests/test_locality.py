"""Locality-aware mesh partitioning x exchange co-design (ISSUE 20).

Contracts pinned here:

  * `locality_partition` is deterministic under a fixed seed and honors
    the hard ``(1 + eps) * N / P`` balance cap BY CONSTRUCTION;
  * on a planted community graph it cuts decisively fewer edges than
    the historical random round-robin placement;
  * the partitioner is a PURE RELABEL: replaying the locality arm's
    placement as an explicit ``node_pb`` over the already-relabeled
    edge list yields the identity relabel and byte-identical batches —
    single-chip (P=1) and on the 8-device mesh;
  * the replica cache is EXACT: a replica-armed dataset's batches are
    byte-identical to the cache-less twin, with lookups measurably
    kept off the wire (`locally_served_ids`); a zero budget builds no
    cache at all;
  * `rebalance_plan` moves a measured-hot range off its overloaded
    owner onto the top underloaded REQUESTER, and `execute_rebalance`
    runs the plan through the PR 19 fenced handoff mid-epoch with the
    epoch still byte-identical;
  * the fused tree path ticks BOTH attribution matrices on a tiered
    epoch (the dead-feature-counter regression);
  * `GLT_PARTITIONER` unset keeps the historical placement
    byte-for-byte; the hetero builder partitions the disjoint union.
"""
import numpy as np
import pytest

from graphlearn_tpu.parallel import make_mesh
from graphlearn_tpu.parallel.dist_data import DistDataset
from graphlearn_tpu.parallel.dist_sampler import DistNeighborLoader
from graphlearn_tpu.parallel.failover import ShardStore
from graphlearn_tpu.parallel.locality import (edge_cut_frac,
                                              execute_rebalance,
                                              locality_partition,
                                              rebalance_plan,
                                              resolve_partitioner)

P = 8
N, E = 200, 1200
C = N // P                       # planted community size


def _community_edges(seed=0, intra=0.85):
  """E edges, ``intra`` of them inside contiguous size-C communities —
  structure a locality partitioner should find."""
  rng = np.random.default_rng(seed)
  rows = rng.integers(0, N, E)
  within = (rows // C) * C + rng.integers(0, C, E)
  anywhere = rng.integers(0, N, E)
  cols = np.where(rng.random(E) < intra, within, anywhere)
  return rows, cols


def _hub_edges(seed=0, hubs=20, frac=0.5):
  """Half the destinations land on nodes [0, hubs) — concentrated
  demand for the rebalance tests."""
  rng = np.random.default_rng(seed)
  rows = rng.integers(0, N, E)
  cols = np.where(rng.random(E) < frac, rng.integers(0, hubs, E),
                  rng.integers(0, N, E))
  return rows, cols


def _feat():
  return (np.arange(N)[:, None] + np.zeros((1, 6))).astype(np.float32)


def _range_pb(seed=0):
  """The historical seeded round-robin placement, reproduced."""
  rng = np.random.default_rng(seed)
  pb = np.empty(N, np.int32)
  perm = rng.permutation(N)
  for p in range(P):
    pb[perm[p::P]] = p
  return pb


def _loader(ds, seeds=None, **kw):
  kw.setdefault('batch_size', 4)
  kw.setdefault('shuffle', True)
  kw.setdefault('seed', 0)
  kw.setdefault('exchange_slack', 1.5)   # static: cross-arm byte
  #                                      # equality must not depend on
  #                                      # the adaptive slack walk
  n = ds.graph.bounds[-1]
  return DistNeighborLoader(ds, [3, 2],
                            np.arange(n) if seeds is None else seeds,
                            **kw)


def _assert_batches_equal(ref, got, what=''):
  assert len(ref) == len(got), f'{what}: {len(got)} != {len(ref)}'
  for i, (a, b) in enumerate(zip(ref, got)):
    for f in ('node', 'x', 'edge_index', 'batch'):
      av, bv = getattr(a, f, None), getattr(b, f, None)
      if av is None and bv is None:
        continue
      assert np.array_equal(np.asarray(av), np.asarray(bv)), \
          f'{what}: {f} differs at batch {i}'


# -- the streaming partitioner ----------------------------------------------

def test_partition_deterministic_and_seed_sensitive():
  rows, cols = _community_edges()
  pb1, st1 = locality_partition(rows, cols, N, P, seed=7)
  pb2, st2 = locality_partition(rows, cols, N, P, seed=7)
  np.testing.assert_array_equal(pb1, pb2)    # same seed => same bytes
  assert st1 == st2
  pb3, _ = locality_partition(rows, cols, N, P, seed=8)
  assert not np.array_equal(pb1, pb3)        # the seed is load-bearing


@pytest.mark.parametrize('eps', (0.05, 0.2))
def test_balance_cap_holds_by_construction(eps):
  rows, cols = _community_edges()
  pb, st = locality_partition(rows, cols, N, P, balance_eps=eps)
  assert pb.shape == (N,) and (pb >= 0).all() and (pb < P).all()
  sizes = np.bincount(pb, minlength=P)
  cap = int(np.ceil((1.0 + eps) * N / P))
  assert sizes.max() <= cap == st['cap']
  assert np.isclose(st['max_part_frac'], sizes.max() * P / N)


def test_cut_beats_random_round_robin():
  rows, cols = _community_edges()
  pb_loc, st = locality_partition(rows, cols, N, P, seed=0)
  cut_rng = edge_cut_frac(rows, cols, _range_pb())
  cut_loc = edge_cut_frac(rows, cols, pb_loc)
  assert np.isclose(cut_loc, st['edge_cut_frac'])
  assert cut_rng > 0.8                       # ~ 1 - 1/P
  assert cut_loc < 0.6 * cut_rng             # structure was found


def test_partitioner_knob_resolution(monkeypatch):
  monkeypatch.delenv('GLT_PARTITIONER', raising=False)
  assert resolve_partitioner() == 'range'
  monkeypatch.setenv('GLT_PARTITIONER', 'locality')
  assert resolve_partitioner() == 'locality'
  rows, cols = _community_edges()
  ds = DistDataset.from_full_graph(P, rows, cols, _feat(), num_nodes=N)
  assert ds.partitioner == 'locality'        # the env knob engaged
  with pytest.raises(ValueError, match='fennel9000'):
    resolve_partitioner('fennel9000')


def test_default_placement_byte_identical(monkeypatch):
  """GLT_PARTITIONER unset: the build must reproduce the historical
  seeded round-robin placement byte-for-byte."""
  monkeypatch.delenv('GLT_PARTITIONER', raising=False)
  rows, cols = _community_edges()
  feat = _feat()
  lab = (np.arange(N) % 4).astype(np.int64)
  ds = DistDataset.from_full_graph(P, rows, cols, feat, lab,
                                   num_nodes=N)
  assert ds.partitioner == 'range'
  ref = DistDataset.from_full_graph(P, rows, cols, feat, lab,
                                    num_nodes=N, node_pb=_range_pb())
  np.testing.assert_array_equal(ds.old2new, ref.old2new)
  np.testing.assert_array_equal(ds.graph.bounds, ref.graph.bounds)
  np.testing.assert_array_equal(ds.graph.indptr, ref.graph.indptr)
  np.testing.assert_array_equal(ds.graph.indices, ref.graph.indices)
  np.testing.assert_array_equal(ds.node_features.shards,
                                ref.node_features.shards)
  np.testing.assert_array_equal(ds.node_labels, ref.node_labels)


# -- pure-rename equivalence ------------------------------------------------

def _rename_twin(ds_loc, rows, cols, feat, num_parts, replica_frac):
  """Replay ``ds_loc``'s placement as an explicit node_pb over the
  ALREADY-relabeled edge list; the relabel must come out the
  identity."""
  o2n, n2o = ds_loc.old2new, ds_loc.new2old
  n = int(ds_loc.graph.bounds[-1])
  pb_new = (np.searchsorted(ds_loc.graph.bounds, np.arange(n),
                            'right') - 1).astype(np.int32)
  ds_ren = DistDataset.from_full_graph(
      num_parts, o2n[rows], o2n[cols], node_feat=feat[n2o],
      num_nodes=n, node_pb=pb_new, replica_frac=replica_frac,
      hotness=np.bincount(o2n[cols], minlength=n))
  np.testing.assert_array_equal(ds_ren.old2new, np.arange(n))
  return ds_ren, o2n


@pytest.mark.parametrize('num_parts', (1, P))
def test_pure_rename_byte_equivalence(num_parts):
  """Single-chip (P=1) and mesh (P=8): the locality build and its
  renamed explicit-node_pb twin emit byte-identical batches — the
  partitioner is a relabel, nothing else."""
  rows, cols = _community_edges()
  feat = _feat()
  frac = 0.1
  ds_loc = DistDataset.from_full_graph(
      num_parts, rows, cols, feat, num_nodes=N, partitioner='locality',
      replica_frac=frac)
  assert ds_loc.partitioner == 'locality'
  ds_ren, o2n = _rename_twin(ds_loc, rows, cols, feat, num_parts, frac)
  mesh = make_mesh(num_parts)
  ref = list(_loader(ds_loc, mesh=mesh))
  got = list(_loader(ds_ren, seeds=o2n[np.arange(N)], mesh=mesh))
  _assert_batches_equal(ref, got, f'pure rename P={num_parts}')


# -- the replica cache ------------------------------------------------------

def test_replica_budget_zero_builds_no_cache():
  rows, cols = _community_edges()
  ds = DistDataset.from_full_graph(P, rows, cols, _feat(), num_nodes=N,
                                   partitioner='locality',
                                   replica_frac=0.0)
  assert not getattr(ds.node_features, 'cache_local', False)
  assert ds.node_features.cache_ids is None


def test_replica_rows_exact_and_off_wire():
  """A tiny replica budget changes NO bytes in any batch — hot remote
  rows are served from the local copy, exactly — while the attribution
  plane shows lookups kept off the wire and a lower cross fraction."""
  rows, cols = _hub_edges()
  feat = _feat()

  def build(frac):
    return DistDataset.from_full_graph(P, rows, cols, feat,
                                       num_nodes=N,
                                       partitioner='locality',
                                       replica_frac=frac)

  l0 = _loader(build(0.0))
  ref = list(l0)
  l1 = _loader(build(0.1))                   # 20 remote rows / device
  got = list(l1)
  _assert_batches_equal(ref, got, 'replica overlay')
  assert l1.sampler.replica_hits() > 0
  a0 = l0.sampler.attribution_stats(tick_metrics=False)
  a1 = l1.sampler.attribution_stats(tick_metrics=False)
  assert a1['locally_served_ids'] > 0 == a0['locally_served_ids']
  assert (a1['cross_partition_bytes_frac']
          < a0['cross_partition_bytes_frac'])


# -- online rebalance -------------------------------------------------------

def test_rebalance_plan_moves_hot_range_to_top_requester():
  m = np.ones((P, P))
  m[:, 3] = 40.0                             # range 3: hot everywhere
  m[5, 3] = 90.0                             # device 5 asks the most
  plan = rebalance_plan({'bytes_matrix': m})
  assert plan, 'the hot range must move'
  mv = plan[0]                               # hottest range first
  assert (mv['range'], mv['frm'], mv['to']) == (3, 3, 5)
  assert mv['demand'] == m[:, 3].sum()
  # every move leaves its identity owner, and no destination is
  # reused (one extra lane per device)
  assert all(p['range'] == p['frm'] for p in plan)
  dests = [p['to'] for p in plan]
  assert len(dests) == len(set(dests))
  assert rebalance_plan({'bytes_matrix': m}, max_moves=1) == [mv]
  # knobs and edges of the ladder
  assert rebalance_plan({'bytes_matrix': m}, max_moves=0) == []
  assert rebalance_plan({'bytes_matrix': m}, overload_factor=50.0) == []
  assert rebalance_plan({'bytes_matrix': None}) == []
  assert rebalance_plan({}) == []


def test_rebalance_plan_prefers_sketch_mass():
  """An attached sketch's exact decayed range histogram supersedes the
  matrix column mass for demand ranking."""
  class _Flat:
    range_mass = np.ones(P)

  class _Skewed:
    range_mass = np.r_[np.ones(3), 50.0, np.ones(P - 4)]

  m = np.ones((P, P))
  m[:, 3] = 40.0
  # flat sketch demand: nobody is overloaded, the hot column ignored
  assert rebalance_plan({'bytes_matrix': m}, sketch=_Flat()) == []
  # skewed sketch demand drives the move even with the same matrix
  plan = rebalance_plan({'bytes_matrix': m}, sketch=_Skewed())
  assert plan and plan[0]['range'] == 3
  assert plan[0]['demand'] == 50.0           # the sketch's mass, not
  #                                          # the matrix column sum


def test_mid_epoch_rebalance_byte_identical(tmp_path):
  """The online arm end-to-end: measured attribution -> plan -> fenced
  execution MID-EPOCH, with the epoch byte-identical to the
  undisturbed run and ownership actually moved."""
  rows, cols = _hub_edges()
  feat = _feat()
  # explicit skew: partition 3 owns every hub => measured demand
  # concentrates on range 3 and the planner must move it
  pb = (np.arange(N) % P).astype(np.int32)
  pb[:20] = 3

  def build():
    return DistDataset.from_full_graph(P, rows, cols, feat,
                                       num_nodes=N, node_pb=pb)

  ref = list(_loader(build()))
  ds = build()
  loader = _loader(ds)
  it = iter(loader)
  got = [next(it) for _ in range(3)]
  att = loader.sampler.attribution_stats(tick_metrics=False)
  plan = rebalance_plan(att, book=ds.partition_book)
  assert plan and plan[0]['range'] == 3      # the hot range moves
  infos = execute_rebalance(ds, plan,
                            store=ShardStore(tmp_path / 'shards'))
  got.extend(it)
  _assert_batches_equal(ref, got, 'mid-epoch rebalance')
  assert len(infos) == len(plan)
  book = ds.partition_book
  assert book.version == len(plan)           # one bump per move
  assert int(book.view().owners[3]) == plan[0]['to']
  assert book.transfers()[0]['range'] == 3
  assert book.adoptions() == []              # planned, not a crash
  # measurable post-rebalance drop: range 3's heaviest requester now
  # OWNS it, so its column flips local under the owner-aware mask
  att2 = loader.sampler.attribution_stats(tick_metrics=False)
  assert (att2['cross_partition_bytes_frac']
          < att['cross_partition_bytes_frac'])


# -- fused tree path: both attribution matrices tick ------------------------

def test_fused_tree_tiered_ticks_both_matrices():
  """The dead-feature-counter regression: a tiered FusedDistTreeEpoch
  must populate the FEATURE attribution matrix, not only the frontier
  one."""
  import jax
  import optax
  from graphlearn_tpu.models import TreeSAGE
  from graphlearn_tpu.parallel import FusedDistTreeEpoch
  n = 96
  rng = np.random.default_rng(0)
  rows = np.repeat(np.arange(n), 6)
  cols = rng.integers(0, n, 6 * n)
  feat = (np.arange(n, dtype=np.float32)[:, None]
          * np.ones((1, 4), np.float32))
  lab = (np.arange(n) % 5).astype(np.int32)
  ds = DistDataset.from_full_graph(P, rows, cols, feat, lab,
                                   num_nodes=n, split_ratio=0.4)
  model = TreeSAGE(hidden_features=8, out_features=5, num_layers=2)
  fused = FusedDistTreeEpoch(ds, [3, 2], np.arange(n), model,
                             optax.adam(1e-2), batch_size=8,
                             mesh=make_mesh(P), shuffle=True, seed=0)
  state = fused.init_state(jax.random.key(0))
  state, stats = fused.run(state)
  assert np.isfinite(np.asarray(stats.losses)).all()
  fr, ft = fused.sampler.attribution_matrices()
  assert fr.sum() > 0, 'frontier attribution dead on the fused path'
  assert ft.sum() > 0, 'feature attribution dead on the fused path'
  # off-diagonal traffic exists on both planes (P=8 random placement)
  assert (fr.sum() - np.trace(fr)) > 0
  assert (ft.sum() - np.trace(ft)) > 0


# -- hetero: joint-union partitioning ---------------------------------------

def test_hetero_locality_smoke():
  """`DistHeteroDataset.from_full_graph(partitioner='locality')`
  partitions the disjoint union; per-type layouts stay consistent and
  the hetero sampler runs on the mesh."""
  from graphlearn_tpu.parallel import (DistHeteroDataset,
                                       DistHeteroNeighborSampler)
  num_parts = 4
  nu, ni = 32, 16
  urow = np.repeat(np.arange(nu), 2)
  icol = np.stack([np.arange(nu) % ni, (np.arange(nu) + 1) % ni],
                  1).reshape(-1)
  et = ('user', 'clicks', 'item')
  et_rev = ('item', 'rev_clicks', 'user')
  ufeat = np.tile(np.arange(nu, dtype=np.float32)[:, None], (1, 4))
  ifeat = np.tile(np.arange(ni, dtype=np.float32)[:, None], (1, 4))
  ds = DistHeteroDataset.from_full_graph(
      num_parts, {et: (urow, icol), et_rev: (icol, urow)},
      node_feat_dict={'user': ufeat, 'item': ifeat},
      num_nodes_dict={'user': nu, 'item': ni},
      partitioner='locality')
  assert ds.num_nodes_dict() == {'user': nu, 'item': ni}
  # the balance cap holds on the UNION of both types
  union_sizes = (np.diff(ds.bounds['user'])
                 + np.diff(ds.bounds['item']))
  cap = int(np.ceil(1.05 * (nu + ni) / num_parts))
  assert union_sizes.max() <= cap
  sampler = DistHeteroNeighborSampler(ds, [2, 2],
                                      mesh=make_mesh(num_parts),
                                      seed=0)
  seeds = ds.old2new['user'][np.arange(nu).reshape(num_parts, -1)]
  out = sampler.sample_from_nodes('user', seeds)
  # every emitted item id decodes to a real node via its feature row
  inodes = np.asarray(out['node']['item'])
  valid = inodes >= 0
  assert valid.any()
  i_old = ds.new2old['item']
  assert (i_old[inodes[valid]] < ni).all()
