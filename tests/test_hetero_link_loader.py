"""Hetero link-prediction loader tests (bipartite + same-type).

Mirrors the reference's hetero link path (`sampler/neighbor_sampler.py:
255-381` hetero branch; exercised by
`examples/hetero/bipartite_sage_unsup.py`): positives resolve to real
edges through the per-type tables, binary negatives are strict
non-edges drawn in the dst type's id space, triplet metadata indexes
the right tables.
"""
import numpy as np
import pytest

from graphlearn_tpu.data import Dataset
from graphlearn_tpu.loader import LinkNeighborLoader
from graphlearn_tpu.sampler import NegativeSampling
from graphlearn_tpu.typing import reverse_edge_type

U, I = 'user', 'item'
ET = (U, 'clicks', I)
ET_REV = (I, 'rev_clicks', U)


def _bipartite(nu=30, ni=12, deg=3, seed=0):
  rng = np.random.default_rng(seed)
  rows = np.repeat(np.arange(nu), deg)
  cols = rng.integers(0, ni, nu * deg)
  ufeat = np.tile(np.arange(nu, dtype=np.float32)[:, None], (1, 4))
  ifeat = np.tile(np.arange(ni, dtype=np.float32)[:, None], (1, 4))
  ds = (Dataset()
        .init_graph({ET: (rows, cols), ET_REV: (cols, rows)},
                    layout='COO', num_nodes={U: nu, I: ni})
        .init_node_features({U: ufeat, I: ifeat}, split_ratio=1.0))
  return ds, rows, cols


def test_bipartite_binary_negatives():
  ds, rows, cols = _bipartite()
  existing = set(zip(rows.tolist(), cols.tolist()))
  loader = LinkNeighborLoader(
      ds, [2, 2], (ET, (rows[:16], cols[:16])),
      neg_sampling=NegativeSampling('binary', 1.0),
      batch_size=8, seed=0)
  batches = 0
  for batch in loader:
    batches += 1
    eli = np.asarray(batch.metadata['edge_label_index'])
    label = np.asarray(batch.metadata['edge_label'])
    mask = np.asarray(batch.metadata['edge_label_mask'])
    unodes = np.asarray(batch.node_dict[U])
    inodes = np.asarray(batch.node_dict[I])
    assert eli.shape == (2, 16)
    xu = np.asarray(batch.x_dict[U])
    for j in range(16):
      if not mask[j]:
        continue
      u = int(unodes[eli[0, j]])      # src table
      v = int(inodes[eli[1, j]])      # dst table
      assert 0 <= v < 12              # negatives drawn in ITEM space
      if label[j] >= 1:
        assert (u, v) in existing
      else:
        assert (u, v) not in existing
      # features prove table identity: value == id
      np.testing.assert_array_equal(xu[eli[0, j], 0], float(u))
  assert batches == 2


def test_bipartite_triplet_metadata():
  ds, rows, cols = _bipartite()
  existing = set(zip(rows.tolist(), cols.tolist()))
  loader = LinkNeighborLoader(
      ds, [2], (ET, (rows[:10], cols[:10])),
      neg_sampling=NegativeSampling('triplet', 2),
      batch_size=10, seed=0)
  batch = next(iter(loader))
  unodes = np.asarray(batch.node_dict[U])
  inodes = np.asarray(batch.node_dict[I])
  src = np.asarray(batch.metadata['src_index'])
  dpos = np.asarray(batch.metadata['dst_pos_index'])
  dneg = np.asarray(batch.metadata['dst_neg_index'])
  assert dneg.shape == (10, 2)
  for j in range(10):
    u = int(unodes[src[j]])
    v = int(inodes[dpos[j]])
    assert (u, v) in existing
    for t in range(2):
      w = int(inodes[dneg[j, t]])
      assert 0 <= w < 12
      # strict rejection (5 trials on a sparse graph: reliably non-edge)
      assert (u, w) not in existing


def test_same_type_hetero_link():
  """Link sampling where src and dst types coincide (cites-style)."""
  P = 'paper'
  E = (P, 'cites', P)
  rng = np.random.default_rng(0)
  n = 24
  rows = np.repeat(np.arange(n), 2)
  cols = rng.integers(0, n, n * 2)
  feats = np.tile(np.arange(n, dtype=np.float32)[:, None], (1, 4))
  ds = (Dataset()
        .init_graph({E: (rows, cols)}, layout='COO', num_nodes={E: n})
        .init_node_features({P: feats}, split_ratio=1.0))
  existing = set(zip(rows.tolist(), cols.tolist()))
  loader = LinkNeighborLoader(
      ds, [2], (E, (rows[:8], cols[:8])),
      neg_sampling=NegativeSampling('binary', 1.0),
      batch_size=8, seed=0)
  batch = next(iter(loader))
  eli = np.asarray(batch.metadata['edge_label_index'])
  label = np.asarray(batch.metadata['edge_label'])
  nodes = np.asarray(batch.node_dict[P])
  for j in range(eli.shape[1]):
    u, v = int(nodes[eli[0, j]]), int(nodes[eli[1, j]])
    if label[j] >= 1:
      assert (u, v) in existing


def test_edges_emitted_under_reversed_types():
  ds, rows, cols = _bipartite()
  loader = LinkNeighborLoader(
      ds, [2, 2], (ET, (rows[:8], cols[:8])),
      neg_sampling=NegativeSampling('binary', 1.0),
      batch_size=8, seed=0)
  batch = next(iter(loader))
  # sampling over {ET, ET_REV} emits under their reversals
  assert set(batch.edge_index_dict) <= {reverse_edge_type(ET),
                                        reverse_edge_type(ET_REV)}
  # every emitted edge resolves to a real interaction
  existing = set(zip(rows.tolist(), cols.tolist()))
  rev = reverse_edge_type(ET)
  if rev in batch.edge_index_dict:
    ei = np.asarray(batch.edge_index_dict[rev])
    em = np.asarray(batch.edge_mask_dict[rev])
    unodes = np.asarray(batch.node_dict[U])
    inodes = np.asarray(batch.node_dict[I])
    for j in np.nonzero(em)[0]:
      # transposed emission: row = discovered item, col = seed user
      v = int(inodes[ei[0, j]])
      u = int(unodes[ei[1, j]])
      assert (u, v) in existing


def test_num_nodes_forwarded_for_negative_space():
  """Zero-click items (never appearing in edges) must stay reachable
  as negatives: the loader forwards feature-store row counts, not
  max-observed-id+1."""
  nu, ni = 10, 20
  rows = np.arange(nu)
  cols = rows % 8          # items 8..19 never clicked
  ufeat = np.ones((nu, 4), np.float32)
  # deliberately NO item features: the count must come from the
  # explicit init_graph num_nodes, not the feature store
  ds = (Dataset()
        .init_graph({ET: (rows, cols)}, layout='COO',
                    num_nodes={U: nu, I: ni})
        .init_node_features({U: ufeat}, split_ratio=1.0))
  loader = LinkNeighborLoader(
      ds, [2], (ET, (rows, cols)),
      neg_sampling=NegativeSampling('binary', 1.0), batch_size=10, seed=0)
  assert loader.sampler._num_nodes[I] == ni
