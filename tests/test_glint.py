"""glint framework tests (ISSUE 11): a positive + negative inline
fixture per pass, suppression and baseline round-trips, the CLI exit
contract, and the tier-1 whole-tree run (zero unsuppressed findings
over the default roots — the machine-checked form of the data-plane
invariants the repo used to enforce by review).
"""
import json
import subprocess
import sys
import textwrap
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from tools.glint import all_passes  # noqa: E402
from tools.glint.driver import (Run, check_source, load_baseline,  # noqa: E402
                                main, run_glint, write_baseline)


def _src(s: str) -> str:
  return textwrap.dedent(s).lstrip()


def _live(findings):
  return [f for f in findings if f.live]


# -- framework -----------------------------------------------------------------
def test_at_least_six_passes_registered():
  table = all_passes()
  assert len(table) >= 6
  for expected in ('host-sync', 'rng-discipline', 'guarded-by',
                   'monotonic-clock', 'env-knob-drift', 'event-schema',
                   'metric-name'):
    assert expected in table, f'missing pass {expected}'
  for name, cls in table.items():
    assert cls.description, f'{name} has no description'


def test_unknown_rule_is_an_error():
  with pytest.raises(ValueError, match='unknown glint rule'):
    run_glint(rules=['no-such-pass'])


# -- host-sync -----------------------------------------------------------------
HOT_SYNC_BAD = _src('''
    import jax
    import numpy as np
    from graphlearn_tpu.loader.fused import _uncached_jit

    def _epoch_fn(state, seeds):
      def body(carry, s):
        carry = carry + s.sum().item()       # sync inside scan body
        return carry, jax.device_get(s)      # sync inside scan body
      out, ys = jax.lax.scan(body, state, seeds)
      np.asarray(out)                        # sync inside jitted fn
      return out

    compiled = _uncached_jit(_epoch_fn)
''')

HOT_SYNC_TRANSITIVE = _src('''
    import jax

    def _helper(x):
      return x.block_until_ready()           # hot via transitive call

    def _epoch_fn(state):
      return _helper(state)

    compiled = jax.jit(_epoch_fn)
''')

HOT_SYNC_OK = _src('''
    import jax
    import jax.numpy as jnp
    import numpy as np
    from graphlearn_tpu.loader.fused import _uncached_jit

    def _epoch_fn(state, seeds):
      def body(carry, s):
        return carry + jnp.sum(s), s
      return jax.lax.scan(body, state, seeds)

    compiled = _uncached_jit(_epoch_fn)

    def host_driver(batch):
      # host-side code may sync freely — it is not in the hot set
      return np.asarray(jax.device_get(batch)).item()
''')


def test_host_sync_positive():
  found = _live(check_source(HOT_SYNC_BAD, 'host-sync'))
  assert len(found) == 3, [f.render() for f in found]
  assert any('.item()' in f.message for f in found)
  assert any('device_get' in f.message for f in found)
  assert any('asarray' in f.message for f in found)


def test_host_sync_transitive_closure():
  found = _live(check_source(HOT_SYNC_TRANSITIVE, 'host-sync'))
  assert len(found) == 1 and 'block_until_ready' in found[0].message


def test_host_sync_negative():
  assert not _live(check_source(HOT_SYNC_OK, 'host-sync'))


def test_host_sync_fori_and_while_bodies():
  """fori_loop/while_loop take their traced callables at positions
  2 and 0/1 — not args[0] like scan (a review catch: the args[0]
  assumption left those bodies unenforced)."""
  src = _src('''
      import jax

      def fbody(i, carry):
        return carry + carry.sum().item()

      def cond(c):
        return bool(c[0])

      def wbody(c):
        return jax.device_get(c)

      def driver(x):
        y = jax.lax.fori_loop(0, 8, fbody, x)
        return jax.lax.while_loop(cond, wbody, y)

      compiled = jax.jit(driver)
  ''')
  found = _live(check_source(src, 'host-sync'))
  assert len(found) == 3, [f.render() for f in found]
  assert any('.item()' in f.message for f in found)
  assert any('bool()' in f.message for f in found)
  assert any('device_get' in f.message for f in found)


# -- rng-discipline ------------------------------------------------------------
RNG_BAD = _src('''
    import jax
    import numpy as np

    def sample(n):
      idx = np.random.permutation(n)         # module-level RandomState
      for i in range(3):
        k = jax.random.PRNGKey(0)            # same key every iteration
      return idx, k
''')

RNG_OK = _src('''
    import jax
    import numpy as np

    def sample(n, seed):
      rng = np.random.default_rng(seed)
      idx = rng.permutation(n)
      base = jax.random.key(seed)
      for i in range(3):
        k = jax.random.fold_in(base, i)
      return idx, k

    def seeded_key_outside_loop():
      return jax.random.PRNGKey(0)           # fine: not in a loop
''')


def test_rng_positive():
  found = _live(check_source(RNG_BAD, 'rng-discipline'))
  assert len(found) == 2, [f.render() for f in found]
  assert any('np.random.permutation' in f.message for f in found)
  assert any('SAME key' in f.message for f in found)


def test_rng_negative():
  assert not _live(check_source(RNG_OK, 'rng-discipline'))


# -- guarded-by ----------------------------------------------------------------
GUARDED_BAD = _src('''
    import threading

    class Counter:
      def __init__(self):
        self._lock = threading.Lock()
        self.served = 0          # guarded-by: self._lock

      def bump(self):
        self.served += 1         # unguarded access

      def wrong_lock(self):
        with self._other_lock:
          self.served += 1       # wrong lock held
''')

GUARDED_OK = _src('''
    import threading

    class Counter:
      def __init__(self):
        self._lock = threading.Lock()
        self.served = 0          # guarded-by: self._lock

      def bump(self):
        with self._lock:
          self.served += 1

      def _bump_locked(self):
        self.served += 1         # *_locked convention: caller holds it

      def helper(self):
        # glint: holds=self._lock
        return self.served

      def unrelated(self):
        return self._lock        # the lock itself is not guarded
''')


def test_guarded_by_positive():
  found = _live(check_source(GUARDED_BAD, 'guarded-by'))
  assert len(found) == 2, [f.render() for f in found]
  assert all('data race' in f.message for f in found)


def test_guarded_by_negative():
  assert not _live(check_source(GUARDED_OK, 'guarded-by'))


# -- monotonic-clock -----------------------------------------------------------
MONO_BAD = _src('''
    import time

    def wait(budget):
      t0 = time.time()                       # flows into arithmetic
      while time.time() - t0 < budget:
        pass
''')

MONO_OK = _src('''
    import time

    def heartbeat():
      return {'at': round(time.time(), 3)}   # pure wall-clock stamp

    def wait(budget):
      deadline = time.monotonic() + budget
      while time.monotonic() < deadline:
        pass
''')


def test_monotonic_positive():
  found = _live(check_source(MONO_BAD, 'monotonic-clock'))
  assert len(found) == 2, [f.render() for f in found]
  assert all('time.monotonic()' in f.message for f in found)


def test_monotonic_negative():
  assert not _live(check_source(MONO_OK, 'monotonic-clock'))


def test_monotonic_sees_import_alias():
  src = _src('''
      import time as _time

      def wait(deadline):
        return _time.time() < deadline
  ''')
  assert len(_live(check_source(src, 'monotonic-clock'))) == 1


# -- env-knob-drift ------------------------------------------------------------
def test_env_knob_positive_and_negative(tmp_path):
  readme = tmp_path / 'README.md'
  readme.write_text('| `GLT_DOCUMENTED` | 1 | a knob |\n')
  run = Run(repo=tmp_path, readme_path=readme)
  src = _src('''
      import os
      a = os.environ.get('GLT_DOCUMENTED', '1')
      b = os.environ.get('GLT_SECRET_KNOB')
  ''')
  found = _live(check_source(src, 'env-knob-drift', run=run))
  assert len(found) == 1 and 'GLT_SECRET_KNOB' in found[0].message
  readme.write_text(readme.read_text()
                    + '| `GLT_SECRET_KNOB` | off | now documented |\n')
  assert not _live(check_source(src, 'env-knob-drift', run=run))


def test_check_env_knobs_shim_still_works():
  """The documented standalone invocation and the helper API
  `tests/test_env_knobs.py` imports must keep working."""
  sys.path.insert(0, str(REPO / 'tools'))
  try:
    import check_env_knobs as shim
  finally:
    sys.path.pop(0)
  refs = shim.knob_references()
  assert 'GLT_FAULT_PLAN' in refs
  assert not shim.undocumented()
  assert shim.main() == 0


# -- event-schema --------------------------------------------------------------
def _schema_fixture(tmp_path, kinds, spans) -> Run:
  schema = tmp_path / 'schema.py'
  fmt = lambda d: '{' + ', '.join(
      f'{k!r}: {v!r}' for k, v in d.items()) + '}'
  schema.write_text(f'EVENT_KINDS = {fmt(kinds)}\n'
                    f'SPAN_NAMES = {fmt(spans)}\n')
  return Run(repo=tmp_path, schema_path=schema, pkg_prefix='pkg')


def test_event_schema_positive(tmp_path):
  run = _schema_fixture(
      tmp_path,
      kinds={'known.kind': 'emitter: field summary',
             'stale.kind': 'emitter: nothing emits this anymore',
             'undocumented.kind': 'short'},
      spans={'known.span': 'emitter: span summary'})
  src = _src('''
      def go(recorder, span):
        recorder.emit('known.kind', x=1)
        recorder.emit('undocumented.kind')
        recorder.emit('rogue.kind', y=2)
        with span('known.span'):
          pass
        with span('rogue.span'):
          pass
  ''')
  found = _live(check_source(src, 'event-schema', rel='pkg/mod.py',
                             run=run))
  msgs = '\n'.join(f.render() for f in found)
  assert len(found) == 4, msgs
  assert "emit('rogue.kind')" in msgs
  assert "'stale.kind'" in msgs and 'no remaining' in msgs
  assert "'undocumented.kind'" in msgs and 'consumer contract' in msgs
  assert "'rogue.span'" in msgs


def test_event_schema_negative(tmp_path):
  run = _schema_fixture(tmp_path,
                        kinds={'known.kind': 'emitter: field summary'},
                        spans={})
  src = "def go(r):\n  r.emit('known.kind', x=1)\n"
  assert not _live(check_source(src, 'event-schema', rel='pkg/mod.py',
                                run=run))


def test_event_schema_ignores_non_package_files(tmp_path):
  run = _schema_fixture(tmp_path, kinds={}, spans={})
  src = "def go(r):\n  r.emit('adhoc.test.kind', x=1)\n"
  assert not _live(check_source(src, 'event-schema',
                                rel='tests/mod.py', run=run))


# -- metric-name ---------------------------------------------------------------
def _metric_fixture(tmp_path, names) -> Run:
  schema = tmp_path / 'schema.py'
  table = '{' + ', '.join(f'{k!r}: {v!r}'
                          for k, v in names.items()) + '}'
  schema.write_text(f'METRIC_NAMES = {table}\n')
  return Run(repo=tmp_path, schema_path=schema, pkg_prefix='pkg')


def test_metric_name_positive(tmp_path):
  run = _metric_fixture(tmp_path, {
      'serving.good_total': 'counter: requests served by the tier',
      'serving.depth': 'gauge: queue depth at scrape time',
      'stale.metric_total': 'counter: nothing registers this anymore',
      'bad.doc_total': 'short',
  })
  src = _src('''
      def wire(live):
        live.counter('serving.good_total')
        live.counter('rogue.metric_total')
        live.counter('NotSnake.Dot')
        live.histogram('serving.depth')
        live.gauge('bad.doc_total')
  ''')
  found = _live(check_source(src, 'metric-name', rel='pkg/mod.py',
                             run=run))
  msgs = '\n'.join(f.render() for f in found)
  # rogue (undeclared), NotSnake.Dot (shape + undeclared), depth
  # registered as histogram but declared gauge, bad.doc_total's
  # declaration malformed, stale.metric_total unregistered
  assert "counter('rogue.metric_total')" in msgs
  assert 'not a snake.dot' in msgs
  assert "declares it 'gauge'" in msgs
  assert "'stale.metric_total'" in msgs and 'no remaining' in msgs
  assert "'bad.doc_total'" in msgs and 'scrape contract' in msgs
  assert len(found) == 6, msgs


def test_metric_name_negative(tmp_path):
  run = _metric_fixture(tmp_path, {
      'serving.good_total': 'counter: requests served by the tier',
      'serving.lat': 'histogram: request latency in log2 buckets',
  })
  src = _src('''
      def wire(live, cap):
        live.counter('serving.good_total', labels={'reason': 'x'})
        live.histogram('serving.lat', labels={'bucket': cap})
  ''')
  assert not _live(check_source(src, 'metric-name', rel='pkg/mod.py',
                                run=run))


def test_metric_name_ignores_non_package_files(tmp_path):
  run = _metric_fixture(tmp_path, {})
  src = "def go(reg):\n  reg.counter('adhoc.test_total')\n"
  assert not _live(check_source(src, 'metric-name',
                                rel='tests/mod.py', run=run))


# -- metric-label-cardinality --------------------------------------------------
def _label_fixture(tmp_path, labels) -> Run:
  schema = tmp_path / 'schema.py'
  table = '{' + ', '.join(f'{k!r}: {v!r}'
                          for k, v in labels.items()) + '}'
  schema.write_text(f'METRIC_LABELS = {table}\n')
  return Run(repo=tmp_path, schema_path=schema, pkg_prefix='pkg')


def test_metric_label_positive(tmp_path):
  run = _label_fixture(tmp_path, {
      'stale_key': 'nothing labels with this anymore',
      'short_doc': 'tiny',
  })
  src = _src('''
      mystery = compute_labels()

      def wire(live, key):
        live.counter('a.b_total', labels={'rogue': 'x'})
        live.counter('a.c_total', labels={key: 'x'})
        live.gauge('a.d', labels=mystery)
        live.counter('a.e_total', labels={'short_doc': 'x'})
  ''')
  found = _live(check_source(src, 'metric-label-cardinality',
                             rel='pkg/mod.py', run=run))
  msgs = '\n'.join(f.render() for f in found)
  # rogue undeclared, {key: ...} non-constant key, `mystery` neither
  # a param nor a unique dict assignment, stale_key unregistered,
  # short_doc's doc too short to state the bounded domain
  assert "'rogue'" in msgs and 'not declared' in msgs
  assert 'non-string-constant' in msgs
  assert "'mystery'" in msgs and 'unique dict literal' in msgs
  assert "'stale_key'" in msgs and 'no remaining' in msgs
  assert "'short_doc'" in msgs and 'cardinality contract' in msgs
  assert len(found) == 5, msgs


def test_metric_label_negative(tmp_path):
  run = _label_fixture(tmp_path, {
      'scope': 'cache scope: one of four fixed cache flavors',
      'bucket': 'bucket capacity: bounded by the serving ladder',
      'window': 'SLO window: bounded by the configured tuple',
  })
  # the four clean conventions: literal dict (dynamic VALUE is fine),
  # positional dict, a forwarding helper whose labels is a parameter,
  # and a bare name bound once to a dict literal in the same file
  src = _src('''
      def helper(live, name, labels, fn):
        live.gauge(name, labels=labels, fn=fn)

      def wire(live, cap, scope):
        live.histogram('a.lat', labels={'bucket': cap})
        live.gauge('a.burn', {'window': '60s'}, lambda: 1.0)
        live.counter('a.plain_total', labels=None)
        labels = {'scope': scope}
        live.counter('a.hits_total', labels=labels)
        helper(live, 'a.g', {'window': '300s'}, lambda: 2.0)
  ''')
  assert not _live(check_source(src, 'metric-label-cardinality',
                                rel='pkg/mod.py', run=run))


def test_metric_label_forbidden_trace_keys(tmp_path):
  # trace_id/span_id are forbidden regardless of schema declarations
  # — a per-request id label mints one series per request, the exact
  # leak exemplars exist to avoid (ISSUE 17)
  run = _label_fixture(tmp_path, {})
  src = _src('''
      def wire(live, tid, sid):
        live.histogram('a.lat', labels={'trace_id': tid})
        live.counter('a.spans_total', labels={'span_id': sid})
  ''')
  found = _live(check_source(src, 'metric-label-cardinality',
                             rel='pkg/mod.py', run=run))
  msgs = '\n'.join(f.render() for f in found)
  assert "'trace_id'" in msgs and 'forbidden label key' in msgs
  assert "'span_id'" in msgs and 'exemplars' in msgs
  assert len(found) == 2, msgs


def test_metric_label_forbidden_keys_negative(tmp_path):
  # exemplar plumbing that never makes trace_id a label KEY is clean:
  # the id rides `observe(..., exemplar=tid)`, not the series space
  run = _label_fixture(tmp_path, {
      'bucket': 'bucket capacity: bounded by the serving ladder',
  })
  src = _src('''
      def wire(live, cap, tid):
        h = live.histogram('a.lat', labels={'bucket': cap})
        h.observe(0.25, exemplar=tid)
  ''')
  assert not _live(check_source(src, 'metric-label-cardinality',
                                rel='pkg/mod.py', run=run))


def test_metric_label_ignores_non_package_files(tmp_path):
  run = _label_fixture(tmp_path, {})
  src = "def go(reg):\n  reg.counter('x.y_total', labels={'z': 1})\n"
  assert not _live(check_source(src, 'metric-label-cardinality',
                                rel='tests/mod.py', run=run))


# -- suppressions --------------------------------------------------------------
def test_inline_suppression_trailing_and_standalone():
  src = _src('''
      import time

      def wait(budget):
        t0 = time.time()  # glint: disable=monotonic-clock
        # glint: disable=monotonic-clock
        while time.time() - t0 < budget:
          pass
  ''')
  found = check_source(src, 'monotonic-clock')
  assert len(found) == 2
  assert all(f.suppressed for f in found), [f.render() for f in found]
  assert not _live(found)


def test_suppression_is_rule_specific():
  src = _src('''
      import time

      def wait(budget):
        t0 = time.time()  # glint: disable=some-other-rule
        return time.time() - t0 < budget
  ''')
  assert len(_live(check_source(src, 'monotonic-clock'))) == 2


# -- baseline ------------------------------------------------------------------
def _violation_tree(tmp_path) -> Run:
  mod = tmp_path / 'pkg'
  mod.mkdir()
  (mod / 'clock.py').write_text(_src('''
      import time

      def wait(budget):
        t0 = time.time()
        return time.time() - t0 < budget
  '''))
  readme = tmp_path / 'README.md'
  readme.write_text('no knobs\n')
  schema = tmp_path / 'schema.py'
  schema.write_text('EVENT_KINDS = {}\nSPAN_NAMES = {}\n')
  return Run(repo=tmp_path, readme_path=readme, schema_path=schema,
             pkg_prefix='pkg')


def test_baseline_round_trip(tmp_path):
  run = _violation_tree(tmp_path)
  findings = run_glint(paths=['pkg'], run=run)
  assert len(_live(findings)) == 2
  bl = tmp_path / 'baseline.json'
  write_baseline(bl, findings)
  assert len(load_baseline(bl)) == 2
  again = run_glint(paths=['pkg'], run=run, baseline=bl)
  assert not _live(again)
  assert all(f.baselined for f in again)


def test_baseline_is_a_multiset(tmp_path):
  """One grandfathered instance must not absolve a SECOND copy of the
  same pattern added later."""
  run = _violation_tree(tmp_path)
  bl = tmp_path / 'baseline.json'
  write_baseline(bl, run_glint(paths=['pkg'], run=run))
  src = (tmp_path / 'pkg' / 'clock.py').read_text()
  (tmp_path / 'pkg' / 'clock.py').write_text(
      src + '\n\ndef wait2(budget):\n  t0 = time.time()\n'
            '  return time.time() - t0 < budget\n')
  again = run_glint(paths=['pkg'], run=run, baseline=bl)
  assert len(_live(again)) == 2, [f.render() for f in again]


def test_baseline_survives_line_shift(tmp_path):
  run = _violation_tree(tmp_path)
  bl = tmp_path / 'baseline.json'
  write_baseline(bl, run_glint(paths=['pkg'], run=run))
  path = tmp_path / 'pkg' / 'clock.py'
  path.write_text('# a new comment shifting every line\n'
                  + path.read_text())
  again = run_glint(paths=['pkg'], run=run, baseline=bl)
  assert not _live(again)


# -- CLI -----------------------------------------------------------------------
def test_cli_exit_codes(tmp_path, capsys):
  run_dir = _violation_tree(tmp_path)
  del run_dir  # only the tree is needed; CLI builds its own Run
  bad = str(tmp_path / 'pkg' / 'clock.py')
  bl = tmp_path / 'bl.json'
  assert main([bad, '--baseline', str(bl)]) == 1
  # --write-baseline refuses a filtered scope (explicit paths or
  # --rules): a subset run would silently drop every grandfathered
  # entry outside the filter
  assert main([bad, '--baseline', str(bl), '--write-baseline']) == 2
  assert main(['--rules', 'monotonic-clock', '--write-baseline',
               '--baseline', str(bl)]) == 2
  write_baseline(bl, run_glint(paths=[bad]))
  assert main([bad, '--baseline', str(bl)]) == 0
  assert main([bad, '--baseline', str(bl), '--no-baseline']) == 1
  assert main(['--list-passes']) == 0
  assert main([bad, '--rules', 'nope']) == 2
  out = capsys.readouterr().out
  assert 'monotonic-clock' in out


def test_cli_module_entry_point():
  """`python -m tools.glint` is the single documented entry point —
  pin that it imports and exits 0 on the real tree."""
  proc = subprocess.run(
      [sys.executable, '-m', 'tools.glint', '-q'],
      cwd=REPO, capture_output=True, text=True, timeout=120)
  assert proc.returncode == 0, proc.stdout + proc.stderr


# -- the tier-1 whole-tree run -------------------------------------------------
def test_whole_tree_clean():
  """The acceptance invariant: zero unsuppressed, un-baselined
  findings over graphlearn_tpu/, benchmarks/, bench.py and examples/
  with all >= 6 passes enabled — against the same checked-in baseline
  the CLI honors, so the two documented entry points agree."""
  from tools.glint.driver import DEFAULT_BASELINE
  findings = run_glint(baseline=DEFAULT_BASELINE)
  live = _live(findings)
  assert not live, 'glint findings on the tree:\n' + '\n'.join(
      f.render() for f in live)


def test_whole_tree_is_not_vacuous():
  """Guard the guard: the scan must actually be seeing the tree —
  the fused drivers' hot sets, the guarded-by annotations, and the
  knob vocabulary.  A discovery regression that scanned nothing would
  make test_whole_tree_clean pass vacuously."""
  from tools.glint.driver import DEFAULT_ROOTS, REPO as GREPO, discover
  files = discover(DEFAULT_ROOTS, GREPO)
  rels = {f.relative_to(GREPO).as_posix() for f in files}
  assert len(rels) > 100
  for must in ('graphlearn_tpu/loader/fused.py',
               'graphlearn_tpu/parallel/fused.py',
               'graphlearn_tpu/serving/frontend.py',
               'graphlearn_tpu/distributed/dist_sampling_producer.py',
               'bench.py'):
    assert must in rels
