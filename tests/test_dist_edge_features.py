"""Distributed edge-feature collection — both engines + offline layout.

VERDICT-r1 missing #1: the reference serves edge features through the
same distributed fan-out as node features
(`distributed/dist_feature.py:39-48,122-269`, collation at
`dist_neighbor_sampler.py:600-673`, separate ``edge_feat_pb`` at
`dist_dataset.py:183-193`).  Here: the mesh engine gathers rows by
global eid through `dist_gather_multi` against even range-sharded
tables; the host runtime collates ``efeats`` in the producers.
Provenance trick: edge-feature rows ENCODE the edge id + endpoints, so
every gathered row is checkable arithmetically.
"""
import numpy as np
import pytest

jax = pytest.importorskip('jax')

from graphlearn_tpu.parallel import (DistDataset, DistNeighborLoader,
                                     make_mesh)

N = 64


def _ring():
  rows = np.concatenate([np.arange(N), np.arange(N)])
  cols = np.concatenate([(np.arange(N) + 1) % N, (np.arange(N) + 2) % N])
  e = len(rows)
  # row i encodes (eid, src, dst) — exact provenance
  efeat = np.stack([np.arange(e), rows, cols], 1).astype(np.float32)
  return rows, cols, efeat


def _check_batch_edge_attr(ea, eid, em, rows, cols, stacked=True):
  ps = range(ea.shape[0]) if stacked else [None]
  for p in ps:
    e, i, m = (ea[p], eid[p], em[p]) if stacked else (ea, eid, em)
    assert m.any()
    np.testing.assert_allclose(e[m][:, 0], i[m])
    np.testing.assert_allclose(e[m][:, 1], rows[i[m]])
    np.testing.assert_allclose(e[m][:, 2], cols[i[m]])
    assert (e[~m] == 0).all()


def test_mesh_node_loader_edge_features():
  rows, cols, efeat = _ring()
  feats = np.tile(np.arange(N, dtype=np.float32)[:, None], (1, 4))
  ds = DistDataset.from_full_graph(8, rows, cols, node_feat=feats,
                                   num_nodes=N, edge_feat=efeat)
  loader = DistNeighborLoader(ds, [2, 2], np.arange(N), batch_size=4,
                              shuffle=True, mesh=make_mesh(8),
                              with_edge=True, seed=0)
  n_checked = 0
  for batch in loader:
    _check_batch_edge_attr(np.asarray(batch.edge_attr),
                           np.asarray(batch.edge),
                           np.asarray(batch.edge_mask), rows, cols)
    n_checked += 1
  assert n_checked == len(loader)


def test_mesh_link_loader_edge_features():
  from graphlearn_tpu.parallel import DistLinkNeighborLoader
  rows, cols, efeat = _ring()
  ds = DistDataset.from_full_graph(8, rows, cols, num_nodes=N,
                                   edge_feat=efeat)
  loader = DistLinkNeighborLoader(
      ds, [2], (rows[:32], cols[:32]), neg_sampling='binary',
      batch_size=4, shuffle=True, mesh=make_mesh(8), with_edge=True,
      seed=1)
  batch = next(iter(loader))
  _check_batch_edge_attr(np.asarray(batch.edge_attr),
                         np.asarray(batch.edge),
                         np.asarray(batch.edge_mask), rows, cols)


def test_mesh_hetero_edge_features():
  """Per-etype gathered rows must encode (eid, src, dst) for every
  valid sampled edge of that type, on both sampled edge types."""
  from graphlearn_tpu.parallel import DistHeteroNeighborLoader
  from graphlearn_tpu.parallel.dist_hetero import DistHeteroDataset
  from graphlearn_tpu.typing import reverse_edge_type
  rng = np.random.default_rng(0)
  nu, ni = 24, 16
  et1, et2 = ('u', 'to', 'i'), ('i', 'by', 'u')
  r1 = rng.integers(0, nu, 96)
  c1 = rng.integers(0, ni, 96)
  r2 = rng.integers(0, ni, 80)
  c2 = rng.integers(0, nu, 80)
  ef1 = np.stack([np.arange(96), r1, c1], 1).astype(np.float32)
  ef2 = np.stack([np.arange(80), r2, c2], 1).astype(np.float32)
  ds = DistHeteroDataset.from_full_graph(
      8, {et1: (r1, c1), et2: (r2, c2)},
      num_nodes_dict={'u': nu, 'i': ni},
      edge_feat_dict={et1: ef1, et2: ef2})
  loader = DistHeteroNeighborLoader(
      ds, [2, 2], ('u', np.arange(nu)), batch_size=3, shuffle=True,
      mesh=make_mesh(8), with_edge=True, seed=2)
  ends = {reverse_edge_type(et1): (r1, c1),
          reverse_edge_type(et2): (r2, c2)}
  seen = set()
  for batch in loader:
    for rev, (rr, cc) in ends.items():
      if rev not in batch.edge_attr_dict:
        continue
      ea = np.asarray(batch.edge_attr_dict[rev])
      eid = np.asarray(batch.metadata['edge_dict'][rev])
      em = np.asarray(batch.edge_mask_dict[rev])
      if em.any():
        seen.add(rev)
      _check_batch_edge_attr(ea, eid, em, rr, cc)
  assert seen == set(ends)


def test_mesh_hetero_edge_features_unselected_etype():
  """Edge features for an etype the fanout dict EXCLUDES must be
  ignored, not crash the step (regression: the gather loop indexed
  eids_acc by every dataset efeat etype)."""
  from graphlearn_tpu.parallel import DistHeteroNeighborLoader
  from graphlearn_tpu.parallel.dist_hetero import DistHeteroDataset
  from graphlearn_tpu.typing import reverse_edge_type
  rng = np.random.default_rng(3)
  nu, ni = 24, 16
  et1, et2 = ('u', 'r1', 'i'), ('u', 'r2', 'i')
  r1 = rng.integers(0, nu, 64)
  c1 = rng.integers(0, ni, 64)
  r2 = rng.integers(0, nu, 48)
  c2 = rng.integers(0, ni, 48)
  ds = DistHeteroDataset.from_full_graph(
      8, {et1: (r1, c1), et2: (r2, c2)},
      num_nodes_dict={'u': nu, 'i': ni},
      edge_feat_dict={et1: np.stack([np.arange(64), r1, c1], 1)
                      .astype(np.float32),
                      et2: np.zeros((48, 2), np.float32)})
  loader = DistHeteroNeighborLoader(
      ds, {et1: [2]}, ('u', np.arange(nu)), batch_size=3,
      mesh=make_mesh(8), with_edge=True, seed=4)
  batch = next(iter(loader))
  rev1 = reverse_edge_type(et1)
  assert reverse_edge_type(et2) not in batch.edge_attr_dict
  ea = np.asarray(batch.edge_attr_dict[rev1])
  eid = np.asarray(batch.metadata['edge_dict'][rev1])
  em = np.asarray(batch.edge_mask_dict[rev1])
  _check_batch_edge_attr(ea, eid, em, r1, c1)


def test_partition_roundtrip_edge_features(tmp_path):
  """Offline layout carries edge features; DistDataset + host dataset
  reload them aligned to the ORIGINAL global edge ids."""
  from graphlearn_tpu.partition import RandomPartitioner, load_partition
  from graphlearn_tpu.distributed import HostDataset
  rows, cols, efeat = _ring()
  part = RandomPartitioner(tmp_path, 4, N, (rows, cols),
                           edge_feat=efeat, seed=0)
  part.partition()
  p0 = load_partition(tmp_path, 0)
  assert p0['edge_feat'] is not None
  np.testing.assert_allclose(p0['edge_feat'].feats[:, 0],
                             p0['edge_feat'].ids)
  ds = DistDataset.from_partition_dir(tmp_path)
  assert ds.edge_features is not None
  loader = DistNeighborLoader(ds, [2], np.arange(N), batch_size=4,
                              shuffle=True, mesh=make_mesh(4),
                              with_edge=True, seed=3)
  batch = next(iter(loader))
  _check_batch_edge_attr(np.asarray(batch.edge_attr),
                         np.asarray(batch.edge),
                         np.asarray(batch.edge_mask), rows, cols)
  hds = HostDataset.from_partition_dir(tmp_path, 0)
  assert hds.edge_features is not None
  assert hds.edge_features.shape[0] == len(rows)
  # rows owned by this partition carry their encoded eid; others zero
  owned = p0['edge_feat'].ids
  np.testing.assert_allclose(hds.edge_features[owned][:, 0], owned)


def test_host_runtime_edge_features():
  """Host producers collate efeats; collocated + mp modes, homo."""
  from graphlearn_tpu import native
  if not native.available():
    pytest.skip('native lib unavailable')
  from graphlearn_tpu.distributed import (DistNeighborLoader as HostLoader,
                                          HostDataset,
                                          MpDistSamplingWorkerOptions)
  rows, cols, efeat = _ring()
  ds = HostDataset.from_coo(rows, cols, N, edge_features=efeat)
  for opts in (None, MpDistSamplingWorkerOptions(num_workers=2)):
    loader = HostLoader(ds, [2, 2], np.arange(N), batch_size=8,
                        with_edge=True, to_device=False,
                        worker_options=opts)
    try:
      n = 0
      for batch in loader:
        _check_batch_edge_attr(np.asarray(batch.edge_attr),
                               np.asarray(batch.edge),
                               np.asarray(batch.edge_mask), rows, cols,
                               stacked=False)
        n += 1
      assert n == len(loader)
    finally:
      loader.shutdown()


def test_host_runtime_hetero_edge_features():
  from graphlearn_tpu import native
  if not native.available():
    pytest.skip('native lib unavailable')
  from graphlearn_tpu.distributed import (DistNeighborLoader as HostLoader,
                                          HostHeteroDataset)
  from graphlearn_tpu.typing import reverse_edge_type
  rng = np.random.default_rng(1)
  nu, ni = 24, 16
  et = ('u', 'to', 'i')
  r1 = rng.integers(0, nu, 96)
  c1 = rng.integers(0, ni, 96)
  ef1 = np.stack([np.arange(96), r1, c1], 1).astype(np.float32)
  ds = HostHeteroDataset.from_coo({et: (r1, c1)},
                                  num_nodes_dict={'u': nu, 'i': ni},
                                  edge_features={et: ef1})
  loader = HostLoader(ds, [2], ('u', np.arange(nu)), batch_size=6,
                      with_edge=True, to_device=False)
  rev = reverse_edge_type(et)
  n = 0
  for batch in loader:
    ea = np.asarray(batch.edge_attr_dict[rev])
    eid = np.asarray(batch.metadata['edge_dict'][rev])
    em = np.asarray(batch.edge_mask_dict[rev])
    _check_batch_edge_attr(ea, eid, em, r1, c1, stacked=False)
    n += 1
  assert n == len(loader)
