"""Hetero distributed sampling on the virtual 8-device CPU mesh.

The hetero analog of `test_dist_sampler.py` (SURVEY §4 all-local
pattern): a deterministic bipartite graph sharded per node type,
features encode global ids, correctness asserted arithmetically — the
real collective stack runs.
"""
import numpy as np
import jax
import pytest

from graphlearn_tpu.parallel import (DistHeteroDataset,
                                     DistHeteroNeighborLoader,
                                     DistHeteroNeighborSampler, make_mesh)
from graphlearn_tpu.typing import reverse_edge_type

U, I = 'user', 'item'
ET = (U, 'clicks', I)
ET_REV = (I, 'rev_clicks', U)
NU, NI = 32, 16


def _bipartite_dist(num_parts=4):
  # user u clicks items u%NI and (u+1)%NI; item i rev-links its users
  urow = np.repeat(np.arange(NU), 2)
  icol = np.stack([np.arange(NU) % NI, (np.arange(NU) + 1) % NI],
                  1).reshape(-1)
  ufeat = np.tile(np.arange(NU, dtype=np.float32)[:, None], (1, 4))
  ifeat = np.tile(np.arange(NI, dtype=np.float32)[:, None], (1, 4))
  labels = (np.arange(NU) % 5).astype(np.int32)
  return DistHeteroDataset.from_full_graph(
      num_parts,
      {ET: (urow, icol), ET_REV: (icol, urow)},
      node_feat_dict={U: ufeat, I: ifeat},
      node_label_dict={U: labels},
      num_nodes_dict={U: NU, I: NI}), urow, icol


def test_layout_per_type_bounds():
  ds, urow, icol = _bipartite_dist(4)
  assert ds.num_partitions == 4
  assert ds.num_nodes_dict() == {U: NU, I: NI}
  # every etype's CSR is sharded by its SRC type's bounds
  np.testing.assert_array_equal(ds.graphs[ET].bounds, ds.bounds[U])
  np.testing.assert_array_equal(ds.graphs[ET_REV].bounds, ds.bounds[I])
  # local degrees: every user has 2 clicks
  for p in range(4):
    cnt = ds.bounds[U][p + 1] - ds.bounds[U][p]
    deg = np.diff(ds.graphs[ET].indptr[p])[:cnt]
    np.testing.assert_array_equal(deg, 2)


def test_dist_hetero_sample_edges_correct():
  num_parts = 4
  ds, urow, icol = _bipartite_dist(num_parts)
  mesh = make_mesh(num_parts)
  sampler = DistHeteroNeighborSampler(ds, [2, 2], mesh=mesh, seed=0)
  edge_set = set(zip(urow.tolist(), icol.tolist()))

  seeds_old = np.arange(NU).reshape(num_parts, NU // num_parts)
  seeds = ds.old2new[U][seeds_old]
  out = sampler.sample_from_nodes(U, seeds)

  unodes = np.asarray(out['node'][U])     # [P, cap] relabeled user ids
  inodes = np.asarray(out['node'][I])
  u_old = ds.new2old[U]
  i_old = ds.new2old[I]
  rev = reverse_edge_type(ET)             # item->user emission
  rows = np.asarray(out['row'][rev])
  cols = np.asarray(out['col'][rev])
  checked = 0
  for p in range(num_parts):
    m = rows[p] >= 0
    for r, c in zip(rows[p][m], cols[p][m]):
      item = i_old[int(inodes[p, r])]     # row = discovered item (local)
      user = u_old[int(unodes[p, c])]     # col = seed-side user (local)
      assert (int(user), int(item)) in edge_set
      checked += 1
  assert checked > 50

  # features prove identity: x[U][p, j, 0] == old id of node j
  xu = np.asarray(out['x'][U])
  for p in range(num_parts):
    valid = unodes[p] >= 0
    np.testing.assert_array_equal(
        xu[p, valid, 0], u_old[unodes[p][valid]].astype(np.float32))
  xi = np.asarray(out['x'][I])
  for p in range(num_parts):
    valid = inodes[p] >= 0
    np.testing.assert_array_equal(
        xi[p, valid, 0], i_old[inodes[p][valid]].astype(np.float32))
  # labels collected for the labeled type
  yu = np.asarray(out['y'][U])
  for p in range(num_parts):
    valid = unodes[p] >= 0
    np.testing.assert_array_equal(yu[p, valid],
                                  u_old[unodes[p][valid]] % 5)


def test_dist_hetero_loader_epochs():
  num_parts = 4
  ds, urow, icol = _bipartite_dist(num_parts)
  mesh = make_mesh(num_parts)
  bs = 4
  loader = DistHeteroNeighborLoader(
      ds, [2, 2], (U, np.arange(NU)), batch_size=bs, shuffle=True,
      mesh=mesh, seed=1)
  assert len(loader) == NU // (bs * num_parts)
  for _ in range(2):
    seeds_seen = []
    for batch in loader:
      assert batch.x_dict[U].shape[0] == num_parts
      b = np.asarray(batch.batch_dict[U]).reshape(-1)
      seeds_seen.append(ds.new2old[U][b[b >= 0]])
    np.testing.assert_array_equal(np.sort(np.concatenate(seeds_seen)),
                                  np.arange(NU))


def test_partition_dir_roundtrip(tmp_path):
  """Offline hetero partition layout -> DistHeteroDataset."""
  from graphlearn_tpu.partition import RandomPartitioner
  urow = np.repeat(np.arange(NU), 2)
  icol = np.stack([np.arange(NU) % NI, (np.arange(NU) + 1) % NI],
                  1).reshape(-1)
  ufeat = np.tile(np.arange(NU, dtype=np.float32)[:, None], (1, 4))
  ifeat = np.tile(np.arange(NI, dtype=np.float32)[:, None], (1, 4))
  p = RandomPartitioner(
      tmp_path, 2, {U: NU, I: NI},
      {ET: (urow, icol), ET_REV: (icol, urow)},
      node_feat={U: ufeat, I: ifeat},
      node_label={U: (np.arange(NU) % 3).astype(np.int32)}, seed=0)
  p.partition()
  ds = DistHeteroDataset.from_partition_dir(tmp_path)
  assert ds.num_partitions == 2
  assert ds.num_nodes_dict() == {U: NU, I: NI}
  # feature provenance survives the relabel: row value == old id
  f = ds.node_features[U]
  for part in range(2):
    cnt = ds.bounds[U][part + 1] - ds.bounds[U][part]
    got = f.shards[part, :cnt, 0]
    np.testing.assert_array_equal(
        got, ds.new2old[U][ds.bounds[U][part]:ds.bounds[U][part + 1]]
        .astype(np.float32))
  lab = ds.node_labels[U]
  for part in range(2):
    cnt = ds.bounds[U][part + 1] - ds.bounds[U][part]
    np.testing.assert_array_equal(
        lab[part, :cnt],
        ds.new2old[U][ds.bounds[U][part]:ds.bounds[U][part + 1]] % 3)


def test_hetero_tiered_feature_provenance():
  """split_ratio < 1 tiers EVERY node type's store: HBM shards shrink,
  cold rows come back through the host overlay with correct values,
  telemetry counts the misses (the IGBH-scale lever)."""
  num_parts = 4
  urow = np.repeat(np.arange(NU), 2)
  icol = np.stack([np.arange(NU) % NI, (np.arange(NU) + 1) % NI],
                  1).reshape(-1)
  ufeat = np.tile(np.arange(NU, dtype=np.float32)[:, None], (1, 4))
  ifeat = np.tile(np.arange(NI, dtype=np.float32)[:, None], (1, 4))
  ds = DistHeteroDataset.from_full_graph(
      num_parts,
      {ET: (urow, icol), ET_REV: (icol, urow)},
      node_feat_dict={U: ufeat, I: ifeat},
      node_label_dict={U: (np.arange(NU) % 5).astype(np.int32)},
      num_nodes_dict={U: NU, I: NI}, split_ratio=0.5)
  for nt, n in ((U, NU), (I, NI)):
    nf = ds.node_features[nt]
    assert nf.is_tiered
    assert nf.shards.shape[1] == (n // num_parts + 1) // 2
    assert nf.cold_host.shape[0] == n
  sampler = DistHeteroNeighborSampler(ds, [2, 2],
                                      mesh=make_mesh(num_parts), seed=0)
  seeds = ds.old2new[U][np.arange(NU).reshape(num_parts,
                                              NU // num_parts)]
  out = sampler.sample_from_nodes(U, seeds)
  for nt in (U, I):
    nodes = np.asarray(out['node'][nt])
    x = np.asarray(out['x'][nt])
    for p in range(num_parts):
      m = nodes[p] >= 0
      old = ds.new2old[nt][nodes[p][m]]
      np.testing.assert_allclose(x[p][m][:, 0], old.astype(np.float32))
  stats = sampler.exchange_stats()
  assert stats['dist.feature.cold_misses'] > 0
  # hetero engine has no dynamic cold cache yet: every cold lookup is
  # host-served, so the cache hit rate reads 0 while the hot tier
  # still serves its share
  assert (stats['dist.feature.cold_misses']
          == stats['dist.feature.cold_lookups'])
  assert stats['dist.feature.cache_hit_rate'] == 0.0
  assert 0.0 < stats['dist.feature.hot_hit_rate'] < 1.0


def test_hetero_tiered_link_mode():
  num_parts = 4
  urow = np.repeat(np.arange(NU), 2)
  icol = np.stack([np.arange(NU) % NI, (np.arange(NU) + 1) % NI],
                  1).reshape(-1)
  ufeat = np.tile(np.arange(NU, dtype=np.float32)[:, None], (1, 4))
  ifeat = np.tile(np.arange(NI, dtype=np.float32)[:, None], (1, 4))
  ds = DistHeteroDataset.from_full_graph(
      num_parts, {ET: (urow, icol), ET_REV: (icol, urow)},
      node_feat_dict={U: ufeat, I: ifeat},
      num_nodes_dict={U: NU, I: NI}, split_ratio=0.25)
  sampler = DistHeteroNeighborSampler(ds, [2], mesh=make_mesh(num_parts),
                                      seed=0)
  src = ds.old2new[U][np.arange(8).reshape(num_parts, 2)]
  dst = ds.old2new[I][(np.arange(8) % NI).reshape(num_parts, 2)]
  pairs = np.stack([src, dst], axis=2)
  out = sampler.sample_from_edges(ET, pairs, neg_sampling='binary')
  for nt in (U, I):
    nodes = np.asarray(out['node'][nt])
    x = np.asarray(out['x'][nt])
    for p in range(num_parts):
      m = nodes[p] >= 0
      old = ds.new2old[nt][nodes[p][m]]
      np.testing.assert_allclose(x[p][m][:, 0], old.astype(np.float32))
