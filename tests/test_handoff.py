"""Planned PartitionBook handoff (ISSUE 19): move ownership with a
zero degraded window.

The contract stack: `book.transfer`'s one-bump cutover and typed
refusal ladder (the SEPARATE ``_transfers`` ledger leaves the
crash-adoption ledger shape untouched); the fenced seam ladder of
`parallel.handoff.handoff` — a mid-epoch handoff completes the epoch
byte-identical to the no-handoff run with EXACTLY one book bump; a
chaos kill at any pre-cutover seam unwinds to clean source retention
(book untouched, nothing staged, the epoch still exact); a drain-seam
fault is post-cutover and is absorbed.
"""
import numpy as np
import pytest

from graphlearn_tpu.parallel.dist_data import DistDataset
from graphlearn_tpu.parallel.dist_sampler import DistNeighborLoader
from graphlearn_tpu.parallel.failover import (NoDurableShardError,
                                              ShardStore)
from graphlearn_tpu.parallel.handoff import (SEAMS, HandoffAbortedError,
                                             handoff)
from graphlearn_tpu.parallel.partition_book import (AdoptionRefusedError,
                                                    PartitionBook)
from graphlearn_tpu.testing import chaos

P = 8
N, E = 200, 1200


def _graph(seed=0):
  rng = np.random.default_rng(seed)
  rows = rng.integers(0, N, E)
  cols = rng.integers(0, N, E)
  feat = (np.arange(N)[:, None] + np.zeros((1, 6))).astype(np.float32)
  lab = (np.arange(N) % 4).astype(np.int64)
  return rows, cols, feat, lab


def _dataset(seed=0):
  rows, cols, feat, lab = _graph(seed)
  return DistDataset.from_full_graph(P, rows, cols, feat, lab)


def _loader(ds, **kw):
  kw.setdefault('batch_size', 4)
  kw.setdefault('shuffle', True)
  kw.setdefault('seed', 0)
  return DistNeighborLoader(ds, [3, 2], np.arange(N), **kw)


def _assert_batches_equal(ref, got, what=''):
  assert len(ref) == len(got), f'{what}: {len(got)} != {len(ref)}'
  for i, (a, b) in enumerate(zip(ref, got)):
    assert np.array_equal(np.asarray(a.node), np.asarray(b.node)), \
        f'{what}: node differs at batch {i}'
    assert np.array_equal(np.asarray(a.x), np.asarray(b.x)), \
        f'{what}: x differs at batch {i}'
    assert np.array_equal(np.asarray(a.y), np.asarray(b.y)), \
        f'{what}: y differs at batch {i}'
    assert np.array_equal(np.asarray(a.edge_index),
                          np.asarray(b.edge_index)), \
        f'{what}: edge_index differs at batch {i}'


# -- the cutover primitive: book.transfer -----------------------------------

def test_book_transfer_one_bump_separate_ledger():
  book = PartitionBook(np.arange(P + 1) * 10)
  v0 = book.view()
  v1 = book.transfer(3, 3, 5)
  # RCU: the pinned old view is untouched; ONE version bump total
  assert v0.version == 0 and int(v0.owners[3]) == 3
  assert v1.version == 1 and int(v1.owners[3]) == 5
  assert book.version == 1
  # the planned move records into its OWN ledger — the crash-adoption
  # ledger shape (test-frozen) stays untouched
  assert book.transfers() == [{'range': 3, 'frm': 3, 'to': 5,
                               'version': 1}]
  assert book.adoptions() == []


def test_book_transfer_refusal_ladder():
  book = PartitionBook(np.arange(P + 1))
  # out-of-range / self-handoff refuse before any mutation
  with pytest.raises(AdoptionRefusedError, match='out of range'):
    book.transfer(99, 99, 0)
  with pytest.raises(AdoptionRefusedError, match='itself'):
    book.transfer(3, 5, 5)
  # stale source: the caller's claimed owner must BE the owner
  with pytest.raises(AdoptionRefusedError, match='stale handoff'):
    book.transfer(3, 4, 5)
  # a range already served off-owner cannot move again in v1
  book.adopt(3, 5)
  with pytest.raises(AdoptionRefusedError, match='off-owner'):
    book.transfer(3, 5, 6)
  # the destination must be alive ...
  with pytest.raises(AdoptionRefusedError, match='itself dead'):
    book.transfer(1, 1, 3)
  # ... and must not already carry an extra lane
  with pytest.raises(AdoptionRefusedError, match='already carries'):
    book.transfer(1, 1, 5)
  assert book.version == 1          # refusals never mutated the book
  assert book.transfers() == []


def test_handoff_requires_durable_store(monkeypatch):
  monkeypatch.delenv('GLT_SHARD_DIR', raising=False)
  ds = _dataset()
  with pytest.raises(NoDurableShardError, match='GLT_SHARD_DIR'):
    handoff(ds, 3, 5)
  assert ds.partition_book.version == 0


# -- the fenced seam ladder -------------------------------------------------

def test_mid_epoch_handoff_byte_identical(tmp_path):
  """The tentpole pin: a handoff fired mid-epoch completes the epoch
  byte-identical to the fault-free run, with EXACTLY one book bump
  and one seam event per ladder phase — zero degraded window."""
  from graphlearn_tpu.telemetry.recorder import recorder
  ref = list(_loader(_dataset()))

  ds = _dataset()
  loader = _loader(ds)
  it = iter(loader)
  got = [next(it) for _ in range(3)]
  recorder.enable(None)
  recorder.clear()
  try:
    info = handoff(ds, 3, 5, store=ShardStore(tmp_path / 'shards'))
  finally:
    events = recorder.events('handoff.transfer')
    recorder.disable()
    recorder.clear()
  got.extend(it)

  _assert_batches_equal(ref, got, 'mid-epoch handoff')
  assert info['frm'] == 3 and info['to'] == 5
  assert info['version'] == 1 and info['drain_fault'] is None
  book = ds.partition_book
  assert book.version == 1                     # EXACTLY one bump
  assert int(book.view().owners[3]) == 5
  assert book.transfers() == [{'range': 3, 'frm': 3, 'to': 5,
                               'version': 1}]
  assert book.adoptions() == []                # not a crash adoption
  assert 3 in ds.adopted_shards                # staged shard serves
  assert [e['phase'] for e in events] == list(SEAMS)


@pytest.mark.parametrize('seam', ('snapshot', 'transfer', 'fence',
                                  'cutover'))
def test_pre_cutover_kill_unwinds_to_source(tmp_path, seam):
  """A chaos kill at any seam BEFORE cutover aborts typed with the
  book untouched and nothing staged — and the epoch then completes
  byte-identical on the retained source."""
  ref = list(_loader(_dataset()))
  ds = _dataset()
  loader = _loader(ds)
  it = iter(loader)
  got = [next(it) for _ in range(3)]
  chaos.install(f'handoff.transfer:kill:1:op={seam}')
  try:
    with pytest.raises(HandoffAbortedError) as ei:
      handoff(ds, 3, 5, store=ShardStore(tmp_path / 'shards'))
  finally:
    chaos.uninstall()
  assert ei.value.seam == seam
  book = ds.partition_book
  assert book.version == 0                     # book untouched
  assert int(book.view().owners[3]) == 3       # source retains
  assert book.transfers() == []
  assert not getattr(ds, 'adopted_shards', {})  # nothing staged
  got.extend(it)
  _assert_batches_equal(ref, got, f'{seam}-seam abort')


def test_drain_fault_absorbed(tmp_path):
  """A drain-seam fault is post-cutover: the destination already owns
  the range, so the move STANDS and the fault is recorded, not
  raised."""
  ds = _dataset()
  chaos.install('handoff.transfer:fail:1:op=drain')
  try:
    info = handoff(ds, 3, 5, store=ShardStore(tmp_path / 'shards'))
  finally:
    chaos.uninstall()
  assert info['version'] == 1
  assert 'InjectedFault' in info['drain_fault']
  assert ds.partition_book.version == 1
  assert int(ds.partition_book.view().owners[3]) == 5
