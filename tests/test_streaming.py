"""Streaming graph ingestion (ISSUE 14): WAL durability + torn-tail
recovery, delta-CSR merge byte-identity, exactly-once kill→restart→
replay at every chaos site, version-fenced serve-during-ingest, and
the mesh dispatch-seam fence.

The acceptance pins:
  * kill at any of ``ingest.wal`` / ``ingest.apply`` /
    ``ingest.compact``, restart, and the recovered graph is
    byte-identical to a fault-free run over the same event sequence
    — no edge lost, none applied twice;
  * a serving coalesced run / a sampling dispatch observes exactly
    one ``graph_version`` end to end under concurrent ingest;
  * GNS-off sampling on a quiesced post-ingest graph is
    byte-identical to the same graph loaded statically.
"""
import json
import threading
import time

import jax
import numpy as np
import pytest

from graphlearn_tpu.data import Dataset
from graphlearn_tpu.streaming import (IngestPipeline, StreamingGraph,
                                      WalCorruptionError, WriteAheadLog)
from graphlearn_tpu.telemetry import recorder
from graphlearn_tpu.telemetry.live import live
from graphlearn_tpu.testing import chaos
from graphlearn_tpu.utils.topo import coo_to_csr

N = 64


@pytest.fixture(autouse=True)
def _clean():
  chaos.uninstall()
  recorder.enable(None)
  recorder.clear()
  yield
  chaos.uninstall()
  recorder.clear()
  recorder.disable()


def _base_coo(seed=0, e=3 * N):
  rng = np.random.default_rng(seed)
  return rng.integers(0, N, e), rng.integers(0, N, e)


def _batches(k=8, b=11, seed=1):
  rng = np.random.default_rng(seed)
  return [(rng.integers(0, N, b), rng.integers(0, N, b))
          for _ in range(k)]


def _fresh_stream(device=False):
  rows, cols = _base_coo()
  return StreamingGraph.from_coo(rows, cols, num_nodes=N,
                                 device=device)


# -- WAL ---------------------------------------------------------------------

def test_wal_roundtrip_seqnos_and_counters(tmp_path):
  wal = WriteAheadLog(tmp_path)
  s1 = wal.append([1, 2], [3, 4])
  s2 = wal.append([5], [6])
  assert (s1, s2) == (1, 2)
  recs = list(wal.replay())
  assert [r.seqno for r in recs] == [1, 2]
  np.testing.assert_array_equal(recs[0].src, [1, 2])
  np.testing.assert_array_equal(recs[1].dst, [6])
  assert wal.total_events == 3 and wal.last_seqno == 2
  # replay is seqno-filtered (the idempotence primitive)
  assert [r.seqno for r in wal.replay(after_seqno=1)] == [2]
  # a fresh handle over the same file re-derives everything
  wal2 = WriteAheadLog(tmp_path)
  assert wal2.last_seqno == 2 and wal2.total_events == 3


def test_wal_torn_tail_truncates_to_whole_prefix(tmp_path):
  wal = WriteAheadLog(tmp_path)
  for i in range(3):
    wal.append([i], [i + 1])
  size = wal.stats()['bytes']
  # tear the newest record mid-byte (a kill mid-append)
  with open(wal.path, 'r+b') as f:
    f.truncate(size - 7)
  wal2 = WriteAheadLog(tmp_path)
  assert wal2.truncations == 1
  assert [r.seqno for r in wal2.replay()] == [1, 2]
  assert wal2.last_seqno == 2
  # the file itself was healed: a third open sees no tear
  assert WriteAheadLog(tmp_path).truncations == 0
  evs = recorder.events('ingest.wal_truncate')
  assert evs and evs[0]['dropped_bytes'] > 0


def test_wal_seqnos_and_lifetime_survive_reset(tmp_path):
  wal = WriteAheadLog(tmp_path)
  for i in range(4):
    wal.append([i, i], [i + 1, i + 1])
  wal.reset_to(3)
  assert [r.seqno for r in wal.replay()] == [4]
  assert wal.lifetime_events == 8      # resets never lose the count
  # appends continue the global sequence — no reuse under a snapshot
  assert wal.append([9], [9]) == 5
  wal.reset_to(5)
  assert wal.append([9], [9]) == 6
  assert WriteAheadLog(tmp_path).lifetime_events == 10


def test_wal_foreign_file_refused(tmp_path):
  (tmp_path / 'wal.log').write_bytes(b'NOTAWAL!' + b'\0' * 64)
  with pytest.raises(WalCorruptionError):
    WriteAheadLog(tmp_path)


def test_wal_chaos_fail_leaves_log_unchanged(tmp_path):
  wal = WriteAheadLog(tmp_path)
  wal.append([1], [2])
  chaos.install('ingest.wal:fail:1')
  with pytest.raises(chaos.InjectedFault):
    wal.append([3], [4])
  chaos.uninstall()
  assert wal.last_seqno == 1 and wal.stats()['truncations'] == 0
  assert wal.append([3], [4]) == 2     # the retry appends cleanly


# -- delta-CSR merge ---------------------------------------------------------

def test_merge_matches_static_construction():
  """The quiesced byte-identity pin: after any sequence of applies,
  the published CSR equals `coo_to_csr` over the full event-ordered
  edge list — what the same graph loaded statically would hold."""
  rows, cols = _base_coo()
  sg = StreamingGraph.from_coo(rows, cols, num_nodes=N, device=False)
  all_r, all_c = list(rows), list(cols)
  for r, c in _batches(k=6, b=13):
    sg.apply_events(r, c)
    all_r += list(r)
    all_c += list(c)
  view = sg.pin()
  si, sx, se = coo_to_csr(np.asarray(all_r), np.asarray(all_c), N)
  np.testing.assert_array_equal(view.indptr, si)
  np.testing.assert_array_equal(view.indices, sx)
  np.testing.assert_array_equal(view.edge_ids, se)
  assert view.version == 7             # base + 6 publishes


def test_out_of_range_events_refused():
  sg = _fresh_stream()
  v = sg.version
  with pytest.raises(ValueError):
    sg.apply_events([0], [N])          # dst past the node universe
  with pytest.raises(ValueError):
    sg.apply_events([N + 3], [0])      # src past indptr
  assert sg.version == v               # nothing half-published


def test_rcu_pin_survives_later_publishes():
  sg = _fresh_stream()
  v1 = sg.pin()
  snap = (v1.indptr.copy(), v1.indices.copy())
  for r, c in _batches(k=3):
    sg.apply_events(r, c)
  # the pinned view is frozen — later publishes never mutate it
  np.testing.assert_array_equal(v1.indptr, snap[0])
  np.testing.assert_array_equal(v1.indices, snap[1])
  assert sg.pin().version == v1.version + 3


def test_edge_capacity_grows_by_powers_of_two():
  rows, cols = _base_coo()
  sg = StreamingGraph.from_coo(rows, cols, num_nodes=N,
                               reserve_edges=256, device=True)
  cap0 = sg.edge_capacity
  sg.apply_events(*_batches(k=1, b=5)[0])
  assert sg.edge_capacity == cap0      # same shape: warm consumers stay warm
  big = np.arange(2 * cap0) % N
  sg.apply_events(big, (big + 1) % N)
  assert sg.edge_capacity > cap0
  assert sg.edge_capacity & (sg.edge_capacity - 1) == 0


# -- exactly-once under chaos ------------------------------------------------

def _drive(wal_dir, plan=None, compact_every=3):
  """Run the fixed event sequence through a pipeline, simulating a
  process kill+restart at every fired chaos fault.  A WAL-append
  fault means the client was never acked — it RE-SUBMITS; an
  apply/compact kill means the event is durably logged — replay owns
  it and a resubmit would be a double-apply."""
  stream = _fresh_stream()
  pipe = IngestPipeline(stream, wal_dir=str(wal_dir),
                        compact_every=compact_every)
  if plan:
    chaos.install(plan)
  kills = 0
  try:
    for r, c in _batches():
      try:
        pipe.ingest(r, c)
      except chaos.ChaosKilledError:
        kills += 1
        pipe.close()
        stream = _fresh_stream()
        pipe = IngestPipeline(stream, wal_dir=str(wal_dir),
                              compact_every=compact_every)
      except chaos.InjectedFault:
        kills += 1
        pipe.close()
        stream = _fresh_stream()
        pipe = IngestPipeline(stream, wal_dir=str(wal_dir),
                              compact_every=compact_every)
        pipe.ingest(r, c)            # never acked -> resubmit
  finally:
    chaos.uninstall()
  stats = pipe.stats()
  pipe.close()
  return stream.pin(), kills, stats


@pytest.mark.parametrize('site,action,nth', [
    ('ingest.apply', 'kill', 4),
    ('ingest.compact', 'kill', 2),
    ('ingest.wal', 'truncate', 4),
    ('ingest.wal', 'fail', 3),
])
def test_exactly_once_under_chaos(tmp_path, site, action, nth):
  """THE acceptance pin: kill at any ingestion site, restart, and the
  recovered graph is byte-identical to a fault-free run over the same
  event sequence — no edge lost, none applied twice."""
  ref, _, ref_stats = _drive(tmp_path / 'ref')
  got, kills, stats = _drive(
      tmp_path / 'chaos',
      {'faults': [{'site': site, 'action': action, 'nth': nth}]})
  assert kills == 1
  np.testing.assert_array_equal(got.indptr, ref.indptr)
  np.testing.assert_array_equal(got.indices, ref.indices)
  np.testing.assert_array_equal(got.edge_ids, ref.edge_ids)
  assert stats['applied_events'] == ref_stats['applied_events']


def test_torn_tail_replay_lands_whole_record_prefix(tmp_path):
  """ISSUE 14 satellite: chaos-truncate the newest record mid-byte,
  restart, and replay applies exactly the whole-record prefix — the
  torn batch is NOT half-applied, and resubmitting it lands once."""
  stream = _fresh_stream()
  pipe = IngestPipeline(stream, wal_dir=str(tmp_path),
                        compact_every=0)
  batches = _batches(k=4)
  for r, c in batches[:3]:
    pipe.ingest(r, c)
  chaos.install('ingest.wal:truncate:1')
  with pytest.raises(chaos.InjectedFault):
    pipe.ingest(*batches[3])
  chaos.uninstall()
  pipe.close()
  # "restart": the torn tail must truncate away; replay = batches 0-2
  stream2 = _fresh_stream()
  pipe2 = IngestPipeline(stream2, wal_dir=str(tmp_path),
                         compact_every=0)
  replays = recorder.events('ingest.replay')
  assert replays[-1]['replayed_records'] == 3
  assert pipe2.wal.truncations == 1
  assert stream2.pin().version == 4          # base + 3, nothing half-applied
  # the unacked batch is resubmitted and applies exactly once
  pipe2.ingest(*batches[3])
  ref = _fresh_stream()
  for r, c in batches:
    ref.apply_events(r, c)
  np.testing.assert_array_equal(stream2.pin().indices,
                                ref.pin().indices)
  pipe2.close()


def test_recover_on_live_pipeline_is_idempotent(tmp_path):
  """recover() on a pipeline that already applied batches must be a
  no-op — replay seeds from the in-memory watermark (no snapshot) or
  resets to the base first (snapshot), never double-applies."""
  for every in (0, 2):             # without and with a compacted base
    d = tmp_path / f'c{every}'
    stream = _fresh_stream()
    pipe = IngestPipeline(stream, wal_dir=str(d), compact_every=every)
    batches = _batches(k=3)
    for r, c in batches:
      pipe.ingest(r, c)
    before = pipe.applied_events
    out = pipe.recover()
    if every == 0:
      # no snapshot: the stream keeps its state, nothing re-applies
      assert out['replayed_records'] == 0
    else:
      # a snapshot RESETS the stream to the base, so replaying the
      # post-watermark suffix is reconstruction, not double-apply
      assert out['restored'] is True
    assert pipe.applied_events == before
    ref = _fresh_stream()
    for r, c in batches:
      ref.apply_events(r, c)
    np.testing.assert_array_equal(stream.pin().indptr, ref.pin().indptr)
    np.testing.assert_array_equal(stream.pin().indices,
                                  ref.pin().indices)
    np.testing.assert_array_equal(stream.pin().edge_ids,
                                  ref.pin().edge_ids)
    pipe.close()


def test_concurrent_ingest_replays_byte_identical(tmp_path):
  """The writer lock pins WAL seqno order == apply (event) order, so
  a restart's seqno-ordered replay reconstructs the live graph byte
  for byte even when several threads ingested concurrently."""
  stream = _fresh_stream()
  pipe = IngestPipeline(stream, wal_dir=str(tmp_path), compact_every=3)
  errs = []

  def worker(seed):
    try:
      for r, c in _batches(k=6, b=9, seed=seed):
        pipe.ingest(r, c)
    except Exception as e:                       # noqa: BLE001
      errs.append(e)

  threads = [threading.Thread(target=worker, args=(s,))
             for s in (21, 22, 23)]
  for t in threads:
    t.start()
  for t in threads:
    t.join(30.0)
  assert not errs
  assert pipe.applied_events == 3 * 6 * 9
  pipe.close()
  stream2 = _fresh_stream()
  pipe2 = IngestPipeline(stream2, wal_dir=str(tmp_path),
                         compact_every=3)
  np.testing.assert_array_equal(stream2.pin().indptr,
                                stream.pin().indptr)
  np.testing.assert_array_equal(stream2.pin().indices,
                                stream.pin().indices)
  np.testing.assert_array_equal(stream2.pin().edge_ids,
                                stream.pin().edge_ids)
  pipe2.close()


def test_compaction_bounds_replay(tmp_path):
  stream = _fresh_stream()
  pipe = IngestPipeline(stream, wal_dir=str(tmp_path), compact_every=2)
  for r, c in _batches(k=7):
    pipe.ingest(r, c)
  assert pipe.stats()['compactions'] == 3
  pipe.close()
  recorder.clear()
  stream2 = _fresh_stream()
  pipe2 = IngestPipeline(stream2, wal_dir=str(tmp_path),
                         compact_every=2)
  rep = recorder.events('ingest.replay')[-1]
  assert rep['restored'] is True
  # only the post-compaction suffix replays (7 batches, last compact
  # at batch 6 -> exactly 1 replayed record)
  assert rep['replayed_records'] == 1
  np.testing.assert_array_equal(stream2.pin().indices,
                                stream.pin().indices)
  pipe2.close()


# -- observability -----------------------------------------------------------

def test_health_metrics_and_lag_flip(tmp_path):
  stream = _fresh_stream()
  pipe = IngestPipeline(stream, wal_dir=str(tmp_path),
                        compact_every=0, max_lag=5)
  pipe.ingest([1, 2], [3, 4])
  snap = live.snapshot()
  assert snap['ingest.events_total'] >= 2
  assert snap['ingest.lag_events'] == 0
  assert snap['graph.version'] == stream.version
  comp = live.healthz()['components']['ingestion']
  assert comp['healthy'] and comp['lag_events'] == 0
  pipe.close()
  # a pipeline that has NOT yet replayed a backlog is lagging: past
  # max_lag the component flips unhealthy
  stream2 = _fresh_stream()
  pipe2 = IngestPipeline(stream2, wal_dir=str(tmp_path),
                         compact_every=0, max_lag=1, recover=False)
  comp = live.healthz()['components']['ingestion']
  assert not comp['healthy'] and comp['lag_events'] == 2
  pipe2.recover()
  assert live.healthz()['components']['ingestion']['healthy']
  pipe2.close()
  # close() unregisters: a dead pipeline exports nothing
  assert 'ingestion' not in live.healthz()['components']
  assert 'ingest.lag_events' not in live.snapshot()


def test_ingest_fault_dumps_postmortem_and_report_renders(
    tmp_path, monkeypatch):
  from graphlearn_tpu.telemetry import postmortem
  from graphlearn_tpu.telemetry.report import (format_resilience_table,
                                               render_postmortem)
  monkeypatch.setenv(postmortem.POSTMORTEM_DIR_ENV,
                     str(tmp_path / 'pm'))
  postmortem.reset()
  stream = _fresh_stream()
  pipe = IngestPipeline(stream, wal_dir=str(tmp_path / 'wal'),
                        compact_every=0)
  chaos.install('ingest.apply:kill:2')
  pipe.ingest([1], [2])
  with pytest.raises(chaos.ChaosKilledError):
    pipe.ingest([3], [4])
  chaos.uninstall()
  bundles = list((tmp_path / 'pm').glob('*.json'))
  assert len(bundles) == 1 and 'ingest_apply' in bundles[0].name
  bundle = json.loads(bundles[0].read_text())
  assert bundle['reason'] == 'ingest.apply'
  assert bundle['extra']['wal_seqno'] == 2
  assert bundle['extra']['applied_seqno'] == 1
  text = render_postmortem(bundle)
  assert '# ingestion at dump' in text
  assert 'ingest.events_total' in text
  assert 'ingestion:' in text              # the healthz component block
  # the resilience table carries the ingest rows
  table = format_resilience_table(recorder.events())
  assert 'ingest.fault' in table and 'apply=1' in table
  pipe.close()
  postmortem.reset()


def test_report_resilience_rows_cover_recovery(tmp_path):
  from graphlearn_tpu.telemetry.report import resilience_counts
  stream = _fresh_stream()
  pipe = IngestPipeline(stream, wal_dir=str(tmp_path), compact_every=2)
  for r, c in _batches(k=3):
    pipe.ingest(r, c)
  pipe.close()
  # tear the tail, restart: the trace shows truncation + replay rows
  wal = WriteAheadLog(tmp_path)
  with open(wal.path, 'r+b') as f:
    f.truncate(wal.stats()['bytes'] - 3)
  stream2 = _fresh_stream()
  pipe2 = IngestPipeline(stream2, wal_dir=str(tmp_path),
                         compact_every=2)
  rows = dict((k, (c, b)) for k, c, b in
              resilience_counts(recorder.events()))
  assert 'ingest.wal_truncate' in rows
  assert 'ingest.replay' in rows
  assert 'ingest.compact' in rows
  pipe2.close()


# -- version fencing: serving + sampling -------------------------------------

def _serving_pieces(reserve=4):
  rng = np.random.default_rng(3)
  rows = np.repeat(np.arange(N), 4)
  cols = rng.integers(0, N, rows.shape[0])
  feats = rng.random((N, 8), dtype=np.float32)
  sg = StreamingGraph.from_coo(rows, cols, num_nodes=N,
                               reserve_edges=reserve * len(rows))
  ds = Dataset().init_node_features(feats).attach_stream(sg)
  return sg, ds, feats


def test_serving_engine_pins_one_version_under_ingest():
  """No torn reads: every coalesced run answers from exactly ONE
  published graph version — byte-identical to a static engine built
  over that version's edge set — while an ingest thread publishes
  concurrently.  Steady-state publishes keep the warm executables
  warm (zero recompiles: shapes are reserved)."""
  from graphlearn_tpu.serving.engine import ServingEngine
  sg, ds, feats = _serving_pieces(reserve=64)
  eng = ServingEngine(ds, [3, 2], seed=7, buckets=(1, 2))
  eng.warmup()
  c0 = eng.compile_count()
  refs = {}                  # version -> static reference engine

  def ref_for(version, view_by_ver):
    if version not in refs:
      topo = view_by_ver[version].as_topo()
      ds_s = (Dataset()
              .init_graph((topo.indptr, topo.indices), layout='CSR',
                          num_nodes=N)
              .init_node_features(feats))
      refs[version] = ServingEngine(ds_s, [3, 2], seed=7,
                                    buckets=(1, 2))
    return refs[version]

  views = {sg.pin().version: sg.pin()}
  stop = threading.Event()
  rng = np.random.default_rng(5)

  def ingest_loop():
    # bounded publishes: total growth stays inside the reserved edge
    # capacity (zero-recompile is assertable), and still far more
    # versions than the serve loop can observe
    for _ in range(400):
      if stop.is_set():
        break
      v = sg.apply_events(rng.integers(0, N, 7),
                          rng.integers(0, N, 7))
      views[v.version] = v
      time.sleep(0.002)

  t = threading.Thread(target=ingest_loop, daemon=True)
  t.start()
  try:
    for i in range(12):
      got = eng.infer([int(i) % N, (3 * i) % N])
      ver = eng.graph_version          # the version this run pinned
      for _ in range(2000):            # the ingest thread records a
        if ver in views:               # view just AFTER publishing it
          break
        time.sleep(0.001)
      ref = ref_for(ver, views)
      want = ref.infer([int(i) % N, (3 * i) % N])
      np.testing.assert_array_equal(got.nodes, want.nodes)
      np.testing.assert_array_equal(np.asarray(got.x),
                                    np.asarray(want.x))
  finally:
    stop.set()
    t.join(5.0)
  assert eng.graph_version > 1         # ingest actually reached serving
  assert eng.compile_count() == c0     # zero recompiles during ingest
  assert eng.compile_status()['graph_version'] == eng.graph_version


def test_hold_graph_freezes_version_across_dispatches():
  """`hold_graph` (the swap parity probe's fence): a publish landing
  between two held dispatches must NOT move the pinned version —
  both run on the graph the hold started on; the next unheld
  dispatch picks the new version up."""
  from graphlearn_tpu.serving.engine import ServingEngine
  sg, ds, _ = _serving_pieces(reserve=16)
  eng = ServingEngine(ds, [3, 2], seed=7, buckets=(1, 2))
  eng.warmup()
  rng = np.random.default_rng(2)
  with eng.hold_graph() as held:
    a = eng.infer([3])
    sg.apply_events(rng.integers(0, N, 5), rng.integers(0, N, 5))
    b = eng.infer([3])
    assert eng.graph_version == held == 1
    np.testing.assert_array_equal(a.nodes, b.nodes)
  eng.infer([3])
  assert eng.graph_version == 2


def test_one_hop_quiesced_stream_matches_static():
  """GNS-off sampling on a quiesced post-ingest graph is
  byte-identical to the same graph loaded statically (single-chip
  kernel over the pinned view's device arrays)."""
  from graphlearn_tpu.ops.neighbor import sample_one_hop
  rows, cols = _base_coo(seed=9)
  sg = StreamingGraph.from_coo(rows, cols, num_nodes=N, device=True)
  extra = _batches(k=2, b=31, seed=4)
  all_r = np.concatenate([rows] + [r for r, _ in extra])
  all_c = np.concatenate([cols] + [c for _, c in extra])
  for r, c in extra:
    sg.apply_events(r, c)
  view = sg.pin()
  g_static = (Dataset()
              .init_graph((all_r, all_c), layout='COO', num_nodes=N)
              .get_graph())
  seeds = np.asarray([0, 5, 17, 40, -1], np.int32)
  key = jax.random.key(11)
  a = sample_one_hop(view.indptr_dev, view.indices_dev,
                     jax.numpy.asarray(seeds), 3, key)
  b = sample_one_hop(g_static.indptr, g_static.indices,
                     jax.numpy.asarray(seeds), 3, key)
  np.testing.assert_array_equal(np.asarray(a.nbrs), np.asarray(b.nbrs))
  np.testing.assert_array_equal(np.asarray(a.mask), np.asarray(b.mask))


def test_mesh_sampler_refreshes_at_dispatch_seam():
  """The mesh arm: a `DistNeighborSampler` over a stream-attached
  `DistDataset` re-pins the newest version at its dispatch seam, and
  the quiesced result is byte-identical to a statically partitioned
  dataset over the same events (same partition book, same key)."""
  from graphlearn_tpu.parallel import (DistDataset, DistNeighborSampler,
                                       make_mesh)
  rows = np.concatenate([np.arange(N), np.arange(N)])
  cols = np.concatenate([(np.arange(N) + 1) % N,
                         (np.arange(N) + 2) % N])
  feats = (np.arange(N, dtype=np.float32)[:, None]
           * np.ones((1, 4), np.float32))
  node_pb = (np.arange(N) % 4).astype(np.int32)

  def make_ds(r, c):
    return DistDataset.from_full_graph(4, r, c, node_feat=feats,
                                       num_nodes=N, node_pb=node_pb)

  sg = StreamingGraph.from_coo(rows, cols, num_nodes=N, device=False)
  ds = make_ds(rows, cols).attach_stream(sg)
  mesh = make_mesh(4)
  samp = DistNeighborSampler(ds, [2], mesh=mesh, seed=0)
  seeds = ds.old2new[np.arange(16).reshape(4, 4)]
  key = jax.random.key(123)
  out1 = samp.sample_from_nodes(seeds, key=key)
  assert samp.maybe_refresh_stream() == 1    # pinned, no change
  sg.apply_events(np.arange(N), (np.arange(N) + 3) % N)
  out2 = samp.sample_from_nodes(seeds, key=key)
  assert samp._stream_ver == 2               # the seam picked it up
  # the new edges actually sample (same key, different frontier)
  assert not np.array_equal(np.asarray(out1['node']),
                            np.asarray(out2['node']))
  ds_s = make_ds(np.concatenate([rows, np.arange(N)]),
                 np.concatenate([cols, (np.arange(N) + 3) % N]))
  samp_s = DistNeighborSampler(ds_s, [2], mesh=mesh, seed=0)
  out_s = samp_s.sample_from_nodes(seeds, key=key)
  for k in ('node', 'row', 'col', 'x'):
    if out2.get(k) is None:
      continue
    np.testing.assert_array_equal(np.asarray(out2[k]),
                                  np.asarray(out_s[k]))
