"""Feature store tests.

Mirrors the reference's `test/python/test_feature.py` intent: id→row
mapping, hot/cold split correctness, dtype handling — on the TPU
two-tier design instead of UnifiedTensor DeviceGroups.
"""
import numpy as np
import jax.numpy as jnp

from graphlearn_tpu.data import CSRTopo, Dataset, Feature, sort_by_in_degree


def _feats(n=32, d=8):
  return (np.arange(n, dtype=np.float32)[:, None]
          * np.ones((1, d), np.float32))


def test_full_device_lookup():
  f = Feature(_feats(), split_ratio=1.0)
  ids = np.array([3, 0, 31, 7])
  out = np.asarray(f[ids])
  np.testing.assert_allclose(out[:, 0], [3, 0, 31, 7])


def test_full_host_lookup():
  f = Feature(_feats(), split_ratio=0.0)
  ids = np.array([5, 2])
  out = np.asarray(f[ids])
  np.testing.assert_allclose(out[:, 0], [5, 2])


def test_mixed_tier_lookup():
  f = Feature(_feats(), split_ratio=0.25)  # rows 0-7 hot, 8-31 cold
  assert f.hot_rows == 8
  ids = np.array([1, 9, 7, 30, 0])
  out = np.asarray(f[ids])
  np.testing.assert_allclose(out[:, 0], [1, 9, 7, 30, 0])


def test_invalid_ids_zero_rows():
  for ratio in (1.0, 0.25, 0.0):
    f = Feature(_feats(), split_ratio=ratio)
    out = np.asarray(f[np.array([-1, 4, -1])])
    np.testing.assert_allclose(out[0], 0)
    np.testing.assert_allclose(out[2], 0)
    np.testing.assert_allclose(out[1, 0], 4)


def test_id2index_mapping():
  feats = _feats()
  # Reversed storage order: global id v lives at row N-1-v.
  id2index = np.arange(31, -1, -1)
  stored = feats[::-1].copy()
  f = Feature(stored, id2index=id2index, split_ratio=0.5)
  out = np.asarray(f[np.array([0, 31, 16])])
  np.testing.assert_allclose(out[:, 0], [0, 31, 16])


def test_bfloat16_storage():
  f = Feature(_feats(), split_ratio=1.0, dtype=jnp.bfloat16)
  out = f[np.array([2, 3])]
  assert out.dtype == jnp.bfloat16
  np.testing.assert_allclose(np.asarray(out, np.float32)[:, 0], [2, 3])


def test_sort_by_in_degree_roundtrip():
  # Star graph: node 0 is pointed at by everyone → hottest.
  n = 10
  rows = np.arange(1, n)
  cols = np.zeros(n - 1, dtype=np.int64)
  topo = CSRTopo((rows, cols), num_nodes=n)
  feats = _feats(n, 4)
  reordered, id2index = sort_by_in_degree(feats, 0.3, topo)
  assert id2index[0] == 0  # hottest row first
  f = Feature(reordered, id2index=id2index, split_ratio=0.3)
  out = np.asarray(f[np.arange(n)])
  np.testing.assert_allclose(out[:, 0], np.arange(n))


def test_host_get():
  f = Feature(_feats(), split_ratio=0.5)
  out = f.host_get(np.array([4, 20]))
  np.testing.assert_allclose(out[:, 0], [4, 20])


def test_dataset_homo():
  rows = np.array([0, 1, 2, 3])
  cols = np.array([1, 2, 3, 0])
  ds = (Dataset()
        .init_graph((rows, cols), layout='COO')
        .init_node_features(_feats(4, 4), split_ratio=1.0)
        .init_node_labels(np.array([0, 1, 0, 1])))
  assert not ds.is_hetero
  assert ds.get_graph().num_nodes == 4
  out = np.asarray(ds.get_node_feature()[np.array([2])])
  np.testing.assert_allclose(out[0, 0], 2)
  assert ds.get_node_label()[1] == 1


def test_dataset_hetero():
  ei = {
      ('user', 'clicks', 'item'): (np.array([0, 1]), np.array([1, 0])),
      ('item', 'rev_clicks', 'user'): (np.array([1, 0]), np.array([0, 1])),
  }
  ds = (Dataset()
        .init_graph(ei, layout='COO')
        .init_node_features({'user': _feats(2, 4), 'item': _feats(2, 4)},
                            split_ratio=1.0))
  assert ds.is_hetero
  assert set(ds.get_node_types()) == {'user', 'item'}
  assert len(ds.get_edge_types()) == 2
  g = ds.get_graph(('user', 'clicks', 'item'))
  assert g.num_edges == 2


def test_partial_id2index_unmapped_returns_zero():
  # id2index built from a partial id set: unmapped ids hold -1 and must
  # come back as zero rows, not the last storage row.
  from graphlearn_tpu.utils.tensor import id2idx
  stored = _feats(3, 4)
  mapping = id2idx(np.array([5, 7, 9]), max_id=9)
  for ratio in (1.0, 0.5, 0.0):
    f = Feature(stored, id2index=mapping, split_ratio=ratio)
    out = np.asarray(f[np.array([6, 5, 9])])
    np.testing.assert_allclose(out[0], 0)
    np.testing.assert_allclose(out[1, 0], 0)   # id 5 -> row 0
    np.testing.assert_allclose(out[2, 0], 2)   # id 9 -> row 2
  out = Feature(stored, id2index=mapping).host_get(np.array([6, 7]))
  np.testing.assert_allclose(out[0], 0)
  np.testing.assert_allclose(out[1, 0], 1)
