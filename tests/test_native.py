"""Tests for the native host runtime (csrc/ via ctypes).

Mirrors the reference's C++ gtest coverage
(`test/cpp/test_shm_queue.cu`, `test_tensor_map_serializer.cu`,
`test_random_sampler.cu`, `test_random_negative_sampler.cu`,
`test_inducer.cu`) — tiny handcrafted graphs, exact assertions, plus a
forked-process queue test.
"""
import multiprocessing as mp
import pickle

import numpy as np
import pytest

from graphlearn_tpu import native as nat


@pytest.fixture(scope='module')
def ring_graph():
  # Node v -> {v+1, v+2} mod n, the reference's deterministic test
  # topology (`test/python/dist_test_utils.py`).
  n = 40
  rows = np.repeat(np.arange(n), 2).astype(np.int64)
  cols = np.concatenate(
      [np.stack([(np.arange(n) + 1) % n, (np.arange(n) + 2) % n], 1)]
  ).reshape(-1).astype(np.int64)
  indptr, indices, perm = nat.coo_to_csr(rows, cols, n)
  return n, indptr, indices, perm


class TestSerializer:
  def test_roundtrip(self):
    msg = {
        'x': np.random.default_rng(0).standard_normal((5, 3)).astype(np.float32),
        'ids': np.arange(7, dtype=np.int64),
        'mask': np.array([True, False, True]),
        'scalar': np.array(42, np.int32),
        'empty': np.zeros((0, 4), np.float32),
    }
    out = nat.parse_tensor_map(nat.serialize_tensor_map(msg))
    assert set(out) == set(msg)
    for k in msg:
      assert out[k].dtype == msg[k].dtype
      assert out[k].shape == msg[k].shape
      assert np.array_equal(out[k], msg[k])

  def test_noncontiguous_input(self):
    big = np.random.default_rng(1).standard_normal((6, 6)).astype(np.float32)
    msg = {'v': big[:, 2]}  # strided view
    out = nat.parse_tensor_map(nat.serialize_tensor_map(msg))
    assert np.array_equal(out['v'], big[:, 2])

  def test_bad_buffer(self):
    with pytest.raises(ValueError):
      nat.parse_tensor_map(b'\x00' * 32)


class TestShmQueue:
  def test_fifo_and_size(self):
    q = nat.ShmQueue(4, 4096)
    for i in range(3):
      q.put({'i': np.array(i, np.int64)})
    assert q.qsize() == 3 and not q.empty()
    got = [int(q.get()['i']) for _ in range(3)]
    assert got == [0, 1, 2]
    assert q.empty()
    q.close()

  def test_oversize_message_rejected(self):
    q = nat.ShmQueue(2, 64)
    with pytest.raises(ValueError):
      q.put_bytes(b'x' * 100)
    q.close()

  def test_cross_process_pickle(self):
    q = nat.ShmQueue(4, 1 << 16)
    msg = {'x': np.random.default_rng(2).standard_normal((8, 4)).astype(np.float32)}
    q.put(msg)
    ctx = mp.get_context('spawn')
    p = ctx.Process(target=_echo_double, args=(pickle.dumps(q),))
    p.start()
    p.join(30)
    assert p.exitcode == 0
    out = q.get()
    assert np.allclose(out['x'], msg['x'] * 2)
    q.close()

  def test_blocking_producer_when_full(self):
    q = nat.ShmQueue(2, 256)
    q.put_bytes(b'a')
    q.put_bytes(b'b')
    ctx = mp.get_context('spawn')
    p = ctx.Process(target=_drain_one, args=(pickle.dumps(q),))
    p.start()
    # This put blocks until the child consumes one slot.
    q.put_bytes(b'c')
    p.join(30)
    assert p.exitcode == 0
    assert q.get_bytes() == b'b'
    assert q.get_bytes() == b'c'
    q.close()


def _echo_double(qp):
  qq = pickle.loads(qp)
  m = qq.get()
  m['x'] = m['x'] * 2
  qq.put(m)


def _drain_one(qp):
  import time
  time.sleep(0.2)
  qq = pickle.loads(qp)
  assert qq.get_bytes() == b'a'


class TestCooToCsr:
  def test_exact(self):
    rows = np.array([2, 0, 1, 0, 2], np.int64)
    cols = np.array([1, 2, 0, 1, 0], np.int64)
    indptr, indices, perm = nat.coo_to_csr(rows, cols, 3)
    assert indptr.tolist() == [0, 2, 3, 5]
    assert indices.tolist() == [2, 1, 0, 1, 0]
    # perm maps CSR slot -> original edge id
    assert rows[perm].tolist() == [0, 0, 1, 2, 2]
    assert np.array_equal(cols[perm], indices)

  def test_matches_device_builder(self, ring_graph):
    n, indptr, indices, _ = ring_graph
    from graphlearn_tpu.data import CSRTopo
    rows = np.repeat(np.arange(n), 2)
    cols = indices.copy()
    topo = CSRTopo((rows, indices), layout='COO', num_nodes=n)
    assert np.array_equal(np.asarray(topo.indptr), indptr)


class TestCpuSampler:
  def test_full_copy_when_deg_le_k(self, ring_graph):
    n, indptr, indices, _ = ring_graph
    seeds = np.arange(10, dtype=np.int64)
    nbrs, mask, eids = nat.sample_one_hop(indptr, indices, seeds, 4,
                                          seed=1, with_edge_ids=True)
    assert nbrs.shape == (10, 4)
    for b, v in enumerate(seeds):
      got = set(nbrs[b][mask[b]].tolist())
      assert got == {(v + 1) % n, (v + 2) % n}
      assert mask[b].sum() == 2
      assert (nbrs[b][~mask[b]] == -1).all()

  def test_downsample_distinct(self):
    n = 50
    rng = np.random.default_rng(0)
    rows = np.repeat(np.arange(n), 20).astype(np.int64)
    cols = rng.integers(0, n, n * 20).astype(np.int64)
    indptr, indices, _ = nat.coo_to_csr(rows, cols, n)
    nbrs, mask, eids = nat.sample_one_hop(indptr, indices,
                                          np.arange(n, dtype=np.int64),
                                          8, seed=7, with_edge_ids=True)
    assert mask.all()
    for b in range(n):
      assert len(set(eids[b].tolist())) == 8  # distinct edges
      lo, hi = indptr[b], indptr[b + 1]
      assert set(nbrs[b]) <= set(indices[lo:hi])

  def test_padded_seed_masked(self, ring_graph):
    n, indptr, indices, _ = ring_graph
    seeds = np.array([0, -1, 3], np.int64)
    nbrs, mask, _ = nat.sample_one_hop(indptr, indices, seeds, 4)
    assert not mask[1].any()
    assert (nbrs[1] == -1).all()

  def test_deterministic_by_seed(self, ring_graph):
    n, indptr, indices, _ = ring_graph
    s = np.arange(n, dtype=np.int64)
    a = nat.sample_one_hop(indptr, indices, s, 1, seed=9)
    b = nat.sample_one_hop(indptr, indices, s, 1, seed=9)
    c = nat.sample_one_hop(indptr, indices, s, 1, seed=10)
    assert np.array_equal(a[0], b[0])
    assert not np.array_equal(a[0], c[0])  # overwhelmingly likely


class TestNegativeSampler:
  def test_strict_rejects_edges(self, ring_graph):
    n, indptr, indices, _ = ring_graph
    rows, cols = nat.negative_sample(indptr, indices, 64, trials=10,
                                     strict=True, seed=3)
    for r, c in zip(rows, cols):
      assert c not in indices[indptr[r]:indptr[r + 1]]

  def test_padding_fills(self, ring_graph):
    n, indptr, indices, _ = ring_graph
    rows, cols = nat.negative_sample(indptr, indices, 100, trials=1,
                                     strict=True, padding=True, seed=3)
    assert len(rows) == 100


class TestCpuInducer:
  def test_seed_dedup(self):
    ind = nat.CpuInducer()
    loc = ind.init_nodes(np.array([5, 7, 5, 9], np.int64))
    assert loc.tolist() == [0, 1, 0, 2]
    assert ind.num_nodes == 3

  def test_induce_relabel_and_direction(self):
    ind = nat.CpuInducer()
    ind.init_nodes(np.array([10, 20], np.int64))
    nbrs = np.array([[20, 30], [10, 40]], np.int64)
    mask = np.ones((2, 2), np.uint8)
    new, rl, cl = ind.induce_next(np.array([10, 20], np.int64), nbrs, mask)
    assert set(new.tolist()) == {30, 40}
    # Edge direction: neighbor -> seed.
    assert rl[0, 0] == 1 and cl[0, 0] == 0   # 20 -> 10
    assert rl[1, 0] == 0 and cl[1, 0] == 1   # 10 -> 20
    assert rl[0, 1] == 2 and cl[0, 1] == 0   # 30 -> 10

  def test_masked_slots_no_edges(self):
    ind = nat.CpuInducer()
    ind.init_nodes(np.array([1], np.int64))
    nbrs = np.array([[2, -1]], np.int64)
    mask = np.array([[1, 0]], np.uint8)
    new, rl, cl = ind.induce_next(np.array([1], np.int64), nbrs, mask)
    assert rl[0, 1] == -1 and cl[0, 1] == -1
    assert new.tolist() == [2]

  def test_clear(self):
    ind = nat.CpuInducer()
    ind.init_nodes(np.array([1, 2], np.int64))
    ind.clear()
    assert ind.num_nodes == 0
    loc = ind.init_nodes(np.array([3], np.int64))
    assert loc.tolist() == [0]


class TestCalNbrProb:
  def test_propagation(self):
    # 0 -> {1, 2}; 1 -> {2}
    rows = np.array([0, 0, 1], np.int64)
    cols = np.array([1, 2, 2], np.int64)
    indptr, indices, _ = nat.coo_to_csr(rows, cols, 3)
    p = nat.cal_nbr_prob(indptr, indices, np.array([1., 0., 0.],
                                                   np.float32), k=1)
    # deg(0)=2, w = 1 * min(1, 1/2) = .5 to each nbr
    assert np.allclose(p, [0., .5, .5])
