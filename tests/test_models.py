"""Model-family tests: correctness of masked aggregation and that a
few steps of training reduce loss on a learnable synthetic task."""
import numpy as np
import pytest
import jax
import jax.numpy as jnp
import optax

from graphlearn_tpu.data import Dataset
from graphlearn_tpu.loader import NeighborLoader
from graphlearn_tpu.models import (GAT, GCN, GraphSAGE, SAGEConv,
                                   create_train_state, make_eval_step,
                                   make_supervised_step, segment_mean)


def test_segment_mean_masks_invalid():
  data = jnp.ones((4, 2))
  seg = jnp.array([0, 0, 1, -1])
  mask = jnp.array([True, True, True, False])
  out = segment_mean(data, seg, 3, mask)
  np.testing.assert_allclose(np.asarray(out[0]), 1.0)
  np.testing.assert_allclose(np.asarray(out[1]), 1.0)
  np.testing.assert_allclose(np.asarray(out[2]), 0.0)


def test_sageconv_matches_manual():
  # 3 nodes, edges 1->0, 2->0 (+ one masked junk edge).
  x = jnp.array([[1., 0.], [0., 1.], [2., 2.]])
  ei = jnp.array([[1, 2, -1], [0, 0, -1]])
  em = jnp.array([True, True, False])
  conv = SAGEConv(4)
  params = conv.init(jax.random.key(0), x, ei, em)
  out = conv.apply(params, x, ei, em)
  w_self = params['params']['lin_self']['kernel']
  b_self = params['params']['lin_self']['bias']
  w_neigh = params['params']['lin_neigh']['kernel']
  agg0 = (np.asarray(x[1]) + np.asarray(x[2])) / 2
  expect0 = np.asarray(x[0]) @ w_self + b_self + agg0 @ w_neigh
  np.testing.assert_allclose(np.asarray(out[0]), expect0, rtol=1e-5)
  # node 1 has no incoming edges -> only self term.
  expect1 = np.asarray(x[1]) @ w_self + b_self
  np.testing.assert_allclose(np.asarray(out[1]), expect1, rtol=1e-5)


def _cluster_dataset(n=60, d=8, classes=3, seed=0):
  """Learnable task: label = cluster id; edges mostly intra-cluster;
  features = noisy one-hot of cluster."""
  rng = np.random.default_rng(seed)
  labels = np.arange(n) % classes
  rows, cols = [], []
  for v in range(n):
    same = np.nonzero(labels == labels[v])[0]
    rows += [v] * 4
    cols += list(rng.choice(same, 3)) + [rng.integers(0, n)]
  feats = np.eye(classes, dtype=np.float32)[labels]
  feats = np.concatenate(
      [feats, rng.normal(0, 0.1, (n, d - classes)).astype(np.float32)], 1)
  feats += rng.normal(0, 0.05, feats.shape).astype(np.float32)
  return (Dataset()
          .init_graph((np.array(rows), np.array(cols)), layout='COO',
                      num_nodes=n)
          .init_node_features(feats, split_ratio=1.0)
          .init_node_labels(labels.astype(np.int32)))


def test_graphsage_trains():
  ds = _cluster_dataset()
  bs = 16
  loader = NeighborLoader(ds, [4, 4], np.arange(60), batch_size=bs,
                          shuffle=True, seed=0)
  model = GraphSAGE(hidden_features=16, out_features=3, num_layers=2)
  tx = optax.adam(1e-2)
  batch0 = next(iter(loader))
  state, apply_fn = create_train_state(model, jax.random.key(0), batch0, tx)
  step = make_supervised_step(apply_fn, tx, bs)
  losses = []
  for epoch in range(10):
    for batch in loader:
      state, loss, _ = step(state, batch)
      losses.append(float(loss))
  assert np.mean(losses[-4:]) < 0.5 * np.mean(losses[:4]), losses[:8]

  ev = make_eval_step(apply_fn, bs)
  correct = total = 0
  for batch in loader:
    c, t = ev(state.params, batch)
    correct += int(c)
    total += int(t)
  assert correct / total > 0.8


def test_gcn_gat_forward_shapes():
  ds = _cluster_dataset()
  loader = NeighborLoader(ds, [3, 3], np.arange(30), batch_size=8)
  batch = next(iter(loader))
  for model in (GCN(hidden_features=8, out_features=3, num_layers=2),
                GAT(hidden_features=8, out_features=3, num_layers=2,
                    heads=2)):
    params = model.init(jax.random.key(0), batch.x, batch.edge_index,
                        batch.edge_mask)
    out = model.apply(params, batch.x, batch.edge_index, batch.edge_mask)
    assert out.shape == (batch.x.shape[0], 3)
    assert np.isfinite(np.asarray(out)).all()


def test_bf16_compute_dtype():
  """dtype=bfloat16 computes on half-width MXU lanes but keeps params
  and outputs f32, and still learns."""
  import jax
  import jax.numpy as jnp
  import numpy as np
  import optax
  from graphlearn_tpu.models import GraphSAGE

  rng = np.random.default_rng(0)
  n, d, classes = 64, 16, 4
  x = rng.standard_normal((n, d)).astype(np.float32)
  y = (np.arange(n) % classes).astype(np.int32)
  ei = jnp.asarray(
      np.stack([rng.integers(0, n, 128), rng.integers(0, n, 128)]))
  em = ei[0] >= 0
  x, y = jnp.asarray(x), jnp.asarray(y)
  model = GraphSAGE(hidden_features=32, out_features=classes,
                    num_layers=2, dtype=jnp.bfloat16)
  params = model.init(jax.random.key(0), x, ei, em)
  out = model.apply(params, x, ei, em)
  assert out.dtype == jnp.float32
  assert all(p.dtype == jnp.float32
             for p in jax.tree_util.tree_leaves(params))
  tx = optax.adam(1e-2)
  opt = tx.init(params)

  @jax.jit
  def step(params, opt):
    def loss_fn(p):
      logits = model.apply(p, x, ei, em)
      return optax.softmax_cross_entropy_with_integer_labels(
          logits, y).mean()
    loss, g = jax.value_and_grad(loss_fn)(params)
    upd, opt = tx.update(g, opt, params)
    return optax.apply_updates(params, upd), opt, loss

  first = None
  for _ in range(30):
    params, opt, loss = step(params, opt)
    first = float(loss) if first is None else first
  assert float(loss) < first * 0.7


def test_bf16_hub_degree_counts_not_saturated():
  """Edge counts/degrees accumulate in f32 even under bf16 compute:
  a 400-degree hub's mean aggregation must match the f32 model
  closely (bf16 scatter-add of ones saturates near 256)."""
  import jax
  import jax.numpy as jnp
  import numpy as np
  from graphlearn_tpu.models import GraphSAGE

  n, deg = 512, 400
  rng = np.random.default_rng(0)
  x = jnp.asarray(rng.standard_normal((n, 8)).astype(np.float32))
  # every edge points at node 0 (the hub)
  src = jnp.asarray(rng.integers(1, n, deg).astype(np.int32))
  ei = jnp.stack([src, jnp.zeros((deg,), jnp.int32)])
  em = jnp.ones((deg,), bool)
  kw = dict(hidden_features=16, out_features=4, num_layers=1)
  m32 = GraphSAGE(**kw)
  m16 = GraphSAGE(**kw, dtype=jnp.bfloat16)
  params = m32.init(jax.random.key(0), x, ei, em)
  o32 = m32.apply(params, x, ei, em)
  o16 = m16.apply(params, x, ei, em)
  # hub row would be off by ~deg/256 (≈1.6x) if counts saturated
  rel = float(jnp.abs(o16[0] - o32[0]).max()
              / jnp.maximum(jnp.abs(o32[0]).max(), 1e-6))
  assert rel < 0.05, rel


@pytest.mark.slow
def test_dgcnn_learns_graph_label():
  """DGCNN separates graphs by structure: dense cliques vs sparse
  rings (graph-level task, static sort-pool)."""
  from graphlearn_tpu.models import DGCNN

  rng = np.random.default_rng(0)
  n = 20

  def clique():
    src, dst = np.meshgrid(np.arange(n), np.arange(n))
    m = src != dst
    return np.stack([src[m], dst[m]])

  def ring():
    return np.stack([np.arange(n), (np.arange(n) + 1) % n])

  graphs = []
  for i in range(24):
    ei = clique() if i % 2 == 0 else ring()
    cap = n * n
    pad = np.full((2, cap), -1)
    pad[:, :ei.shape[1]] = ei
    x = rng.standard_normal((n, 4)).astype(np.float32)
    graphs.append((jnp.asarray(x), jnp.asarray(pad),
                   jnp.asarray(pad[0] >= 0),
                   jnp.ones((n,), bool), i % 2))

  model = DGCNN(hidden_features=16, out_features=2, num_layers=2, k=8)
  params = model.init(jax.random.key(0), *graphs[0][:4])
  tx = optax.adam(1e-2)
  opt = tx.init(params)

  @jax.jit
  def step(params, opt, x, ei, em, nm, y):
    def loss_fn(p):
      logit = model.apply(p, x, ei, em, nm)
      return optax.softmax_cross_entropy_with_integer_labels(logit, y)
    loss, g = jax.value_and_grad(loss_fn)(params)
    upd, opt = tx.update(g, opt, params)
    return optax.apply_updates(params, upd), opt, loss

  for _ in range(20):
    for x, ei, em, nm, y in graphs[:16]:
      params, opt, loss = step(params, opt, x, ei, em, nm,
                               jnp.asarray(y))

  @jax.jit
  def predict(params, x, ei, em, nm):
    return jnp.argmax(model.apply(params, x, ei, em, nm))

  correct = sum(int(predict(params, x, ei, em, nm)) == y
                for x, ei, em, nm, y in graphs[16:])
  assert correct >= 7, correct


@pytest.mark.slow
def test_gin_and_gatv2_convs_mask_and_learn():
  """New zoo members (r3): masked padded edges contribute nothing, and
  an L-layer stack learns the clustered-graph task."""
  import jax
  import jax.numpy as jnp
  import optax
  from graphlearn_tpu.models import (GATv2Conv, GIN, GINConv,
                                     create_train_state,
                                     make_eval_step,
                                     make_supervised_step)
  rng = np.random.default_rng(0)
  n, e = 12, 30
  x = jnp.asarray(rng.normal(size=(n, 6)).astype(np.float32))
  src = rng.integers(0, n, e).astype(np.int32)
  dst = rng.integers(0, n, e).astype(np.int32)
  for cls, kw in ((GINConv, dict(out_features=5)),
                  (GATv2Conv, dict(out_features=5, heads=2))):
    conv = cls(**kw)
    ei_full = jnp.asarray(np.stack([src, dst]))
    mask = jnp.asarray(np.ones(e, bool))
    params = conv.init(jax.random.key(0), x, ei_full, mask)
    out_full = conv.apply(params, x, ei_full, mask)
    # append PADDED edges: outputs must be identical
    pad_src = np.concatenate([src, rng.integers(0, n, 7)]).astype(np.int32)
    pad_dst = np.concatenate([dst, np.full(7, -1)]).astype(np.int32)
    pad_mask = jnp.asarray(np.concatenate([np.ones(e, bool),
                                           np.zeros(7, bool)]))
    out_pad = conv.apply(params, x, jnp.asarray(np.stack([pad_src,
                                                          pad_dst])),
                         pad_mask)
    np.testing.assert_allclose(np.asarray(out_full),
                               np.asarray(out_pad), atol=1e-5)

  # GIN stack learns the clustered graph end-to-end
  import sys
  from pathlib import Path
  sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
  from examples._synthetic import clustered_graph
  from graphlearn_tpu.data import Dataset
  from graphlearn_tpu.loader import NeighborLoader
  rows, cols, feats, labels = clustered_graph(n=400, deg=8, classes=4,
                                              d=12, seed=1)
  ds = (Dataset().init_graph((rows, cols), layout='COO', num_nodes=400)
        .init_node_features(feats).init_node_labels(labels))
  loader = NeighborLoader(ds, [5, 5], np.arange(300), batch_size=64,
                          shuffle=True, seed=0)
  test_loader = NeighborLoader(ds, [5, 5], np.arange(300, 400),
                               batch_size=64)
  model = GIN(hidden_features=32, out_features=4, num_layers=2)
  tx = optax.adam(5e-3)
  state, apply_fn = create_train_state(model, jax.random.key(0),
                                       next(iter(loader)), tx)
  step = make_supervised_step(apply_fn, tx, 64)
  eval_step = make_eval_step(apply_fn, 64)
  for _ in range(5):
    for batch in loader:
      state, _, _ = step(state, batch)
  correct = total = 0
  for batch in test_loader:
    c, t = eval_step(state.params, batch)
    correct += int(c)
    total += int(t)
  assert correct / total > 0.8, correct / total
