"""Test configuration: force an 8-device virtual CPU mesh.

Mirrors the reference's test strategy of running the real distributed
stack all-locally (`test/python/dist_test_utils.py`): multi-chip
sharding paths compile and execute on 8 virtual CPU devices; the same
code runs unchanged on a real TPU slice.

NOTE: this environment pre-imports jax at interpreter startup (a
sitecustomize on PYTHONPATH registers the TPU tunnel plugin), so
``JAX_PLATFORMS`` from the environment is already latched — setting
env vars here is too late.  ``jax.config.update`` works post-import,
and ``XLA_FLAGS`` is parsed at first backend init, which hasn't
happened yet when conftest loads.  Real-chip validation runs as plain
scripts (see .claude/skills/verify), not through pytest.
"""
import os

_flags = os.environ.get('XLA_FLAGS', '')
if '--xla_force_host_platform_device_count' not in _flags:
  os.environ['XLA_FLAGS'] = (
      _flags + ' --xla_force_host_platform_device_count=8').strip()

import jax

jax.config.update('jax_platforms', 'cpu')
# Tests assert SEMANTICS (provenance, masks, parity), not kernel perf:
# skipping XLA's heavy optimization passes cuts the CPU-mesh compile
# wall ~35% across the suite (measured) with identical test outcomes.
# GLT_TEST_NO_FAST_XLA=1 runs under the PRODUCTION pass pipeline —
# `tests/test_optimization_canary.py` re-runs a parity slice that way
# in-suite so an optimization-pass numerics bug cannot hide behind
# this flag (ADVICE r4).
if os.environ.get('GLT_TEST_NO_FAST_XLA') != '1':
  jax.config.update('jax_disable_most_optimizations', True)
