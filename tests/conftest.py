"""Test configuration: force an 8-device virtual CPU mesh.

Mirrors the reference's test strategy of running the real distributed
stack all-locally (`test/python/dist_test_utils.py`): multi-chip
sharding paths compile and execute on 8 virtual CPU devices; the same
code runs unchanged on a real TPU slice.
"""
import os

os.environ.setdefault('JAX_PLATFORMS', 'cpu')
_flags = os.environ.get('XLA_FLAGS', '')
if '--xla_force_host_platform_device_count' not in _flags:
  os.environ['XLA_FLAGS'] = (
      _flags + ' --xla_force_host_platform_device_count=8').strip()
