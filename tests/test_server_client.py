"""Server-client deployment tests — all roles as local processes
(the SURVEY §4 pattern: real RPC over localhost, no mocks; reference
`test_dist_neighbor_loader.py:run_test_as_server/client`, `:180-213`).
"""
import multiprocessing as mp

import numpy as np
import pytest

from graphlearn_tpu import native

pytestmark = pytest.mark.skipif(not native.available(),
                                reason='native lib unavailable')


def _ring(n=40, d=4):
  from graphlearn_tpu.distributed import HostDataset
  rows = np.repeat(np.arange(n), 2)
  cols = np.stack([(np.arange(n) + 1) % n,
                   (np.arange(n) + 2) % n], 1).reshape(-1)
  feats = np.tile(np.arange(n, dtype=np.float32)[:, None], (1, d))
  return HostDataset.from_coo(rows, cols, n, node_features=feats,
                              node_labels=np.arange(n) % 4)


def _server_proc(port_q):
  from graphlearn_tpu.distributed import (get_server, init_server,
                                          wait_and_shutdown_server)
  srv = init_server(num_servers=1, num_clients=1, rank=0,
                    dataset=_ring(), host='127.0.0.1', port=0)
  port_q.put(srv.port)
  wait_and_shutdown_server(timeout=60)


@pytest.mark.slow
def test_multi_server_fanout():
  """List-valued server_rank spreads one loader across servers."""
  ctx = mp.get_context('forkserver')
  procs, ports = [], []
  for _ in range(2):
    q = ctx.Queue()
    p = ctx.Process(target=_server_proc, args=(q,), daemon=False)
    p.start()
    procs.append(p)
    ports.append(q.get(timeout=30))

  from graphlearn_tpu.distributed import (
      DistNeighborLoader, RemoteDistSamplingWorkerOptions, init_client,
      shutdown_client)
  init_client([('127.0.0.1', pt) for pt in ports], rank=0, num_clients=1)
  n = 40
  loader = DistNeighborLoader(
      None, [2], np.arange(n), batch_size=8, shuffle=False,
      worker_options=RemoteDistSamplingWorkerOptions(
          server_rank=[0, 1], num_workers=1, prefetch_size=2),
      to_device=False)
  for _ in range(2):
    seeds_seen = []
    for batch in loader:
      s = np.asarray(batch.batch)
      seeds_seen.append(s[s >= 0])
    np.testing.assert_array_equal(np.sort(np.concatenate(seeds_seen)),
                                  np.arange(n))
  loader.shutdown()
  shutdown_client()
  for p in procs:
    p.join(timeout=20)
    assert not p.is_alive()


def test_remote_loader_epochs():
  ctx = mp.get_context('forkserver')
  port_q = ctx.Queue()
  # non-daemonic: the server itself spawns producer subprocesses
  p = ctx.Process(target=_server_proc, args=(port_q,), daemon=False)
  p.start()
  port = port_q.get(timeout=30)

  from graphlearn_tpu.distributed import (
      DistNeighborLoader, RemoteDistSamplingWorkerOptions, init_client,
      shutdown_client)
  client = init_client([('127.0.0.1', port)], rank=0, num_clients=1)
  meta = client.get_dataset_meta()
  assert meta['num_nodes'] == 40 and meta['feature_dim'] == 4

  n = 40
  loader = DistNeighborLoader(
      None, [2, 2], np.arange(n), batch_size=8, shuffle=True,
      worker_options=RemoteDistSamplingWorkerOptions(
          server_rank=0, num_workers=2, prefetch_size=2),
      to_device=False, seed=1)
  for _ in range(2):
    seeds_seen = []
    batches = 0
    for batch in loader:
      batches += 1
      ids = np.asarray(batch.node)
      valid = np.asarray(batch.node_mask)
      np.testing.assert_allclose(np.asarray(batch.x)[:, 0][valid],
                                 ids[valid].astype(np.float32))
      s = np.asarray(batch.batch)
      seeds_seen.append(s[s >= 0])
    assert batches == 5
    np.testing.assert_array_equal(np.sort(np.concatenate(seeds_seen)),
                                  np.arange(n))

  loader.shutdown()
  shutdown_client()          # client-0 tells the server to exit
  p.join(timeout=20)
  assert not p.is_alive()
