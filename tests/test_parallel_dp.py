"""Data-parallel training over the virtual 8-device mesh.

The TPU analog of the reference's DDP examples (SURVEY §2.3.1): same
model quality contract — DP loss must match single-device training
given the same batches — plus gradient-sync correctness via pmean.
"""
import numpy as np
import jax
import optax

from graphlearn_tpu.data import Dataset
from graphlearn_tpu.loader import NeighborLoader
from graphlearn_tpu.models import (GraphSAGE, create_train_state,
                                   make_supervised_step)
from graphlearn_tpu.parallel import (DataParallelLoader,
                                     make_dp_supervised_step, make_mesh,
                                     replicate, shard_stacked)


def _dataset(n=64, d=8, classes=4, seed=0):
  rng = np.random.default_rng(seed)
  rows = np.repeat(np.arange(n), 4)
  cols = rng.integers(0, n, n * 4)
  feats = rng.standard_normal((n, d)).astype(np.float32)
  labels = (np.arange(n) % classes).astype(np.int32)
  return (Dataset()
          .init_graph((rows, cols), layout='COO', num_nodes=n)
          .init_node_features(feats, split_ratio=1.0)
          .init_node_labels(labels))


def test_dp_step_runs_on_mesh():
  assert len(jax.devices()) >= 8
  mesh = make_mesh(8)
  ds = _dataset()
  bs = 8
  loader = NeighborLoader(ds, [3, 2], np.arange(64), batch_size=bs)
  model = GraphSAGE(hidden_features=16, out_features=4, num_layers=2)
  tx = optax.adam(1e-2)
  state, apply_fn = create_train_state(
      model, jax.random.key(0), next(iter(loader)), tx)
  step = make_dp_supervised_step(apply_fn, tx, bs, mesh)
  stacked = shard_stacked(next(iter(DataParallelLoader(loader, 8))), mesh)
  state = replicate(state, mesh)
  state, loss, correct = step(state, stacked)
  assert np.isfinite(float(loss))
  assert 0 <= int(correct) <= 64


def test_dp_matches_sequential_gradient_average():
  """One DP step over 4 devices == one step with grads averaged over
  the same 4 batches sequentially."""
  mesh = make_mesh(4)
  ds = _dataset()
  bs = 8
  loader = NeighborLoader(ds, [3, 2], np.arange(64), batch_size=bs,
                          shuffle=False)
  model = GraphSAGE(hidden_features=16, out_features=4, num_layers=2)
  tx = optax.sgd(0.1)
  batches = list(loader)[:4]
  state, apply_fn = create_train_state(
      model, jax.random.key(0), batches[0], tx)

  # Sequential reference: average grads over the 4 batches by hand.
  from graphlearn_tpu.models.train import supervised_loss

  def loss_fn(params, batch):
    logits = apply_fn(params, batch.x, batch.edge_index, batch.edge_mask)
    return supervised_loss(logits, batch.y, batch.batch, bs)

  grads = [jax.grad(loss_fn)(state.params, b) for b in batches]
  mean_grads = jax.tree_util.tree_map(
      lambda *g: sum(g) / len(g), *grads)
  updates, _ = tx.update(mean_grads, state.opt_state, state.params)
  ref_params = optax.apply_updates(state.params, updates)

  # DP step over the same 4 batches.
  from graphlearn_tpu.parallel import stack_batches
  step = make_dp_supervised_step(apply_fn, tx, bs, mesh)
  stacked = shard_stacked(stack_batches(batches), mesh)
  dp_state = replicate(state, mesh)
  dp_state, _, _ = step(dp_state, stacked)

  jax.tree_util.tree_map(
      lambda a, b: np.testing.assert_allclose(
          np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5),
      ref_params, dp_state.params)


def test_multihost_seed_shard_single_process():
  """Per-host seed sharding: deterministic permutation, full disjoint
  coverage (single-process degenerate case covers the slicing math)."""
  from graphlearn_tpu.parallel import multihost
  seeds = np.arange(100)
  a = multihost.host_seed_shard(seeds, epoch=3, seed=1)
  b = multihost.host_seed_shard(seeds, epoch=3, seed=1)
  np.testing.assert_array_equal(a, b)           # same epoch -> same order
  c = multihost.host_seed_shard(seeds, epoch=4, seed=1)
  assert not np.array_equal(a, c)               # epochs reshuffle
  np.testing.assert_array_equal(np.sort(a), seeds)  # 1 host = everything
  mesh = multihost.global_mesh()
  assert mesh.devices.size == len(jax.devices())
  sl = multihost.host_device_slice()
  assert (sl.stop - sl.start) == len(jax.devices())
