"""Telemetry plane (ISSUE r6): flight-recorder bounds + thread safety,
mesh-aggregated metrics on the 8-device virtual mesh, per-hop padding
gauges against the loader's own numbers, slack-ladder transition
events, and the compile-cache dispatch telemetry."""
import json
import threading
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from graphlearn_tpu.telemetry import (EventRecorder, exchange_summary,
                                      gather_metrics, metrics,
                                      per_hop_padding, recorder)
from graphlearn_tpu.utils.profiling import Metrics

P = 8
N = 256
FANOUT = [2, 2]
BATCH = 8


# -- recorder mechanics -----------------------------------------------------

def test_recorder_ring_bounded():
  r = EventRecorder(max_events=16)
  r.enable()
  for i in range(100):
    r.emit('tick', i=i)
  evs = r.events('tick')
  assert len(evs) == 16                 # bounded: oldest dropped
  assert [e['i'] for e in evs] == list(range(84, 100))
  assert r.stats()['ring_capacity'] == 16


def test_recorder_disabled_is_noop():
  r = EventRecorder(max_events=8)
  r.emit('tick')                        # default OFF
  assert r.events() == []
  r.enable()
  r.emit('tick')
  r.disable()
  r.emit('tick')
  assert len(r.events()) == 1


def test_recorder_file_sink_bounded(tmp_path):
  p = str(tmp_path / 'flight.jsonl')
  r = EventRecorder(path=p, max_events=64, max_file_events=10)
  for i in range(25):
    r.emit('tick', i=i)
  lines = open(p).read().strip().splitlines()
  assert len(lines) == 10               # file cap holds
  assert all(json.loads(ln)['kind'] == 'tick' for ln in lines)
  st = r.stats()
  assert st['dropped_file_events'] == 15
  assert st['ring_events'] == 25        # ring kept recording


def test_recorder_thread_safety(tmp_path):
  p = str(tmp_path / 'flight.jsonl')
  r = EventRecorder(path=p, max_events=4096, max_file_events=100000)
  threads, per = 8, 200

  def work(tid):
    for i in range(per):
      r.emit('t', tid=tid, i=i)

  ts = [threading.Thread(target=work, args=(t,)) for t in range(threads)]
  for t in ts:
    t.start()
  for t in ts:
    t.join()
  lines = open(p).read().strip().splitlines()
  assert len(lines) == threads * per
  # every line is intact JSON (no interleaved writes)
  parsed = [json.loads(ln) for ln in lines]
  assert all(pv['kind'] == 't' for pv in parsed)
  assert len(r.events()) == threads * per


def test_recorder_coerces_numpy_scalars(tmp_path):
  p = str(tmp_path / 'f.jsonl')
  r = EventRecorder(path=p)
  r.emit('x', a=np.int64(3), b=np.float32(0.5), c=np.arange(2))
  ev = json.loads(open(p).read())
  assert ev['a'] == 3 and abs(ev['b'] - 0.5) < 1e-6 and ev['c'] == [0, 1]


def test_recorder_unserializable_degrades_to_repr(tmp_path):
  """ISSUE-2 satellite: bytes/enums/arbitrary objects degrade the
  FIELD (repr), never the event — emit must not raise from hot
  paths."""
  import enum

  class Kind(enum.Enum):
    A = 1

  class Opaque:
    def __repr__(self):
      return '<opaque>'

  p = str(tmp_path / 'f.jsonl')
  r = EventRecorder(path=p)
  r.emit('x', raw=b'\x00\xff', kind_=Kind.A, obj=Opaque(), ok=1,
         nested={'deep': b'zz'})
  r.emit('y', after=2)                   # the stream keeps flowing
  lines = open(p).read().strip().splitlines()
  assert len(lines) == 2
  ev = json.loads(lines[0])
  assert ev['ok'] == 1
  assert ev['obj'] == '<opaque>'
  assert 'Kind.A' in ev['kind_']
  assert isinstance(ev['raw'], str)      # repr of the bytes
  assert isinstance(ev['nested']['deep'], str)   # container leaf too
  assert json.loads(lines[1])['after'] == 2
  # the ring snapshot dumps the same events without raising
  dump = str(tmp_path / 'dump.jsonl')
  assert r.dump(dump) == 2
  assert len(open(dump).read().strip().splitlines()) == 2


def test_recorder_nonstring_dict_keys_degrade(tmp_path):
  """default=repr can't fix non-string dict KEYS (json raises
  TypeError before consulting it); the whole field degrades to repr
  instead of emit raising from the hot path."""
  p = str(tmp_path / 'f.jsonl')
  r = EventRecorder(path=p)
  r.emit('x', per_etype={('paper', 'cites', 'paper'): 5}, ok=1)
  r.emit('y', after=2)
  lines = open(p).read().strip().splitlines()
  assert len(lines) == 2
  ev = json.loads(lines[0])
  assert ev['ok'] == 1
  assert 'cites' in ev['per_etype']       # repr of the whole dict
  assert r.dump(str(tmp_path / 'd.jsonl')) == 2


def test_reenable_same_path_reopens_after_io_failure(tmp_path):
  """An emit-time I/O failure closes the sink; a later enable() with
  the SAME path must reopen the file, not silently stay ring-only."""
  p = str(tmp_path / 'f.jsonl')
  r = EventRecorder(path=p)
  r.emit('a')
  with r._lock:
    r._close_file_locked()          # what an ENOSPC emit does
  r.emit('b')                       # ring-only while closed
  r.enable(p)                       # operator freed space: resume
  r.emit('c')
  kinds = [json.loads(ln)['kind']
           for ln in open(p).read().strip().splitlines()]
  assert kinds == ['a', 'c']
  assert [e['kind'] for e in r.events()] == ['a', 'b', 'c']


def test_recorder_mono_field_monotonic(tmp_path):
  """ISSUE-2 satellite: every event carries a monotonic-clock `mono`
  next to wall `ts`, and mono never goes backwards (span durations
  derive from it)."""
  r = EventRecorder(path=str(tmp_path / 'f.jsonl'))
  for i in range(5):
    r.emit('tick', i=i)
  evs = r.events('tick')
  assert all('mono' in e and 'ts' in e for e in evs)
  monos = [e['mono'] for e in evs]
  assert monos == sorted(monos)
  assert monos[-1] > 0


def test_recorder_concurrent_emit_with_both_bounds(tmp_path):
  """ISSUE-2 satellite: many threads emitting with BOTH the ring and
  file bounds active — no torn/interleaved JSONL lines, the file cap
  holds exactly, and the ring keeps the NEWEST window (oldest-drop)."""
  p = str(tmp_path / 'flight.jsonl')
  ring_cap, file_cap, threads, per = 64, 300, 8, 100
  r = EventRecorder(path=p, max_events=ring_cap,
                    max_file_events=file_cap)
  start = threading.Barrier(threads)

  def work(tid):
    start.wait()
    for i in range(per):
      r.emit('t', tid=tid, i=i)

  ts = [threading.Thread(target=work, args=(t,))
        for t in range(threads)]
  for t in ts:
    t.start()
  for t in ts:
    t.join()
  lines = open(p).read().strip().splitlines()
  assert len(lines) == file_cap            # file bound holds exactly
  parsed = [json.loads(ln) for ln in lines]       # every line intact
  # r13: the FIRST ring drop emits a one-shot recorder.overflow event
  # (it rides the same bounded file like any other event)
  assert all(pv['kind'] in ('t', 'recorder.overflow')
             and 'mono' in pv for pv in parsed)
  overflow_lines = [pv for pv in parsed
                    if pv['kind'] == 'recorder.overflow']
  assert len(overflow_lines) == 1, 'overflow event must be one-shot'
  st = r.stats()
  total_emits = threads * per + 1          # + the overflow event
  assert st['dropped_file_events'] == total_emits - file_cap
  # every emit past ring capacity dropped an oldest event — counted
  assert st['ring_dropped'] == total_emits - ring_cap
  # ring: full at capacity, holding each thread's NEWEST emissions —
  # the oldest-drop contract (per-thread order is preserved by the
  # single append lock, so kept i's are each thread's tail)
  ring = r.events('t')
  assert len(ring) == ring_cap == st['ring_events']
  by_tid = {}
  for e in ring:
    by_tid.setdefault(e['tid'], []).append(e['i'])
  for tid, seen in by_tid.items():
    assert seen == sorted(seen)
    assert seen == list(range(per - len(seen), per)), tid


# -- aggregation helpers ----------------------------------------------------

def test_gather_metrics_single_host_matches_local():
  reg = Metrics()
  reg.inc('dist.frontier.offered', 100)
  reg.inc('dist.frontier.dropped', 3)
  reg.inc('other.counter', 7)
  out = gather_metrics(reg)
  assert out['num_hosts'] == 1
  assert out['aggregate'] == reg.snapshot()
  assert out['per_host'] == [reg.snapshot()]
  only = gather_metrics(reg, prefix='dist.')
  assert set(only['aggregate']) == {'dist.frontier.offered',
                                    'dist.frontier.dropped'}


def test_exchange_summary_derivations():
  st = {'dist.frontier.offered': 100, 'dist.frontier.dropped': 10,
        'dist.frontier.slots': 300, 'dist.feature.offered': 0,
        'dist.feature.dropped': 0, 'dist.feature.slots': 0,
        'dist.feature.cold_lookups': 50, 'dist.feature.cold_misses': 5}
  s = exchange_summary(st)
  assert s['frontier_padding_waste_pct'] == pytest.approx(70.0)
  assert s['frontier_drop_rate_pct'] == pytest.approx(10.0)
  assert s['feature_padding_waste_pct'] is None
  assert s['cold_hit_rate'] == pytest.approx(0.9)


def test_per_hop_padding_stacked_axes():
  # [P, H+1] mesh form: capacities scale by the collapsed axis
  nsn = np.array([[4, 6, 10]] * 2)
  rows = per_hop_padding(nsn, 4, [2, 3])
  assert rows[0] == {'hop': 0, 'nodes': 8, 'capacity': 8, 'fill': 1.0}
  assert rows[1]['capacity'] == 16 and rows[1]['nodes'] == 12
  assert rows[2]['capacity'] == 48 and rows[2]['fill'] == pytest.approx(
      20 / 48)


# -- mesh-integrated paths (8-device virtual mesh) --------------------------

def _dist_dataset():
  from graphlearn_tpu.parallel import DistDataset
  rows = np.concatenate([np.arange(N), np.arange(N)])
  cols = np.concatenate([(np.arange(N) + 1) % N,
                         (np.arange(N) + 2) % N])
  feats = np.random.default_rng(0).random((N, 8), np.float32)
  labels = np.random.default_rng(1).integers(0, 4, N).astype(np.int32)
  return DistDataset.from_full_graph(P, rows, cols, node_feat=feats,
                                     node_label=labels, num_nodes=N)


@pytest.fixture(scope='module')
def dist_run(tmp_path_factory):
  """One adaptive dist-loader run (2 epochs) plus one fused dist
  epoch, flight recorder ON — several tests read its outputs.  Model
  init happens BEFORE the recorder turns on so the loader events in
  the JSONL all belong to the adaptive loader."""
  from graphlearn_tpu.models import GraphSAGE, create_train_state
  from graphlearn_tpu.parallel import (DistNeighborLoader,
                                       FusedDistEpoch, local_batch_piece,
                                       make_mesh, replicate)
  import optax
  path = str(tmp_path_factory.mktemp('telemetry') / 'flight.jsonl')
  ds = _dist_dataset()
  mesh = make_mesh(P)
  # recorder OFF: init batch + params
  b0 = next(iter(DistNeighborLoader(ds, FANOUT, np.arange(N),
                                    batch_size=BATCH, mesh=mesh,
                                    shuffle=True, seed=0)))
  model = GraphSAGE(hidden_features=8, out_features=4, num_layers=2)
  tx = optax.adam(1e-2)
  state, apply_fn = create_train_state(
      model, jax.random.key(0), local_batch_piece(b0, P), tx)
  base = metrics.snapshot()
  recorder.enable(path, max_events=8192)
  try:
    loader = DistNeighborLoader(ds, FANOUT, np.arange(N),
                                batch_size=BATCH, shuffle=True,
                                mesh=mesh, seed=0,
                                exchange_slack='adaptive')
    nsn_per_batch = []
    for _ in range(2):
      for b in loader:
        nsn_per_batch.append(np.asarray(b.num_sampled_nodes))
    loader_stats = loader.sampler.exchange_stats()

    fused = FusedDistEpoch(ds, FANOUT, np.arange(N), apply_fn, tx,
                           batch_size=BATCH, mesh=mesh, shuffle=True,
                           seed=0)
    state = replicate(state, mesh)
    state, stats = fused.run(state)
    loss = stats.loss
    cluster = fused.cluster_exchange_stats()
  finally:
    recorder.disable()
  yield dict(path=path, loader=loader, fused=fused,
             loader_stats=loader_stats, cluster=cluster,
             nsn_per_batch=nsn_per_batch, base=base, loss=loss)


def test_flight_recorder_jsonl_complete(dist_run):
  lines = open(dist_run['path']).read().strip().splitlines()
  assert lines, 'flight recorder wrote nothing'
  kinds = {json.loads(ln)['kind'] for ln in lines}
  # the acceptance trio: per-hop padding fill, a slack-ladder
  # transition, and exchange drains all land in ONE JSONL
  assert 'hop.padding' in kinds
  assert 'slack.transition' in kinds
  assert 'dist.exchange' in kinds


def test_per_hop_gauges_match_loader(dist_run):
  """The recorder's hop.padding events must equal the gauges computed
  from the loader's own num_sampled_nodes output."""
  evs = [e for e in recorderless_events(dist_run['path'], 'hop.padding')
         if e.get('scope') == 'dist_loader']
  per_batch = {}
  for e in evs:
    per_batch.setdefault(e['batch'], []).append(e)
  assert len(per_batch) == len(dist_run['nsn_per_batch'])
  for bidx, nsn in enumerate(dist_run['nsn_per_batch'], start=1):
    want = per_hop_padding(nsn, BATCH, FANOUT)
    got = sorted(per_batch[bidx], key=lambda e: e['hop'])
    assert len(got) == len(FANOUT) + 1
    for w, g in zip(want, got):
      assert g['nodes'] == w['nodes']
      assert g['capacity'] == w['capacity']
      assert g['fill'] == pytest.approx(w['fill'])
      assert 0.0 < g['fill'] <= 1.0


def test_exchange_events_sum_to_loader_waste(dist_run):
  """Summing the dist.exchange drain deltas reproduces the loader's
  padding_waste_pct exactly — the events are the same counters the
  bench derives its number from.  The loader drained fully before the
  fused phase, so its totals are a PREFIX of the event stream."""
  evs = recorderless_events(dist_run['path'], 'dist.exchange')
  st = dist_run['loader_stats']
  waste_loader = 100.0 * (
      1 - (st['dist.frontier.offered'] - st['dist.frontier.dropped'])
      / max(st['dist.frontier.slots'], 1))
  run_off = run_drop = run_slots = 0
  matched = False
  for e in evs:
    run_off += e['frontier_offered']
    run_drop += e['frontier_dropped']
    run_slots += e['frontier_slots']
    if run_off == st['dist.frontier.offered']:
      matched = True
      waste_prefix = 100.0 * (1 - (run_off - run_drop)
                              / max(run_slots, 1))
      assert waste_prefix == pytest.approx(waste_loader)
      break
  assert matched, 'loader totals never appeared in the event stream'


def test_gather_metrics_mesh_delta_consistent(dist_run):
  """`gather_metrics` over the global registry: the delta ticked
  during the run equals the two samplers' host-local totals summed —
  the cluster aggregate is consistent with the per-host numbers."""
  agg = gather_metrics(prefix='dist.')
  assert agg['num_hosts'] == 1
  base = dist_run['base']
  delta = (agg['aggregate'].get('dist.frontier.offered', 0)
           - base.get('dist.frontier.offered', 0))
  fused_st = dist_run['fused'].sampler.exchange_stats(
      tick_metrics=False)
  want = (dist_run['loader_stats']['dist.frontier.offered']
          + fused_st['dist.frontier.offered'])
  assert delta == want


def test_fused_epoch_hop_events_and_cluster(dist_run):
  evs = [e for e in recorderless_events(dist_run['path'], 'hop.padding')
         if e.get('scope') == 'FusedDistEpoch']
  assert len(evs) == len(FANOUT) + 1
  by_hop = {e['hop']: e for e in evs}
  steps = evs[0]['steps']
  assert by_hop[0]['capacity'] == BATCH * P * steps
  for h in range(len(FANOUT) + 1):
    assert 0.0 < by_hop[h]['fill'] <= 1.0
  # hop 0 = seeds: every seed slot was a real seed in this run
  assert by_hop[0]['fill'] == pytest.approx(1.0)
  assert np.isfinite(dist_run['loss'])

  # cluster-wide report must be CONSISTENT with the sampler's own
  # host-local totals (single controller: identical) and with the
  # derivation helper
  cluster = dist_run['cluster']
  assert cluster['num_hosts'] == 1
  st = dist_run['fused'].sampler.exchange_stats(tick_metrics=False)
  assert cluster['dist.frontier.offered'] == \
      st['dist.frontier.offered']
  assert cluster['dist.feature.slots'] == st['dist.feature.slots']
  want = exchange_summary(st)
  assert cluster['frontier_padding_waste_pct'] == \
      want['frontier_padding_waste_pct']
  assert cluster['frontier_drop_rate_pct'] == 0.0


def test_slack_transition_event_fields(dist_run):
  evs = recorderless_events(dist_run['path'], 'slack.transition')
  assert evs, 'adaptive controller never transitioned'
  e = evs[0]
  assert e['reason'] in ('drops', 'drop_free')
  assert e['from_slack'] != e['to_slack']
  assert metrics.snapshot().get('dist.slack.transitions', 0) >= len(evs)


def recorderless_events(path, kind):
  return [json.loads(ln) for ln in open(path).read().splitlines()
          if json.loads(ln)['kind'] == kind]


# -- compile-cache dispatch telemetry (satellite) ---------------------------

def test_uncached_jit_dispatch_time_env(monkeypatch):
  from graphlearn_tpu.loader.fused import _uncached_jit
  calls = {'n': 0}

  def f(x):
    calls['n'] += 1
    return x + 1

  base = metrics.snapshot()
  wrapped = _uncached_jit(f, cacheable=True)
  monkeypatch.delenv('GLT_FUSED_COMPILE_CACHE', raising=False)
  out = wrapped(jnp.zeros((4,)))
  assert float(out.sum()) == 4.0
  # env flipped AFTER construction must take effect (dispatch-time
  # read): the cached path still executes correctly
  monkeypatch.setenv('GLT_FUSED_COMPILE_CACHE', '1')
  out = wrapped(jnp.ones((4,)))
  assert float(out.sum()) == 8.0
  snap = metrics.snapshot()
  assert snap.get('fused.compile.misses', 0) > base.get(
      'fused.compile.misses', 0)
  # second call with identical shapes is an in-memory hit
  wrapped(jnp.ones((4,)))
  assert metrics.snapshot().get('fused.compile.hits', 0) > base.get(
      'fused.compile.hits', 0)
  assert wrapped.jitted is not None


def test_uncached_jit_not_cacheable_ignores_env(monkeypatch):
  """Full-length programs must NEVER take the persistent-cache path,
  even with the env var set (the r3 watchdog crash class)."""
  from graphlearn_tpu.loader import fused as fused_mod
  seen = []
  orig = fused_mod._fresh_compile
  monkeypatch.setattr(fused_mod, '_fresh_compile',
                      lambda: (seen.append(1), orig())[1])
  monkeypatch.setenv('GLT_FUSED_COMPILE_CACHE', '1')
  wrapped = fused_mod._uncached_jit(lambda x: x * 2, cacheable=False)
  wrapped(jnp.ones((2,)))
  assert seen, 'cacheable=False must still route through _fresh_compile'
  seen.clear()
  cached = fused_mod._uncached_jit(lambda x: x * 3, cacheable=True)
  cached(jnp.ones((2,)))
  assert not seen, 'cacheable=True + env=1 must skip _fresh_compile'


def test_fused_compile_event_emitted(tmp_path):
  from graphlearn_tpu.loader.fused import _uncached_jit
  p = str(tmp_path / 'f.jsonl')
  recorder.enable(p)
  try:
    wrapped = _uncached_jit(lambda x: x - 1)
    wrapped(jnp.ones((3,)))
  finally:
    recorder.disable()
  evs = [json.loads(ln) for ln in open(p).read().splitlines()]
  comp = [e for e in evs if e['kind'] == 'fused.compile']
  assert comp and comp[0]['secs'] >= 0
  assert comp[0]['persistent_cache'] is False


# -- channel stall telemetry ------------------------------------------------

def test_channel_stall_recorded(tmp_path):
  from graphlearn_tpu.channel import MpChannel
  p = str(tmp_path / 'f.jsonl')
  recorder.enable(p)
  ch = MpChannel()
  try:
    def produce():
      time.sleep(0.15)
      ch.send({'a': np.arange(3)})

    t = threading.Thread(target=produce)
    t.start()
    msg = ch.recv()                     # blocks ~0.15s -> stall
    t.join()
  finally:
    recorder.disable()
    ch.close()
  assert msg['a'].tolist() == [0, 1, 2]
  snap = metrics.snapshot()
  assert snap.get('channel.recv.calls', 0) >= 1
  assert snap.get('channel.recv.stalls', 0) >= 1
  evs = [json.loads(ln) for ln in open(p).read().splitlines()
         if json.loads(ln)['kind'] == 'channel.stall']
  assert evs and evs[0]['op'] == 'recv'
  assert evs[0]['secs'] >= 0.1


# -- data satellites --------------------------------------------------------

def test_device_csr_num_nodes_mismatch_raises():
  from graphlearn_tpu.data import Dataset
  indptr = jnp.asarray(np.array([0, 1, 2, 2], np.int32))   # 3 nodes
  indices = jnp.asarray(np.array([1, 2], np.int32))
  with pytest.raises(ValueError, match='num_nodes'):
    Dataset().init_graph((indptr, indices), layout='CSR', num_nodes=5)
  ds = Dataset().init_graph((indptr, indices), layout='CSR',
                            num_nodes=3)
  assert ds.get_graph().num_nodes == 3


def test_device_csr_requires_both_device_arrays():
  """A mixed (jax.Array, numpy) pair must NOT take the device-native
  fast path; it flows through the host CSR builder and still works."""
  from graphlearn_tpu.data import Dataset
  indptr = jnp.asarray(np.array([0, 1, 2, 2], np.int32))
  indices = np.array([1, 2], np.int32)                      # host!
  ds = Dataset().init_graph((indptr, indices), layout='CSR',
                            num_nodes=3)
  g = ds.get_graph()
  assert g.num_nodes == 3
  assert isinstance(g.indices, jax.Array)


def test_feature_sort_func_with_device_table_raises():
  from graphlearn_tpu.data import Dataset
  from graphlearn_tpu.data.reorder import sort_by_in_degree
  feats = jnp.ones((4, 2))
  with pytest.raises(ValueError, match='sort_func'):
    Dataset().init_node_features(feats, sort_func=sort_by_in_degree)


def test_feature_device_native_honors_device():
  from graphlearn_tpu.data.feature import Feature
  devs = jax.devices()
  if len(devs) < 2:
    pytest.skip('needs >= 2 devices')
  arr = jax.device_put(jnp.ones((4, 2)), devs[0])
  f = Feature(arr, device=devs[1])
  assert devs[1] in f.hot_tier.devices()
  # same-device placement is a no-op (no copy)
  f0 = Feature(arr, device=devs[0])
  assert f0.hot_tier is arr
