"""Worker for the 2-process span-histogram merge test
(tests/test_spans.py::test_histograms_merge_across_two_process_mesh):
each process records span latencies into its LOCAL metrics registry,
then `gather_metrics(prefix='span.')` allgathers + sums the flat
histogram encodings over the real cross-process collective plane.
"""
import json
import sys
import time

coordinator, num_procs, proc_id, out_file = (
    sys.argv[1], int(sys.argv[2]), int(sys.argv[3]), sys.argv[4])

from graphlearn_tpu.parallel import multihost

multihost.initialize(coordinator_address=coordinator,
                     num_processes=num_procs, process_id=proc_id)

import jax

assert jax.process_count() == num_procs, jax.process_count()

from graphlearn_tpu.telemetry import gather_metrics, recorder, span

recorder.enable()                       # ring-only: spans need it on
try:
  # proc 0 records 1 span, proc 1 records 2 — the merged histogram
  # must show count 3 on BOTH processes
  for i in range(proc_id + 1):
    with span('mesh.stage', proc=proc_id, i=i):
      time.sleep(0.005 * (proc_id + 1))
finally:
  recorder.disable()

agg = gather_metrics(prefix='span.')
with open(out_file, 'w') as f:
  json.dump(agg, f)
print('WORKER OK', proc_id)
