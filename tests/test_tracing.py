"""Request-scoped fleet tracing + memory/capacity accounting
(ISSUE 17): tail-based retention rules, the in-process router trace
tree, exemplar round-trips through the strict exposition parsers and
the federation merge, the ``GLT_TRACE_SAMPLE=0`` byte-identity
contract, per-tier memory gauges vs actual nbytes, the capacity/
headroom model — and the acceptance gate: one serve request routed
over the REAL 2-process DistServer RPC yields one assembled trace
with ≥5 spans across ≥2 pids, fetchable at ``/trace?trace_id=`` and
Perfetto-exportable with cross-process flow events.
"""
import json
import multiprocessing as mp
import time
import urllib.request

import numpy as np
import pytest

from graphlearn_tpu.telemetry import Metrics
from graphlearn_tpu.telemetry.live import (LiveRegistry,
                                           parse_prometheus_text,
                                           split_exemplar)
from graphlearn_tpu.telemetry.memaccount import (TIERS, CapacityModel,
                                                 register_tier)
from graphlearn_tpu.telemetry.tracing import (Tracer, child_ctx,
                                              spans_to_events, tracer)

N, D = 48, 4
FANOUTS = [2, 2]
BUCKETS = (1, 2, 4)


def _reg():
  return LiveRegistry(store=Metrics(), strict=True)


def _tiered_dataset():
  from graphlearn_tpu.data import Dataset
  from graphlearn_tpu.data.feature import Feature
  rng = np.random.default_rng(0)
  rows = np.repeat(np.arange(N), 4)
  cols = rng.integers(0, N, rows.shape[0])
  feats = (np.arange(N, dtype=np.float32)[:, None]
           * np.ones((1, D), np.float32))
  ds = Dataset().init_graph((rows, cols), layout='COO', num_nodes=N)
  # tiered store (hot split + cold cache): the serve path pays a
  # host cold fill, so traced requests grow a `serving.cold_fill` leg
  ds.node_features = Feature(feats, split_ratio=0.5, cold_cache_rows=8)
  return ds


@pytest.fixture(autouse=True)
def _trace_clean():
  yield
  tracer.configure(sample=0, slow_ms=0.0, buffer=None)
  tracer.clear()


# -- tracer unit behavior ------------------------------------------------------
def test_tail_retention_rules():
  tr = Tracer(sample=2, slow_ms=50.0, buffer=4)
  c1, c2 = tr.mint(), tr.mint()
  assert c1['k'] == 1 and c2['k'] == 0     # 1-in-2 head sample
  tr.span('serving.route', c2, dur=0.001)
  # fast + ok + unsampled -> dropped (and its pending spans freed)
  assert not tr.resolve(c2, outcome='ok', latency_ms=1.0)
  assert tr.spans_of(c2['t']) == []
  # head-sampled -> retained even when fast
  assert tr.resolve(c1, outcome='ok', latency_ms=1.0)
  c3, c4 = tr.mint(), tr.mint()
  assert c4['k'] == 0
  # slow tail -> retained without the sample bit
  assert tr.resolve(c4, outcome='ok', latency_ms=60.0)
  # failed/shed -> retained regardless of speed and sampling
  c5, c6 = tr.mint(), tr.mint()
  assert c6['k'] == 0
  assert tr.resolve(c6, outcome='shed', latency_ms=0.1)
  idx = tr.traces()
  assert [e['outcome'] for e in idx] == ['shed', 'ok', 'ok']
  assert idx[0]['trace_id'] == c6['t']      # newest first


def test_resolve_merge_is_idempotent():
  tr = Tracer(sample=1, slow_ms=0.0, buffer=8)
  ctx = tr.mint()
  tr.span('serving.queue_wait', ctx, dur=0.001)
  assert tr.resolve(ctx, outcome='ok', latency_ms=2.0)
  # a late span (the rpc wrapper closing after the frontend resolved)
  # merges into the retained tree, and a second resolve upgrades the
  # outcome/latency instead of double-retaining
  tr.span('serving.rpc', ctx, dur=0.002)
  assert tr.resolve(ctx, outcome='error', latency_ms=5.0)
  assert len(tr.traces()) == 1
  entry = tr.traces()[0]
  assert entry['outcome'] == 'error'
  assert entry['latency_ms'] == 5.0
  assert {s['name'] for s in tr.spans_of(ctx['t'])} == \
      {'serving.queue_wait', 'serving.rpc'}


def test_retained_ring_is_bounded():
  tr = Tracer(sample=1, slow_ms=0.0, buffer=3)
  tids = []
  for _ in range(5):
    ctx = tr.mint()
    tids.append(ctx['t'])
    tr.resolve(ctx, outcome='ok')
  idx = [e['trace_id'] for e in tr.traces()]
  assert idx == list(reversed(tids[-3:]))   # oldest evicted first


def test_chrome_export_flow_events_across_pids():
  """Cross-process parent→child edges become Perfetto flow arrows
  ('s'/'f' pairs), and every span exports as one balanced X slice."""
  from graphlearn_tpu.telemetry.export import to_chrome_trace
  root = {'kind': 'span', 'name': 'serving.route', 'trace_id': 'T',
          'span_id': 'a', 'parent_id': None, 'pid': 100, 'tid': 1,
          'ts': 1000.0, 'dur': 0.05}
  child = {'kind': 'span', 'name': 'serving.rpc', 'trace_id': 'T',
           'span_id': 'b', 'parent_id': 'a', 'pid': 200, 'tid': 2,
           'ts': 1000.01, 'dur': 0.03}
  events = spans_to_events([root, child])
  assert all('mono' not in e for e in events)   # wall-clock timebase
  trace = to_chrome_trace(events)
  evs = trace['traceEvents']
  xs = [e for e in evs if e.get('ph') == 'X']
  assert len(xs) == 2
  starts = [e for e in evs if e.get('ph') == 's']
  finishes = [e for e in evs if e.get('ph') == 'f']
  assert len(starts) == 1 and len(finishes) == 1
  assert starts[0]['pid'] == 100 and finishes[0]['pid'] == 200
  assert starts[0]['id'] == finishes[0]['id']


# -- exemplars -----------------------------------------------------------------
def test_exemplar_roundtrip_render_parse_federate():
  from graphlearn_tpu.telemetry.federation import (FleetScraper,
                                                   parse_exposition)
  r1, r2 = _reg(), _reg()
  h1 = r1.histogram('serving.request_latency', labels={'bucket': 4})
  h1.observe(0.2, exemplar='aaaa00000000000b')
  h1.observe(0.004)                 # exemplar-free bucket stays bare
  h2 = r2.histogram('serving.request_latency', labels={'bucket': 4})
  h2.observe(0.1, exemplar='cccc00000000000d')
  text = r1.prometheus_text()
  ex_lines = [ln for ln in text.splitlines() if ' # {' in ln]
  assert len(ex_lines) == 1
  assert '# {trace_id="aaaa00000000000b"}' in ex_lines[0]
  assert '_bucket{' in ex_lines[0]
  sample, ex = split_exemplar(ex_lines[0])
  assert ' # {' not in sample and 'trace_id="aaaa00000000000b"' in ex
  # both strict parsers accept-and-strip the exemplar suffix
  flat = parse_prometheus_text(text)
  assert any(k.startswith('glt_serving_request_latency_bucket{')
             for k in flat)
  fams = parse_exposition(text)
  assert 'glt_serving_request_latency' in fams
  # federation merge over exemplar-carrying expositions stays exact
  fs = FleetScraper(registry=_reg())
  fs.add_registry('a', r1)
  fs.add_registry('b', r2)
  fs.scrape()
  merged = parse_prometheus_text(fs.prometheus_text())
  assert merged[
      'glt_fleet_serving_request_latency_bucket{bucket="4",le="+Inf"}'
  ] == 3.0


def test_exemplar_of_and_report_jump():
  from graphlearn_tpu.telemetry.histogram import bucket_index
  from graphlearn_tpu.telemetry.report import format_exemplars
  reg = _reg()
  h = reg.histogram('serving.request_latency', labels={'bucket': 2})
  h.observe(0.2, exemplar='feedfacefeedface')
  assert reg.exemplar_of(h.key, bucket_index(0.2))[0] == \
      'feedfacefeedface'
  table = format_exemplars(reg.prometheus_text())
  assert 'feedfacefeedface' in table
  assert '/trace?trace_id=feedfacefeedface' in table


# -- memory + capacity accounting ----------------------------------------------
def test_memaccount_gauges_match_nbytes():
  reg = _reg()
  arrays = {'streaming': np.zeros((100, 8), np.float32),
            'cold_cache': np.zeros((16, 4), np.float32),
            'wal': np.zeros(333, np.uint8),
            # r19: the zero-copy cold feature buffer's tier
            'pinned_host': np.zeros((64, 16), np.float32)}
  unregs = [register_tier(t, lambda a=a: a.nbytes, registry=reg)
            for t, a in arrays.items()]
  snap = parse_prometheus_text(reg.prometheus_text())
  total = 0
  for t, a in arrays.items():
    assert snap[f'glt_memory_tier_bytes{{tier="{t}"}}'] == a.nbytes
    assert snap[f'glt_memory_tier_peak_bytes{{tier="{t}"}}'] == \
        a.nbytes
    total += a.nbytes
  assert sum(v for k, v in snap.items()
             if k.startswith('glt_memory_tier_bytes{')) == total
  for u in unregs:
    u()
  assert 'glt_memory_tier_bytes' not in reg.prometheus_text()


def test_memaccount_peak_watermark_and_closed_vocabulary():
  reg = _reg()
  state = {'n': 4096}
  register_tier('gns', lambda: state['n'], registry=reg)
  snap = parse_prometheus_text(reg.prometheus_text())
  assert snap['glt_memory_tier_peak_bytes{tier="gns"}'] == 4096
  state['n'] = 128                  # occupancy shrinks, peak stands
  snap = parse_prometheus_text(reg.prometheus_text())
  assert snap['glt_memory_tier_bytes{tier="gns"}'] == 128
  assert snap['glt_memory_tier_peak_bytes{tier="gns"}'] == 4096
  with pytest.raises(ValueError):
    register_tier('scratch', lambda: 1, registry=reg)
  assert 'scratch' not in TIERS


def test_capacity_model_headroom():
  reg = _reg()
  cm = CapacityModel(slo=None, registry=reg)
  assert cm.capacity_qps() is None  # no dispatches yet -> no claim
  # header declared, but no SAMPLE until the first dispatch lands
  assert '\nglt_fleet_headroom_qps ' not in reg.prometheus_text()
  cm.observe(bucket=4, requests=2, secs=0.2)   # 0.1 s/request
  assert cm.capacity_qps() == pytest.approx(10.0)
  snap = parse_prometheus_text(reg.prometheus_text())
  assert snap['glt_fleet_headroom_qps'] == pytest.approx(10.0)
  # the EWMA tracks a cost shift; weights follow the traffic mix
  for _ in range(50):
    cm.observe(bucket=4, requests=1, secs=0.05)
  assert cm.capacity_qps() == pytest.approx(20.0, rel=0.15)
  cm.close()
  assert '\nglt_fleet_headroom_qps ' not in reg.prometheus_text()


# -- the serve plane, in process -----------------------------------------------
@pytest.fixture(scope='module')
def local_fleet():
  from graphlearn_tpu.serving import ServingEngine, ServingFrontend
  from graphlearn_tpu.serving.router import FleetRouter, LocalReplica
  engine = ServingEngine(_tiered_dataset(), FANOUTS, seed=7,
                         buckets=BUCKETS)
  frontend = ServingFrontend(engine, auto_start=True, warmup=True,
                             max_wait_ms=1.0,
                             default_deadline_ms=4000.0)
  router = FleetRouter([LocalReplica('r0', frontend)],
                       auto_start=False)
  yield router, frontend, engine
  router.close()
  frontend.shutdown()


def test_local_router_trace_tree_and_exemplar(local_fleet):
  from graphlearn_tpu.telemetry.live import live
  router, frontend, _ = local_fleet
  tracer.configure(sample=1, slow_ms=0.0, buffer=64)
  tracer.clear()
  router.infer([3, 5], timeout=60)
  idx = tracer.traces()
  assert len(idx) == 1 and idx[0]['outcome'] == 'ok'
  tid = idx[0]['trace_id']
  spans = tracer.spans_of(tid)
  by_name = {s['name']: s for s in spans}
  assert {'serving.route', 'serving.queue_wait',
          'serving.dispatch_slice', 'serving.sample_collect',
          'serving.cold_fill'} <= set(by_name)
  root = by_name['serving.route']
  assert root['span_id'] == tid and root['parent_id'] is None
  assert by_name['serving.queue_wait']['parent_id'] == tid
  for leaf in ('serving.sample_collect', 'serving.cold_fill'):
    assert by_name[leaf]['parent_id'] == \
        by_name['serving.dispatch_slice']['span_id']
  # the trace id landed as the latency histogram's bucket exemplar
  ex = [ln for ln in live.prometheus_text().splitlines()
        if f'trace_id="{tid}"' in ln]
  assert ex and all('glt_serving_request_latency_bucket{' in ln
                    for ln in ex)
  # the capacity model saw the dispatch -> headroom is exported
  assert 'headroom_qps' in frontend.stats()


def test_sample_zero_is_byte_identical(local_fleet):
  from graphlearn_tpu.telemetry.live import live
  router, _, _ = local_fleet
  seeds = [7, 11, 13]
  tracer.configure(sample=1, slow_ms=0.0, buffer=64)
  tracer.clear()
  traced = router.infer(seeds, timeout=60)
  tracer.configure(sample=0, slow_ms=0.0, buffer=64)
  tracer.clear()
  before = dict(live._exemplars)
  untraced = router.infer(seeds, timeout=60)
  # the data plane is byte-identical with tracing off...
  assert untraced.nodes.tobytes() == traced.nodes.tobytes()
  assert untraced.x.tobytes() == traced.x.tobytes()
  # ...and nothing was minted, retained, or exemplar-stamped
  st = tracer.stats()
  assert st['minted'] == 0 and st['retained'] == 0 \
      and st['pending'] == 0
  assert dict(live._exemplars) == before


def test_shed_trace_is_retained(local_fleet):
  from graphlearn_tpu.serving import AdmissionRejected
  router, _, _ = local_fleet
  tracer.configure(sample=1000000, slow_ms=0.0, buffer=64)
  tracer.clear()
  tracer.mint()                     # burn the 1-in-N head-sample slot
  with pytest.raises(AdmissionRejected):
    router.infer(list(range(BUCKETS[-1] + 1)), timeout=60)
  # the shed request was NOT head-sampled, yet its trace is
  # tail-retained (outcome != ok is always interesting)
  idx = tracer.traces()
  assert len(idx) == 1 and idx[0]['outcome'] == 'shed'
  assert idx[0]['sampled'] == 0


# -- the acceptance gate: 2-process trace assembly -----------------------------
class _StubHostDataset:
  """`DistServer` wants a dataset for the PRODUCER path; serving
  tests never touch producers (the test_serving_rpc stub)."""
  num_nodes = N
  num_edges = N * 4
  node_features = None
  node_labels = None


def _traced_server_proc(q):
  """Child: serving tier + RPC server + ops endpoint; exits when the
  parent client leaves."""
  from graphlearn_tpu.distributed import (init_server,
                                          wait_and_shutdown_server)
  from graphlearn_tpu.serving import ServingEngine, ServingFrontend
  from graphlearn_tpu.telemetry.opsserver import OpsServer
  engine = ServingEngine(_tiered_dataset(), FANOUTS, seed=7,
                         buckets=BUCKETS)
  frontend = ServingFrontend(engine, auto_start=True, warmup=True,
                             max_wait_ms=1.0,
                             default_deadline_ms=8000.0)
  srv = init_server(num_servers=1, num_clients=1, rank=0,
                    dataset=_StubHostDataset(), host='127.0.0.1',
                    port=0)
  srv.attach_serving(frontend)
  ops = OpsServer(port=0)
  q.put((srv.port, ops.url))
  wait_and_shutdown_server(timeout=300)


@pytest.mark.slow
def test_cross_process_trace_assembly():
  """One routed serve request through FleetRouter → RemoteReplica →
  the real serve RPC → coalesced dispatch → tiered cold fill yields
  ONE assembled trace: ≥5 spans, ≥2 processes, correct parentage,
  fetchable via the coordinator's ``/trace?trace_id=`` and exported
  as a Perfetto-loadable Chrome trace with flow events."""
  from graphlearn_tpu.distributed import init_client
  from graphlearn_tpu.serving.router import FleetRouter, RemoteReplica
  from graphlearn_tpu.telemetry.federation import FleetScraper
  from graphlearn_tpu.telemetry.opsserver import OpsServer

  ctx_mp = mp.get_context('forkserver')
  q = ctx_mp.Queue()
  # non-daemonic: the server process owns its own threads/executors
  proc = ctx_mp.Process(target=_traced_server_proc, args=(q,),
                        daemon=False)
  proc.start()
  client = router = None
  try:
    port, ops_url = q.get(timeout=240)
    client = init_client([('127.0.0.1', port)], rank=0,
                         num_clients=1)
    tracer.configure(sample=1, slow_ms=0.0, buffer=64)
    tracer.clear()
    router = FleetRouter([RemoteReplica('r0', client, 0)],
                         auto_start=False)
    out = router.infer([3, 5], timeout=120)
    assert out.nodes.shape[0] == 2

    idx = tracer.traces()
    assert len(idx) == 1
    tid = idx[0]['trace_id']
    # this process only saw the routing leg...
    assert {s['name'] for s in tracer.spans_of(tid)} == \
        {'serving.route'}
    # ...the fleet scraper reassembles the full cross-process tree
    fs = FleetScraper(registry=_reg())
    fs.add_url('r0', ops_url)
    spans = fs.fetch_trace(tid)
    by_name = {s['name']: s for s in spans}
    assert {'serving.route', 'serving.rpc', 'serving.queue_wait',
            'serving.dispatch_slice', 'serving.sample_collect',
            'serving.cold_fill'} <= set(by_name)
    assert len(spans) >= 5
    assert len({s['pid'] for s in spans}) >= 2
    root = by_name['serving.route']
    rpc = by_name['serving.rpc']
    assert root['parent_id'] is None and root['span_id'] == tid
    assert rpc['parent_id'] == root['span_id']
    assert rpc['pid'] != root['pid']
    for child in ('serving.queue_wait', 'serving.dispatch_slice'):
      assert by_name[child]['parent_id'] == rpc['span_id']
      assert by_name[child]['pid'] == rpc['pid']
    for leaf in ('serving.sample_collect', 'serving.cold_fill'):
      assert by_name[leaf]['parent_id'] == \
          by_name['serving.dispatch_slice']['span_id']

    # the ops routes serve the assembled trace
    ops = OpsServer(registry=_reg(), port=0)
    ops.attach_fleet(fs)
    try:
      with urllib.request.urlopen(
          f'{ops.url}/trace?trace_id={tid}', timeout=15) as r:
        payload = json.loads(r.read().decode('utf-8'))
      assert payload['trace_id'] == tid
      assert len(payload['spans']) >= 5
      with urllib.request.urlopen(
          f'{ops.url}/trace?trace_id={tid}&format=chrome',
          timeout=15) as r:
        chrome = json.loads(r.read().decode('utf-8'))
      xs = [e for e in chrome['traceEvents'] if e.get('ph') == 'X']
      assert len(xs) == len(spans)     # balanced: every span a slice
      assert any(e.get('ph') == 's' for e in chrome['traceEvents'])
      assert any(e.get('ph') == 'f' for e in chrome['traceEvents'])
    finally:
      ops.close()

    # the child's own /traces index lists the retained trace
    with urllib.request.urlopen(f'{ops_url}/traces', timeout=15) as r:
      listing = json.loads(r.read().decode('utf-8'))
    assert any(e['trace_id'] == tid for e in listing['traces'])
  finally:
    if router is not None:
      router.close()
    if client is not None:
      client.shutdown()
    proc.join(timeout=120)
    if proc.is_alive():
      proc.terminate()
      proc.join(timeout=30)
  assert proc.exitcode == 0
