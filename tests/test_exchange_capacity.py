"""Capacity-bounded exchange: defaults, telemetry, and sampling bias.

The VERDICT-r1 "#1 scaling risk" items: `exchange_slack` must be a
defaulted, *measured* mechanism — shuffled loaders cap send buffers at
2x the balanced share, overflow drops are counted (never invisible),
and sampling statistics stay unbiased under the default cap.
"""
import numpy as np
import pytest

jax = pytest.importorskip('jax')

from graphlearn_tpu.parallel import (DistDataset, DistNeighborLoader,
                                     make_mesh)
from graphlearn_tpu.parallel.dist_sampler import (
    DEFAULT_EXCHANGE_SLACK, DistNeighborSampler, resolve_exchange_slack)
from graphlearn_tpu.utils.profiling import metrics

N = 512
DEG = 8
FANOUT = 4


def _regular_graph(seed=0):
  rng = np.random.default_rng(seed)
  rows = np.repeat(np.arange(N), DEG)
  cols = rng.integers(0, N, N * DEG)
  return rows.astype(np.int64), cols.astype(np.int64)


def test_auto_slack_resolution():
  assert resolve_exchange_slack('auto', True) == DEFAULT_EXCHANGE_SLACK
  assert resolve_exchange_slack('auto', False) is None
  assert resolve_exchange_slack(None, True) is None
  assert resolve_exchange_slack(3.0, False) == 3.0
  with pytest.raises(ValueError):
    resolve_exchange_slack('always', True)


def test_loader_defaults_capped_only_when_shuffled():
  rows, cols = _regular_graph()
  ds = DistDataset.from_full_graph(8, rows, cols, num_nodes=N)
  shuffled = DistNeighborLoader(ds, [FANOUT], np.arange(N),
                                batch_size=8, shuffle=True, mesh=make_mesh(8))
  sequential = DistNeighborLoader(ds, [FANOUT], np.arange(N),
                                  batch_size=8, shuffle=False, mesh=make_mesh(8))
  assert shuffled.sampler.exchange_slack == DEFAULT_EXCHANGE_SLACK
  assert sequential.sampler.exchange_slack is None


def test_sampling_unbiased_under_default_cap():
  """Every edge of a degree-8 graph must be selected with frequency
  ~= fanout/degree under the 2.0 cap, uniformly across owner
  partitions (owner-correlated drops would skew per-partition means).
  """
  rows, cols = _regular_graph()
  ds = DistDataset.from_full_graph(8, rows, cols, num_nodes=N, seed=3)
  epochs = 30
  loader = DistNeighborLoader(ds, [FANOUT], np.arange(N), batch_size=16,
                              shuffle=True, mesh=make_mesh(8), with_edge=True,
                              collect_features=False, seed=11)
  b_k = 16 * FANOUT
  counts = np.zeros(N * DEG, np.int64)
  for _ in range(epochs):
    for batch in loader:
      eids = np.asarray(batch.edge)[:, :b_k].reshape(-1)
      counts += np.bincount(eids[eids >= 0], minlength=N * DEG)
  freq = counts / epochs                     # per-edge selection freq
  expect = FANOUT / DEG
  assert abs(freq.mean() - expect) < 0.02
  # owner-partition uniformity: edges grouped by their source's owner
  owner = ds.old2new[rows] * 8 // N          # bounds are equal ranges
  for p in range(8):
    sel = freq[owner == p]
    assert abs(sel.mean() - expect) < 0.03, f'owner {p} biased'
  # the default cap on this balanced workload loses (almost) nothing
  st = loader.sampler.exchange_stats(tick_metrics=False)
  assert st['dist.frontier.dropped'] <= 0.01 * st['dist.frontier.offered']


def test_overflow_drops_are_counted():
  """A deliberately starved capacity must (a) drop frontier ids, (b)
  surface them in exchange_stats AND the global metrics registry, and
  (c) still never emit a wrong edge."""
  n2 = 8192
  rng = np.random.default_rng(2)
  rows = np.repeat(np.arange(n2), 2)
  cols = rng.integers(0, n2, n2 * 2)
  edge_set = set(zip(rows.tolist(), cols.tolist()))
  ds = DistDataset.from_full_graph(8, rows, cols, num_nodes=n2, seed=5)
  sampler = DistNeighborSampler(ds, [2], mesh=make_mesh(8),
                                collect_features=False,
                                exchange_slack=0.25)
  # 1024 DISTINCT seeds/device (the inducer dedups repeats): ~128 per
  # owner against the starved cap max(1024/8*0.25, floor)=64 ->
  # guaranteed overflow
  seeds = ds.old2new[np.arange(n2)].reshape(8, 1024)
  out = sampler.sample_from_nodes(seeds)
  node = np.asarray(out['node'])
  row_l = np.asarray(out['row'])
  col_l = np.asarray(out['col'])
  new2old = ds.new2old
  for d in range(8):
    for i in np.nonzero(row_l[d] >= 0)[0]:
      u = int(new2old[node[d, col_l[d, i]]])
      v = int(new2old[node[d, row_l[d, i]]])
      # emitted direction is transposed (neighbor -> seed)
      assert (u, v) in edge_set
  st = sampler.exchange_stats()              # ticks global metrics
  assert st['dist.frontier.dropped'] > 0
  snap = metrics.snapshot()
  assert snap.get('dist.frontier.dropped', 0) >= st['dist.frontier.dropped']
  # accounting invariant: what was actually sent fits in the slots
  assert (st['dist.frontier.slots']
          >= st['dist.frontier.offered'] - st['dist.frontier.dropped'])


def test_negative_loss_counter():
  """On a near-complete bipartite-ish graph strict negatives exhaust
  their trials; the lost count must reach the telemetry."""
  n = 32
  rows = np.repeat(np.arange(n), n)
  cols = np.tile(np.arange(n), n)
  from graphlearn_tpu.parallel.dist_sampler import DistLinkNeighborSampler
  ds = DistDataset.from_full_graph(8, rows, cols, num_nodes=n, seed=7)
  sampler = DistLinkNeighborSampler(ds, [2], neg_sampling='binary',
                                    mesh=make_mesh(8), collect_features=False)
  pairs = np.stack([ds.old2new[rows[:64]], ds.old2new[cols[:64]]],
                   axis=1).reshape(8, 8, 2)
  out = sampler.sample_from_edges(pairs)
  st = sampler.exchange_stats(tick_metrics=False)
  assert st['dist.negative.lost'] > 0
  mask = np.asarray(out['metadata']['edge_label_mask'])
  lab = np.asarray(out['metadata']['edge_label'])
  assert not mask[lab == 0].any()
