"""Worker for the P=16 scale smoke test (tests/test_scale_p16.py).

Runs one batch of each mesh engine — node, hetero, induced-subgraph —
on a 16-device virtual CPU mesh (twice the suite's fixed 8), checking
output validity so compile + execute beyond P=8 is demonstrated, not
assumed.
"""
import json
import sys

import numpy as np
import jax

assert len(jax.devices()) == 16, jax.devices()

from graphlearn_tpu.parallel import (DistDataset, DistHeteroNeighborLoader,
                                     DistNeighborLoader, DistSubGraphLoader,
                                     make_mesh)
from graphlearn_tpu.parallel.dist_hetero import DistHeteroDataset

P = 16
mesh = make_mesh(P)
out_file = sys.argv[1]
report = {}

n = 256
rng = np.random.default_rng(0)
rows = np.concatenate([np.arange(n), np.arange(n)])
cols = np.concatenate([(np.arange(n) + 1) % n, (np.arange(n) + 3) % n])
feats = np.tile(np.arange(n, dtype=np.float32)[:, None], (1, 4))
edge_set = set(zip(rows.tolist(), cols.tolist()))

ds = DistDataset.from_full_graph(P, rows, cols, node_feat=feats,
                                 num_nodes=n)
loader = DistNeighborLoader(ds, [3, 2], np.arange(n), batch_size=4,
                            shuffle=True, mesh=mesh, seed=0)
b = next(iter(loader))
node = np.asarray(b.node)
x = np.asarray(b.x)
nm = np.asarray(b.node_mask)
rl, cl = np.asarray(b.edge_index)[:, 0], np.asarray(b.edge_index)[:, 1]
ok_edges = 0
for p in range(P):
  m = np.asarray(b.edge_mask)[p]
  u = ds.new2old[node[p][cl[p][m]]]
  v = ds.new2old[node[p][rl[p][m]]]
  assert (((v - u) % n == 1) | ((v - u) % n == 3)).all()
  ok_edges += int(m.sum())
  np.testing.assert_allclose(x[p][nm[p]][:, 0], ds.new2old[node[p][nm[p]]])
report['node_edges'] = ok_edges
st = loader.sampler.exchange_stats(tick_metrics=False)
report['dropped'] = st['dist.frontier.dropped']

hds = DistHeteroDataset.from_full_graph(
    P, {('u', 'to', 'i'): (rng.integers(0, 96, 384),
                           rng.integers(0, 64, 384))},
    node_feat_dict={'u': np.arange(96, dtype=np.float32)[:, None]},
    num_nodes_dict={'u': 96, 'i': 64})
hl = DistHeteroNeighborLoader(hds, [2], ('u', np.arange(96)),
                              batch_size=2, shuffle=True, mesh=mesh,
                              seed=1)
hb = next(iter(hl))
assert np.asarray(hb.node_dict['i']).shape[0] == P
report['hetero_nodes'] = int(
    (np.asarray(hb.node_dict['i']) >= 0).sum())

sg = DistSubGraphLoader(ds, [2], np.arange(n), batch_size=2, mesh=mesh,
                        collect_features=False, seed=2)
sb = next(iter(sg))
got = 0
node_s = np.asarray(sb.node)
ei = np.asarray(sb.edge_index)
for p in range(P):
  m = np.asarray(sb.edge_mask)[p]
  for i in np.nonzero(m)[0]:
    u = int(ds.new2old[node_s[p, ei[p, 0, i]]])
    v = int(ds.new2old[node_s[p, ei[p, 1, i]]])
    assert (u, v) in edge_set
    got += 1
report['subgraph_edges'] = got

# tiered store + chunked SEAL window at P=16 (the r3 scale levers)
ds_t = DistDataset.from_full_graph(P, rows, cols, node_feat=feats,
                                   num_nodes=n, split_ratio=0.5)
tl = DistNeighborLoader(ds_t, [3, 2], np.arange(n), batch_size=4,
                        shuffle=True, mesh=mesh, seed=3)
tb = next(iter(tl))
node_t = np.asarray(tb.node)
x_t = np.asarray(tb.x)
for p in range(P):
  m = node_t[p] >= 0
  np.testing.assert_allclose(x_t[p][m][:, 0],
                             ds_t.new2old[node_t[p][m]])
st = tl.sampler.exchange_stats(tick_metrics=False)
report['tiered_cold_misses'] = st['dist.feature.cold_misses']
assert report['tiered_cold_misses'] > 0

sgc = DistSubGraphLoader(ds, [2], np.arange(n), batch_size=2, mesh=mesh,
                         collect_features=False, seed=2, hop_chunk=16)
scb = next(iter(sgc))
node_c = np.asarray(scb.node)
eic = np.asarray(scb.edge_index)
chunked = 0
for p in range(P):
  m = np.asarray(scb.edge_mask)[p]
  for i in np.nonzero(m)[0]:
    u = int(ds.new2old[node_c[p, eic[p, 0, i]]])
    v = int(ds.new2old[node_c[p, eic[p, 1, i]]])
    assert (u, v) in edge_set
    chunked += 1
report['subgraph_edges_chunked'] = chunked

with open(out_file, 'w') as f:
  json.dump(report, f)
print('P16 OK', report)
