"""Host-local partition loading (`from_partition_dir(host_parts=...)`):
this process materializes only its partitions' tensors and the sampler
assembles the global sharded arrays shard-by-shard
(`make_array_from_single_device_arrays`) — the multi-host RAM story.
Single-process equivalence here (host_parts = every partition must
reproduce the full load bit-for-bit); the REAL 2-process arm runs in
tests/test_multihost.py.
"""
import numpy as np
import pytest

from graphlearn_tpu.parallel import (DistDataset, DistNeighborLoader,
                                     make_mesh)
from graphlearn_tpu.partition import RandomPartitioner

P, N = 8, 128


def _write(root):
  rows = np.concatenate([np.arange(N), np.arange(N)])
  cols = np.concatenate([(np.arange(N) + 1) % N, (np.arange(N) + 2) % N])
  feats = np.arange(N, dtype=np.float32)[:, None] * np.ones((1, 3),
                                                            np.float32)
  labels = (np.arange(N) % 5).astype(np.int32)
  RandomPartitioner(root, P, N, (rows, cols), node_feat=feats,
                    node_label=labels, seed=0).partition()


def test_host_local_equals_full_load(tmp_path):
  _write(tmp_path)
  full = DistDataset.from_partition_dir(tmp_path)
  local = DistDataset.from_partition_dir(tmp_path,
                                         host_parts=np.arange(P))
  np.testing.assert_array_equal(full.graph.bounds, local.graph.bounds)
  np.testing.assert_array_equal(full.old2new, local.old2new)
  np.testing.assert_array_equal(full.graph.indptr, local.graph.indptr)
  # CSR column ORDER within a row may differ (independent sorts);
  # compare per-row sets via a canonical sort
  for p in range(P):
    for r in range(full.graph.max_local_nodes):
      a = np.sort(full.graph.indices[p][full.graph.indptr[p][r]:
                                        full.graph.indptr[p][r + 1]])
      b = np.sort(local.graph.indices[p][local.graph.indptr[p][r]:
                                         local.graph.indptr[p][r + 1]])
      np.testing.assert_array_equal(a, b)
  np.testing.assert_array_equal(full.node_features.shards,
                                local.node_features.shards)
  np.testing.assert_array_equal(full.node_labels, local.node_labels)


def test_host_local_loader_epoch(tmp_path):
  _write(tmp_path)
  ds = DistDataset.from_partition_dir(tmp_path,
                                      host_parts=np.arange(P))
  loader = DistNeighborLoader(ds, [2, 2], np.arange(N), batch_size=4,
                              shuffle=True, mesh=make_mesh(P), seed=0)
  nb = 0
  for b in loader:
    nodes = np.asarray(b.node)
    x = np.asarray(b.x)
    y = np.asarray(b.y)
    for p in range(P):
      m = nodes[p] >= 0
      old = ds.new2old[nodes[p][m]]
      np.testing.assert_allclose(x[p][m][:, 0], old.astype(np.float32))
      np.testing.assert_array_equal(y[p][m], old % 5)
    nb += 1
  assert nb == len(loader)


def test_host_local_put_guard(tmp_path):
  _write(tmp_path)
  ds = DistDataset.from_partition_dir(tmp_path, host_parts=[0, 1])
  loader = DistNeighborLoader(ds, [2], np.arange(N), batch_size=4,
                              shuffle=True, mesh=make_mesh(P), seed=0)
  # single process owns ALL 8 mesh positions but only loaded 2 shards:
  # the put must refuse, not silently mis-place
  with pytest.raises(ValueError, match='host_parts'):
    next(iter(loader))


def _write_rich(root, split_feats: bool = True):
  """Layout with every optional payload: provenance features
  (col 0 = old id + 1), labels, edge features encoding (eid, src,
  dst), and an offline cache plan."""
  rows = np.concatenate([np.arange(N), np.arange(N)])
  cols = np.concatenate([(np.arange(N) + 1) % N, (np.arange(N) + 2) % N])
  e = len(rows)
  feats = np.tile((np.arange(N, dtype=np.float32) + 1)[:, None], (1, 3))
  labels = (np.arange(N) % 5).astype(np.int32)
  efeat = np.stack([np.arange(e), rows, cols], 1).astype(np.float32)
  RandomPartitioner(root, P, N, (rows, cols),
                    node_feat=feats if split_feats else None,
                    node_label=labels, edge_feat=efeat,
                    cache_ratio=0.1, seed=0).partition()
  return rows, cols, efeat


def test_host_local_tiered_equals_full(tmp_path):
  """Tiered host-local load (the IGBH-large enabler, VERDICT r3 #3):
  hot shards, hot counts, cache plan, and edge features must all
  match a single-controller load of the same (layout, split_ratio);
  the cold stack must hold exactly this host's partitions' rows of
  the full cold table."""
  _write_rich(tmp_path)
  full = DistDataset.from_partition_dir(tmp_path, split_ratio=0.4)
  local = DistDataset.from_partition_dir(tmp_path, split_ratio=0.4,
                                         host_parts=np.arange(P))
  np.testing.assert_array_equal(full.old2new, local.old2new)
  nf_f, nf_l = full.node_features, local.node_features
  np.testing.assert_array_equal(nf_f.hot_counts, nf_l.hot_counts)
  np.testing.assert_array_equal(nf_f.shards, nf_l.shards)
  # cache plan honored (was: ignored with a warning in v1)
  assert nf_l.cache_ids is not None and nf_l.has_cache
  np.testing.assert_array_equal(nf_f.cache_ids, nf_l.cache_ids)
  np.testing.assert_array_equal(nf_f.cache_rows, nf_l.cache_rows)
  # cold provenance: local stack row r of partition p == global cold
  # table row bounds[p] + r
  assert nf_l.cold_local is not None and nf_l.cold_host is None
  bounds = full.graph.bounds
  counts = np.diff(bounds)
  for j, p in enumerate(range(P)):
    np.testing.assert_array_equal(
        nf_l.cold_local[j, :counts[p]],
        nf_f.cold_host[bounds[p]:bounds[p + 1]])
  # edge features (was: NotImplementedError in v1)
  assert local.edge_features is not None
  np.testing.assert_array_equal(full.edge_features.shards,
                                local.edge_features.shards)


def test_host_local_tiered_loader_epoch(tmp_path):
  """The composed path end-to-end on the virtual mesh: tiered store +
  cache plan + edge features + host-local layout, one loader epoch
  with per-row provenance (cold rows included — a failed owner-served
  overlay would leave zeros where col 0 must read old id + 1)."""
  rows, cols, _ = _write_rich(tmp_path)
  ds = DistDataset.from_partition_dir(tmp_path, split_ratio=0.3,
                                      host_parts=np.arange(P))
  loader = DistNeighborLoader(ds, [2, 2], np.arange(N), batch_size=4,
                              shuffle=True, with_edge=True,
                              mesh=make_mesh(P), seed=0)
  nb = 0
  for b in loader:
    nodes = np.asarray(b.node)
    x = np.asarray(b.x)
    y = np.asarray(b.y)
    ea = np.asarray(b.edge_attr)
    eid = np.asarray(b.edge)
    em = np.asarray(b.edge_mask)
    for p in range(P):
      m = nodes[p] >= 0
      old = ds.new2old[nodes[p][m]]
      np.testing.assert_allclose(x[p][m][:, 0],
                                 old.astype(np.float32) + 1)
      np.testing.assert_array_equal(y[p][m], old % 5)
      me = em[p]
      np.testing.assert_allclose(ea[p][me][:, 0], eid[p][me])
      np.testing.assert_allclose(ea[p][me][:, 1], rows[eid[p][me]])
      np.testing.assert_allclose(ea[p][me][:, 2], cols[eid[p][me]])
    nb += 1
  assert nb == len(loader)
  st = loader.sampler.exchange_stats(tick_metrics=False)
  assert st['dist.feature.cold_misses'] > 0
  assert 0.0 <= st['dist.feature.cache_hit_rate'] <= 1.0
  assert 0.0 < st['dist.feature.hot_hit_rate'] < 1.0


def test_host_local_by_dst_layout(tmp_path):
  """by_dst layouts re-bucket by src owner under host-local loading
  (was: NotImplementedError in v1) and must reproduce the
  single-controller CSR per-row edge sets."""
  rows = np.concatenate([np.arange(N), np.arange(N)])
  cols = np.concatenate([(np.arange(N) + 1) % N, (np.arange(N) + 2) % N])
  feats = np.tile((np.arange(N, dtype=np.float32) + 1)[:, None], (1, 2))
  RandomPartitioner(tmp_path, P, N, (rows, cols), node_feat=feats,
                    seed=0, edge_assign='by_dst').partition()
  full = DistDataset.from_partition_dir(tmp_path)
  local = DistDataset.from_partition_dir(tmp_path,
                                         host_parts=np.arange(P))
  np.testing.assert_array_equal(full.graph.bounds, local.graph.bounds)
  np.testing.assert_array_equal(full.graph.indptr, local.graph.indptr)
  for p in range(P):
    for r in range(full.graph.max_local_nodes):
      a = np.sort(full.graph.indices[p][full.graph.indptr[p][r]:
                                        full.graph.indptr[p][r + 1]])
      b = np.sort(local.graph.indices[p][local.graph.indptr[p][r]:
                                         local.graph.indptr[p][r + 1]])
      np.testing.assert_array_equal(a, b)
  np.testing.assert_array_equal(full.node_features.shards,
                                local.node_features.shards)


def test_hetero_host_local_equals_full(tmp_path):
  """Hetero host-local loading (host_parts = all) must match the full
  load's id spaces and serve provenance-correct batches."""
  from graphlearn_tpu.parallel import (DistHeteroDataset,
                                       DistHeteroNeighborLoader)
  U, I = 'u', 'i'
  ET = (U, 'to', I)
  REV = (I, 'rev_to', U)
  nu, ni = 48, 24
  urow = np.repeat(np.arange(nu), 2)
  icol = np.stack([np.arange(nu) % ni, (np.arange(nu) + 1) % ni],
                  1).reshape(-1)
  ufeat = np.tile(np.arange(nu, dtype=np.float32)[:, None], (1, 3))
  ifeat = np.tile(np.arange(ni, dtype=np.float32)[:, None], (1, 3))
  RandomPartitioner(tmp_path, P,
                    num_nodes={U: nu, I: ni},
                    edge_index={ET: (urow, icol), REV: (icol, urow)},
                    node_feat={U: ufeat, I: ifeat},
                    node_label={U: (np.arange(nu) % 4).astype(np.int32)},
                    seed=0).partition()
  full = DistHeteroDataset.from_partition_dir(tmp_path)
  local = DistHeteroDataset.from_partition_dir(
      tmp_path, host_parts=np.arange(P))
  for nt in (U, I):
    np.testing.assert_array_equal(full.bounds[nt], local.bounds[nt])
    np.testing.assert_array_equal(full.old2new[nt], local.old2new[nt])
    np.testing.assert_array_equal(full.node_features[nt].shards,
                                  local.node_features[nt].shards)
  np.testing.assert_array_equal(np.asarray(full.node_labels[U]),
                                local.node_labels[U])
  loader = DistHeteroNeighborLoader(local, [2, 2], (U, np.arange(nu)),
                                    batch_size=2, shuffle=True,
                                    mesh=make_mesh(P), seed=0)
  nb = 0
  for b in loader:
    for nt in (U, I):
      nodes = np.asarray(b.node_dict[nt])
      x = np.asarray(b.x_dict[nt])
      for p in range(P):
        m = nodes[p] >= 0
        np.testing.assert_allclose(
            x[p][m][:, 0],
            local.new2old[nt][nodes[p][m]].astype(np.float32))
    nb += 1
  assert nb == len(loader)


def test_hetero_host_local_csr_and_guard(tmp_path):
  """Hetero arm of the homo checks: per-etype CSR equality against the
  full load, and the sampler's put refusing a host_parts/mesh
  mismatch."""
  from graphlearn_tpu.parallel import (DistHeteroDataset,
                                       DistHeteroNeighborLoader)
  U, I = 'u', 'i'
  ET = (U, 'to', I)
  REV = (I, 'rev_to', U)
  nu, ni = 48, 24
  urow = np.repeat(np.arange(nu), 2)
  icol = np.stack([np.arange(nu) % ni, (np.arange(nu) + 1) % ni],
                  1).reshape(-1)
  RandomPartitioner(tmp_path, P,
                    num_nodes={U: nu, I: ni},
                    edge_index={ET: (urow, icol), REV: (icol, urow)},
                    node_feat={U: np.ones((nu, 2), np.float32)},
                    seed=0).partition()
  full = DistHeteroDataset.from_partition_dir(tmp_path)
  local = DistHeteroDataset.from_partition_dir(
      tmp_path, host_parts=np.arange(P))
  for et in (ET, REV):
    gf, gl = full.graphs[et], local.graphs[et]
    np.testing.assert_array_equal(gf.indptr, gl.indptr)
    for p in range(P):
      for r in range(gf.max_local_nodes):
        a = np.sort(gf.indices[p][gf.indptr[p][r]:gf.indptr[p][r + 1]])
        b = np.sort(gl.indices[p][gl.indptr[p][r]:gl.indptr[p][r + 1]])
        np.testing.assert_array_equal(a, b)
  bad = DistHeteroDataset.from_partition_dir(tmp_path,
                                             host_parts=[0, 1])
  loader = DistHeteroNeighborLoader(bad, [2], (U, np.arange(nu)),
                                    batch_size=2, shuffle=True,
                                    mesh=make_mesh(P), seed=0)
  with pytest.raises(ValueError, match='host_parts'):
    next(iter(loader))


def test_hetero_host_local_tiered_composition(tmp_path):
  """Hetero arm of the composed host-local path: per-type tiered
  stores (owner-served cold), per-etype edge features — host-local
  load must match single-controller and serve provenance-correct
  batches end to end."""
  from graphlearn_tpu.parallel import (DistHeteroDataset,
                                       DistHeteroNeighborLoader)
  U, I = 'u', 'i'
  ET = (U, 'to', I)
  REV = (I, 'rev_to', U)
  nu, ni = 48, 24
  urow = np.repeat(np.arange(nu), 2)
  icol = np.stack([np.arange(nu) % ni, (np.arange(nu) + 1) % ni],
                  1).reshape(-1)
  ufeat = np.tile((np.arange(nu, dtype=np.float32) + 1)[:, None],
                  (1, 3))
  ifeat = np.tile((np.arange(ni, dtype=np.float32) + 1)[:, None],
                  (1, 3))
  ef_fwd = np.stack([np.arange(len(urow)), urow, icol],
                    1).astype(np.float32)
  ef_rev = np.stack([np.arange(len(urow)), icol, urow],
                    1).astype(np.float32)
  RandomPartitioner(tmp_path, P,
                    num_nodes={U: nu, I: ni},
                    edge_index={ET: (urow, icol), REV: (icol, urow)},
                    node_feat={U: ufeat, I: ifeat},
                    node_label={U: (np.arange(nu) % 4).astype(np.int32)},
                    edge_feat={ET: ef_fwd, REV: ef_rev},
                    seed=0).partition()
  full = DistHeteroDataset.from_partition_dir(tmp_path, split_ratio=0.4)
  local = DistHeteroDataset.from_partition_dir(
      tmp_path, split_ratio=0.4, host_parts=np.arange(P))
  for nt in (U, I):
    np.testing.assert_array_equal(full.old2new[nt], local.old2new[nt])
    nf_f, nf_l = full.node_features[nt], local.node_features[nt]
    np.testing.assert_array_equal(nf_f.hot_counts, nf_l.hot_counts)
    np.testing.assert_array_equal(nf_f.shards, nf_l.shards)
    assert nf_l.cold_local is not None and nf_l.cold_host is None
    counts = np.diff(full.bounds[nt])
    for j, p in enumerate(range(P)):
      np.testing.assert_array_equal(
          nf_l.cold_local[j, :counts[p]],
          nf_f.cold_host[full.bounds[nt][p]:full.bounds[nt][p + 1]])
  for et in (ET, REV):
    np.testing.assert_array_equal(full.edge_features[et].shards,
                                  local.edge_features[et].shards)
  loader = DistHeteroNeighborLoader(local, [2, 2], (U, np.arange(nu)),
                                    batch_size=2, shuffle=True,
                                    with_edge=True, mesh=make_mesh(P),
                                    seed=0)
  nb = 0
  for b in loader:
    for nt in (U, I):
      nodes = np.asarray(b.node_dict[nt])
      x = np.asarray(b.x_dict[nt])
      for p in range(P):
        m = nodes[p] >= 0
        np.testing.assert_allclose(
            x[p][m][:, 0],
            local.new2old[nt][nodes[p][m]].astype(np.float32) + 1)
    for et, ea in b.edge_attr_dict.items():
      ea = np.asarray(ea)
      eid = np.asarray(b.metadata['edge_dict'][et])
      em = np.asarray(b.edge_mask_dict[et])
      for p in range(P):
        np.testing.assert_allclose(ea[p][em[p]][:, 0], eid[p][em[p]])
    nb += 1
  assert nb == len(loader)
  st = loader.sampler.exchange_stats(tick_metrics=False)
  assert st['dist.feature.cold_misses'] > 0


def test_multihost_global_max():
  from graphlearn_tpu.parallel import multihost
  mesh = make_mesh(P)
  assert multihost.global_max(7, mesh) == 7
