"""Mesh-engine induced subgraph (SEAL on the ICI path).

VERDICT-r1 missing #2: the reference samples induced subgraphs ACROSS
partitions (`distributed/dist_neighbor_sampler.py:456-516`); round 1
only had the host-runtime arm.  The mesh step = collective closure +
full-window hop + local membership/relabel; exactness is asserted
against a brute-force edge filter, per device.
"""
import numpy as np
import pytest

jax = pytest.importorskip('jax')

from graphlearn_tpu.parallel import (DistDataset, DistSubGraphLoader,
                                     make_mesh)

N = 48


def _graph():
  rng = np.random.default_rng(7)
  rows = np.concatenate([np.arange(N), np.arange(N), np.arange(N)])
  cols = np.concatenate([(np.arange(N) + 1) % N,
                         (np.arange(N) + 5) % N,
                         rng.integers(0, N, N)])
  return rows, cols


def test_mesh_subgraph_matches_bruteforce():
  rows, cols = _graph()
  edge_set = set(zip(rows.tolist(), cols.tolist()))
  feats = np.tile(np.arange(N, dtype=np.float32)[:, None], (1, 3))
  ds = DistDataset.from_full_graph(8, rows, cols, node_feat=feats,
                                   num_nodes=N)
  loader = DistSubGraphLoader(ds, [3, 3], np.arange(N), batch_size=2,
                              shuffle=True, mesh=make_mesh(8),
                              with_edge=True, seed=0)
  new2old = ds.new2old
  batches = 0
  for batch in loader:
    node = np.asarray(batch.node)
    nm = np.asarray(batch.node_mask)
    ei = np.asarray(batch.edge_index)
    em = np.asarray(batch.edge_mask)
    eid = np.asarray(batch.edge)
    x = np.asarray(batch.x)
    for p in range(8):
      kept_old = set(new2old[node[p][nm[p]]].tolist())
      got = set()
      for i in np.nonzero(em[p])[0]:
        u = int(new2old[node[p, ei[p, 0, i]]])
        v = int(new2old[node[p, ei[p, 1, i]]])
        got.add((u, v))
        # eid provenance: the emitted global edge id maps back to the
        # original COO slot for this (u, v)
        e = int(eid[p, i])
        assert rows[e] == u and cols[e] == v
      expect = {(u, v) for u, v in edge_set
                if u in kept_old and v in kept_old}
      assert got == expect, (p, got ^ expect)
      # features present for every kept node, encoding its id
      np.testing.assert_allclose(x[p][nm[p]][:, 0],
                                 new2old[node[p][nm[p]]])
    # mapping locates the seeds (the SEAL contract)
    mapping = np.asarray(batch.metadata['mapping'])
    seeds = np.asarray(batch.batch)
    for p in range(8):
      for j, s in enumerate(seeds[p]):
        if s >= 0:
          assert node[p, mapping[p, j]] == s
    batches += 1
  assert batches == len(loader)


def test_mesh_subgraph_truncated_window_counts_drops():
  """max_degree below the true max truncates windows — results are a
  subset of the true induced edges, never wrong edges."""
  rows, cols = _graph()
  edge_set = set(zip(rows.tolist(), cols.tolist()))
  ds = DistDataset.from_full_graph(8, rows, cols, num_nodes=N)
  loader = DistSubGraphLoader(ds, [3], np.arange(N), batch_size=2,
                              mesh=make_mesh(8), max_degree=2,
                              collect_features=False, seed=1)
  new2old = ds.new2old
  batch = next(iter(loader))
  node = np.asarray(batch.node)
  ei = np.asarray(batch.edge_index)
  em = np.asarray(batch.edge_mask)
  for p in range(8):
    for i in np.nonzero(em[p])[0]:
      u = int(new2old[node[p, ei[p, 0, i]]])
      v = int(new2old[node[p, ei[p, 1, i]]])
      assert (u, v) in edge_set


@pytest.mark.slow
def test_mesh_subgraph_hop_chunk_exact():
  """Chunked full-window hops (the SEAL-at-scale bound, hop_chunk)
  must produce the SAME subgraphs as one node_cap-wide exchange — the
  window is exact either way, only the exchange width changes."""
  rows, cols = _graph()
  feats = np.tile(np.arange(N, dtype=np.float32)[:, None], (1, 3))
  ds = DistDataset.from_full_graph(8, rows, cols, node_feat=feats,
                                   num_nodes=N)
  results = []
  for chunk in (None, 8):
    loader = DistSubGraphLoader(ds, [3, 3], np.arange(16), batch_size=2,
                                shuffle=False, mesh=make_mesh(8),
                                with_edge=True, seed=0, hop_chunk=chunk)
    edges = []
    for batch in loader:
      node = np.asarray(batch.node)
      ei = np.asarray(batch.edge_index)
      em = np.asarray(batch.edge_mask)
      for p in range(8):
        es = {(int(ds.new2old[node[p, ei[p, 0, i]]]),
               int(ds.new2old[node[p, ei[p, 1, i]]]))
              for i in np.nonzero(em[p])[0]}
        edges.append(es)
    results.append(edges)
  assert results[0] == results[1]


def test_hop_chunk_auto_resolution():
  """'auto' keeps one wide exchange below the window budget and
  bounds the chunk above it."""
  from graphlearn_tpu.parallel.dist_sampler import (
      SUBGRAPH_WINDOW_BUDGET, resolve_hop_chunk)
  assert resolve_hop_chunk(None, 10**9, 64) is None
  assert resolve_hop_chunk(512, 10**9, 64) == 512
  assert resolve_hop_chunk('auto', 1000, 64) is None
  big_cap = SUBGRAPH_WINDOW_BUDGET // 64 + 1000
  chunk = resolve_hop_chunk('auto', big_cap, 64)
  assert chunk is not None and chunk * 64 <= SUBGRAPH_WINDOW_BUDGET
  with pytest.raises(ValueError, match='hop_chunk'):
    resolve_hop_chunk('bogus', 10, 10)


def test_hop_chunk_auto_respects_budget_any_degree():
  from graphlearn_tpu.parallel.dist_sampler import (
      MIN_EXCHANGE_CAP, SUBGRAPH_WINDOW_BUDGET, resolve_hop_chunk)
  for md in (7, 64, 1000, 4097):
    chunk = resolve_hop_chunk('auto', 10**9, md)
    assert chunk is not None
    assert (chunk * md <= SUBGRAPH_WINDOW_BUDGET
            or chunk == MIN_EXCHANGE_CAP)
