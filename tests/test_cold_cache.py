"""Adaptive HBM cold-row victim cache (`data/cold_cache.py`, ISSUE 5).

The contract under test: the cache is a pure ACCELERATION layer —
batches are byte-identical to the uncached cold overlay at EVERY cache
size (0 / tiny / effectively-infinite), under eviction churn, and with
the double-buffered cold pipeline on or off.  Plus the CLOCK policy's
second-chance semantics and the telemetry counters the bench keys off.
"""
import os

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from graphlearn_tpu.data import Dataset
from graphlearn_tpu.data.cold_cache import (ClockShardCache,
                                            DeviceColdCache,
                                            resolve_cache_rows)
from graphlearn_tpu.parallel import (DistDataset, DistNeighborLoader,
                                     DistNeighborSampler, make_mesh)

N = 64
P = 4


def _ring_dataset(split_ratio, num_parts=P):
  rows = np.concatenate([np.arange(N), np.arange(N)])
  cols = np.concatenate([(np.arange(N) + 1) % N, (np.arange(N) + 2) % N])
  feats = (np.arange(N, dtype=np.float32)[:, None]
           * np.ones((1, 4), np.float32))          # feat[v] == v
  labels = (np.arange(N) % 5).astype(np.int32)
  node_pb = (np.arange(N) % num_parts).astype(np.int32)
  return DistDataset.from_full_graph(
      num_parts, rows, cols, node_feat=feats, node_label=labels,
      num_nodes=N, node_pb=node_pb, split_ratio=split_ratio)


# -- policy unit tests ------------------------------------------------------

def test_clock_policy_admission_and_lookup():
  c = ClockShardCache(4)
  ids = np.array([10, 20, 30], np.int64)
  adm, slots, ev = c.plan_admissions(ids)
  assert ev == 0 and len(adm) == 3
  c.commit(adm, slots)
  hit, slot = c.lookup(np.array([10, 20, 99], np.int64))
  assert hit.tolist() == [True, True, False]
  # the hits set the reference bit on their slots
  assert c.ref[slot[:2]].all()


def test_clock_policy_second_chance():
  """Residents TOUCHED since the last sweep survive one eviction pass;
  untouched residents are the victims."""
  c = ClockShardCache(2)
  adm, slots, _ = c.plan_admissions(np.array([1, 2], np.int64))
  c.commit(adm, slots)
  # touch id 1 only — its ref bit protects it from the next sweep
  c.lookup(np.array([1], np.int64))
  adm2, slots2, ev = c.plan_admissions(np.array([3], np.int64))
  c.commit(adm2, slots2)
  assert ev == 1
  hit, _ = c.lookup(np.array([1, 2, 3], np.int64))
  assert hit.tolist() == [True, False, True]      # 2 was the victim


def test_clock_policy_frequency_ranked():
  """With more candidates than capacity, the ids the batch touched
  most win the slots."""
  c = ClockShardCache(2)
  ids = np.array([5, 6, 7], np.int64)
  counts = np.array([1, 9, 4], np.int64)
  adm, slots, _ = c.plan_admissions(ids, counts)
  c.commit(adm, slots)
  hit, _ = c.lookup(ids)
  assert hit.tolist() == [False, True, True]


def test_resolve_cache_rows():
  assert resolve_cache_rows(0, 1000) == 0
  assert resolve_cache_rows(17, 1000) == 17
  assert resolve_cache_rows('auto', 1000) == 150          # 15% default
  assert resolve_cache_rows(None, 0) == 0
  os.environ['GLT_COLD_CACHE_ROWS'] = '33'
  try:
    assert resolve_cache_rows('auto', 1000) == 33
  finally:
    del os.environ['GLT_COLD_CACHE_ROWS']


# -- single-chip Feature (DeviceColdCache) ----------------------------------

def _feature(split_ratio, cache_rows, n=48, d=4):
  from graphlearn_tpu.data.feature import Feature
  feats = (np.arange(n, dtype=np.float32)[:, None]
           * np.ones((1, d), np.float32))
  return Feature(feats, split_ratio=split_ratio,
                 cold_cache_rows=cache_rows)


@pytest.mark.parametrize('cache_rows', [0, 3, 10_000])
def test_feature_cache_byte_identity(cache_rows):
  """The cached mixed lookup returns byte-identical values to the
  uncached one for every batch of a repeated-id stream, at cache sizes
  {0, tiny, effectively-infinite}."""
  rng = np.random.default_rng(0)
  ref = _feature(0.25, 0)
  cached = _feature(0.25, cache_rows)
  for _ in range(6):
    ids = rng.integers(-1, 48, 32)                # includes invalid -1
    a = np.asarray(ref[ids])
    b = np.asarray(cached[ids])
    np.testing.assert_array_equal(a, b)
  if cache_rows >= 10_000:
    # every cold repeat after first touch is a hit
    assert cached._cold_cache.stats.hits > 0
    assert cached._cold_cache.stats.evicts == 0


def test_feature_cache_eviction_churn():
  """Working set (36 cold rows) >> budget (4): the cache churns
  through evictions and the values stay exact."""
  rng = np.random.default_rng(1)
  ref = _feature(0.25, 0)
  cached = _feature(0.25, 4)
  for _ in range(8):
    ids = rng.integers(0, 48, 40)
    np.testing.assert_array_equal(np.asarray(ref[ids]),
                                  np.asarray(cached[ids]))
  st = cached._cold_cache.stats
  assert st.admits > 4 and st.evicts > 0
  assert st.hits + st.misses == cached.cold_stats['cold_lookups']


def test_feature_cache_all_hits_on_repeat():
  """A repeated identical batch is served entirely from the cache the
  second time (cross-batch dedup through the ring)."""
  cached = _feature(0.25, 10_000)
  ids = np.arange(48)
  first = np.asarray(cached[ids])
  m0 = cached._cold_cache.stats.misses
  second = np.asarray(cached[ids])
  np.testing.assert_array_equal(first, second)
  assert cached._cold_cache.stats.misses == m0    # zero new misses


# -- mesh engines (MeshColdCache) -------------------------------------------

def test_mesh_overlay_byte_identity_across_cache_sizes():
  """Same seeds, same sampling key: the cache-served overlay must
  produce the exact features of the uncached overlay at cache sizes
  {0, tiny, inf} — across several batches so admissions from batch k
  serve hits in batch k+1."""
  ds = _ring_dataset(0.25)
  mesh = make_mesh(P)
  samplers = {
      rows: DistNeighborSampler(ds, [2, 2], mesh=mesh, seed=0,
                                cold_cache_rows=rows)
      for rows in (0, 2, 1_000_000)}
  rng = np.random.default_rng(0)
  for step in range(4):
    seeds = ds.old2new[rng.integers(0, N, (P, 8))]
    key = jax.random.fold_in(jax.random.key(7), step)
    outs = {rows: s.sample_from_nodes(seeds, key=key)
            for rows, s in samplers.items()}
    x0 = np.asarray(outs[0]['x'])
    for rows in (2, 1_000_000):
      np.testing.assert_array_equal(x0, np.asarray(outs[rows]['x']),
                                    err_msg=f'cache_rows={rows}')
  # the big cache actually served hits; the uncached sampler missed on
  # every cold lookup
  st_big = samplers[1_000_000].exchange_stats(tick_metrics=False)
  st_off = samplers[0].exchange_stats(tick_metrics=False)
  assert st_big['dist.feature.cache_hits'] > 0
  assert st_big['dist.feature.cache_hit_rate'] > 0.0
  assert st_off['dist.feature.cache_hits'] == 0
  assert (st_off['dist.feature.cold_misses']
          == st_off['dist.feature.cold_lookups'])
  # tiny cache churned
  st_tiny = samplers[2].exchange_stats(tick_metrics=False)
  assert st_tiny['dist.feature.cache_evicts'] > 0


def test_mesh_eviction_churn_working_set_exceeds_budget():
  """Every partition's cold set cycles through a 2-row cache for
  several epochs: values stay exact while evictions churn."""
  ds = _ring_dataset(0.25)
  mesh = make_mesh(P)
  s_ref = DistNeighborSampler(ds, [2], mesh=mesh, seed=0,
                              cold_cache_rows=0)
  s_tiny = DistNeighborSampler(ds, [2], mesh=mesh, seed=0,
                               cold_cache_rows=2)
  for step in range(6):
    seeds = ds.old2new[(np.arange(P * 8).reshape(P, 8) * (step + 1))
                       % N]
    key = jax.random.fold_in(jax.random.key(3), step)
    a = s_ref.sample_from_nodes(seeds, key=key)
    b = s_tiny.sample_from_nodes(seeds, key=key)
    np.testing.assert_array_equal(np.asarray(a['x']),
                                  np.asarray(b['x']))
  st = s_tiny.exchange_stats(tick_metrics=False)
  assert st['dist.feature.cache_evicts'] > 0


def test_pipelined_cold_overlay_parity():
  """GLT_COLD_PREFETCH=1 (double-buffered dispatch) vs =0
  (synchronous): identical batch sequences — only the host/device
  interleaving may differ."""
  ds = _ring_dataset(0.3)
  mesh = make_mesh(P)
  batches = {}
  for flag in ('0', '1'):
    os.environ['GLT_COLD_PREFETCH'] = flag
    try:
      loader = DistNeighborLoader(ds, [2, 2], np.arange(N),
                                  batch_size=4, shuffle=True,
                                  mesh=mesh, seed=0)
      assert loader._cold_pipeline == (flag == '1')
      batches[flag] = [(np.asarray(b.x), np.asarray(b.node),
                        np.asarray(b.y)) for b in loader]
    finally:
      del os.environ['GLT_COLD_PREFETCH']
  assert len(batches['0']) == len(batches['1']) > 0
  for (x0, n0, y0), (x1, n1, y1) in zip(batches['0'], batches['1']):
    np.testing.assert_array_equal(n0, n1)
    np.testing.assert_array_equal(x0, x1)
    np.testing.assert_array_equal(y0, y1)


def test_cache_telemetry_events_and_metrics():
  """cache.* flight-recorder events flow from the overlay, and the
  exchange_stats vocabulary carries the r10 keys with consistent
  arithmetic."""
  from graphlearn_tpu.telemetry import recorder
  ds = _ring_dataset(0.25)
  sampler = DistNeighborSampler(ds, [2, 2], mesh=make_mesh(P), seed=0,
                                cold_cache_rows=1_000_000)
  recorder.enable(None)
  try:
    for step in range(3):
      seeds = ds.old2new[np.arange(P * 8).reshape(P, 8) % N]
      sampler.sample_from_nodes(
          seeds, key=jax.random.fold_in(jax.random.key(0), step))
    kinds = {e['kind'] for e in recorder.events()}
  finally:
    recorder.disable()
  assert 'cache.miss' in kinds and 'cache.admit' in kinds
  assert 'cache.hit' in kinds                     # repeats hit
  st = sampler.exchange_stats(tick_metrics=False)
  assert (st['dist.feature.cache_hits'] + st['dist.feature.cold_misses']
          == st['dist.feature.cold_lookups'])
  assert st['dist.feature.lookups'] >= st['dist.feature.cold_lookups']
  expected = 1.0 - (st['dist.feature.cold_misses']
                    / st['dist.feature.cold_lookups'])
  assert st['dist.feature.cache_hit_rate'] == pytest.approx(expected)
