"""FusedHeteroEpoch: the hetero one-program epoch must train the
bipartite task the per-batch hetero loader trains, refuse bad
configurations, and match the per-batch program's batch structure."""
import numpy as np
import jax
import jax.numpy as jnp
import optax
import pytest

from graphlearn_tpu.data import Dataset
from graphlearn_tpu.loader import FusedHeteroEpoch, NeighborLoader
from graphlearn_tpu.models import RGCN
from graphlearn_tpu.models.train import TrainState

#: CPU-mesh scan-compile heavy (multi-minute): excluded from the
#: default run, selected by `pytest -m slow` (see pyproject.toml)
pytestmark = pytest.mark.slow

U, I = 'user', 'item'
ET_UI = (U, 'clicks', I)
ET_IU = (I, 'rev_clicks', U)


def _dataset(nu=48, ni=12, classes=3, d=12, seed=0, split_ratio=1.0):
  rng = np.random.default_rng(seed)
  labels = (np.arange(nu) % classes).astype(np.int32)
  block = ni // classes
  rows, cols = [], []
  for u in range(nu):
    c = labels[u]
    for _ in range(3):
      rows.append(u)
      cols.append(c * block + int(rng.integers(0, block)))
    rows.append(u)
    cols.append(int(rng.integers(0, ni)))
  rows, cols = np.array(rows), np.array(cols)
  ufeat = rng.normal(0, 1, (nu, d)).astype(np.float32)
  ifeat = np.pad(np.eye(ni, dtype=np.float32),
                 ((0, 0), (0, max(0, d - ni))))[:, :d].astype(np.float32)
  return (Dataset()
          .init_graph({ET_UI: (rows, cols), ET_IU: (cols, rows)},
                      layout='COO', num_nodes={ET_UI: nu, ET_IU: ni})
          .init_node_features({U: ufeat, I: ifeat},
                              split_ratio=split_ratio)
          .init_node_labels({U: labels}))


def _model_state(ds, tx, bs=16):
  loader = NeighborLoader(ds, [3, 3], (U, np.arange(48)), batch_size=bs,
                          shuffle=True, seed=0)
  batch0 = next(iter(loader))
  model = RGCN(etypes=tuple(batch0.edge_index_dict.keys()),
               hidden_features=16, out_features=3, num_layers=2,
               target_ntype=U)
  params = model.init(jax.random.key(0), batch0.x_dict,
                      batch0.edge_index_dict, batch0.edge_mask_dict)
  state = TrainState(params, tx.init(params), jnp.zeros((), jnp.int32))
  return model, state, batch0


def test_fused_hetero_epoch_trains():
  ds = _dataset()
  tx = optax.adam(1e-2)
  model, state, _ = _model_state(ds, tx)
  fused = FusedHeteroEpoch(ds, [3, 3], (U, np.arange(48)), model.apply,
                           tx, batch_size=16, shuffle=True, seed=0)
  assert len(fused) == 3
  state, first = fused.run(state)
  for _ in range(25):
    state, stats = fused.run(state)
  assert stats['seeds'] == 48
  assert stats['loss'] < first['loss']
  assert stats['accuracy'] > 0.8
  assert int(state.step) == 26 * len(fused)


def test_fused_hetero_batch_matches_loader_structure():
  """The scan body's HeteroBatch must carry the same type keys and
  static shapes as the per-batch loader's collation."""
  ds = _dataset()
  tx = optax.adam(1e-2)
  model, state, batch0 = _model_state(ds, tx)
  fused = FusedHeteroEpoch(ds, [3, 3], (U, np.arange(48)), model.apply,
                           tx, batch_size=16, shuffle=False, seed=0)
  seeds = np.stack(list(fused._batcher))
  key = jax.random.fold_in(jax.random.fold_in(fused._base_key, 1), 0)
  fb = fused._sample_collate(jnp.asarray(seeds[0]), key, fused._dev,
                             False)
  assert set(fb.x_dict) == set(batch0.x_dict)
  assert set(fb.edge_index_dict) == set(batch0.edge_index_dict)
  for et in fb.edge_index_dict:
    assert fb.edge_index_dict[et].shape == \
        batch0.edge_index_dict[et].shape, et
  for nt in fb.x_dict:
    assert fb.x_dict[nt].shape == batch0.x_dict[nt].shape, nt
  assert fb.y_dict[U].shape == batch0.y_dict[U].shape


def test_fused_hetero_evaluate():
  """The fused eval pass agrees with training accuracy on the learned
  task (same seed-type slots, same masking)."""
  ds = _dataset()
  tx = optax.adam(1e-2)
  model, state, _ = _model_state(ds, tx)
  fused = FusedHeteroEpoch(ds, [3, 3], (U, np.arange(48)), model.apply,
                           tx, batch_size=16, shuffle=True, seed=0)
  for _ in range(25):
    state, stats = fused.run(state)
  acc = fused.evaluate(state.params, np.arange(48))
  assert acc > 0.8
  assert abs(acc - stats['accuracy']) < 0.25
  with pytest.raises(ValueError, match='empty'):
    fused.evaluate(state.params, np.zeros(48, dtype=bool))


def test_fused_hetero_remat_trains():
  ds = _dataset()
  tx = optax.adam(1e-2)
  model, state, _ = _model_state(ds, tx)
  fused = FusedHeteroEpoch(ds, [3, 3], (U, np.arange(48)), model.apply,
                           tx, batch_size=16, shuffle=True, seed=0,
                           remat=True)
  state, first = fused.run(state)
  for _ in range(20):
    state, stats = fused.run(state)
  assert stats['loss'] < first['loss']
  assert stats['accuracy'] > 0.7


def test_fused_hetero_refuses_bad_configs():
  tx = optax.adam(1e-2)
  ds_tiered = _dataset(split_ratio=0.5)
  model, _, _ = _model_state(_dataset(), tx)
  with pytest.raises(ValueError, match='split_ratio'):
    FusedHeteroEpoch(ds_tiered, [3, 3], (U, np.arange(48)), model.apply,
                     tx, batch_size=16)
  with pytest.raises(ValueError, match='node_type'):
    FusedHeteroEpoch(_dataset(), [3, 3], np.arange(48), model.apply,
                     tx, batch_size=16)
  with pytest.raises(ValueError, match='hetero Dataset'):
    homo = (Dataset()
            .init_graph((np.arange(8), (np.arange(8) + 1) % 8),
                        layout='COO', num_nodes=8)
            .init_node_features(np.ones((8, 4), np.float32))
            .init_node_labels(np.zeros(8, np.int32)))
    FusedHeteroEpoch(homo, [3], (U, np.arange(8)), model.apply, tx,
                     batch_size=4)
