"""Loader-layer tests: seed batching, collation, provenance.

Mirrors the reference's loader tests (`test/python/test_neighbor_sampler
.py` usage through loaders) with the deterministic-provenance trick from
`dist_test_utils.py`: features encode the node id, so every gathered row
is checkable arithmetically.
"""
import numpy as np

from graphlearn_tpu.data import Dataset
from graphlearn_tpu.loader import NeighborLoader, SeedBatcher


def _ring_dataset(n=40, d=8):
  # Ring: v -> v+1, v -> v+2 (the reference's synthetic dist dataset
  # shape, `dist_test_utils.py:15-60`).
  rows = np.concatenate([np.arange(n), np.arange(n)])
  cols = np.concatenate([(np.arange(n) + 1) % n, (np.arange(n) + 2) % n])
  feats = np.arange(n, dtype=np.float32)[:, None] * np.ones((1, d),
                                                            np.float32)
  labels = np.arange(n, dtype=np.int32) % 4
  return (Dataset()
          .init_graph((rows, cols), layout='COO', num_nodes=n)
          .init_node_features(feats, split_ratio=1.0)
          .init_node_labels(labels))


def test_seed_batcher_pads_tail():
  b = SeedBatcher(np.arange(10), batch_size=4, shuffle=False,
                  drop_last=False)
  batches = list(b)
  assert len(batches) == 3 == len(b)
  assert (batches[0] == [0, 1, 2, 3]).all()
  assert (batches[2] == [8, 9, -1, -1]).all()


def test_seed_batcher_drop_last():
  b = SeedBatcher(np.arange(10), batch_size=4, shuffle=False, drop_last=True)
  batches = list(b)
  assert len(batches) == 2 == len(b)


def test_seed_batcher_shuffle_covers_all():
  b = SeedBatcher(np.arange(12), batch_size=4, shuffle=True, seed=0)
  e1 = np.sort(np.concatenate(list(b)))
  e2_batches = list(b)
  np.testing.assert_array_equal(e1, np.arange(12))
  assert not all((x == y).all()
                 for x, y in zip(e2_batches, list(b)))  # reshuffles


def test_neighbor_loader_epoch():
  ds = _ring_dataset()
  loader = NeighborLoader(ds, [2, 2], np.arange(40), batch_size=8,
                          shuffle=True, seed=0)
  seen = []
  for batch in loader:
    bs = np.asarray(batch.batch)
    seen.append(bs[bs >= 0])
    nodes = np.asarray(batch.node)
    mask = np.asarray(batch.node_mask)
    x = np.asarray(batch.x)
    y = np.asarray(batch.y)
    # Feature provenance: x[i] == node id for valid slots, 0 for padded.
    np.testing.assert_allclose(x[mask, 0], nodes[mask])
    np.testing.assert_allclose(x[~mask], 0)
    np.testing.assert_array_equal(y[mask], nodes[mask] % 4)
    # Topology invariant: every valid edge (r, c) means r ∈ {c+1, c+2}
    # (transposed emission: row=neighbor, col=seed side).
    ei = np.asarray(batch.edge_index)
    em = np.asarray(batch.edge_mask)
    r, c = nodes[ei[0][em]], nodes[ei[1][em]]
    assert (((r - c) % 40 == 1) | ((r - c) % 40 == 2)).all()
  np.testing.assert_array_equal(np.sort(np.concatenate(seen)),
                                np.arange(40))


def test_neighbor_loader_static_shapes():
  ds = _ring_dataset()
  loader = NeighborLoader(ds, [3, 2], np.arange(20), batch_size=8)
  shapes = {(*batch.x.shape, *batch.edge_index.shape) for batch in loader}
  assert len(shapes) == 1  # one compiled program for the whole epoch


def test_neighbor_loader_with_edge_ids():
  ds = _ring_dataset()
  loader = NeighborLoader(ds, [2], np.arange(16), batch_size=16,
                          with_edge=True)
  batch = next(iter(loader))
  em = np.asarray(batch.edge_mask)
  eids = np.asarray(batch.edge)
  assert (eids[em] >= 0).all() and (eids[em] < 80).all()
  assert (eids[~em] == -1).all()
