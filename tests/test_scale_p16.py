"""P=16 scale smoke: every mesh engine beyond the suite's 8 devices.

VERDICT-r1 weak #2 ("scale validation stops at P=8"): the suite's
conftest fixes an 8-device mesh, so this test spawns a subprocess with
16 virtual CPU devices and runs one batch each of the node, hetero,
and induced-subgraph engines with full provenance checks
(tests/_p16_worker.py).  P=32 at the realistic batch-1024 workload is
covered by `bench_dist_loader.py --capacity-sweep`.
"""
import os
import json
import subprocess
import sys
from pathlib import Path
import pytest

#: CPU-mesh scan-compile heavy (multi-minute): excluded from the
#: default run, selected by `pytest -m slow` (see pyproject.toml)
pytestmark = pytest.mark.slow

REPO = Path(__file__).resolve().parent.parent


def test_engines_at_p16(tmp_path):
  env = dict(os.environ)
  env.pop('PALLAS_AXON_POOL_IPS', None)
  env['JAX_PLATFORMS'] = 'cpu'
  flags = ' '.join(
      f for f in env.get('XLA_FLAGS', '').split()
      if '--xla_force_host_platform_device_count' not in f)
  env['XLA_FLAGS'] = (
      flags + ' --xla_force_host_platform_device_count=16').strip()
  env['PYTHONPATH'] = str(REPO) + os.pathsep + env.get('PYTHONPATH', '')
  out = tmp_path / 'p16.json'
  r = subprocess.run(
      [sys.executable, str(Path(__file__).parent / '_p16_worker.py'),
       str(out)],
      env=env, capture_output=True, text=True, timeout=900)
  assert r.returncode == 0, r.stdout[-3000:] + r.stderr[-3000:]
  rep = json.loads(out.read_text())
  assert rep['node_edges'] > 0
  assert rep['hetero_nodes'] > 0
  assert rep['subgraph_edges'] > 0
  assert rep['dropped'] == 0
