"""FusedDistEpoch: the one-program distributed epoch must train, keep
its telemetry, match the per-batch mesh step's numbers, and refuse the
configurations its design excludes."""
import numpy as np
import jax
import jax.numpy as jnp
import optax
import pytest

from graphlearn_tpu.loader import NeighborLoader
from graphlearn_tpu.data import Dataset
from graphlearn_tpu.models import GraphSAGE, create_train_state
from graphlearn_tpu.parallel import (DistDataset, DistNeighborLoader,
                                     FusedDistEpoch, make_mesh, replicate)

#: CPU-mesh scan-compile heavy (multi-minute): excluded from the
#: default run, selected by `pytest -m slow` (see pyproject.toml)
pytestmark = pytest.mark.slow

N = 256
CLASSES = 4
P_PARTS = 4


def _dist_dataset(split_ratio=None):
  rng = np.random.default_rng(0)
  labels = (np.arange(N) % CLASSES).astype(np.int32)
  rows, cols = [], []
  for v in range(N):
    for _ in range(5):
      if rng.random() < 0.8:
        u = int(rng.choice(np.nonzero(labels == labels[v])[0]))
      else:
        u = int(rng.integers(0, N))
      rows.append(v)
      cols.append(u)
  feats = np.eye(CLASSES, 8, dtype=np.float32)[labels]
  feats += rng.normal(0, 0.3, feats.shape).astype(np.float32)
  kw = {} if split_ratio is None else {'split_ratio': split_ratio}
  return DistDataset.from_full_graph(
      P_PARTS, np.asarray(rows), np.asarray(cols), node_feat=feats,
      node_label=labels, num_nodes=N, **kw)


def _init_state(tx, bs=16):
  """Params from a single-chip loader batch over an equivalent graph
  (shapes only matter via feature dim / classes)."""
  rng = np.random.default_rng(0)
  ds = (Dataset()
        .init_graph((np.arange(32), (np.arange(32) + 1) % 32),
                    layout='COO', num_nodes=32)
        .init_node_features(rng.random((32, 8), np.float32).astype(
            np.float32))
        .init_node_labels((np.arange(32) % CLASSES).astype(np.int32)))
  loader = NeighborLoader(ds, [3, 2], np.arange(32), batch_size=bs)
  model = GraphSAGE(hidden_features=16, out_features=CLASSES,
                    num_layers=2)
  return create_train_state(model, jax.random.key(0),
                            next(iter(loader)), tx)


def test_fused_dist_epoch_trains():
  ds = _dist_dataset()
  mesh = make_mesh(P_PARTS)
  tx = optax.adam(1e-2)
  state, apply_fn = _init_state(tx)
  fused = FusedDistEpoch(ds, [3, 2], np.arange(N), apply_fn, tx,
                         batch_size=16, mesh=mesh, shuffle=True, seed=0)
  assert len(fused) == N // (16 * P_PARTS)
  state = replicate(state, mesh)
  state, first = fused.run(state)
  for _ in range(12):
    state, stats = fused.run(state)
  assert stats['seeds'] == N
  assert stats['loss'] < first['loss']
  assert stats['accuracy'] > 0.6
  # telemetry flowed out of the fused program
  st = fused.sampler.exchange_stats(tick_metrics=False)
  assert st['dist.frontier.offered'] > 0
  # evaluate(): one SPMD scan program, same graph as the train split's
  # accuracy (VERDICT r4 #5 — dist fused eval without leaving the
  # fused path).  Params are replicated; pass the replicated leaf tree.
  acc = fused.evaluate(state.params, np.arange(N))
  assert acc > 0.6
  assert abs(acc - stats['accuracy']) < 0.25


def test_fused_dist_matches_per_batch_engine():
  """Same seeds, same slack: fused scan step 0 must equal the
  per-batch mesh sampler + DP step (identical key schedules are not
  promised — compare the TRAINING SIGNAL by loss magnitude and the
  telemetry's offered counts over one epoch)."""
  ds = _dist_dataset()
  mesh = make_mesh(P_PARTS)
  tx = optax.adam(1e-2)
  state, apply_fn = _init_state(tx)

  fused = FusedDistEpoch(ds, [3, 2], np.arange(N), apply_fn, tx,
                         batch_size=16, mesh=mesh, shuffle=False,
                         seed=0, input_space='old')
  s1 = replicate(jax.tree_util.tree_map(jnp.copy, state), mesh)
  s1, stats = fused.run(s1)
  offered_fused = fused.sampler.exchange_stats(
      tick_metrics=False)['dist.frontier.offered']

  from graphlearn_tpu.parallel import make_dp_supervised_step
  loader = DistNeighborLoader(ds, [3, 2], np.arange(N), batch_size=16,
                              mesh=mesh, shuffle=False, seed=0)
  step = make_dp_supervised_step(apply_fn, tx, 16, mesh)
  s2 = replicate(jax.tree_util.tree_map(jnp.copy, state), mesh)
  losses = []
  for batch in loader:
    s2, loss, _ = step(s2, batch)
    losses.append(float(loss))
  st_loader = loader.sampler.exchange_stats(tick_metrics=False)
  # identical exchange GEOMETRY: same static slot budget per epoch
  # (offered counts differ by RNG schedule — compare only coarsely)
  st_fused = fused.sampler.exchange_stats(tick_metrics=False)
  assert st_fused['dist.frontier.slots'] == st_loader[
      'dist.frontier.slots']
  assert 0 < offered_fused
  ratio = offered_fused / max(st_loader['dist.frontier.offered'], 1)
  assert 0.7 < ratio < 1.4, ratio
  assert len(losses) == len(np.asarray(stats['losses']))
  assert abs(stats['loss'] - np.mean(losses)) < 0.3


def test_fused_dist_link_epoch_trains():
  """Binary-mode fused mesh link training: loss decreases below ln(2)
  (positives separated from collective strict negatives) and the
  exchange telemetry flows out of the scan."""
  from graphlearn_tpu.parallel import FusedDistLinkEpoch
  ds = _dist_dataset()
  mesh = make_mesh(P_PARTS)
  tx = optax.adam(1e-2)
  state, apply_fn = _init_embed_state(tx)
  # seed edges = existing edges (positives), OLD id space
  rows = np.repeat(np.arange(N), 5)[:512]
  cols = np.asarray(
      [int(c) for r in range(N) for c in _neighbors_of(ds, r)])[:512]
  fused = FusedDistLinkEpoch(ds, [3, 2], (rows[:512], cols[:512]),
                             apply_fn, tx, batch_size=16, mesh=mesh,
                             neg_sampling='binary', shuffle=True,
                             seed=0)
  state = replicate(state, mesh)
  state, first = fused.run(state)
  for _ in range(15):
    state, stats = fused.run(state)
  assert stats['seeds'] == 512
  assert stats['loss'] < first['loss']
  assert stats['loss'] < 0.67
  st = fused.sampler.exchange_stats(tick_metrics=False)
  assert st['dist.frontier.offered'] > 0
  # evaluate(): held-out link AUC as one SPMD scan program — trained
  # positives must rank above fresh strict negatives (VERDICT r4 #5)
  auc = fused.evaluate(state.params, (rows[:128], cols[:128]))
  assert 0.6 < auc <= 1.0


def _neighbors_of(ds, r):
  """Old-space out-neighbors of old node r (via the shard CSR)."""
  new = int(ds.old2new[r])
  bounds = np.asarray(ds.graph.bounds)
  p = int(np.searchsorted(bounds, new, side='right')) - 1
  local = new - bounds[p]
  indptr = np.asarray(ds.graph.indptr[p])
  indices = np.asarray(ds.graph.indices[p])
  nbrs = indices[indptr[local]:indptr[local + 1]]
  return ds.new2old[nbrs]


def _init_embed_state(tx, bs=16):
  """Embedding model (out = 16-dim embeddings) for the link tests."""
  model = GraphSAGE(hidden_features=16, out_features=16, num_layers=2)
  rng = np.random.default_rng(0)
  ds0 = (Dataset()
         .init_graph((np.arange(32), (np.arange(32) + 1) % 32),
                     layout='COO', num_nodes=32)
         .init_node_features(rng.random((32, 8)).astype(np.float32)))
  loader = NeighborLoader(ds0, [3, 2], np.arange(32), batch_size=bs)
  b0 = next(iter(loader))
  params = model.init(jax.random.key(0), b0.x, b0.edge_index,
                      b0.edge_mask)
  from graphlearn_tpu.models.train import TrainState
  state = TrainState(params, tx.init(params), jnp.zeros((), jnp.int32))
  return state, model.apply


def test_fused_dist_link_tiered_trains():
  """The mesh link driver's tiered path: chunked collect → cold
  service → train/AUC-consume scans run end-to-end."""
  from graphlearn_tpu.parallel import FusedDistLinkEpoch
  ds = _dist_dataset(split_ratio=0.5)
  mesh = make_mesh(P_PARTS)
  tx = optax.adam(1e-2)
  state, apply_fn = _init_embed_state(tx)
  rows = np.repeat(np.arange(N), 5)[:256]
  cols = np.asarray(
      [int(c) for r in range(N) for c in _neighbors_of(ds, r)])[:256]
  fused = FusedDistLinkEpoch(ds, [3, 2], (rows, cols), apply_fn, tx,
                             batch_size=16, mesh=mesh,
                             neg_sampling='binary', shuffle=True,
                             seed=0)
  assert fused._tiered
  state = replicate(state, mesh)
  state, first = fused.run(state)
  for _ in range(4):
    state, stats = fused.run(state)
  assert stats['seeds'] == 256
  assert np.isfinite(float(stats['loss']))
  st = fused.sampler.exchange_stats(tick_metrics=False)
  assert st['dist.feature.cold_lookups'] > 0
  auc = fused.evaluate(state.params, (rows[:64], cols[:64]))
  assert 0.0 <= auc <= 1.0


def test_fused_dist_link_refuses_adaptive():
  from graphlearn_tpu.parallel import FusedDistLinkEpoch
  ds = _dist_dataset()
  tx = optax.adam(1e-2)
  state, apply_fn = _init_embed_state(tx)
  with pytest.raises(ValueError, match='adaptive'):
    FusedDistLinkEpoch(ds, [3, 2], (np.arange(16), np.arange(16)),
                       apply_fn, tx, batch_size=8,
                       mesh=make_mesh(P_PARTS),
                       exchange_slack='adaptive')


def test_fused_dist_tiered_epoch_matches_per_batch():
  """ISSUE 5 acceptance: FusedDistEpoch runs end-to-end with a
  ``split_ratio < 1`` store, and its chunked collect + cold-service
  batches are IDENTICAL to the per-batch tiered sampler driven with
  the same keys."""
  from graphlearn_tpu.parallel import DistNeighborSampler
  ds = _dist_dataset(split_ratio=0.4)
  mesh = make_mesh(P_PARTS)
  tx = optax.adam(1e-2)
  state, apply_fn = _init_state(tx)
  fused = FusedDistEpoch(ds, [3, 2], np.arange(N), apply_fn, tx,
                         batch_size=16, mesh=mesh, shuffle=False,
                         seed=0)
  assert fused._tiered
  # -- batch identity vs the per-batch engine, same keys ------------
  seeds = np.stack(list(fused._batcher)).reshape(-1, P_PARTS, 16)
  key = jax.random.fold_in(fused._base_key, 1)    # epoch 1's key
  keys = fused._chunk_key_stack(key, 0, seeds.shape[0])
  batches, _stats = fused._compiled_collect(
      fused._put_batches(seeds), keys, fused.sampler._arrays())
  batches = fused._overlay_chunk(batches)
  ref = DistNeighborSampler(ds, [3, 2], mesh=mesh, seed=0)
  for i in range(seeds.shape[0]):
    out = ref.sample_from_nodes(seeds[i], key=keys[i])
    np.testing.assert_array_equal(np.asarray(batches.node[i]),
                                  np.asarray(out['node']))
    np.testing.assert_array_equal(np.asarray(batches.x[i]),
                                  np.asarray(out['x']))
    np.testing.assert_array_equal(np.asarray(batches.y[i]),
                                  np.asarray(out['y']))
  # the store really is tiered and the cold tier was exercised
  st = fused.sampler.exchange_stats(tick_metrics=False)
  assert st['dist.feature.cold_lookups'] > 0
  # -- end-to-end: run() + evaluate() through the tiered path -------
  state = replicate(state, mesh)
  state, first = fused.run(state)
  for _ in range(8):
    state, stats = fused.run(state)
  assert stats['seeds'] == N
  assert stats['loss'] < first['loss']
  acc = fused.evaluate(state.params, np.arange(N))
  assert 0.0 <= acc <= 1.0


def test_fused_dist_tiered_tail_chunk_padded():
  """S % chunk != 0: the tail chunk pads with INVALID_ID steps so
  every chunk reuses ONE compiled shape, and losses/valid counts are
  identical to the unchunked epoch (padded steps contribute nothing)."""
  import os
  ds = _dist_dataset(split_ratio=0.4)
  mesh = make_mesh(P_PARTS)
  tx = optax.adam(1e-2)
  state, apply_fn = _init_state(tx)
  state = replicate(state, mesh)

  def epoch_losses(chunk_env):
    os.environ['GLT_FUSED_COLD_CHUNK'] = chunk_env
    try:
      fused = FusedDistEpoch(ds, [3, 2], np.arange(N), apply_fn, tx,
                             batch_size=16, mesh=mesh, shuffle=False,
                             seed=0)
      assert fused._tiered
      _, stats = fused.run(jax.tree_util.tree_map(jnp.copy, state))
      acc = fused.evaluate(state.params, np.arange(N))
      return np.asarray(stats.losses), int(stats['seeds']), acc
    finally:
      del os.environ['GLT_FUSED_COLD_CHUNK']

  # 4 steps per epoch: chunk=3 → chunks of 3 + a 1-step tail padded
  # to 3; chunk=4 → one exact chunk (the reference)
  ls_tail, seeds_tail, acc_tail = epoch_losses('3')
  ls_ref, seeds_ref, acc_ref = epoch_losses('4')
  assert ls_tail.shape == ls_ref.shape          # padded steps sliced
  np.testing.assert_allclose(ls_tail, ls_ref, rtol=1e-6)
  assert seeds_tail == seeds_ref == N
  assert acc_tail == acc_ref


def test_fused_dist_refuses_adaptive_slack():
  ds = _dist_dataset()
  tx = optax.adam(1e-2)
  _, apply_fn = _init_state(tx)
  with pytest.raises(ValueError, match='adaptive'):
    FusedDistEpoch(ds, [3, 2], np.arange(N), apply_fn, tx,
                   batch_size=16, mesh=make_mesh(P_PARTS),
                   exchange_slack='adaptive')


def test_fused_dist_tree_epoch_trains():
  """The mesh tree path: sharded-graph tree expansion + one fused
  feature/label exchange + pmean DP updates learn the planted
  communities, evaluate() agrees, and telemetry flows."""
  from graphlearn_tpu.models import TreeSAGE
  from graphlearn_tpu.parallel import FusedDistTreeEpoch
  ds = _dist_dataset()
  mesh = make_mesh(P_PARTS)
  tx = optax.adam(1e-2)
  model = TreeSAGE(hidden_features=16, out_features=CLASSES,
                   num_layers=2)
  fused = FusedDistTreeEpoch(ds, [4, 3], np.arange(N), model, tx,
                             batch_size=16, mesh=mesh, shuffle=True,
                             seed=0)
  assert len(fused) == N // (16 * P_PARTS)
  state = fused.init_state(jax.random.key(0))
  state, first = fused.run(state)
  for _ in range(14):
    state, stats = fused.run(state)
  assert stats['seeds'] == N
  assert stats['loss'] < first['loss']
  assert stats['accuracy'] > 0.6, stats['accuracy']
  acc = fused.evaluate(state.params, np.arange(N))
  assert acc > 0.6, acc
  st = fused.sampler.exchange_stats(tick_metrics=False)
  assert st['dist.frontier.offered'] > 0
  assert st['dist.feature.offered'] > 0


def test_fused_dist_tree_tiered_trains():
  """The tree driver's tiered path: chunked collect (concatenated
  level layout) → cold service → consume scans train end-to-end."""
  from graphlearn_tpu.models import TreeSAGE
  from graphlearn_tpu.parallel import FusedDistTreeEpoch
  ds = _dist_dataset(split_ratio=0.5)
  mesh = make_mesh(P_PARTS)
  tx = optax.adam(1e-2)
  model = TreeSAGE(hidden_features=16, out_features=CLASSES,
                   num_layers=2)
  fused = FusedDistTreeEpoch(ds, [4, 3], np.arange(N), model, tx,
                             batch_size=16, mesh=mesh, shuffle=True,
                             seed=0)
  assert fused._tiered
  state = fused.init_state(jax.random.key(0))
  state, first = fused.run(state)
  for _ in range(6):
    state, stats = fused.run(state)
  assert stats['seeds'] == N
  assert np.isfinite(float(stats['loss']))
  assert stats['loss'] < first['loss']
  st = fused.sampler.exchange_stats(tick_metrics=False)
  assert st['dist.feature.cold_lookups'] > 0
  acc = fused.evaluate(state.params, np.arange(N))
  assert 0.0 <= acc <= 1.0


def test_fused_dist_tree_refuses_adaptive():
  from graphlearn_tpu.models import TreeSAGE
  from graphlearn_tpu.parallel import FusedDistTreeEpoch
  model = TreeSAGE(hidden_features=8, out_features=CLASSES,
                   num_layers=2)
  tx = optax.adam(1e-2)
  with pytest.raises(ValueError, match='adaptive'):
    FusedDistTreeEpoch(_dist_dataset(), [3, 2], np.arange(N), model,
                       tx, batch_size=16, mesh=make_mesh(P_PARTS),
                       exchange_slack='adaptive')
