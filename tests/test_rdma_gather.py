"""RDMA feature-exchange prototype vs the all_to_all reference path.

Interpret-mode validation on the virtual CPU mesh (VERDICT-r1 next-7):
the per-row remote-DMA gather must return exactly what
`dist_gather` returns for the same sharded table and id sets —
including invalid ids and capacity-dropped slots.
"""
import numpy as np
import pytest

jax = pytest.importorskip('jax')
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from graphlearn_tpu.parallel import make_mesh
from graphlearn_tpu.parallel.dist_sampler import dist_gather
from graphlearn_tpu.parallel.rdma_gather import rdma_gather
from graphlearn_tpu.parallel.shard_map_compat import shard_map

NP = 8
ROWS = 16          # per shard
D = 8


def _setup():
  mesh = make_mesh(NP)
  bounds = np.arange(NP + 1, dtype=np.int64) * ROWS
  # shard p row r holds value (global id = p*ROWS + r) in every column
  shards = np.arange(NP * ROWS, dtype=np.float32).reshape(
      NP, ROWS)[:, :, None] * np.ones((1, 1, D), np.float32)
  return mesh, bounds, shards


def _run(fn, mesh, shards, bounds, ids, **kw):
  sh = NamedSharding(mesh, P('data'))
  rp = NamedSharding(mesh, P())

  def per_dev(shard_s, bounds_r, ids_s):
    return fn(shard_s[0], bounds_r, ids_s[0], 'data', NP, **kw)[None]

  f = shard_map(per_dev, mesh=mesh, in_specs=(P('data'), P(), P('data')),
                out_specs=P('data'))
  return np.asarray(jax.jit(f)(
      jax.device_put(shards, sh), jax.device_put(bounds, rp),
      jax.device_put(ids, sh)))


def test_rdma_gather_matches_all_to_all():
  mesh, bounds, shards = _setup()
  rng = np.random.default_rng(0)
  ids = rng.integers(0, NP * ROWS, (NP, 24)).astype(np.int32)
  ids[0, 3] = -1                      # invalid slots return zero rows
  ids[5, 0] = -1
  ref = _run(dist_gather, mesh, shards, bounds, ids)
  got = _run(rdma_gather, mesh, shards, bounds, ids)
  np.testing.assert_allclose(got, ref)
  # value check against first principles too
  for p in range(NP):
    for i, gid in enumerate(ids[p]):
      expect = 0.0 if gid < 0 else float(gid)
      assert got[p, i, 0] == expect, (p, i, gid)


def test_rdma_gather_respects_capacity_drops():
  mesh, bounds, shards = _setup()
  # all ids owned by partition 0 -> a capacity of 8 drops the tail
  ids = np.tile(np.arange(12, dtype=np.int32), (NP, 1))
  got = _run(rdma_gather, mesh, shards, bounds, ids,
             exchange_capacity=8)
  for p in range(NP):
    kept = (got[p, :, 0] != 0).sum()
    assert kept <= 8
    for i in range(12):
      v = got[p, i, 0]
      assert v == float(ids[p, i]) or v == 0.0
