"""Hetero sampler/loader tests.

Mirrors reference `test/python/test_hetero_neighbor_sampler.py` intent:
per-etype fanouts, per-ntype dedup, reversed-etype emission, feature
provenance — on a deterministic bipartite-ish graph.
"""
import numpy as np

from graphlearn_tpu.data import Dataset
from graphlearn_tpu.loader import NeighborLoader
from graphlearn_tpu.sampler import HeteroNeighborSampler, NodeSamplerInput
from graphlearn_tpu.typing import reverse_edge_type


U, I = 'user', 'item'
ET_UI = (U, 'clicks', I)
ET_IU = (I, 'rev_clicks', U)


def _hetero_dataset(nu=12, ni=20, d=4):
  # user u clicks items (2u) % ni and (2u+1) % ni; reverse edges too.
  rows_ui = np.repeat(np.arange(nu), 2)
  cols_ui = (2 * rows_ui + np.tile([0, 1], nu)) % ni
  ds = (Dataset()
        .init_graph({ET_UI: (rows_ui, cols_ui),
                     ET_IU: (cols_ui, rows_ui)}, layout='COO',
                    num_nodes={ET_UI: nu, ET_IU: ni})
        .init_node_features(
            {U: np.arange(nu, dtype=np.float32)[:, None]
             * np.ones((1, d), np.float32),
             I: 1000 + np.arange(ni, dtype=np.float32)[:, None]
             * np.ones((1, d), np.float32)},
            split_ratio=1.0)
        .init_node_labels({U: np.arange(nu, dtype=np.int32) % 3}))
  return ds, rows_ui, cols_ui


def test_hetero_one_hop_edges_exist():
  ds, rows_ui, cols_ui = _hetero_dataset()
  graphs = ds.get_graph()
  s = HeteroNeighborSampler(graphs, [2], seed=0)
  out = s.sample_from_nodes(
      NodeSamplerInput(node=np.arange(6), input_type=U))
  # users sampled via (user, clicks, item): emitted under reversed type.
  rev = reverse_edge_type(ET_UI)
  assert rev in out.row
  r = np.asarray(out.row[rev])
  c = np.asarray(out.col[rev])
  m = np.asarray(out.edge_mask[rev])
  users = np.asarray(out.node[U])
  items = np.asarray(out.node[I])
  existing = set(zip(rows_ui.tolist(), cols_ui.tolist()))
  assert m.any()
  for i in np.nonzero(m)[0]:
    item_local, user_local = r[i], c[i]
    # user -> item edge must exist in the original graph.
    assert (int(users[user_local]), int(items[item_local])) in existing


def test_hetero_two_hop_discovers_users():
  ds, _, _ = _hetero_dataset()
  s = HeteroNeighborSampler(ds.get_graph(), [2, 2], seed=0)
  out = s.sample_from_nodes(
      NodeSamplerInput(node=np.arange(4), input_type=U))
  ucount = int(out.node_count[U])
  icount = int(out.node_count[I])
  assert icount > 0
  # hop 2 walks item->user, discovering more users than the 4 seeds.
  assert ucount >= 4
  rev_iu = reverse_edge_type(ET_IU)
  assert np.asarray(out.edge_mask[rev_iu]).any()
  # seeds keep local ids 0..3.
  users = np.asarray(out.node[U])
  np.testing.assert_array_equal(users[:4], np.arange(4))


def test_hetero_per_etype_fanouts():
  ds, _, _ = _hetero_dataset()
  s = HeteroNeighborSampler(ds.get_graph(),
                            {ET_UI: [2], ET_IU: []}, seed=0)
  out = s.sample_from_nodes(
      NodeSamplerInput(node=np.arange(4), input_type=U))
  assert reverse_edge_type(ET_UI) in out.row
  assert reverse_edge_type(ET_IU) not in out.row


def test_hetero_loader_collates_features():
  ds, _, _ = _hetero_dataset()
  loader = NeighborLoader(ds, [2, 2], (U, np.arange(12)), batch_size=4,
                          seed=0)
  n_batches = 0
  for batch in loader:
    n_batches += 1
    for nt in (U, I):
      ids = np.asarray(batch.node_dict[nt])
      m = np.asarray(batch.node_mask_dict[nt])
      x = np.asarray(batch.x_dict[nt])
      base = 0 if nt == U else 1000
      np.testing.assert_allclose(x[m, 0], base + ids[m])
      np.testing.assert_allclose(x[~m], 0)
    y = np.asarray(batch.y_dict[U])
    ids = np.asarray(batch.node_dict[U])
    m = np.asarray(batch.node_mask_dict[U])
    np.testing.assert_array_equal(y[m], ids[m] % 3)
  assert n_batches == 3


def test_hetero_dedup_across_hops():
  # Two users share items: item table must not contain duplicates.
  ds, _, _ = _hetero_dataset()
  s = HeteroNeighborSampler(ds.get_graph(), [2, 2], seed=0)
  out = s.sample_from_nodes(
      NodeSamplerInput(node=np.arange(12), input_type=U))
  for nt in (U, I):
    ids = np.asarray(out.node[nt])
    cnt = int(out.node_count[nt])
    valid = ids[:cnt]
    assert len(np.unique(valid)) == cnt
