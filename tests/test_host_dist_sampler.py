"""Cross-server host runtime: 2 partition services on localhost, real
socket RPC, partition-encoded provenance (VERDICT r2 item 2).

The SURVEY §4 pattern: a deterministic synthetic 2-partition dataset
whose features encode node ids, every role a local process/thread, no
mocks — the real RPC + native-op stack runs.  Correctness is asserted
against the FULL graph: with fanout >= max degree the sampled
neighborhood equals the exact one, so a shard-fed sampler that failed
to fan out per hop would visibly under-sample.
"""
import numpy as np
import pytest

from graphlearn_tpu.distributed import (HostDataset,
                                        HostDistNeighborSampler,
                                        HostNeighborSampler,
                                        PartitionService, connect_peers)
from graphlearn_tpu.partition import RandomPartitioner

N = 40
E = 2 * N  # ring: v -> v+1, v -> v+2


def _write_partitions(root, num_parts=2, with_efeat=True):
  rows = np.concatenate([np.arange(N), np.arange(N)]).astype(np.int64)
  cols = np.concatenate([(np.arange(N) + 1) % N,
                         (np.arange(N) + 2) % N]).astype(np.int64)
  feats = np.arange(N, dtype=np.float32)[:, None] * np.ones(
      (1, 4), np.float32)                      # feat[v] == v
  labels = (np.arange(N) % 3).astype(np.int32)
  efeats = (np.arange(E, dtype=np.float32)[:, None] * np.ones(
      (1, 2), np.float32) if with_efeat else None)   # efeat[e] == e
  RandomPartitioner(root, num_parts, N, (rows, cols), node_feat=feats,
                    node_label=labels, edge_feat=efeats,
                    seed=0).partition()
  return rows, cols, feats, labels, efeats


@pytest.fixture
def deployment(tmp_path):
  """2 shards served on localhost + a sampler on each shard."""
  _write_partitions(tmp_path)
  shards = [HostDataset.from_partition_dir(tmp_path, i) for i in range(2)]
  services = [PartitionService(s, host='127.0.0.1') for s in shards]
  addrs = [('127.0.0.1', sv.port) for sv in services]
  yield shards, services, addrs
  for sv in services:
    sv.shutdown()


def test_guard_refuses_shard(deployment):
  shards, _, _ = deployment
  with pytest.raises(ValueError, match='partition shard'):
    HostNeighborSampler(shards[0], [2])


def test_cross_server_node_sampling_exact(deployment):
  """fanout >= degree: neighborhoods must equal the full-graph exact
  ones — impossible without per-hop remote fan-out (each shard owns
  only half the rows)."""
  shards, _, addrs = deployment
  for part in range(2):
    sampler = HostDistNeighborSampler(
        shards[part], [2, 2], connect_peers(addrs, part),
        with_edge=True, seed=7)
    seeds = np.arange(0, N, 5, dtype=np.int64)
    msg = sampler.sample_from_nodes(seeds)
    ids, rows, cols = msg['ids'], msg['rows'], msg['cols']
    # exact 2-hop closure of the ring: {s, s+1, s+2, s+3, s+4}
    expect = set()
    for s in seeds:
      expect.update(((s + d) % N) for d in range(5))
    assert set(ids.tolist()) == expect
    # every edge is a real ring edge (emitted transposed for PyG
    # message passing: graph edge is col -> row)
    d = (ids[rows] - ids[cols]) % N
    assert np.isin(d, [1, 2]).all()
    # both hops sampled everything: 2 edges per frontier node per hop
    hop1 = len(seeds) * 2
    assert len(rows) >= hop1
    # provenance: features/labels encode ORIGINAL node ids — remote
    # rows included (zero-filled shard features would fail here)
    np.testing.assert_allclose(msg['nfeats'][:, 0],
                               ids.astype(np.float32))
    np.testing.assert_array_equal(msg['nlabels'], ids % 3)
    # edge features encode global eids (collected on the owning server)
    np.testing.assert_allclose(msg['efeats'][:, 0],
                               msg['eids'].astype(np.float32))


def test_cross_server_feature_only_lookup(deployment):
  """Feature fan-out alone (seeds on one shard, features everywhere)."""
  shards, _, addrs = deployment
  sampler = HostDistNeighborSampler(shards[0], [2],
                                    connect_peers(addrs, 0), seed=1)
  feats = sampler._gather_node_features(np.arange(N, dtype=np.int64))
  np.testing.assert_allclose(feats[:, 0], np.arange(N, dtype=np.float32))
  labels = sampler._gather_node_labels(np.arange(N, dtype=np.int64))
  np.testing.assert_array_equal(labels, np.arange(N) % 3)


def test_cross_server_link_sampling(deployment):
  shards, _, addrs = deployment
  sampler = HostDistNeighborSampler(shards[0], [2],
                                    connect_peers(addrs, 0),
                                    with_edge=True, seed=3)
  src = np.arange(8, dtype=np.int64)
  dst = (src + 1) % N
  msg = sampler.sample_from_edges(src, dst, neg_mode='binary')
  ids = msg['ids']
  np.testing.assert_allclose(msg['nfeats'][:, 0], ids.astype(np.float32))
  eli = msg['#META.edge_label_index']
  elab = msg['#META.edge_label']
  emask = msg['#META.edge_label_mask']
  # positive pairs map to the seed endpoints
  np.testing.assert_array_equal(ids[eli[0, :8]], src)
  np.testing.assert_array_equal(ids[eli[1, :8]], dst)
  assert elab[:8].all() and emask[:8].all()
  # negatives marked ok must not be ring edges
  edge_set = {( int(a), int((a + 1) % N)) for a in range(N)} | \
             {( int(a), int((a + 2) % N)) for a in range(N)}
  neg_r = ids[eli[0, 8:]][emask[8:]]
  neg_c = ids[eli[1, 8:]][emask[8:]]
  for a, b in zip(neg_r.tolist(), neg_c.tolist()):
    assert (a, b) not in edge_set


def test_cross_server_subgraph(deployment):
  """Induced subgraph over the 2-hop closure: edges among closure
  nodes must match the brute-force count over the FULL ring."""
  shards, _, addrs = deployment
  sampler = HostDistNeighborSampler(shards[1], [2, 2],
                                    connect_peers(addrs, 1),
                                    with_edge=True, seed=5)
  seeds = np.array([0, 20], dtype=np.int64)
  msg = sampler.sample_subgraph(seeds)
  ids, rows, cols = msg['ids'], msg['rows'], msg['cols']
  closure = set(ids.tolist())
  # brute force: every ring edge with both ends in the closure
  expect = {(u, (u + d) % N) for u in range(N) for d in (1, 2)
            if u in closure and (u + d) % N in closure}
  got = {(int(ids[r]), int(ids[c])) for r, c in zip(rows, cols)}
  assert got == expect
  # edge features for every induced edge, by global eid
  np.testing.assert_allclose(msg['efeats'][:, 0],
                             msg['eids'].astype(np.float32))
  np.testing.assert_allclose(msg['nfeats'][:, 0], ids.astype(np.float32))


def test_missing_peer_raises(deployment):
  shards, _, addrs = deployment
  with pytest.raises(ValueError, match='no peer client'):
    HostDistNeighborSampler(shards[0], [2], {})


def test_dead_peer_raises_not_hangs(deployment, monkeypatch):
  """A peer that dies mid-epoch must surface a typed error once the
  retry deadline expires (a peer that came BACK inside the deadline
  would heal the hop transparently — distributed/resilience.py),
  never a silent under-sample or an indefinite hang — the
  host-runtime arm of the failure-handling story.  The deadline is
  shortened so 'prompt' stays prompt on the test clock."""
  from graphlearn_tpu.distributed.resilience import (
      RetryExhausted, reset_default_policy)
  monkeypatch.setenv('GLT_RPC_DEADLINE', '2.0')
  monkeypatch.setenv('GLT_RPC_BACKOFF_CAP', '0.2')
  reset_default_policy()
  try:
    shards, services, addrs = deployment
    sampler = HostDistNeighborSampler(shards[0], [2],
                                      connect_peers(addrs, 0), seed=0)
    # first batch works
    sampler.sample_from_nodes(np.arange(4, dtype=np.int64))
    services[1].shutdown()
    with pytest.raises((RetryExhausted, ConnectionError, OSError)):
      # remote-owned seeds force RPC to the dead peer
      for _ in range(4):
        sampler.sample_from_nodes(np.arange(N, dtype=np.int64))
  finally:
    reset_default_policy()         # don't leak the short deadline
