"""Host-runtime strict-negative validity + tail-batch pair_mask.

Regressions for two runtime-disagreement bugs: (1) on graphs dense
enough that strict rejection exhausts its trials, the host producers
used to ship the fallback (possibly real-edge) pairs unmasked, while
the mesh engine masked them via ``neg_ok``; (2) ``pair_mask`` was
derived from emission width (always all-True) instead of seed
validity, marking padded tail-batch slots valid.  Mirrors the
reference's padding semantics (`random_negative_sampler.cu:96-120`)
with the mesh engine's masking contract on top.
"""
import numpy as np
import pytest

from graphlearn_tpu import native
from graphlearn_tpu.distributed import DistLinkNeighborLoader, HostDataset

pytestmark = pytest.mark.skipif(not native.available(),
                                reason='native lib unavailable')

N = 10


def _complete_graph():
  """Every (u, v) pair INCLUDING self-loops is an edge: strict
  negative sampling cannot succeed, every trial collides."""
  rows = np.repeat(np.arange(N), N)
  cols = np.tile(np.arange(N), N)
  feats = np.tile(np.arange(N, dtype=np.float32)[:, None], (1, 3))
  return HostDataset.from_coo(rows, cols, N, node_features=feats), rows, cols


def test_binary_exhausted_trials_are_masked():
  ds, rows, cols = _complete_graph()
  loader = DistLinkNeighborLoader(
      ds, [2], (rows[:8], cols[:8]),
      neg_sampling=('binary', 1.0), batch_size=8, to_device=False)
  for batch in loader:
    lab = np.asarray(batch.metadata['edge_label'])
    mask = np.asarray(batch.metadata['edge_label_mask'])
    # positives stay valid; every negative slot collided and must be
    # masked out (its fallback pair IS a real edge on this graph)
    assert mask[:8].all()
    assert not mask[lab == 0].any()


def test_triplet_exhausted_trials_invalidate_dst_neg():
  ds, rows, cols = _complete_graph()
  loader = DistLinkNeighborLoader(
      ds, [2], (rows[:8], cols[:8]),
      neg_sampling=('triplet', 2), batch_size=8, to_device=False)
  batch = next(iter(loader))
  dneg = np.asarray(batch.metadata['dst_neg_index'])
  assert (dneg == -1).all()


def test_tail_batch_pair_mask_tracks_seed_validity():
  # sparse ring so negatives succeed; 10 seeds into batches of 8
  # leaves a 2-seed tail whose 6 padded slots must read invalid
  rows = np.arange(40)
  cols = (rows + 1) % 40
  ds = HostDataset.from_coo(rows, cols, 40)
  loader = DistLinkNeighborLoader(
      ds, [2], (rows[:10], cols[:10]),
      neg_sampling=('triplet', 1), batch_size=8, to_device=False)
  masks = []
  for batch in loader:
    pm = np.asarray(batch.metadata['pair_mask'])
    si = np.asarray(batch.metadata['src_index'])
    assert (pm == (si >= 0)).all()
    masks.append(pm.sum())
  assert sorted(masks) == [2, 8]
