"""Two-process jax.distributed smoke test (VERDICT-r1 missing #3).

Spawns 2 REAL ``jax.distributed`` CPU processes on localhost (4
virtual devices each -> one 8-device global mesh), runs
`multihost.initialize()` + a full DistNeighborLoader epoch + one DP
training step in each, and asserts: identical per-host seed-shard
schedules (disjoint, covering), equal finite losses (the psum'd DP
step is replicated), and matching batch counts.  The JAX analog of the
reference's localhost multi-role tests
(`test/python/dist_test_utils.py:15-120`) — no mocks, the real
cross-process runtime.
"""
import json
import os
import socket
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

#: CPU-mesh scan-compile heavy (multi-minute): excluded from the
#: default run, selected by `pytest -m slow` (see pyproject.toml)
pytestmark = pytest.mark.slow


def _free_port() -> int:
  with socket.socket() as s:
    s.bind(('localhost', 0))
    return s.getsockname()[1]


def test_two_process_distributed_epoch(tmp_path):
  port = _free_port()
  worker = Path(__file__).parent / '_multihost_worker.py'
  env = dict(os.environ)
  env.pop('PALLAS_AXON_POOL_IPS', None)   # no TPU plugin in children
  env['JAX_PLATFORMS'] = 'cpu'
  flags = ' '.join(
      f for f in env.get('XLA_FLAGS', '').split()
      if '--xla_force_host_platform_device_count' not in f)
  env['XLA_FLAGS'] = (
      flags + ' --xla_force_host_platform_device_count=4').strip()
  env['PYTHONPATH'] = (str(Path(__file__).resolve().parent.parent)
                       + os.pathsep + env.get('PYTHONPATH', ''))
  # partition layout for the HOST-LOCAL loading phase: each process
  # materializes only its 4 mesh positions' shards
  from graphlearn_tpu.partition import RandomPartitioner
  n = 64
  rows = np.concatenate([np.arange(n), np.arange(n)])
  cols = np.concatenate([(np.arange(n) + 1) % n, (np.arange(n) + 2) % n])
  feats = (np.arange(n, dtype=np.float32)[:, None]
           * np.ones((1, 4), np.float32))
  pdir = tmp_path / 'parts'
  RandomPartitioner(pdir, 8, n, (rows, cols), node_feat=feats,
                    node_label=(np.arange(n) % 4).astype(np.int32),
                    seed=0).partition()
  # rich layout for the COMPOSED phase (r4): provenance features
  # (col 0 = old id + 1), edge features encoding eids, cache plan —
  # loaded host-local + tiered by the workers
  e = len(rows)
  efeat = np.stack([np.arange(e), rows, cols], 1).astype(np.float32)
  feats2 = np.tile((np.arange(n, dtype=np.float32) + 1)[:, None],
                   (1, 4))
  pdir2 = tmp_path / 'rich'
  RandomPartitioner(pdir2, 8, n, (rows, cols), node_feat=feats2,
                    node_label=(np.arange(n) % 4).astype(np.int32),
                    edge_feat=efeat, cache_ratio=0.1,
                    seed=0).partition()
  procs = []
  outs = []
  for pid in range(2):
    out = tmp_path / f'worker{pid}.json'
    outs.append(out)
    procs.append(subprocess.Popen(
        [sys.executable, str(worker), f'localhost:{port}', '2',
         str(pid), str(out), str(pdir), str(pdir2)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True))
  results = []
  for p in procs:
    try:
      stdout, _ = p.communicate(timeout=360)
    except subprocess.TimeoutExpired:
      for q in procs:
        q.kill()
      raise
    assert p.returncode == 0, stdout[-4000:]
    results.append(stdout)
  r0, r1 = (json.loads(o.read_text()) for o in outs)
  # deterministic, disjoint, covering seed shards
  s0, s1 = set(r0['shard']), set(r1['shard'])
  assert not (s0 & s1)
  assert s0 | s1 == set(range(64))
  assert r0['host_slice'] == [0, 4] and r1['host_slice'] == [4, 8]
  # both ran the full epoch and agree on the replicated DP loss
  assert r0['batches'] == r1['batches'] == 64 // (4 * 8)
  assert np.isfinite(r0['loss'])
  assert abs(r0['loss'] - r1['loss']) < 1e-5
  # host-local loading: each process materialized ITS 4 partitions and
  # the assembled global batch carried provenance-correct features
  assert r0['host_local']['host_parts'] == [0, 1, 2, 3]
  assert r1['host_local']['host_parts'] == [4, 5, 6, 7]
  assert r0['host_local']['provenance_rows'] > 0
  assert r1['host_local']['provenance_rows'] > 0
  # composed phase: tiered + cache + edge features host-local, with
  # cold rows OWNER-served across the two real processes
  for r in (r0, r1):
    assert r['composed']['provenance_rows'] > 0
    assert r['composed']['cold_misses'] > 0
    assert (r['composed']['cold_lookups']
            >= r['composed']['cold_misses'])
