"""IGBH on-disk layout ingestion: the reference's npy directory scheme
(`examples/igbh/dataset.py:51-157`) round-trips into the hetero
engines, torch-free.  Real-data acceptance runs wherever an IGBH dir
exists (`examples/igbh/dist_train_rgnn.py --igbh-root`)."""
import numpy as np
import pytest

from graphlearn_tpu.data import (igbh_num_classes, load_igbh_dir,
                                 partition_igbh)

NP_, NA, NI_, NF = 24, 16, 6, 8   # paper/author/institute/fos counts


def _write_igbh(root, size='tiny'):
  rng = np.random.default_rng(0)
  base = root / size / 'processed'
  spec = {
      ('paper', 'cites', 'paper'): (NP_, NP_, 48),
      ('paper', 'written_by', 'author'): (NP_, NA, 40),
      ('author', 'affiliated_to', 'institute'): (NA, NI_, 20),
      ('paper', 'topic', 'fos'): (NP_, NF, 30),
  }
  edges = {}
  for (s, rel, t), (ns, nt, e) in spec.items():
    d = base / f'{s}__{rel}__{t}'
    d.mkdir(parents=True)
    ei = np.stack([rng.integers(0, ns, e), rng.integers(0, nt, e)], 1)
    np.save(d / 'edge_index.npy', ei.astype(np.int64))
    edges[(s, rel, t)] = ei
  feats = {}
  for nt, n in (('paper', NP_), ('author', NA), ('institute', NI_),
                ('fos', NF)):
    d = base / nt
    d.mkdir(parents=True, exist_ok=True)
    f = rng.normal(size=(n, 5)).astype(np.float32)
    f[:, 0] = np.arange(n)
    np.save(d / 'node_feat.npy', f)
    feats[nt] = f
  labels = (np.arange(NP_) % 19).astype(np.int64)
  np.save(base / 'paper' / 'node_label_19.npy', labels)
  return edges, feats, labels


def test_load_igbh_dir(tmp_path):
  edges, feats, labels = _write_igbh(tmp_path)
  d = load_igbh_dir(tmp_path, 'tiny', add_reverse=False,
                    symmetrize_cites=False)
  assert set(d['edge_index_dict']) == set(edges)
  for et, ei in edges.items():
    np.testing.assert_array_equal(d['edge_index_dict'][et][0], ei[:, 0])
    np.testing.assert_array_equal(d['edge_index_dict'][et][1], ei[:, 1])
  for nt, f in feats.items():
    np.testing.assert_allclose(np.asarray(d['node_feat_dict'][nt]), f)
  np.testing.assert_array_equal(d['paper_labels'], labels)
  assert d['num_nodes_dict'] == {'paper': NP_, 'author': NA,
                                 'institute': NI_, 'fos': NF}
  # reference split convention: 60/20/20 over paper ids in order
  assert len(d['train_idx']) == int(NP_ * 0.6)
  np.testing.assert_array_equal(
      np.concatenate([d['train_idx'], d['val_idx'], d['test_idx']]),
      np.arange(NP_))
  assert igbh_num_classes() == 19


@pytest.mark.slow
def test_igbh_partition_roundtrip_to_hetero_engine(tmp_path):
  """partition_igbh -> DistHeteroDataset (tiered) -> loader epoch with
  provenance — the full IGBH pipeline minus the real download."""
  _write_igbh(tmp_path)
  pdir = tmp_path / 'parts'
  partition_igbh(tmp_path, pdir, 4, 'tiny')
  from graphlearn_tpu.parallel import (DistHeteroDataset,
                                       DistHeteroNeighborLoader,
                                       make_mesh)
  ds = DistHeteroDataset.from_partition_dir(pdir, split_ratio=0.5)
  assert ds.num_partitions == 4
  assert set(ds.ntypes) == {'paper', 'author', 'institute', 'fos'}
  loader = DistHeteroNeighborLoader(
      ds, [2, 2], ('paper', np.arange(NP_)), batch_size=2,
      shuffle=True, mesh=make_mesh(4), seed=0)
  nb = 0
  for b in loader:
    for nt in ds.ntypes:
      if nt not in b.x_dict:
        continue
      nodes = np.asarray(b.node_dict[nt])
      x = np.asarray(b.x_dict[nt])
      for p in range(4):
        m = nodes[p] >= 0
        np.testing.assert_allclose(
            x[p][m][:, 0],
            ds.new2old[nt][nodes[p][m]].astype(np.float32))
    nb += 1
  assert nb == len(loader)
  st = loader.sampler.exchange_stats()
  assert st['dist.feature.cold_lookups'] > 0


def test_missing_dir_raises(tmp_path):
  with pytest.raises(FileNotFoundError):
    load_igbh_dir(tmp_path, 'tiny')


def test_reference_graph_construction(tmp_path):
  """Default load matches the reference recipe (dataset.py:79-96):
  cites symmetrized with one self-loop per paper, every cross-type
  relation mirrored as rev_*."""
  edges, feats, labels = _write_igbh(tmp_path)
  d = load_igbh_dir(tmp_path, 'tiny')
  ets = set(d['edge_index_dict'])
  assert ('author', 'rev_written_by', 'paper') in ets
  assert ('institute', 'rev_affiliated_to', 'author') in ets
  assert ('fos', 'rev_topic', 'paper') in ets
  assert ('paper', 'rev_cites', 'paper') not in ets   # same-type: no rev
  # cites: undirected + self loops
  r, c = d['edge_index_dict'][('paper', 'cites', 'paper')]
  got = set(zip(r.tolist(), c.tolist()))
  raw = edges[('paper', 'cites', 'paper')]
  expect = set()
  for a, b in zip(raw[:, 0].tolist(), raw[:, 1].tolist()):
    if a != b:
      expect.add((a, b))
      expect.add((b, a))
  expect |= {(v, v) for v in range(NP_)}
  assert got == expect
  # reverse arrays mirror the forward ones
  fr, fc = d['edge_index_dict'][('paper', 'written_by', 'author')]
  rr, rc = d['edge_index_dict'][('author', 'rev_written_by', 'paper')]
  np.testing.assert_array_equal(fr, rc)
  np.testing.assert_array_equal(fc, rr)
