"""DistRandomPartitioner: all ranks as local processes, real sockets.

The SURVEY §4 pattern (reference `test_dist_random_partitioner.py`):
spawn every rank locally, partition a deterministic graph whose
features encode node ids, then validate the on-disk layout with the
same checks the offline partitioner's tests use — and that
`load_partition` consumes it unchanged.
"""
import multiprocessing as mp
import socket

import numpy as np
import pytest

from graphlearn_tpu.partition import load_partition


def _free_port() -> int:
  with socket.socket() as s:
    s.bind(('127.0.0.1', 0))
    return s.getsockname()[1]


def _ring(n, deg=2):
  rows = np.repeat(np.arange(n), deg)
  cols = (rows + np.tile(np.arange(1, deg + 1), n)) % n
  return rows.astype(np.int64), cols.astype(np.int64)


def _rank_main(rank, world, port, out_dir, n):
  from graphlearn_tpu.distributed.dist_random_partitioner import (
      DistRandomPartitioner, node_range)
  rows, cols = _ring(n)
  lo, hi = node_range(rank, world, n)
  # this rank holds the edges whose src is in its node range
  sel = (rows >= lo) & (rows < hi)
  # global edge ids are positions in the full COO list; a contiguous
  # ring slice makes them an offset + arange
  offset = int(np.nonzero(sel)[0][0]) if sel.any() else 0
  feats = np.tile(np.arange(lo, hi, dtype=np.float32)[:, None], (1, 4))
  labels = np.arange(lo, hi, dtype=np.int64) % 3
  p = DistRandomPartitioner(
      out_dir, n, (rows[sel], cols[sel]), feats, labels,
      rank=rank, world_size=world, master_port=port,
      edge_id_offset=offset, seed=7)
  p.partition()


@pytest.mark.parametrize('world', [2, 3])
def test_dist_partition_layout(world, tmp_path):
  n = 60
  port = _free_port()
  ctx = mp.get_context('forkserver')
  procs = [ctx.Process(target=_rank_main, args=(r, world, port,
                                                str(tmp_path), n))
           for r in range(world)]
  for p in procs:
    p.start()
  for p in procs:
    p.join(timeout=120)
    assert p.exitcode == 0

  rows, cols = _ring(n)
  parts = [load_partition(tmp_path, i) for i in range(world)]
  node_pb = np.asarray(parts[0]['node_pb'].table)
  edge_pb = np.asarray(parts[0]['edge_pb'].table)
  assert node_pb.shape == (n,)
  assert edge_pb.shape == (len(rows),)
  assert set(np.unique(node_pb)) <= set(range(world))

  seen_nodes, seen_edges = [], []
  for i, part in enumerate(parts):
    g = part['graph']
    r, c, e = g.edge_index[0], g.edge_index[1], g.eids
    # every edge is owned by its src's partition and matches the COO list
    np.testing.assert_array_equal(node_pb[r], i)
    np.testing.assert_array_equal(rows[e], r)
    np.testing.assert_array_equal(cols[e], c)
    np.testing.assert_array_equal(edge_pb[e], i)
    seen_edges.append(e)

    f = part['node_feat']
    np.testing.assert_array_equal(node_pb[f.ids], i)
    # feature value encodes the global node id
    np.testing.assert_array_equal(f.feats[:, 0], f.ids.astype(np.float32))
    labels, lids = part['node_label']
    np.testing.assert_array_equal(labels, lids % 3)
    seen_nodes.append(f.ids)

  # full disjoint coverage
  np.testing.assert_array_equal(np.sort(np.concatenate(seen_edges)),
                                np.arange(len(rows)))
  np.testing.assert_array_equal(np.sort(np.concatenate(seen_nodes)),
                                np.arange(n))


def test_matches_seeded_book(tmp_path):
  """All ranks derive the identical node book from (seed, owner)."""
  from graphlearn_tpu.distributed.dist_random_partitioner import (
      DistRandomPartitioner, node_range)
  n, world = 50, 2
  expect = np.empty((n,), np.int8)
  for r in range(world):
    lo, hi = node_range(r, world, n)
    rng = np.random.default_rng((7, r))
    expect[lo:hi] = rng.integers(0, world, hi - lo, dtype=np.int8)

  port = _free_port()
  ctx = mp.get_context('forkserver')
  procs = [ctx.Process(target=_rank_main, args=(r, world, port,
                                                str(tmp_path), n))
           for r in range(world)]
  for p in procs:
    p.start()
  for p in procs:
    p.join(timeout=120)
    assert p.exitcode == 0
  np.testing.assert_array_equal(np.load(tmp_path / 'node_pb.npy'), expect)


def _table_rank_main(rank, world, port, out_dir, n):
  from graphlearn_tpu.distributed import DistTableRandomPartitioner
  from graphlearn_tpu.distributed.dist_random_partitioner import node_range
  import tempfile, os
  rows, cols = _ring(n)
  lo, hi = node_range(rank, world, n)
  sel = (rows >= lo) & (rows < hi)
  offset = int(np.nonzero(sel)[0][0]) if sel.any() else 0
  d = tempfile.mkdtemp()
  with open(os.path.join(d, 'e.csv'), 'w') as f:
    for r, c in zip(rows[sel], cols[sel]):
      f.write(f'{r},{c}\n')
  with open(os.path.join(d, 'n.csv'), 'w') as f:
    for i in range(lo, hi):
      f.write(f'{i},{float(i)}:{float(i)}\n')
  p = DistTableRandomPartitioner(
      out_dir, n, edge_table=os.path.join(d, 'e.csv'),
      node_table=os.path.join(d, 'n.csv'),
      rank=rank, world_size=world, master_port=port,
      edge_id_offset=offset, seed=5)
  p.partition()


def test_dist_table_partitioner(tmp_path):
  n, world = 40, 2
  port = _free_port()
  ctx = mp.get_context('forkserver')
  procs = [ctx.Process(target=_table_rank_main,
                       args=(r, world, port, str(tmp_path), n))
           for r in range(world)]
  for p in procs:
    p.start()
  for p in procs:
    p.join(timeout=120)
    assert p.exitcode == 0
  pb = np.load(tmp_path / 'node_pb.npy')
  nids = []
  for i in range(world):
    part = load_partition(tmp_path, i)
    f = part['node_feat']
    np.testing.assert_array_equal(pb[f.ids], i)
    np.testing.assert_array_equal(f.feats[:, 0], f.ids.astype(np.float32))
    nids.append(f.ids)
  np.testing.assert_array_equal(np.sort(np.concatenate(nids)), np.arange(n))
