"""CSR window-gather experiment kernel (VERDICT r2 item 6): the
aligned-overfetch DMA path must agree with the XLA window gather
(interpret mode on the CPU mesh; the real-chip measurement lives in
benchmarks/bench_pallas_window.py and the pallas_gather module notes).
"""
import numpy as np
import jax.numpy as jnp
import pytest

from graphlearn_tpu.ops.pallas_window import (MAX_W, csr_window_gather,
                                              xla_window_gather)


@pytest.mark.parametrize('e,w', [(5000, 128), (5000, 64), (130000, 128),
                                 (1024, 16)])
def test_window_matches_direct(e, w):
  rng = np.random.default_rng(0)
  ind = rng.integers(0, 1 << 20, e).astype(np.int32)
  starts = rng.integers(0, e, 97).astype(np.int32)
  # force unit-boundary crossings and edge positions into the set
  starts[:3] = [max(e - 1, 0), max(e - w, 0), min(1020, e - 1)]
  out = np.asarray(csr_window_gather(jnp.asarray(ind),
                                     jnp.asarray(starts), w,
                                     interpret=True))
  assert out.shape == (97, w)
  for i, s in enumerate(starts):
    valid = min(w, e - s)
    np.testing.assert_array_equal(out[i, :valid], ind[s:s + valid])


def test_window_width_bound():
  ind = jnp.zeros((100,), jnp.int32)
  with pytest.raises(AssertionError):
    csr_window_gather(ind, jnp.zeros((4,), jnp.int32), MAX_W + 1,
                      interpret=True)


def test_xla_window_gather_clamps():
  ind = jnp.arange(100, dtype=jnp.int32)
  out = np.asarray(xla_window_gather(ind, jnp.asarray([95]), 10))
  np.testing.assert_array_equal(out[0], [95, 96, 97, 98, 99, 99, 99,
                                         99, 99, 99])
