"""Preemption-tolerant data plane (ISSUE 6): durable mid-epoch
snapshots, byte-identical resume, and the mesh stall watchdog.

The acceptance contract under test: a chaos-killed epoch, restored
from the latest published snapshot in a FRESH driver/loader (the
stand-in for a new process), finishes with exact unique batch counts
and batches/losses byte-identical to an uninterrupted seeded run; a
hung dispatch under a ``fused.dispatch`` delay fault surfaces as a
typed `MeshStallError` within the configured deadline instead of
wedging the epoch; and a failed/truncated snapshot write never
shadows the previous durable snapshot.
"""
import time

import numpy as np
import jax
import jax.numpy as jnp
import optax
import pytest

from graphlearn_tpu.data import Dataset
from graphlearn_tpu.distributed.resilience import (MeshStallError,
                                                   run_with_deadline)
from graphlearn_tpu.loader import FusedEpoch, NeighborLoader
from graphlearn_tpu.models import GraphSAGE, create_train_state
from graphlearn_tpu.parallel import (DistDataset, DistNeighborLoader,
                                     make_mesh)
from graphlearn_tpu.telemetry import recorder
from graphlearn_tpu.testing import chaos
from graphlearn_tpu.utils.checkpoint import (CheckpointMismatchError,
                                             Checkpointer,
                                             SnapshotManager,
                                             pack_rng_state,
                                             restore_rng_state,
                                             validate_tree)


@pytest.fixture(autouse=True)
def _clean():
  chaos.uninstall()
  recorder.enable(None)
  recorder.clear()
  yield
  chaos.uninstall()
  recorder.clear()
  recorder.disable()


def _tree(v=0.0):
  return {'w': np.full((3, 2), v, np.float32),
          'opt': {'step': np.int32(4), 'mu': np.arange(3, dtype=np.float64)}}


# -- Checkpointer template validation (satellite 1) -------------------------

def _mismatch_cases():
  bad_struct = {'w': np.zeros((3, 2), np.float32),
                'opt': {'step': np.int32(0)}}              # 'mu' missing
  bad_shape = _tree()
  bad_shape['w'] = np.zeros((2, 2), np.float32)
  bad_dtype = _tree()
  bad_dtype['opt']['mu'] = np.arange(3, dtype=np.float32)
  return (('structure', bad_struct, 'structure'),
          ('shape', bad_shape, 'shape'),
          ('dtype', bad_dtype, 'dtype'))


@pytest.mark.parametrize('use_orbax', [False, True],
                         ids=['numpy', 'orbax'])
def test_checkpointer_restore_validates_template(tmp_path, use_orbax):
  """A stale checkpoint must raise `CheckpointMismatchError` naming
  the first diverging path — not restore garbage silently — on BOTH
  backends."""
  if use_orbax:
    pytest.importorskip('orbax.checkpoint')
  ckpt = Checkpointer(tmp_path / 'ck', use_orbax=use_orbax)
  ckpt.save(1, _tree(1.5))
  out = ckpt.restore(template=_tree())           # matching: round trips
  np.testing.assert_array_equal(out['w'], np.full((3, 2), 1.5, np.float32))
  for name, template, msg in _mismatch_cases():
    with pytest.raises(CheckpointMismatchError, match=msg) as ei:
      ckpt.restore(template=template)
    assert ei.value.path, f'{name}: the diverging path is the point'


def test_validate_tree_names_first_diverging_path():
  good = _tree()
  bad = _tree()
  bad['opt']['mu'] = np.arange(4, dtype=np.float64)
  with pytest.raises(CheckpointMismatchError) as ei:
    validate_tree(bad, good)
  assert 'mu' in ei.value.path


def test_rng_state_pack_roundtrip():
  rng = np.random.default_rng(11)
  packed = pack_rng_state(rng)
  a = rng.permutation(32)
  fresh = np.random.default_rng(0)
  restore_rng_state(fresh, packed)
  np.testing.assert_array_equal(fresh.permutation(32), a)


# -- SnapshotManager + checkpoint.io chaos ----------------------------------

def test_snapshot_manager_roundtrip_and_cadence(tmp_path, monkeypatch):
  monkeypatch.setenv('GLT_SNAPSHOT_EVERY', '2')
  snap = SnapshotManager(str(tmp_path / 's'))
  assert snap.every == 2
  assert [snap.due() for _ in range(5)] == [True, False, True, False,
                                            True]
  ok = snap.save({'cursor': np.int64(3)},
                 {'epoch': 1, 'next_chunk': 2,
                  'losses': np.arange(2, dtype=np.float32)},
                 train=_tree(2.0))
  assert ok
  fresh = SnapshotManager(str(tmp_path / 's'))   # a new process
  payload = fresh.restore_latest()
  assert int(np.asarray(payload['plane']['cursor'])) == 3
  assert int(np.asarray(payload['progress']['next_chunk'])) == 2
  np.testing.assert_array_equal(payload['train']['w'],
                                np.full((3, 2), 2.0, np.float32))
  saves = recorder.events('snapshot.save')
  restores = recorder.events('snapshot.restore')
  assert saves and saves[0]['ok'] and saves[0]['secs'] >= 0
  assert restores and restores[0]['epoch'] == 1
  assert restores[0]['next_chunk'] == 2
  assert SnapshotManager(str(tmp_path / 'empty')).restore_latest() is None


def test_snapshot_write_faults_keep_previous_durable(tmp_path):
  """`checkpoint.io` ``fail`` (dies before any byte) and ``truncate``
  (partial tmp write, death before the atomic rename) are both
  absorbed — save() returns False, the failure lands in telemetry,
  and the PREVIOUS published snapshot stays the durable latest."""
  snap = SnapshotManager(str(tmp_path / 's'), every=1,
                         max_to_keep=1)
  assert snap.save({'k': np.int64(1)}, {'epoch': 0, 'next_chunk': 1})
  chaos.install('checkpoint.io:fail:1; checkpoint.io:truncate:2')
  assert not snap.save({'k': np.int64(2)}, {'epoch': 0, 'next_chunk': 2})
  assert not snap.save({'k': np.int64(3)}, {'epoch': 0, 'next_chunk': 3})
  assert chaos.active().exhausted()
  chaos.uninstall()
  payload = SnapshotManager(str(tmp_path / 's')).restore_latest()
  assert int(np.asarray(payload['plane']['k'])) == 1, \
      'a failed write must never shadow the last good snapshot'
  evs = recorder.events('snapshot.save')
  assert [e['ok'] for e in evs] == [True, False, False]
  assert all('error' in e for e in evs[1:])


def test_restore_latest_skips_corrupt_newest(tmp_path):
  """A newest snapshot that PUBLISHED but is unreadable (torn disk,
  non-atomic dir rename) is skipped to the older retained step —
  that's what ``max_to_keep > 1`` is for; only when every retained
  snapshot is unreadable does the error propagate."""
  snap = SnapshotManager(str(tmp_path / 's'), every=1)
  assert snap.save({'k': np.int64(1)}, {'epoch': 0, 'next_chunk': 1})
  assert snap.save({'k': np.int64(2)}, {'epoch': 0, 'next_chunk': 2})
  steps = sorted((tmp_path / 's').glob('step_*'))
  assert len(steps) == 2
  (steps[-1] / 'leaves.npz').write_bytes(b'not a zipfile')
  payload = SnapshotManager(str(tmp_path / 's')).restore_latest()
  assert int(np.asarray(payload['plane']['k'])) == 1, \
      'corrupt newest must fall back to the older good snapshot'
  evs = recorder.events('snapshot.restore')
  assert any(e.get('ok') is False and 'error' in e for e in evs)
  (steps[0] / 'leaves.npz').write_bytes(b'also broken')
  with pytest.raises(Exception):
    SnapshotManager(str(tmp_path / 's')).restore_latest()


# -- single-chip fused kill-resume acceptance -------------------------------

def _cluster_dataset(n=90, d=8, classes=3, seed=0, split_ratio=1.0):
  rng = np.random.default_rng(seed)
  labels = (np.arange(n) % classes).astype(np.int32)
  rows, cols = [], []
  for v in range(n):
    for _ in range(6):
      u = (rng.choice(np.nonzero(labels == labels[v])[0])
           if rng.random() < 0.85 else rng.integers(0, n))
      rows.append(v)
      cols.append(int(u))
  feats = np.eye(classes, d, dtype=np.float32)[labels]
  feats += rng.normal(0, 0.3, feats.shape).astype(np.float32)
  return (Dataset()
          .init_graph((np.array(rows), np.array(cols)), layout='COO',
                      num_nodes=n)
          .init_node_features(feats, split_ratio=split_ratio)
          .init_node_labels(labels))


def _setup(ds, batch_size=32, seed=0):
  model = GraphSAGE(hidden_features=16, out_features=3, num_layers=2)
  tx = optax.adam(1e-2)
  loader = NeighborLoader(ds, [4, 3], np.arange(90),
                          batch_size=batch_size)
  state, apply_fn = create_train_state(
      model, jax.random.key(seed), next(iter(loader)), tx)
  return state, apply_fn, tx


def _copy(state):
  return jax.tree_util.tree_map(jnp.copy, state)


def _params_equal(a, b):
  for la, lb in zip(jax.tree_util.tree_leaves(a.params),
                    jax.tree_util.tree_leaves(b.params)):
    np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))


def _fused(ds, apply_fn, tx, **kw):
  kw.setdefault('batch_size', 32)
  kw.setdefault('shuffle', True)
  kw.setdefault('seed', 5)
  kw.setdefault('max_steps_per_program', 1)
  return FusedEpoch(ds, [4, 3], np.arange(90), apply_fn, tx, **kw)


@pytest.mark.slow
@pytest.mark.parametrize('split_ratio', [1.0, 0.5],
                         ids=['resident', 'tiered'])
def test_fused_epoch_kill_resume_byte_identical(tmp_path, monkeypatch,
                                                split_ratio):
  """THE acceptance loop, single-chip: chunked epoch, planned
  preemption at the third chunk, restore in a fresh driver, finish —
  losses, stats and final params byte-identical to an uninterrupted
  seeded twin.  The tiered variant carries the cold-cache rings
  through the snapshot as well."""
  if split_ratio < 1.0:
    monkeypatch.setenv('GLT_FUSED_COLD_CHUNK', '1')
  ds = _cluster_dataset(split_ratio=split_ratio)
  state, apply_fn, tx = _setup(ds)

  ref = _fused(ds, apply_fn, tx)
  ref_state, ref_stats = ref.run(_copy(state))
  # host copies BEFORE epoch 2 donates the state buffers
  ref_params1 = jax.tree_util.tree_map(np.asarray, ref_state.params)
  ref_state2, ref_stats2 = ref.run(ref_state)    # epoch 2 reference

  snap_dir = str(tmp_path / 'plane')
  fused = _fused(ds, apply_fn, tx)
  assert fused.attach_snapshots(SnapshotManager(snap_dir, every=1))
  chaos.install('fused.dispatch:kill:3')         # 3rd chunk arrival
  with pytest.raises(chaos.ChaosKilledError):
    fused.run(_copy(state))
  chaos.uninstall()
  assert recorder.events('snapshot.save'), 'chunk boundaries must save'

  # fresh process stand-in: same constructor args, restore, finish
  resumed = _fused(ds, apply_fn, tx)
  resumed.attach_snapshots(SnapshotManager(snap_dir))
  got = resumed.restore_from_snapshot(state)
  assert got is not None
  assert recorder.events('snapshot.restore')
  state_r, stats_r = resumed.run(got)

  assert stats_r['seeds'] == 90                  # exact unique count
  np.testing.assert_array_equal(np.asarray(stats_r['losses']),
                                np.asarray(ref_stats['losses']))
  assert stats_r['correct'] == ref_stats['correct']
  for la, lb in zip(jax.tree_util.tree_leaves(ref_params1),
                    jax.tree_util.tree_leaves(state_r.params)):
    np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))

  # the NEXT epoch continues exactly where an uninterrupted run would
  state_r2, stats_r2 = resumed.run(state_r)
  np.testing.assert_array_equal(np.asarray(stats_r2['losses']),
                                np.asarray(ref_stats2['losses']))
  _params_equal(ref_state2, state_r2)


def test_fused_epoch_restore_rejects_stale_train_state(tmp_path):
  """A snapshot whose TrainState no longer matches the caller's
  template (a changed model) must raise `CheckpointMismatchError`,
  not restore garbage."""
  ds = _cluster_dataset()
  state, apply_fn, tx = _setup(ds)
  fused = _fused(ds, apply_fn, tx)
  fused.attach_snapshots(SnapshotManager(str(tmp_path / 'p'), every=1))
  fused.run(_copy(state))
  other = GraphSAGE(hidden_features=24, out_features=3, num_layers=2)
  loader = NeighborLoader(ds, [4, 3], np.arange(90), batch_size=32)
  other_state, other_apply = create_train_state(
      other, jax.random.key(0), next(iter(loader)), optax.adam(1e-2))
  fresh = _fused(ds, other_apply, optax.adam(1e-2))
  fresh.attach_snapshots(SnapshotManager(str(tmp_path / 'p')))
  with pytest.raises(CheckpointMismatchError):
    fresh.restore_from_snapshot(other_state)


def test_fused_epoch_resume_rejects_changed_chunk_size(tmp_path,
                                                       monkeypatch):
  """Resuming under a different chunk size would mis-stitch the key
  schedule — the typed mismatch error must name the knob."""
  ds = _cluster_dataset()
  state, apply_fn, tx = _setup(ds)
  fused = _fused(ds, apply_fn, tx)
  fused.attach_snapshots(SnapshotManager(str(tmp_path / 'p'), every=1))
  chaos.install('fused.dispatch:kill:3')
  with pytest.raises(chaos.ChaosKilledError):
    fused.run(_copy(state))
  chaos.uninstall()
  resumed = _fused(ds, apply_fn, tx, max_steps_per_program=2)
  resumed.attach_snapshots(SnapshotManager(str(tmp_path / 'p')))
  resumed.restore_from_snapshot(state)
  with pytest.raises(CheckpointMismatchError, match='chunk'):
    resumed.run(_copy(state))


# -- mesh loader kill-resume acceptance -------------------------------------

MESH_N = 64
MESH_P = 4


def _mesh_dataset(split_ratio=0.3):
  rows = np.concatenate([np.arange(MESH_N), np.arange(MESH_N)])
  cols = np.concatenate([(np.arange(MESH_N) + 1) % MESH_N,
                         (np.arange(MESH_N) + 2) % MESH_N])
  feats = (np.arange(MESH_N, dtype=np.float32)[:, None]
           * np.ones((1, 4), np.float32))        # feat[v] == v
  labels = (np.arange(MESH_N) % 5).astype(np.int32)
  node_pb = (np.arange(MESH_N) % MESH_P).astype(np.int32)
  return DistDataset.from_full_graph(
      MESH_P, rows, cols, node_feat=feats, node_label=labels,
      num_nodes=MESH_N, node_pb=node_pb, split_ratio=split_ratio)


def _mesh_loader(ds, mesh, seed=9, **kw):
  return DistNeighborLoader(ds, [2, 2], np.arange(MESH_N),
                            batch_size=4, shuffle=True, mesh=mesh,
                            seed=seed, **kw)


def _batch_bytes(b):
  return (np.asarray(b.node).tobytes(), np.asarray(b.x).tobytes(),
          np.asarray(b.y).tobytes(),
          np.asarray(b.edge_index).tobytes())


def test_mesh_loader_kill_resume_byte_identical(tmp_path):
  """THE acceptance loop, mesh loader variant: consume part of a
  tiered epoch (cold cache + dispatch-ahead overlay live), snapshot
  through the DURABLE store, lose the process (a fresh loader), and
  finish — the union of pre-kill and resumed batches is byte-identical
  to an uninterrupted seeded twin, and the following epoch continues
  exactly where the uninterrupted run would."""
  ds = _mesh_dataset()
  mesh = make_mesh(MESH_P)
  ref = _mesh_loader(ds, mesh)
  epoch1 = [_batch_bytes(b) for b in ref]
  epoch2 = [_batch_bytes(b) for b in ref]
  assert len(epoch1) >= 3

  loader = _mesh_loader(ds, mesh)
  it = iter(loader)
  got = [_batch_bytes(next(it)) for _ in range(2)]
  snap = SnapshotManager(str(tmp_path / 'plane'), every=1)
  assert snap.save(loader.state_dict(),
                   {'epoch': 0, 'next_chunk': loader._consumed})
  # the kill: this loader is never touched again
  payload = SnapshotManager(str(tmp_path / 'plane')).restore_latest()

  resumed = _mesh_loader(ds, mesh)
  resumed.load_state_dict(payload['plane'])
  rest = [_batch_bytes(b) for b in resumed.resume_epoch()]
  assert len(got) + len(rest) == len(epoch1), 'exact batch count'
  assert got + rest == epoch1, 'batches must be byte-identical'
  # next epoch: same stream as the uninterrupted twin's epoch 2
  assert [_batch_bytes(b) for b in resumed] == epoch2


def test_mesh_loader_cold_service_fault_then_resume(tmp_path):
  """`feature.cold_service` fail mid-epoch: the host cold tier dies,
  the epoch surfaces `InjectedFault` — and the snapshot taken at the
  last delivered batch turns it into a finished, byte-identical epoch
  in a fresh loader."""
  ds = _mesh_dataset()
  mesh = make_mesh(MESH_P)
  ref = _mesh_loader(ds, mesh)
  epoch1 = [_batch_bytes(b) for b in ref]

  loader = _mesh_loader(ds, mesh)
  snap = SnapshotManager(str(tmp_path / 'plane'), every=1)
  it = iter(loader)
  got = []
  # the cold service dies on the arrival after the second batch
  chaos.install('feature.cold_service:fail:3:op=dist')
  with pytest.raises(chaos.InjectedFault):
    while True:
      b = next(it)
      got.append(_batch_bytes(b))
      snap.save(loader.state_dict(), {'epoch': 0,
                                      'next_chunk': loader._consumed})
  chaos.uninstall()
  assert got, 'some batches must land before the fault'
  assert recorder.events('fault.injected')

  payload = SnapshotManager(str(tmp_path / 'plane')).restore_latest()
  resumed = _mesh_loader(ds, mesh)
  resumed.load_state_dict(payload['plane'])
  rest = [_batch_bytes(b) for b in resumed.resume_epoch()]
  assert got + rest == epoch1


def test_mesh_loader_snapshot_refuses_prefetch(tmp_path):
  ds = _mesh_dataset()
  loader = _mesh_loader(ds, make_mesh(MESH_P), prefetch=2)
  it = iter(loader)
  next(it)
  with pytest.raises(ValueError, match='prefetch'):
    loader.state_dict()
  loader.close()


def test_adaptive_slack_ladder_state_roundtrip():
  """The AdaptiveSlack rung/pin survive a snapshot: a fresh loader
  restored from state resumes at the tuned rung instead of silently
  resetting to the 2.0 default (ISSUE 6 tentpole: resumable is a
  property of EVERY stateful component)."""
  ds = _mesh_dataset(split_ratio=1.0)
  mesh = make_mesh(MESH_P)
  loader = _mesh_loader(ds, mesh, exchange_slack='adaptive')
  ctl = loader._adaptive
  assert ctl is not None
  for b in loader:                 # epoch 1 telemetry
    pass
  for b in loader:                 # iter() retunes: drop-free tightens
    break
  loader.close()
  assert not ctl._pinned
  tuned = ctl._idx
  assert ctl.sampler.exchange_slack == ctl.slack

  state = loader.state_dict()
  resumed = _mesh_loader(ds, mesh, exchange_slack='adaptive')
  assert resumed._adaptive._idx != tuned or tuned == 4
  resumed.load_state_dict(state)
  assert resumed._adaptive._idx == tuned
  assert resumed.sampler.exchange_slack == ctl.slack
  assert resumed._adaptive._pinned == ctl._pinned


# -- the mesh stall watchdog ------------------------------------------------

def test_run_with_deadline_passthrough_and_errors():
  assert run_with_deadline(lambda x: x + 1, 41, deadline=0) == 42
  assert run_with_deadline(lambda: 'ok', deadline=5.0) == 'ok'
  with pytest.raises(ZeroDivisionError):
    run_with_deadline(lambda: 1 / 0, deadline=5.0)


def test_run_with_deadline_converts_hang_to_mesh_stall():
  t0 = time.monotonic()
  with pytest.raises(MeshStallError) as ei:
    run_with_deadline(time.sleep, 30.0, deadline=0.3,
                      scope='fused.dispatch')
  assert time.monotonic() - t0 < 5.0, 'must not wait out the hang'
  assert ei.value.deadline == 0.3
  assert ei.value.scope == 'fused.dispatch'
  assert ei.value.healthy == [0], 'single-process: trivially healthy'
  evs = recorder.events('mesh.stall')
  assert evs and evs[0]['deadline_secs'] == 0.3


def test_dispatch_delay_fault_raises_stall_within_deadline(monkeypatch):
  """The acceptance wording verbatim: a hung dispatch under a
  ``fused.dispatch`` delay fault raises `MeshStallError` within the
  configured ``GLT_DISPATCH_DEADLINE`` instead of hanging the epoch."""
  monkeypatch.setenv('GLT_DISPATCH_DEADLINE', '0.3')
  chaos.install('fused.dispatch:delay:1:secs=30')

  def dispatch():
    chaos.fused_dispatch_check(chunk=0, epoch=1)
    return 'finished'

  t0 = time.monotonic()
  with pytest.raises(MeshStallError):
    run_with_deadline(dispatch, scope='fused.dispatch')
  assert time.monotonic() - t0 < 5.0
  chaos.uninstall()
  monkeypatch.delenv('GLT_DISPATCH_DEADLINE')
  assert run_with_deadline(dispatch, scope='fused.dispatch') == \
      'finished', 'no deadline: direct call, zero overhead'


def test_cold_service_fault_single_chip():
  ds = _cluster_dataset(split_ratio=0.5)
  feat = ds.node_features
  chaos.install('feature.cold_service:fail:1:op=feature')
  with pytest.raises(chaos.InjectedFault):
    feat[np.arange(60)]
  chaos.uninstall()
  out = np.asarray(feat[np.arange(60)])          # service healthy again
  assert out.shape[0] == 60


# -- report CLI resilience counters (satellite 4) ---------------------------

def test_report_resilience_table():
  from graphlearn_tpu.telemetry.report import (format_resilience_table,
                                               resilience_counts)
  events = [
      {'kind': 'rpc.retry', 'op': 'fetch'},
      {'kind': 'rpc.retry', 'op': 'fetch'},
      {'kind': 'fault.injected', 'site': 'fused.dispatch'},
      {'kind': 'snapshot.save', 'ok': True},
      {'kind': 'snapshot.save', 'ok': False},
      {'kind': 'snapshot.restore', 'dir': '/tmp/s'},
      {'kind': 'mesh.stall', 'scope': 'fused.dispatch'},
      {'kind': 'span.begin', 'name': 'batch'},   # not a resilience kind
  ]
  rows = {r[0]: (r[1], r[2]) for r in resilience_counts(events)}
  assert rows['rpc.retry'] == ('2', 'fetch=2')
  assert rows['snapshot.save'][0] == '2'
  assert 'False=1' in rows['snapshot.save'][1]
  assert rows['mesh.stall'] == ('1', 'fused.dispatch=1')
  assert 'span.begin' not in rows
  table = format_resilience_table(events)
  assert 'snapshot.restore' in table and 'count' in table
  assert format_resilience_table([]) == ''


# -- host runtime (mp producers): DistLoader snapshot/resume ----------------

HOST_N = 48
HOST_BATCH = 8


def _host_ring(n=HOST_N, d=4):
  from graphlearn_tpu.distributed import HostDataset
  rows = np.repeat(np.arange(n), 2)
  cols = np.stack([(np.arange(n) + 1) % n,
                   (np.arange(n) + 2) % n], 1).reshape(-1)
  feats = np.tile(np.arange(n, dtype=np.float32)[:, None], (1, d))
  return HostDataset.from_coo(rows, cols, n, node_features=feats,
                              node_labels=np.arange(n) % 4)


def _host_mp_loader(seed=3):
  from graphlearn_tpu.distributed import (DistNeighborLoader,
                                          MpDistSamplingWorkerOptions)
  return DistNeighborLoader(
      _host_ring(), [2], np.arange(HOST_N), batch_size=HOST_BATCH,
      shuffle=True, worker_options=MpDistSamplingWorkerOptions(
          num_workers=2, mp_start_method='spawn'),
      to_device=False, seed=seed)


def _host_key(b):
  s = np.asarray(b.batch)
  return (tuple(np.sort(s[s >= 0]).tolist()),
          np.asarray(b.node).tobytes(), np.asarray(b.x).tobytes())


@pytest.mark.slow
@pytest.mark.skipif(
    not __import__('graphlearn_tpu').native.available(),
    reason='native lib unavailable')
def test_host_mp_loader_snapshot_resume_exact(tmp_path):
  """Host mp mode: producer (epoch, seq) positions + delivered-seq set
  snapshot and resume — the resumed epoch re-produces the SAME epoch,
  discards the already-delivered prefix, and yields exactly the
  remaining batches, byte-identical (batch content is a function of
  (epoch, seq))."""
  n_batches = HOST_N // HOST_BATCH
  ref = _host_mp_loader()
  try:
    clean = sorted(_host_key(b) for b in ref)
    clean2 = sorted(_host_key(b) for b in ref)   # epoch 2 reference
  finally:
    ref.shutdown()

  loader = _host_mp_loader()
  try:
    it = iter(loader)
    got = [_host_key(next(it)) for _ in range(2)]
    snap = SnapshotManager(str(tmp_path / 'plane'), every=1)
    assert snap.save(loader.state_dict(), {'epoch': 0, 'next_chunk': 2})
  finally:
    loader.shutdown()              # the preemption

  payload = SnapshotManager(str(tmp_path / 'plane')).restore_latest()
  resumed = _host_mp_loader()
  try:
    resumed.load_state_dict(payload['plane'])
    rest = [_host_key(b) for b in resumed.resume_epoch()]
    assert len(got) + len(rest) == n_batches
    assert sorted(got + rest) == clean, \
        'resumed epoch must be byte-identical to the clean epoch'
    assert resumed.replayed_discarded >= len(got), \
        're-produced prefix must be discarded, not re-delivered'
    # the NEXT epoch advances the shuffle stream exactly as the
    # uninterrupted twin's second epoch
    nxt = sorted(_host_key(b) for b in resumed)
    assert nxt == clean2
  finally:
    resumed.shutdown()


# -- mesh fused drivers: stall watchdog + degraded rollback (slow) ----------

FN = 256
FCLASSES = 4


def _fused_mesh_dataset(split_ratio=0.3):
  rng = np.random.default_rng(0)
  labels = (np.arange(FN) % FCLASSES).astype(np.int32)
  rows, cols = [], []
  for v in range(FN):
    for _ in range(5):
      u = (int(rng.choice(np.nonzero(labels == labels[v])[0]))
           if rng.random() < 0.8 else int(rng.integers(0, FN)))
      rows.append(v)
      cols.append(u)
  feats = np.eye(FCLASSES, 8, dtype=np.float32)[labels]
  feats += rng.normal(0, 0.3, feats.shape).astype(np.float32)
  return DistDataset.from_full_graph(
      MESH_P, np.asarray(rows), np.asarray(cols), node_feat=feats,
      node_label=labels, num_nodes=FN, split_ratio=split_ratio)


def _copy2(host_tree):
  return jax.tree_util.tree_map(np.copy, host_tree)


def _fused_mesh_state(tx, bs=16):
  rng = np.random.default_rng(0)
  ds = (Dataset()
        .init_graph((np.arange(32), (np.arange(32) + 1) % 32),
                    layout='COO', num_nodes=32)
        .init_node_features(rng.random((32, 8)).astype(np.float32))
        .init_node_labels((np.arange(32) % FCLASSES).astype(np.int32)))
  loader = NeighborLoader(ds, [3, 2], np.arange(32), batch_size=bs)
  model = GraphSAGE(hidden_features=16, out_features=FCLASSES,
                    num_layers=2)
  return create_train_state(model, jax.random.key(0),
                            next(iter(loader)), tx)


@pytest.mark.slow
def test_mesh_tiered_stall_watchdog_and_degraded_resume(tmp_path,
                                                        monkeypatch):
  """The mesh acceptance loop: a tiered fused epoch whose chunk
  dispatch hangs under a ``fused.dispatch`` delay fault (1) raises
  `MeshStallError` within ``GLT_DISPATCH_DEADLINE`` instead of
  wedging, and (2) with ``GLT_DEGRADED_OK=1``, rolls back to the last
  chunk-boundary snapshot and finishes the epoch byte-identically to
  an unfaulted seeded twin."""
  from graphlearn_tpu.parallel import FusedDistEpoch, replicate
  monkeypatch.setenv('GLT_FUSED_COLD_CHUNK', '1')
  ds = _fused_mesh_dataset()
  mesh = make_mesh(MESH_P)
  tx = optax.adam(1e-2)
  state, apply_fn = _fused_mesh_state(tx)
  # replicate() may ALIAS the source buffer for the same-device shard,
  # and the epoch donates it — replicate each run from host copies so
  # one run's donation cannot delete another's input
  host_state = jax.tree_util.tree_map(np.asarray, state)

  def make():
    return FusedDistEpoch(ds, [3, 2], np.arange(FN), apply_fn, tx,
                          batch_size=16, mesh=mesh, shuffle=True,
                          seed=0)

  ref = make()
  sref, ref1 = ref.run(replicate(_copy2(host_state), mesh))
  sref, ref2 = ref.run(sref)
  ref1_losses = np.asarray(ref1.losses)
  ref2_losses = np.asarray(ref2.losses)
  ref2_params = jax.tree_util.tree_map(np.asarray, sref.params)

  # arm 1: epoch 1 fault-free (warms this driver's compiles), then a
  # hung chunk-0 collect in epoch 2 -> typed MeshStallError, fast
  snap_dir = str(tmp_path / 'plane')
  fused = make()
  fused.attach_snapshots(SnapshotManager(snap_dir, every=1))
  s, st1 = fused.run(replicate(_copy2(host_state), mesh))
  np.testing.assert_array_equal(np.asarray(st1.losses), ref1_losses)
  monkeypatch.setenv('GLT_DISPATCH_DEADLINE', '10')
  monkeypatch.delenv('GLT_DEGRADED_OK', raising=False)
  chaos.install('fused.dispatch:delay:1:secs=90:op=collect')
  t0 = time.monotonic()
  with pytest.raises(MeshStallError) as ei:
    fused.run(s)
  assert time.monotonic() - t0 < 60, 'must not wait out the hang'
  assert ei.value.healthy == [0]
  assert recorder.events('mesh.stall')
  chaos.uninstall()

  # arm 2: fresh driver (fresh process stand-in), restore the epoch-2
  # snapshot, degraded mode on; the chunk-1 collect hangs once -> the
  # driver rolls back to its own chunk-boundary snapshot and finishes
  monkeypatch.setenv('GLT_DEGRADED_OK', '1')
  fused2 = make()
  fused2.run(replicate(_copy2(host_state), mesh))   # warm compiles only
  fused2.attach_snapshots(SnapshotManager(snap_dir))
  restored = fused2.restore_from_snapshot(host_state)
  assert restored is not None
  chaos.install('fused.dispatch:delay:2:secs=90:op=collect')
  s2, st2 = fused2.run(restored)
  assert chaos.active().exhausted(), 'the planned stall must fire'
  chaos.uninstall()
  np.testing.assert_array_equal(np.asarray(st2.losses), ref2_losses)
  for la, lb in zip(jax.tree_util.tree_leaves(ref2_params),
                    jax.tree_util.tree_leaves(s2.params)):
    np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))
