"""Time-series history tests (ISSUE 16 leg 1): counter->rate
conversion, bounded rings, windowed queries, the ``/timeseries``
route, the postmortem attachment (≥60 s of rings, rendered), and the
lock-freedom pin — the cadence sweep reads SLO/admission gauges
through the scrape memo without taking the tracker lock."""
import json
import threading
import time
import urllib.request

import pytest

from graphlearn_tpu.telemetry import (LiveRegistry, Metrics, OpsServer,
                                      SloTracker)
from graphlearn_tpu.telemetry import timeseries
from graphlearn_tpu.telemetry.report import (format_timeseries,
                                             render_postmortem)
from graphlearn_tpu.telemetry.timeseries import TimeSeriesStore


@pytest.fixture(autouse=True)
def _clean():
  yield
  timeseries.stop_global()


class FakeClock:
  def __init__(self, t0=1000.0):
    self.t = t0

  def __call__(self):
    return self.t

  def advance(self, dt):
    self.t += dt


def _reg():
  return LiveRegistry(store=Metrics(), strict=True)


def test_counter_becomes_rate_and_gauge_samples_raw():
  reg = _reg()
  clk = FakeClock()
  store = TimeSeriesStore(registry=reg, cadence_ms=1000,
                          retention_s=60, clock=clk)
  c = reg.counter('serving.requests_total')
  depth = [3.0]
  reg.gauge('serving.queue_depth', fn=lambda: depth[0])
  store.sample_once()               # anchors the counter at 0.0
  c.inc(10)
  depth[0] = 7.0
  clk.advance(2.0)
  store.sample_once()
  q = store.query()
  assert q['schema'] == 'glt.timeseries.v1'
  rate = q['series']['serving.requests_total:rate']
  assert rate['kind'] == 'rate'
  assert rate['points'][-1][1] == pytest.approx(5.0)   # 10 in 2 s
  g = q['series']['serving.queue_depth']
  assert g['kind'] == 'gauge'
  assert [v for _, v in g['points']] == [3.0, 7.0]


def test_counter_rewind_clamps_to_zero_rate():
  reg = _reg()
  clk = FakeClock()
  store = TimeSeriesStore(registry=reg, cadence_ms=1000,
                          retention_s=60, clock=clk)
  c = reg.counter('serving.requests_total')
  c.inc(100)
  store.sample_once()
  # a rollback rewinds the backing store (fused snapshot restore)
  reg._backing().inc('serving.requests_total', -50.0)
  clk.advance(1.0)
  store.sample_once()
  pts = store.query()['series']['serving.requests_total:rate']['points']
  assert pts[-1][1] == 0.0          # clamped, not negative


def test_histogram_summarizes_as_observation_rate():
  reg = _reg()
  clk = FakeClock()
  store = TimeSeriesStore(registry=reg, cadence_ms=1000,
                          retention_s=60, clock=clk)
  h = reg.histogram('serving.request_latency')
  store.sample_once()
  for _ in range(6):
    h.observe(0.004)
  clk.advance(3.0)
  store.sample_once()
  key = 'serving.request_latency.hist:rate'
  pts = store.query()['series'][key]['points']
  assert pts[-1][1] == pytest.approx(2.0)


def test_rings_bounded_by_retention_and_window_query():
  reg = _reg()
  clk = FakeClock()
  store = TimeSeriesStore(registry=reg, cadence_ms=1000,
                          retention_s=10, clock=clk)
  reg.gauge('serving.queue_depth', fn=lambda: 1.0)
  for _ in range(50):               # 50 s of 1 Hz samples, 10 s ring
    store.sample_once()
    clk.advance(1.0)
  q = store.query()
  pts = q['series']['serving.queue_depth']['points']
  assert len(pts) <= store._ring_len
  assert store.span_s() <= 10.0 + 1.0
  # window narrows further; names filters by exact key/prefix
  qw = store.query(names=['serving.queue_depth'], window_s=3.0)
  assert 0 < len(qw['series']['serving.queue_depth']['points']) <= 4
  assert store.query(names=['nomatch'])['series'] == {}


def test_timeseries_route_serves_global_store():
  reg = _reg()
  reg.counter('serving.requests_total').inc(5)
  store = timeseries.ensure_global(registry=reg)
  store.sample_once()
  store.sample_once()
  srv = OpsServer(registry=reg, port=0)
  try:
    with urllib.request.urlopen(
        f'{srv.url}/timeseries?names=serving.requests_total&window_s=60',
        timeout=10) as r:
      body = json.loads(r.read())
    assert body['schema'] == 'glt.timeseries.v1'
    assert 'serving.requests_total:rate' in body['series']
  finally:
    srv.close()


def test_timeseries_route_404_without_store():
  srv = OpsServer(registry=_reg(), port=0)
  try:
    with pytest.raises(urllib.error.HTTPError) as ei:
      urllib.request.urlopen(f'{srv.url}/timeseries', timeout=10)
    assert ei.value.code == 404
  finally:
    srv.close()


def test_postmortem_bundle_carries_60s_of_rings_and_renders(
    monkeypatch, tmp_path):
  """Acceptance: a killed process's bundle holds ≥60 s of burn-rate /
  queue-depth / ingest-lag history and ``report --postmortem``
  renders it."""
  from graphlearn_tpu.telemetry import postmortem
  from graphlearn_tpu.telemetry.live import live as global_live
  monkeypatch.setenv(postmortem.POSTMORTEM_DIR_ENV, str(tmp_path))
  postmortem.reset()
  clk = FakeClock()
  depth_fn = lambda: 4.0            # noqa: E731
  burn_fn = lambda: 1.5             # noqa: E731
  lag_fn = lambda: 12.0             # noqa: E731
  global_live.gauge('serving.queue_depth', fn=depth_fn)
  global_live.gauge('serving.slo.burn_rate',
                    labels={'window': '60s'}, fn=burn_fn)
  global_live.gauge('ingest.lag_events', fn=lag_fn)
  store = TimeSeriesStore(registry=global_live, cadence_ms=1000,
                          retention_s=300, clock=clk)
  monkeypatch.setattr(timeseries, '_global', store)
  try:
    for _ in range(90):             # 90 s of fake-clock history
      store.sample_once()
      clk.advance(1.0)
    path = postmortem.dump('test.reason')
    assert path
    bundle = postmortem.load_bundle(path)
    series = bundle['timeseries']['series']
    for key in ('serving.queue_depth',
                'serving.slo.burn_rate{window=60s}',
                'ingest.lag_events'):
      pts = series[key]['points']
      assert pts[-1][0] - pts[0][0] >= 60.0, key
    text = render_postmortem(bundle)
    assert '# time-series rings' in text
    assert 'serving.queue_depth' in text and 'burn_rate' in text
    assert 'ingest.lag_events' in text
  finally:
    store.close()
    monkeypatch.setattr(timeseries, '_global', None)
    for name, fn in (('serving.queue_depth', depth_fn),
                     ('ingest.lag_events', lag_fn)):
      global_live.unregister_gauge(name, fn=fn)
    global_live.unregister_gauge('serving.slo.burn_rate',
                                 labels={'window': '60s'}, fn=burn_fn)
    postmortem.reset()


def test_format_timeseries_sparkline():
  block = {'cadence_ms': 1000, 'retention_s': 60, 'series': {
      'serving.queue_depth': {
          'kind': 'gauge',
          'points': [[float(i), float(i % 7)] for i in range(30)]}}}
  text = format_timeseries(block)
  assert 'serving.queue_depth' in text and 'span=29s' in text
  assert '|' in text                # the sparkline row


class _CountingLock:
  """Wraps a Lock, counting acquisitions — the probe for the
  sweep-must-not-take-the-tracker-lock pin."""

  def __init__(self, inner):
    self._inner = inner
    self.acquisitions = 0

  def __enter__(self):
    self.acquisitions += 1
    return self._inner.__enter__()

  def __exit__(self, *exc):
    return self._inner.__exit__(*exc)

  def acquire(self, *a, **kw):
    self.acquisitions += 1
    return self._inner.acquire(*a, **kw)

  def release(self):
    return self._inner.release()


def test_sweep_reads_slo_through_memo_without_tracker_lock():
  """The lock-freedom pin (ISSUE 16 satellite): once the scrape memo
  is warm, a cadence sweep evaluating every SLO gauge takes the
  tracker lock ZERO times — `SloTracker._cached_stats` reads the
  memo dict lock-free, so the sweep can never serialize observe()
  behind a full-window copy+sort."""
  reg = _reg()
  clk = FakeClock()
  tr = SloTracker(p99_target_ms=10.0, windows=(60.0, 300.0),
                  registry=reg, clock=clk)
  store = TimeSeriesStore(registry=reg, cadence_ms=1000,
                          retention_s=60, clock=clk)
  try:
    for _ in range(20):
      tr.observe(5.0)
    store.sample_once()             # warms the memo for every window
    counting = _CountingLock(tr._lock)
    tr._lock = counting
    for _ in range(10):             # memo TTL never expires: clock
      store.sample_once()           # is frozen between sweeps
    assert counting.acquisitions == 0, (
        'cadence sweep acquired the SloTracker lock — the scrape '
        'memo is being bypassed')
  finally:
    tr.close()
    store.close()


@pytest.mark.slow
def test_concurrent_observe_and_sample_consistent():
  """observe() writers hammer the tracker while the sweep samples at
  full speed: no exception, every query parses, and the final window
  count matches what was observed (no lost updates)."""
  reg = _reg()
  tr = SloTracker(p99_target_ms=10.0, windows=(60.0,), registry=reg)
  store = TimeSeriesStore(registry=reg, cadence_ms=10, retention_s=60)
  stop = threading.Event()
  observed = [0, 0, 0, 0]

  def writer(i):
    while not stop.is_set():
      tr.observe(1.0)
      observed[i] += 1

  threads = [threading.Thread(target=writer, args=(i,), daemon=True)
             for i in range(4)]
  for t in threads:
    t.start()
  try:
    deadline = time.monotonic() + 10.0
    sweeps = 0
    while sweeps < 120 and time.monotonic() < deadline:
      store.sample_once()
      json.dumps(store.query())     # always JSON-able mid-traffic
      sweeps += 1
  finally:
    stop.set()
    for t in threads:
      t.join(5)
  assert sweeps >= 30
  st = tr.window_stats(60.0)
  assert st['count'] == min(sum(observed), 20000) or \
      st['count'] > 0               # deque cap may clip the tail
  tr.close()
  store.close()


def test_admission_depth_is_lock_free_len():
  """`AdmissionController.depth` must not touch the queue lock — it
  is sampled by the cadence loop."""
  import inspect
  from graphlearn_tpu.serving.admission import AdmissionController
  src = inspect.getsource(AdmissionController.depth)
  assert 'with self._lock' not in src
  q = AdmissionController(max_queue=8)
  assert q.depth() == 0
