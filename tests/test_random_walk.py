"""Random walks: single-chip op + distributed walker.

Beyond-parity coverage (the reference only reserves
``SamplingType.RANDOM_WALK``, `sampler/base.py:325-331`; the BASELINE
north star names random-walk sampling).  Every consecutive walk pair
must be a real edge; dead ends truncate with INVALID_ID; restart jumps
return to the start node; the mesh walker agrees with the same
invariants across partitions.
"""
import numpy as np
import pytest

jax = pytest.importorskip('jax')

from graphlearn_tpu.ops import random_walk, walk_edges
from graphlearn_tpu.parallel import DistDataset, DistRandomWalker, make_mesh
from graphlearn_tpu.utils.topo import coo_to_csr

N = 64


def _ring_csr():
  rows = np.concatenate([np.arange(N), np.arange(N)])
  cols = np.concatenate([(np.arange(N) + 1) % N, (np.arange(N) + 2) % N])
  indptr, indices, _ = coo_to_csr(rows, cols, N)
  return indptr, indices, rows, cols


def test_walks_follow_real_edges():
  indptr, indices, rows, cols = _ring_csr()
  edge_set = set(zip(rows.tolist(), cols.tolist()))
  starts = np.arange(32, dtype=np.int32)
  walks = np.asarray(random_walk(np.asarray(indptr), np.asarray(indices),
                                 starts, jax.random.key(0),
                                 walk_length=8))
  assert walks.shape == (32, 9)
  np.testing.assert_array_equal(walks[:, 0], starts)
  for w in walks:
    for a, b in zip(w[:-1], w[1:]):
      assert (int(a), int(b)) in edge_set


def test_dead_ends_truncate_with_invalid():
  # node 2 has no out-edges: walks reaching it stop
  rows = np.array([0, 1])
  cols = np.array([1, 2])
  indptr, indices, _ = coo_to_csr(rows, cols, 3)
  walks = np.asarray(random_walk(np.asarray(indptr), np.asarray(indices),
                                 np.array([0, 2], np.int32),
                                 jax.random.key(1), walk_length=4))
  np.testing.assert_array_equal(walks[0], [0, 1, 2, -1, -1])
  np.testing.assert_array_equal(walks[1], [2, -1, -1, -1, -1])


def test_restart_prob_returns_to_start():
  indptr, indices, _, _ = _ring_csr()
  starts = np.zeros(256, np.int32)
  walks = np.asarray(random_walk(np.asarray(indptr), np.asarray(indices),
                                 starts, jax.random.key(2),
                                 walk_length=6, restart_prob=0.5))
  # with p=0.5 over 256x6 steps, restarts to node 0 are certain
  assert (walks[:, 1:] == 0).any()


def test_walk_edges_window():
  walks = np.array([[0, 1, 2, -1]], np.int32)
  src, dst = (np.asarray(v) for v in walk_edges(walks, window=2))
  pairs = {(int(a), int(b)) for a, b in zip(src, dst) if a >= 0 and b >= 0}
  assert pairs == {(0, 1), (1, 2), (0, 2)}


def test_node2vec_bias_matches_bruteforce_distribution():
  """Empirical transition frequencies from a fixed (prev, cur) state
  must match the node2vec weights (1/p back, 1 to common neighbors,
  1/q otherwise) within sampling noise."""
  from graphlearn_tpu.ops import node2vec_walk
  # cur = 1 with neighbors {0 (=prev), 2 (also neighbor of 0), 3};
  # prev = 0 with neighbors {1, 2}
  rows = np.array([0, 0, 1, 1, 1, 2, 3])
  cols = np.array([1, 2, 0, 2, 3, 1, 1])
  indptr, indices, _ = coo_to_csr(rows, cols, 4)
  p, q = 4.0, 0.25
  # force the walk through (0 -> 1): start at 0; 0's first uniform
  # step may go to 2, so filter walks whose second node is 1
  m = 40000
  walks = np.asarray(node2vec_walk(
      np.asarray(indptr), np.asarray(indices),
      np.zeros(m, np.int32), jax.random.key(5), walk_length=2,
      p=p, q=q, max_degree=4))
  sel = walks[:, 1] == 1
  third = walks[sel, 2]
  cnt = {v: int((third == v).sum()) for v in (0, 2, 3)}
  total = sum(cnt.values())
  # weights: back to 0 = 1/p; 2 is a neighbor of 0 = 1; 3 = 1/q
  wts = np.array([1 / p, 1.0, 1 / q])
  expect = wts / wts.sum()
  got = np.array([cnt[0], cnt[2], cnt[3]]) / total
  np.testing.assert_allclose(got, expect, atol=0.02)


def test_node2vec_edges_are_real():
  from graphlearn_tpu.ops import node2vec_walk
  indptr, indices, rows, cols = _ring_csr()
  edge_set = set(zip(rows.tolist(), cols.tolist()))
  walks = np.asarray(node2vec_walk(
      np.asarray(indptr), np.asarray(indices),
      np.arange(32, dtype=np.int32), jax.random.key(6),
      walk_length=6, p=2.0, q=0.5, max_degree=2))
  for w in walks:
    for a, b in zip(w[:-1], w[1:]):
      assert (int(a), int(b)) in edge_set


def test_dist_walker_matches_edge_membership():
  indptr, indices, rows, cols = _ring_csr()
  edge_set = set(zip(rows.tolist(), cols.tolist()))
  ds = DistDataset.from_full_graph(8, rows, cols, num_nodes=N)
  walker = DistRandomWalker(ds, walk_length=6, mesh=make_mesh(8), seed=0)
  starts = ds.old2new[np.arange(32)].reshape(8, 4)
  walks = np.asarray(walker.walk(starts))
  assert walks.shape == (8, 4, 7)
  new2old = ds.new2old
  for p in range(8):
    for w in walks[p]:
      assert w[0] >= 0
      for a, b in zip(w[:-1], w[1:]):
        if b < 0:
          assert (w[np.nonzero(w == b)[0][0]:] < 0).all()
          break
        assert (int(new2old[a]), int(new2old[b])) in edge_set
  # on a ring (deg 2 everywhere) with the default slack, no walk ever
  # truncates
  assert (walks >= 0).all()
  st = walker.exchange_stats(tick_metrics=False)
  assert st['dist.frontier.offered'] > 0
  assert st['dist.frontier.dropped'] == 0
