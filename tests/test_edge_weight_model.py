"""GNS importance weights at the MODEL (ISSUE 13 satellite, ROADMAP
item 5a): `Batch.metadata['edge_weight']` (PR 10's per-edge 1/q
correction) threads through the SAGE aggregation so cache-biased
sampling is unbiased end-to-end — pinned by a weight-of-ones identity,
a monte-carlo expectation check THROUGH SAGEConv, and a small
convergence-parity run (biased+weighted trains to the uniform
optimum; biased-unweighted provably cannot).
"""
import jax
import jax.numpy as jnp
import numpy as np
import optax

from graphlearn_tpu.loader.transform import Batch
from graphlearn_tpu.models.basic_gnn import GraphSAGE
from graphlearn_tpu.models.conv import SAGEConv, segment_mean
from graphlearn_tpu.models.train import (create_train_state,
                                         make_supervised_step)

# one target row aggregating a pool of neighbors — the estimator shape
# ops/gns.py proves unbiased: q(v) boosts "cached" neighbors, each
# edge carries w = p/q = (1/d)/q_v, and sum(w*f)/k recovers the
# uniform neighbor mean in expectation
D_NEIGH = 16
BOOST = 8.0


def _pool(seed=0):
  rng = np.random.default_rng(seed)
  feats = rng.random(D_NEIGH).astype(np.float32)
  hot = feats > np.median(feats)        # bias correlated with VALUE:
  # the worst case — an uncorrected boost shifts the estimate
  q = 1.0 + BOOST * hot
  q = q / q.sum()
  w = (1.0 / D_NEIGH) / q               # p/q importance weights
  return feats, q, w


def test_segment_mean_weight_of_ones_is_bit_identical():
  rng = np.random.default_rng(1)
  data = jnp.asarray(rng.random((10, 3), ).astype(np.float32))
  seg = jnp.asarray(rng.integers(0, 4, 10))
  mask = jnp.asarray(rng.random(10) > 0.3)
  base = segment_mean(data, seg, 4, mask)
  ones = segment_mean(data, seg, 4, mask, weights=jnp.ones(10))
  np.testing.assert_array_equal(np.asarray(base), np.asarray(ones))


def test_sage_conv_weighted_mean_unbiased_monte_carlo():
  """E[SAGEConv(biased sample, 1/q weights)] == SAGEConv(full
  neighborhood): the model-level twin of the ops/gns kernel pin.
  SAGEConv is linear in the aggregation, so the expectation passes
  through the Dense layers exactly."""
  feats, q, w = _pool()
  n = 1 + D_NEIGH                       # node 0 = target, rest = pool
  x = np.zeros((n, 2), np.float32)
  x[1:, 0] = feats
  conv = SAGEConv(out_features=2, aggr='mean')
  full_src = np.arange(1, n)
  full_ei = jnp.asarray(np.stack([full_src, np.zeros(D_NEIGH)]), jnp.int32)
  params = conv.init(jax.random.key(0), jnp.asarray(x), full_ei)
  ref = conv.apply(params, jnp.asarray(x), full_ei)[0]

  k, trials = 4, 400
  rng = np.random.default_rng(7)
  acc = np.zeros_like(np.asarray(ref))
  for _ in range(trials):
    draw = rng.choice(D_NEIGH, size=k, p=q)
    ei = jnp.asarray(np.stack([draw + 1, np.zeros(k)]), jnp.int32)
    ew = jnp.asarray(w[draw].astype(np.float32))
    out = conv.apply(params, jnp.asarray(x), ei, None, ew)
    acc += np.asarray(out[0]) / trials
  np.testing.assert_allclose(acc, np.asarray(ref), atol=0.02)


def _train_sampled(mode: str, steps=300, seed=3):
  """Train one SAGEConv to regress each target's TRUE neighbor mean
  from per-step sampled edges; return the full-neighborhood eval MSE.
  mode: 'uniform' | 'weighted' (biased draw + 1/q weights) |
  'unweighted' (biased draw, correction dropped)."""
  feats, q, w = _pool()
  T, k = 24, 4
  n = T + D_NEIGH
  x = np.zeros((n, 1), np.float32)
  x[T:, 0] = feats
  y = np.full((T,), feats.mean(), np.float32)   # true uniform mean
  conv = SAGEConv(out_features=1, aggr='mean')
  full_src = np.tile(np.arange(D_NEIGH) + T, T)
  full_dst = np.repeat(np.arange(T), D_NEIGH)
  full_ei = jnp.asarray(np.stack([full_src, full_dst]), jnp.int32)
  params = conv.init(jax.random.key(seed), jnp.asarray(x), full_ei)
  tx = optax.adam(0.05)
  opt = tx.init(params)

  def loss_fn(p, ei, ew):
    out = conv.apply(p, jnp.asarray(x), ei, None, ew)
    return jnp.mean((out[:T, 0] - jnp.asarray(y)) ** 2)

  grad = jax.jit(jax.grad(loss_fn))
  rng = np.random.default_rng(seed)
  probs = None if mode == 'uniform' else q
  for _ in range(steps):
    draws = rng.choice(D_NEIGH, size=(T, k), p=probs)
    src = (draws + T).reshape(-1)
    dst = np.repeat(np.arange(T), k)
    ei = jnp.asarray(np.stack([src, dst]), jnp.int32)
    ew = (jnp.asarray(w[draws].reshape(-1).astype(np.float32))
          if mode == 'weighted' else None)
    g = grad(params, ei, ew)
    up, opt = tx.update(g, opt, params)
    params = optax.apply_updates(params, up)
  out = conv.apply(params, jnp.asarray(x), full_ei)
  return float(jnp.mean((out[:T, 0] - jnp.asarray(y)) ** 2))


def test_convergence_parity_weighted_matches_uniform():
  """The satellite pin: GNS-biased sampling WITH the 1/q weights
  trains to (near) the uniform-sampling optimum; dropping the
  correction leaves an irreducible bias-squared floor the weighted
  run does not have."""
  mse_uniform = _train_sampled('uniform')
  mse_weighted = _train_sampled('weighted')
  mse_unweighted = _train_sampled('unweighted')
  assert mse_uniform < 1e-3
  assert mse_weighted < 4 * mse_uniform + 1e-3    # parity (variance
  # of the importance-weighted estimator costs a little, bias none)
  assert mse_unweighted > 10 * max(mse_weighted, 1e-4), \
      (mse_uniform, mse_weighted, mse_unweighted)


def test_supervised_step_threads_metadata_edge_weight():
  """`make_supervised_step` feeds metadata['edge_weight'] into the
  model: weights of ONES reproduce the unweighted loss bit-for-bit,
  real weights change it (the correction actually reaches the
  aggregation through the example SAGE path)."""
  rng = np.random.default_rng(0)
  n, d, bs, e = 12, 4, 4, 20
  x = rng.random((n, d)).astype(np.float32)
  src = rng.integers(0, n, e)
  dst = rng.integers(0, bs, e)
  ei = np.stack([src, dst]).astype(np.int32)
  y = rng.integers(0, 3, n)
  seeds = np.arange(bs)
  model = GraphSAGE(hidden_features=8, out_features=3, num_layers=2)

  def batch(md):
    return Batch(x=jnp.asarray(x), y=jnp.asarray(y),
                 edge_index=jnp.asarray(ei),
                 edge_mask=jnp.ones((e,), bool),
                 batch=jnp.asarray(seeds), batch_size=bs,
                 metadata=md)

  tx = optax.sgd(0.1)
  state, _ = create_train_state(model, jax.random.key(0), batch({}), tx)
  step = make_supervised_step(model.apply, tx, bs)
  _, loss_plain, _ = step(state, batch({}))
  _, loss_ones, _ = step(
      state, batch({'edge_weight': jnp.ones((e,), jnp.float32)}))
  _, loss_scaled, _ = step(
      state, batch({'edge_weight': jnp.full((e,), 3.0, jnp.float32)}))
  assert float(loss_plain) == float(loss_ones)
  assert float(loss_scaled) != float(loss_plain)
