"""Serving-fleet resilience (ISSUE 13): FleetRouter health
classification (healthy / overloaded / draining / dead), exactly-once
request redrive on replica loss, the heartbeat overloaded-vs-dead
discriminator under chaos delay, drain-free hot model swap with
offline-reference parity gating + rollback, and the SLO/healthz
exemption for intentional draining sheds.

Replicas are in-process `LocalReplica` handles; a replica with
``auto_start=False`` never pumps, so its queued requests sit exactly
like in-flight traffic on a wedged process — the deterministic way to
strand requests for the redrive ledger.
"""
import time

import jax
import numpy as np
import pytest

from graphlearn_tpu.data import Dataset
from graphlearn_tpu.distributed.resilience import (FailoverExhausted,
                                                   ReplicaLostError)
from graphlearn_tpu.models.tree import TreeSAGE
from graphlearn_tpu.serving import (AdmissionRejected, FleetRouter,
                                    LocalReplica, ServingEngine,
                                    ServingFrontend, SwapParityError,
                                    SwapValidationError, hot_swap)
from graphlearn_tpu.telemetry import recorder
from graphlearn_tpu.testing import chaos

N, D = 48, 4
FANOUTS = [3, 2]
BUCKETS = (1, 2, 4)


@pytest.fixture(autouse=True)
def _clean():
  chaos.uninstall()
  recorder.enable(None)
  recorder.clear()
  yield
  chaos.uninstall()
  recorder.clear()
  recorder.disable()


def _dataset():
  rng = np.random.default_rng(0)
  rows = np.repeat(np.arange(N), 3)
  cols = rng.integers(0, N, rows.shape[0])
  feats = (np.arange(N, dtype=np.float32)[:, None]
           * np.ones((1, D), np.float32))
  return (Dataset().init_graph((rows, cols), layout='COO', num_nodes=N)
          .init_node_features(feats))


_WARM = {}


def _engine(model=False):
  m = (TreeSAGE(hidden_features=8, out_features=5,
                num_layers=len(FANOUTS)) if model else None)
  eng = ServingEngine(_dataset(), FANOUTS, model=m, seed=7,
                      buckets=BUCKETS)
  if model:
    eng.init_params(jax.random.key(0))
  return eng


def _frontend(auto=True, model=False, **kw):
  kw.setdefault('max_wait_ms', 1.0)
  kw.setdefault('default_deadline_ms', 30000.0)
  return ServingFrontend(_engine(model=model), auto_start=auto,
                         warmup=True, **kw)


def _fleet(n=3, auto=(), model=False, **router_kw):
  """n local replicas r0..r{n-1}; indices in ``auto`` run their
  executor, the rest stay manual (queued requests sit — strandable)."""
  router_kw.setdefault('auto_start', False)
  router_kw.setdefault('dead_after', 2)
  reps = [LocalReplica(f'r{i}', _frontend(auto=i in auto, model=model))
          for i in range(n)]
  return FleetRouter(reps, **router_kw), reps


def _drain_all(router, reps, futs, timeout=20.0):
  """Pump every live replica until the given futures resolve."""
  deadline = time.monotonic() + timeout
  out = []
  for f in futs:
    while not f.done():
      for r in reps:
        if not r._dead:
          r.frontend.pump_once(block=False)
      if time.monotonic() > deadline:
        raise TimeoutError('fleet futures stuck')
    out.append(f.result(1.0))
  return out


# -- routing & accounting ----------------------------------------------------
def test_fleet_spreads_and_resolves_all(request):
  router, reps = _fleet(3, auto=(0, 1, 2))
  request.addfinalizer(lambda: router.close(close_replicas=True))
  futs = [router.submit([i % N]) for i in range(12)]
  res = [f.result(20.0) for f in futs]
  assert len(res) == 12
  st = router.stats()
  assert st['submitted'] == 12
  assert st['resolved'] == {'ok': 12, 'shed': 0, 'error': 0}
  assert st['in_flight'] == 0
  # the weighted cycle reaches every replica
  for r in reps:
    assert r.frontend.admission.admitted > 0


def test_fleet_answers_match_offline_reference(request):
  """Whichever replica serves (one engine seed fleet-wide), the answer
  is the per-seed offline reference — the property that makes redrive
  answers byte-identical too."""
  router, reps = _fleet(2, auto=(0, 1))
  request.addfinalizer(lambda: router.close(close_replicas=True))
  ref_eng = reps[0].frontend.engine
  for seed in (3, 11, 7):
    got = router.infer([seed], timeout=20.0)
    ref = ref_eng.offline_reference([seed])
    np.testing.assert_array_equal(got.nodes, ref.nodes)


# -- failover: eviction + exactly-once redrive -------------------------------
def test_dead_replica_evicted_and_stranded_requests_redriven(request):
  """Kill a replica with queued requests: after eviction every
  stranded request is redriven to a survivor EXACTLY once and every
  future resolves ok — zero lost, zero failed (the acceptance
  arithmetic)."""
  router, reps = _fleet(3, auto=(1, 2))
  request.addfinalizer(lambda: router.close(close_replicas=True))
  futs = [router.submit([i % N]) for i in range(9)]
  stranded = reps[0].frontend.admission.depth()
  assert stranded > 0                  # r0 never pumps: requests sit
  reps[0].kill()
  assert router.check_replicas()['r0'] == 'healthy'   # miss 1
  assert router.check_replicas()['r0'] == 'dead'      # miss 2: evict
  st = router.stats()
  assert st['evictions'] == 1
  assert st['redriven'] == stranded
  res = _drain_all(router, reps, futs)
  assert len(res) == 9
  st = router.stats()
  assert st['resolved'] == {'ok': 9, 'shed': 0, 'error': 0}
  assert st['submitted'] == 9 and st['in_flight'] == 0
  evicts = [e for e in recorder.events('serving.failover')
            if e.get('event') == 'evict']
  assert evicts and evicts[0]['redriven'] == stranded
  redrives = [e for e in recorder.events('serving.failover')
              if e.get('event') == 'redrive']
  assert len(redrives) == stranded


def test_second_loss_after_redrive_resolves_typed(request):
  """A request may be redriven at most once: when its survivor dies
  too, the future resolves with typed FailoverExhausted — never a
  silent drop, never an endless bounce."""
  router, reps = _fleet(2, auto=())
  request.addfinalizer(lambda: router.close(close_replicas=True))
  fut = router.submit([3])
  first = next(n for n, e in router.stats()['replicas'].items()
               if reps[int(n[1])].frontend.admission.depth())
  reps[int(first[1])].kill()
  router.check_replicas(), router.check_replicas()
  assert router.stats()['redriven'] == 1
  second = 'r1' if first == 'r0' else 'r0'
  reps[int(second[1])].kill()
  router.check_replicas(), router.check_replicas()
  with pytest.raises(FailoverExhausted):
    fut.result(5.0)
  st = router.stats()
  assert st['resolved'] == {'ok': 0, 'shed': 0, 'error': 1}
  assert [e for e in recorder.events('serving.failover')
          if e.get('event') == 'exhausted']


def test_no_replica_accepts_raises_typed(request):
  router, reps = _fleet(2, auto=())
  request.addfinalizer(lambda: router.close(close_replicas=True))
  for r in reps:
    r.kill()
  router.check_replicas(), router.check_replicas()
  with pytest.raises(FailoverExhausted):
    router.submit([1])


# -- the overloaded-vs-dead discriminator under chaos delay ------------------
def test_slow_replica_overloaded_not_evicted_under_chaos_delay(request):
  """ISSUE 13 satellite: chaos ``delay`` on one replica's heartbeat
  classifies it OVERLOADED (slow-but-alive) — it keeps serving at
  reduced weight and is never evicted; its in-flight requests stay
  put (no redrive)."""
  chaos.install({'faults': [{'site': 'serving.replica',
                             'action': 'delay', 'op': 'heartbeat',
                             'replica': 'r1', 'nth': 1, 'count': 99,
                             'secs': 0.06}]})
  router, reps = _fleet(3, auto=(0, 1, 2), slow_ms=30.0)
  request.addfinalizer(lambda: router.close(close_replicas=True))
  for _ in range(3):
    states = router.check_replicas()
  assert states['r1'] == 'overloaded'
  assert router.stats()['evictions'] == 0
  futs = [router.submit([i % N]) for i in range(24)]
  for f in futs:
    f.result(20.0)
  counts = {r.name: r.frontend.admission.admitted for r in reps}
  assert counts['r1'] > 0                      # still serving
  assert counts['r1'] < counts['r0']           # at reduced weight
  assert counts['r1'] < counts['r2']
  assert router.stats()['redriven'] == 0       # nothing moved


def test_chaos_kill_evicts_and_redrives_exactly_once(request):
  """The dead half of the discriminator, driven by the declarative
  chaos plan: a ``kill`` on the replica seam makes heartbeats miss,
  the router evicts after ``dead_after`` misses and redrives the
  stranded in-flight requests exactly once."""
  router, reps = _fleet(3, auto=(1, 2))
  request.addfinalizer(lambda: router.close(close_replicas=True))
  futs = [router.submit([i % N]) for i in range(9)]
  stranded = reps[0].frontend.admission.depth()
  assert stranded > 0
  chaos.install({'faults': [{'site': 'serving.replica',
                             'action': 'kill', 'op': 'heartbeat',
                             'replica': 'r0', 'nth': 1}]})
  router.check_replicas()                      # kill fires -> miss 1
  router.check_replicas()                      # miss 2 -> evict
  assert router.replica_states()['r0'] == 'dead'
  assert router.stats()['redriven'] == stranded
  assert len(_drain_all(router, reps, futs)) == 9
  assert router.stats()['resolved']['error'] == 0


def test_flap_below_threshold_costs_nothing(request):
  """A flap shorter than the eviction threshold: one heartbeat miss,
  no eviction, no redrive; the replica is healthy again on its next
  good heartbeat."""
  router, reps = _fleet(2, auto=(0, 1), dead_after=3)
  request.addfinalizer(lambda: router.close(close_replicas=True))
  reps[0]._flap_until = time.monotonic() + 0.05
  assert router.check_replicas()['r0'] == 'healthy'   # miss 1 only
  assert router.stats()['replicas']['r0']['misses'] == 1
  time.sleep(0.06)
  assert router.check_replicas()['r0'] == 'healthy'
  assert router.stats()['replicas']['r0']['misses'] == 0
  assert router.stats()['evictions'] == 0


def test_flap_past_threshold_evicts_then_readmits(request):
  router, reps = _fleet(2, auto=(0, 1), dead_after=2)
  request.addfinalizer(lambda: router.close(close_replicas=True))
  reps[0]._flap_until = time.monotonic() + 0.15
  router.check_replicas()
  assert router.check_replicas()['r0'] == 'dead'
  time.sleep(0.16)
  assert router.check_replicas()['r0'] == 'healthy'   # re-admitted
  assert [e for e in recorder.events('serving.failover')
          if e.get('event') == 'readmit']
  router.infer([1], timeout=20.0)              # takes traffic again


def test_submit_evict_race_still_redrives(request):
  """The monitor may evict a replica BETWEEN handle.submit and the
  ledger insert — the eviction's stranded snapshot misses the entry,
  so submit itself must notice and redrive (else the future freezes:
  the one way to silently lose a request)."""
  router, reps = _fleet(2, auto=(1,))
  request.addfinalizer(lambda: router.close(close_replicas=True))
  orig = reps[0].submit

  def racing_submit(seeds, deadline_ms=None, trace=None):
    fut = orig(seeds, deadline_ms, trace=trace)
    router._evict('r0')              # the monitor wins the race
    return fut

  reps[0].submit = racing_submit
  fut = router.submit([3])
  assert router.stats()['redriven'] == 1   # caught by the guard
  assert fut.result(20.0) is not None
  assert router.stats()['resolved'] == {'ok': 1, 'shed': 0, 'error': 0}


# -- draining routing --------------------------------------------------------
def test_draining_replica_skipped_not_evicted(request):
  router, reps = _fleet(2, auto=(0, 1))
  request.addfinalizer(lambda: router.close(close_replicas=True))
  reps[0].frontend.admission.set_draining(True)
  assert router.check_replicas()['r0'] == 'draining'
  before = reps[0].frontend.admission.admitted
  futs = [router.submit([i % N]) for i in range(6)]
  for f in futs:
    f.result(20.0)
  assert reps[0].frontend.admission.admitted == before  # all to r1
  assert router.stats()['evictions'] == 0
  assert router._health()['healthy']
  reps[0].frontend.admission.set_draining(False)
  assert router.check_replicas()['r0'] == 'healthy'


def test_abandoned_futures_swept_from_ledger(request):
  """A caller that times out and walks away must not grow the ledger
  (and the /healthz in_flight count) forever: resolved-but-never-
  collected entries are swept after the grace window."""
  router, reps = _fleet(2, auto=(0, 1), abandon_grace_s=0.05)
  request.addfinalizer(lambda: router.close(close_replicas=True))
  fut = router.submit([3])
  deadline = time.monotonic() + 10
  while not fut.done():
    assert time.monotonic() < deadline
    time.sleep(0.01)
  time.sleep(0.06)                   # past the grace window
  router.check_replicas()
  st = router.stats()
  assert st['in_flight'] == 0 and st['swept'] == 1
  with pytest.raises(RuntimeError, match='swept'):
    fut.result(1.0)


def test_malformed_request_raises_without_charging_misses(request):
  """A bad client input (seed outside the node space) is the CLIENT's
  ValueError — it must not count heartbeat misses against replicas
  (two bad inputs must never evict a healthy fleet) nor surface as
  FailoverExhausted."""
  router, reps = _fleet(2, auto=(0, 1))
  request.addfinalizer(lambda: router.close(close_replicas=True))
  for _ in range(3):
    with pytest.raises(ValueError):
      router.submit([N + 5])
  st = router.stats()
  assert st['evictions'] == 0
  assert all(r['misses'] == 0 for r in st['replicas'].values())
  router.infer([1], timeout=20.0)    # fleet unharmed


def test_shutdown_replica_rerouted_and_rotated_out(request):
  """A cleanly shut-down replica still answers heartbeats (queue 0,
  draining False): its typed shutdown rejections must REROUTE to
  survivors, and its heartbeats count as misses so it leaves
  rotation — not sit at full weight refusing its traffic share."""
  router, reps = _fleet(2, auto=(0, 1))
  request.addfinalizer(lambda: router.close(close_replicas=True))
  reps[0].frontend.shutdown()
  # submits that land on r0 first reroute to r1 — callers never see
  # the shutdown rejection while a survivor serves
  for i in range(6):
    router.infer([i], timeout=20.0)
  assert router.stats()['resolved']['ok'] == 6
  router.check_replicas()
  assert router.check_replicas()['r0'] == 'dead'  # rotated out
  router.infer([7], timeout=20.0)


def test_all_replicas_draining_raises_admission_typed(request):
  """A coordinated swap (every live replica draining) must surface as
  the documented AdmissionRejected(reason='draining') with its
  retry-after hint — NOT as a fleet-wide-outage FailoverExhausted."""
  router, reps = _fleet(2, auto=(0, 1))
  request.addfinalizer(lambda: router.close(close_replicas=True))
  for r in reps:
    r.frontend.admission.set_draining(True)
  router.check_replicas()
  with pytest.raises(AdmissionRejected) as ei:
    router.submit([1])
  assert ei.value.reason == 'draining'
  assert ei.value.retry_after_ms and ei.value.retry_after_ms > 0
  for r in reps:
    r.frontend.admission.set_draining(False)
  router.check_replicas()
  router.infer([1], timeout=20.0)    # cutover over


def test_overlapping_drain_windows_refcounted():
  """Two overlapping cutovers: the FIRST one's exit must not reopen
  admission while the second still drains (depth-counted)."""
  fe = _frontend(auto=False, model=False)
  try:
    fe.admission.set_draining(True)
    fe.admission.set_draining(True)
    fe.admission.set_draining(False)     # first window closes
    assert fe.admission.draining()       # second still open
    with pytest.raises(AdmissionRejected):
      fe.submit([1])
    fe.admission.set_draining(False)
    assert not fe.admission.draining()
    fe.submit([1])                       # reopened
  finally:
    fe.shutdown()


# -- hot model swap ----------------------------------------------------------
def test_hot_swap_commits_new_version_zero_drops(request):
  fe = _frontend(auto=True, model=True)
  request.addfinalizer(fe.shutdown)
  eng = fe.engine
  r_before = fe.infer([3])
  new_params = eng.model.init(
      jax.random.key(99),
      [np.zeros((w, D), np.float32) for w in eng.level_widths],
      [np.ones((w,), bool) for w in eng.level_widths])
  out = fe.swap_model(new_params, version=7)
  assert out['version'] == 7 and eng.model_version == 7
  assert not fe.admission.draining()           # window closed
  r_after = fe.infer([3])
  np.testing.assert_array_equal(r_before.nodes, r_after.nodes)
  assert not np.array_equal(r_before.logits, r_after.logits)
  ref = eng.offline_reference([3], params=new_params)
  np.testing.assert_allclose(np.asarray(r_after.logits),
                             np.asarray(ref.logits), atol=1e-4)
  ev = [e for e in recorder.events('serving.swap') if e.get('ok')]
  assert ev and ev[-1]['version'] == 7
  assert fe.stats()['model_version'] == 7


def test_hot_swap_parity_mismatch_rolls_back_typed(request):
  """atol=0 makes the cross-bucket float-tolerance identity (engine
  fine print, ~1e-6) register as a parity failure: the swap must roll
  back typed, keep the prior version serving, and drop nothing."""
  fe = _frontend(auto=True, model=True)
  request.addfinalizer(fe.shutdown)
  eng = fe.engine
  old_params, old_version = eng.params, eng.model_version
  r_before = fe.infer([5])
  new_params = eng.model.init(
      jax.random.key(99),
      [np.zeros((w, D), np.float32) for w in eng.level_widths],
      [np.ones((w,), bool) for w in eng.level_widths])
  with pytest.raises(SwapParityError):
    fe.swap_model(new_params, probe_seeds=[0, 9, 17, 25], atol=0.0)
  assert eng.params is old_params              # rolled back
  assert eng.model_version == old_version
  assert not fe.admission.draining()
  r_after = fe.infer([5])                      # old version serving
  np.testing.assert_array_equal(np.asarray(r_before.logits),
                                np.asarray(r_after.logits))
  ev = [e for e in recorder.events('serving.swap')
        if e.get('rolled_back')]
  assert ev and not ev[-1]['ok']
  assert fe.stats()['shed']['shutdown'] == 0   # nothing flushed


def test_swap_validation_refuses_bad_tree_before_drain(request):
  fe = _frontend(auto=True, model=True)
  request.addfinalizer(fe.shutdown)
  other = TreeSAGE(hidden_features=16, out_features=5,
                   num_layers=len(FANOUTS))
  eng = fe.engine
  bad = other.init(jax.random.key(0),
                   [np.zeros((w, D), np.float32)
                    for w in eng.level_widths],
                   [np.ones((w,), bool) for w in eng.level_widths])
  with pytest.raises(SwapValidationError):
    fe.swap_model(bad)
  assert not fe.admission.draining()           # never even drained
  assert not recorder.events('serving.swap')


def test_swap_abort_when_executor_never_quiesces(request):
  """A wedged in-flight dispatch aborts the swap TYPED as an
  executor-health signal (SwapAbortedError, not a parity verdict),
  still emits its one serving.swap event, and leaves the drain window
  closed and the prior version serving."""
  from graphlearn_tpu.serving import SwapAbortedError
  fe = _frontend(auto=True, model=True)
  request.addfinalizer(fe.shutdown)
  eng = fe.engine
  new_params = eng.model.init(
      jax.random.key(99),
      [np.zeros((w, D), np.float32) for w in eng.level_widths],
      [np.ones((w,), bool) for w in eng.level_widths])
  assert fe._dispatch_gate.acquire(timeout=5.0)   # wedge the gate
  try:
    with pytest.raises(SwapAbortedError):
      fe.swap_model(new_params, gate_timeout_s=0.1)
  finally:
    fe._dispatch_gate.release()
  assert not fe.admission.draining()
  assert eng.model_version == 0                   # never displaced
  ev = [e for e in recorder.events('serving.swap') if not e.get('ok')]
  assert ev and not ev[-1]['rolled_back']
  fe.infer([3])                                   # still serving


def test_swap_needs_model(request):
  fe = _frontend(auto=True, model=False)
  request.addfinalizer(fe.shutdown)
  with pytest.raises(SwapValidationError):
    hot_swap(fe, {'w': np.ones(3)})


def test_draining_rejection_carries_retry_after(request):
  fe = _frontend(auto=True, model=False)
  request.addfinalizer(fe.shutdown)
  fe.admission.set_draining(True)
  with pytest.raises(AdmissionRejected) as ei:
    fe.submit([1])
  assert ei.value.reason == 'draining'
  assert ei.value.retry_after_ms and ei.value.retry_after_ms > 0
  fe.admission.set_draining(False)
  fe.infer([1])                                # window over, serving


# -- SLO / healthz during drain (ISSUE 13 satellite) -------------------------
def test_draining_sheds_do_not_burn_slo_but_real_sheds_do(monkeypatch):
  monkeypatch.setenv('GLT_SERVING_SLO_P99_MS', '50')
  fe = _frontend(auto=False, model=False, max_queue=4,
                 default_deadline_ms=50.0)
  try:
    win = fe.slo.windows[0]
    # intentional draining sheds: NO SLO samples, no budget burned
    fe.admission.set_draining(True)
    for _ in range(5):
      with pytest.raises(AdmissionRejected):
        fe.submit([1])
    assert fe.slo.window_stats(win)['count'] == 0
    assert fe.slo.window_stats(win)['burn_rate'] == 0.0
    assert fe.admission.stats()['shed']['draining'] == 5
    # healthz stays green while draining
    h = fe._health()
    assert h['healthy'] and h['draining']
    fe.admission.set_draining(False)
    # a REAL overload shed (queue_full) burns budget
    for _ in range(4):
      fe.submit([1])
    with pytest.raises(AdmissionRejected):
      fe.submit([1])                           # queue_full at 4/4
    st = fe.slo.window_stats(win)
    assert st['count'] == 1 and st['violations'] == 1
    assert st['burn_rate'] > 1.0
    # deadline sheds burn too (queued past deadline, shed at take)
    time.sleep(0.06)
    fe.pump_once(block=False)
    assert fe.slo.window_stats(win)['violations'] >= 2
  finally:
    fe.shutdown()


def test_fleet_health_component_reports_per_replica(request):
  from graphlearn_tpu.telemetry.live import live
  router, reps = _fleet(2, auto=(0, 1))
  request.addfinalizer(lambda: router.close(close_replicas=True))
  router.check_replicas()
  h = live.healthz()
  fleet = h['components']['fleet']
  assert fleet['healthy']
  assert set(fleet['replicas']) == {'r0', 'r1'}
  assert fleet['replicas']['r0']['state'] == 'healthy'
  # per-replica SLO feed rides the heartbeat serving block
  assert fleet['replicas']['r0']['slo'] is not None
  # gauges: replica counts by state
  reps[0].kill()
  router.check_replicas(), router.check_replicas()
  st = router.stats()['replicas']
  assert st['r0']['state'] == 'dead' and st['r1']['state'] == 'healthy'


# -- flap damping (ISSUE 19) -------------------------------------------------
def _flap_once(router, reps, i=0):
  """One full dead→healthy flap: miss past dead_after, then answer
  again — returns the state map of the re-admission pass."""
  reps[i]._flap_until = time.monotonic() + 30.0
  router.check_replicas()
  router.check_replicas()                      # dead at dead_after=2
  reps[i]._flap_until = 0.0
  return router.check_replicas()


def test_three_flaps_quarantine_with_backoff(request):
  """≥3 dead→healthy readmits inside GLT_FLEET_FLAP_WINDOW_S: the
  replica is quarantined (zero routing weight, typed in stats), a
  good heartbeat during the backoff does NOT re-admit it, and after
  the backoff it returns to rotation.  The readmit history is NOT
  cleared on quarantine, so an immediate re-flap re-quarantines at a
  DOUBLED backoff."""
  from graphlearn_tpu.telemetry.live import live
  base = live.counter('fleet.quarantines_total').value()
  router, reps = _fleet(2, auto=(0, 1), flap_window_s=60.0,
                        quarantine_backoff_s=0.2)
  request.addfinalizer(lambda: router.close(close_replicas=True))
  assert _flap_once(router, reps)['r0'] == 'healthy'     # flap 1
  assert _flap_once(router, reps)['r0'] == 'healthy'     # flap 2
  assert _flap_once(router, reps)['r0'] == 'quarantined'  # flap 3
  assert router.stats()['quarantined'] == 1
  assert live.counter('fleet.quarantines_total').value() == base + 1
  assert [e for e in recorder.events('serving.failover')
          if e.get('event') == 'quarantine']
  # zero routing weight: every request lands on the survivor
  before = reps[0].frontend.admission.admitted
  futs = [router.submit([i % N]) for i in range(6)]
  for f in futs:
    f.result(20.0)
  assert reps[0].frontend.admission.admitted == before
  # a good heartbeat during the backoff does NOT re-admit — that
  # free readmit is the churn the damper exists to stop
  assert router.check_replicas()['r0'] == 'quarantined'
  time.sleep(0.25)                            # backoff 0.2s expires
  assert router.check_replicas()['r0'] == 'healthy'
  assert [e for e in recorder.events('serving.failover')
          if e.get('event') == 'readmit']
  # re-flap right after re-admission: the aged-in history
  # re-quarantines immediately, backing off twice as long
  assert _flap_once(router, reps)['r0'] == 'quarantined'
  assert router.stats()['quarantined'] == 2
  time.sleep(0.25)                            # 0.4s backoff now
  assert router.check_replicas()['r0'] == 'quarantined'
  time.sleep(0.25)
  assert router.check_replicas()['r0'] == 'healthy'


def test_slow_flaps_outside_window_never_quarantine(request):
  """Flaps the window has aged out cost nothing: each one re-admits
  free, exactly the pre-damping behavior."""
  router, reps = _fleet(2, auto=(0, 1), flap_window_s=0.01)
  request.addfinalizer(lambda: router.close(close_replicas=True))
  for _ in range(4):
    assert _flap_once(router, reps)['r0'] == 'healthy'
    time.sleep(0.02)
  assert router.stats()['quarantined'] == 0
