"""Online serving plane (ISSUE 9): engine byte-identity across bucket
boundaries, admission control (typed shedding, bounded queue),
coalescing frontend, and the zero-recompile-after-warmup pin.
"""
import time

import jax
import numpy as np
import pytest

from graphlearn_tpu.data import Dataset
from graphlearn_tpu.models.tree import TreeSAGE
from graphlearn_tpu.serving import (AdmissionRejected, ServingEngine,
                                    ServingFrontend, resolve_buckets)
from graphlearn_tpu.serving.admission import AdmissionController
from graphlearn_tpu.telemetry import recorder
from graphlearn_tpu.testing import chaos

N, D = 64, 6
FANOUTS = [3, 2]
BUCKETS = (1, 2, 4)


@pytest.fixture(autouse=True)
def _clean():
  chaos.uninstall()
  recorder.enable(None)
  recorder.clear()
  yield
  chaos.uninstall()
  recorder.clear()
  recorder.disable()


def _dataset(split_ratio=1.0, cold_cache_rows='auto'):
  rng = np.random.default_rng(0)
  rows = np.repeat(np.arange(N), 4)
  cols = rng.integers(0, N, rows.shape[0])
  # row r of the table = [r, r, ...]: a gathered feature row names its
  # node id, so identity assertions read directly off x
  feats = (np.arange(N, dtype=np.float32)[:, None]
           * np.ones((1, D), np.float32))
  ds = Dataset().init_graph((rows, cols), layout='COO', num_nodes=N)
  if split_ratio < 1.0:
    from graphlearn_tpu.data.feature import Feature
    ds.node_features = Feature(feats, split_ratio=split_ratio,
                               cold_cache_rows=cold_cache_rows)
  else:
    ds.init_node_features(feats)
  return ds


@pytest.fixture(scope='module')
def engine():
  eng = ServingEngine(_dataset(), FANOUTS, seed=7, buckets=BUCKETS)
  eng.warmup()
  return eng


@pytest.fixture(scope='module')
def model_engine():
  model = TreeSAGE(hidden_features=8, out_features=5,
                   num_layers=len(FANOUTS))
  eng = ServingEngine(_dataset(), FANOUTS, model=model, seed=7,
                      buckets=BUCKETS)
  eng.init_params(jax.random.key(0))
  eng.warmup()
  return eng


# -- bucket ladder ----------------------------------------------------------
def test_resolve_buckets(monkeypatch):
  assert resolve_buckets((8, 2, 2, 4)) == (2, 4, 8)
  monkeypatch.setenv('GLT_SERVING_BUCKETS', '1, 4,16')
  assert resolve_buckets() == (1, 4, 16)
  monkeypatch.setenv('GLT_SERVING_BUCKETS', 'garbage')
  assert resolve_buckets() == (1, 2, 4, 8, 16)   # degrade to default


def test_bucket_for(engine):
  assert engine.bucket_for(1) == 1
  assert engine.bucket_for(3) == 4
  with pytest.raises(ValueError):
    engine.bucket_for(5)


# -- byte-identity (the coalescing contract) --------------------------------
def test_coalesced_byte_identity_across_buckets(engine):
  """A request's nodes/x are byte-identical whether it was served
  alone (bucket 1) or coalesced with strangers into a deeper bucket —
  the per-seed key schedule at work."""
  seeds = np.array([5, 9, 33])
  co = engine.infer(seeds)                 # bucket 4, one dispatch
  off = engine.offline_reference(seeds)    # bucket 1, one per seed
  np.testing.assert_array_equal(co.nodes, off.nodes)
  np.testing.assert_array_equal(co.x, off.x)
  # gathered rows really are the sampled nodes' rows (zero for pads)
  valid = co.nodes >= 0
  np.testing.assert_array_equal(
      co.x[..., 0], np.where(valid, co.nodes, 0).astype(np.float32))
  # mid-ladder bucket agrees too
  two = engine.infer(seeds[:2])            # bucket 2
  np.testing.assert_array_equal(two.nodes, off.nodes[:2])
  np.testing.assert_array_equal(two.x, off.x[:2])


def test_rider_independence(engine):
  """Same seed, different co-batched traffic, same bucket -> the same
  bytes (what makes demuxed results request-private)."""
  a = engine.infer(np.array([5, 9, 33]))
  b = engine.infer(np.array([5, 60, 61, 62]))
  np.testing.assert_array_equal(a.nodes[0], b.nodes[0])
  np.testing.assert_array_equal(a.x[0], b.x[0])


def test_repeat_determinism(engine):
  """Two identical requests (e.g. an RPC retry's re-execution) answer
  byte-identically."""
  a = engine.infer(np.array([17, 3]))
  b = engine.infer(np.array([17, 3]))
  np.testing.assert_array_equal(a.nodes, b.nodes)
  np.testing.assert_array_equal(a.x, b.x)


def test_model_logits_identity(model_engine):
  """Fused-forward logits: byte-identical within a bucket shape
  whatever the request rode with; across bucket shapes nodes stay
  byte-identical and logits agree to float tolerance (XLA retiles
  matmuls per shape — see the engine docstring's fine print)."""
  seeds = np.array([5, 9, 33])
  a = model_engine.infer(seeds)                     # cap 4
  b = model_engine.infer(np.array([5, 9, 33, 60]))  # cap 4, one rider
  np.testing.assert_array_equal(a.logits, b.logits[:3])
  off = model_engine.offline_reference(seeds)       # cap 1 each
  np.testing.assert_array_equal(a.nodes, off.nodes)
  np.testing.assert_allclose(a.logits, off.logits, atol=1e-5)
  # pinned-cap offline reference IS bitwise, logits included
  off4 = model_engine.offline_reference(seeds, cap=4)
  np.testing.assert_array_equal(a.logits, off4.logits)


def test_tiered_matches_hot(engine):
  """A tiered table (hot split + cold cache + host misses) serves the
  same bytes as the fully-HBM table — for any cache budget."""
  seeds = np.array([5, 9, 33, 60])
  ref = engine.infer(seeds)
  for cache_rows in (0, 4):
    eng_t = ServingEngine(_dataset(split_ratio=0.5,
                                   cold_cache_rows=cache_rows),
                          FANOUTS, seed=7, buckets=BUCKETS)
    got = eng_t.infer(seeds)
    np.testing.assert_array_equal(got.nodes, ref.nodes)
    np.testing.assert_array_equal(got.x, ref.x)
  # cold-cache telemetry lands under the serving scope
  if any(e.get('scope') == 'serving'
         for e in recorder.events('cache.miss')):
    assert all(e['scope'] in ('serving', 'feature', 'dist')
               for e in recorder.events('cache.miss'))


def test_warmup_zero_recompiles(engine):
  """THE serving acceptance pin: after warmup, the whole traffic
  envelope (every request size up to the top bucket, both arms) hits
  warm executables — the `_uncached_jit` per-callable compile
  counters must not move."""
  assert all(engine.warm.values())
  before = engine.compile_count()
  for k in (1, 2, 3, 4, 1, 2, 3, 4):
    engine.infer(np.arange(k) + 1)
  assert engine.compile_count() == before, \
      'a traffic shape escaped the bucket ladder and recompiled'
  status = engine.compile_status()
  assert status['buckets'] == {'1': True, '2': True, '4': True}


def test_driver_compile_count_counters():
  """The `_uncached_jit` per-callable counters behind the pin: a
  compile ticks, a warm executable hit does not, a new shape ticks
  again — and `driver_compile_count` sums them duck-typed (the same
  helper the mesh epoch drivers expose as `compile_count()`)."""
  import jax.numpy as jnp
  from graphlearn_tpu.loader.fused import (_uncached_jit,
                                           driver_compile_count)

  class _D:
    pass

  d = _D()
  d._compiled = _uncached_jit(lambda x: x * 2)
  d._compiled(jnp.ones((2,)))
  assert (d._compiled.calls, d._compiled.compiles) == (1, 1)
  d._compiled(jnp.ones((2,)))
  assert d._compiled.compiles == 1          # in-memory executable hit
  d._compiled(jnp.ones((3,)))
  assert d._compiled.compiles == 2          # new shape = new compile
  assert driver_compile_count(d) == 2


# -- admission control ------------------------------------------------------
def test_queue_bound_typed_rejection():
  ctl = AdmissionController(max_queue=2, default_deadline_ms=1000)
  ctl.submit([1])
  ctl.submit([2])
  with pytest.raises(AdmissionRejected) as ei:
    ctl.submit([3])
  assert ei.value.reason == 'queue_full'
  assert ei.value.queue_depth == 2 and ei.value.limit == 2
  assert ctl.stats()['shed']['queue_full'] == 1
  assert len(recorder.events('serving.admit')) == 2
  shed = recorder.events('serving.shed')
  assert shed and shed[-1]['reason'] == 'queue_full'


def test_deadline_shed_typed_never_silent():
  """A queued request whose deadline passes is resolved with a typed
  AdmissionRejected (reason='deadline', waited_ms diagnostics) — its
  caller learns immediately; nothing is dropped on the floor."""
  ctl = AdmissionController(max_queue=8, default_deadline_ms=1000)
  expired = ctl.submit([1], deadline_ms=1)
  alive = ctl.submit([2], deadline_ms=10_000)
  time.sleep(0.05)
  run = ctl.take(max_seeds=4, max_wait_s=0.0)
  assert [r is alive for r in run] == [True]
  assert expired.future.done()
  with pytest.raises(AdmissionRejected) as ei:
    expired.future.result(0)
  assert ei.value.reason == 'deadline'
  assert ei.value.waited_ms > 0
  assert ctl.stats()['shed']['deadline'] == 1
  assert any(e['reason'] == 'deadline'
             for e in recorder.events('serving.shed'))


def test_burst_respects_queue_bound():
  """Under a burst the queue never exceeds its bound: exactly
  max_queue admissions succeed, the rest are refused typed, and every
  admitted request is eventually answered."""
  ctl = AdmissionController(max_queue=4, default_deadline_ms=10_000)
  admitted, refused = [], 0
  for i in range(10):
    try:
      admitted.append(ctl.submit([i]))
    except AdmissionRejected as e:
      refused += 1
      assert e.reason == 'queue_full'
  assert len(admitted) == 4 and refused == 6
  assert ctl.depth() == 4
  served = []
  while ctl.depth():
    served += ctl.take(max_seeds=2, max_wait_s=0.0)
  assert len(served) == 4
  ctl.close()


def test_shutdown_resolves_queued_typed():
  ctl = AdmissionController(max_queue=8, default_deadline_ms=10_000)
  req = ctl.submit([1])
  ctl.close()
  with pytest.raises(AdmissionRejected) as ei:
    req.future.result(0)
  assert ei.value.reason == 'shutdown'
  with pytest.raises(AdmissionRejected):
    ctl.submit([2])                 # the closed door is typed too


# -- coalescing frontend ----------------------------------------------------
def test_frontend_coalesces_and_demuxes(engine):
  fe = ServingFrontend(engine, auto_start=False, max_wait_ms=0.0,
                       default_deadline_ms=10_000)
  seeds = [np.array([5]), np.array([9, 33]), np.array([60])]
  futs = [fe.submit(s) for s in seeds]
  assert fe.pump_once() == 3
  flat = np.concatenate(seeds)
  ref = engine.offline_reference(flat)
  got = np.concatenate([f.result(1.0).x for f in futs])
  np.testing.assert_array_equal(got, ref.x)
  ev = recorder.events('serving.coalesce')
  assert ev and ev[-1]['requests'] == 3 and ev[-1]['seeds'] == 4 \
      and ev[-1]['bucket'] == 4
  reqs = recorder.events('serving.request')
  assert len(reqs) == 3 and all(e['ok'] for e in reqs)
  assert all(e['latency_ms'] >= 0 for e in reqs)
  assert fe.stats()['served_requests'] == 3
  fe.shutdown()


def test_frontend_too_large_typed(engine):
  fe = ServingFrontend(engine, auto_start=False)
  with pytest.raises(AdmissionRejected) as ei:
    fe.submit(np.arange(5))         # top bucket is 4
  assert ei.value.reason == 'too_large'
  fe.shutdown()


def test_frontend_refuses_out_of_range_seeds(engine):
  """Malformed seed ids are REFUSED, not clamped: jax gathers clamp
  out-of-range indices, so without the door check a bogus id would
  come back as a plausible answer for a different node."""
  fe = ServingFrontend(engine, auto_start=False)
  with pytest.raises(ValueError, match='outside'):
    fe.submit([N + 100])
  with pytest.raises(ValueError, match='outside'):
    fe.submit([-5])
  with pytest.raises(ValueError):
    fe.submit([])
  fe.shutdown()


def test_pump_once_nonblocking_empty_queue(engine):
  fe = ServingFrontend(engine, auto_start=False)
  assert fe.pump_once(block=False) == 0   # returns, never waits
  fe.shutdown()


def test_model_without_params_typed():
  eng = ServingEngine(
      _dataset(), FANOUTS,
      model=TreeSAGE(hidden_features=8, out_features=5,
                     num_layers=len(FANOUTS)),
      seed=7, buckets=(1,))
  with pytest.raises(ValueError, match='init_params'):
    eng.infer(np.array([3]))


def test_frontend_executor_fault_resolves_every_future(engine):
  """A dispatch that dies (injected serving.request drop at the
  executor seam) resolves EVERY rider's future with the typed error —
  the no-lost-requests contract under faults."""
  chaos.install('serving.request:drop:1:op=dispatch')
  fe = ServingFrontend(engine, auto_start=False, max_wait_ms=0.0,
                       default_deadline_ms=10_000)
  futs = [fe.submit([s]) for s in (3, 7)]
  assert fe.pump_once() == 0
  for f in futs:
    with pytest.raises(chaos.InjectedFault):
      f.result(1.0)
  reqs = recorder.events('serving.request')
  assert len(reqs) == 2 and not any(e['ok'] for e in reqs)
  assert fe.stats()['failed'] == 2
  assert chaos.active().exhausted()
  chaos.uninstall()
  # the tier recovers: the next pump serves normally
  fut = fe.submit([5])
  assert fe.pump_once() == 1
  np.testing.assert_array_equal(fut.result(1.0).x,
                                engine.offline_reference([5]).x)
  fe.shutdown()


def test_frontend_threaded_end_to_end(engine):
  """The real executor thread: concurrent submitters, everything
  answered, byte-identical to the offline reference."""
  fe = ServingFrontend(engine, auto_start=True, warmup=False,
                       max_wait_ms=1.0, default_deadline_ms=10_000)
  seeds = np.array([3, 5, 9, 17, 33, 60, 2, 41])
  futs = [fe.submit([int(s)]) for s in seeds]
  got = np.concatenate([f.result(10.0).x for f in futs])
  np.testing.assert_array_equal(got,
                                engine.offline_reference(seeds).x)
  fe.shutdown()
  with pytest.raises(AdmissionRejected):
    fe.submit([1])
