"""Dist link + subgraph loaders through the host runtime.

Mirrors reference `test/python/test_dist_link_loader.py` (396) and
`test_dist_subgraph_loader.py` (330) on the all-local pattern:
collocated and mp (subprocess + shm channel) modes run the real stack;
provenance checked arithmetically on a deterministic ring.
"""
import numpy as np
import pytest

from graphlearn_tpu import native
from graphlearn_tpu.distributed import (DistLinkNeighborLoader,
                                        DistSubGraphLoader,
                                        HostDataset,
                                        MpDistSamplingWorkerOptions)

pytestmark = pytest.mark.skipif(not native.available(),
                                reason='native lib unavailable')

N = 40


def _ring(d=4):
  rows = np.repeat(np.arange(N), 2)
  cols = np.stack([(np.arange(N) + 1) % N,
                   (np.arange(N) + 2) % N], 1).reshape(-1)
  feats = np.tile(np.arange(N, dtype=np.float32)[:, None], (1, d))
  return (HostDataset.from_coo(rows, cols, N, node_features=feats,
                               node_labels=np.arange(N) % 4),
          rows, cols)


def _check_link_batches(loader, existing, bs, neg_cap, epochs=2):
  for _ in range(epochs):
    batches = 0
    for batch in loader:
      batches += 1
      eli = np.asarray(batch.metadata['edge_label_index'])
      lab = np.asarray(batch.metadata['edge_label'])
      mask = np.asarray(batch.metadata['edge_label_mask'])
      nodes = np.asarray(batch.node)
      assert eli.shape == (2, bs + neg_cap)
      assert mask.any()
      for j in np.nonzero(mask)[0]:
        u = int(nodes[eli[0, j]])
        v = int(nodes[eli[1, j]])
        if lab[j] >= 1:
          assert (u, v) in existing
        else:
          assert (u, v) not in existing
        # feature value encodes the id
        if batch.x is not None:
          assert float(np.asarray(batch.x)[eli[0, j], 0]) == float(u)
    assert batches == len(loader)


def test_collocated_link_loader_binary():
  ds, rows, cols = _ring()
  existing = set(zip(rows.tolist(), cols.tolist()))
  bs = 8
  loader = DistLinkNeighborLoader(
      ds, [2, 2], (rows[:16], cols[:16]),
      neg_sampling=('binary', 1.0), batch_size=bs, to_device=False)
  _check_link_batches(loader, existing, bs, neg_cap=bs)


def test_mp_link_loader_binary_with_labels():
  ds, rows, cols = _ring()
  existing = set(zip(rows.tolist(), cols.tolist()))
  bs = 8
  loader = DistLinkNeighborLoader(
      ds, [2], (rows[:16], cols[:16]),
      edge_label=np.zeros(16, np.int64),       # user label 0 -> shifted 1
      neg_sampling=('binary', 1.0), batch_size=bs, shuffle=True,
      worker_options=MpDistSamplingWorkerOptions(num_workers=2),
      to_device=False, seed=3)
  try:
    _check_link_batches(loader, existing, bs, neg_cap=bs)
  finally:
    loader.shutdown()


def test_collocated_link_loader_triplet():
  ds, rows, cols = _ring()
  existing = set(zip(rows.tolist(), cols.tolist()))
  bs = 10
  loader = DistLinkNeighborLoader(
      ds, [2], (rows[:10], cols[:10]),
      neg_sampling=('triplet', 2), batch_size=bs, to_device=False)
  batch = next(iter(loader))
  nodes = np.asarray(batch.node)
  src = np.asarray(batch.metadata['src_index'])
  dpos = np.asarray(batch.metadata['dst_pos_index'])
  dneg = np.asarray(batch.metadata['dst_neg_index'])
  pm = np.asarray(batch.metadata['pair_mask'])
  assert dneg.shape == (bs, 2)
  for j in np.nonzero(pm)[0]:
    u = int(nodes[src[j]])
    assert (u, int(nodes[dpos[j]])) in existing
    for t in range(2):
      assert (u, int(nodes[dneg[j, t]])) not in existing


@pytest.mark.parametrize('mp_mode', [False, True])
def test_subgraph_loader_matches_bruteforce(mp_mode):
  ds, rows, cols = _ring()
  edge_set = set(zip(rows.tolist(), cols.tolist()))
  kwargs = {}
  if mp_mode:
    kwargs['worker_options'] = MpDistSamplingWorkerOptions(num_workers=2)
  loader = DistSubGraphLoader(ds, [2], np.arange(N), batch_size=8,
                              to_device=False, **kwargs)
  try:
    seen = 0
    for batch in loader:
      nodes = np.asarray(batch.node)
      nmask = np.asarray(batch.node_mask)
      kept = set(nodes[nmask].tolist())
      ei = np.asarray(batch.edge_index)
      em = np.asarray(batch.edge_mask)
      got = {(int(nodes[ei[0, i]]), int(nodes[ei[1, i]]))
             for i in np.nonzero(em)[0]}
      expect = {(u, v) for u, v in edge_set if u in kept and v in kept}
      assert got == expect
      # mapping locates the seeds
      mapping = np.asarray(batch.metadata['mapping'])
      seeds = np.asarray(batch.batch)
      for j, s in enumerate(seeds):
        if s >= 0:
          assert nodes[mapping[j]] == s
      seen += 1
    assert seen == len(loader)
  finally:
    loader.shutdown()


def test_fractional_neg_amount_capacities():
  """batch_size * neg_amount with fractional part: static caps must
  match the sampler's exact seed construction (regression)."""
  ds, rows, cols = _ring()
  loader = DistLinkNeighborLoader(
      ds, [2], (rows[:20], cols[:20]),
      neg_sampling=('binary', 0.25), batch_size=10, to_device=False)
  for batch in loader:
    eli = np.asarray(batch.metadata['edge_label_index'])
    assert eli.shape[0] == 2
    lab = np.asarray(batch.metadata['edge_label'])
    assert len(lab) == eli.shape[1]
