"""FusedTreeEpoch / TreeSAGE: the scatter-free tree-layout flagship
path — masked-math parity with a numpy reference, learnability,
epoch-length chunk reuse, and the padded-step no-op guard."""
import numpy as np
import jax
import jax.numpy as jnp
import optax
import pytest

from graphlearn_tpu.data import Dataset
from graphlearn_tpu.loader import FusedTreeEpoch
from graphlearn_tpu.models import TreeSAGE, tree_level_sizes

N = 240
CLASSES = 4


def _planted_dataset(seed=0):
  """Community graph: labels recoverable from neighborhoods."""
  rng = np.random.default_rng(seed)
  labels = (np.arange(N) % CLASSES).astype(np.int32)
  rows, cols = [], []
  for v in range(N):
    for _ in range(6):
      if rng.random() < 0.85:
        u = int(rng.choice(np.nonzero(labels == labels[v])[0]))
      else:
        u = int(rng.integers(0, N))
      rows.append(v)
      cols.append(u)
  feats = np.eye(CLASSES, 8, dtype=np.float32)[labels]
  feats += rng.normal(0, 0.4, feats.shape).astype(np.float32)
  return (Dataset()
          .init_graph((np.asarray(rows), np.asarray(cols)),
                      layout='COO', num_nodes=N)
          .init_node_features(feats)
          .init_node_labels(labels)), feats, labels


def test_tree_sage_matches_numpy_reference():
  """One TreeSAGE forward == hand-computed masked tree math."""
  rng = np.random.default_rng(1)
  b, k1, k2, d, h, c = 3, 2, 2, 5, 4, 3
  sizes = tree_level_sizes(b, (k1, k2))
  assert sizes == (3, 6, 12)
  xs = [rng.standard_normal((s, d)).astype(np.float32) for s in sizes]
  masks = [rng.random(s) < 0.8 for s in sizes]
  masks[0][:] = True
  model = TreeSAGE(hidden_features=h, out_features=c, num_layers=2)
  params = model.init(jax.random.key(0),
                      [jnp.asarray(x) for x in xs],
                      [jnp.asarray(m) for m in masks])
  out = np.asarray(model.apply(params,
                               [jnp.asarray(x) for x in xs],
                               [jnp.asarray(m) for m in masks]))

  def dense(p, x, bias=True):
    y = x @ np.asarray(p['kernel'])
    return y + np.asarray(p['bias']) if bias else y

  p = params['params']
  hs = [x * m[:, None] for x, m in zip(xs, masks)]

  def level_step(parent, child, cmask, lp, act):
    k = child.shape[0] // parent.shape[0]
    cd = child.reshape(parent.shape[0], k, -1)
    cm = cmask.reshape(parent.shape[0], k)
    # the mask gates the sum (not just the count): hidden-layer
    # activations of invalid slots are relu(bias) != 0
    mean = ((cd * cm[..., None]).sum(1)
            / np.maximum(cm.sum(1), 1)[:, None])
    y = dense(lp[0], parent) + dense(lp[1], mean, bias=False)
    return np.maximum(y, 0) if act else y

  l0 = (p['layer0_self'], p['layer0_neigh'])
  l1 = (p['layer1_self'], p['layer1_neigh'])
  h0 = level_step(hs[0], hs[1], masks[1], l0, act=True)
  h1 = level_step(hs[1], hs[2], masks[2], l0, act=True)
  ref = level_step(h0, h1, masks[1], l1, act=False)
  np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)


def test_fused_tree_epoch_learns():
  ds, _, labels = _planted_dataset()
  model = TreeSAGE(hidden_features=16, out_features=CLASSES,
                   num_layers=2)
  tx = optax.adam(1e-2)
  fused = FusedTreeEpoch(ds, [4, 3], np.arange(N), model, tx,
                         batch_size=32, shuffle=True, seed=0)
  state = fused.init_state(jax.random.key(0))
  state, first = fused.run(state)
  for _ in range(14):
    state, stats = fused.run(state)
  assert stats['seeds'] == N
  assert stats['loss'] < first['loss']
  assert stats['accuracy'] > 0.6, stats['accuracy']
  acc = fused.evaluate(state.params, np.arange(N))
  assert acc > 0.6, acc


def test_fused_tree_chunked_reuses_one_program():
  """max_steps_per_program: ONE compiled [chunk, B] program serves an
  epoch whose length does not divide the chunk, padded tail steps are
  state no-ops, and losses come back trimmed to real steps."""
  ds, _, _ = _planted_dataset()
  model = TreeSAGE(hidden_features=8, out_features=CLASSES,
                   num_layers=2)
  tx = optax.adam(1e-2)
  # 240/32 = 7.5 -> 8 seed batches; chunk 3 -> dispatches 3+3+2(pad 1)
  fused = FusedTreeEpoch(ds, [3, 2], np.arange(N), model, tx,
                         batch_size=32, shuffle=True, seed=0,
                         max_steps_per_program=3)
  state = fused.init_state(jax.random.key(0))
  state, stats = fused.run(state)
  assert stats.losses.shape[0] == len(fused) == 8
  assert stats['seeds'] == N
  # a second, SHORTER seed set reuses the same compiled program
  fused2 = FusedTreeEpoch(ds, [3, 2], np.arange(64), model, tx,
                          batch_size=32, shuffle=True, seed=0,
                          max_steps_per_program=3)
  fused2._compiled = fused._compiled       # shared executable cache
  state2 = fused2.init_state(jax.random.key(1))
  state2, stats2 = fused2.run(state2)
  assert stats2.losses.shape[0] == len(fused2) == 2
  assert stats2['seeds'] == 64


def test_fused_tree_padded_step_is_noop():
  """A dispatch whose steps are ALL padding must leave params
  bit-identical (adam moments included)."""
  ds, _, _ = _planted_dataset()
  model = TreeSAGE(hidden_features=8, out_features=CLASSES,
                   num_layers=2)
  tx = optax.adam(1e-2)
  fused = FusedTreeEpoch(ds, [3, 2], np.arange(N), model, tx,
                         batch_size=32, seed=0)
  state = fused.init_state(jax.random.key(0))
  pad = jnp.full((2, 32), -1, jnp.int32)
  before = jax.tree_util.tree_map(np.asarray, state.params)
  state2, *_ = fused._compiled(state, pad, jax.random.key(5),
                               fused._dev, False)
  after = jax.tree_util.tree_map(np.asarray, state2.params)
  jax.tree_util.tree_map(np.testing.assert_array_equal, before, after)


def test_tree_level_count_validation():
  ds, _, _ = _planted_dataset()
  model = TreeSAGE(hidden_features=8, out_features=CLASSES,
                   num_layers=3)
  with pytest.raises(ValueError, match='num_layers'):
    FusedTreeEpoch(ds, [3, 2], np.arange(N), model, optax.adam(1e-2),
                   batch_size=32)


def test_fused_tree_bf16_learns():
  """bf16 COMPUTE parity evidence for the artifact's
  fused_epoch_secs_bf16: the planted-community task reaches the same
  accuracy bar with TreeSAGE(dtype=bfloat16) as with f32 (params and
  logits stay f32 — only the MXU work narrows)."""
  ds, _, _ = _planted_dataset()
  model = TreeSAGE(hidden_features=16, out_features=CLASSES,
                   num_layers=2, dtype=jnp.bfloat16)
  tx = optax.adam(1e-2)
  fused = FusedTreeEpoch(ds, [4, 3], np.arange(N), model, tx,
                         batch_size=32, shuffle=True, seed=0)
  state = fused.init_state(jax.random.key(0))
  for _ in range(15):
    state, stats = fused.run(state)
  assert stats['accuracy'] > 0.6, stats['accuracy']
  acc = fused.evaluate(state.params, np.arange(N))
  assert acc > 0.6, acc
