"""Distributed sampling tests on the virtual 8-device CPU mesh.

The TPU translation of the reference's all-local distributed tests
(`test/python/test_dist_neighbor_loader.py` + `dist_test_utils.py`):
a deterministic ring graph partitioned across devices, features that
encode node ids, correctness asserted arithmetically — the real
collective stack runs, no mocks.
"""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

from graphlearn_tpu.parallel import (DistDataset, DistNeighborLoader,
                                     DistNeighborSampler, make_mesh)

N = 64  # ring: v -> v+1, v -> v+2 (mod N)


def _ring_dist_dataset(num_parts=4, contiguous=False, with_feats=True):
  rows = np.concatenate([np.arange(N), np.arange(N)])
  cols = np.concatenate([(np.arange(N) + 1) % N, (np.arange(N) + 2) % N])
  feats = (np.arange(N, dtype=np.float32)[:, None]
           * np.ones((1, 4), np.float32)) if with_feats else None
  labels = (np.arange(N) % 5).astype(np.int32)
  if contiguous:
    node_pb = (np.arange(N) * num_parts // N).astype(np.int32)
  else:
    node_pb = (np.arange(N) % num_parts).astype(np.int32)  # interleaved
  return DistDataset.from_full_graph(
      num_parts, rows, cols, node_feat=feats, node_label=labels,
      num_nodes=N, node_pb=node_pb)


def test_dist_graph_layout():
  ds = _ring_dist_dataset(4)
  g = ds.graph
  assert g.num_partitions == 4
  assert g.num_nodes == N
  np.testing.assert_array_equal(g.bounds, [0, 16, 32, 48, 64])
  # each node has out-degree 2 in its owner's local CSR.
  for p in range(4):
    deg = np.diff(g.indptr[p])[:16]
    np.testing.assert_array_equal(deg, 2)


def test_dist_one_hop_edges_correct():
  ds = _ring_dist_dataset(4)
  sampler = DistNeighborSampler(ds, [2], mesh=make_mesh(4), seed=0)
  # each device seeds 4 of its own... seeds can be ANY nodes; use a
  # spread so every device requests remote partitions.
  seeds = ds.old2new[np.arange(16).reshape(4, 4)]
  out = sampler.sample_from_nodes(seeds)
  nodes = np.asarray(out['node'])       # [P, cap] relabeled ids
  rows = np.asarray(out['row'])
  cols = np.asarray(out['col'])
  new2old = ds.new2old
  for p in range(4):
    m = rows[p] >= 0
    assert m.any()
    r_old = new2old[nodes[p][rows[p][m]]]
    c_old = new2old[nodes[p][cols[p][m]]]
    # ring invariant: neighbor = seed + 1 or + 2 (mod N).
    d = (r_old - c_old) % N
    assert np.isin(d, [1, 2]).all(), d


def test_dist_feature_and_label_provenance():
  ds = _ring_dist_dataset(4)
  sampler = DistNeighborSampler(ds, [2, 2], mesh=make_mesh(4), seed=0)
  seeds = ds.old2new[np.arange(32).reshape(4, 8)]
  out = sampler.sample_from_nodes(seeds)
  nodes = np.asarray(out['node'])
  x = np.asarray(out['x'])
  y = np.asarray(out['y'])
  for p in range(4):
    m = nodes[p] >= 0
    old_ids = ds.new2old[nodes[p][m]]
    # feature rows encode the ORIGINAL node id — remote gathers
    # included (the dist_test_utils provenance trick).
    np.testing.assert_allclose(x[p][m][:, 0], old_ids)
    np.testing.assert_allclose(x[p][~m], 0)
    np.testing.assert_array_equal(y[p][m], old_ids % 5)


def test_dist_sampling_matches_single_chip_statistics():
  # every sampled edge must be a real edge; seeds keep slots 0..B-1.
  ds = _ring_dist_dataset(8)
  sampler = DistNeighborSampler(ds, [2], mesh=make_mesh(8), seed=0)
  seeds = ds.old2new[np.arange(64).reshape(8, 8)]
  out = sampler.sample_from_nodes(seeds)
  sl = np.asarray(out['seed_local'])
  for p in range(8):
    np.testing.assert_array_equal(sl[p], np.arange(8))


def test_dist_loader_epoch_and_training():
  import optax
  from graphlearn_tpu.models import GraphSAGE, create_train_state
  from graphlearn_tpu.parallel import make_dp_supervised_step, replicate
  from graphlearn_tpu.parallel.dp import make_mesh as mm

  num_parts = 4
  mesh = make_mesh(num_parts)
  ds = _ring_dist_dataset(num_parts)
  bs = 4
  loader = DistNeighborLoader(ds, [2, 2], np.arange(N), batch_size=bs,
                              shuffle=True, mesh=mesh, seed=0)
  batches = list(loader)
  assert len(batches) == len(loader) == N // (bs * num_parts)
  b0 = batches[0]
  assert b0.x.shape[0] == num_parts
  assert b0.edge_index.shape[1] == 2

  model = GraphSAGE(hidden_features=8, out_features=5, num_layers=2)
  tx = optax.adam(1e-2)
  single = jax.tree_util.tree_map(lambda v: v[0], b0)
  state, _ = create_train_state(model, jax.random.key(0), single, tx)
  step = make_dp_supervised_step(model.apply, tx, bs, mesh)
  state = replicate(state, mesh)
  losses = []
  for _ in range(3):
    for batch in loader:
      state, loss, _ = step(state, batch)
      losses.append(float(loss))
  assert np.isfinite(losses).all()
  assert losses[-1] < losses[0]


def test_cache_overlay_exact():
  """Cache hits overlay the exchanged rows; results match the uncached
  gather bit-exactly (cache rows mirror the table)."""
  from graphlearn_tpu.parallel.dist_sampler import (cache_overlay,
                                                    dist_gather)
  from graphlearn_tpu.parallel.dist_data import CACHE_PAD_ID
  from graphlearn_tpu.parallel.shard_map_compat import shard_map
  from jax.sharding import PartitionSpec as P

  num_parts = 4
  mesh = make_mesh(num_parts)
  rows_max = N // num_parts
  bounds = np.arange(num_parts + 1) * rows_max
  shards = (np.arange(N, dtype=np.float32).reshape(num_parts, rows_max, 1)
            * np.ones((1, 1, 4), np.float32))
  # each device caches 3 rows of the NEXT partition
  cids = np.full((num_parts, 3), CACHE_PAD_ID, np.int32)
  crows = np.zeros((num_parts, 3, 4), np.float32)
  for p in range(num_parts):
    ids = (bounds[(p + 1) % num_parts] + np.arange(3)).astype(np.int32)
    cids[p] = np.sort(ids)
    crows[p] = ids[:, None].astype(np.float32)
  ids_req = np.stack([np.arange(p, p + 8, dtype=np.int32) * 7 % N
                      for p in range(num_parts)])

  def run(shards_s, bounds_r, ids_s, cids_s, crows_s):
    ref = dist_gather(shards_s[0], bounds_r, ids_s[0], 'data', num_parts)
    out = cache_overlay(ref, ids_s[0], cids_s[0], crows_s[0])
    return out[None], ref[None]

  sh = P('data')
  f = jax.jit(shard_map(run, mesh=mesh,
                        in_specs=(sh, P(), sh, sh, sh),
                        out_specs=(sh, sh)))
  out, ref = f(shards, bounds, ids_req, cids, crows)
  np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))
  # value correctness: row value == id
  np.testing.assert_array_equal(np.asarray(out)[..., 0],
                                ids_req.astype(np.float32))


def test_partition_dir_cache_roundtrip(tmp_path):
  """cache_ratio partitions -> DistDataset with a live cache -> loader
  features still exact (the cat_feature_cache flow, end to end)."""
  from graphlearn_tpu.partition import RandomPartitioner
  rows = np.concatenate([np.arange(N), np.arange(N)])
  cols = np.concatenate([(np.arange(N) + 1) % N, (np.arange(N) + 2) % N])
  feats = np.arange(N, dtype=np.float32)[:, None] * np.ones((1, 4),
                                                            np.float32)
  labels = (np.arange(N) % 5).astype(np.int32)
  RandomPartitioner(tmp_path, 4, N, (rows, cols), feats, labels,
                    cache_ratio=0.2, seed=0).partition()
  ds = DistDataset.from_partition_dir(tmp_path)
  assert ds.node_features.has_cache
  mesh = make_mesh(4)
  loader = DistNeighborLoader(ds, [2], np.arange(N), batch_size=4,
                              mesh=mesh, seed=0)
  for batch in loader:
    nodes = np.asarray(batch.node)
    x = np.asarray(batch.x)
    new2old = ds.new2old
    for p in range(4):
      valid = nodes[p] >= 0
      np.testing.assert_array_equal(
          x[p][valid][:, 0], new2old[nodes[p][valid]].astype(np.float32))


def test_exchange_capacity_lossless_with_slack():
  """With balanced buckets and 2x slack the capped exchange returns
  exactly the uncapped results (bytes shrink, nothing drops)."""
  ds = _ring_dist_dataset(4)
  mesh = make_mesh(4)
  a = DistNeighborSampler(ds, [2, 2], mesh=mesh, seed=0)
  b = DistNeighborSampler(ds, [2, 2], mesh=mesh, seed=0,
                          exchange_slack=2.0)
  seeds = ds.old2new[np.arange(16).reshape(4, 4)]
  oa = a.sample_from_nodes(seeds)
  ob = b.sample_from_nodes(seeds)
  for k in ('node', 'row', 'col', 'x', 'y'):
    np.testing.assert_array_equal(np.asarray(oa[k]), np.asarray(ob[k]))


def test_bucket_capacity_drops_overflow_not_valid_ids():
  """Direct bucket_by_owner contract under a cap smaller than one
  owner's load: exactly `cap` ids of the hot owner survive, invalid
  ids never consume slots, and dropped ids get slot_j == -1."""
  from functools import partial
  from graphlearn_tpu.parallel.dist_sampler import bucket_by_owner
  from graphlearn_tpu.parallel.shard_map_compat import shard_map
  from jax.sharding import PartitionSpec as P

  num_parts = 2
  mesh = make_mesh(num_parts)
  # device row: 5 ids for owner 1, one invalid FIRST, 2 for owner 0
  ids = np.tile(np.array([-1, 10, 11, 12, 13, 14, 2, 3], np.int32),
                (num_parts, 1))
  owner = np.tile(np.array([0, 1, 1, 1, 1, 1, 0, 0], np.int32),
                  (num_parts, 1))

  def run(ids_s, owner_s):
    send, slot_p, slot_j = bucket_by_owner(
        ids_s[0], owner_s[0], num_parts,
        jax.lax.axis_index('data'), capacity=3)
    return send[None], slot_p[None], slot_j[None]

  sh = P('data')
  f = jax.jit(shard_map(run, mesh=mesh, in_specs=(sh, sh),
                        out_specs=(sh, sh, sh)))
  send, slot_p, slot_j = (np.asarray(v) for v in f(ids, owner))
  d = 0
  # owner 0 had 2 valid ids (+1 invalid that must NOT take a slot)
  assert set(send[d, 0][send[d, 0] >= 0]) == {2, 3}
  # owner 1 had 5 ids, cap 3: exactly the first 3 survive
  np.testing.assert_array_equal(send[d, 1], [10, 11, 12])
  # dropped: ids 13, 14 and the invalid id -> slot_j -1
  dropped = slot_j[d] < 0
  np.testing.assert_array_equal(ids[0][dropped], [-1, 13, 14])
  # surviving slots point at their id
  for i in np.nonzero(~dropped)[0]:
    assert send[d, slot_p[d, i], slot_j[d, i]] == ids[0, i]


def test_exchange_capacity_drops_are_masked():
  """A skewed workload (every seed targets partition 0's range) with a
  small slack: real drops happen, survivors stay correct.  The ring is
  sized so the skewed bucket exceeds the MIN_EXCHANGE_CAP floor (tiny
  exchanges are deliberately exact)."""
  n = 1024
  rows = np.concatenate([np.arange(n), np.arange(n)])
  cols = np.concatenate([(np.arange(n) + 1) % n, (np.arange(n) + 2) % n])
  node_pb = (np.arange(n) * 4 // n).astype(np.int32)
  ds = DistDataset.from_full_graph(4, rows, cols, num_nodes=n,
                                   node_pb=node_pb)
  mesh = make_mesh(4)
  s = DistNeighborSampler(ds, [2], mesh=mesh, seed=0,
                          exchange_slack=0.5)
  # 256 seeds per device, ALL in partition 0's range [0, 256): buckets
  # are maximally skewed, the cap max(256/4*0.5, 64) = 64 binds hard
  seeds = ds.old2new[np.tile(np.arange(256), (4, 1))]
  out = s.sample_from_nodes(seeds)
  rows_l = np.asarray(out['row'])
  cols_l = np.asarray(out['col'])
  nodes = np.asarray(out['node'])
  new2old = ds.new2old
  survived = 0
  for p in range(4):
    m = rows_l[p] >= 0
    for r, c in zip(rows_l[p][m], cols_l[p][m]):
      u = new2old[nodes[p, c]]
      v = new2old[nodes[p, r]]
      assert (v - u) % n in (1, 2)     # still a real ring edge
      survived += 1
  # the uncapped run yields 2 edges/seed; drops must actually occur
  uncapped = DistNeighborSampler(ds, [2], mesh=mesh, seed=0)
  out_u = uncapped.sample_from_nodes(seeds)
  full = int((np.asarray(out_u['row']) >= 0).sum())
  assert 0 < survived < full
  # each dropped frontier id loses exactly min(deg, k) = 2 edges
  st = s.exchange_stats(tick_metrics=False)
  assert st['dist.frontier.dropped'] * 2 == full - survived
