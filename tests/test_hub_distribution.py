"""Distribution tests at hub degrees (VERDICT r3 weak #7).

`ops/neighbor.py::sample_one_hop` has three degree regimes:
``deg <= k`` takes every neighbor; ``k < deg <= W`` samples EXACTLY
without replacement (Gumbel top-k over the W-wide window); ``deg > W``
falls back to k independent uniform draws WITH replacement (documented
deviation: expected colliding slots < k/16, duplicates later deduped
by the inducer).  These tests pin the STATISTICS of both sampling
regimes on a hub node:

  * marginal uniformity over the hub's neighbors (chi-square against
    the uniform null at ~4-sigma thresholds);
  * the window path never emits a duplicate within a row;
  * the with-replacement path's per-row collision rate sits in a
    confidence band around its analytic expectation k(k-1)/(2*deg).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from graphlearn_tpu.ops.neighbor import default_window, sample_one_hop

K = 8


def _hub_csr(deg: int):
  """Node 0 is a hub with out-neighbors 1..deg; all others empty."""
  n = deg + 1
  indptr = np.zeros(n + 1, np.int64)
  indptr[1:] = deg
  indices = np.arange(1, deg + 1, dtype=np.int32)
  return jnp.asarray(indptr), jnp.asarray(indices)


def _frequencies(indptr, indices, deg, calls, batch, seed):
  seeds = jnp.zeros(batch, jnp.int32)
  counts = np.zeros(deg + 1, np.int64)
  dup_slots = 0
  base = jax.random.key(seed)
  for i in range(calls):
    res = sample_one_hop(indptr, indices, seeds, K,
                         jax.random.fold_in(base, i))
    nb = np.asarray(res.nbrs)
    assert np.asarray(res.mask).all()          # deg > k: full rows
    counts += np.bincount(nb.reshape(-1), minlength=deg + 1)
    for row in nb:
      dup_slots += K - len(np.unique(row))
  return counts[1:], dup_slots, calls * batch


def test_hub_with_replacement_uniform_and_bounded_collisions():
  """deg > W regime: uniform marginals, collision rate at its
  analytic expectation (and far under the documented k/16 bound)."""
  w = default_window(K)
  deg = 4 * w                                   # 256 with K=8
  indptr, indices = _hub_csr(deg)
  counts, dup_slots, rows = _frequencies(indptr, indices, deg,
                                         calls=40, batch=256, seed=0)
  mean = counts.sum() / deg
  chi2 = float(((counts - mean) ** 2 / mean).sum())
  # df = deg-1 = 255: mean 255, sd ~22.6; 380 is ~5.5 sigma
  assert chi2 < 380, f'non-uniform hub marginals: chi2={chi2:.1f}'
  rate = dup_slots / rows
  expect = K * (K - 1) / (2 * deg)              # ~0.109 duplicate
  assert rate < K / 16, rate                    # slots per row
  assert 0.3 * expect < rate < 3 * expect, (rate, expect)


def test_window_path_exact_without_replacement():
  """k < deg <= W regime: NEVER a duplicate in a row, uniform
  marginals, full support coverage."""
  w = default_window(K)
  indptr, indices = _hub_csr(w)
  seeds = jnp.zeros(128, jnp.int32)
  counts = np.zeros(w + 1, np.int64)
  base = jax.random.key(1)
  for i in range(30):
    res = sample_one_hop(indptr, indices, seeds, K,
                         jax.random.fold_in(base, i))
    nb = np.asarray(res.nbrs)
    for row in nb:
      assert len(np.unique(row)) == K, 'duplicate in exact regime'
    counts += np.bincount(nb.reshape(-1), minlength=w + 1)
  counts = counts[1:]
  assert (counts > 0).all(), 'neighbor never sampled'
  mean = counts.sum() / w
  chi2 = float(((counts - mean) ** 2 / mean).sum())
  # df = w-1 = 63: mean 63, sd ~11.2; 130 is ~6 sigma
  assert chi2 < 130, f'non-uniform window marginals: chi2={chi2:.1f}'
