"""Env-knob documentation enforcement (ISSUE 6 satellite): every
``GLT_*`` knob referenced anywhere in the package or bench drivers
must appear in the ``benchmarks/README.md`` knob tables — the same
drift-proofing contract `test_event_schema.py` applies to event kinds
(PR 4/5 both shipped knobs the docs never learned about)."""
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent / 'tools'))

from check_env_knobs import (documented_knobs, knob_references,
                             undocumented)


def test_every_knob_documented():
  missing = undocumented()
  assert not missing, (
      f'GLT_* knobs referenced in code but missing from '
      f'benchmarks/README.md: {missing} — add a row to the knob '
      'tables (an undocumented knob is a feature only its author can '
      'use)')


def test_scan_actually_sees_known_knobs():
  """The scanner must keep finding the long-standing knobs — an AST
  regression that finds nothing would make the drift test pass
  vacuously."""
  refs = knob_references()
  for knob in ('GLT_FAULT_PLAN', 'GLT_COLD_CACHE_ROWS',
               'GLT_SNAPSHOT_DIR', 'GLT_DISPATCH_DEADLINE'):
    assert knob in refs, f'{knob} not found by the AST scan'
  assert len(documented_knobs()) >= 20
