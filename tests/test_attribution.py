"""Traffic-attribution tests (ISSUE 16 leg 3): the per-(src, dst)
exchange matrices accumulated by the mesh engine, the byte/hotness
rollup feeding ``report.py --attribution`` and the regress guards,
snapshot round-trips (including pre-attribution state compat), the
GNS per-range hotness sketch, and the report renderings.

Same virtual 8-device CPU mesh + deterministic ring graph as
test_dist_sampler.py, so every expected count is derivable by hand:
with the interleaved partition book (owner = v mod 4) and fanout
[2], node v's neighbors v+1 and v+2 land in ranges (v+1)%4 and
(v+2)%4 — mostly remote by construction.
"""
import json

import numpy as np
import jax.numpy as jnp
import pytest

from graphlearn_tpu.ops.gns import DecayedSketch, register_hotness_gauges
from graphlearn_tpu.parallel import DistNeighborSampler, make_mesh
from graphlearn_tpu.parallel.exchange import dest_histogram
from graphlearn_tpu.telemetry import LiveRegistry, Metrics
from graphlearn_tpu.telemetry.report import (find_attribution,
                                             format_attribution,
                                             format_varz_diff,
                                             load_varz_snapshot)

from test_dist_sampler import _ring_dist_dataset


def _sampled_ring_sampler():
  ds = _ring_dist_dataset(4)
  s = DistNeighborSampler(ds, [2], mesh=make_mesh(4), seed=0)
  seeds = ds.old2new[np.arange(16).reshape(4, 4)]
  s.sample_from_nodes(seeds)
  return ds, s


def test_attribution_matrices_ring_exact():
  _, s = _sampled_ring_sampler()
  fr, ft = s.attribution_matrices()
  # frontier exchange: each device requests one id from every range
  np.testing.assert_array_equal(fr, np.ones((4, 4), np.int64))
  # feature exchange: each device gathers its 4 seeds + 2 unique
  # frontier nodes, and the interleaved book spreads every device's
  # gather [2, 2, 1, 1] across ranges 0..3
  np.testing.assert_array_equal(ft, np.tile([2, 2, 1, 1], (4, 1)))
  assert ft.dtype == np.int64 and np.trace(ft) == 6
  # draining twice without sampling again returns the SAME totals
  fr2, ft2 = s.attribution_matrices()
  np.testing.assert_array_equal(fr, fr2)
  np.testing.assert_array_equal(ft, ft2)


def test_attribution_stats_rollup():
  _, s = _sampled_ring_sampler()
  st = s.attribution_stats(tick_metrics=False)
  assert st['num_parts'] == 4
  assert st['feature_row_bytes'] == 16        # 4 float32 features
  ids = np.asarray(st['frontier_ids']) + np.asarray(st['feature_ids'])
  assert st['local_ids'] == int(np.trace(ids))
  assert st['cross_ids'] == int(ids.sum() - np.trace(ids))
  assert st['cross_partition_ids_frac'] == pytest.approx(0.75)
  assert st['cross_partition_bytes_frac'] == pytest.approx(0.75)
  # byte weighting: frontier ids 4 B, feature ids one 16 B row
  bm = np.asarray(st['bytes_matrix'])
  np.testing.assert_array_equal(
      bm, np.asarray(st['frontier_ids']) * 4
      + np.asarray(st['feature_ids']) * 16)
  # no GNS sketch on this sampler: hotness falls back to measured
  # column mass, K = max(1, P // 4) = 1
  assert st['hotness_source'] == 'exchange'
  assert st['top_k'] == 1 and len(st['hot_ranges']) == 1
  assert st['hot_range_coverage'] == pytest.approx(
      st['hot_ranges'][0]['share'])
  json.dumps(st)                      # pure-Python, JSON-safe


def test_attribution_snapshot_roundtrip():
  ds, s = _sampled_ring_sampler()
  fr, ft = s.attribution_matrices()
  packed = s._stats_state()
  s2 = DistNeighborSampler(ds, [2], mesh=make_mesh(4), seed=0)
  s2._load_stats_state(packed)
  fr2, ft2 = s2.attribution_matrices()
  np.testing.assert_array_equal(fr, fr2)
  np.testing.assert_array_equal(ft, ft2)


def test_pre_attribution_snapshot_restores_cold():
  """A snapshot taken before the attribution tail existed (13 int64s:
  7 exchange counters + 6 cold-tier counters) restores the counters
  and restarts the matrix cold — never a reshape crash."""
  ds, s = _sampled_ring_sampler()
  old = np.arange(13, dtype=np.int64)
  s._load_stats_state(old)
  fr, ft = s.attribution_matrices()
  np.testing.assert_array_equal(fr, np.zeros((4, 4), np.int64))
  np.testing.assert_array_equal(ft, np.zeros((4, 4), np.int64))


def test_attribution_tick_metrics_watermark():
  """`attribution_stats(tick_metrics=True)` ticks the global
  exchange.{local,cross}_ids_total counters by the DELTA since the
  last report — calling twice must not double-count."""
  from graphlearn_tpu.telemetry.live import live
  _, s = _sampled_ring_sampler()
  c_local = live.counter('exchange.local_ids_total')
  c_cross = live.counter('exchange.cross_ids_total')
  base = (c_local.value(), c_cross.value())
  st = s.attribution_stats()
  assert c_local.value() - base[0] == st['local_ids']
  assert c_cross.value() - base[1] == st['cross_ids']
  s.attribution_stats()               # watermarked: no new ticks
  assert c_local.value() - base[0] == st['local_ids']
  assert c_cross.value() - base[1] == st['cross_ids']


def test_dest_histogram_matches_numpy():
  bounds = np.array([0, 16, 32, 48, 64], np.int64)

  def owner(ids):
    return jnp.searchsorted(jnp.asarray(bounds), ids, side='right') - 1

  ids = jnp.array([0, 5, 17, 33, 50, 63, -1, -1], jnp.int32)
  h = np.asarray(dest_histogram(ids, owner, 4))
  ref = np.bincount(
      np.searchsorted(bounds, [0, 5, 17, 33, 50, 63], side='right') - 1,
      minlength=4)
  np.testing.assert_array_equal(h, ref)
  assert h.sum() == 6                 # invalid ids route to no range


def test_gns_sketch_range_mass_and_hot_ranges():
  bounds = np.array([0, 16, 32, 48, 64], np.int64)
  sk = DecayedSketch(slots=64, decay=0.5, bounds=bounds)
  sk.update(np.array([1, 2, 3, 17, 50], np.int64))       # 3/1/0/1
  sk.update(np.array([4, 5], np.int64))                  # decayed +2
  assert sk.range_mass is not None and len(sk.range_mass) == 4
  # round 1 decayed once: [3, 1, 0, 1] * 0.5 + [2, 0, 0, 0]
  np.testing.assert_allclose(sk.range_mass, [3.5, 0.5, 0.0, 0.5])
  hot = sk.hot_ranges(2)
  assert hot[0][0] == 0 and hot[0][1] == pytest.approx(3.5 / 4.5)
  # state round-trip carries the mass; an OLD state without the
  # range_mass key restores with the mass intact (no crash)
  st = sk.state_dict()
  sk2 = DecayedSketch(slots=64, decay=0.5, bounds=bounds)
  sk2.load_state_dict(st)
  np.testing.assert_allclose(sk2.range_mass, sk.range_mass)
  del st['range_mass']
  sk2.load_state_dict(st)             # pre-attribution state: ok


def test_register_hotness_gauges_top_k_only():
  bounds = np.array([0, 16, 32, 48, 64], np.int64)
  sk = DecayedSketch(slots=64, decay=1.0, bounds=bounds)
  sk.update(np.array([1, 2, 3, 17], np.int64))           # 3/1/0/0

  reg = LiveRegistry(store=Metrics(), strict=True)
  fns = register_hotness_gauges(lambda: [sk], 4, registry=reg)
  assert len(fns) == 4
  text = reg.prometheus_text()
  # only the top-K (K = max(1, 4 // 4) = 1) ranges sample a value
  assert 'glt_gns_range_hotness{partition="0"} 0.75' in text
  assert text.count('glt_gns_range_hotness{') == 1


def test_report_attribution_render_and_find(tmp_path):
  _, s = _sampled_ring_sampler()
  st = s.attribution_stats(tick_metrics=False)
  # whole-file JSON with an 'attribution' key (the bench artifact lift)
  art = tmp_path / 'row.json'
  art.write_text(json.dumps(
      {'num_parts': 4, 'attribution': st,
       'layouts': {'dense': {'padding_waste_pct': 12.5,
                             'drop_rate_pct': 0.0}}}))
  stats, layouts = find_attribution(str(art))
  assert stats['num_parts'] == 4 and layouts
  text = format_attribution(stats, layouts)
  assert 'traffic attribution (P=4' in text
  assert 'cross_frac=0.75' in text
  assert 'src0' in text and 'r3' in text
  assert 'hot ranges' in text and 'source=exchange' in text
  # JSONL line-scan path: the highest-P envelope row wins
  rows = tmp_path / 'records.jsonl'
  small = dict(st, num_parts=2)
  rows.write_text(
      json.dumps({'attribution': small}) + '\n'
      + json.dumps({'attribution': st}) + '\n')
  stats2, _ = find_attribution(str(rows))
  assert stats2['num_parts'] == 4
  with pytest.raises(SystemExit):
    empty = tmp_path / 'none.jsonl'
    empty.write_text('{"no": "attribution"}\n')
    find_attribution(str(empty))


def test_report_varz_diff(tmp_path):
  base = {'ts': 1.0, 'metrics': {
      'dist.exchange.cross_ids': 10.0, 'span.step.hist.count': 4.0,
      'span.step.hist.b03': 2.0, 'span.step.hist.secs': 0.5}}
  cur = {'ts': 11.0, 'metrics': {
      'dist.exchange.cross_ids': 30.0, 'span.step.hist.count': 8.0,
      'span.step.hist.b03': 6.0, 'span.step.hist.secs': 1.0,
      'dist.new_metric': 1.0}}
  b = tmp_path / 'base.json'
  c = tmp_path / 'cur.json'
  b.write_text(json.dumps(base))
  c.write_text(json.dumps(cur))
  assert load_varz_snapshot(str(b)) == base
  text = format_varz_diff(load_varz_snapshot(str(c)),
                          load_varz_snapshot(str(b)))
  assert 'dist.exchange.cross_ids' in text and '+20' in text
  assert 'dist.new_metric' in text
  # per-bucket histogram keys roll up — count/secs survive
  assert 'b03' not in text
  assert 'span.step.hist.count' in text
  # a JSONL trace is NOT a varz snapshot
  j = tmp_path / 'trace.jsonl'
  j.write_text('{"kind": "x"}\n{"kind": "y"}\n')
  assert load_varz_snapshot(str(j)) is None
