"""Device-native construction path: `Graph.from_device_arrays`,
device `Feature`, device labels — the zero-upload setup `bench.py`
uses on tunneled chips (benchmarks/common.build_graph_csr_device).

The contract under test: a Dataset built from device arrays behaves
identically to one built from the same arrays via the host path.
"""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

from graphlearn_tpu.data import Dataset
from graphlearn_tpu.loader import NeighborLoader


def _device_dataset(n, indptr, indices, feats, labels):
  return (Dataset()
          .init_graph((jnp.asarray(indptr), jnp.asarray(indices)),
                      layout='CSR', num_nodes=n)
          .init_node_features(jnp.asarray(feats))
          .init_node_labels(jnp.asarray(labels)))


def _host_dataset(n, indptr, indices, feats, labels):
  return (Dataset()
          .init_graph((indptr, indices), layout='CSR', num_nodes=n)
          .init_node_features(feats)
          .init_node_labels(labels))


@pytest.fixture(scope='module')
def tiny():
  rng = np.random.default_rng(0)
  n, e = 200, 1600
  rows = rng.integers(0, n, e)
  cols = rng.integers(0, n, e).astype(np.int64)
  # canonical sorted-CSR: the device path trusts its input as-is
  order = np.lexsort((cols, rows))
  rows, cols = rows[order], cols[order]
  indptr = np.searchsorted(rows, np.arange(n + 1)).astype(np.int64)
  feats = rng.random((n, 8), np.float32)
  labels = rng.integers(0, 5, n).astype(np.int32)
  return n, indptr, cols, feats, labels


def test_device_graph_metadata(tiny):
  n, indptr, cols, feats, labels = tiny
  ds = _device_dataset(n, indptr, cols, feats, labels)
  g = ds.get_graph()
  assert g.num_nodes == n
  assert g.num_edges == len(cols)
  assert g.max_degree == int(np.max(np.diff(indptr)))
  assert g.indices.dtype == jnp.int32


def test_device_feature_matches_host(tiny):
  n, indptr, cols, feats, labels = tiny
  dev = _device_dataset(n, indptr, cols, feats, labels)
  host = _host_dataset(n, indptr, cols, feats, labels)
  ids = jnp.asarray([0, 3, -1, n - 1], jnp.int32)
  np.testing.assert_allclose(np.asarray(dev.node_features[ids]),
                             np.asarray(host.node_features[ids]))
  # host-side access works through the shim (one lazy pull)
  np.testing.assert_allclose(dev.node_features.host_get([2, 5]),
                             host.node_features.host_get([2, 5]))


def test_device_feature_rejects_cold_tier(tiny):
  n, indptr, cols, feats, labels = tiny
  with pytest.raises(ValueError, match='split_ratio'):
    Dataset().init_node_features(jnp.asarray(feats), split_ratio=0.5)


def test_device_loader_parity(tiny):
  """Same seed → identical batches from the device- and host-built
  datasets (the sampler consumes the same CSR either way)."""
  n, indptr, cols, feats, labels = tiny
  dev = _device_dataset(n, indptr, cols, feats, labels)
  host = _host_dataset(n, indptr, cols, feats, labels)
  seeds = np.arange(0, n, 2)
  for ds_a, ds_b in ((dev, host),):
    la = NeighborLoader(ds_a, [3, 2], seeds, batch_size=32, shuffle=False)
    lb = NeighborLoader(ds_b, [3, 2], seeds, batch_size=32, shuffle=False)
    for ba, bb in zip(la, lb):
      np.testing.assert_array_equal(np.asarray(ba.node),
                                    np.asarray(bb.node))
      np.testing.assert_allclose(np.asarray(ba.x), np.asarray(bb.x))
      np.testing.assert_array_equal(np.asarray(ba.y), np.asarray(bb.y))


def test_build_graph_csr_device_valid():
  import sys, os
  sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..'))
  from benchmarks.common import build_graph_csr_device
  n = 500
  indptr, indices, eids = build_graph_csr_device(num_nodes=n, avg_deg=4,
                                                 seed=1)
  indptr_h = np.asarray(indptr)
  assert indptr_h[0] == 0 and indptr_h[-1] == n * 4
  assert np.all(np.diff(indptr_h) >= 0)
  assert np.asarray(indices).min() >= 0
  assert np.asarray(indices).max() < n
  # determinism across calls (cross-session comparability contract)
  indptr2, indices2, _ = build_graph_csr_device(num_nodes=n, avg_deg=4,
                                                seed=1)
  np.testing.assert_array_equal(np.asarray(indptr), np.asarray(indptr2))
  np.testing.assert_array_equal(np.asarray(indices), np.asarray(indices2))


def test_device_native_hetero_dataset():
  """Per-etype device CSR + device feature/label dicts (the bench's
  hetero session path) behave like the host construction."""
  rng = np.random.default_rng(2)
  nu, ni, e = 60, 40, 300
  rows = rng.integers(0, nu, e)
  cols = rng.integers(0, ni, e)
  order = np.lexsort((cols, rows))
  rows, cols = rows[order], cols[order]
  indptr = np.searchsorted(rows, np.arange(nu + 1)).astype(np.int64)
  fu = rng.random((nu, 6), np.float32)
  fi = rng.random((ni, 6), np.float32)
  lab = rng.integers(0, 3, nu).astype(np.int32)
  et = ('u', 'to', 'i')
  ds = (Dataset()
        .init_graph({et: (jnp.asarray(indptr), jnp.asarray(cols))},
                    layout='CSR', num_nodes={'u': nu, 'i': ni})
        .init_node_features({'u': jnp.asarray(fu), 'i': jnp.asarray(fi)})
        .init_node_labels({'u': jnp.asarray(lab)}))
  g = ds.get_graph(et)
  assert g.num_edges == e
  assert ds.num_nodes_dict() == {'u': nu, 'i': ni}
  np.testing.assert_array_equal(
      np.asarray(ds.get_node_label_device('u')), lab)
  ids = jnp.asarray([0, 5, -1], jnp.int32)
  np.testing.assert_allclose(
      np.asarray(ds.node_features['i'][ids]),
      np.vstack([fi[[0, 5]], np.zeros((1, 6), np.float32)]))
