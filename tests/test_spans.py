"""Causal span layer (ISSUE 2 tentpole): begin/end pairing, parentage
through the distributed pipeline, monotonic durations, channel context
propagation, log2 histograms, Chrome trace export, and the report CLI.
"""
import json
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import jax

from graphlearn_tpu.telemetry import (Histogram, from_snapshot, metrics,
                                      recorder, span)
from graphlearn_tpu.telemetry import histogram as histogram_mod
from graphlearn_tpu.telemetry import spans as spans_mod
from graphlearn_tpu.telemetry.export import (load_events,
                                             span_durations,
                                             to_chrome_trace)
from graphlearn_tpu.utils.profiling import Metrics

P = 8
N = 256
FANOUT = [2, 2]
BATCH = 8


def _events(path):
  return [json.loads(ln) for ln in open(path).read().splitlines()]


# -- span mechanics ---------------------------------------------------------

def test_span_noop_when_recorder_off():
  recorder.disable()
  with span('x') as ctx:
    assert ctx is None
  assert spans_mod.current() is None


def test_span_pairing_parentage_and_duration(tmp_path):
  p = str(tmp_path / 'f.jsonl')
  recorder.enable(p)
  try:
    with span('root', tag='r') as rctx:
      assert spans_mod.current() == rctx
      with span('child') as cctx:
        assert cctx.trace_id == rctx.trace_id
        time.sleep(0.02)
    assert spans_mod.current() is None
  finally:
    recorder.disable()
  evs = _events(p)
  begins = {e['span_id']: e for e in evs if e['kind'] == 'span.begin'}
  ends = {e['span_id']: e for e in evs if e['kind'] == 'span.end'}
  assert set(begins) == set(ends) and len(begins) == 2
  root = begins[rctx.span_id]
  child = begins[cctx.span_id]
  assert root['parent_id'] is None
  assert root['span_id'] == root['trace_id']    # root id == trace id
  assert root['tag'] == 'r'                     # caller fields ride
  assert child['parent_id'] == root['span_id']
  assert child['trace_id'] == root['trace_id']
  # durations are monotonic-clock and nest: child <= root
  assert ends[cctx.span_id]['dur'] >= 0.02
  assert ends[rctx.span_id]['dur'] >= ends[cctx.span_id]['dur']
  # every event carries the mono timebase the durations derive from
  assert all('mono' in e for e in evs)


def test_span_explicit_parent_and_error_field(tmp_path):
  p = str(tmp_path / 'f.jsonl')
  recorder.enable(p)
  try:
    with span('other') as octx:
      pass
    with pytest.raises(ValueError):
      with span('linked', parent=octx):
        raise ValueError('boom')
  finally:
    recorder.disable()
  evs = _events(p)
  linked_b = [e for e in evs if e['kind'] == 'span.begin'
              and e['name'] == 'linked'][0]
  linked_e = [e for e in evs if e['kind'] == 'span.end'
              and e['name'] == 'linked'][0]
  assert linked_b['parent_id'] == octx.span_id
  assert linked_b['trace_id'] == octx.trace_id
  assert linked_e['error'] == 'ValueError'


def test_span_reserved_kwargs_renamed_not_raised(tmp_path):
  """Caller fields colliding with the span machinery's own event
  fields are suffixed, so enabling telemetry can never TypeError a
  pipeline that ran clean with it off."""
  p = str(tmp_path / 'f.jsonl')
  recorder.enable(p)
  try:
    with span('stagey', name='user-name', dur=3, error='prior'):
      pass
  finally:
    recorder.disable()
  b = [e for e in _events(p) if e['kind'] == 'span.begin'][0]
  assert b['name'] == 'stagey'                  # machinery field wins
  assert b['name_'] == 'user-name'              # caller field renamed
  assert b['dur_'] == 3 and b['error_'] == 'prior'


def test_events_carry_pid_tid(tmp_path):
  """Every recorder event (not just spans) lands on a real
  process/thread row — the Chrome-trace instant rows."""
  import os as os_mod
  p = str(tmp_path / 'f.jsonl')
  recorder.enable(p)
  try:
    recorder.emit('channel.stall', op='recv', secs=0.02)
  finally:
    recorder.disable()
  ev = _events(p)[0]
  assert ev['pid'] == os_mod.getpid()
  assert ev['tid'] == threading.get_ident()


def test_span_instance_not_reentrant(tmp_path):
  """Re-entering one OPEN span instance raises (it would leak the
  contextvar); sequential reuse of a closed instance stays fine."""
  recorder.enable(str(tmp_path / 'f.jsonl'))
  try:
    s = span('once')
    with s:
      with pytest.raises(RuntimeError, match='re-entered'):
        with s:
          pass
    with s:                                   # sequential reuse: ok
      pass
  finally:
    recorder.disable()
  assert spans_mod.current() is None          # no contextvar leak


def test_span_decorator(tmp_path):
  p = str(tmp_path / 'f.jsonl')

  @span('decorated')
  def work():
    return 7

  recorder.enable(p)
  try:
    assert work() == 7
  finally:
    recorder.disable()
  names = [e['name'] for e in _events(p)]
  assert names == ['decorated', 'decorated']


def test_span_thread_isolation(tmp_path):
  """A fresh thread starts its own trace — no parent leaks across
  threads (contextvars semantics the prefetch workers rely on)."""
  p = str(tmp_path / 'f.jsonl')
  recorder.enable(p)
  seen = {}
  try:
    with span('main') as mctx:
      def other():
        with span('worker') as wctx:
          seen['ctx'] = wctx
      t = threading.Thread(target=other)
      t.start()
      t.join()
  finally:
    recorder.disable()
  assert seen['ctx'].trace_id != mctx.trace_id
  wb = [e for e in _events(p) if e['kind'] == 'span.begin'
        and e['name'] == 'worker'][0]
  assert wb['parent_id'] is None


# -- histogram --------------------------------------------------------------

def test_histogram_bucket_edges():
  assert histogram_mod.bucket_index(0.0) == 0
  assert histogram_mod.bucket_index(0.5e-6) == 0
  assert histogram_mod.bucket_index(1e-6) == 1
  assert histogram_mod.bucket_index(3e-6) == 2      # [2, 4) us
  assert histogram_mod.bucket_index(1.0) == 20      # 2^19..2^20 us
  assert histogram_mod.bucket_index(1e6) == \
      histogram_mod.NUM_BUCKETS - 1                 # overflow clamps


def test_histogram_record_merge_quantile_roundtrip():
  reg = Metrics()
  for secs in (1e-5, 2e-5, 4e-4, 0.1):
    histogram_mod.record('stage', secs, registry=reg)
  hists = from_snapshot(reg.snapshot())
  assert set(hists) == {'stage'}
  h = hists['stage']
  assert h.count == 4
  assert h.secs == pytest.approx(1e-5 + 2e-5 + 4e-4 + 0.1)
  # quantiles are log2 upper edges: p50 lands in the 16-32us bucket
  assert h.quantile(0.5) == pytest.approx(32e-6)
  assert h.quantile(1.0) >= 0.1
  # merge == the sum gather_metrics computes on the flat encoding
  h2 = Histogram('stage')
  h2.add(0.2)
  merged_flat = dict(h.to_flat())
  for k, v in h2.to_flat().items():
    merged_flat[k] = merged_flat.get(k, 0) + v
  via_flat = from_snapshot(merged_flat)['stage']
  h.merge(h2)
  assert via_flat.count == h.count == 5
  assert via_flat.buckets == h.buckets


def test_span_ticks_histogram(tmp_path):
  recorder.enable(str(tmp_path / 'f.jsonl'))
  base = metrics.snapshot().get('span.histest.hist.count', 0)
  try:
    with span('histest'):
      pass
  finally:
    recorder.disable()
  assert metrics.snapshot()['span.histest.hist.count'] == base + 1


# -- channel context propagation --------------------------------------------

def test_inject_extract_roundtrip(tmp_path):
  recorder.enable(str(tmp_path / 'f.jsonl'))
  try:
    msg = {'ids': np.arange(3)}
    with span('producer.sample') as ctx:
      spans_mod.inject(msg)
    assert spans_mod.SPAN_KEY in msg
    got = spans_mod.extract(msg)
    assert got == ctx
    assert spans_mod.SPAN_KEY not in msg        # extract strips it
    # no ambient span -> no injection
    msg2 = {}
    spans_mod.inject(msg2)
    assert msg2 == {}
  finally:
    recorder.disable()
  # recorder off -> injection is a no-op
  msg3 = {}
  with span('x'):
    spans_mod.inject(msg3)
  assert msg3 == {}


def test_send_retries_without_span_on_budget_overflow(tmp_path):
  """A '#SPAN' tensor pushing a message past a fixed transport budget
  (the shm slot size) drops the LINK, never the message — telemetry
  on must not fail sends that succeed with it off."""
  from graphlearn_tpu.channel.base import ChannelBase

  class TightChannel(ChannelBase):
    def __init__(self):
      self.sent = []

    def _put(self, msg):
      if spans_mod.SPAN_KEY in msg:
        raise ValueError('message exceeds slot size')
      self.sent.append(msg)

    def send(self, msg):
      self._send_traced('send', self._put, msg)

    def recv(self):
      return self._recv_traced('recv', self.sent.pop, 0)

  ch = TightChannel()
  recorder.enable(str(tmp_path / 'f.jsonl'))
  try:
    with span('producer.sample'):
      ch.send({'a': np.arange(3)})
  finally:
    recorder.disable()
  assert len(ch.sent) == 1                      # message survived
  assert spans_mod.SPAN_KEY not in ch.sent[0]   # link degraded
  # a ValueError NOT caused by the span context still propagates
  class AlwaysFull(TightChannel):
    def _put(self, msg):
      raise ValueError('oversize regardless')
  ch2 = AlwaysFull()
  with pytest.raises(ValueError):
    ch2.send({'a': np.arange(3)})


def test_mp_channel_carries_span_context(tmp_path):
  """The channel ships the sender's ambient context and parks it at
  `last_span_context` on recv — the cross-process causal link."""
  from graphlearn_tpu.channel import MpChannel
  recorder.enable(str(tmp_path / 'f.jsonl'))
  ch = MpChannel()
  sent = {}
  try:
    def produce():
      with span('producer.sample') as ctx:
        sent['ctx'] = ctx
        ch.send({'a': np.arange(3)})

    t = threading.Thread(target=produce)
    t.start()
    msg = ch.recv()
    t.join()
    assert msg['a'].tolist() == [0, 1, 2]
    assert spans_mod.SPAN_KEY not in msg
    assert ch.last_span_context == sent['ctx']
    link = spans_mod.link_fields(ch.last_span_context)
    assert link == {'producer_trace': sent['ctx'].trace_id,
                    'producer_span': sent['ctx'].span_id}
  finally:
    recorder.disable()
    ch.close()


# -- the distributed pipeline (8-device virtual mesh) -----------------------

def _dist_dataset():
  from graphlearn_tpu.parallel import DistDataset
  rows = np.concatenate([np.arange(N), np.arange(N)])
  cols = np.concatenate([(np.arange(N) + 1) % N,
                         (np.arange(N) + 2) % N])
  feats = np.random.default_rng(0).random((N, 8), np.float32)
  # tiered (split_ratio): the feature.lookup span only exists where
  # there is a cold overlay to attribute
  return DistDataset.from_full_graph(P, rows, cols, node_feat=feats,
                                     num_nodes=N, split_ratio=0.5)


@pytest.fixture(scope='module')
def traced_run(tmp_path_factory):
  """One DistNeighborLoader epoch with the recorder on; several tests
  read the resulting trace (the acceptance artifact)."""
  from graphlearn_tpu.parallel import DistNeighborLoader, make_mesh
  path = str(tmp_path_factory.mktemp('spans') / 'flight.jsonl')
  ds = _dist_dataset()
  loader = DistNeighborLoader(ds, FANOUT, np.arange(N),
                              batch_size=BATCH, mesh=make_mesh(P),
                              shuffle=True, seed=0)
  recorder.enable(path, max_events=8192)
  try:
    batches = sum(1 for _ in loader)
  finally:
    recorder.disable()
  return {'path': path, 'batches': batches}


def test_dist_loader_spans_pair_and_nest(traced_run):
  """Acceptance: every span.end pairs with a span.begin, and the
  exchange/feature spans are children of the batch span."""
  evs = _events(traced_run['path'])
  begins = {e['span_id']: e for e in evs if e['kind'] == 'span.begin'}
  ends = {e['span_id']: e for e in evs if e['kind'] == 'span.end'}
  assert begins and set(begins) == set(ends)
  batch_spans = {s: e for s, e in begins.items() if e['name'] == 'batch'}
  assert len(batch_spans) == traced_run['batches']
  for kind in ('sample.exchange', 'feature.lookup', 'stitch'):
    ks = [e for e in begins.values() if e['name'] == kind]
    assert len(ks) == traced_run['batches'], kind
    for e in ks:
      assert e['parent_id'] in batch_spans, (kind, e)
      assert e['trace_id'] == begins[e['parent_id']]['trace_id']
  # every batch is its own trace (root span id == trace id)
  for s, e in batch_spans.items():
    assert e['parent_id'] is None and e['trace_id'] == s


def test_chrome_trace_export_structure(traced_run, tmp_path):
  """Acceptance: the Chrome trace-event export is structurally valid —
  ph/ts/dur/pid/tid on every slice, begin/end balanced."""
  evs = load_events(traced_run['path'])
  trace = to_chrome_trace(evs)
  assert 'traceEvents' in trace
  xs = [e for e in trace['traceEvents'] if e['ph'] == 'X']
  n_ends = sum(1 for e in evs if e['kind'] == 'span.end')
  assert len(xs) == n_ends        # every pair became exactly one slice
  for e in xs:
    assert isinstance(e['name'], str) and e['name']
    assert isinstance(e['ts'], float) and e['ts'] >= 0
    assert isinstance(e['dur'], float) and e['dur'] >= 0
    assert isinstance(e['pid'], int) and isinstance(e['tid'], int)
    assert 'span_id' in e['args'] and 'trace_id' in e['args']
  # slices are time-ordered and json-serializable end to end
  ts = [e['ts'] for e in trace['traceEvents']]
  assert ts == sorted(ts)
  out = tmp_path / 'chrome.json'
  out.write_text(json.dumps(trace))
  assert json.loads(out.read_text())['traceEvents']


def test_mixed_timebase_events_stay_on_one_timeline():
  """A pre-`mono` dump appended to by the new recorder: each timebase
  gets its own origin, so no event lands decades down the timeline."""
  evs = [{'kind': 'channel.stall', 'ts': 1.7e9, 'op': 'recv'},   # old
         {'kind': 'channel.stall', 'ts': 1.7e9 + 1.0, 'op': 'recv'},
         {'kind': 'span.begin', 'name': 'b', 'span_id': 's',
          'trace_id': 's', 'parent_id': None, 'mono': 6000.0,
          'ts': 1.7e9 + 2.0, 'pid': 1, 'tid': 1},
         {'kind': 'span.end', 'name': 'b', 'span_id': 's',
          'trace_id': 's', 'mono': 6000.5, 'ts': 1.7e9 + 2.5,
          'dur': 0.5, 'pid': 1, 'tid': 1}]
  trace = to_chrome_trace(evs)
  ts = [e['ts'] for e in trace['traceEvents']]
  assert len(ts) == 3                    # 2 instants + 1 slice
  assert all(0 <= t <= 10e6 for t in ts), ts   # all within 10 s


def test_unpaired_begin_dropped():
  evs = [{'kind': 'span.begin', 'name': 'a', 'span_id': 's1',
          'trace_id': 's1', 'parent_id': None, 'mono': 1.0,
          'pid': 1, 'tid': 1},
         {'kind': 'span.end', 'name': 'b', 'span_id': 'ghost',
          'trace_id': 'g', 'mono': 2.0, 'dur': 0.5, 'pid': 1,
          'tid': 1}]
  trace = to_chrome_trace(evs, include_instants=False)
  assert trace['traceEvents'] == []     # no guessed slices


def test_report_cli_table_and_diff(traced_run, tmp_path):
  out = subprocess.run(
      [sys.executable, '-m', 'graphlearn_tpu.telemetry.report',
       traced_run['path'], '--diff', traced_run['path'],
       '--chrome', str(tmp_path / 'c.json')],
      capture_output=True, text=True,
      env={**__import__('os').environ, 'JAX_PLATFORMS': 'cpu'})
  assert out.returncode == 0, out.stderr[-2000:]
  for stage in ('batch', 'sample.exchange', 'feature.lookup'):
    assert stage in out.stdout
  # self-diff: every Δmean% is +0.0
  assert '+0.0' in out.stdout
  chrome = json.loads((tmp_path / 'c.json').read_text())
  assert chrome['traceEvents']


def test_span_durations_helper(traced_run):
  durs = span_durations(load_events(traced_run['path']))
  assert set(durs) >= {'batch', 'sample.exchange', 'feature.lookup',
                       'stitch'}
  assert all(d >= 0 for ds in durs.values() for d in ds)


def test_span_children_tree(traced_run):
  from graphlearn_tpu.telemetry.export import span_children
  evs = load_events(traced_run['path'])
  tree = span_children(evs)
  names = {e['span_id']: e.get('name') for e in evs
           if e['kind'] == 'span.begin'}
  roots = tree[None]
  assert len(roots) == traced_run['batches']
  # each batch root parents runtime stage children; the FIRST batch
  # additionally parents build-time spans (the exchange.layout
  # step-construction marker lands inside the batch that triggered
  # the compile — honest attribution of build cost).  The tiered
  # loader's cold pipeline dispatches batch k+1 inside batch k's span
  # (honest attribution of the overlap), so one root may parent two
  # sample.exchange children and the last none — but the EPOCH total
  # is exactly 3 stage spans per batch.
  stage_names = {'sample.exchange', 'feature.lookup', 'stitch'}
  per_root = []
  for r in roots:
    stages = [c for c in tree[r] if names.get(c) in stage_names]
    per_root.append(len(stages))
    assert all(names.get(c) in stage_names | {'exchange.layout'}
               for c in tree[r])
  assert sum(per_root) == 3 * traced_run['batches']
  assert all(2 <= n <= 4 for n in per_root)
  # malformed begin (no span_id) is skipped, not a KeyError
  assert span_children([{'kind': 'span.begin', 'parent_id': None}]) \
      == {}


def test_histograms_merge_across_two_process_mesh(tmp_path):
  """Acceptance: per-stage latency histograms recorded on a REAL
  2-process jax.distributed mesh merge via gather_metrics (sum per
  flat key) and render in the report CLI."""
  import os
  import socket
  from pathlib import Path
  with socket.socket() as s:
    s.bind(('localhost', 0))
    port = s.getsockname()[1]
  worker = Path(__file__).parent / '_span_hist_worker.py'
  env = dict(os.environ)
  env.pop('PALLAS_AXON_POOL_IPS', None)
  env['JAX_PLATFORMS'] = 'cpu'
  env['XLA_FLAGS'] = ' '.join(
      f for f in env.get('XLA_FLAGS', '').split()
      if '--xla_force_host_platform_device_count' not in f)
  env['PYTHONPATH'] = (str(Path(__file__).resolve().parent.parent)
                       + os.pathsep + env.get('PYTHONPATH', ''))
  outs = [tmp_path / f'agg{i}.json' for i in range(2)]
  procs = [subprocess.Popen(
      [sys.executable, str(worker), f'localhost:{port}', '2', str(i),
       str(outs[i])],
      env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
      text=True) for i in range(2)]
  for pr in procs:
    try:
      stdout, _ = pr.communicate(timeout=180)
    except subprocess.TimeoutExpired:
      for q in procs:
        q.kill()
      raise
    assert pr.returncode == 0, stdout[-4000:]
  r0, r1 = (json.loads(o.read_text()) for o in outs)
  # both processes computed the SAME merged aggregate
  assert r0['num_hosts'] == 2
  assert r0['aggregate'] == r1['aggregate']
  hists = from_snapshot(r0['aggregate'])
  # proc 0 recorded 1 span, proc 1 recorded 2 — the merge sums them
  assert hists['mesh.stage'].count == 3
  assert hists['mesh.stage'].secs > 0
  # and the merged view renders through the report CLI
  agg_file = tmp_path / 'merged.json'
  agg_file.write_text(json.dumps(r0))
  out = subprocess.run(
      [sys.executable, '-m', 'graphlearn_tpu.telemetry.report',
       '--metrics-json', str(agg_file)],
      capture_output=True, text=True, env=env)
  assert out.returncode == 0, out.stderr[-2000:]
  assert 'mesh.stage' in out.stdout
  assert ' 3 ' in out.stdout or '  3' in out.stdout
