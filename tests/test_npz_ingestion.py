"""End-to-end .npz ingestion with the REAL ogbn-products export schema.

VERDICT-r1 missing #6: the ingestion path had never run against a
products-schema file.  This test writes an `.npz` with the exact
shapes/dtypes a straight OGB export produces (int64 COO, float32
[N, 100] features, labels in OGB's [N, 1] layout with a float/nan
variant) and runs `examples/train_sage.py` end-to-end on it, enforcing
the example-level accuracy acceptance (``--expect-acc``, the
clustered-graph threshold pattern promoted from tests/test_models.py).
"""
import os
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

REPO = Path(__file__).resolve().parent.parent


def _products_schema_npz(path, n=4000, d=100, classes=12, seed=0):
  from examples._synthetic import clustered_graph
  rows, cols, feats, labels = clustered_graph(n=n, deg=8,
                                              classes=classes, d=d,
                                              seed=seed)
  idx = np.random.default_rng(seed).permutation(n)
  # OGB label layout: [N, 1] float with nan for unlabeled nodes
  lab = labels.astype(np.float32)[:, None]
  lab[idx[-5:], 0] = np.nan
  np.savez(path,
           rows=rows.astype(np.int64), cols=cols.astype(np.int64),
           feats=feats.astype(np.float32), labels=lab,
           train_idx=idx[:int(n * .6)].astype(np.int64),
           val_idx=idx[int(n * .6):int(n * .8)].astype(np.int64),
           test_idx=idx[int(n * .8):n - 5].astype(np.int64))


@pytest.mark.slow
@pytest.mark.parametrize('split_ratio', ['1.0', '0.5'])
def test_train_sage_on_products_schema_npz(tmp_path, split_ratio):
  npz = tmp_path / 'products_schema.npz'
  _products_schema_npz(npz)
  env = dict(os.environ)
  env.pop('PALLAS_AXON_POOL_IPS', None)
  env['JAX_PLATFORMS'] = 'cpu'
  env['PYTHONPATH'] = str(REPO) + os.pathsep + env.get('PYTHONPATH', '')
  out = subprocess.run(
      [sys.executable, str(REPO / 'examples' / 'train_sage.py'),
       '--data', str(npz), '--epochs', '2', '--batch-size', '512',
       '--fanout', '5', '3', '--hidden', '64',
       '--split-ratio', split_ratio, '--expect-acc', '0.5'],
      env=env, capture_output=True, text=True, timeout=600)
  assert out.returncode == 0, out.stdout[-3000:] + out.stderr[-2000:]
  assert 'test acc:' in out.stdout
