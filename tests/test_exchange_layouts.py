"""Exchange-layout invariants (ISSUE 3): the pluggable all-to-all
layouts (`parallel.exchange`) must keep the bucketing contract the
engines rely on —

  * capacity accounting: what was actually sent fits in the slots
    (``offered - dropped <= slots``) at every P and layout;
  * round trip: bucketed -> exchanged -> answered -> stitched equals
    the unbucketed reference for a deterministic reply function;
  * layout equivalence: dense / compacted / hierarchical deliver
    identical valid ids and masks for deterministic gathers;
  * the ragged backend import-gates cleanly on jax 0.4.37.

P in {2, 8} runs on the real 8-device test mesh; P in {16, 64} uses
the host-simulated bucketing twin (`simulate_assignment`), which
mirrors the traced slot assignment exactly.
"""
import numpy as np
import pytest

jax = pytest.importorskip('jax')
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from graphlearn_tpu.parallel.exchange import (
    AUTO_COMPACT_MIN_PARTS, ExchangeSpec, HAVE_RAGGED, capacity_spec,
    mesh_factors, plan_exchange, resolve_layout, simulate_assignment)
from graphlearn_tpu.parallel.shard_map_compat import shard_map

LAYOUTS = ('dense', 'compact', 'hier')


def _mesh(p):
  return Mesh(np.array(jax.devices()[:p]), ('data',))


def _owner_fn(bounds):
  return lambda v: (jnp.searchsorted(bounds, v, side='right')
                    - 1).astype(jnp.int32)


def _run_plan(p, n_ids, spec, seed=0, num_nodes=4096):
  """Drive one plan on a real p-device mesh: exchange ids, answer with
  the deterministic reply ``3 * id + owner`` at the owner, stitch.
  Returns (ids, out, delivered) stacked host arrays."""
  rng = np.random.default_rng(seed)
  ids = rng.integers(0, num_nodes, (p, n_ids)).astype(np.int32)
  ids[:, -1] = -1                       # padded tail in every shard
  bounds_h = (np.arange(p + 1) * (num_nodes // p)).astype(np.int32)
  bounds_h[-1] = num_nodes
  mesh = _mesh(p)

  def body(ids_s, bounds):
    my = jax.lax.axis_index('data')
    plan = plan_exchange(ids_s[0], _owner_fn(bounds), p, 'data', spec)
    # deterministic owner-side answer: f(id) = 3 * id + owner(id);
    # invalid request slots answer 0
    ans = jnp.where(plan.recv >= 0,
                    3 * plan.recv + _owner_fn(bounds)(plan.recv), 0)
    out = plan.reply(ans, fill=-7)
    offered, dropped, slots = plan.stats
    stats = jnp.stack([offered, dropped, slots])
    return out[None], plan.delivered[None], stats[None]

  f = jax.jit(shard_map(body, mesh=mesh,
                        in_specs=(P('data'), P()),
                        out_specs=(P('data'), P('data'), P('data'))))
  out, delivered, stats = f(
      jax.device_put(ids, NamedSharding(mesh, P('data'))),
      jax.device_put(bounds_h, NamedSharding(mesh, P())))
  return (ids, np.asarray(out), np.asarray(delivered),
          np.asarray(stats))


@pytest.mark.parametrize('p', [2, 8])
@pytest.mark.parametrize('layout', LAYOUTS)
def test_roundtrip_matches_unbucketed_reference(p, layout):
  n = 96
  spec = capacity_spec(n, p, 2.0, layout=layout)
  if layout == 'hier' and p == 2:
    assert spec.layout == 'dense'       # too small to factor
  ids, out, delivered, stats = _run_plan(p, n, spec)
  num_nodes = 4096
  bounds = (np.arange(p + 1) * (num_nodes // p)).astype(np.int64)
  bounds[-1] = num_nodes
  owner = np.clip(np.searchsorted(bounds, ids, side='right') - 1,
                  0, p - 1)
  ref = 3 * ids.astype(np.int64) + owner      # unbucketed reference
  valid = ids >= 0
  # every delivered id's reply equals the reference; undelivered and
  # invalid slots carry the fill
  assert (out[valid & delivered] == ref[valid & delivered]).all()
  assert (out[~delivered] == -7).all()
  for d in range(p):
    offered, dropped, slots = stats[d]
    assert offered - dropped <= slots
  # mesh-wide: hier counts each id once per wire stage (stage-2
  # offered lives on the intermediate device, so only the SUM over
  # devices is meaningful); single-stage layouts count once
  total_offered = int(stats[:, 0].sum())
  total_valid = int(valid.sum())
  if spec.layout == 'hier':
    assert total_valid <= total_offered <= 2 * total_valid
  else:
    assert total_offered == total_valid


@pytest.mark.parametrize('p', [2, 8])
def test_layouts_identical_valid_ids_and_masks(p):
  """Deterministic replies: every layout must deliver the same values
  for the ids it kept, and at slack 2.0 with near-balanced buckets all
  layouts keep everything -> identical outputs and masks."""
  n = 64
  outs, masks = [], []
  for layout in LAYOUTS:
    spec = capacity_spec(n, p, 2.0, layout=layout)
    ids, out, delivered, _ = _run_plan(p, n, spec, seed=3)
    outs.append(np.where(delivered, out, -7))
    masks.append(delivered & (ids >= 0))
  for o, m in zip(outs[1:], masks[1:]):
    np.testing.assert_array_equal(masks[0], m)
    np.testing.assert_array_equal(outs[0], o)
  # and nothing was dropped at this slack on balanced ids
  assert masks[0].sum() == (ids >= 0).sum()


@pytest.mark.parametrize('p', [2, 8, 16, 64])
@pytest.mark.parametrize('layout', LAYOUTS)
def test_capacity_invariants_host_simulated(p, layout):
  """Property-style capacity accounting at every P (host-simulated
  bucketing — no mesh needed): sent fits in slots, kept ids never
  exceed any per-bucket capacity, pool never over-admits."""
  rng = np.random.default_rng(p * 7 + 1)
  for n, slack in ((32, 1.0), (320, 1.25), (1024, 2.0)):
    ids = rng.integers(0, 20000, n).astype(np.int64)
    ids[rng.random(n) < 0.1] = -1
    owner = np.clip(ids * p // 20000, 0, p - 1)
    spec = capacity_spec(n, p, slack, layout=layout)
    sim = simulate_assignment(ids, owner, spec)
    assert sim['offered'] == int((ids >= 0).sum())
    assert sim['offered'] - sim['dropped'] <= sim['slots']
    assert sim['dropped'] >= 0
    kept = sim['kept']
    assert not kept[ids < 0].any()
    if spec.layout == 'dense':
      # no owner bucket may exceed the per-destination cap
      for q in range(p):
        assert kept[owner == q].sum() <= spec.capacity
    elif spec.layout == 'compact':
      over = 0
      for q in range(p):
        over += max(kept[owner == q].sum() - spec.capacity, 0)
      assert over <= spec.pool
    # where the dense FLOOR binds (small per-destination shares — the
    # P=16/64 waste blowup), the compacted layouts must beat dense
    # slots; compact additionally auto-degrades to dense when the
    # floor never bound (its spec.layout comes back 'dense')
    dense = capacity_spec(n, p, slack, layout='dense')
    floor_bound = (n / p * slack) < dense.capacity
    if (p >= AUTO_COMPACT_MIN_PARTS and floor_bound
        and layout == 'compact'):
      assert spec.slots < dense.slots
    if layout == 'compact' and not floor_bound:
      assert spec.slots <= dense.slots


def test_compact_pool_catches_full_skew():
  """Every id owned by ONE partition: the tight base drops most, the
  pool admits up to its budget, accounting stays exact."""
  p = 16
  n = 256
  ids = np.arange(n).astype(np.int64)
  owner = np.zeros(n, np.int64)               # all on partition 0
  spec = capacity_spec(n, p, 1.25, layout='compact')
  sim = simulate_assignment(ids, owner, spec)
  assert sim['kept'].sum() == min(n, spec.capacity + spec.pool)
  assert sim['dropped'] == n - sim['kept'].sum()
  assert sim['offered'] - sim['dropped'] <= sim['slots']


def test_capacity_spec_shapes():
  # exact stays exact (None) — the walkers/subgraph contract
  assert capacity_spec(128, 8, None, layout='compact') is None
  # dense reproduces the legacy floor + rounding
  d = capacity_spec(100, 8, 2.0, layout='dense')
  assert d.layout == 'dense' and d.capacity == 64   # floor dominates
  # compact pool-only for tiny shares: slots ~ n, not P * floor
  c = capacity_spec(32, 64, 1.25, layout='compact')
  assert c.capacity == 0 and c.pool == 32 and c.slots == 32
  # hierarchical factors ~sqrt(P) and pays the floor 2*sqrt(P) times
  h = capacity_spec(320, 64, 1.25, layout='hier')
  assert (h.rows, h.cols) == (8, 8)
  assert h.slots < capacity_spec(320, 64, 1.25, layout='dense').slots


def test_auto_and_env_resolution(monkeypatch):
  assert resolve_layout(None, 8) == 'dense'
  assert resolve_layout('auto', AUTO_COMPACT_MIN_PARTS) == 'compact'
  monkeypatch.setenv('GLT_EXCHANGE_LAYOUT', 'hier')
  assert resolve_layout('auto', 64) == 'hier'
  # explicit beats env
  assert resolve_layout('dense', 64) == 'dense'
  monkeypatch.delenv('GLT_EXCHANGE_LAYOUT')
  with pytest.raises(ValueError):
    resolve_layout('mystery', 8)


def test_ragged_import_gates_cleanly():
  """jax 0.4.37 has no ragged_all_to_all: the gate must be closed and
  'ragged' must fall back to the compacted dense layout rather than
  crash at plan time."""
  assert HAVE_RAGGED == hasattr(jax.lax, 'ragged_all_to_all')
  resolved = resolve_layout('ragged', 16)
  if not HAVE_RAGGED:
    assert resolved == 'compact'
    spec = capacity_spec(128, 16, 1.5, layout='ragged')
    assert spec.layout == 'compact'
  else:  # pragma: no cover — newer jax
    assert resolved == 'ragged'


def test_mesh_factors():
  assert mesh_factors(64) == (8, 8)
  assert mesh_factors(16) == (4, 4)
  assert mesh_factors(8) == (4, 2)
  assert mesh_factors(7) == (7, 1)
  for p in (2, 4, 6, 8, 12, 16, 32, 64, 128):
    r, c = mesh_factors(p)
    assert r * c == p


def test_loader_layouts_agree_on_features():
  """End to end on the 8-device mesh: the three layouts serve
  identical (deterministic) feature rows for every valid node."""
  from graphlearn_tpu.parallel import (DistDataset, DistNeighborLoader,
                                       make_mesh)
  n = 512
  rng = np.random.default_rng(0)
  rows = np.repeat(np.arange(n), 4)
  cols = rng.integers(0, n, n * 4)
  feats = np.arange(n, dtype=np.float32)[:, None] * np.ones(
      (1, 3), np.float32)
  ds = DistDataset.from_full_graph(8, rows, cols, node_feat=feats,
                                   num_nodes=n)
  mesh = make_mesh(8)
  for layout in LAYOUTS:
    loader = DistNeighborLoader(ds, [3, 2], np.arange(n),
                                batch_size=16, shuffle=True, mesh=mesh,
                                seed=0, exchange_slack=1.5,
                                exchange_layout=layout)
    b = next(iter(loader))
    nodes = np.asarray(b.node)
    x = np.asarray(b.x)
    for p_ in range(8):
      m = nodes[p_] >= 0
      np.testing.assert_allclose(
          x[p_][m][:, 0], ds.new2old[nodes[p_][m]].astype(np.float32))
    st = loader.sampler.exchange_stats(tick_metrics=False)
    assert st['dist.frontier.dropped'] == 0
    assert st['dist.feature.dropped'] == 0


def test_hetero_engine_runs_on_compact_and_hier():
  """The hetero engine routes every per-etype hop and per-type gather
  through the same plan API — both non-dense layouts must deliver
  valid, drop-free node tables on the 8-device mesh."""
  from graphlearn_tpu.parallel import DistHeteroNeighborSampler, make_mesh
  from graphlearn_tpu.parallel.dist_hetero import DistHeteroDataset
  rng = np.random.default_rng(0)
  nu, ni = 64, 32
  urow = np.repeat(np.arange(nu), 2)
  icol = rng.integers(0, ni, nu * 2)
  ds = DistHeteroDataset.from_full_graph(
      8, {('u', 'to', 'i'): (urow, icol),
          ('i', 'rev_to', 'u'): (icol, urow)},
      num_nodes_dict={'u': nu, 'i': ni})
  mesh = make_mesh(8)
  for layout in ('compact', 'hier'):
    hs = DistHeteroNeighborSampler(ds, [2, 2], mesh=mesh, seed=0,
                                   collect_features=False,
                                   exchange_slack=2.0,
                                   exchange_layout=layout)
    seeds = ds.old2new['u'][np.arange(16).reshape(8, 2) % nu]
    out = hs.sample_from_nodes('u', seeds)
    nodes_u = np.asarray(out['node']['u'])
    assert (nodes_u >= 0).any()
    st = hs.exchange_stats(tick_metrics=False)
    assert st['dist.frontier.dropped'] == 0


def test_pad_1d_truncation_surfaces():
  """The pad_1d small fix: silent truncation of valid entries emits a
  telemetry event and raises under the strict flag."""
  from graphlearn_tpu.telemetry.recorder import EventRecorder, recorder
  from graphlearn_tpu.utils.padding import pad_1d
  # routine padding and fill-tail truncation stay silent
  out = pad_1d(np.array([1, 2]), 4)
  assert (out == np.array([1, 2, -1, -1])).all()
  pad_1d(np.array([1, 2, -1, -1]), 2)
  events = recorder.events('padding.truncate')
  n0 = len(events)
  pad_1d(np.arange(8), 4)                     # drops 4 valid entries
  assert len(recorder.events('padding.truncate')) >= n0  # no crash
  with pytest.raises(ValueError, match='truncate'):
    pad_1d(np.arange(8), 4, strict=True)
  # event payload (on a private recorder so the global one stays
  # clean for other tests); the recorder MODULE is fetched from
  # sys.modules — the telemetry package re-exports the instance under
  # the same name, shadowing attribute-style module access
  import sys
  rec_mod = sys.modules['graphlearn_tpu.telemetry.recorder']
  rec = EventRecorder()
  rec.enable()
  orig = rec_mod.recorder
  rec_mod.recorder = rec
  try:
    pad_1d(np.arange(10), 6)
  finally:
    rec_mod.recorder = orig
    evs = rec.events('padding.truncate')
    rec.disable()
  assert evs and evs[-1]['dropped'] == 4
  assert evs[-1]['requested'] == 10 and evs[-1]['size'] == 6
