"""Chaos suite (ISSUE 4): deterministic fault plans driven through the
REAL runtime — producer worker kills mid-epoch, RPC connection drops
and delays on the server-fed path — asserting exact batch accounting
(expected count, zero duplicate '#SEQ', full seed coverage) and that
the fault-free path is byte-identical with the resilience layer on.
"""
import multiprocessing as mp
import os

import numpy as np
import pytest

from graphlearn_tpu import native
from graphlearn_tpu.telemetry import recorder
from graphlearn_tpu.testing import chaos
from graphlearn_tpu.testing.chaos import ChaosPlan, Fault, parse_plan

pytestmark = pytest.mark.skipif(not native.available(),
                                reason='native lib unavailable')

N = 48
BATCH = 8
N_BATCHES = N // BATCH


# -- plan grammar (no native needed, but grouped with the suite) ------------
def test_parse_plan_json_and_compact():
  p = parse_plan('{"seed": 7, "faults": [{"site": "rpc.request", '
                 '"action": "drop", "nth": 3, "op": "fetch"}]}')
  assert p.seed == 7 and p.faults[0].nth == 3
  c = parse_plan('rpc.request:drop:3:op=fetch;'
                 'producer.worker:kill:2:worker=0:epoch=1')
  assert len(c.faults) == 2
  assert c.faults[1] == Fault('producer.worker', 'kill', nth=2,
                              worker=0, epoch=1)
  with pytest.raises(ValueError):
    parse_plan('nowhere:drop:1')
  with pytest.raises(ValueError):
    parse_plan('rpc.request:explode:1')


def test_plan_counting_is_deterministic():
  plan = ChaosPlan([Fault('rpc.request', 'drop', nth=2, count=2,
                          op='fetch')])
  fired = [bool(plan.on('rpc.request', op='fetch')) for _ in range(5)]
  assert fired == [False, True, True, False, False]
  # non-matching ops don't advance the counter
  plan2 = ChaosPlan([Fault('rpc.request', 'drop', nth=2, op='fetch')])
  plan2.on('rpc.request', op='other')
  assert not plan2.on('rpc.request', op='fetch')
  assert plan2.on('rpc.request', op='fetch')
  assert plan2.exhausted()


# -- shared fixtures --------------------------------------------------------
@pytest.fixture(autouse=True)
def _clean(monkeypatch):
  from graphlearn_tpu.distributed.dist_loader import DistLoader
  from graphlearn_tpu.distributed.resilience import reset_default_policy
  monkeypatch.setenv('GLT_RPC_TIMEOUT', '10')
  monkeypatch.setenv('GLT_RPC_DEADLINE', '30')
  monkeypatch.setenv('GLT_RPC_BACKOFF_BASE', '0.02')
  monkeypatch.setattr(DistLoader, 'RECV_POLL_SECS', 0.5)
  reset_default_policy()
  chaos.uninstall()
  recorder.enable(None)
  recorder.clear()
  yield
  chaos.uninstall()
  recorder.clear()
  recorder.disable()
  reset_default_policy()


def _ring(n=N, d=4):
  from graphlearn_tpu.distributed import HostDataset
  rows = np.repeat(np.arange(n), 2)
  cols = np.stack([(np.arange(n) + 1) % n,
                   (np.arange(n) + 2) % n], 1).reshape(-1)
  feats = np.tile(np.arange(n, dtype=np.float32)[:, None], (1, d))
  return HostDataset.from_coo(rows, cols, n, node_features=feats,
                              node_labels=np.arange(n) % 4)


def _mp_loader(seed=3):
  from graphlearn_tpu.distributed import (DistNeighborLoader,
                                          MpDistSamplingWorkerOptions)
  # spawn (not forkserver): workers inherit the CURRENT os.environ, so
  # monkeypatched fault plans reach them deterministically
  return DistNeighborLoader(
      _ring(), [2], np.arange(N), batch_size=BATCH, shuffle=False,
      worker_options=MpDistSamplingWorkerOptions(
          num_workers=2, mp_start_method='spawn'),
      to_device=False, seed=seed)


def _drain(loader):
  """One epoch -> [(sorted-seed-tuple, node-bytes, edge-bytes)]."""
  out = []
  for b in loader:
    s = np.asarray(b.batch)
    key = tuple(np.sort(s[s >= 0]).tolist())
    out.append((key, np.asarray(b.node).tobytes(),
                np.asarray(b.edge_index).tobytes()))
  return out


def _assert_exact(batches, loader=None):
  assert len(batches) == N_BATCHES
  seeds = sorted(x for key, _, _ in batches for x in key)
  assert seeds == list(range(N)), 'lost or duplicated seeds'
  if loader is not None:
    assert len(loader._seen_seqs) == N_BATCHES, \
        'duplicate or missing #SEQ stamps'


# -- mp mode: worker kill mid-epoch -----------------------------------------
def test_mp_worker_kill_restart_exact_and_byte_identical(monkeypatch,
                                                         tmp_path):
  jsonl = str(tmp_path / 'workers.jsonl')
  # worker 0 dies before its 3rd batch of epoch 0 (it owns seqs 0-2):
  # seqs 0,1 delivered, seq 2 replayed by the restarted worker
  monkeypatch.setenv('GLT_FAULT_PLAN',
                     'producer.worker:kill:3:worker=0:epoch=0')
  monkeypatch.setenv('GLT_TELEMETRY_JSONL', jsonl)
  loader = _mp_loader()
  chaotic = _drain(loader)
  _assert_exact(chaotic, loader)
  restarts = recorder.events('producer.restart')
  assert restarts, 'supervisor must have restarted the killed worker'
  assert restarts[0]['worker'] == 0
  assert restarts[0]['exitcode'] == chaos.WORKER_KILL_EXIT
  assert restarts[0]['replayed'] >= 1
  loader.shutdown()
  # the killed worker recorded its own injected fault before dying
  with open(jsonl) as f:
    assert any('"kind": "fault.injected"' in ln and '"kill"' in ln
               for ln in f), 'worker-side fault.injected missing'

  # fault-free epoch, same config+seed, resilience layer still on:
  # every batch byte-identical to the chaos run (replayed batches
  # included — batch content is a function of (epoch, seq) only)
  monkeypatch.delenv('GLT_FAULT_PLAN')
  monkeypatch.delenv('GLT_TELEMETRY_JSONL')
  chaos.uninstall()
  clean_loader = _mp_loader()
  clean = _drain(clean_loader)
  clean_loader.shutdown()
  _assert_exact(clean)
  assert sorted(chaotic) == sorted(clean), \
      'faulted epoch must be byte-identical to the fault-free epoch'


def test_mp_worker_lost_raises_with_diagnostics(monkeypatch):
  from graphlearn_tpu.distributed import PeerLostError
  monkeypatch.setenv('GLT_FAULT_PLAN',
                     'producer.worker:kill:1:worker=0:epoch=0')
  monkeypatch.setenv('GLT_MAX_WORKER_RESTARTS', '0')
  loader = _mp_loader()
  with pytest.raises(PeerLostError, match='unrecoverable'):
    _drain(loader)
  loader.shutdown()
  assert recorder.events('peer.lost'), 'loss must hit the recorder'


def test_mp_worker_lost_degraded_finishes_on_survivors(monkeypatch):
  # worker 0 dies before its FIRST batch and may not be restarted:
  # its 3 batches are written off; the epoch finishes with worker 1's
  monkeypatch.setenv('GLT_FAULT_PLAN',
                     'producer.worker:kill:1:worker=0:epoch=0')
  monkeypatch.setenv('GLT_MAX_WORKER_RESTARTS', '0')
  monkeypatch.setenv('GLT_DEGRADED_OK', '1')
  loader = _mp_loader()
  batches = _drain(loader)
  lost_evs = [e for e in recorder.events('peer.lost')
              if e.get('degraded')]
  assert lost_evs, 'degraded completion must be flagged in telemetry'
  lost = sum(e['lost_batches'] for e in lost_evs)
  assert lost >= 1
  assert len(batches) == N_BATCHES - lost
  # the surviving batches are still exact — no duplicates among them
  seeds = sorted(x for key, _, _ in batches for x in key)
  assert len(seeds) == len(set(seeds))
  loader.shutdown()


# -- remote mode: connection drop + delayed fetch ---------------------------
def _server_chaos_proc(port_q, jsonl, worker_plan):
  # env set BEFORE the producer pool exists: sampling workers inherit
  # the kill plan and the telemetry sink from this process
  if worker_plan:
    os.environ['GLT_FAULT_PLAN'] = worker_plan
  os.environ['GLT_TELEMETRY_JSONL'] = jsonl
  from graphlearn_tpu.distributed import (init_server,
                                          wait_and_shutdown_server)
  recorder.enable(jsonl)
  srv = init_server(num_servers=1, num_clients=1, rank=0,
                    dataset=_ring(), host='127.0.0.1', port=0)
  port_q.put(srv.port)
  wait_and_shutdown_server(timeout=180)


@pytest.mark.slow
def test_remote_chaos_epoch_exact(monkeypatch, tmp_path):
  """The acceptance scenario: one worker kill (server side) + one
  connection drop + one delayed fetch in a single epoch -> exact batch
  count, zero duplicate '#SEQ', producer.restart + rpc.retry events
  present; the next (fault-free) epoch is exact too."""
  jsonl = str(tmp_path / 'server.jsonl')
  ctx = mp.get_context('spawn')
  port_q = ctx.Queue()
  p = ctx.Process(
      target=_server_chaos_proc,
      args=(port_q, jsonl, 'producer.worker:kill:3:worker=0:epoch=0'),
      daemon=False)
  p.start()
  port = port_q.get(timeout=120)

  from graphlearn_tpu.distributed import (
      DistNeighborLoader, RemoteDistSamplingWorkerOptions, init_client,
      shutdown_client)
  init_client([('127.0.0.1', port)], rank=0, num_clients=1)
  loader = DistNeighborLoader(
      None, [2], np.arange(N), batch_size=BATCH, shuffle=False,
      worker_options=RemoteDistSamplingWorkerOptions(
          server_rank=0, num_workers=2, prefetch_size=1),
      to_device=False, seed=3)
  # prefetch_size=1 keeps fetch arrivals totally ordered, so 'nth'
  # counting is deterministic; the 1.5s delay overshoots the 0.5s
  # recv poll, driving the heartbeat slow-vs-dead probe
  chaos.install(
      'rpc.request:drop:3:op=fetch_one_sampled_message;'
      'rpc.request:delay:5:op=fetch_one_sampled_message:secs=1.5')
  epoch1 = _drain(loader)
  _assert_exact(epoch1)
  ch = loader.channel
  assert len(ch._seen_seqs) == N_BATCHES, 'duplicate/missing #SEQ'
  retries = recorder.events('rpc.retry')
  assert retries, 'the dropped connection must surface as rpc.retry'
  assert all(e['op'] == 'fetch_one_sampled_message' for e in retries)
  assert chaos.active().exhausted(), 'every planned fault must fire'

  chaos.uninstall()
  epoch2 = _drain(loader)         # fault-free epoch after the storm
  _assert_exact(epoch2)

  loader.shutdown()
  shutdown_client()
  p.join(timeout=60)
  assert not p.is_alive()
  with open(jsonl) as f:
    lines = f.read()
  assert '"kind": "producer.restart"' in lines, \
      'server-side supervisor must log the worker restart'
  assert '"kind": "fault.injected"' in lines
