"""Planted-structure learnability: the stack must LEARN, not just run.

VERDICT r3 weak #5: every synthetic bench uses random labels, so a
decreasing loss proves plumbing, not learning; the real-data accuracy
harnesses (`examples/acc_ogbn_products.py` etc.) SKIP on this
zero-egress box.  This is the offline analog of the reference's 0.787
ogbn-products bar (`examples/train_sage_ogbn_products.py:16`): a task
whose labels are derivable ONLY from neighborhood features —

  * every node gets a random color z(v); its feature is a noisy
    one-hot of z(v);
  * its LABEL is the majority color among its out-neighbors.

A node's own feature says nothing about its label (colors are i.i.d.),
so chance is 1/C for any feature-only model; one round of neighbor
aggregation reads the histogram and solves it.  Training through each
data path must therefore reach accuracy >> chance — proving sampling,
collation, masking, and the step wiring preserve the neighborhood
signal end to end:

  (a) NeighborLoader + per-batch supervised step,
  (b) FusedEpoch (whole-epoch scan program) + fused evaluate,
  (c) DistNeighborLoader + DP step on the 8-device virtual mesh.
"""
import numpy as np
import optax
import pytest

jax = pytest.importorskip('jax')

from graphlearn_tpu.data import Dataset
from graphlearn_tpu.loader import FusedEpoch, NeighborLoader
from graphlearn_tpu.models import (GraphSAGE, create_train_state,
                                   make_eval_step, make_supervised_step)

N, C, DEG, NOISE = 2000, 5, 10, 0.1
CHANCE = 1.0 / C
BAR = 0.75                      # >> chance (0.2); hop 1 covers the
                                # full out-neighborhood (fanout >= DEG)


def _planted(seed=0):
  rng = np.random.default_rng(seed)
  z = rng.integers(0, C, N)
  rows = np.repeat(np.arange(N), DEG)
  cols = rng.integers(0, N, N * DEG)
  hist = np.zeros((N, C), np.int64)
  np.add.at(hist, rows, np.eye(C, dtype=np.int64)[z[cols]])
  y = hist.argmax(1).astype(np.int32)
  x = (np.eye(C, dtype=np.float32)[z]
       + NOISE * rng.standard_normal((N, C)).astype(np.float32))
  return rows, cols, x, y


def _splits(seed=1):
  rng = np.random.default_rng(seed)
  perm = rng.permutation(N)
  return perm[:1500], perm[1500:]


def _model_tx():
  return (GraphSAGE(hidden_features=32, out_features=C, num_layers=2),
          optax.adam(1e-2))


@pytest.mark.slow
def test_learns_through_per_batch_loader():
  rows, cols, x, y = _planted()
  train_idx, test_idx = _splits()
  ds = (Dataset().init_graph((rows, cols), num_nodes=N)
        .init_node_features(x).init_node_labels(y))
  loader = NeighborLoader(ds, [10, 5], train_idx, batch_size=256,
                          shuffle=True, seed=0)
  model, tx = _model_tx()
  state, apply_fn = create_train_state(model, jax.random.key(0),
                                       next(iter(loader)), tx)
  step = make_supervised_step(apply_fn, tx, 256)
  for _ in range(12):
    for batch in loader:
      state, loss, _ = step(state, batch)
  ev = make_eval_step(apply_fn, 256)
  test_loader = NeighborLoader(ds, [10, 5], test_idx, batch_size=256,
                               shuffle=False, seed=0)
  correct = total = 0
  for batch in test_loader:
    c, t = ev(state.params, batch)
    correct += int(c)
    total += int(t)
  acc = correct / max(total, 1)
  assert acc > BAR, f'per-batch path accuracy {acc:.3f} <= {BAR}'


@pytest.mark.slow
def test_learns_through_fused_epoch():
  rows, cols, x, y = _planted()
  train_idx, test_idx = _splits()
  ds = (Dataset().init_graph((rows, cols), num_nodes=N)
        .init_node_features(x, split_ratio=1.0).init_node_labels(y))
  loader = NeighborLoader(ds, [10, 5], train_idx, batch_size=256,
                          shuffle=True, seed=0)
  model, tx = _model_tx()
  state, apply_fn = create_train_state(model, jax.random.key(0),
                                       next(iter(loader)), tx)
  fused = FusedEpoch(ds, [10, 5], train_idx, apply_fn, tx,
                     batch_size=256, shuffle=True, seed=0)
  first_loss = last = None
  for _ in range(12):
    state, stats = fused.run(state)
    if first_loss is None:
      first_loss = stats.loss
    last = stats
  assert last.loss < first_loss
  acc = fused.evaluate(state.params, test_idx)
  assert acc > BAR, f'fused path accuracy {acc:.3f} <= {BAR}'


@pytest.mark.slow
def test_learns_through_dist_loader():
  from graphlearn_tpu.parallel import (DistNeighborLoader,
                                       local_batch_piece,
                                       make_dp_supervised_step,
                                       make_mesh, replicate)
  num_parts = 8
  rows, cols, x, y = _planted()
  train_idx, test_idx = _splits()
  from graphlearn_tpu.parallel import DistDataset
  dds = DistDataset.from_full_graph(num_parts, rows, cols, node_feat=x,
                                    node_label=y, num_nodes=N)
  mesh = make_mesh(num_parts)
  bs = 32
  loader = DistNeighborLoader(dds, [10, 5], train_idx, batch_size=bs,
                              shuffle=True, mesh=mesh, seed=0)
  model, tx = _model_tx()
  first = next(iter(loader))
  local_piece = local_batch_piece(first, num_parts)
  state, apply_fn = create_train_state(model, jax.random.key(0),
                                       local_piece, tx)
  state = replicate(state, mesh)
  step = make_dp_supervised_step(model.apply, tx, bs, mesh)
  for _ in range(12):
    for batch in loader:
      state, loss, correct = step(state, batch)
  # params are mesh-replicated: pull one copy and evaluate through the
  # single-device path on the SAME relabeled graph
  params = jax.tree_util.tree_map(
      lambda v: np.asarray(v.addressable_shards[0].data), state.params)
  ds_eval = (Dataset()
             .init_graph((dds.old2new[rows], dds.old2new[cols]),
                         num_nodes=N)
             .init_node_features(x[dds.new2old])
             .init_node_labels(y[dds.new2old]))
  ev = make_eval_step(apply_fn, 256)
  test_loader = NeighborLoader(ds_eval, [10, 5], dds.old2new[test_idx],
                               batch_size=256, shuffle=False, seed=0)
  correct = total = 0
  for batch in test_loader:
    c, t = ev(params, batch)
    correct += int(c)
    total += int(t)
  acc = correct / max(total, 1)
  assert acc > BAR, f'dist path accuracy {acc:.3f} <= {BAR}'
