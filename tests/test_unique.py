"""Tests for the sort-based inducer (ops/unique.py).

Mirrors the coverage of reference `test/cpp/test_inducer.cu` /
`test_hash_table.cu`: dedup correctness, insertion-order preservation,
relabeling, capacity overflow.
"""
import jax.numpy as jnp
import numpy as np

from graphlearn_tpu.ops import induce_next, init_node, unique_stable


def test_unique_stable_basic():
  x = jnp.array([5, 3, 5, 7, 3, 9], dtype=jnp.int32)
  res = unique_stable(x, capacity=8)
  assert int(res.count) == 4
  np.testing.assert_array_equal(np.asarray(res.values[:4]), [5, 3, 7, 9])
  np.testing.assert_array_equal(np.asarray(res.values[4:]), [-1] * 4)
  np.testing.assert_array_equal(np.asarray(res.inverse), [0, 1, 0, 2, 1, 3])


def test_unique_stable_with_invalid():
  x = jnp.array([4, -1, 4, 2, -1, 0], dtype=jnp.int32)
  res = unique_stable(x, capacity=4)
  assert int(res.count) == 3
  np.testing.assert_array_equal(np.asarray(res.values[:3]), [4, 2, 0])
  np.testing.assert_array_equal(np.asarray(res.inverse), [0, -1, 0, 1, -1, 2])


def test_unique_stable_overflow():
  x = jnp.arange(10, dtype=jnp.int32)
  res = unique_stable(x, capacity=4)
  assert int(res.count) == 4
  # Which 4 survive is defined by value-sort segment order; the
  # guarantee is: exactly `capacity` uniques, inverse in [-1, cap).
  inv = np.asarray(res.inverse)
  assert ((inv >= -1) & (inv < 4)).all()


def test_inducer_init_and_induce():
  seeds = jnp.array([10, 20, 30, -1], dtype=jnp.int32)
  state, seed_local = init_node(seeds, capacity=16)
  assert int(state.count) == 3
  np.testing.assert_array_equal(np.asarray(seed_local), [0, 1, 2, -1])

  # hop: node 10 sampled [20, 40], node 20 sampled [40, 50]
  nbrs = jnp.array([[20, 40], [40, 50], [-1, -1], [-1, -1]], jnp.int32)
  mask = nbrs >= 0
  src_local = seed_local
  state2, rows, cols, frontier_start = induce_next(state, src_local, nbrs,
                                                   mask)
  assert int(frontier_start) == 3
  assert int(state2.count) == 5
  nodes = np.asarray(state2.nodes[:5])
  np.testing.assert_array_equal(nodes, [10, 20, 30, 40, 50])
  # rows = neighbor local idx, cols = src local idx (PyG transposed);
  # static [B*k] layout with -1 padding for masked slots.
  np.testing.assert_array_equal(np.asarray(rows),
                                [1, 3, 3, 4, -1, -1, -1, -1])
  np.testing.assert_array_equal(np.asarray(cols),
                                [0, 0, 1, 1, -1, -1, -1, -1])


def test_inducer_idempotent_reinsert():
  seeds = jnp.array([1, 2], dtype=jnp.int32)
  state, _ = init_node(seeds, capacity=8)
  nbrs = jnp.array([[2, 1], [1, 2]], jnp.int32)
  state2, rows, cols, _ = induce_next(state, jnp.array([0, 1]), nbrs,
                                      nbrs >= 0)
  assert int(state2.count) == 2  # nothing new
  np.testing.assert_array_equal(np.asarray(rows), [1, 0, 0, 1])
  np.testing.assert_array_equal(np.asarray(cols), [0, 0, 1, 1])


def test_unique_overflow_drops_latest_not_largest():
  # Regression: overflow must drop the latest-appearing ids, keeping
  # earlier local indices stable (id 10 appears first and must survive).
  import jax.numpy as jnp
  from graphlearn_tpu.ops import unique_stable
  res = unique_stable(jnp.array([10, 1, 2, 3], jnp.int32), capacity=3)
  np.testing.assert_array_equal(np.asarray(res.values), [10, 1, 2])
  np.testing.assert_array_equal(np.asarray(res.inverse), [0, 1, 2, -1])


def test_unique_capacity_larger_than_input():
  res = unique_stable(jnp.array([7, 7, 5], jnp.int32), capacity=10)
  assert int(res.count) == 2
  np.testing.assert_array_equal(np.asarray(res.values[:2]), [7, 5])
  assert (np.asarray(res.values[2:]) == -1).all()


def test_inducer_overflow_keeps_existing_table():
  # Regression: existing table entries must keep their local indices on
  # overflow; only new arrivals get dropped.
  state, _ = init_node(jnp.array([100, 5], jnp.int32), capacity=4)
  nbrs = jnp.array([[1, 2, 3]], jnp.int32)
  state2, rows, cols, _ = induce_next(state, jnp.array([0]), nbrs,
                                      nbrs >= 0)
  nodes = np.asarray(state2.nodes)
  np.testing.assert_array_equal(nodes, [100, 5, 1, 2])  # 3 dropped
  # dropped neighbor's edge is masked out
  np.testing.assert_array_equal(np.asarray(rows), [2, 3, -1])
