"""Tiered distributed feature store: HBM hot shards + host-DRAM cold
tier (VERDICT r2 item 1).

The scale claim under test: the mesh engine must serve feature tables
LARGER than the per-device HBM shard budget.  On the virtual CPU mesh
that is asserted structurally — the device shard array holds only
``ceil(split_ratio * rows)`` rows per partition — while provenance
features (row value == original node id) prove every cold row is
served correctly through the host overlay, and the telemetry reports
the hit rate.  Mirrors the reference's beyond-HBM contract
(`data/feature.py:174-206`, `csrc/cuda/unified_tensor.cu:202+`).
"""
import numpy as np
import jax
import pytest

from graphlearn_tpu.parallel import (DistDataset, DistNeighborLoader,
                                     DistNeighborSampler, make_mesh)
from graphlearn_tpu.parallel.dist_sampler import (DistLinkNeighborLoader,
                                                  DistSubGraphLoader)

N = 64
P = 4


def _ring_dataset(split_ratio, num_parts=P):
  rows = np.concatenate([np.arange(N), np.arange(N)])
  cols = np.concatenate([(np.arange(N) + 1) % N, (np.arange(N) + 2) % N])
  feats = (np.arange(N, dtype=np.float32)[:, None]
           * np.ones((1, 4), np.float32))          # feat[v] == v
  labels = (np.arange(N) % 5).astype(np.int32)
  node_pb = (np.arange(N) % num_parts).astype(np.int32)
  return DistDataset.from_full_graph(
      num_parts, rows, cols, node_feat=feats, node_label=labels,
      num_nodes=N, node_pb=node_pb, split_ratio=split_ratio)


def _assert_provenance(ds, out):
  nodes = np.asarray(out['node'])
  x = np.asarray(out['x'])
  y = np.asarray(out['y'])
  for p in range(ds.num_partitions):
    m = nodes[p] >= 0
    old = ds.new2old[nodes[p][m]]
    np.testing.assert_allclose(x[p][m][:, 0], old.astype(np.float32))
    np.testing.assert_array_equal(y[p][m], old % 5)


def test_tiered_layout_smaller_hbm_shards():
  ds = _ring_dataset(split_ratio=0.5)
  nf = ds.node_features
  assert nf.is_tiered
  # each partition owns 16 rows; the HBM shard holds only 8 of them.
  assert nf.shards.shape == (P, 8, 4)
  np.testing.assert_array_equal(nf.hot_counts, [8, 8, 8, 8])
  assert nf.cold_host.shape == (N, 4)
  # hotness relabel: within each partition, hot rows (the first half of
  # the ownership range) have in-degree >= the cold rows' (ring: all
  # equal, so just check the id map round-trips).
  np.testing.assert_array_equal(np.sort(ds.new2old), np.arange(N))


@pytest.mark.parametrize('split_ratio', [0.0, 0.25, 0.75])
def test_tiered_feature_provenance(split_ratio):
  ds = _ring_dataset(split_ratio)
  sampler = DistNeighborSampler(ds, [2, 2], mesh=make_mesh(P), seed=0)
  assert sampler.tiered
  # seeds span the whole id range so cold rows (the coldest tail of
  # every partition) are guaranteed to appear in the neighborhoods
  seeds = ds.old2new[np.arange(0, N, 2).reshape(P, 8)]
  out = sampler.sample_from_nodes(seeds)
  _assert_provenance(ds, out)
  stats = sampler.exchange_stats()
  # new r10 vocabulary: lookups = all valid feature lookups,
  # cold_lookups = lookups past the hot tier (the cache denominator)
  assert stats['dist.feature.cold_lookups'] > 0
  assert (stats['dist.feature.cold_lookups']
          <= stats['dist.feature.lookups'])
  assert (0 < stats['dist.feature.cold_misses']
          <= stats['dist.feature.cold_lookups'])
  if split_ratio == 0.0:
    # everything is cold: no lookup is hot-served
    assert (stats['dist.feature.cold_lookups']
            == stats['dist.feature.lookups'])
    assert stats['dist.feature.hot_hit_rate'] == 0.0
  else:
    assert (stats['dist.feature.cold_lookups']
            < stats['dist.feature.lookups'])
    assert 0.0 < stats['dist.feature.hot_hit_rate'] < 1.0
  assert 0.0 <= stats['dist.feature.cache_hit_rate'] <= 1.0
  assert (stats['dist.feature.cold_hit_rate']
          == stats['dist.feature.cache_hit_rate'])


def test_tiered_matches_untiered():
  """Tiering must not perturb sampled topology: with fanout >= max
  degree the hop is exact (no RNG influence), so the edge SET in old-id
  space must be identical between the tiered and fully-HBM stores
  (relabels differ — hotness order — so sets, not arrays)."""
  ds_full = _ring_dataset(1.0)
  ds_tier = _ring_dataset(0.4)
  s_full = DistNeighborSampler(ds_full, [2], mesh=make_mesh(P), seed=7)
  s_tier = DistNeighborSampler(ds_tier, [2], mesh=make_mesh(P), seed=7)
  edge_sets = []
  for s, ds in ((s_full, ds_full), (s_tier, ds_tier)):
    out = s.sample_from_nodes(ds.old2new[np.arange(16).reshape(P, 4)])
    _assert_provenance(ds, out)
    nodes = np.asarray(out['node'])
    rows = np.asarray(out['row'])
    cols = np.asarray(out['col'])
    es = set()
    for p in range(P):
      m = rows[p] >= 0
      r_old = ds.new2old[nodes[p][rows[p][m]]]
      c_old = ds.new2old[nodes[p][cols[p][m]]]
      es.update(zip(r_old.tolist(), c_old.tolist()))
    edge_sets.append(es)
  assert edge_sets[0] == edge_sets[1]


def test_tiered_loader_epoch_and_training():
  """Full mesh-loader epoch over a table deliberately larger than the
  HBM shard budget (split_ratio=0.3): every batch trains."""
  import jax.numpy as jnp
  ds = _ring_dataset(0.3)
  loader = DistNeighborLoader(ds, [2, 2], np.arange(N), batch_size=4,
                              shuffle=True, mesh=make_mesh(P), seed=0)
  seen = 0
  for batch in loader:
    x = np.asarray(batch.x)
    nodes = np.asarray(batch.node)
    for p in range(P):
      m = nodes[p] >= 0
      np.testing.assert_allclose(
          x[p][m][:, 0], ds.new2old[nodes[p][m]].astype(np.float32))
    # a model consumes the batch: masked mean must be finite
    total = jnp.where(batch.node_mask[..., None], batch.x, 0).sum()
    assert np.isfinite(float(total))
    seen += 1
  assert seen == len(loader)
  stats = loader.sampler.exchange_stats()
  assert stats['dist.feature.cold_misses'] > 0


@pytest.mark.slow
def test_tiered_link_and_subgraph():
  ds = _ring_dataset(0.5)
  link = DistLinkNeighborLoader(
      ds, [2], edge_label_index=(np.arange(16), (np.arange(16) + 1) % N),
      neg_sampling='binary', batch_size=4, mesh=make_mesh(P), seed=0)
  b = next(iter(link))
  nodes = np.asarray(b.node)
  x = np.asarray(b.x)
  for p in range(P):
    m = nodes[p] >= 0
    np.testing.assert_allclose(
        x[p][m][:, 0], ds.new2old[nodes[p][m]].astype(np.float32))
  sub = DistSubGraphLoader(ds, [2], np.arange(8), batch_size=2,
                           mesh=make_mesh(P), seed=0)
  b = next(iter(sub))
  nodes = np.asarray(b.node)
  x = np.asarray(b.x)
  for p in range(P):
    m = nodes[p] >= 0
    np.testing.assert_allclose(
        x[p][m][:, 0], ds.new2old[nodes[p][m]].astype(np.float32))
