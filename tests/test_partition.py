"""Partitioning tests (mirrors reference `test/python/test_partition.py`
intent): round-trip through the on-disk layout, ownership invariants,
frequency/cache planning."""
import numpy as np
import pytest

from graphlearn_tpu.partition import (FrequencyPartitioner,
                                      RandomPartitioner,
                                      cat_feature_cache, load_partition)


def _graph(n=40, e=200, seed=0):
  rng = np.random.default_rng(seed)
  rows = rng.integers(0, n, e).astype(np.int64)
  cols = rng.integers(0, n, e).astype(np.int64)
  feats = np.arange(n, dtype=np.float32)[:, None] * np.ones((1, 4),
                                                            np.float32)
  labels = (np.arange(n) % 3).astype(np.int32)
  return rows, cols, feats, labels


def test_random_partition_roundtrip(tmp_path):
  n = 40
  rows, cols, feats, labels = _graph(n)
  p = RandomPartitioner(tmp_path, 2, n, (rows, cols), node_feat=feats,
                        node_label=labels, seed=0)
  p.partition()

  all_eids = []
  node_pb_ref = None
  for i in range(2):
    part = load_partition(tmp_path, i)
    node_pb = part['node_pb']
    node_pb_ref = node_pb
    assert node_pb.num_partitions == 2
    r, c = part['graph'].edge_index
    eids = part['graph'].eids
    all_eids.append(eids)
    # by_src ownership: every edge's src belongs to this partition.
    assert (node_pb[r] == i).all()
    # eids point to the original edge list.
    np.testing.assert_array_equal(rows[eids], r)
    np.testing.assert_array_equal(cols[eids], c)
    # features: provenance by value.
    nf = part['node_feat']
    np.testing.assert_allclose(nf.feats[:, 0], nf.ids)
    assert (node_pb[nf.ids] == i).all()
    # labels
    lab, lab_ids = part['node_label']
    np.testing.assert_array_equal(lab, lab_ids % 3)
  # every edge exactly once.
  got = np.sort(np.concatenate(all_eids))
  np.testing.assert_array_equal(got, np.arange(200))
  # balanced: 20 nodes each.
  counts = np.bincount(node_pb_ref.table, minlength=2)
  np.testing.assert_array_equal(counts, [20, 20])


def test_frequency_partitioner_prefers_hot_owner(tmp_path):
  n = 100
  rows, cols, feats, _ = _graph(n, 300)
  # partition 0 is hot on the first half, partition 1 on the second.
  probs = np.zeros((2, n), np.float32)
  probs[0, :50] = 1.0
  probs[1, 50:] = 1.0
  p = FrequencyPartitioner(tmp_path, 2, n, (rows, cols), node_feat=feats,
                           probs=probs, chunk_size=10, cache_ratio=0.1)
  p.partition()
  part0 = load_partition(tmp_path, 0)
  pb = part0['node_pb'].table
  # hot-half ownership respected.
  assert (pb[:50] == 0).all()
  assert (pb[50:] == 1).all()
  # cache: partition 0 caches hottest REMOTE rows — but its remote rows
  # (second half) have hotness 0 for partition 0, so cache picks the
  # highest-scored remote ids deterministically; they must be remote.
  nf = part0['node_feat']
  assert nf.cache_ids is not None and len(nf.cache_ids) == 10
  assert (pb[nf.cache_ids] == 1).all()
  np.testing.assert_allclose(nf.cache_feats[:, 0], nf.cache_ids)


def test_cat_feature_cache():
  from graphlearn_tpu.typing import FeaturePartitionData
  feats = np.arange(4, dtype=np.float32)[:, None]
  ids = np.array([5, 7, 9, 11])
  cache_feats = np.array([[100.0], [101.0]], np.float32)
  cache_ids = np.array([2, 3])
  merged, mids, id2index = cat_feature_cache(
      FeaturePartitionData(feats, ids, cache_feats, cache_ids))
  assert merged.shape == (6, 1)
  # cached rows first (hot tier).
  np.testing.assert_allclose(merged[:2, 0], [100, 101])
  np.testing.assert_array_equal(id2index[[2, 3, 5, 11]], [0, 1, 2, 5])
  assert id2index[4] == -1
  # Feature accepts the merged store directly.
  from graphlearn_tpu.data import Feature
  f = Feature(merged, id2index=id2index, split_ratio=2 / 6)
  out = np.asarray(f[np.array([2, 5, 4])])
  np.testing.assert_allclose(out[:, 0], [100, 0, 0])  # 4 unmapped -> 0
  out2 = np.asarray(f[np.array([11])])
  np.testing.assert_allclose(out2[:, 0], [3.0])


def test_hetero_partition_roundtrip(tmp_path):
  nu, ni = 20, 12
  rng = np.random.default_rng(0)
  rows = rng.integers(0, nu, 60)
  cols = rng.integers(0, ni, 60)
  ET = ('user', 'clicks', 'item')
  p = RandomPartitioner(
      tmp_path, 2, {'user': nu, 'item': ni},
      {ET: (rows, cols)},
      node_feat={'user': np.arange(nu, dtype=np.float32)[:, None]
                 * np.ones((1, 2), np.float32)},
      seed=0)
  p.partition()
  for i in range(2):
    part = load_partition(tmp_path, i)
    assert ET in part['graph']
    r, c = part['graph'][ET].edge_index
    assert (part['node_pb']['user'][r] == i).all()
    nf = part['node_feat']['user']
    np.testing.assert_allclose(nf.feats[:, 0], nf.ids)
