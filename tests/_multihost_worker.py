"""Worker program for the two-process jax.distributed smoke test.

Launched by tests/test_multihost.py as ``python _multihost_worker.py
<coordinator> <num_procs> <proc_id> <out_file>`` with a CPU platform
and 4 virtual devices per process — the JAX analog of the reference's
all-local multi-role tests (`test/python/dist_test_utils.py:15-120`):
the REAL cross-process runtime comes up, the mesh spans both
processes' devices, and one DistNeighborLoader epoch + one DP step run
over it.
"""
import json
import sys

coordinator, num_procs, proc_id, out_file = (
    sys.argv[1], int(sys.argv[2]), int(sys.argv[3]), sys.argv[4])
partition_dir = sys.argv[5] if len(sys.argv) > 5 else None
rich_dir = sys.argv[6] if len(sys.argv) > 6 else None

import numpy as np
from graphlearn_tpu.parallel import multihost

multihost.initialize(coordinator_address=coordinator,
                     num_processes=num_procs, process_id=proc_id)

import jax

assert jax.process_count() == num_procs, jax.process_count()
mesh = multihost.global_mesh()
num_parts = mesh.devices.size
assert num_parts == 8, num_parts

N = 64
rows = np.concatenate([np.arange(N), np.arange(N)])
cols = np.concatenate([(np.arange(N) + 1) % N, (np.arange(N) + 2) % N])
feats = (np.arange(N, dtype=np.float32)[:, None]
         * np.ones((1, 4), np.float32))
labels = (np.arange(N) % 4).astype(np.int32)

from graphlearn_tpu.parallel import (DistDataset, DistNeighborLoader,
                                     make_dp_supervised_step, replicate)

# every host builds the SAME sharded dataset (same seed) and feeds the
# SAME global seed schedule; device_put scatters each host's
# addressable shards
ds = DistDataset.from_full_graph(num_parts, rows, cols, node_feat=feats,
                                 node_label=labels, num_nodes=N, seed=0)

shard = multihost.host_seed_shard(np.arange(N), epoch=0, seed=3)
hsl = multihost.host_device_slice(num_parts)

bs = 4
loader = DistNeighborLoader(ds, [2, 2], np.arange(N), batch_size=bs,
                            shuffle=True, mesh=mesh, seed=0)

import optax
from graphlearn_tpu.models import GraphSAGE, create_train_state

batches = 0
first = None
for batch in loader:
  if first is None:
    first = batch
  batches += 1

model = GraphSAGE(hidden_features=8, out_features=4, num_layers=2)
tx = optax.adam(1e-2)
# single-device template for param init: the local addressable piece
# of the stacked batch
from graphlearn_tpu.parallel import local_batch_piece
local_piece = local_batch_piece(first, num_parts)
state, _ = create_train_state(model, jax.random.key(0), local_piece, tx)
state = replicate(state, mesh)
step = make_dp_supervised_step(model.apply, tx, bs, mesh)
state, loss, correct = step(state, first)
loss_val = float(np.asarray(loss.addressable_shards[0].data))
assert np.isfinite(loss_val), loss_val

host_local = {}
if partition_dir is not None:
  # HOST-LOCAL loading: this process materializes ONLY its mesh
  # positions' partitions; the sampler assembles the global arrays
  # shard-by-shard.  Feature provenance is checked on the local
  # addressable pieces (feat[v, 0] == old id v).
  hp = multihost.host_partition_ids(mesh)
  ds2 = DistDataset.from_partition_dir(partition_dir, num_parts,
                                       host_parts=hp)
  loader2 = DistNeighborLoader(ds2, [2, 2], np.arange(N), batch_size=4,
                               shuffle=True, mesh=mesh, seed=5)
  b2 = next(iter(loader2))
  checked = 0
  for ns, xs in zip(b2.node.addressable_shards,
                    b2.x.addressable_shards):
    nodes = np.asarray(ns.data)[0]
    x = np.asarray(xs.data)[0]
    m = nodes >= 0
    old = ds2.new2old[nodes[m]]
    np.testing.assert_allclose(x[m][:, 0], old.astype(np.float32))
    checked += int(m.sum())
  host_local = {'host_parts': hp.tolist(),
                'provenance_rows': checked}

composed = {}
if rich_dir is not None:
  # the COMPOSED host-local path (r4, the IGBH-large enabler): tiered
  # store + offline cache plan + edge features, all host-local.  Cold
  # rows are OWNER-served across the two REAL processes
  # (`overlay_cold_owner`: process_allgather capacity handshake + two
  # cross-process collectives + each owner gathering from its own
  # DRAM stack); provenance (feat[v, 0] == old id + 1, efeat[e, 0] ==
  # eid) proves every byte arrived from the right host.
  hp = multihost.host_partition_ids(mesh)
  ds3 = DistDataset.from_partition_dir(rich_dir, num_parts,
                                       split_ratio=0.4, host_parts=hp)
  assert ds3.node_features.cold_local is not None
  assert ds3.node_features.has_cache
  assert ds3.edge_features is not None
  loader3 = DistNeighborLoader(ds3, [2, 2], np.arange(N), batch_size=4,
                               shuffle=True, with_edge=True, mesh=mesh,
                               seed=7)
  b3 = next(iter(loader3))
  checked3 = 0
  for ns, xs in zip(b3.node.addressable_shards,
                    b3.x.addressable_shards):
    nodes = np.asarray(ns.data)[0]
    x = np.asarray(xs.data)[0]
    m = nodes >= 0
    old = ds3.new2old[nodes[m]]
    np.testing.assert_allclose(x[m][:, 0], old.astype(np.float32) + 1)
    checked3 += int(m.sum())
  for es, eas, ems in zip(b3.edge.addressable_shards,
                          b3.edge_attr.addressable_shards,
                          b3.edge_mask.addressable_shards):
    eid = np.asarray(es.data)[0]
    ea = np.asarray(eas.data)[0]
    em = np.asarray(ems.data)[0]
    np.testing.assert_allclose(ea[em][:, 0], eid[em])
  st3 = loader3.sampler.exchange_stats(tick_metrics=False)
  composed = {'provenance_rows': checked3,
              'cold_misses': int(st3['dist.feature.cold_misses']),
              'cold_lookups': int(st3['dist.feature.cold_lookups'])}

with open(out_file, 'w') as f:
  json.dump({'proc': proc_id, 'shard': shard.tolist(),
             'host_slice': [hsl.start, hsl.stop],
             'batches': batches, 'loss': loss_val,
             'host_local': host_local, 'composed': composed}, f)
print('WORKER OK', proc_id, loss_val)
