"""Hetero model tests: RGCN/HGT forward + training on a learnable
bipartite task (user labels recoverable from item neighborhoods)."""
import pytest
import numpy as np
import jax
import optax

from graphlearn_tpu.data import Dataset
from graphlearn_tpu.loader import NeighborLoader
from graphlearn_tpu.models import HGT, RGCN
from graphlearn_tpu.typing import reverse_edge_type

U, I = 'user', 'item'
ET_UI = (U, 'clicks', I)
ET_IU = (I, 'rev_clicks', U)
# sampler emits under reversed etypes:
REV_UI = reverse_edge_type(ET_UI)   # (item, clicks, user)... see typing
REV_IU = reverse_edge_type(ET_IU)


def _dataset(nu=48, ni=12, classes=3, d=8, seed=0):
  rng = np.random.default_rng(seed)
  labels = (np.arange(nu) % classes).astype(np.int32)
  # user of class c clicks items from the c-th item block (+ noise).
  block = ni // classes
  rows, cols = [], []
  for u in range(nu):
    c = labels[u]
    for _ in range(3):
      rows.append(u)
      cols.append(c * block + int(rng.integers(0, block)))
    rows.append(u)
    cols.append(int(rng.integers(0, ni)))
  rows, cols = np.array(rows), np.array(cols)
  ufeat = rng.normal(0, 1, (nu, d)).astype(np.float32)  # uninformative
  ifeat = np.eye(ni, dtype=np.float32)[:, :d] if d >= ni else \
      rng.normal(0, 1, (ni, d)).astype(np.float32)
  ifeat = np.pad(np.eye(ni, dtype=np.float32), ((0, 0), (0, max(0, d - ni)))
                 )[:, :d].astype(np.float32)
  ds = (Dataset()
        .init_graph({ET_UI: (rows, cols), ET_IU: (cols, rows)},
                    layout='COO', num_nodes={ET_UI: nu, ET_IU: ni})
        .init_node_features({U: ufeat, I: ifeat}, split_ratio=1.0)
        .init_node_labels({U: labels}))
  return ds


def _etypes_in_batches(loader):
  batch = next(iter(loader))
  return tuple(batch.edge_index_dict.keys())


@pytest.mark.slow
def test_rgcn_trains_on_bipartite_task():
  ds = _dataset(d=12)
  bs = 16
  loader = NeighborLoader(ds, [3, 3], (U, np.arange(48)), batch_size=bs,
                          shuffle=True, seed=0)
  etypes = _etypes_in_batches(loader)
  model = RGCN(etypes=etypes, hidden_features=16, out_features=3,
               num_layers=2, target_ntype=U)
  tx = optax.adam(1e-2)
  batch0 = next(iter(loader))
  params = model.init(jax.random.key(0), batch0.x_dict,
                      batch0.edge_index_dict, batch0.edge_mask_dict)
  opt_state = tx.init(params)

  import jax.numpy as jnp

  @jax.jit
  def step(params, opt_state, batch):
    def loss_fn(p):
      logits = model.apply(p, batch.x_dict, batch.edge_index_dict,
                           batch.edge_mask_dict)
      y = batch.y_dict[U][:bs]
      seeds = batch.batch_dict[U]
      valid = (seeds >= 0).astype(logits.dtype)
      ce = optax.softmax_cross_entropy_with_integer_labels(
          logits[:bs], y)
      return (ce * valid).sum() / jnp.maximum(valid.sum(), 1.0)
    loss, grads = jax.value_and_grad(loss_fn)(params)
    upd, opt_state = tx.update(grads, opt_state, params)
    return optax.apply_updates(params, upd), opt_state, loss

  losses = []
  for _ in range(8):
    for batch in loader:
      params, opt_state, loss = step(params, opt_state, batch)
      losses.append(float(loss))
  assert np.mean(losses[-4:]) < 0.5 * np.mean(losses[:4]), (
      losses[:4], losses[-4:])


def test_hgt_forward():
  ds = _dataset(d=12)
  loader = NeighborLoader(ds, [3, 3], (U, np.arange(16)), batch_size=8,
                          seed=0)
  batch = next(iter(loader))
  etypes = tuple(batch.edge_index_dict.keys())
  model = HGT(ntypes=(U, I), etypes=etypes, hidden_features=16,
              out_features=3, num_layers=2, heads=2, target_ntype=U)
  params = model.init(jax.random.key(0), batch.x_dict,
                      batch.edge_index_dict, batch.edge_mask_dict)
  out = model.apply(params, batch.x_dict, batch.edge_index_dict,
                    batch.edge_mask_dict)
  assert out.shape == (batch.x_dict[U].shape[0], 3)
  assert np.isfinite(np.asarray(out)).all()


def test_heteroconv_factory_rgat():
  """make_conv factory path (RGAT flavor): per-etype GAT attention run
  bipartite via source-offset concatenation."""
  import flax.linen as nn
  from graphlearn_tpu.models import GATConv, HeteroConv

  ds = _dataset(d=12)
  loader = NeighborLoader(ds, [3, 3], (U, np.arange(16)), batch_size=8,
                          seed=0)
  batch = next(iter(loader))
  etypes = tuple(batch.edge_index_dict.keys())

  class RGAT(nn.Module):
    @nn.compact
    def __call__(self, x_dict, ei_dict, em_dict):
      h = {nt: nn.Dense(16)(x) for nt, x in x_dict.items()}
      for li in range(2):
        conv = HeteroConv(etypes, 16,
                          make_conv=lambda: GATConv(8, heads=2),
                          name=f'conv{li}')
        h = conv(h, ei_dict, em_dict)
        h = {nt: nn.relu(v) for nt, v in h.items()}
      return nn.Dense(3)(h[U])

  model = RGAT()
  params = model.init(jax.random.key(0), batch.x_dict,
                      batch.edge_index_dict, batch.edge_mask_dict)
  out = model.apply(params, batch.x_dict, batch.edge_index_dict,
                    batch.edge_mask_dict)
  assert out.shape == (batch.x_dict[U].shape[0], 3)
  assert np.isfinite(np.asarray(out)).all()


def test_heteroconv_factory_rejects_width_mismatch():
  import pytest
  import jax.numpy as jnp
  from graphlearn_tpu.models import HeteroConv, SAGEConv

  et = (U, 'clicks', I)
  conv = HeteroConv((et,), 8, make_conv=lambda: SAGEConv(8))
  x = {U: jnp.ones((4, 6)), I: jnp.ones((3, 5))}
  ei = {et: jnp.zeros((2, 2), jnp.int32)}
  with pytest.raises(ValueError, match='equal feature widths'):
    conv.init(jax.random.key(0), x, ei, None)


def test_hgt_bf16_dtype():
  """bfloat16 compute keeps params/outputs f32 in the hetero stack."""
  import jax
  import jax.numpy as jnp
  import numpy as np
  from graphlearn_tpu.models import HGT

  rng = np.random.default_rng(0)
  U, V = 'u', 'v'
  ET1, ET2 = (U, 'r', V), (V, 'rev_r', U)
  x = {U: jnp.asarray(rng.standard_normal((16, 8)).astype(np.float32)),
       V: jnp.asarray(rng.standard_normal((12, 8)).astype(np.float32))}
  ei = {ET1: jnp.asarray(np.stack([rng.integers(0, 16, 24),
                                   rng.integers(0, 12, 24)])),
        ET2: jnp.asarray(np.stack([rng.integers(0, 12, 24),
                                   rng.integers(0, 16, 24)]))}
  em = {k: v[0] >= 0 for k, v in ei.items()}
  model = HGT(ntypes=(U, V), etypes=(ET1, ET2), hidden_features=16,
              out_features=4, num_layers=2, target_ntype=U,
              dtype=jnp.bfloat16)
  params = model.init(jax.random.key(0), x, ei, em)
  out = model.apply(params, x, ei, em)
  assert out.dtype == jnp.float32
  assert out.shape == (16, 4)
  assert all(p.dtype == jnp.float32
             for p in jax.tree_util.tree_leaves(params))
  assert bool(jnp.isfinite(out).all())
