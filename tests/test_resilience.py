"""Resilience layer unit + RPC-level tests (ISSUE 4): retry policy
determinism, per-request timeouts, socket reset on transport faults,
and the server-side replay cache's exactly-once guarantee.  All
against a real localhost `RpcServer` — no mocks, no native dependency
(payloads stay on the pickle path).
"""
import threading
import time

import pytest

from graphlearn_tpu.distributed.resilience import (PeerLostError,
                                                   RetryExhausted,
                                                   RetryPolicy,
                                                   reset_default_policy)
from graphlearn_tpu.distributed.rpc import (RpcClient, RpcError,
                                            RpcServer)
from graphlearn_tpu.telemetry import recorder
from graphlearn_tpu.testing import chaos


@pytest.fixture(autouse=True)
def _clean():
  reset_default_policy()
  chaos.uninstall()
  recorder.enable(None)
  recorder.clear()
  yield
  chaos.uninstall()
  recorder.clear()
  recorder.disable()
  reset_default_policy()


def _fast_policy(**kw):
  kw.setdefault('request_timeout', 2.0)
  kw.setdefault('deadline', 6.0)
  kw.setdefault('base_delay', 0.01)
  kw.setdefault('max_delay', 0.05)
  kw.setdefault('seed', 7)
  return RetryPolicy(**kw)


# -- policy -----------------------------------------------------------------
def test_retry_policy_deterministic_schedule():
  a = RetryPolicy(base_delay=0.1, max_delay=1.0, jitter=0.5, seed=42)
  b = RetryPolicy(base_delay=0.1, max_delay=1.0, jitter=0.5, seed=42)
  da = [a.delay(i) for i in range(8)]
  db = [b.delay(i) for i in range(8)]
  assert da == db, 'same seed must give the same jittered schedule'
  c = RetryPolicy(base_delay=0.1, max_delay=1.0, jitter=0.5, seed=43)
  assert [c.delay(i) for i in range(8)] != da


def test_retry_policy_capped_exponential():
  p = RetryPolicy(base_delay=0.1, max_delay=0.4, jitter=0.0, seed=0)
  assert [p.delay(i) for i in range(5)] == [0.1, 0.2, 0.4, 0.4, 0.4]


def test_retry_policy_from_env(monkeypatch):
  monkeypatch.setenv('GLT_RPC_TIMEOUT', '3.5')
  monkeypatch.setenv('GLT_RPC_DEADLINE', '11')
  monkeypatch.setenv('GLT_RPC_BACKOFF_BASE', '0.2')
  monkeypatch.setenv('GLT_RPC_RETRY_SEED', '9')
  p = RetryPolicy.from_env()
  assert (p.request_timeout, p.deadline, p.base_delay, p.seed) == \
      (3.5, 11.0, 0.2, 9)
  monkeypatch.setenv('GLT_RPC_DEADLINE', 'not-a-number')
  assert RetryPolicy.from_env().deadline == 120.0   # degrade, not raise


def test_error_hierarchy():
  assert issubclass(RetryExhausted, RpcError)
  assert issubclass(PeerLostError, RpcError)
  e = PeerLostError('gone', peer=3, received=4, expected=10,
                    outstanding=6)
  assert (e.peer, e.received, e.expected, e.outstanding) == (3, 4, 10, 6)


# -- rpc transport ----------------------------------------------------------
@pytest.fixture
def server():
  srv = RpcServer('127.0.0.1', 0)
  calls = []
  lock = threading.Lock()

  def bump(tag='x'):
    with lock:
      calls.append(tag)
    return len(calls)

  srv.register('bump', bump)
  srv.register('echo', lambda v: v)
  srv.register('slow', lambda secs: (time.sleep(secs), bump('slow'))[1])
  srv.register('boom', lambda: 1 / 0)
  srv.start()
  srv.calls = calls
  yield srv
  srv.shutdown()


def test_basic_roundtrip_and_probe(server):
  cli = RpcClient('127.0.0.1', server.port, policy=_fast_policy())
  assert cli.request('echo', {'a': 1}) == {'a': 1}
  assert cli.probe()
  cli.close()
  with pytest.raises(RpcError):
    cli.request('echo', 1)        # closed clients refuse, not hang


def test_application_error_no_retry(server):
  cli = RpcClient('127.0.0.1', server.port, policy=_fast_policy())
  with pytest.raises(RpcError, match='ZeroDivisionError'):
    cli.request('boom')
  assert not recorder.events('rpc.retry'), \
      'application errors must not burn retry budget'
  cli.close()


def test_drop_fault_retries_without_double_execution(server):
  cli = RpcClient('127.0.0.1', server.port, policy=_fast_policy())
  assert cli.request('bump') == 1
  chaos.install({'faults': [{'site': 'rpc.request', 'action': 'drop',
                             'nth': 1, 'op': 'bump'}]})
  out = cli.request('bump')
  assert out == 2, 'retried request must be answered from replay cache'
  assert len(server.calls) == 2, 'handler must NOT run twice'
  retries = recorder.events('rpc.retry')
  assert retries and retries[0]['op'] == 'bump'
  injected = recorder.events('fault.injected')
  assert injected and injected[0]['action'] == 'drop'
  cli.close()


def test_corrupt_reply_resets_and_retries(server):
  cli = RpcClient('127.0.0.1', server.port, policy=_fast_policy())
  chaos.install({'faults': [{'site': 'rpc.request', 'action': 'corrupt',
                             'nth': 1, 'op': 'echo'}]})
  # a scrambled reply must not poison the stream: the socket is reset
  # and the retry parses a clean frame
  assert cli.request('echo', [1, 2, 3]) == [1, 2, 3]
  assert recorder.events('rpc.retry')
  assert cli.request('echo', 'after') == 'after'   # stream healthy
  cli.close()


def test_delay_fault_sleeps_then_succeeds(server):
  cli = RpcClient('127.0.0.1', server.port, policy=_fast_policy())
  chaos.install({'faults': [{'site': 'rpc.request', 'action': 'delay',
                             'nth': 1, 'op': 'echo', 'secs': 0.3}]})
  t0 = time.monotonic()
  assert cli.request('echo', 5) == 5
  assert time.monotonic() - t0 >= 0.3
  cli.close()


def test_slow_request_times_out_but_replay_keeps_it_exactly_once(server):
  # per-request timeout (0.4s) < handler latency (1.2s): the client
  # retries; every retry parks on the in-flight replay entry instead
  # of re-executing; the reply lands on the retry that survives
  cli = RpcClient('127.0.0.1', server.port,
                  policy=_fast_policy(request_timeout=0.4, deadline=8.0))
  out = cli.request('slow', 1.2)
  assert out == 1
  assert server.calls == ['slow'], 'slow handler must run exactly once'
  assert recorder.events('rpc.retry')
  cli.close()


def test_dead_server_retry_exhausted_and_probe_false(server):
  cli = RpcClient('127.0.0.1', server.port,
                  policy=_fast_policy(deadline=1.0, request_timeout=0.5))
  assert cli.request('echo', 1) == 1
  server.shutdown()
  with pytest.raises(RetryExhausted):
    cli.request('echo', 2)
  assert not cli.probe(timeout=0.5)
  cli.close()


def test_reconnect_after_transient_death():
  srv = RpcServer('127.0.0.1', 0)
  srv.register('echo', lambda v: v)
  srv.start()
  port = srv.port
  cli = RpcClient('127.0.0.1', port,
                  policy=_fast_policy(deadline=10.0, request_timeout=0.5))
  assert cli.request('echo', 1) == 1
  srv.shutdown()

  def resurrect():
    time.sleep(0.6)
    srv2 = RpcServer('127.0.0.1', port)
    srv2.register('echo', lambda v: v)
    srv2.start()
    resurrect.srv2 = srv2

  t = threading.Thread(target=resurrect)
  t.start()
  # transparent reconnect: the request rides out the outage
  assert cli.request('echo', 'back') == 'back'
  t.join()
  cli.close()
  resurrect.srv2.shutdown()


# -- server shutdown diagnostics --------------------------------------------
def test_wait_for_exit_timeout_logs_missing_clients():
  from graphlearn_tpu.distributed.dist_server import DistServer
  srv = DistServer(dataset=None)
  srv.rank = 2
  srv.num_clients = 3
  srv.notify_leave(1)
  assert srv.wait_for_exit(timeout=0.05) is False
  evs = recorder.events('server.shutdown_timeout')
  assert len(evs) == 1
  assert evs[0]['clients_never_exited'] == [0, 2]
  assert evs[0]['clients_left'] == [1]
  assert evs[0]['rank'] == 2


def test_heartbeat_reports_producers():
  from graphlearn_tpu.distributed.dist_server import DistServer
  srv = DistServer(dataset=None)
  hb = srv.heartbeat()
  assert hb['producers'] == {} and 'time' in hb


# -- replay-cache horizon (ISSUE 6 satellite) -------------------------------
def test_replay_cache_eviction_watermark_unit():
  """`_ReplayCache.begin` for a seq pruned under entry pressure must
  report EVICTED (never hand out a fresh entry that would re-execute):
  client seqs are monotone, so a pruned seq below the per-client
  watermark can only be a retry whose reply is gone."""
  from graphlearn_tpu.distributed.rpc import _ReplayCache
  cache = _ReplayCache(max_entries=2)
  for seq in range(4):
    ent, fresh = cache.begin('tok', seq)
    assert fresh is True
    ent.frame = (b'h', b'x' * 8)
    ent.done_at = time.monotonic()
    ent.done.set()
  # seqs 0/1 were pruned by the entry bound as 2/3 landed
  got = cache.begin('tok', 0)
  assert got == (None, _ReplayCache.EVICTED)
  # live entries still replay
  ent, fresh = cache.begin('tok', 3)
  assert fresh is False and ent.frame is not None
  # an UNSEEN higher seq is still fresh
  _, fresh = cache.begin('tok', 9)
  assert fresh is True


def test_replay_evicted_retry_gets_typed_error_not_reexecution(server):
  """End-to-end horizon contract: a retry whose replay entry was
  pruned under cache pressure gets `ReplayEvictedError` — the handler
  must NOT run a second time under the same request id (exactly-once
  beats availability here)."""
  from graphlearn_tpu.distributed.resilience import ReplayEvictedError
  cli = RpcClient('127.0.0.1', server.port, policy=_fast_policy())
  assert cli.request('bump') == 1                # seq 0, cached
  server._replay._max_entries = 1               # cache pressure
  assert cli.request('bump') == 2                # seq 1 evicts seq 0
  executed = len(server.calls)
  # a zombie retry of seq 0 (the client re-presents the same request
  # id after its reply was pruned)
  import itertools
  cli._seq = itertools.count(0)
  with pytest.raises(ReplayEvictedError, match='evicted'):
    cli.request('bump')
  assert len(server.calls) == executed, \
      'the evicted request id must never re-execute'
  cli.close()
