"""Pallas feature-gather kernel vs the XLA gather (interpret mode).

Real-chip validation runs as a plain script on TPU (the kernel was
verified bit-exact on v5e); here the same kernel runs through the
Pallas interpreter on the CPU backend, mirroring the reference's
C++ gtest of ``GatherTensorKernel`` (`test/cpp/test_unified_tensor.cu`).
"""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

from graphlearn_tpu.ops.pallas_gather import gather_rows


@pytest.mark.parametrize('n,d,b,tile', [
    (500, 128, 37, 8),     # unaligned batch -> padded grid tail
    (100, 256, 64, 32),    # batch smaller than tile
    (1000, 128, 256, 16),
])
def test_gather_rows_matches_xla(n, d, b, tile):
  rng = np.random.default_rng(0)
  table = jnp.asarray(rng.standard_normal((n, d)).astype(np.float32))
  idx = jnp.asarray(rng.integers(0, n, b).astype(np.int32))
  out = gather_rows(table, idx, tile=tile, interpret=True)
  assert out.shape == (b, d)
  np.testing.assert_array_equal(np.asarray(out),
                                np.asarray(jnp.take(table, idx, axis=0)))


def test_gather_rows_int32_table():
  rng = np.random.default_rng(1)
  table = jnp.asarray(rng.integers(0, 1 << 30, (300, 128)).astype(np.int32))
  idx = jnp.asarray(rng.integers(0, 300, 50).astype(np.int32))
  out = gather_rows(table, idx, interpret=True)
  np.testing.assert_array_equal(np.asarray(out),
                                np.asarray(jnp.take(table, idx, axis=0)))


def test_gather_rows_repeated_and_boundary_ids():
  table = jnp.arange(64 * 128, dtype=jnp.float32).reshape(64, 128)
  idx = jnp.asarray([0, 63, 0, 63, 7, 7, 7], dtype=jnp.int32)
  out = gather_rows(table, idx, tile=4, interpret=True)
  np.testing.assert_array_equal(np.asarray(out),
                                np.asarray(table)[np.asarray(idx)])


def test_unaligned_dim_falls_back():
  # d % 128 != 0 on a compiled backend falls back to XLA take; in
  # interpret mode the DMA path itself handles it — both must agree.
  rng = np.random.default_rng(2)
  table = jnp.asarray(rng.standard_normal((100, 100)).astype(np.float32))
  idx = jnp.asarray(rng.integers(0, 100, 17).astype(np.int32))
  out = gather_rows(table, idx, interpret=True)
  np.testing.assert_array_equal(np.asarray(out),
                                np.asarray(jnp.take(table, idx, axis=0)))


def test_feature_store_uses_kernel(monkeypatch):
  # Force the pallas path (interpret on CPU) through Feature.__getitem__.
  monkeypatch.setenv('GLT_PALLAS', '1')
  from graphlearn_tpu.data.feature import Feature
  rng = np.random.default_rng(3)
  feats = rng.standard_normal((200, 128)).astype(np.float32)
  f = Feature(feats, split_ratio=1.0)
  ids = np.array([5, -1, 199, 0, 5], dtype=np.int64)
  out = np.asarray(f[ids])
  assert out.shape == (5, 128)
  np.testing.assert_array_equal(out[1], np.zeros(128, np.float32))
  np.testing.assert_allclose(out[0], feats[5], rtol=0, atol=0)
  np.testing.assert_allclose(out[2], feats[199], rtol=0, atol=0)


def test_dma_id_budget_routes_to_take(monkeypatch):
  """Oversized id vectors must NEVER reach the DMA kernel: the ids
  are scalar-prefetched into SMEM (1 MB), and products-scale
  collation gathers ~938k ids — 4x the budget (r4 discovery: the
  kernel aborts with an smem allocation error at 2^20 ids, so any
  lane-aligned table at that batch would have crashed)."""
  import jax.numpy as jnp
  from graphlearn_tpu.ops import pallas_gather as pg
  called = {}

  def spy(table, idx, **k):
    # no pass-through: reaching the kernel at all IS the failure, and
    # the real kernel with interpret=False would die in lowering
    # before the assert below could fire
    called['dma'] = True
    return pg._xla_take(table, idx)

  monkeypatch.setattr(pg, '_gather_rows_dma', spy)
  table = jnp.arange(64 * 128, dtype=jnp.float32).reshape(64, 128)
  big = jnp.zeros((pg._MAX_DMA_IDS + 8,), jnp.int32)
  out = pg.gather_rows(table, big, interpret=False)
  assert 'dma' not in called, 'oversized ids reached the DMA kernel'
  assert out.shape == (pg._MAX_DMA_IDS + 8, 128)
  np.testing.assert_array_equal(np.asarray(out[0]),
                                np.asarray(table[0]))
