"""Adaptive exchange capacity (SURVEY §7 "partition-aware capacity
tuning", self-tuning arm): the controller tightens on drop-free
epochs, widens on drops, and pins after its first reversal — verified
on a balanced and a deliberately skewed partition book."""
import numpy as np
import pytest

from graphlearn_tpu.parallel import (DistDataset, DistNeighborLoader,
                                     make_mesh)
from graphlearn_tpu.parallel.dist_sampler import (DEFAULT_EXCHANGE_SLACK,
                                                  SLACK_LADDER,
                                                  AdaptiveSlack)

N = 256
P = 4


def _dataset(balanced=True):
  rows = np.concatenate([np.arange(N), np.arange(N)])
  cols = np.concatenate([(np.arange(N) + 1) % N, (np.arange(N) + 2) % N])
  feats = np.arange(N, dtype=np.float32)[:, None] * np.ones((1, 3),
                                                            np.float32)
  if balanced:
    node_pb = (np.arange(N) % P).astype(np.int32)
  else:
    # partition 0 owns 85% of the nodes: every shuffled batch's
    # frontier floods owner 0 past any capped share
    node_pb = np.zeros(N, np.int32)
    node_pb[int(N * 0.85):] = np.arange(N - int(N * 0.85)) % (P - 1) + 1
  return DistDataset.from_full_graph(P, rows, cols, node_feat=feats,
                                     num_nodes=N, node_pb=node_pb)


def _epochs(loader, n):
  for _ in range(n):
    for b in loader:
      pass
    yield loader._adaptive


def test_adaptive_tightens_when_balanced():
  loader = DistNeighborLoader(_dataset(True), [2, 2], np.arange(N),
                              batch_size=8, shuffle=True,
                              mesh=make_mesh(P), seed=0,
                              exchange_slack='adaptive')
  assert loader._adaptive.slack == DEFAULT_EXCHANGE_SLACK
  ctl = None
  for ctl in _epochs(loader, 3):
    pass
  # drop-free balanced epochs walk DOWN the ladder
  assert ctl.slack is not None
  assert ctl.slack < DEFAULT_EXCHANGE_SLACK
  st = loader.sampler.exchange_stats(tick_metrics=False)
  assert st['dist.frontier.dropped'] == 0
  # batches stay provenance-correct at the tightened capacity
  for b in loader:
    nodes = np.asarray(b.node)
    x = np.asarray(b.x)
    for p in range(P):
      m = nodes[p] >= 0
      np.testing.assert_allclose(
          x[p][m][:, 0],
          loader.ds.new2old[nodes[p][m]].astype(np.float32))


@pytest.mark.slow
def test_adaptive_widens_and_pins_when_skewed():
  # batch 64/device: hop-2 frontiers (256 ids) exceed the capped
  # shares, so the 85% owner drops ids at every finite slack —
  # MIN_EXCHANGE_CAP makes smaller frontiers effectively exact
  loader = DistNeighborLoader(_dataset(False), [2, 2], np.arange(N),
                              batch_size=64, shuffle=True,
                              mesh=make_mesh(P), seed=0,
                              exchange_slack='adaptive')
  hist = []
  for ctl in _epochs(loader, 5):
    hist.append(ctl.slack)
  # the controller must end wider than the default (or pinned after a
  # reversal), and once pinned it stops moving
  idx = SLACK_LADDER.index(hist[-1])
  assert idx > SLACK_LADDER.index(DEFAULT_EXCHANGE_SLACK) or ctl._pinned
  if ctl._pinned:
    assert hist[-1] == hist[-2]
  st = loader.sampler.exchange_stats(tick_metrics=False)
  assert st['dist.frontier.dropped'] > 0


def test_adaptive_requires_shuffle():
  with pytest.raises(ValueError, match='adaptive'):
    DistNeighborLoader(_dataset(True), [2], np.arange(N), batch_size=8,
                       shuffle=False, mesh=make_mesh(P),
                       exchange_slack='adaptive')


def test_adaptive_controller_unit():
  """Ladder mechanics without a mesh: fake sampler counters."""
  class FakeSampler:
    exchange_slack = None
    _steps = {}

    def __init__(self):
      self.offered = 0
      self.dropped = 0

    def exchange_stats(self, tick_metrics=True):
      return {'dist.frontier.offered': self.offered,
              'dist.frontier.dropped': self.dropped,
              'dist.feature.offered': 0, 'dist.feature.dropped': 0,
              'dist.negative.lost': 0}

  s = FakeSampler()
  ctl = AdaptiveSlack(s)
  assert s.exchange_slack == DEFAULT_EXCHANGE_SLACK
  # clean epoch: tighten
  s.offered = 1000
  ctl.on_epoch_end()
  assert ctl.slack == 1.5
  # clean again: tighten to the floor
  s.offered = 2000
  ctl.on_epoch_end()
  assert ctl.slack == 1.25
  # drops: widen back, and that reversal pins
  s.offered, s.dropped = 3000, 100
  ctl.on_epoch_end()
  assert ctl.slack == 1.5 and ctl._pinned
  s.offered, s.dropped = 4000, 200
  ctl.on_epoch_end()
  assert ctl.slack == 1.5          # pinned: no further movement


def _fake_sampler():
  class FakeSampler:
    exchange_slack = None
    _steps = {}

    def __init__(self):
      self.offered = 0
      self.dropped = 0

    def exchange_stats(self, tick_metrics=True):
      return {'dist.frontier.offered': self.offered,
              'dist.frontier.dropped': self.dropped,
              'dist.feature.offered': 0, 'dist.feature.dropped': 0,
              'dist.negative.lost': 0}
  return FakeSampler()


def test_ladder_floor_configurable_and_pins_there():
  """ISSUE 3 satellite: the ladder keeps tightening while epochs stay
  drop-free, down to a CONFIGURABLE floor, and a drop-free epoch at
  the floor pins with pin_reason='floor' instead of silently idling
  (the r5 envelope's 'stuck at 1.25' ambiguity)."""
  import sys
  from graphlearn_tpu.telemetry.recorder import EventRecorder
  rec_mod = sys.modules['graphlearn_tpu.telemetry.recorder']
  rec = EventRecorder()
  rec.enable()
  orig = rec_mod.recorder
  rec_mod.recorder = rec
  try:
    s = _fake_sampler()
    ctl = AdaptiveSlack(s, floor=1.0)
    assert ctl.floor == 1.0
    for _ in range(4):               # 2.0 -> 1.5 -> 1.25 -> 1.0
      s.offered += 1000              # cumulative counters grow
      ctl.on_epoch_end()
    assert ctl.slack == 1.0          # below the old 1.25 terminus
    assert ctl._pinned               # 4th drop-free epoch: floor pin
    pins = rec.events('slack.pinned')
    assert pins and pins[-1]['pin_reason'] == 'floor'
    # a FLOOR pin only stops tightening: drops arriving later must
    # still widen (then hard-pin as a reversal) — the safety response
    # survives the pin
    s.offered, s.dropped = s.offered + 1000, 50
    ctl.on_epoch_end()
    assert ctl.slack == 1.25
    assert ctl._pinned and ctl._pin_reason == 'reversal'
    s.offered, s.dropped = s.offered + 1000, 100
    ctl.on_epoch_end()
    assert ctl.slack == 1.25         # reversal pin is final
    # transitions carry the pin_reason field ('' while walking)
    trans = rec.events('slack.transition')
    assert trans and all('pin_reason' in t for t in trans)
    # a reversal pin reports its own reason
    s2 = _fake_sampler()
    ctl2 = AdaptiveSlack(s2, floor=1.0)
    s2.offered = 1000
    ctl2.on_epoch_end()              # tighten 2.0 -> 1.5
    s2.offered, s2.dropped = 2000, 100
    ctl2.on_epoch_end()              # widen back: reversal pin
    assert ctl2._pinned
    assert rec.events('slack.pinned')[-1]['pin_reason'] == 'reversal'
    assert rec.events('slack.transition')[-1]['pin_reason'] == \
        'reversal'
  finally:
    rec_mod.recorder = orig
    rec.disable()


def test_ladder_floor_from_env(monkeypatch):
  monkeypatch.setenv('GLT_SLACK_FLOOR', '0.75')
  ctl = AdaptiveSlack(_fake_sampler())
  assert ctl.floor == 0.75
  monkeypatch.setenv('GLT_SLACK_FLOOR', '1.5')
  ctl2 = AdaptiveSlack(_fake_sampler())
  assert ctl2.floor == 1.5
  s = ctl2.sampler
  s.offered = 500
  ctl2.on_epoch_end()
  s.offered = 1000
  ctl2.on_epoch_end()
  assert ctl2.slack == 1.5           # floored above the old terminus


@pytest.mark.slow
def test_adaptive_with_tiered_store_and_prefetch():
  """The three r3 levers compose: adaptive capacity retunes across
  epochs while the tiered store's cold overlay and the prefetch worker
  keep serving ground-truth features at every slack visited."""
  rows = np.concatenate([np.arange(N), np.arange(N)])
  cols = np.concatenate([(np.arange(N) + 1) % N, (np.arange(N) + 2) % N])
  feats = np.arange(N, dtype=np.float32)[:, None] * np.ones((1, 3),
                                                            np.float32)
  ds = DistDataset.from_full_graph(P, rows, cols, node_feat=feats,
                                   num_nodes=N, split_ratio=0.4)
  loader = DistNeighborLoader(ds, [2, 2], np.arange(N), batch_size=16,
                              shuffle=True, mesh=make_mesh(P), seed=1,
                              exchange_slack='adaptive', prefetch=2)
  for _ in range(3):
    for b in loader:
      nodes = np.asarray(b.node)
      x = np.asarray(b.x)
      for p in range(P):
        m = nodes[p] >= 0
        np.testing.assert_allclose(
            x[p][m][:, 0], ds.new2old[nodes[p][m]].astype(np.float32))
  st = loader.sampler.exchange_stats(tick_metrics=False)
  assert st['dist.feature.cold_misses'] > 0
  assert loader._adaptive.slack != DEFAULT_EXCHANGE_SLACK or \
      loader._adaptive._pinned
