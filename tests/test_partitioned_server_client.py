"""Partitioned server-client deployment: every sampling server owns ONE
shard, producers fan each hop/feature lookup out to peer servers over
RPC (VERDICT r2 item 2, full-stack arm).

All roles are local processes (SURVEY §4: real RPC + shm + producer
subprocesses, no mocks): 2 shard servers x 1 producer worker each, one
client loader spread over both servers, provenance features asserting
remote rows arrive intact and exact (fanout >= degree) neighborhoods
asserting per-hop fan-out actually happened.
"""
import multiprocessing as mp

import numpy as np
import pytest

from graphlearn_tpu import native

pytestmark = pytest.mark.skipif(not native.available(),
                                reason='native lib unavailable')

N = 40


def _write_partitions(root):
  from graphlearn_tpu.partition import RandomPartitioner
  rows = np.repeat(np.arange(N), 2)
  cols = np.stack([(np.arange(N) + 1) % N,
                   (np.arange(N) + 2) % N], 1).reshape(-1)
  feats = np.tile(np.arange(N, dtype=np.float32)[:, None], (1, 4))
  RandomPartitioner(root, 2, N, (rows, cols), node_feat=feats,
                    node_label=(np.arange(N) % 4), seed=0).partition()


def _shard_server_proc(root, rank, port_q):
  from graphlearn_tpu.distributed import (HostDataset, init_server,
                                          wait_and_shutdown_server)
  shard = HostDataset.from_partition_dir(root, rank)
  srv = init_server(num_servers=2, num_clients=1, rank=rank,
                    dataset=shard, host='127.0.0.1', port=0)
  port_q.put(srv.port)
  wait_and_shutdown_server(timeout=120)


@pytest.mark.slow
def test_partitioned_server_client_loader(tmp_path):
  _write_partitions(tmp_path)
  ctx = mp.get_context('forkserver')
  procs, ports = [], []
  for rank in range(2):
    q = ctx.Queue()
    p = ctx.Process(target=_shard_server_proc,
                    args=(str(tmp_path), rank, q), daemon=False)
    p.start()
    procs.append(p)
    ports.append(q.get(timeout=60))

  from graphlearn_tpu.distributed import (
      DistNeighborLoader, HostSamplingConfig,
      RemoteDistSamplingWorkerOptions, init_client, shutdown_client)
  addrs = tuple(('127.0.0.1', pt) for pt in ports)
  init_client(list(addrs), rank=0, num_clients=1)
  loader = DistNeighborLoader(
      None, [2, 2], np.arange(N), batch_size=8, shuffle=False,
      worker_options=RemoteDistSamplingWorkerOptions(
          server_rank=[0, 1], num_workers=1, prefetch_size=2),
      sampling_config=HostSamplingConfig(sampling_type='node',
                                         peer_addrs=addrs),
      to_device=False)
  for _ in range(2):
    seeds_seen = []
    for batch in loader:
      ids = np.asarray(batch.node)
      valid = np.asarray(batch.node_mask)
      # remote feature rows intact (zero-filled -> mismatch)
      np.testing.assert_allclose(np.asarray(batch.x)[:, 0][valid],
                                 ids[valid].astype(np.float32))
      np.testing.assert_array_equal(np.asarray(batch.y)[valid],
                                    ids[valid] % 4)
      s = np.asarray(batch.batch)
      s = s[s >= 0]
      seeds_seen.append(s)
      # fanout == degree: the 2-hop closure must be EXACT — a shard-
      # local sampler would miss every remotely-owned frontier row
      expect = set()
      for sd in s:
        expect.update(((sd + d) % N) for d in range(5))
      assert set(ids[valid].tolist()) == expect
    np.testing.assert_array_equal(np.sort(np.concatenate(seeds_seen)),
                                  np.arange(N))
  loader.shutdown()
  shutdown_client()
  for p in procs:
    p.join(timeout=30)
    assert not p.is_alive()
