"""Regression: the fused-epoch runners compose with the aux
subsystems — checkpoint/resume mid-training and the metrics registry —
the same way the per-batch loaders do."""
import numpy as np
import jax
import jax.numpy as jnp
import optax

from graphlearn_tpu.data import Dataset
from graphlearn_tpu.loader import FusedEpoch, NeighborLoader
from graphlearn_tpu.models import GraphSAGE, create_train_state
from graphlearn_tpu.utils import Checkpointer
from graphlearn_tpu.utils.profiling import metrics
import pytest

#: CPU-mesh scan-compile heavy (multi-minute): excluded from the
#: default run, selected by `pytest -m slow` (see pyproject.toml)
pytestmark = pytest.mark.slow


def _dataset(n=90, d=8, classes=3, seed=0):
  rng = np.random.default_rng(seed)
  labels = (np.arange(n) % classes).astype(np.int32)
  rows, cols = [], []
  for v in range(n):
    for _ in range(6):
      if rng.random() < 0.85:
        u = int(rng.choice(np.nonzero(labels == labels[v])[0]))
      else:
        u = int(rng.integers(0, n))
      rows.append(v)
      cols.append(u)
  feats = np.eye(classes, d, dtype=np.float32)[labels]
  feats += rng.normal(0, 0.3, feats.shape).astype(np.float32)
  return (Dataset()
          .init_graph((np.array(rows), np.array(cols)), layout='COO',
                      num_nodes=n)
          .init_node_features(feats)
          .init_node_labels(labels))


def test_fused_checkpoint_resume(tmp_path):
  """Train fused -> checkpoint -> restore into a FRESH runner ->
  continue training: the restored run keeps improving and evaluates
  like the uninterrupted one."""
  ds = _dataset()
  model = GraphSAGE(hidden_features=16, out_features=3, num_layers=2)
  tx = optax.adam(1e-2)
  loader = NeighborLoader(ds, [4, 3], np.arange(90), batch_size=32)
  state, apply_fn = create_train_state(
      model, jax.random.key(0), next(iter(loader)), tx)

  fused = FusedEpoch(ds, [4, 3], np.arange(90), apply_fn, tx,
                     batch_size=32, shuffle=True, seed=0)
  for _ in range(8):
    state, stats = fused.run(state)
  mid_loss = stats['loss']
  ckpt = Checkpointer(tmp_path / 'ck', max_to_keep=2)
  ckpt.save(8, state)

  # fresh process analog: new runner + template-restored state
  template, _ = create_train_state(
      model, jax.random.key(0), next(iter(loader)), tx)
  restored = ckpt.restore(template=template)
  assert restored is not None
  state2 = jax.tree_util.tree_map(jnp.asarray, restored)
  assert int(state2.step) == int(state.step)
  fused2 = FusedEpoch(ds, [4, 3], np.arange(90), apply_fn, tx,
                      batch_size=32, shuffle=True, seed=1)
  for _ in range(8):
    state2, stats2 = fused2.run(state2)
  assert stats2['loss'] < mid_loss          # resumed run keeps learning
  acc = fused2.evaluate(state2.params, np.arange(90))
  assert acc > 0.8


def test_fused_ticks_metrics_registry():
  ds = _dataset()
  model = GraphSAGE(hidden_features=16, out_features=3, num_layers=2)
  tx = optax.adam(1e-2)
  loader = NeighborLoader(ds, [4, 3], np.arange(90), batch_size=32)
  state, apply_fn = create_train_state(
      model, jax.random.key(0), next(iter(loader)), tx)
  fused = FusedEpoch(ds, [4, 3], np.arange(90), apply_fn, tx,
                     batch_size=32, shuffle=True, seed=0)
  before = metrics.snapshot().get('loader.batches', 0)
  state, _ = fused.run(state)
  after = metrics.snapshot().get('loader.batches', 0)
  assert after - before == len(fused)
