"""Serving over the real RPC runtime (ISSUE 9 satellites): the
`serve_infer` handler + `DistClient.serve` round trip, the heartbeat
serving block, typed admission propagation over the wire, and the
replay-cache exactly-once contract extended to serving RPCs under
injected connection drops.  Server runs IN-PROCESS (the `RpcServer`
is threaded — the test_resilience idiom), so no native dependency and
no subprocess jax imports.
"""
import threading
import time

import numpy as np
import pytest

from graphlearn_tpu.data import Dataset
from graphlearn_tpu.distributed import (init_client, init_server,
                                        shutdown_client,
                                        wait_and_shutdown_server)
from graphlearn_tpu.distributed.resilience import reset_default_policy
from graphlearn_tpu.serving import (AdmissionRejected, ServingEngine,
                                    ServingFrontend)
from graphlearn_tpu.telemetry import recorder
from graphlearn_tpu.testing import chaos

N, D = 48, 4
FANOUTS = [2, 2]
BUCKETS = (1, 2, 4)


def _dataset():
  rng = np.random.default_rng(1)
  rows = np.repeat(np.arange(N), 3)
  cols = rng.integers(0, N, rows.shape[0])
  feats = (np.arange(N, dtype=np.float32)[:, None]
           * np.ones((1, D), np.float32))
  return (Dataset().init_graph((rows, cols), layout='COO', num_nodes=N)
          .init_node_features(feats))


class _StubHostDataset:
  """`DistServer` wants a dataset for the PRODUCER path; the serving
  tests never touch producers, so a shape-only stub keeps the fixture
  free of the host sampling stack."""
  num_nodes = N
  num_edges = N * 3
  node_features = None
  node_labels = None


@pytest.fixture(scope='module')
def serving_cluster():
  """One in-process server with a warmed serving tier + one client."""
  engine = ServingEngine(_dataset(), FANOUTS, seed=7, buckets=BUCKETS)
  frontend = ServingFrontend(engine, auto_start=True, warmup=True,
                             max_wait_ms=1.0,
                             default_deadline_ms=2000.0)
  srv = init_server(num_servers=1, num_clients=1, rank=0,
                    dataset=_StubHostDataset(), host='127.0.0.1',
                    port=0)
  srv.attach_serving(frontend)
  client = init_client([('127.0.0.1', srv.port)], rank=0,
                       num_clients=1)
  yield srv, client, engine, frontend
  client.shutdown()                  # notify_leave + exit + close
  wait_and_shutdown_server(timeout=10)


@pytest.fixture(autouse=True)
def _clean():
  reset_default_policy()
  chaos.uninstall()
  recorder.enable(None)
  recorder.clear()
  yield
  chaos.uninstall()
  recorder.clear()
  recorder.disable()
  reset_default_policy()


def test_serve_roundtrip_matches_offline(serving_cluster):
  _, client, engine, _ = serving_cluster
  out = client.serve([5, 9])
  ref = engine.offline_reference([5, 9])
  np.testing.assert_array_equal(out['nodes'], ref.nodes)
  np.testing.assert_array_equal(out['x'], ref.x)
  assert 'logits' not in out         # model-less engine serves x


def test_heartbeat_serving_block(serving_cluster):
  _, client, _, frontend = serving_cluster
  client.serve([3])
  hb = client.heartbeat(0)
  assert hb is not None and 'serving' in hb
  s = hb['serving']
  assert s['queue_depth'] == 0 and s['in_flight'] == 0
  assert s['served_requests'] >= 1
  assert s['compile_status']['buckets'] == \
      {'1': True, '2': True, '4': True}
  assert s['compile_status']['compiles'] == frontend.engine.compile_count()
  assert 'shed' in s and s['max_queue'] == frontend.admission.max_queue


def test_admission_rejection_travels_typed(serving_cluster):
  """A server-side shed resurfaces client-side as AdmissionRejected
  via the wire's structured error-kind field — callers can tell
  overload from failure without message sniffing."""
  _, client, _, _ = serving_cluster
  with pytest.raises(AdmissionRejected):
    client.serve(list(range(BUCKETS[-1] + 1)))   # past the top bucket


def test_replay_cache_exactly_once_under_drop(serving_cluster):
  """The PR 4 contract extended to serving RPCs: a connection dropped
  after the send (server already executing) is retried under the SAME
  request id and answered from the replay cache — the tier admits the
  request ONCE, and the client still gets the full (byte-identical)
  answer."""
  _, client, engine, frontend = serving_cluster
  admitted_before = frontend.admission.stats()['admitted']
  chaos.install({'seed': 3, 'faults': [
      {'site': 'rpc.request', 'action': 'drop', 'nth': 1,
       'op': 'serve_infer'}]})
  out = client.serve([7, 11])
  assert chaos.active().exhausted(), 'the planned drop must fire'
  retries = recorder.events('rpc.retry')
  assert retries and retries[0]['op'] == 'serve_infer'
  ref = engine.offline_reference([7, 11])
  np.testing.assert_array_equal(out['nodes'], ref.nodes)
  np.testing.assert_array_equal(out['x'], ref.x)
  admitted_after = frontend.admission.stats()['admitted']
  assert admitted_after - admitted_before == 1, \
      'the retried request must NOT be admitted/executed twice'


def test_server_side_drop_surfaces_typed_not_lost(serving_cluster):
  """A serving.request 'drop' fault inside the handler: the client
  gets a typed RPC error naming the injected fault — the request is
  answered (with its failure), never lost or double-executed."""
  from graphlearn_tpu.distributed.rpc import RpcError
  _, client, _, frontend = serving_cluster
  admitted_before = frontend.admission.stats()['admitted']
  chaos.install('serving.request:drop:1:op=serve_infer')
  with pytest.raises(RpcError) as ei:
    client.serve([3])
  assert 'InjectedFault' in str(ei.value) or \
      getattr(ei.value, 'remote_kind', '') == 'InjectedFault'
  assert frontend.admission.stats()['admitted'] == admitted_before
  chaos.uninstall()
  out = client.serve([3])            # the tier recovers
  assert out['nodes'].shape[0] == 1


def test_slow_dispatch_sheds_queued_request_typed(serving_cluster):
  """SLO gating under a stuck executor: request A's dispatch stalls
  (injected delay at the executor seam); request B, queued behind it
  with a short deadline, expires in queue and comes back as a TYPED
  AdmissionRejected — p99 is shed, not silently stretched."""
  _, client, _, _ = serving_cluster
  chaos.install('serving.request:delay:1:op=dispatch:secs=0.8')
  errs = {}

  def slow_rider():
    try:
      errs['a'] = client.serve([5], deadline_ms=5000)
    except Exception as e:           # noqa: BLE001
      errs['a'] = e

  t = threading.Thread(target=slow_rider)
  t.start()
  time.sleep(0.3)                    # A is mid-dispatch (sleeping)
  with pytest.raises(AdmissionRejected):
    client.serve([9], deadline_ms=100)
  t.join(10)
  assert isinstance(errs['a'], dict), \
      'the slow rider itself still completes (picked before deadline)'
  assert any(e['reason'] == 'deadline'
             for e in recorder.events('serving.shed'))


def test_draining_rejection_travels_with_reason(serving_cluster):
  """ISSUE 13: the hot-swap cutover's reason='draining' + retry-after
  hint survive the wire — rebuilt from the structured extra field,
  never parsed out of the message text (a fleet router keys its
  reroute decision off the reason)."""
  _, client, _, frontend = serving_cluster
  frontend.admission.set_draining(True)
  try:
    with pytest.raises(AdmissionRejected) as ei:
      client.serve([3])
    assert ei.value.reason == 'draining'
    assert ei.value.retry_after_ms and ei.value.retry_after_ms > 0
  finally:
    frontend.admission.set_draining(False)
  out = client.serve([3])            # cutover over, serving again
  assert out['nodes'].shape[0] == 1


def test_swap_validation_error_travels_typed(serving_cluster):
  """serving_swap on a model-less tier refuses typed; the client sees
  the same SwapValidationError class (wire error-kind field)."""
  from graphlearn_tpu.serving.swap import SwapValidationError
  _, client, _, _ = serving_cluster
  with pytest.raises(SwapValidationError):
    client.swap_model({'w': np.ones(3, np.float32)})
