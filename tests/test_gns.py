"""Cache-aware Global Neighbor Sampling (`ops/gns.py`, ISSUE 10).

The contract under test, in three layers:

  * **kernel** — `sample_one_hop_gns` is seeded/jit-stable, its boost
    actually skews draws toward the cached set, and the importance-
    weighted estimator over many keys matches the uniform-sampling
    reference within tolerance (the 1/q unbiasedness correction);
  * **engines** — ``GLT_GNS=0`` (and the default) is bit-identical to
    the unbiased path across the single-chip, mesh and fused-tiered
    engines; GNS-on batches carry per-edge weights, keep feature
    values exact, and break the budget/universe cache-hit ceiling on
    a uniform cold stream (the PR 5 honesty-note regime);
  * **shared working set** — cold-cache admission ranks by the same
    decayed sketch the bias mask derives from, and both persist
    through `state_dict`/`load_state_dict` (PR 6 snapshot/resume
    keeps the learned working set).
"""
import os

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from graphlearn_tpu.data.cold_cache import ClockShardCache
from graphlearn_tpu.ops.gns import (DecayedSketch, bitmask_lookup,
                                    cached_set_bits, gns_enabled,
                                    sample_one_hop_gns)
from graphlearn_tpu.parallel import (DistDataset, DistNeighborLoader,
                                     DistNeighborSampler, FusedDistEpoch,
                                     make_mesh)

P = 4


def _uniform_dataset(n, split_ratio, num_parts=P, deg=8, dim=4, seed=0):
  """Uniform random regular-ish graph: the cold stream the static
  split can't help (no hubs to hot-tier) — the honesty-note regime."""
  rng = np.random.default_rng(seed)
  rows = np.repeat(np.arange(n), deg)
  cols = rng.integers(0, n, n * deg)
  feats = (np.arange(n, dtype=np.float32)[:, None]
           * np.ones((1, dim), np.float32))
  labels = (np.arange(n) % 5).astype(np.int32)
  node_pb = (np.arange(n) % num_parts).astype(np.int32)
  return DistDataset.from_full_graph(
      num_parts, rows, cols, node_feat=feats, node_label=labels,
      num_nodes=n, node_pb=node_pb, split_ratio=split_ratio)


# -- sketch ----------------------------------------------------------------

def test_sketch_cross_batch_ranking():
  """A steadily revisited id outranks a one-batch burst once the
  burst decays — the property the per-batch multiset ranking lacked."""
  sk = DecayedSketch(slots=128, decay=0.5)
  sk.update([7], counts=[100])            # one-batch burst
  for _ in range(6):
    sk.update([3], counts=[2])            # steady repeat visitor
  assert sk.score([3])[0] > sk.score([7])[0]
  assert sk.score([-1])[0] == 0.0


def test_sketch_fresh_reduces_to_multiset():
  """On a fresh sketch the admission ranking equals the old per-batch
  multiset order (the drop-in-replacement contract)."""
  c = ClockShardCache(2)
  ids = np.array([5, 6, 7], np.int64)
  counts = np.array([1, 9, 4], np.int64)
  adm, slots, _ = c.plan_admissions(ids, counts)
  c.commit(adm, slots)
  hit, _ = c.lookup(ids)
  assert hit.tolist() == [False, True, True]


def test_sketch_persists_with_cache_state():
  """ClockShardCache snapshots carry the sketch: a resumed cache
  ranks admissions with the LEARNED visit frequencies, not a cold
  restart (ISSUE 10 satellite)."""
  a = ClockShardCache(2)
  adm, slots, _ = a.plan_admissions(np.array([1, 2], np.int64),
                                    np.array([9, 8], np.int64))
  a.commit(adm, slots)
  state = a.state_dict()
  assert 'sketch' in state

  b = ClockShardCache(2)
  b.load_state_dict(state)
  np.testing.assert_array_equal(b.sketch.scores, a.sketch.scores)
  np.testing.assert_array_equal(b.ids, a.ids)
  # pre-r11 snapshot (no sketch key): residency restores, no crash
  legacy = {k: v for k, v in state.items() if k != 'sketch'}
  c = ClockShardCache(2)
  c.load_state_dict(legacy)
  np.testing.assert_array_equal(c.ids, a.ids)


def test_gns_enabled_resolution():
  assert gns_enabled(True) and not gns_enabled(False)
  assert not gns_enabled(None)
  os.environ['GLT_GNS'] = '1'
  try:
    assert gns_enabled(None)
    assert not gns_enabled(False)      # explicit kwarg beats env
  finally:
    del os.environ['GLT_GNS']


# -- membership bitmask ----------------------------------------------------

def test_cached_set_bits_lookup():
  bounds = np.array([0, 10, 20])
  hot_counts = np.array([3, 2])            # hot: 0,1,2 and 10,11
  residents = np.array([5, 17, 999])       # out-of-range id ignored
  bits = cached_set_bits(20, bounds, hot_counts, residents)
  got = np.asarray(bitmask_lookup(jnp.asarray(bits),
                                  jnp.arange(-1, 20)))
  want = np.zeros(21, np.uint8)
  for v in (0, 1, 2, 10, 11, 5, 17):
    want[v + 1] = 1                        # +1: index 0 is id -1
  np.testing.assert_array_equal(got, want)


def test_set_resident_bits_matches_full_rebuild():
  """The incremental refresh (static hot mask + resident scatter)
  equals the one-shot builder bit for bit."""
  from graphlearn_tpu.ops.gns import set_resident_bits
  bounds = np.array([0, 10, 20])
  hot = np.array([3, 2])
  base = cached_set_bits(20, bounds, hot, np.empty(0, np.int64))
  res = np.array([5, 17, -1, 99])
  inc = set_resident_bits(base, res, 20)
  full = cached_set_bits(20, bounds, hot, res)
  np.testing.assert_array_equal(inc, full)
  # the base mask is untouched (copy semantics)
  np.testing.assert_array_equal(
      base, cached_set_bits(20, bounds, hot, np.empty(0, np.int64)))


def test_subgraph_sampler_never_biases():
  """Induced subgraphs are exact by contract: a global GLT_GNS=1 must
  not flip the subgraph sampler's flag (its step never biases)."""
  from graphlearn_tpu.parallel import DistSubGraphSampler
  os.environ['GLT_GNS'] = '1'
  try:
    ds = _uniform_dataset(96, 0.3)
    s = DistSubGraphSampler(ds, [2], mesh=make_mesh(P))
    assert not s.gns and s.gns_boost is None
  finally:
    del os.environ['GLT_GNS']


# -- biased kernel ---------------------------------------------------------

def _chain_csr(deg):
  """One seed (node 0) with neighbors 1..deg; the other nodes are
  isolated (indptr flat past row 0)."""
  n = deg + 1
  indptr = np.concatenate([[0], np.full(n, deg)]).astype(np.int64)
  indices = np.arange(1, deg + 1, dtype=np.int32)
  return jnp.asarray(indptr), jnp.asarray(indices), n


def test_gns_kernel_bias_and_unbiasedness():
  """The boost measurably skews draws toward the cached set, and the
  importance-weighted estimator of the neighbor mean matches the
  exact mean over many seeds (the 1/q correction)."""
  deg, k = 16, 4
  indptr, indices, n = _chain_csr(deg)
  # cache neighbors 1..4
  bits = jnp.asarray(cached_set_bits(
      n, np.array([0, n]), np.array([0]), np.arange(1, 5)))
  seeds = jnp.zeros((1,), jnp.int32)
  true_mean = np.arange(1, deg + 1).mean()

  trials = 2000
  est = np.zeros(trials)
  cached_frac = 0.0
  for t in range(trials):
    res = sample_one_hop_gns(indptr, indices, seeds, k,
                             jax.random.fold_in(jax.random.key(0), t),
                             bits, 8.0)
    nbrs = np.asarray(res.nbrs[0])
    w = np.asarray(res.weights[0])
    m = np.asarray(res.mask[0])
    assert m.all() and (nbrs >= 1).all()
    # weighted estimator of the neighbor mean: sum(w f)/k
    est[t] = (w * nbrs).sum() / k
    cached_frac += (nbrs <= 4).mean() / trials
  # the bias bites: cached neighbors are 4/16 = 25% of the adjacency
  # but far more of the draws (q = 9/(12 + 9*4) = 0.1875 each -> 75%)
  assert cached_frac > 0.5, cached_frac
  # ...and the correction undoes it: the estimator mean is the
  # uniform neighbor mean within monte-carlo tolerance
  se = est.std() / np.sqrt(trials)
  assert abs(est.mean() - true_mean) < 4 * se + 1e-6, (
      est.mean(), true_mean, se)


def test_gns_kernel_take_all_and_beyond_window_arms():
  """deg <= k: take-all with weight 1; deg > window: uniform draws
  with weight 1 (the boost only engages between the two)."""
  deg, k = 3, 4
  indptr, indices, n = _chain_csr(deg)
  bits = jnp.asarray(cached_set_bits(n, np.array([0, n]),
                                     np.array([0]), np.arange(1, 3)))
  res = sample_one_hop_gns(indptr, indices, jnp.zeros((1,), jnp.int32),
                           k, jax.random.key(1), bits, 8.0)
  m = np.asarray(res.mask[0])
  assert m.sum() == deg
  np.testing.assert_array_equal(np.asarray(res.weights[0])[m], 1.0)
  np.testing.assert_array_equal(np.asarray(res.weights[0])[~m], 0.0)

  deg2 = 32
  indptr2, indices2, n2 = _chain_csr(deg2)
  res2 = sample_one_hop_gns(indptr2, indices2,
                            jnp.zeros((1,), jnp.int32), 4,
                            jax.random.key(2),
                            jnp.asarray(cached_set_bits(
                                n2, np.array([0, n2]), np.array([0]),
                                np.arange(1, 5))),
                            8.0, window=16)     # deg > window
  np.testing.assert_array_equal(np.asarray(res2.weights[0]), 1.0)


def test_gns_kernel_seeded_and_sorted_equivalence():
  """Same key -> same draws; sort_locality returns input order."""
  deg = 16
  indptr, indices, n = _chain_csr(deg)
  bits = jnp.asarray(cached_set_bits(n, np.array([0, n]),
                                     np.array([0]), np.arange(1, 5)))
  seeds = jnp.asarray([0, 0, -1], jnp.int32)
  a = sample_one_hop_gns(indptr, indices, seeds, 4, jax.random.key(3),
                         bits, 8.0)
  b = sample_one_hop_gns(indptr, indices, seeds, 4, jax.random.key(3),
                         bits, 8.0)
  np.testing.assert_array_equal(np.asarray(a.nbrs), np.asarray(b.nbrs))
  np.testing.assert_array_equal(np.asarray(a.weights),
                                np.asarray(b.weights))
  assert not np.asarray(a.mask[2]).any()        # invalid seed: empty


# -- mesh engines ----------------------------------------------------------

def _loader(ds, mesh, n, gns=None, **kw):
  return DistNeighborLoader(ds, [3, 2], np.arange(n), batch_size=8,
                            shuffle=True, mesh=mesh, seed=0, gns=gns,
                            **kw)


def test_gns_off_byte_identity_mesh():
  """GLT_GNS=0, gns=False and the default all produce bit-identical
  mesh batches (the off path IS the unbiased sampler, not a
  zero-boost GNS program)."""
  n = 96
  ds = _uniform_dataset(n, 0.3)
  mesh = make_mesh(P)
  runs = {}
  for tag, env, kwarg in (('default', None, None),
                          ('env0', '0', None),
                          ('kwfalse', None, False)):
    if env is not None:
      os.environ['GLT_GNS'] = env
    try:
      loader = _loader(ds, mesh, n, gns=kwarg)
      assert not loader.sampler.gns
      batches = list(loader)
      assert all('edge_weight' not in b.metadata for b in batches)
      runs[tag] = [(np.asarray(b.x), np.asarray(b.node),
                    np.asarray(b.edge_index)) for b in batches]
    finally:
      os.environ.pop('GLT_GNS', None)
  for tag in ('env0', 'kwfalse'):
    for (x0, n0, e0), (x1, n1, e1) in zip(runs['default'], runs[tag]):
      np.testing.assert_array_equal(x0, x1, err_msg=tag)
      np.testing.assert_array_equal(n0, n1, err_msg=tag)
      np.testing.assert_array_equal(e0, e1, err_msg=tag)


def test_gns_on_values_exact_and_weighted():
  """GNS batches keep feature values exact (the overlay serves the
  biased sample correctly) and carry per-edge weights aligned with
  the edge list."""
  n = 96
  ds = _uniform_dataset(n, 0.3)
  mesh = make_mesh(P)
  new2old = np.argsort(ds.old2new)
  loader = _loader(ds, mesh, n, gns=True)
  assert loader.sampler.gns
  saw_weighted = False
  for b in loader:
    node = np.asarray(b.node)
    x = np.asarray(b.x)
    valid = node >= 0
    np.testing.assert_allclose(x[valid][:, 0], new2old[node[valid]])
    ew = np.asarray(b.metadata['edge_weight'])
    emask = np.asarray(b.edge_mask)
    assert ew.shape == emask.shape
    assert (ew[emask] > 0).all()
    assert (ew[~emask] == 0).all()
    saw_weighted |= bool((np.abs(ew[emask] - 1.0) > 1e-6).any())
  assert saw_weighted            # the boost engaged somewhere


def test_gns_breaks_hit_rate_ceiling():
  """On a uniform cold stream at split 0.3 — the PR 5 honesty-note
  regime where cache_hit_rate pins at budget/universe — GNS-on
  steering lifts the hit rate well past the ceiling at identical
  budget, while GNS-off stays near it."""
  n = 512
  ds = _uniform_dataset(n, 0.3, deg=8)
  mesh = make_mesh(P)
  cache_rows = 16
  counts = np.diff(ds.graph.bounds)
  universe = int(np.maximum(
      counts - ds.node_features.hot_counts, 0).sum())
  ceiling = cache_rows / universe

  os.environ['GLT_GNS_BOOST'] = '32'     # margin over the 3x bar
  rates = {}
  try:
    for gns in (False, True):
      s = DistNeighborSampler(ds, [3, 2], mesh=mesh, seed=0,
                              cold_cache_rows=cache_rows, gns=gns)
      rng = np.random.default_rng(1)
      for step in range(24):
        seeds = ds.old2new[rng.integers(0, n, (P, 16))]
        s.sample_from_nodes(seeds,
                            key=jax.random.fold_in(jax.random.key(5),
                                                   step))
      st = s.exchange_stats(tick_metrics=False)
      rates[gns] = st['dist.feature.cache_hit_rate']
  finally:
    del os.environ['GLT_GNS_BOOST']
  # acceptance shape (ISSUE 10): >= 3x budget/universe with the
  # sampler biased, and decisively above the unbiased sampler
  # (measured: off 0.050 ~ ceiling 0.045; on 0.188 ~ 4.2x)
  assert rates[True] >= 3 * ceiling, (rates, ceiling)
  assert rates[True] > 1.5 * rates[False], (rates, ceiling)


@pytest.mark.slow
def test_gns_fused_tiered_trains_and_off_is_identical():
  """FusedDistEpoch on a tiered store: GLT_GNS=0 epochs are
  bit-identical to the default driver, and a GNS-on epoch trains to
  finite losses through the chunked collect -> cold-service -> train
  path with the bitmask refreshed at chunk seams."""
  import optax
  from graphlearn_tpu.models import GraphSAGE, create_train_state
  from graphlearn_tpu.parallel import local_batch_piece, replicate
  n = 96
  ds = _uniform_dataset(n, 0.3)
  mesh = make_mesh(P)
  model = GraphSAGE(hidden_features=8, out_features=5, num_layers=2)
  tx = optax.adam(1e-2)
  b0 = next(iter(_loader(ds, mesh, n)))
  b0_local = local_batch_piece(b0, P)

  def run_epoch(**kw):
    fused = FusedDistEpoch(ds, [3, 2], np.arange(n), apply_fn, tx,
                           batch_size=8, mesh=mesh, shuffle=True,
                           seed=0, **kw)
    state = replicate(
        create_train_state(model, jax.random.key(0), b0_local, tx)[0],
        mesh)
    state, stats = fused.run(state)
    return np.asarray(stats.losses)

  state0, apply_fn = create_train_state(model, jax.random.key(0),
                                        b0_local, tx)
  l_default = run_epoch()
  os.environ['GLT_GNS'] = '0'
  try:
    l_env0 = run_epoch()
  finally:
    del os.environ['GLT_GNS']
  np.testing.assert_array_equal(l_default, l_env0)

  l_gns = run_epoch(gns=True)
  assert np.isfinite(l_gns).all()
  assert l_gns.shape == l_default.shape


def test_gns_fused_tree_tiered_smoke():
  """FusedDistTreeEpoch with GNS on: the tiered collect phase carries
  cumulative level weights, the consume phase scales features by
  them, and the epoch trains to finite losses."""
  import optax
  from graphlearn_tpu.models import TreeSAGE
  from graphlearn_tpu.parallel import FusedDistTreeEpoch
  n = 96
  ds = _uniform_dataset(n, 0.3)
  mesh = make_mesh(P)
  model = TreeSAGE(hidden_features=8, out_features=5, num_layers=2)
  tx = optax.adam(1e-2)
  fused = FusedDistTreeEpoch(ds, [3, 2], np.arange(n), model, tx,
                             batch_size=8, mesh=mesh, shuffle=True,
                             seed=0, gns=True)
  assert fused.sampler.gns
  state = fused.init_state(jax.random.key(0))
  state, stats = fused.run(state)
  losses = np.asarray(stats.losses)
  assert np.isfinite(losses).all() and losses.size > 0


# -- serving cold-path dedup (ISSUE 10 satellite) --------------------------

def test_serving_cold_dedup_pays_unique_ids_only():
  """A coalesced dispatch whose riders repeat the same seed fetches
  each distinct id once: results stay byte-identical to the per-seed
  reference while the tiered host path sees ~tree-width lookups, not
  riders x tree-width."""
  from graphlearn_tpu.data import Dataset
  from graphlearn_tpu.data.feature import Feature
  from graphlearn_tpu.serving.engine import ServingEngine
  n = 64
  rng = np.random.default_rng(0)
  rows = np.repeat(np.arange(n), 4)
  cols = rng.integers(0, n, 4 * n)
  feats = (np.arange(n, dtype=np.float32)[:, None]
           * np.ones((1, 4), np.float32))
  ds = Dataset().init_graph((rows, cols), layout='COO', num_nodes=n)
  ds.node_features = Feature(feats, split_ratio=0.5)
  eng = ServingEngine(ds, [3, 2], seed=0, buckets=(8,))
  eng.warmup()
  feat = ds.node_features
  before = feat.cold_stats['lookups']
  seeds = np.array([5, 5, 5, 5, 9, 9, 9, 9])
  out = eng.infer(seeds)
  dedup_lookups = feat.cold_stats['lookups'] - before
  ref = eng.offline_reference(seeds, cap=8)
  np.testing.assert_array_equal(out.nodes, ref.nodes)
  np.testing.assert_array_equal(out.x, ref.x)
  # 8 riders x tree width would be 8 * (1 + 3 + 6) = 80 lookups; the
  # deduped run pays the distinct ids of TWO trees (plus pow2 pad)
  assert dedup_lookups < 8 * eng.tree_width / 2, dedup_lookups


# -- per-requester masks (ISSUE 15: the PR 10 known-limit fix) --------------

def test_per_requester_mask_no_remote_boost():
  """A row resident ONLY on another device's cache ring gets no boost
  locally: the kernel judged by requester 1's mask must not favor a
  node only requester 0 caches — while requester 0's draws do."""
  from graphlearn_tpu.ops.gns import per_requester_bits
  n = 64
  # one seed with a wide neighborhood, far above fanout
  deg = 32
  indptr = jnp.asarray(np.asarray([0, deg], np.int64))
  nbrs = np.arange(deg, dtype=np.int32)
  indices = jnp.asarray(nbrs)
  hot = np.zeros(1, np.int64)           # nothing statically hot
  bounds = np.asarray([0, n], np.int64)
  special = 7
  bits2 = per_requester_bits(n, bounds, hot,
                             {0: np.asarray([special], np.int64)})
  assert bits2.shape[0] == 1 + 1        # P=1 device row + hot-only fallback
  k, boost = 4, 1000.0
  seeds = jnp.zeros(1, jnp.int32)
  hits = {0: 0, 1: 0}
  for req_dev in (0, 1):
    cnt = 0
    for trial in range(30):
      res = sample_one_hop_gns(
          indptr, indices, seeds, k, jax.random.key(trial),
          jnp.asarray(bits2), boost,
          req=jnp.full((1,), req_dev, jnp.int32),
          sort_locality=False)
      cnt += int(np.sum(np.asarray(res.nbrs) == special))
    hits[req_dev] = cnt
  # requester 0 (caches `special`): the 1000x boost dominates every
  # draw; requester 1: uniform over 32 neighbors
  assert hits[0] > 60, hits
  assert hits[1] <= 20, hits


def test_per_requester_rows_follow_device_rings():
  """`DistNeighborSampler._gns_arrays` builds one mask row per
  device from ITS shard's residents (+ the hot-only fallback row):
  a resident planted in device 0's ring sets the bit in row 0 only.
  r19: the rows arrive as the dedup (table, row_index) tuple —
  requester r's row is table[row_index[r]], and devices with empty
  rings collapse onto the shared base row instead of replicating it."""
  ds = _uniform_dataset(16 * P, split_ratio=0.5)
  sampler = DistNeighborSampler(ds, [2], gns=True,
                                cold_cache_rows=4)
  cache = sampler._ensure_cold_cache()
  assert cache is not None
  # plant a cold resident in device 0's ring only
  hot0 = int(ds.node_features.hot_counts[0])
  cold_id = int(ds.graph.bounds[0]) + hot0     # first cold row of p0
  cache.shards[0].commit(np.asarray([cold_id], np.int64),
                         np.asarray([0], np.int32))
  table, row_index = (np.asarray(a) for a in
                      jax.device_get(sampler._gns_arrays()))
  assert row_index.shape == (P + 1,)     # P requesters + hot fallback
  bits = table[row_index]                # the replicated PR 15 view
  assert bits.ndim == 2 and bits.shape[0] == P + 1
  byte, bit = cold_id >> 3, cold_id & 7
  assert bits[0, byte] >> bit & 1 == 1         # requester 0 boosts it
  for row in range(1, P + 1):
    assert bits[row, byte] >> bit & 1 == 0, row  # nobody else does
  # the dedup the tuple exists for: only device 0 diverges from the
  # base row, so 2 distinct rows carry all P + 1 requester views
  assert table.shape[0] == 2
