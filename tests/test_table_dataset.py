"""Tabular ingestion tests (reference format parity: edge tables of
(src, dst); node tables of (id, "f0:f1:..."), ids arriving unordered).
"""
import numpy as np
import pytest

from graphlearn_tpu.data.table_dataset import (
    CsvTableReader, NpzTableReader, TableDataset, read_edge_table,
    read_node_table)


def _write_tables(tmp_path, n=20, deg=2):
  rows = np.repeat(np.arange(n), deg)
  cols = (rows + np.tile(np.arange(1, deg + 1), n)) % n
  with open(tmp_path / 'edges.csv', 'w') as f:
    for r, c in zip(rows, cols):
      f.write(f'{r},{c}\n')
  # node records shuffled: features must land at row id anyway
  order = np.random.default_rng(0).permutation(n)
  with open(tmp_path / 'nodes.csv', 'w') as f:
    for i in order:
      f.write(f'{i},{float(i)}:{float(2 * i)}\n')
  return rows, cols


def test_read_edge_and_node_tables(tmp_path):
  rows, cols = _write_tables(tmp_path)
  r, c = read_edge_table(tmp_path / 'edges.csv', batch_size=7)
  np.testing.assert_array_equal(r, rows)
  np.testing.assert_array_equal(c, cols)
  feats = read_node_table(tmp_path / 'nodes.csv', batch_size=7)
  assert feats.shape == (20, 2)
  np.testing.assert_array_equal(feats[:, 0], np.arange(20, dtype=np.float32))
  np.testing.assert_array_equal(feats[:, 1],
                                2 * np.arange(20, dtype=np.float32))


def test_npz_reader(tmp_path):
  np.savez(tmp_path / 'edges.npz',
           src=np.array([0, 1, 2]), dst=np.array([1, 2, 0]))
  r, c = read_edge_table(NpzTableReader(tmp_path / 'edges.npz',
                                        columns=['src', 'dst']))
  np.testing.assert_array_equal(r, [0, 1, 2])
  np.testing.assert_array_equal(c, [1, 2, 0])


def test_table_dataset_end_to_end(tmp_path):
  _write_tables(tmp_path)
  ds = TableDataset().load(
      edge_tables={'n__to__n': tmp_path / 'edges.csv'},
      node_tables={'n': tmp_path / 'nodes.csv'},
      label=np.arange(20) % 3)
  g = ds.get_graph()
  assert g.num_nodes == 20 and g.num_edges == 40
  assert ds.get_node_feature().shape == (20, 2)

  from graphlearn_tpu.loader import NeighborLoader
  loader = NeighborLoader(ds, [2], input_nodes=np.arange(20), batch_size=10)
  batch = next(iter(loader))
  ids = np.asarray(batch.node)
  valid = np.asarray(batch.node_mask)
  # feature column 0 encodes the node id
  np.testing.assert_array_equal(np.asarray(batch.x)[valid][:, 0],
                                ids[valid].astype(np.float32))


def test_table_dataset_hetero(tmp_path):
  nu, nv = 6, 8
  with open(tmp_path / 'u2v.csv', 'w') as f:
    for u in range(nu - 1):  # last u node isolated: count must still be 6
      f.write(f'{u},{u % nv}\n')
  for name, cnt in (('u.csv', nu), ('v.csv', nv)):
    with open(tmp_path / name, 'w') as f:
      for i in range(cnt):
        f.write(f'{i},{float(i)}:{float(i)}\n')
  et = ('u', 'to', 'v')
  ds = TableDataset().load(edge_tables={et: tmp_path / 'u2v.csv'},
                           node_tables={'u': tmp_path / 'u.csv',
                                        'v': tmp_path / 'v.csv'})
  assert ds.get_graph(et).num_edges == nu - 1
  # num_nodes comes from the node table, not the max edge endpoint
  assert ds.get_graph(et).num_nodes == nu
  assert ds.get_node_feature('u').shape == (6, 2)
  assert ds.get_node_feature('v').shape == (8, 2)


def test_duplicate_node_ids_rejected(tmp_path):
  with open(tmp_path / 'dup.csv', 'w') as f:
    f.write('0,1.0\n1,2.0\n1,3.0\n3,4.0\n')
  with pytest.raises(ValueError, match='permutation'):
    read_node_table(tmp_path / 'dup.csv')


def test_odps_reader_gated():
  from graphlearn_tpu.data.table_dataset import OdpsTableReader
  with pytest.raises(ImportError, match='common_io'):
    OdpsTableReader('odps://project/tables/foo')
