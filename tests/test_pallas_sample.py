"""Pallas fused sampling pipeline parity (`ops/pallas_sample.py`,
`ops/pallas_delta.py`, the pinned cold gather — ISSUE 18, r19).

The contract under test is BYTE/VALUE PARITY, not speed: every r19
kernel is a drop-in lowering of an existing XLA/host path, so
flipping its knob must never change a result —

  * **fused sampler** — `sample_one_hop_fused` (interpret mode on
    CPU) equals `sample_one_hop` / `sample_one_hop_gns` exactly:
    uniform and GNS-biased arms, per-requester masks (replicated 2-D
    AND the dedup tuple), the deg<=k take-all arm and the deg>W hub
    arm, with and without edge ids / sort_locality;
  * **GNS dedup** — `dedup_requester_bits`' (table, row_index)
    encoding answers `bitmask_lookup` identically to the replicated
    [R+1, N/8] stack and drops mask memory;
  * **delta merge** — `merge_delta_csr_device` is byte-identical to
    `streaming.delta.merge_delta_csr` (dtypes included), ties,
    empty-segment and empty-base corners pinned;
  * **pinned cold gather** — the mixed-tier `Feature.get` with
    GLT_PALLAS_COLD=1 returns byte-identical batches to the host
    `np.take` path at cache budgets {0, tiny};
  * **dispatch discipline** — `sample_one_hop_auto` with the knob
    OFF routes to the XLA kernels (the fault-free default path), and
    unsupported shapes fall back transparently with a
    ``pallas.fallback`` event.
"""
import os

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from graphlearn_tpu.ops.gns import (bitmask_lookup, bits_table,
                                    cached_set_bits,
                                    dedup_requester_bits,
                                    fallback_req_index,
                                    is_per_requester,
                                    per_requester_bits,
                                    sample_one_hop_gns)
from graphlearn_tpu.ops.neighbor import default_window, sample_one_hop
from graphlearn_tpu.ops.pallas_sample import (fused_sample_supported,
                                              sample_one_hop_auto,
                                              sample_one_hop_fused)

K = 8
BOOST = 16.0


def _csr(n=220, mean_deg=10, seed=0, *, zero=(3,), hub=(9,)):
  """Poisson-degree CSR with forced empty rows and beyond-window hubs
  (deg > default_window(K)) so every sampling arm is exercised."""
  rng = np.random.default_rng(seed)
  deg = rng.poisson(mean_deg, n)
  for z in zero:
    deg[z] = 0
  for h in hub:
    deg[h] = default_window(K) * 3 + 5
  indptr = np.zeros(n + 1, np.int64)
  np.cumsum(deg, out=indptr[1:])
  e = int(indptr[-1])
  indices = rng.integers(0, n, e).astype(np.int32)
  eids = np.arange(e, dtype=np.int64)
  return (jnp.asarray(indptr), jnp.asarray(indices), jnp.asarray(eids),
          n, e)


def _seeds(n, b=32, seed=1, *, pad=True, include=()):
  rng = np.random.default_rng(seed)
  s = rng.integers(0, n, b).astype(np.int32)
  for i, v in enumerate(include):
    s[i] = v
  if pad:
    s[-2:] = -1                    # INVALID_ID-padded tail slots
  return jnp.asarray(s)


def _assert_onehop_equal(ref, got):
  np.testing.assert_array_equal(np.asarray(ref.nbrs),
                                np.asarray(got.nbrs))
  np.testing.assert_array_equal(np.asarray(ref.mask),
                                np.asarray(got.mask))
  assert (ref.eids is None) == (got.eids is None)
  if ref.eids is not None:
    np.testing.assert_array_equal(np.asarray(ref.eids),
                                  np.asarray(got.eids))
  assert (ref.weights is None) == (got.weights is None)
  if ref.weights is not None:
    np.testing.assert_array_equal(np.asarray(ref.weights),
                                  np.asarray(got.weights))


# -- fused sampler: uniform arms ------------------------------------------

@pytest.mark.parametrize('sort_locality', [False, True])
@pytest.mark.parametrize('with_edge', [False, True])
def test_fused_uniform_exact(sort_locality, with_edge):
  indptr, indices, eids, n, e = _csr()
  seeds = _seeds(n, include=(3, 9))   # empty row + hub in-batch
  key = jax.random.PRNGKey(42)
  ref = sample_one_hop(indptr, indices, seeds, K, key,
                       eids if with_edge else None,
                       with_edge_ids=with_edge,
                       sort_locality=sort_locality)
  got = sample_one_hop_fused(indptr, indices, seeds, K, key,
                             eids if with_edge else None,
                             with_edge_ids=with_edge,
                             sort_locality=sort_locality,
                             interpret=True)
  _assert_onehop_equal(ref, got)


def test_fused_take_all_arm_exact():
  # every degree <= K: the kernel's take-all select must reproduce
  # the XLA slot identity (off = slot), not a draw
  indptr, indices, eids, n, _ = _csr(mean_deg=3, hub=())
  seeds = _seeds(n, include=(3,))
  key = jax.random.PRNGKey(7)
  ref = sample_one_hop(indptr, indices, seeds, K, key, eids,
                       with_edge_ids=True)
  got = sample_one_hop_fused(indptr, indices, seeds, K, key, eids,
                             with_edge_ids=True, interpret=True)
  _assert_onehop_equal(ref, got)


# -- fused sampler: GNS-biased arms ---------------------------------------

def _shared_bits(n, seed=2):
  rng = np.random.default_rng(seed)
  bounds = np.array([0, n // 2, n], np.int64)
  hot = np.array([12, 12], np.int64)
  return jnp.asarray(cached_set_bits(
      n, bounds, hot, rng.integers(0, n, 60).astype(np.int64)))


@pytest.mark.parametrize('sort_locality', [False, True])
def test_fused_gns_shared_bits_exact(sort_locality):
  indptr, indices, eids, n, _ = _csr(seed=3)
  seeds = _seeds(n, include=(3, 9))
  bits = _shared_bits(n)
  key = jax.random.PRNGKey(11)
  ref = sample_one_hop_gns(indptr, indices, seeds, K, key, bits,
                           BOOST, eids, with_edge_ids=True,
                           sort_locality=sort_locality)
  got = sample_one_hop_fused(indptr, indices, seeds, K, key, eids,
                             bits=bits, boost=BOOST,
                             with_edge_ids=True,
                             sort_locality=sort_locality,
                             interpret=True)
  _assert_onehop_equal(ref, got)


def _dedup_fixture(n, seed=4, parts=4):
  rng = np.random.default_rng(seed)
  bounds = np.linspace(0, n, parts + 1).astype(np.int64)
  hot = np.full(parts, 10, np.int64)
  residents = {0: rng.integers(0, n, 24).astype(np.int64),
               2: rng.integers(0, n, 12).astype(np.int64)}
  return bounds, hot, residents


def test_fused_gns_per_requester_exact():
  """The dedup tuple through BOTH bias paths == the replicated 2-D
  stack: XLA-tuple, fused-tuple and fused-2-D all byte-match the
  XLA-2-D reference."""
  indptr, indices, eids, n, _ = _csr(seed=5)
  parts = 4
  bounds, hot, residents = _dedup_fixture(n, parts=parts)
  table, row_index = dedup_requester_bits(n, bounds, hot, residents)
  rep = np.asarray(table)[np.asarray(row_index)]   # the PR 15 layout
  bits_t = (jnp.asarray(table), jnp.asarray(row_index))
  seeds = _seeds(n, include=(3, 9))
  req = jnp.asarray(np.random.default_rng(6).integers(
      0, parts + 1, seeds.shape[0]).astype(np.int32))
  key = jax.random.PRNGKey(13)
  ref = sample_one_hop_gns(indptr, indices, seeds, K, key,
                           jnp.asarray(rep), BOOST, eids, req=req,
                           with_edge_ids=True)
  for got in (
      sample_one_hop_gns(indptr, indices, seeds, K, key, bits_t,
                         BOOST, eids, req=req, with_edge_ids=True),
      sample_one_hop_fused(indptr, indices, seeds, K, key, eids,
                           bits=bits_t, boost=BOOST, req=req,
                           with_edge_ids=True, interpret=True),
      sample_one_hop_fused(indptr, indices, seeds, K, key, eids,
                           bits=jnp.asarray(rep), boost=BOOST,
                           req=req, with_edge_ids=True,
                           interpret=True)):
    _assert_onehop_equal(ref, got)


def test_fused_per_requester_needs_req():
  indptr, indices, eids, n, _ = _csr(seed=5)
  bounds, hot, residents = _dedup_fixture(n)
  table, row_index = dedup_requester_bits(n, bounds, hot, residents)
  bits_t = (jnp.asarray(table), jnp.asarray(row_index))
  with pytest.raises(ValueError, match='req'):
    sample_one_hop_fused(jnp.asarray(indptr), jnp.asarray(indices),
                         _seeds(n), K, jax.random.PRNGKey(0),
                         bits=bits_t, boost=BOOST, interpret=True)


# -- GNS dedup encoding ----------------------------------------------------

def test_dedup_bits_lookup_equivalence_and_memory_drop():
  n, parts = 4096, 16
  rng = np.random.default_rng(8)
  bounds = np.linspace(0, n, parts + 1).astype(np.int64)
  hot = np.full(parts, 32, np.int64)
  # only 3 of 16 devices own residents -> 4 distinct rows (base + 3)
  residents = {1: rng.integers(0, n, 50).astype(np.int64),
               5: rng.integers(0, n, 50).astype(np.int64),
               11: rng.integers(0, n, 50).astype(np.int64)}
  rep = per_requester_bits(n, bounds, hot, residents)
  table, row_index = dedup_requester_bits(n, bounds, hot, residents)
  bits_t = (jnp.asarray(table), jnp.asarray(row_index))

  assert is_per_requester(bits_t) and is_per_requester(rep)
  assert fallback_req_index(bits_t) == fallback_req_index(rep) == parts
  assert bits_table(bits_t).shape == table.shape
  # exact row equivalence through the indirection
  np.testing.assert_array_equal(table[row_index], rep)
  # distinct-row count: base + devices-with-residents, NOT P+1
  assert table.shape[0] == 1 + len(residents)
  # the memory drop the dedup exists for (here 17 rows -> 4)
  assert table.nbytes + row_index.nbytes < rep.nbytes / 3

  ids = jnp.asarray(rng.integers(0, n, 256).astype(np.int32))
  req = jnp.asarray(rng.integers(0, parts + 1, 256).astype(np.int32))
  np.testing.assert_array_equal(
      np.asarray(bitmask_lookup(jnp.asarray(rep), ids, req)),
      np.asarray(bitmask_lookup(bits_t, ids, req)))
  # no-req callers resolve the base row (row 0 == hot-split ∪ nothing)
  np.testing.assert_array_equal(
      np.asarray(bitmask_lookup(jnp.asarray(table[0]), ids)),
      np.asarray(bitmask_lookup(
          bits_t, ids, jnp.zeros_like(ids))))


# -- the auto dispatcher ---------------------------------------------------

def test_auto_knob_off_is_the_xla_path():
  """Fault-free default: with GLT_PALLAS_SAMPLE unset the dispatcher
  IS `sample_one_hop` — byte-identical, no kernel anywhere."""
  os.environ.pop('GLT_PALLAS_SAMPLE', None)
  indptr, indices, eids, n, _ = _csr()
  seeds = _seeds(n)
  key = jax.random.PRNGKey(21)
  ref = sample_one_hop(indptr, indices, seeds, K, key, eids,
                       with_edge_ids=True)
  got = sample_one_hop_auto(indptr, indices, seeds, K, key, eids,
                            with_edge_ids=True)
  _assert_onehop_equal(ref, got)


def test_auto_knob_on_matches_and_unsupported_falls_back(monkeypatch):
  monkeypatch.setenv('GLT_PALLAS_SAMPLE', '1')
  indptr, indices, eids, n, _ = _csr()
  seeds = _seeds(n)
  key = jax.random.PRNGKey(22)
  ref = sample_one_hop(indptr, indices, seeds, K, key, eids,
                       with_edge_ids=True)
  got = sample_one_hop_auto(indptr, indices, seeds, K, key, eids,
                            with_edge_ids=True)
  _assert_onehop_equal(ref, got)
  # replace=True has no window arm -> transparent XLA fallback
  ref_r = sample_one_hop(indptr, indices, seeds, K, key, eids,
                         with_edge_ids=True, replace=True)
  got_r = sample_one_hop_auto(indptr, indices, seeds, K, key, eids,
                              with_edge_ids=True, replace=True)
  _assert_onehop_equal(ref_r, got_r)


def test_fused_supported_reasons():
  w = default_window(K)
  assert fused_sample_supported(32, K, w, jnp.int32,
                                num_edges=100) is None
  assert fused_sample_supported(32, K, w, jnp.int32,
                                replace=True) == 'replace-arm'
  assert fused_sample_supported(32, K, w, jnp.int32,
                                num_edges=0) == 'empty'
  assert fused_sample_supported(32, K, 4, jnp.int32,
                                num_edges=100) == 'k>window'
  assert fused_sample_supported(32, K, 256, jnp.int32,
                                num_edges=100).startswith('window>')
  assert fused_sample_supported(32, K, w, jnp.int64,
                                num_edges=100) == 'indices-dtype'


def test_fallback_event_emitted(monkeypatch):
  from graphlearn_tpu.telemetry.recorder import recorder
  monkeypatch.setenv('GLT_PALLAS_SAMPLE', '1')
  indptr, indices, eids, n, _ = _csr()
  was = recorder.enabled
  recorder.enable()
  try:
    recorder.clear()
    sample_one_hop_auto(indptr, indices, _seeds(n), K,
                        jax.random.PRNGKey(0), eids,
                        with_edge_ids=True, replace=True)
    kinds = [e['kind'] for e in recorder.events()]
    assert 'pallas.fallback' in kinds
    fb = [e for e in recorder.events()
          if e['kind'] == 'pallas.fallback'][0]
    assert fb['kernel'] == 'fused_sample'
    assert fb['reason'] == 'replace-arm'
    recorder.clear()
    sample_one_hop_auto(indptr, indices, _seeds(n), K,
                        jax.random.PRNGKey(0), eids,
                        with_edge_ids=True)
    kinds = [e['kind'] for e in recorder.events()]
    assert 'pallas.dispatch' in kinds
  finally:
    recorder.clear()
    if not was:
      recorder.disable()


# -- the NeighborSampler / fused-epoch threading ---------------------------

def test_neighbor_sampler_knob_parity(monkeypatch):
  from graphlearn_tpu.data.graph import Graph
  from graphlearn_tpu.sampler.base import NodeSamplerInput
  from graphlearn_tpu.sampler.neighbor_sampler import NeighborSampler
  indptr, indices, _, n, _ = _csr(seed=9)
  g = Graph.from_device_arrays(indptr, indices)
  seeds = np.asarray(_seeds(n))

  def run():
    s = NeighborSampler(g, [5, 3], with_edge=True, seed=17)
    return s.sample_from_nodes(NodeSamplerInput(node=seeds))

  monkeypatch.delenv('GLT_PALLAS_SAMPLE', raising=False)
  a = run()
  monkeypatch.setenv('GLT_PALLAS_SAMPLE', '1')
  b = run()
  for f in ('node', 'node_count', 'row', 'col', 'edge',
            'num_sampled_nodes', 'num_sampled_edges'):
    np.testing.assert_array_equal(np.asarray(getattr(a, f)),
                                  np.asarray(getattr(b, f)))


# -- delta-CSR merge kernel ------------------------------------------------

def _delta_fixture(n=60, seed=12, events=41):
  from graphlearn_tpu.streaming.delta import DeltaSegment
  rng = np.random.default_rng(seed)
  deg = rng.poisson(6, n)
  indptr = np.zeros(n + 1, np.int64)
  np.cumsum(deg, out=indptr[1:])
  e = int(indptr[-1])
  indices = (np.concatenate([np.sort(rng.integers(0, n, d))
                             for d in deg])
             if e else np.zeros(0, np.int64))
  eids = rng.permutation(e).astype(np.int64)
  seg = DeltaSegment(src=rng.integers(0, n, events).astype(np.int64),
                     dst=rng.integers(0, n, events).astype(np.int64),
                     eids=(np.arange(events) + e).astype(np.int64))
  return indptr, indices, eids, seg


def _assert_merge_equal(a, b):
  for x, y, name in zip(a, b, ('indptr', 'indices', 'eids')):
    assert x.dtype == y.dtype, name
    np.testing.assert_array_equal(x, y, err_msg=name)


def test_delta_merge_device_byte_identity():
  from graphlearn_tpu.ops.pallas_delta import merge_delta_csr_device
  from graphlearn_tpu.streaming.delta import merge_delta_csr
  indptr, indices, eids, seg = _delta_fixture()
  _assert_merge_equal(
      merge_delta_csr(indptr, indices, eids, seg),
      merge_delta_csr_device(indptr, indices, eids, seg,
                             interpret=True))


def test_delta_merge_device_corners():
  from graphlearn_tpu.ops.pallas_delta import merge_delta_csr_device
  from graphlearn_tpu.streaming.delta import (DeltaSegment,
                                              merge_delta_csr)
  indptr, indices, eids, seg = _delta_fixture(seed=13)
  empty = DeltaSegment(src=seg.src[:0], dst=seg.dst[:0],
                       eids=seg.eids[:0])
  _assert_merge_equal(
      merge_delta_csr(indptr, indices, eids, empty),
      merge_delta_csr_device(indptr, indices, eids, empty,
                             interpret=True))
  n = len(indptr) - 1
  ip0 = np.zeros(n + 1, np.int64)
  _assert_merge_equal(
      merge_delta_csr(ip0, indices[:0], eids[:0], seg),
      merge_delta_csr_device(ip0, indices[:0], eids[:0], seg,
                             interpret=True))
  # heavy duplicate columns: the stable base-first tie-break
  ties = DeltaSegment(src=np.full(20, 7, np.int64),
                      dst=np.array([3] * 10 + [5] * 10, np.int64),
                      eids=np.arange(20, dtype=np.int64) + 1000)
  _assert_merge_equal(
      merge_delta_csr(indptr, indices, eids, ties),
      merge_delta_csr_device(indptr, indices, eids, ties,
                             interpret=True))


def test_delta_merge_range_check_matches_host():
  from graphlearn_tpu.ops.pallas_delta import merge_delta_csr_device
  from graphlearn_tpu.streaming.delta import DeltaSegment
  indptr, indices, eids, _ = _delta_fixture()
  bad = DeltaSegment(src=np.array([len(indptr)], np.int64),
                     dst=np.array([0], np.int64),
                     eids=np.array([0], np.int64))
  with pytest.raises(ValueError, match='out of range'):
    merge_delta_csr_device(indptr, indices, eids, bad, interpret=True)


def test_streaming_graph_knob_parity(monkeypatch):
  """`StreamingGraph.apply_events` publishes byte-identical versions
  with GLT_PALLAS_DELTA on and off (and keeps the fault-free default
  path jax-free)."""
  from graphlearn_tpu.streaming.delta import StreamingGraph

  def build_and_apply(seed):
    rng = np.random.default_rng(seed)
    n = 40
    deg = rng.poisson(4, n)
    indptr = np.zeros(n + 1, np.int64)
    np.cumsum(deg, out=indptr[1:])
    e = int(indptr[-1])
    indices = (np.concatenate([np.sort(rng.integers(0, n, d))
                               for d in deg])
               if e else np.zeros(0, np.int64))
    g = StreamingGraph(indptr, indices, np.arange(e, dtype=np.int64))
    v = None
    for wave in range(3):
      m = 17 + wave
      v = g.apply_events(rng.integers(0, n, m).astype(np.int64),
                         rng.integers(0, n, m).astype(np.int64))
    return (np.asarray(v.indptr), np.asarray(v.indices),
            np.asarray(v.edge_ids))

  monkeypatch.delenv('GLT_PALLAS_DELTA', raising=False)
  a = build_and_apply(31)
  monkeypatch.setenv('GLT_PALLAS_DELTA', '1')
  b = build_and_apply(31)
  for x, y in zip(a, b):
    assert x.dtype == y.dtype
    np.testing.assert_array_equal(x, y)


# -- pinned-host zero-copy cold gather ------------------------------------

def _tiered_feature(budget, monkeypatch=None):
  from graphlearn_tpu.data import Feature
  n, d = 64, 8
  feats = (np.arange(n, dtype=np.float32)[:, None]
           * np.ones((1, d), np.float32))
  return Feature(feats, split_ratio=0.25, cold_cache_rows=budget)


@pytest.mark.parametrize('budget', [0, 4])
def test_pinned_cold_fill_byte_identity(budget, monkeypatch):
  ids = np.array([1, 9, 7, 30, 0, 63, -1, 9, 40], np.int64)
  monkeypatch.delenv('GLT_PALLAS_COLD', raising=False)
  ref_f = _tiered_feature(budget)
  refs = [np.asarray(ref_f[ids]) for _ in range(3)]  # admits mutate
  monkeypatch.setenv('GLT_PALLAS_COLD', '1')
  got_f = _tiered_feature(budget)
  assert got_f._pinned_buffer() is not None
  for i in range(3):
    got = np.asarray(got_f[ids])
    assert got.dtype == refs[i].dtype
    np.testing.assert_array_equal(got, refs[i])


def test_pinned_cold_kill_switch(monkeypatch):
  """GLT_PALLAS_COLD is re-read per batch: flipping it off mid-life
  reverts to the compact host path with identical values."""
  ids = np.array([2, 33, 8, 61], np.int64)
  monkeypatch.setenv('GLT_PALLAS_COLD', '1')
  f = _tiered_feature(0)
  on = np.asarray(f[ids])
  assert f._pinned_cold is not None
  monkeypatch.delenv('GLT_PALLAS_COLD', raising=False)
  off = np.asarray(f[ids])
  np.testing.assert_array_equal(on, off)


def test_pinned_buffer_registers_memaccount_tier(monkeypatch):
  from graphlearn_tpu.data.cold_cache import make_pinned_cold_buffer
  from graphlearn_tpu.telemetry.live import live
  monkeypatch.setenv('GLT_PALLAS_COLD', '1')
  rows = np.random.default_rng(0).standard_normal((32, 8))
  buf = make_pinned_cold_buffer(rows, 8, np.float32)
  assert buf is not None
  text = live.prometheus_text()
  assert 'glt_memory_tier_bytes{tier="pinned_host"}' in text
  # dtype cast applied once at build == per-batch astype
  idx = np.array([3, 0, 31], np.int32)
  np.testing.assert_array_equal(
      np.asarray(buf.gather(idx)), rows[idx].astype(np.float32))
