"""FusedEpoch: the whole-epoch lax.scan program must train like the
per-batch path, be deterministic under its seed, and refuse datasets
its constraints exclude."""
import numpy as np
import jax
import jax.numpy as jnp
import optax
import pytest

from graphlearn_tpu.data import Dataset
from graphlearn_tpu.loader import FusedEpoch, NeighborLoader
from graphlearn_tpu.models import (GraphSAGE, create_train_state,
                                   make_supervised_step)
from graphlearn_tpu.sampler.neighbor_sampler import _multihop_sample


def _cluster_dataset(n=90, d=8, classes=3, seed=0, split_ratio=1.0):
  rng = np.random.default_rng(seed)
  labels = (np.arange(n) % classes).astype(np.int32)
  rows, cols = [], []
  for v in range(n):
    for _ in range(6):
      if rng.random() < 0.85:
        u = rng.choice(np.nonzero(labels == labels[v])[0])
      else:
        u = rng.integers(0, n)
      rows.append(v)
      cols.append(int(u))
  feats = np.eye(classes, d, dtype=np.float32)[labels]
  feats += rng.normal(0, 0.3, feats.shape).astype(np.float32)
  ds = (Dataset()
        .init_graph((np.array(rows), np.array(cols)), layout='COO',
                    num_nodes=n)
        .init_node_features(feats, split_ratio=split_ratio)
        .init_node_labels(labels))
  return ds, labels


def _setup(ds, batch_size=32, seed=0):
  model = GraphSAGE(hidden_features=16, out_features=3, num_layers=2)
  tx = optax.adam(1e-2)
  loader = NeighborLoader(ds, [4, 3], np.arange(90), batch_size=batch_size)
  state, apply_fn = create_train_state(
      model, jax.random.key(seed), next(iter(loader)), tx)
  return state, apply_fn, tx


def test_fused_epoch_trains():
  ds, _ = _cluster_dataset()
  state, apply_fn, tx = _setup(ds)
  fused = FusedEpoch(ds, [4, 3], np.arange(90), apply_fn, tx,
                     batch_size=32, shuffle=True, seed=0)
  assert len(fused) == 3                      # 90 seeds / 32 -> padded tail
  state, first = fused.run(state)             # run() donates its input state
  for _ in range(15):
    state, stats = fused.run(state)
  assert stats['seeds'] == 90                 # padded slots not counted
  assert stats['loss'] < first['loss']
  assert stats['accuracy'] > 0.8
  assert int(state.step) == 16 * len(fused)   # every scan step stepped optax


def test_fused_epoch_deterministic():
  ds, _ = _cluster_dataset()
  state, apply_fn, tx = _setup(ds)
  runs = []
  for _ in range(2):
    fused = FusedEpoch(ds, [4, 3], np.arange(90), apply_fn, tx,
                       batch_size=32, shuffle=True, seed=7)
    s, stats = fused.run(jax.tree_util.tree_map(jnp.copy, state))
    runs.append((np.asarray(stats['losses']),
                 np.asarray(jax.tree_util.tree_leaves(s.params)[0])))
  np.testing.assert_array_equal(runs[0][0], runs[1][0])
  np.testing.assert_array_equal(runs[0][1], runs[1][1])


def test_fused_step_matches_manual_batch():
  """One-batch epoch parity: re-derive the scan body's sample with the
  fused key schedule (epoch=1, i=0), collate it by hand, push it
  through `make_supervised_step` — the fused loss must match exactly."""
  from graphlearn_tpu.loader.transform import Batch, _gather_labels
  ds, _ = _cluster_dataset()
  state, apply_fn, tx = _setup(ds, batch_size=90)
  fused = FusedEpoch(ds, [4, 3], np.arange(90), apply_fn, tx,
                     batch_size=90, shuffle=False, seed=3)
  seeds = np.stack(list(fused._batcher))
  assert seeds.shape == (1, 90)
  key = jax.random.fold_in(fused._base_key, 1)
  g = ds.get_graph()
  (nodes, count, row, col, _e, emask, seed_local, _nsn,
   _nse) = _multihop_sample(
       g.indptr, g.indices, None, jnp.asarray(seeds[0]),
       jax.random.fold_in(key, 0), fanouts=(4, 3),
       node_cap=fused._node_cap, with_edge=False)
  assert int(count) <= fused._node_cap
  batch = Batch(
      x=ds.node_features._device_get(nodes),
      y=_gather_labels(ds.get_node_label_device(), nodes),
      edge_index=jnp.stack([row, col]),
      node=nodes, node_mask=nodes >= 0, edge_mask=emask,
      batch=jnp.asarray(seeds[0]), batch_size=90,
      metadata={'seed_local': seed_local})
  step = make_supervised_step(apply_fn, tx, 90)
  state_copy = jax.tree_util.tree_map(jnp.copy, state)
  _, loss_manual, correct_manual = step(state_copy, batch)
  _, stats = fused.run(state)
  np.testing.assert_allclose(np.asarray(stats['losses'][0]),
                             np.asarray(loss_manual), rtol=1e-6)
  assert stats['correct'] == int(correct_manual)


def test_fused_epoch_remat_trains_same_task():
  """remat=True must only change memory behavior, not learning: the
  rematerialized epoch trains to the same quality."""
  ds, _ = _cluster_dataset()
  state, apply_fn, tx = _setup(ds)
  fused = FusedEpoch(ds, [4, 3], np.arange(90), apply_fn, tx,
                     batch_size=32, shuffle=True, seed=0, remat=True)
  state, first = fused.run(state)
  for _ in range(15):
    state, stats = fused.run(state)
  assert stats['loss'] < first['loss']
  assert stats['accuracy'] > 0.8


def test_fused_epoch_tiered_matches_untiered():
  """Tiered Features (split_ratio < 1) now run as tiered fused epochs
  (r10): chunked collect scans + the cache-aware cold service between
  dispatches + train scans.  Same seed, same feature VALUES, so the
  per-step losses must match the fully-HBM single-program epoch."""
  ds_full, _ = _cluster_dataset()
  ds_tier, _ = _cluster_dataset(split_ratio=0.4)
  state_f, apply_fn, tx = _setup(ds_full)
  state_t = jax.tree_util.tree_map(jnp.copy, state_f)
  fused_f = FusedEpoch(ds_full, [4, 3], np.arange(90), apply_fn, tx,
                       batch_size=32, shuffle=True, seed=0)
  fused_t = FusedEpoch(ds_tier, [4, 3], np.arange(90), apply_fn, tx,
                       batch_size=32, shuffle=True, seed=0)
  assert fused_t._tiered and not fused_f._tiered
  state_f, stats_f = fused_f.run(state_f)
  state_t, stats_t = fused_t.run(state_t)
  np.testing.assert_allclose(np.asarray(stats_t['losses']),
                             np.asarray(stats_f['losses']), rtol=1e-5)
  assert stats_t['seeds'] == stats_f['seeds'] == 90
  # the cold tier actually served rows (this is not a vacuous run)
  assert fused_t._feat.cold_stats['cold_lookups'] > 0
  # and evaluate() takes the chunked path end-to-end
  acc = fused_t.evaluate(state_t.params, np.arange(90))
  assert 0.0 <= acc <= 1.0


def test_fused_epoch_refuses_missing_labels():
  ds, _ = _cluster_dataset()
  ds2 = (Dataset()
         .init_graph((ds.get_graph().indptr, ds.get_graph().indices),
                     layout='CSR', num_nodes=90)
         .init_node_features(np.ones((90, 4), np.float32)))
  _, apply_fn, tx = _setup(ds)
  with pytest.raises(ValueError, match='labels'):
    FusedEpoch(ds2, [4, 3], np.arange(90), apply_fn, tx, batch_size=32)


@pytest.mark.slow
def test_fused_evaluate_matches_eval_loop():
  """fused.evaluate == a make_eval_step loop over the same split
  (different sampling keys; on a well-separated task both sides must
  land at high accuracy)."""
  from graphlearn_tpu.models import make_eval_step
  ds, _ = _cluster_dataset()
  state, apply_fn, tx = _setup(ds)
  fused = FusedEpoch(ds, [4, 3], np.arange(90), apply_fn, tx,
                     batch_size=32, shuffle=True, seed=0)
  for _ in range(15):
    state, _ = fused.run(state)
  acc_fused = fused.evaluate(state.params, np.arange(90))
  eval_step = make_eval_step(apply_fn, 32)
  loader = NeighborLoader(ds, [4, 3], np.arange(90), batch_size=32)
  correct = total = 0
  for batch in loader:
    c, t = eval_step(state.params, batch)
    correct += int(c)
    total += int(t)
  assert total == 90
  assert acc_fused > 0.8
  assert abs(acc_fused - correct / total) < 0.15


@pytest.mark.slow
def test_fused_link_epoch_trains():
  """Binary-mode fused link training: loss decreases and positive
  pairs end up scoring above sampled negatives."""
  from graphlearn_tpu.loader import FusedLinkEpoch
  ds, labels = _cluster_dataset()
  g = ds.get_graph()
  # seed edges = existing edges (positives)
  rows = np.repeat(np.arange(90), np.diff(np.asarray(g.indptr)))
  cols = np.asarray(g.indices)
  sel = np.random.default_rng(0).permutation(len(rows))[:128]
  model = GraphSAGE(hidden_features=16, out_features=8, num_layers=2)
  import optax as _optax
  tx = _optax.adam(1e-2)
  loader = NeighborLoader(ds, [4, 3], np.arange(90), batch_size=32)
  state, apply_fn = create_train_state(
      model, jax.random.key(0), next(iter(loader)), tx)
  fused = FusedLinkEpoch(ds, [4, 3], (rows[sel], cols[sel]), apply_fn,
                         tx, batch_size=32, neg_sampling='binary',
                         shuffle=True, seed=0)
  assert len(fused) == 4
  state, first = fused.run(state)
  for _ in range(20):
    state, stats = fused.run(state)
  assert stats['seeds'] == 128
  assert stats['loss'] < first['loss']
  assert stats['loss'] < 0.62       # below ln(2): pos/neg separated


@pytest.mark.slow
def test_fused_link_triplet_trains():
  from graphlearn_tpu.loader import FusedLinkEpoch
  from graphlearn_tpu.sampler import NegativeSampling
  ds, _ = _cluster_dataset()
  g = ds.get_graph()
  rows = np.repeat(np.arange(90), np.diff(np.asarray(g.indptr)))
  cols = np.asarray(g.indices)
  sel = np.random.default_rng(1).permutation(len(rows))[:64]
  model = GraphSAGE(hidden_features=16, out_features=8, num_layers=2)
  import optax as _optax
  tx = _optax.adam(1e-2)
  loader = NeighborLoader(ds, [4, 3], np.arange(90), batch_size=32)
  state, apply_fn = create_train_state(
      model, jax.random.key(0), next(iter(loader)), tx)
  fused = FusedLinkEpoch(ds, [4, 3], (rows[sel], cols[sel]), apply_fn,
                         tx, batch_size=32,
                         neg_sampling=NegativeSampling('triplet', 2),
                         shuffle=True, seed=0)
  state, first = fused.run(state)
  for _ in range(20):
    state, stats = fused.run(state)
  assert stats['loss'] < first['loss']


def test_fused_link_tiered_matches_untiered():
  """FusedLinkEpoch over a tiered Feature (r10): the sample-only
  collect scans + the cache-aware cold service must reproduce the
  fully-HBM single-program epoch's losses under the same seed."""
  from graphlearn_tpu.loader import FusedLinkEpoch
  import optax as _optax
  ds_full, _ = _cluster_dataset()
  ds_tier, _ = _cluster_dataset(split_ratio=0.4)
  g = ds_full.get_graph()
  rows = np.repeat(np.arange(90), np.diff(np.asarray(g.indptr)))
  cols = np.asarray(g.indices)
  sel = np.arange(64)
  model = GraphSAGE(hidden_features=16, out_features=8, num_layers=2)
  tx = _optax.adam(1e-2)
  loader = NeighborLoader(ds_full, [4, 3], np.arange(90), batch_size=32)
  state, apply_fn = create_train_state(
      model, jax.random.key(0), next(iter(loader)), tx)
  state_t = jax.tree_util.tree_map(jnp.copy, state)
  fused_f = FusedLinkEpoch(ds_full, [4, 3], (rows[sel], cols[sel]),
                           apply_fn, tx, batch_size=32,
                           neg_sampling='binary', shuffle=False, seed=3)
  fused_t = FusedLinkEpoch(ds_tier, [4, 3], (rows[sel], cols[sel]),
                           apply_fn, tx, batch_size=32,
                           neg_sampling='binary', shuffle=False, seed=3)
  assert fused_t._tiered and not fused_f._tiered
  state, stats_f = fused_f.run(state)
  state_t, stats_t = fused_t.run(state_t)
  np.testing.assert_allclose(np.asarray(stats_t['losses']),
                             np.asarray(stats_f['losses']), rtol=1e-5)
  assert fused_t._feat.cold_stats['cold_lookups'] > 0
  # tiered evaluate() takes the chunked collect + AUC-consume path
  auc = fused_t.evaluate(state_t.params, (rows[sel][:32],
                                          cols[sel][:32]))
  assert 0.0 <= auc <= 1.0


@pytest.mark.slow
def test_fused_link_step_matches_manual_batch():
  """Parity pin for the duplicated seed/metadata assembly: one-batch
  fused link epoch == manual sample_negative + _multihop_sample +
  metadata + link step with the fused key schedule."""
  from graphlearn_tpu.loader import FusedLinkEpoch
  from graphlearn_tpu.loader.transform import Batch
  from graphlearn_tpu.models.train import link_loss_from_metadata
  from graphlearn_tpu.ops.negative import sample_negative
  import optax as _optax
  ds, _ = _cluster_dataset()
  g = ds.get_graph()
  rows = np.repeat(np.arange(90), np.diff(np.asarray(g.indptr)))
  cols = np.asarray(g.indices)
  b = 32
  sel = np.arange(b)
  model = GraphSAGE(hidden_features=16, out_features=8, num_layers=2)
  tx = _optax.adam(1e-2)
  loader = NeighborLoader(ds, [4, 3], np.arange(90), batch_size=b)
  state, apply_fn = create_train_state(
      model, jax.random.key(0), next(iter(loader)), tx)
  fused = FusedLinkEpoch(ds, [4, 3], (rows[sel], cols[sel]), apply_fn,
                         tx, batch_size=b, neg_sampling='binary',
                         shuffle=False, seed=5)
  # re-derive step 0's batch with the fused key schedule
  key = jax.random.fold_in(jax.random.fold_in(fused._base_key, 1), 0)
  src = jnp.asarray(rows[sel].astype(np.int32))
  dst = jnp.asarray(cols[sel].astype(np.int32))
  batch = fused._link_batch(src, dst, jnp.ones((b,), jnp.int32), key,
                            fused._dev, False)

  def loss_fn(params):
    emb = apply_fn(params, batch.x, batch.edge_index, batch.edge_mask)
    return link_loss_from_metadata(emb, batch.metadata)

  loss_manual = float(loss_fn(state.params))
  state2 = jax.tree_util.tree_map(jnp.copy, state)
  _, stats = fused.run(state2)
  np.testing.assert_allclose(float(np.asarray(stats['losses'])[0]),
                             loss_manual, rtol=1e-5)


@pytest.mark.slow
def test_fused_matches_per_batch_loss_scale():
  """Fused and per-batch paths train to comparable losses on the same
  task (not bit-identical: the key schedules differ by design)."""
  ds, _ = _cluster_dataset()
  state, apply_fn, tx = _setup(ds)
  step = make_supervised_step(apply_fn, tx, 32)
  loader = NeighborLoader(ds, [4, 3], np.arange(90), batch_size=32,
                          shuffle=True, seed=0)
  s_loop = state
  for _ in range(10):
    for batch in loader:
      s_loop, loss_loop, _ = step(s_loop, batch)
  fused = FusedEpoch(ds, [4, 3], np.arange(90), apply_fn, tx,
                     batch_size=32, shuffle=True, seed=0)
  s_fused = state
  for _ in range(10):
    s_fused, stats = fused.run(s_fused)
  assert abs(float(loss_loop) - stats['loss']) < 0.5


def test_fused_link_evaluate_auc():
  """`FusedLinkEpoch.evaluate`: held-out link AUC as one scan
  program.  Untrained embeddings must score near chance; after
  training on the clustered graph, held-out WITHIN-cluster edges
  must rank above strict random negatives (mostly cross-cluster)."""
  from graphlearn_tpu.loader import FusedLinkEpoch
  ds, labels = _cluster_dataset()
  g = ds.get_graph()
  rows = np.repeat(np.arange(90), np.diff(np.asarray(g.indptr)))
  cols = np.asarray(g.indices)
  perm = np.random.default_rng(1).permutation(len(rows))
  train_sel, eval_sel = perm[:256], perm[256:352]
  model = GraphSAGE(hidden_features=16, out_features=8, num_layers=2)
  import optax as _optax
  tx = _optax.adam(1e-2)
  loader = NeighborLoader(ds, [4, 3], np.arange(90), batch_size=32)
  state, apply_fn = create_train_state(
      model, jax.random.key(0), next(iter(loader)), tx)
  fused = FusedLinkEpoch(ds, [4, 3], (rows[train_sel], cols[train_sel]),
                         apply_fn, tx, batch_size=32,
                         neg_sampling='binary', shuffle=True, seed=0)
  eval_edges = (rows[eval_sel], cols[eval_sel])
  auc0 = fused.evaluate(state.params, eval_edges)
  assert 0.2 < auc0 < 0.8, f'untrained AUC {auc0} not near chance'
  for _ in range(20):
    state, _ = fused.run(state)
  auc1 = fused.evaluate(state.params, eval_edges)
  assert auc1 > 0.8, f'trained AUC {auc1} <= 0.8'
  assert auc1 > auc0
  # triplet mode refuses: precision@rank is its metric, not this AUC
  tri = FusedLinkEpoch(ds, [4, 3], eval_edges, apply_fn, tx,
                       batch_size=32, neg_sampling=('triplet', 1),
                       seed=0)
  with pytest.raises(ValueError, match='binary'):
    tri.evaluate(state.params, eval_edges)


def test_fresh_compile_internals_present():
  """`loader.fused._fresh_compile` leans on jax._src internals that
  have no stability guarantee; this pin makes a jax upgrade that
  moves them FAIL here instead of silently degrading the cache
  bypass to its process-wide fallback (ADVICE r4)."""
  from jax._src import compilation_cache as cc
  from jax._src import config as cfg
  assert callable(cc.reset_cache)
  assert hasattr(cfg, 'enable_compilation_cache')
