"""Link-loader tests: binary/triplet negatives, label shift, masks.

Mirrors the intent of reference `test/python/test_link_loader.py` on
the TPU padding contract.
"""
import numpy as np
import pytest

from graphlearn_tpu.data import Dataset
from graphlearn_tpu.loader import LinkNeighborLoader
from graphlearn_tpu.sampler import NegativeSampling


def _ring_dataset(n=40, d=4):
  rows = np.concatenate([np.arange(n), np.arange(n)])
  cols = np.concatenate([(np.arange(n) + 1) % n, (np.arange(n) + 2) % n])
  feats = np.arange(n, dtype=np.float32)[:, None] * np.ones((1, d),
                                                            np.float32)
  ds = (Dataset()
        .init_graph((rows, cols), layout='COO', num_nodes=n)
        .init_node_features(feats, split_ratio=1.0))
  return ds, rows, cols


def _edge_set(rows, cols):
  return set(zip(rows.tolist(), cols.tolist()))


def test_binary_negative_sampling():
  ds, rows, cols = _ring_dataset()
  seed_edges = (rows[:16], cols[:16])
  loader = LinkNeighborLoader(ds, [2, 2], seed_edges,
                              neg_sampling=NegativeSampling('binary', 1.0),
                              batch_size=8, seed=0)
  existing = _edge_set(rows, cols)
  n_batches = 0
  for batch in loader:
    n_batches += 1
    eli = np.asarray(batch.metadata['edge_label_index'])
    label = np.asarray(batch.metadata['edge_label'])
    mask = np.asarray(batch.metadata['edge_label_mask'])
    nodes = np.asarray(batch.node)
    assert eli.shape[1] == label.shape[0] == mask.shape[0] == 16
    # positives: first 8 slots; resolve local -> global and check the
    # edge really exists.
    for i in range(8):
      if not mask[i]:
        continue
      u, v = nodes[eli[0, i]], nodes[eli[1, i]]
      assert (u, v) in existing
      assert label[i] == 1
    # negatives: last 8 slots, label 0, strict non-edges (padding may
    # rarely relax, but on this sparse ring strict succeeds).
    for i in range(8, 16):
      if not mask[i]:
        continue
      u, v = nodes[eli[0, i]], nodes[eli[1, i]]
      assert label[i] == 0
      assert (u, v) not in existing
  assert n_batches == 2


def test_binary_label_shift():
  ds, rows, cols = _ring_dataset()
  labels = np.zeros(16, dtype=np.int32)  # user label 0
  loader = LinkNeighborLoader(ds, [2], (rows[:16], cols[:16]),
                              edge_label=labels,
                              neg_sampling=NegativeSampling('binary', 1.0),
                              batch_size=16, seed=0)
  batch = next(iter(loader))
  label = np.asarray(batch.metadata['edge_label'])
  # user labels shifted +1 => positives 1, negatives 0.
  assert (label[:16] == 1).all()
  assert (label[16:] == 0).all()


def test_triplet_negative_sampling():
  ds, rows, cols = _ring_dataset()
  loader = LinkNeighborLoader(ds, [2], (rows[:10], cols[:10]),
                              neg_sampling=NegativeSampling('triplet', 2),
                              batch_size=10, seed=0)
  existing = _edge_set(rows, cols)
  batch = next(iter(loader))
  md = batch.metadata
  nodes = np.asarray(batch.node)
  src = np.asarray(md['src_index'])
  dpos = np.asarray(md['dst_pos_index'])
  dneg = np.asarray(md['dst_neg_index'])
  pmask = np.asarray(md['pair_mask'])
  assert dneg.shape == (10, 2)
  for i in range(10):
    if not pmask[i]:
      continue
    u = nodes[src[i]]
    assert (u, nodes[dpos[i]]) in existing
    for j in range(2):
      # strict negatives: (u, neg) should not be an edge.
      assert (u, nodes[dneg[i, j]]) not in existing


def test_padded_tail_batch_masks():
  ds, rows, cols = _ring_dataset()
  # 10 seed edges, batch 8 -> tail has 6 padded pairs.
  loader = LinkNeighborLoader(ds, [2], (rows[:10], cols[:10]),
                              neg_sampling=NegativeSampling('binary', 1.0),
                              batch_size=8, seed=0)
  batches = list(loader)
  assert len(batches) == 2
  mask = np.asarray(batches[1].metadata['edge_label_mask'])
  # slots 2..7 are padded positives -> masked out.
  assert mask[:2].all()
  assert not mask[2:8].any()


@pytest.mark.slow
def test_unsupervised_training_decreases_loss():
  import jax
  import optax
  from graphlearn_tpu.models import (GraphSAGE, create_train_state,
                                     make_unsupervised_step)
  ds, rows, cols = _ring_dataset()
  loader = LinkNeighborLoader(ds, [2, 2], (rows, cols),
                              neg_sampling=NegativeSampling('binary', 1.0),
                              batch_size=20, shuffle=True, seed=0)
  model = GraphSAGE(hidden_features=16, out_features=8, num_layers=2)
  tx = optax.adam(1e-2)
  state, apply_fn = create_train_state(model, jax.random.key(0),
                                       next(iter(loader)), tx)
  step = make_unsupervised_step(apply_fn, tx)
  losses = []
  for _ in range(5):
    for batch in loader:
      state, loss = step(state, batch)
      losses.append(float(loss))
  assert np.mean(losses[-4:]) < np.mean(losses[:4])
