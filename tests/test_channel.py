"""Channel + producer pipeline tests.

Mirrors the reference's `test/python/test_shm_channel.py` (cross-process
shm send/recv) and the mp-producer epoch protocol of
`test_dist_neighbor_loader.py` — all-local processes, real shm, no
mocks (SURVEY §4 pattern).
"""
import multiprocessing as mp

import numpy as np
import pytest

from graphlearn_tpu import native
from graphlearn_tpu.channel import MpChannel, ShmChannel
from graphlearn_tpu.distributed import (
    CollocatedDistSamplingWorkerOptions, DistNeighborLoader, HostDataset,
    HostNeighborSampler, MpDistSamplingWorkerOptions)

pytestmark = pytest.mark.skipif(not native.available(),
                                reason='native lib unavailable')


def ring_dataset(n=40, d=8):
  """Deterministic ring: node v -> v+1, v+2; feature row = id value."""
  rows = np.repeat(np.arange(n), 2)
  cols = np.concatenate([(np.arange(n) + 1) % n,
                         (np.arange(n) + 2) % n]).reshape(2, n).T.reshape(-1)
  feats = np.tile(np.arange(n, dtype=np.float32)[:, None], (1, d))
  labels = np.arange(n, dtype=np.int64) % 4
  return HostDataset.from_coo(rows, cols, n, node_features=feats,
                              node_labels=labels)


def _producer_proc(ch, n_msgs):
  for i in range(n_msgs):
    ch.send({'ids': np.arange(i + 1, dtype=np.int64),
             'val': np.full((2, 3), float(i), np.float32)})


def _stress_producer(ch, rank, per):
  for i in range(per):
    ch.send({'tag': np.array([rank, i], np.int64),
             'pay': np.full(64, rank * 1000 + i, np.int32)})


class TestShmChannel:
  def test_roundtrip_same_process(self):
    ch = ShmChannel(capacity=4, shm_size='1MB')
    msg = {'a': np.arange(5, dtype=np.int64),
           'b': np.ones((3, 2), np.float32)}
    ch.send(msg)
    out = ch.recv()
    assert set(out) == {'a', 'b'}
    np.testing.assert_array_equal(out['a'], msg['a'])
    np.testing.assert_array_equal(out['b'], msg['b'])
    assert ch.empty()
    ch.close()

  def test_cross_process(self):
    ch = ShmChannel(capacity=4, shm_size='1MB')
    ctx = mp.get_context('forkserver')
    p = ctx.Process(target=_producer_proc, args=(ch, 6), daemon=True)
    p.start()
    for i in range(6):
      out = ch.recv()
      assert len(out['ids']) == i + 1
      assert out['val'][0, 0] == float(i)
    p.join(timeout=10)
    ch.close()


class TestMpChannel:
  def test_roundtrip(self):
    ch = MpChannel()
    ch.send({'x': np.arange(3)})
    np.testing.assert_array_equal(ch.recv()['x'], np.arange(3))


class TestHostSampler:
  def test_message_contract(self):
    ds = ring_dataset()
    s = HostNeighborSampler(ds, [2, 2], with_edge=True)
    msg = s.sample_from_nodes(np.array([0, 1], np.int64))
    assert msg['#IS_HETERO'] == 0
    # seeds lead the node table; ring neighbors are v+1/v+2
    np.testing.assert_array_equal(msg['ids'][:2], [0, 1])
    ids = msg['ids']
    rows, cols = msg['rows'], msg['cols']
    assert len(rows) == len(cols) == len(msg['eids'])
    # every edge's endpoints index into the node table; direction is
    # neighbor -> seed and the ring invariant holds mod n
    n = ds.num_nodes
    for r, c in zip(rows, cols):
      assert (ids[r] - ids[c]) % n in (1, 2)
    # features encode ids
    np.testing.assert_allclose(msg['nfeats'][:, 0], ids.astype(np.float32))
    np.testing.assert_array_equal(msg['nlabels'], ids % 4)


class TestDistLoaderModes:
  def _check_epoch(self, loader, n, num_batches, bs):
    seen_seeds = []
    count = 0
    for batch in loader:
      count += 1
      ids = np.asarray(batch.node)
      valid = np.asarray(batch.node_mask)
      # feature rows encode global ids (partition-provenance trick)
      x0 = np.asarray(batch.x)[:, 0]
      np.testing.assert_allclose(x0[valid], ids[valid].astype(np.float32))
      y = np.asarray(batch.y)
      np.testing.assert_array_equal(y[valid], ids[valid] % 4)
      ei = np.asarray(batch.edge_index)
      em = np.asarray(batch.edge_mask)
      r, c = ei[0][em], ei[1][em]
      assert ((ids[r] - ids[c]) % n).max(initial=1) <= 2
      seeds = np.asarray(batch.batch)
      seen_seeds.append(seeds[seeds >= 0])
    assert count == num_batches
    all_seeds = np.concatenate(seen_seeds)
    np.testing.assert_array_equal(np.sort(all_seeds), np.arange(n))

  def test_collocated(self):
    ds = ring_dataset()
    loader = DistNeighborLoader(
        ds, [2, 2], np.arange(40), batch_size=8, shuffle=True,
        worker_options=CollocatedDistSamplingWorkerOptions(),
        to_device=False)
    for _ in range(2):   # two epochs
      self._check_epoch(loader, 40, 5, 8)

  def test_mp_early_break_and_drop_last(self):
    """Abandoning an epoch mid-way must not leak stale batches into the
    next epoch (epoch-stamp filtering), and drop_last truncates."""
    ds = ring_dataset(n=44)
    loader = DistNeighborLoader(
        ds, [2], np.arange(44), batch_size=8, shuffle=True, drop_last=True,
        worker_options=MpDistSamplingWorkerOptions(num_workers=2),
        to_device=False, seed=5)
    try:
      it = iter(loader)
      next(it)          # consume one of 5, then abandon the epoch
      for _ in range(3):
        count = 0
        for batch in loader:
          count += 1
          s = np.asarray(batch.batch)
          assert (s >= 0).all()       # full batches only (drop_last)
        assert count == 5             # 44 // 8
    finally:
      loader.shutdown()

  def test_mp(self):
    ds = ring_dataset()
    loader = DistNeighborLoader(
        ds, [2, 2], np.arange(40), batch_size=8, shuffle=True,
        worker_options=MpDistSamplingWorkerOptions(num_workers=2),
        to_device=False, seed=3)
    try:
      for _ in range(2):
        self._check_epoch(loader, 40, 5, 8)
    finally:
      loader.shutdown()


def test_dead_workers_raise_not_hang(monkeypatch):
  """Crashed sampling pool surfaces as a typed error (the reference's
  MP_STATUS_CHECK_INTERVAL watchdog), never an infinite semaphore
  wait.  The restart budget is pinned to zero — with budget available
  the supervisor would RESTART the pool and finish the epoch exactly
  (tests/test_chaos.py pins that healing path); this test pins the
  irrecoverable arm.  The epoch is far larger than the channel
  capacity, so terminating the workers mid-epoch is guaranteed to
  leave outstanding batches — the test can only pass through the
  watchdog."""
  from graphlearn_tpu.distributed import DistNeighborLoader, PeerLostError
  monkeypatch.setenv('GLT_MAX_WORKER_RESTARTS', '0')
  ds = ring_dataset(n=40)
  seeds = np.tile(np.arange(40), 100)          # 500 batches expected
  loader = DistNeighborLoader(
      ds, [2], seeds, batch_size=8,
      worker_options=MpDistSamplingWorkerOptions(
          num_workers=2, channel_capacity=4),
      to_device=False)
  try:
    it = iter(loader)
    next(it)                       # epoch running
    for w in loader._producer._workers:
      w.terminate()
      w.join(timeout=10)
    with pytest.raises(PeerLostError, match='worker'):
      for _ in range(600):
        next(it)
  finally:
    loader.shutdown()


def test_shm_queue_mpmc_stress():
  """Many producers + two consumer threads hammering one shm ring:
  every message arrives exactly once, payloads intact (the native
  queue's MPMC contract under real contention — the reference gtest
  `test_shm_queue.cu` forks processes likewise)."""
  import threading
  ch = ShmChannel(capacity=8, shm_size='2MB')
  n_producers, per = 4, 50
  ctx = mp.get_context('forkserver')
  procs = []
  for r in range(n_producers):
    # module-level target: forkserver children pickle their target
    p = ctx.Process(target=_stress_producer, args=(ch, r, per),
                    daemon=True)
    p.start()
    procs.append(p)

  import time
  got, lock = [], threading.Lock()
  # deadline-based: forkserver children re-import the package (seconds
  # of startup before the first send), so a short single-recv timeout
  # would bail early; the count check still exits promptly when done
  deadline = time.monotonic() + 120
  def consume():
    while time.monotonic() < deadline:
      with lock:
        if len(got) >= n_producers * per:
          return
      m = ch.recv_timeout(0.5)
      if m is None:
        continue
      with lock:
        got.append(m)

  threads = [threading.Thread(target=consume) for _ in range(2)]
  for t in threads:
    t.start()
  for t in threads:
    t.join(timeout=60)
  for p in procs:
    p.join(timeout=10)
  assert len(got) == n_producers * per
  seen = set()
  for m in got:
    rank, i = int(m['tag'][0]), int(m['tag'][1])
    assert (rank, i) not in seen
    seen.add((rank, i))
    np.testing.assert_array_equal(m['pay'],
                                  np.full(64, rank * 1000 + i, np.int32))
  ch.close()
