"""Fleet federation tests (ISSUE 16 leg 2): per-replica merge under
the ``replica=`` label, fleet aggregates (counter sum / gauge max /
histogram quantile-merge), the ``/fleet`` route, healthz rollup, and
the acceptance gate — a strict `parse_prometheus_text` round-trip of
the federated exposition with ≥2 replicas under concurrent traffic."""
import json
import threading
import time
import urllib.error
import urllib.request

import pytest

from graphlearn_tpu.telemetry import (LiveRegistry, Metrics, OpsServer,
                                      parse_prometheus_text)
from graphlearn_tpu.telemetry.federation import (FleetScraper,
                                                 LocalReplicaTarget,
                                                 ReplicaTarget,
                                                 parse_exposition)


def _reg():
  # each in-process "replica" needs its OWN backing store — a shared
  # process-global Metrics would double-count the fleet sums
  return LiveRegistry(store=Metrics(), strict=True)


def _scraper(**kw):
  return FleetScraper(registry=_reg(), **kw)


def _two_replica_fleet():
  r1, r2 = _reg(), _reg()
  r1.counter('serving.requests_total').inc(3)
  r2.counter('serving.requests_total').inc(7)
  r1.gauge('serving.queue_depth', fn=lambda: 2.0)
  r2.gauge('serving.queue_depth', fn=lambda: 9.0)
  for v in (0.001, 0.002, 0.004):
    r1.histogram('serving.request_latency').observe(v)
  for v in (0.05, 0.1):
    r2.histogram('serving.request_latency').observe(v)
  fs = _scraper()
  fs.add_registry('a', r1)
  fs.add_registry('b', r2)
  return fs, r1, r2


def test_parse_exposition_structure():
  text = ('# HELP glt_x a thing\n# TYPE glt_x counter\n'
          'glt_x{replica="a"} 3\nglt_x 1\n'
          '# TYPE glt_h histogram\n'
          'glt_h_bucket{le="+Inf"} 2\nglt_h_sum 0.5\nglt_h_count 2\n')
  fams = parse_exposition(text)
  assert fams['glt_x']['type'] == 'counter'
  assert fams['glt_x']['help'] == 'a thing'
  assert (('glt_x', [('replica', 'a')], 3.0)
          in fams['glt_x']['samples'])
  # _bucket/_sum/_count samples all group under the histogram family
  assert fams['glt_h']['type'] == 'histogram'
  names = {s[0] for s in fams['glt_h']['samples']}
  assert names == {'glt_h_bucket', 'glt_h_sum', 'glt_h_count'}


def test_merge_counter_sum_gauge_max_histogram_quantiles():
  fs, _, _ = _two_replica_fleet()
  fs.scrape()
  text = fs.prometheus_text()
  metrics = parse_prometheus_text(text)   # strict: raises on junk
  # per-replica samples survive under the replica label
  assert metrics['glt_serving_requests_total{replica="a"}'] == 3.0
  assert metrics['glt_serving_requests_total{replica="b"}'] == 7.0
  # aggregates: counters sum, gauges max
  assert metrics['glt_fleet_serving_requests_total'] == 10.0
  assert metrics['glt_fleet_serving_queue_depth'] == 9.0
  # histogram: bucket-vector sum + nearest-rank merged quantiles
  assert metrics['glt_fleet_serving_request_latency_count'] == 5.0
  assert metrics['glt_fleet_serving_request_latency_p50_secs'] == \
      pytest.approx(0.004096)
  assert metrics['glt_fleet_serving_request_latency_p99_secs'] == \
      pytest.approx(0.131072)


def test_fleet_json_rollup_and_error_entry():
  fs, _, _ = _two_replica_fleet()

  class Dead(ReplicaTarget):
    def scrape(self):
      raise OSError('connection refused')

  fs.add_target(Dead('c'))
  fs.scrape()
  roll = fs.fleet_json()
  assert roll['schema'] == 'glt.fleet.v1'
  assert roll['ok'] is False          # one unscrapeable replica
  assert roll['replicas_up'] == 2
  assert 'OSError' in roll['replicas']['c']['error']
  assert roll['replicas']['a']['ok'] and roll['replicas']['b']['ok']
  # the per-replica scrape-error counter ticked for c only
  assert fs._err_counters['c'].value() >= 1.0
  assert fs._err_counters['a'].value() == 0.0


def test_malformed_replica_is_refused_not_merged():
  fs = _scraper()

  class Junk(ReplicaTarget):
    def scrape(self):
      return 'glt_x this-is-not-a-number\n', {'ok': True}

  fs.add_target(Junk('bad'))
  good = _reg()
  good.counter('serving.requests_total').inc(1)
  fs.add_registry('good', good)
  last = fs.scrape()
  assert not last['bad']['ok'] and last['bad']['error']
  # the merged exposition still strict-parses — junk never leaks in
  parse_prometheus_text(fs.prometheus_text())
  assert fs.fleet_json()['ok'] is False


def test_http_target_scrapes_real_ops_server():
  reg = _reg()
  reg.counter('serving.requests_total').inc(4)
  srv = OpsServer(registry=reg, port=0)
  try:
    fs = _scraper()
    fs.add_url('web', srv.url)
    last = fs.scrape()
    assert last['web']['ok'], last['web']['error']
    metrics = parse_prometheus_text(fs.prometheus_text())
    assert metrics['glt_serving_requests_total{replica="web"}'] == 4.0
    assert metrics['glt_fleet_serving_requests_total'] == 4.0
  finally:
    srv.close()


def test_local_replica_target_renders_heartbeat_gauges():
  class FakeReplica:
    def heartbeat(self):
      return {'serving': {'inflight': 3, 'healthy': True},
              'epoch': 7}

  t = LocalReplicaTarget('r0', FakeReplica())
  text, health = t.scrape()
  metrics = parse_prometheus_text(text)
  assert metrics['glt_serving_inflight'] == 3.0
  assert metrics['glt_epoch'] == 7.0
  assert 'glt_serving_healthy' not in metrics   # bools are skipped
  assert health['ok'] is True
  fs = _scraper()
  fs.add_local_replica('r0', FakeReplica())
  fs.scrape()
  merged = parse_prometheus_text(fs.prometheus_text())
  assert merged['glt_serving_inflight{replica="r0"}'] == 3.0


def test_fleet_route_prom_and_json():
  fs, _, _ = _two_replica_fleet()
  fs.scrape()
  reg = _reg()
  srv = OpsServer(registry=reg, port=0)
  try:
    srv.attach_fleet(fs)
    with urllib.request.urlopen(f'{srv.url}/fleet', timeout=10) as r:
      body = r.read().decode('utf-8')
      assert r.status == 200
    metrics = parse_prometheus_text(body)
    assert metrics['glt_fleet_serving_requests_total'] == 10.0
    with urllib.request.urlopen(f'{srv.url}/fleet?format=json',
                                timeout=10) as r:
      roll = json.loads(r.read())
    assert roll['schema'] == 'glt.fleet.v1'
    assert roll['ok'] is True and roll['replicas_up'] == 2
  finally:
    srv.close()


def test_fleet_route_503_when_replica_down_and_404_unattached():
  srv = OpsServer(registry=_reg(), port=0)
  try:
    with pytest.raises(urllib.error.HTTPError) as ei:
      urllib.request.urlopen(f'{srv.url}/fleet', timeout=10)
    assert ei.value.code == 404
  finally:
    srv.close()
  fs = _scraper()

  class Dead(ReplicaTarget):
    def scrape(self):
      raise OSError('down')

  fs.add_target(Dead('c'))
  fs.scrape()
  srv = OpsServer(registry=_reg(), port=0)
  try:
    srv.attach_fleet(fs)
    with pytest.raises(urllib.error.HTTPError) as ei:
      urllib.request.urlopen(f'{srv.url}/fleet?format=json',
                             timeout=10)
    assert ei.value.code == 503
    roll = json.loads(ei.value.read())
    assert roll['ok'] is False
  finally:
    srv.close()


def test_strict_roundtrip_under_concurrent_traffic():
  """The acceptance gate in miniature: two live replicas take writes
  from worker threads while the scraper repeatedly federates; every
  single exposition must strict-parse and the fleet counter sum must
  equal the per-replica sum WITHIN that exposition (the merge is a
  consistent view of whatever the scrape saw)."""
  r1, r2 = _reg(), _reg()
  c1 = r1.counter('serving.requests_total')
  c2 = r2.counter('serving.requests_total')
  r1.histogram('serving.request_latency')
  r2.histogram('serving.request_latency')
  fs = _scraper()
  fs.add_registry('a', r1)
  fs.add_registry('b', r2)
  stop = threading.Event()

  def writer(c, reg):
    h = reg.histogram('serving.request_latency')
    while not stop.is_set():
      c.inc()
      h.observe(0.002)

  threads = [threading.Thread(target=writer, args=args, daemon=True)
             for args in ((c1, r1), (c2, r2))]
  for t in threads:
    t.start()
  try:
    deadline = time.monotonic() + 10.0
    rounds = 0
    while rounds < 40 and time.monotonic() < deadline:
      fs.scrape()
      metrics = parse_prometheus_text(fs.prometheus_text())  # strict
      total = metrics['glt_fleet_serving_requests_total']
      per = (metrics['glt_serving_requests_total{replica="a"}']
             + metrics['glt_serving_requests_total{replica="b"}'])
      assert total == per
      rounds += 1
  finally:
    stop.set()
    for t in threads:
      t.join(5)
  assert rounds >= 10
  assert parse_prometheus_text(
      fs.prometheus_text())['glt_fleet_serving_requests_total'] > 0


def test_router_make_scraper_federates_replicas():
  """`FleetRouter.make_scraper` is the one-call wiring: every replica
  handle becomes a target (LocalReplica → heartbeat gauges) and the
  hosting registry joins as ``self``."""
  from graphlearn_tpu.serving.router import FleetRouter

  class FakeReplica:
    def __init__(self, name, inflight):
      self.name = name
      self._inflight = inflight

    def heartbeat(self):
      return {'serving': {'inflight': self._inflight}}

    def reachable(self):
      return True

  host = _reg()
  host.counter('serving.requests_total').inc(5)
  router = FleetRouter([FakeReplica('r0', 1), FakeReplica('r1', 4)],
                       auto_start=False)
  fs = router.make_scraper(registry=host)
  try:
    fs.scrape()
    metrics = parse_prometheus_text(fs.prometheus_text())
    assert metrics['glt_serving_inflight{replica="r0"}'] == 1.0
    assert metrics['glt_serving_inflight{replica="r1"}'] == 4.0
    assert metrics['glt_fleet_serving_inflight'] == 4.0      # gauge max
    assert metrics['glt_serving_requests_total{replica="self"}'] == 5.0
    assert fs.fleet_json()['replicas_up'] == 3
  finally:
    fs.close()
    router.close()


def test_scrape_loop_start_close():
  fs = _scraper(scrape_ms=10)
  reg = _reg()
  reg.counter('serving.requests_total').inc(2)
  fs.add_registry('a', reg)
  fs.start()
  try:
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline:
      if fs._latest().get('a', {}).get('ok'):
        break
      time.sleep(0.02)
    assert fs._latest()['a']['ok']
  finally:
    fs.close()
