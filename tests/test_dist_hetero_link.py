"""Hetero link sampling on the device mesh: per-etype collective
strict negatives + two-type endpoint expansion, checked against
host-side ground truth on the 8-device CPU mesh (the hetero arm of
`test_dist_link_sampler.py`)."""
import numpy as np
import pytest

from graphlearn_tpu.parallel import DistHeteroNeighborSampler, make_mesh
from graphlearn_tpu.parallel.dist_hetero import DistHeteroDataset

NU, NI, P = 96, 64, 8
ET = ('u', 'to', 'i')
REV = ('i', 'rev_to', 'u')


def _setup():
  rng = np.random.default_rng(0)
  urow = np.repeat(np.arange(NU), 3)
  icol = rng.integers(0, NI, NU * 3)
  feats = {'u': (np.arange(NU)[:, None]
                 + np.zeros((1, 4))).astype(np.float32),
           'i': (1000 + np.arange(NI)[:, None]
                 + np.zeros((1, 4))).astype(np.float32)}
  hds = DistHeteroDataset.from_full_graph(
      P, {ET: (urow, icol), REV: (icol, urow)},
      node_feat_dict=feats, num_nodes_dict={'u': NU, 'i': NI})
  edge_set = set(zip(urow.tolist(), icol.tolist()))
  return hds, edge_set, urow, icol


def _pairs(hds, urow, icol, m=64, bs=2):
  rng = np.random.default_rng(1)
  idx = rng.choice(len(urow), m, replace=False)
  src = hds.old2new['u'][urow[idx]]
  dst = hds.old2new['i'][icol[idx]]
  return np.stack([src, dst], 1).reshape(P, -1, 2)[:, :bs * 4].reshape(
      P, -1, 2)


def test_mesh_hetero_link_binary():
  hds, edge_set, urow, icol = _setup()
  mesh = make_mesh(P)
  s = DistHeteroNeighborSampler(hds, [2, 2], mesh=mesh, seed=0)
  pairs = _pairs(hds, urow, icol)
  out = s.sample_from_edges(ET, pairs, neg_sampling='binary')
  u = np.asarray(out['node']['u'])
  i = np.asarray(out['node']['i'])
  n2o_u, n2o_i = hds.new2old['u'], hds.new2old['i']
  eli = np.asarray(out['metadata']['edge_label_index'])
  lab = np.asarray(out['metadata']['edge_label'])
  lm = np.asarray(out['metadata']['edge_label_mask'])
  x_u = np.asarray(out['x']['u'])
  x_i = np.asarray(out['x']['i'])
  npos = 0
  for p in range(P):
    # feature provenance per type
    vm = u[p] >= 0
    assert np.all(x_u[p][vm, 0] == n2o_u[u[p][vm]])
    vm = i[p] >= 0
    assert np.all(x_i[p][vm, 0] == 1000 + n2o_i[i[p][vm]])
    # sampled u->i edges (reversed-key emission) are real
    if REV in out['row']:
      r = np.asarray(out['row'][REV][p])
      c = np.asarray(out['col'][REV][p])
      mm = r >= 0
      for a, b in zip(n2o_u[u[p][c[mm]]].tolist(),
                      n2o_i[i[p][r[mm]]].tolist()):
        assert (a, b) in edge_set
    # labels: positives exist, strict negatives don't
    ok = lm[p]
    gs = n2o_u[u[p][eli[p, 0, ok]]]
    gd = n2o_i[i[p][eli[p, 1, ok]]]
    for a, b, y in zip(gs.tolist(), gd.tolist(), lab[p][ok].tolist()):
      if y >= 1:
        assert (a, b) in edge_set
        npos += 1
      else:
        assert (a, b) not in edge_set
  assert npos == pairs.shape[0] * pairs.shape[1]


@pytest.mark.slow
def test_mesh_hetero_link_triplet():
  hds, edge_set, urow, icol = _setup()
  mesh = make_mesh(P)
  s = DistHeteroNeighborSampler(hds, [2], mesh=mesh, seed=0)
  pairs = _pairs(hds, urow, icol)
  out = s.sample_from_edges(ET, pairs, neg_sampling=('triplet', 2))
  u = np.asarray(out['node']['u'])
  i = np.asarray(out['node']['i'])
  n2o_u, n2o_i = hds.new2old['u'], hds.new2old['i']
  si = np.asarray(out['metadata']['src_index'])
  dp = np.asarray(out['metadata']['dst_pos_index'])
  dn = np.asarray(out['metadata']['dst_neg_index'])
  pm = np.asarray(out['metadata']['pair_mask'])
  for p in range(P):
    gs = n2o_u[u[p][si[p][pm[p]]]]
    gp = n2o_i[i[p][dp[p][pm[p]]]]
    for a, b in zip(gs.tolist(), gp.tolist()):
      assert (a, b) in edge_set
    for j, a in enumerate(gs.tolist()):
      for dl in dn[p][pm[p]][j].tolist():
        if dl < 0:
          continue
        assert (a, n2o_i[i[p][dl]]) not in edge_set


@pytest.mark.slow
def test_mesh_hetero_link_loader_epochs():
  """Loader facade: every seed edge appears as a positive exactly once
  per epoch; batches are HeteroBatch pytrees."""
  import jax
  from graphlearn_tpu.parallel import DistHeteroLinkNeighborLoader
  hds, edge_set, urow, icol = _setup()
  mesh = make_mesh(P)
  m = 64
  rng = np.random.default_rng(2)
  idx = rng.choice(len(urow), m, replace=False)
  loader = DistHeteroLinkNeighborLoader(
      hds, [2, 2], (ET, (urow[idx], icol[idx])),
      neg_sampling='binary', batch_size=2, shuffle=True, mesh=mesh,
      seed=0)
  n2o_u, n2o_i = hds.new2old['u'], hds.new2old['i']
  structs = set()
  for _ in range(2):
    pos = []
    for batch in loader:
      structs.add(jax.tree_util.tree_structure(
          jax.tree_util.tree_map(lambda a: a.shape, batch)))
      u = np.asarray(batch.node_dict['u'])
      i = np.asarray(batch.node_dict['i'])
      eli = np.asarray(batch.metadata['edge_label_index'])
      lab = np.asarray(batch.metadata['edge_label'])
      lm = np.asarray(batch.metadata['edge_label_mask'])
      for p in range(P):
        ok = lm[p] & (lab[p] >= 1)
        gs = n2o_u[u[p][eli[p, 0, ok]]]
        gd = n2o_i[i[p][eli[p, 1, ok]]]
        for a, b in zip(gs.tolist(), gd.tolist()):
          assert (a, b) in edge_set
          pos.append((a, b))
    assert len(pos) == m
  assert len(structs) == 1
