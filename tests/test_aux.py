"""Aux subsystems: metrics/tracing + checkpoint/resume.

These exceed the reference deliberately (SURVEY §5 lists tracing and
checkpointing as absent there); tests pin the public contracts.
"""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

from graphlearn_tpu.utils import Checkpointer, Metrics, metrics, trace


def test_metrics_counts_and_timers():
  m = Metrics()
  m.inc('a')
  m.inc('a', 2)
  with m.timer('t'):
    pass
  snap = m.snapshot()
  assert snap['a'] == 3
  assert snap['t.calls'] == 1
  assert snap['t.secs'] >= 0
  m.reset()
  assert m.snapshot() == {}


def test_trace_annotation_ticks_registry():
  m = Metrics()
  with trace('region', registry=m):
    jnp.ones(4).block_until_ready()
  assert m.snapshot()['region.calls'] == 1


def test_loader_ticks_global_metrics():
  from graphlearn_tpu.data import Dataset
  from graphlearn_tpu.loader import NeighborLoader
  rows = np.repeat(np.arange(20), 2)
  cols = (rows + 1) % 20
  ds = Dataset().init_graph((rows, cols), layout='COO', num_nodes=20)
  loader = NeighborLoader(ds, [2], np.arange(20), batch_size=8)
  before = metrics.snapshot().get('loader.batches', 0)
  list(loader)
  after = metrics.snapshot()['loader.batches']
  assert after - before == 3


@pytest.mark.parametrize('use_orbax', [True, False])
def test_checkpoint_roundtrip(tmp_path, use_orbax):
  if use_orbax:
    pytest.importorskip('orbax.checkpoint')
  ck = Checkpointer(tmp_path / 'ck', max_to_keep=2, use_orbax=use_orbax)
  assert ck.restore(template=None if use_orbax else {'x': np.zeros(2)}
                    ) is None
  tree = {'w': jnp.arange(6, dtype=jnp.float32).reshape(2, 3),
          'opt': {'mu': jnp.ones(3)}, 'step': jnp.asarray(7)}
  ck.save(1, tree)
  ck.save(5, jax.tree_util.tree_map(lambda v: v + 1, tree))
  ck.save(9, jax.tree_util.tree_map(lambda v: v * 2, tree))
  assert ck.all_steps() == [5, 9]        # max_to_keep=2 pruned step 1
  assert ck.latest_step() == 9
  out = ck.restore(template=tree)
  np.testing.assert_array_equal(out['w'], np.asarray(tree['w']) * 2)
  np.testing.assert_array_equal(out['opt']['mu'], 2 * np.ones(3))
  assert int(out['step']) == 14
  # restore a specific retained step
  out5 = ck.restore(template=tree, step=5)
  np.testing.assert_array_equal(out5['w'], np.asarray(tree['w']) + 1)


def test_checkpoint_resume_training_state(tmp_path):
  """Round-trips a real TrainState through save/restore and continues
  training — the examples' --ckpt-dir flow."""
  import optax
  from graphlearn_tpu.data import Dataset
  from graphlearn_tpu.loader import NeighborLoader
  from graphlearn_tpu.models import (GraphSAGE, create_train_state,
                                     make_supervised_step)
  rng = np.random.default_rng(0)
  n = 32
  rows = np.repeat(np.arange(n), 3)
  cols = rng.integers(0, n, n * 3)
  ds = (Dataset()
        .init_graph((rows, cols), layout='COO', num_nodes=n)
        .init_node_features(rng.standard_normal((n, 8)).astype(np.float32))
        .init_node_labels((np.arange(n) % 3).astype(np.int32)))
  loader = NeighborLoader(ds, [2], np.arange(n), batch_size=8)
  model = GraphSAGE(hidden_features=8, out_features=3, num_layers=1)
  tx = optax.adam(1e-2)
  state, apply_fn = create_train_state(
      model, jax.random.key(0), next(iter(loader)), tx)
  step = make_supervised_step(apply_fn, tx, 8)
  for b in loader:
    state, _, _ = step(state, b)

  ck = Checkpointer(tmp_path / 'run')
  ck.save(1, state)
  restored = ck.restore(template=state)
  chex_equal = jax.tree_util.tree_map(
      lambda a, b: np.testing.assert_array_equal(np.asarray(a), b),
      state, restored)
  del chex_equal
  # training continues from the restored pytree
  state2 = jax.tree_util.tree_map(jnp.asarray, restored)
  for b in loader:
    state2, loss, _ = step(state2, b)
  assert np.isfinite(float(loss))


def test_merge_hetero_sampler_output():
  """Partition partials merge with dedup + edge-index remap (reference
  `utils/common.py:55-98`)."""
  import jax.numpy as jnp
  from graphlearn_tpu.sampler.base import HeteroSamplerOutput
  from graphlearn_tpu.utils import (format_hetero_sampler_output,
                                    merge_hetero_sampler_output)

  # emission shape of the hetero samplers: u->i edges appear under the
  # REVERSED key with row = i-type (K[0]) locals, col = u-type locals
  ET = ('i', 'rev_to', 'u')
  a = HeteroSamplerOutput(
      node={'u': jnp.array([10, 11, -1, -1]), 'i': jnp.array([5, 6, -1, -1])},
      node_count={'u': jnp.int32(2), 'i': jnp.int32(2)},
      # edges (i-local row, u-local col): (5<-10), (6<-11)
      row={ET: jnp.array([0, 1])}, col={ET: jnp.array([0, 1])},
      edge_mask={ET: jnp.array([True, True])},
      batch={'u': jnp.array([10, 11])}, edge_types=[ET])
  b = HeteroSamplerOutput(
      node={'u': jnp.array([11, 12, -1, -1]), 'i': jnp.array([6, 7, -1, -1])},
      node_count={'u': jnp.int32(2), 'i': jnp.int32(2)},
      # edges: (6<-11), (7<-12)
      row={ET: jnp.array([0, 1])}, col={ET: jnp.array([0, 1])},
      edge_mask={ET: jnp.array([True, True])},
      batch={'u': jnp.array([11, 12])}, edge_types=[ET])
  m = merge_hetero_sampler_output(a, b)
  u = np.asarray(m.node['u'])
  i = np.asarray(m.node['i'])
  assert list(u[:int(m.node_count['u'])]) == [10, 11, 12]
  assert list(i[:int(m.node_count['i'])]) == [5, 6, 7]
  # remapped global edges must be exactly the union
  got = set()
  em = np.asarray(m.edge_mask[ET])
  for r, c, v in zip(np.asarray(m.row[ET]), np.asarray(m.col[ET]), em):
    if v:
      got.add((int(u[c]), int(i[r])))
  assert got == {(10, 5), (11, 6), (12, 7)}

  # merged batch carries BOTH partials' seeds
  assert list(np.asarray(m.batch['u'])) == [10, 11, 11, 12]
  m = format_hetero_sampler_output(m, ntypes=('w',),
                                   etypes=(('w', 'r', 'u'),),
                                   node_cap=16, edge_cap=24)
  assert m.node['w'].shape == (16,)
  assert m.row[('w', 'r', 'u')].shape == (24,)
