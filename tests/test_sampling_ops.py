"""Tests for device sampling ops (ops/neighbor.py, ops/negative.py,
ops/subgraph.py).

Mirrors reference C++ op tests (`test/cpp/test_random_sampler.cu`,
`test_random_negative_sampler.cu`, `test_subgraph.cu`): tiny handcrafted
CSR graphs, exact assertions on device results.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from graphlearn_tpu.ops import (cal_nbr_prob, edge_in_csr, induced_subgraph,
                                lookup_degree, sample_negative,
                                sample_one_hop)
from graphlearn_tpu.utils import coo_to_csr


def ring_graph(n, deg=2):
  """Node v points to v+1..v+deg (mod n) — the reference's synthetic
  deterministic graph family (`test/python/dist_test_utils.py`)."""
  rows = np.repeat(np.arange(n), deg)
  cols = (rows + np.tile(np.arange(1, deg + 1), n)) % n
  return coo_to_csr(rows, cols, n)


@pytest.fixture(scope='module')
def small_csr():
  indptr, indices, eids = ring_graph(10, deg=3)
  return jnp.asarray(indptr), jnp.asarray(indices), jnp.asarray(eids)


def test_sample_one_hop_take_all(small_csr):
  indptr, indices, eids = small_csr
  seeds = jnp.array([0, 4, 9], jnp.int32)
  out = sample_one_hop(indptr, indices, seeds, k=5,
                       key=jax.random.PRNGKey(0))
  # deg=3 <= k=5: all neighbors taken, in order.
  np.testing.assert_array_equal(np.asarray(out.mask),
                                [[1, 1, 1, 0, 0]] * 3)
  np.testing.assert_array_equal(np.asarray(out.nbrs[0, :3]), [1, 2, 3])
  np.testing.assert_array_equal(np.asarray(out.nbrs[1, :3]), [5, 6, 7])
  np.testing.assert_array_equal(np.asarray(out.nbrs[2, :3]), [0, 1, 2])
  assert (np.asarray(out.nbrs)[:, 3:] == -1).all()


def test_sample_one_hop_downsample(small_csr):
  indptr, indices, _ = small_csr
  seeds = jnp.array([3], jnp.int32)
  for seed in range(5):
    out = sample_one_hop(indptr, indices, seeds, k=2,
                         key=jax.random.PRNGKey(seed))
    nbrs = np.asarray(out.nbrs[0])
    assert np.asarray(out.mask).sum() == 2
    assert set(nbrs).issubset({4, 5, 6})
    # without-replacement (windowed gumbel path): distinct neighbors
    assert nbrs[0] != nbrs[1]


def test_sample_one_hop_invalid_seed(small_csr):
  indptr, indices, _ = small_csr
  seeds = jnp.array([2, -1], jnp.int32)
  out = sample_one_hop(indptr, indices, seeds, k=3,
                       key=jax.random.PRNGKey(0))
  assert np.asarray(out.mask[1]).sum() == 0
  assert (np.asarray(out.nbrs[1]) == -1).all()


def test_sample_one_hop_edge_ids(small_csr):
  indptr, indices, eids = small_csr
  seeds = jnp.array([1], jnp.int32)
  out = sample_one_hop(indptr, indices, seeds, k=3,
                       key=jax.random.PRNGKey(0), edge_ids=eids,
                       with_edge_ids=True)
  # node 1's edges occupy CSR slots 3,4,5; edge ids preserved from COO.
  got = set(np.asarray(out.eids[0]))
  assert got == {3, 4, 5}


def test_sample_one_hop_uniformity(small_csr):
  indptr, indices, _ = small_csr
  # statistical check on the large-degree (with-replacement) path
  n = 200
  indptr2, indices2, _ = ring_graph(n, deg=150)
  indptr2, indices2 = jnp.asarray(indptr2), jnp.asarray(indices2)
  seeds = jnp.zeros((64,), jnp.int32)
  counts = np.zeros(n)
  for it in range(20):
    out = sample_one_hop(indptr2, indices2, seeds, k=10,
                         key=jax.random.PRNGKey(it))
    ids, c = np.unique(np.asarray(out.nbrs), return_counts=True)
    counts[ids[ids >= 0]] += c[ids >= 0]
  picked = counts[1:151]  # node 0's neighborhood
  assert picked.sum() == 20 * 64 * 10
  # roughly uniform: each neighbor ~85 expected hits
  assert picked.min() > 30 and picked.max() < 200


def test_lookup_degree(small_csr):
  indptr, _, _ = small_csr
  deg = lookup_degree(indptr, jnp.array([0, 5, -1], jnp.int32))
  np.testing.assert_array_equal(np.asarray(deg), [3, 3, 0])


def test_edge_in_csr(small_csr):
  indptr, indices, _ = small_csr
  rows = jnp.array([0, 0, 0, 9, -1], jnp.int32)
  cols = jnp.array([1, 3, 5, 0, 1], jnp.int32)
  hit = edge_in_csr(indptr, indices, rows, cols)
  np.testing.assert_array_equal(np.asarray(hit),
                                [True, True, False, True, False])


def test_sample_negative_strict(small_csr):
  indptr, indices, _ = small_csr
  res = sample_negative(indptr, indices, 64, jax.random.PRNGKey(0),
                        trials=8, strict=True, padding=False)
  rows = np.asarray(res.rows)[np.asarray(res.mask)]
  cols = np.asarray(res.cols)[np.asarray(res.mask)]
  assert len(rows) > 50  # graph is sparse; nearly all draws valid
  # none of the returned pairs may be real edges
  hit = np.asarray(edge_in_csr(indptr, indices, jnp.asarray(rows),
                               jnp.asarray(cols)))
  assert not hit.any()


def test_sample_negative_padding(small_csr):
  indptr, indices, _ = small_csr
  res = sample_negative(indptr, indices, 32, jax.random.PRNGKey(1),
                        strict=True, padding=True)
  assert np.asarray(res.mask).all()
  assert (np.asarray(res.rows) >= 0).all()


def test_induced_subgraph(small_csr):
  indptr, indices, _ = small_csr
  # nodes {0,1,2}: edges 0->1, 0->2, 1->2 present (plus 1->3.. excluded)
  nodes = jnp.array([0, 1, 2, -1], jnp.int32)
  res = induced_subgraph(indptr, indices, nodes, max_degree=4,
                         with_edge_ids=True)
  mask = np.asarray(res.edge_mask)
  got = {(int(r), int(c))
         for r, c in zip(np.asarray(res.rows)[mask],
                         np.asarray(res.cols)[mask])}
  assert got == {(0, 1), (0, 2), (1, 2)}
  eids = np.asarray(res.eids)[mask]
  assert set(eids) == {0, 1, 3}


def test_cal_nbr_prob(small_csr):
  indptr, indices, _ = small_csr
  prob = jnp.ones((10,), jnp.float32)
  out = cal_nbr_prob(indptr, indices, prob, k=2)
  # every node has deg 3, receives 3 contributions of 1 * 2/3
  np.testing.assert_allclose(np.asarray(out), np.full(10, 2.0), rtol=1e-5)


def test_edge_in_csr_power_of_two_hub():
  # Regression: one-short binary search missed edges on power-of-two
  # hub rows (E=4 all on node 0).
  indptr = jnp.array([0, 4, 4, 4, 4, 4, 4, 4, 4])
  indices = jnp.array([1, 3, 5, 7], jnp.int32)
  hit = edge_in_csr(indptr, indices, jnp.zeros(4, jnp.int32),
                    jnp.array([1, 3, 5, 7], jnp.int32))
  assert np.asarray(hit).all()


def test_csr_layout_sorts_columns():
  # Regression: user CSR input with unsorted columns must be re-sorted
  # so edge membership binary search works.
  from graphlearn_tpu.data.topology import CSRTopo
  topo = CSRTopo((np.array([0, 3, 4]), np.array([5, 1, 3, 0])),
                 layout='CSR', edge_ids=np.array([10, 11, 12, 13]))
  np.testing.assert_array_equal(topo.indices, [1, 3, 5, 0])
  np.testing.assert_array_equal(topo.edge_ids, [11, 12, 10, 13])
  hit = edge_in_csr(jnp.asarray(topo.indptr), jnp.asarray(topo.indices),
                    jnp.array([0, 0], jnp.int32),
                    jnp.array([5, 2], jnp.int32))
  np.testing.assert_array_equal(np.asarray(hit), [True, False])


def test_csc_layout_preserves_isolated_tail_nodes():
  # Regression: CSC round-trip dropped trailing isolated nodes.
  from graphlearn_tpu.data.topology import CSRTopo
  topo = CSRTopo((np.array([0, 1, 2, 2, 2, 2]), np.array([1, 2])),
                 layout='CSC')
  assert topo.num_nodes == 5
  deg = lookup_degree(jnp.asarray(topo.indptr),
                      jnp.array([4], jnp.int32))
  assert int(deg[0]) == 0


def test_sort_locality_restores_input_order():
  """The locality sort is internal: outputs align with the UNSORTED
  input seed order (regression for the inverse permutation — existing
  tests all pass pre-sorted seeds, for which argsort is identity)."""
  import jax
  import jax.numpy as jnp
  from graphlearn_tpu.ops.neighbor import sample_one_hop
  # ring: node v -> v+1 only, so the correct neighbor is seed+1
  n = 50
  indptr = jnp.arange(n + 1, dtype=jnp.int32)
  indices = jnp.asarray((np.arange(n) + 1) % n, dtype=jnp.int32)
  seeds = jnp.asarray([9, -1, 3, 0, 41, 3, 17], dtype=jnp.int32)
  res = sample_one_hop(indptr, indices, seeds, 1, jax.random.key(0),
                       sort_locality=True)
  nbrs = np.asarray(res.nbrs)[:, 0]
  mask = np.asarray(res.mask)[:, 0]
  expect_valid = np.asarray(seeds) >= 0
  np.testing.assert_array_equal(mask, expect_valid)
  np.testing.assert_array_equal(nbrs[expect_valid],
                                (np.asarray(seeds)[expect_valid] + 1) % n)
