"""Persistent AOT executable cache (ISSUE 13): warm-restore skips
recompilation (compile_count == 0, THE acceptance pin), corrupt/
stale entries skip to recompile (never a crash or a wrong
executable), write faults are absorbed, publishes are atomic.

A "second process" is simulated by a FRESH `ServingEngine` over the
same cache dir: every engine builds fresh `_uncached_jit` wrappers
(empty in-memory executable caches), so a zero compile-count warmup
can only come from the disk restore.
"""
import os
import pickle

import jax
import numpy as np
import pytest

from graphlearn_tpu.data import Dataset
from graphlearn_tpu.models.tree import TreeSAGE
from graphlearn_tpu.serving import AotExecutableCache, ServingEngine
from graphlearn_tpu.serving import aot_cache as aot_mod
from graphlearn_tpu.telemetry import recorder
from graphlearn_tpu.testing import chaos

N, D = 48, 4
FANOUTS = [3, 2]
BUCKETS = (1, 2)


@pytest.fixture(autouse=True)
def _clean():
  chaos.uninstall()
  recorder.enable(None)
  recorder.clear()
  yield
  chaos.uninstall()
  recorder.clear()
  recorder.disable()


def _dataset():
  rng = np.random.default_rng(0)
  rows = np.repeat(np.arange(N), 3)
  cols = rng.integers(0, N, rows.shape[0])
  feats = (np.arange(N, dtype=np.float32)[:, None]
           * np.ones((1, D), np.float32))
  return (Dataset().init_graph((rows, cols), layout='COO', num_nodes=N)
          .init_node_features(feats))


def _engine(model=False, seed=7):
  m = (TreeSAGE(hidden_features=8, out_features=5,
                num_layers=len(FANOUTS)) if model else None)
  eng = ServingEngine(_dataset(), FANOUTS, model=m, seed=seed,
                      buckets=BUCKETS)
  if model:
    eng.init_params(jax.random.key(0))
  return eng


def test_warm_restore_skips_recompilation(tmp_path):
  """THE acceptance pin: a second process with a populated
  GLT_AOT_CACHE_DIR warms with compile_count == 0 and answers
  byte-identically to the compiling process."""
  cache = AotExecutableCache(tmp_path)
  e1 = _engine(model=True)
  w1 = e1.warmup(aot_cache=cache)
  assert w1['compiles'] == len(BUCKETS)   # forward program per bucket
  assert e1.compile_count() == len(BUCKETS)
  assert len(cache.entries()) == len(BUCKETS)
  ref = e1.infer([3, 5])

  recorder.clear()
  e2 = _engine(model=True)
  w2 = e2.warmup(aot_cache=cache)
  assert w2['compiles'] == 0
  assert e2.compile_count() == 0          # the warm-start pin
  assert w2['aot_restored'] == len(BUCKETS)
  got = e2.infer([3, 5])
  np.testing.assert_array_equal(ref.nodes, got.nodes)
  np.testing.assert_array_equal(np.asarray(ref.logits),
                                np.asarray(got.logits))
  hits = recorder.events('aot.cache_hit')
  assert len(hits) == len(BUCKETS)
  # traffic after warm restore stays at zero compiles across buckets
  for seeds in ([1], [2, 9]):
    e2.infer(seeds)
  assert e2.compile_count() == 0


def test_env_knob_routes_warmup_through_cache(tmp_path, monkeypatch):
  monkeypatch.setenv(aot_mod.AOT_CACHE_DIR_ENV, str(tmp_path))
  e1 = _engine()
  w1 = e1.warmup()
  assert w1['aot_restored'] == 0
  assert len(AotExecutableCache(tmp_path).entries()) == len(BUCKETS)
  # re-warm of the SAME engine: the stat counts THIS call's restores
  # (not a lifetime delta that would read 0 forever after a compile)
  w1b = e1.warmup()
  assert w1b['aot_restored'] == len(BUCKETS)
  e2 = _engine()
  w2 = e2.warmup()
  assert e2.compile_count() == 0
  assert w2['aot_restored'] == len(BUCKETS)


def test_corrupt_entry_falls_back_to_recompile(tmp_path):
  """A scrambled payload is caught by the checksum: the warmup
  recompiles (one aot.cache_miss reason=corrupt per bad entry) and
  the answers stay correct — never a crash, never a wrong
  executable."""
  cache = AotExecutableCache(tmp_path)
  e1 = _engine()
  e1.warmup(aot_cache=cache)
  ref = e1.infer([4])
  for name in cache.entries():
    p = tmp_path / name
    rec = pickle.loads(p.read_bytes())
    buf = bytearray(rec['payload'])
    buf[::5] = bytes((b ^ 0xAA) for b in buf[::5])
    rec['payload'] = bytes(buf)
    p.write_bytes(pickle.dumps(rec))
  recorder.clear()
  e2 = _engine()
  e2.warmup(aot_cache=cache)
  assert e2.compile_count() == len(BUCKETS)   # recompiled, no crash
  got = e2.infer([4])
  np.testing.assert_array_equal(ref.nodes, got.nodes)
  reasons = [e.get('reason') for e in recorder.events('aot.cache_miss')]
  assert reasons.count('corrupt') == len(BUCKETS)


def test_garbage_file_and_stale_fingerprint_skip(tmp_path):
  cache = AotExecutableCache(tmp_path)
  e1 = _engine()
  e1.warmup(aot_cache=cache)
  entries = cache.entries()
  # unpicklable garbage in one, fingerprint drift in another
  (tmp_path / entries[0]).write_bytes(b'not a pickle at all')
  p = tmp_path / entries[1]
  rec = pickle.loads(p.read_bytes())
  rec['fingerprint'] = dict(rec['fingerprint'], seed=999)
  p.write_bytes(pickle.dumps(rec))
  recorder.clear()
  e2 = _engine()
  e2.warmup(aot_cache=cache)
  assert e2.compile_count() == len(BUCKETS)
  reasons = sorted(e.get('reason')
                   for e in recorder.events('aot.cache_miss'))
  assert reasons == ['corrupt', 'stale']


def test_different_seed_is_a_different_program(tmp_path):
  """The serve key is a traced closure constant: an engine with a
  different seed must NOT restore another seed's executables (it
  would answer with the wrong sampling trees)."""
  cache = AotExecutableCache(tmp_path)
  _engine(seed=7).warmup(aot_cache=cache)
  e2 = _engine(seed=8)
  e2.warmup(aot_cache=cache)
  assert e2.compile_count() == len(BUCKETS)   # no cross-seed reuse
  assert len(cache.entries()) == 2 * len(BUCKETS)


def test_chaos_fail_write_absorbed(tmp_path):
  """aot.cache:fail on save — the warmup succeeds (this process pays
  nothing), the directory stays empty (the next one pays a compile)."""
  chaos.install('aot.cache:fail:1:op=save;aot.cache:fail:2:op=save')
  cache = AotExecutableCache(tmp_path)
  e1 = _engine()
  w = e1.warmup(aot_cache=cache)
  assert w['buckets'] == {1: True, 2: True}
  assert cache.entries() == []
  assert not list(tmp_path.glob('*.tmp.*'))   # no torn tmp carcass
  chaos.uninstall()
  e2 = _engine()
  e2.warmup(aot_cache=cache)
  assert e2.compile_count() == len(BUCKETS)   # cache was never fed


def test_chaos_corrupt_write_caught_on_later_load(tmp_path):
  """aot.cache:corrupt scrambles the payload on disk; the NEXT
  process's load must detect the checksum mismatch and recompile."""
  chaos.install({'faults': [{'site': 'aot.cache', 'action': 'corrupt',
                             'op': 'save', 'nth': 1, 'count': 99}]})
  cache = AotExecutableCache(tmp_path)
  e1 = _engine()
  e1.warmup(aot_cache=cache)
  assert len(cache.entries()) == len(BUCKETS)   # published, but bad
  chaos.uninstall()
  recorder.clear()
  e2 = _engine()
  e2.warmup(aot_cache=cache)
  assert e2.compile_count() == len(BUCKETS)
  reasons = [e.get('reason') for e in recorder.events('aot.cache_miss')]
  assert reasons.count('corrupt') == len(BUCKETS)
  np.testing.assert_array_equal(e2.infer([4]).nodes,
                                e1.infer([4]).nodes)


def test_atomic_publish_leaves_no_tmp(tmp_path):
  cache = AotExecutableCache(tmp_path)
  _engine().warmup(aot_cache=cache)
  names = os.listdir(tmp_path)
  assert names and all(n.endswith('.aotx') for n in names)


def test_static_toggle_bypasses_baked_executable(tmp_path,
                                                 monkeypatch):
  """GLT_PALLAS keeps its documented DISPATCH-time semantics: an AOT
  executable that baked the other value at warmup is bypassed (the
  jit path serves the call), not silently served stale — and the
  entry still serves once the toggle flips back."""
  cache = AotExecutableCache(tmp_path)
  e1 = _engine()
  e1.warmup(aot_cache=cache)         # bakes use_pallas=False
  ref = e1.infer([4])
  e2 = _engine()
  e2.warmup(aot_cache=cache)
  assert e2.compile_count() == 0
  monkeypatch.setenv('GLT_PALLAS', '1')
  got = e2.infer([4])                # statics mismatch -> jit path
  np.testing.assert_array_equal(ref.nodes, got.nodes)
  assert e2.compile_count() > 0      # the bypass paid a real compile
  monkeypatch.delenv('GLT_PALLAS')
  before = e2.compile_count()
  got2 = e2.infer([4])               # baked statics match again
  np.testing.assert_array_equal(ref.nodes, got2.nodes)
  assert e2.compile_count() == before   # served by the AOT entry


def test_mutated_graph_skips_stale_executable(tmp_path):
  """ISSUE 14 satellite: `_aot_fingerprint` includes the graph shape
  AND the ingest graph_version, so a replica warming against a
  MUTATED graph pays a fresh compile instead of restoring an
  executable fingerprinted against the pre-ingest graph — and a
  replica at the SAME version still warm-restores."""
  from graphlearn_tpu.streaming import StreamingGraph
  cache = AotExecutableCache(tmp_path)
  rng = np.random.default_rng(0)
  rows = np.repeat(np.arange(N), 3)
  cols = rng.integers(0, N, rows.shape[0])
  feats = (np.arange(N, dtype=np.float32)[:, None]
           * np.ones((1, D), np.float32))
  sg = StreamingGraph.from_coo(rows, cols, num_nodes=N,
                               reserve_edges=4 * len(rows))

  def make():
    ds = Dataset().init_node_features(feats).attach_stream(sg)
    return ServingEngine(ds, FANOUTS, seed=7, buckets=BUCKETS)

  e1 = make()
  e1.warmup(aot_cache=cache)
  assert e1.compile_count() == len(BUCKETS)
  n_before = len(cache.entries())
  # same graph version: a replacement replica warm-restores
  e2 = make()
  e2.warmup(aot_cache=cache)
  assert e2.compile_count() == 0
  assert e2.graph_version == e1.graph_version
  # mutate the graph (same padded shape — reserve_edges holds), bump
  # the version: the old entries must NOT serve the new graph's warmup
  sg.apply_events(rng.integers(0, N, 10), rng.integers(0, N, 10))
  recorder.clear()
  e3 = make()
  e3.warmup(aot_cache=cache)
  assert e3.graph_version == sg.version
  assert e3.compile_count() == len(BUCKETS)   # recompiled, not stale
  assert len(cache.entries()) == n_before + len(BUCKETS)
  reasons = [e.get('reason') for e in recorder.events('aot.cache_miss')]
  assert reasons.count('absent') == len(BUCKETS)
  # and a fourth replica AT the new version warm-restores again
  e4 = make()
  e4.warmup(aot_cache=cache)
  assert e4.compile_count() == 0


def test_runtime_failure_of_restored_exec_recompiles(tmp_path):
  """skip-to-recompile extends to CALL time: a restored executable
  that raises is dropped and the dispatch falls back to the compile
  path, still answering correctly."""
  cache = AotExecutableCache(tmp_path)
  e1 = _engine()
  e1.warmup(aot_cache=cache)
  ref = e1.infer([4])
  e2 = _engine()
  e2.warmup(aot_cache=cache)
  assert e2.compile_count() == 0

  def boom(*a, **k):
    raise RuntimeError('deserialized executable rejected the call')
  for key in list(e2._aot):
    e2._aot[key] = (boom, e2._aot[key][1])
  got = e2.infer([4])                  # falls back, recompiles
  np.testing.assert_array_equal(ref.nodes, got.nodes)
  assert e2.compile_count() > 0
  assert ('gather', 1) not in e2._aot  # the bad exec it hit is dropped
