"""Bench regression gate (ISSUE 2): baseline bootstrap on first run,
per-metric FAIL report on an artificially slowed metric, direction
handling for rate metrics, and the bench.py wiring (artifact verdict
stamped into the bounded summary line).
"""
import importlib.util
import json
import sys
from pathlib import Path

import pytest

_ROOT = Path(__file__).resolve().parent.parent


@pytest.fixture(scope='module')
def regress():
  spec = importlib.util.spec_from_file_location(
      'regress_under_test',
      _ROOT / 'graphlearn_tpu' / 'telemetry' / 'regress.py')
  mod = importlib.util.module_from_spec(spec)
  spec.loader.exec_module(mod)
  return mod


@pytest.fixture(scope='module')
def bench():
  spec = importlib.util.spec_from_file_location('bench_for_regress',
                                                _ROOT / 'bench.py')
  mod = importlib.util.module_from_spec(spec)
  argv = sys.argv
  sys.argv = ['bench.py']
  try:
    spec.loader.exec_module(mod)
  finally:
    sys.argv = argv
  return mod


ART = {'metric': 'graphsage_fused_epoch_secs', 'value': 7.1,
       'unit': 's', 'fused_epoch_secs': 7.1, 'train_step_mfu': 0.02,
       'dist': {'seeds_per_sec': 1000.0,
                'edges_per_sec_per_chip': 2e4}}


def _write(path, obj):
  path.write_text(json.dumps(obj))
  return str(path)


def test_first_run_creates_baseline(regress, tmp_path):
  art = _write(tmp_path / 'A.json', ART)
  bl = tmp_path / 'BL.json'
  verdict, rc = regress.check(art, str(bl))
  assert rc == 0 and verdict['baseline_created']
  assert json.loads(bl.read_text())['value'] == 7.1
  assert 'BASELINE_CREATED' in regress.format_report(verdict)
  assert regress.summary(verdict) == 'BASELINE_CREATED'


def test_partial_bootstrap_names_unguarded_metrics(regress, tmp_path):
  """Pinning a baseline from a partial run (a crashed phase) must
  loudly name the tracked metrics it leaves unguarded."""
  partial = {'value': 7.1}                      # no fused/dist keys
  art = _write(tmp_path / 'A.json', partial)
  verdict, rc = regress.check(art, str(tmp_path / 'BL.json'))
  assert rc == 0 and verdict['baseline_created']
  assert 'fused_epoch_secs' in verdict['unguarded']
  assert 'dist.seeds_per_sec' in verdict['unguarded']
  assert 'UNGUARDED' in regress.format_report(verdict)


def test_corrupt_baseline_errors_without_rebasing(regress, tmp_path):
  """A corrupt baseline is rc 2 (reported, non-fatal to the bench) and
  NOT rewritten — a regressed run must never re-base the trajectory
  onto its own numbers through a conveniently broken file."""
  art = _write(tmp_path / 'A.json', dict(ART, value=99.0))
  bl = tmp_path / 'BL.json'
  bl.write_text('{"value": 7.')                 # truncated JSON
  verdict, rc = regress.check(art, str(bl))
  assert rc == 2 and verdict['status'] == 'ERROR'
  assert 'corrupt' in verdict['error']
  assert bl.read_text() == '{"value": 7.'       # untouched
  assert regress.summary(verdict) == 'ERROR'
  assert 'corrupt' in regress.format_report(verdict)


def test_check_accepts_in_memory_artifact(regress, tmp_path):
  """bench passes the fresh aggregate dict directly, so a stale
  artifact file can never be what gets gated."""
  bl = _write(tmp_path / 'BL.json', ART)
  verdict, rc = regress.check(dict(ART, value=9.0,
                                   fused_epoch_secs=9.0), bl)
  assert rc == 1 and 'value' in verdict['regressed']


def test_slowed_metric_fails_and_names_key(regress, tmp_path):
  """Acceptance: an artificially >= 20% slowed metric exits nonzero
  with a per-metric report naming the regressed key."""
  bl = _write(tmp_path / 'BL.json', ART)
  slow = dict(ART, value=9.0, fused_epoch_secs=9.0)   # +26.8%
  art = _write(tmp_path / 'A.json', slow)
  verdict, rc = regress.check(art, bl)
  assert rc == 1 and verdict['status'] == 'FAIL'
  assert set(verdict['regressed']) == {'value', 'fused_epoch_secs'}
  report = regress.format_report(verdict)
  assert '[FAIL] fused_epoch_secs' in report
  assert '+26.8%' in report
  assert regress.summary(verdict).startswith('FAIL ')
  # CLI form: same verdict, nonzero exit
  assert regress.main([art, bl]) == 1


def test_within_threshold_passes(regress, tmp_path):
  bl = _write(tmp_path / 'BL.json', ART)
  ok = dict(ART, value=7.8)                           # +9.9%
  verdict, rc = regress.check(_write(tmp_path / 'A.json', ok), bl)
  assert rc == 0 and verdict['status'] == 'PASS'
  assert regress.summary(verdict) == 'PASS'


def test_rate_metric_direction(regress, tmp_path):
  """higher-is-better metrics regress when they DROP: a fallen
  seeds_per_sec must fail, a risen one must not."""
  bl = _write(tmp_path / 'BL.json', ART)
  dropped = dict(ART, dist={'seeds_per_sec': 700.0,   # -30% rate
                            'edges_per_sec_per_chip': 3e4})
  verdict, rc = regress.check(_write(tmp_path / 'A.json', dropped), bl)
  assert rc == 1
  assert verdict['regressed'] == ['dist.seeds_per_sec']
  row = {m['key']: m for m in verdict['metrics']}
  assert row['dist.seeds_per_sec']['change_pct'] > 20
  assert row['dist.edges_per_sec_per_chip']['status'] == 'ok'


def test_scale_envelope_rows_guarded(regress, tmp_path):
  """ISSUE 3 satellite: the P=16 / P=64 scale-envelope rows'
  padding_waste_pct and seeds_per_sec are guarded metrics — a waste
  regression at P=64 fails the gate, and the 'pNN' path segment
  addresses the right row of the list."""
  def env_art(w16, w64, s16=900.0, s64=900.0):
    return dict(ART, dist={
        'seeds_per_sec': 1000.0, 'edges_per_sec_per_chip': 2e4,
        'scale_envelope': [
            {'num_parts': 16, 'padding_waste_pct': w16,
             'seeds_per_sec': s16},
            {'num_parts': 64, 'padding_waste_pct': w64,
             'seeds_per_sec': s64},
        ]})
  bl = _write(tmp_path / 'BL.json', env_art(24.0, 28.0))
  # same numbers: pass, and all four envelope keys were compared
  verdict, rc = regress.check(
      _write(tmp_path / 'A.json', env_art(24.0, 28.0)), bl)
  assert rc == 0
  rows = {m['key']: m for m in verdict['metrics']}
  for key in ('dist.scale_envelope.p16.padding_waste_pct',
              'dist.scale_envelope.p64.padding_waste_pct',
              'dist.scale_envelope.p16.seeds_per_sec',
              'dist.scale_envelope.p64.seeds_per_sec'):
    assert rows[key]['status'] == 'ok', key
  # waste blowing back up at P=64 (lower-is-better) fails the gate
  verdict, rc = regress.check(
      _write(tmp_path / 'B.json', env_art(24.0, 90.0)), bl)
  assert rc == 1
  assert 'dist.scale_envelope.p64.padding_waste_pct' in \
      verdict['regressed']
  # rows are matched by num_parts, not list position
  flipped = env_art(24.0, 28.0)
  flipped['dist']['scale_envelope'].reverse()
  verdict, rc = regress.check(
      _write(tmp_path / 'C.json', flipped), bl)
  assert rc == 0
  # a missing envelope (crashed phase) skips, never fails
  verdict, rc = regress.check(_write(tmp_path / 'D.json', ART), bl)
  assert rc == 0
  rows = {m['key']: m for m in verdict['metrics']}
  assert rows['dist.scale_envelope.p16.padding_waste_pct'][
      'status'] == 'skipped'


def test_rate_collapse_stays_strict_json(regress, tmp_path):
  """A rate falling to 0 regresses with a CLAMPED finite change_pct —
  json.dumps of the verdict must stay strict (no Infinity token)."""
  bl = _write(tmp_path / 'BL.json', ART)
  dead = dict(ART, dist={'seeds_per_sec': 0.0})
  verdict, rc = regress.check(_write(tmp_path / 'A.json', dead), bl)
  assert rc == 1 and 'dist.seeds_per_sec' in verdict['regressed']
  row = {m['key']: m for m in verdict['metrics']}['dist.seeds_per_sec']
  assert row['change_pct'] == 1e6          # clamped, finite
  text = json.dumps(verdict, allow_nan=False)   # raises on inf/nan
  assert 'Infinity' not in text
  assert regress.format_report(verdict)    # renders without error


def test_missing_metrics_skip_not_fail(regress, tmp_path):
  """A phase that degraded away (key missing on one side) is skipped —
  a bad bench day is not a regression."""
  bl = _write(tmp_path / 'BL.json', ART)
  partial = {'value': 7.2}
  verdict, rc = regress.check(_write(tmp_path / 'A.json', partial), bl)
  assert rc == 0
  rows = {m['key']: m['status'] for m in verdict['metrics']}
  assert rows['fused_epoch_secs'] == 'skipped'
  assert rows['value'] == 'ok'


def test_threshold_override(regress, tmp_path):
  bl = _write(tmp_path / 'BL.json', ART)
  mild = dict(ART, value=7.9)                         # +11.3%
  _, rc = regress.check(_write(tmp_path / 'A.json', mild), bl,
                        threshold=0.1)
  assert rc == 1
  _, rc = regress.check(str(tmp_path / 'A.json'), bl, threshold=0.15)
  assert rc == 0


def test_update_baseline_after_pass(regress, tmp_path):
  bl = _write(tmp_path / 'BL.json', ART)
  faster = dict(ART, value=5.0, fused_epoch_secs=5.0)
  verdict, rc = regress.check(_write(tmp_path / 'A.json', faster), bl,
                              update_baseline=True)
  assert rc == 0 and verdict.get('baseline_updated')
  assert json.loads(Path(bl).read_text())['value'] == 5.0


def test_bench_gate_wiring(bench, regress, tmp_path, monkeypatch):
  """bench.py --check-regression: first run creates the baseline;
  a slowed artifact exits nonzero and the re-emitted summary line
  carries the compact verdict near the front."""
  art_path = tmp_path / 'BENCH_ARTIFACT.json'
  bl_path = tmp_path / 'BENCH_BASELINE.json'
  monkeypatch.setenv('GLT_BENCH_ARTIFACT', str(art_path))
  monkeypatch.setenv('GLT_BENCH_BASELINE', str(bl_path))
  art = dict(ART)
  _write(art_path, art)
  rc = bench._run_regression_gate(art)
  assert rc == 0 and bl_path.exists()      # baseline bootstrapped
  slow = dict(ART, value=9.0, fused_epoch_secs=9.0)
  _write(art_path, slow)
  rc = bench._run_regression_gate(slow)
  assert rc == 1
  # the verdict was stamped into the re-emitted artifact + summary
  full = json.loads(art_path.read_text())
  assert full['regression'].startswith('FAIL ')
  assert full['regression_report']['status'] == 'FAIL'
  from graphlearn_tpu.telemetry import sink
  line = sink.summary_line(full, artifact=str(art_path))
  assert json.loads(line)['regression'].startswith('FAIL ')


def test_summary_line_keeps_regression_under_degradation():
  """The satellite contract: a FAIL verdict survives even when the
  summary line degrades to its minimum."""
  from graphlearn_tpu.telemetry import sink
  art = {'metric': 'm' * 500, 'value': 1.0, 'unit': 's',
         'regression': 'FAIL fused_epoch_secs +34.0%',
         'protocol': 'p' * 900,
         'epoch_secs_min_med_max': [0.1] * 400,
         'dist': {'padding_waste_pct': 1.0, 'error': 'e' * 1200}}
  line = sink.summary_line(art, artifact='/tmp/a.json', limit=700)
  parsed = json.loads(line)
  assert parsed['regression'].startswith('FAIL')
  assert parsed['value'] == 1.0
